"""Tests for mxnet_tpu.parallel — mesh construction and the fused SPMD
training step, run on the virtual 8-device CPU mesh (SURVEY §4: the TPU
analog of the reference's local-process fake cluster for kvstore tests)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, parallel
from mxnet_tpu.parallel import PartitionSpec as P


def _mlp(classes=10):
    net = gluon.nn.HybridSequential()
    with net.name_scope():
        net.add(gluon.nn.Dense(32, activation="relu"))
        net.add(gluon.nn.BatchNorm())
        net.add(gluon.nn.Dense(classes))
    net.initialize()
    return net


def test_make_mesh_axes():
    mesh = parallel.make_mesh({"data": 4, "model": 2})
    assert mesh.axis_names == ("data", "model")
    assert mesh.devices.shape == (4, 2)
    mesh2 = parallel.make_mesh({"data": -1, "model": 2})
    assert mesh2.devices.shape == (4, 2)
    with pytest.raises(mx.MXNetError):
        parallel.make_mesh({"data": 3, "model": 5})


def test_use_mesh_scope():
    mesh = parallel.make_mesh({"data": 4, "model": 2})
    with parallel.use_mesh(mesh) as m:
        assert parallel.current_mesh() is mesh
    # outside the scope the default (all-data) mesh is current again
    assert parallel.current_mesh().axis_names == ("data",)


def test_sharded_trainer_loss_decreases():
    net = _mlp()
    mesh = parallel.make_mesh({"data": 4, "model": 2})
    tr = parallel.ShardedTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        optimizer_params={"learning_rate": 0.5, "momentum": 0.9},
        mesh=mesh,
        param_rules=[(r".*dense0_weight", P("model", None))])
    rng = np.random.RandomState(0)
    x = rng.randn(64, 16).astype(np.float32)
    y = rng.randint(0, 10, (64,))
    losses = [tr.step(x, y).asscalar() for _ in range(10)]
    assert losses[-1] < losses[0] * 0.7
    assert np.isfinite(losses[-1])


def test_sharded_trainer_matches_eager_sgd():
    """The fused sharded step must produce the same result as the eager
    gluon.Trainer path (the reference's check_consistency method, §4)."""
    rng = np.random.RandomState(1)
    x = rng.randn(16, 8).astype(np.float32)
    y = rng.randint(0, 4, (16,))

    def make():
        net = gluon.nn.HybridSequential()
        with net.name_scope():
            net.add(gluon.nn.Dense(16, activation="tanh", in_units=8))
            net.add(gluon.nn.Dense(4, in_units=16))
        net.initialize(mx.init.Xavier(rnd_type="gaussian"))
        return net

    mx.random.seed(7)
    net_a = make()
    mx.random.seed(7)
    net_b = make()
    for pa, pb in zip(net_a.collect_params().values(),
                      net_b.collect_params().values()):
        np.testing.assert_allclose(pa.data().asnumpy(), pb.data().asnumpy())

    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    # eager path: forward/backward/step; grads divided by batch via step(B)
    trainer = gluon.Trainer(net_a.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    from mxnet_tpu import autograd
    for _ in range(3):
        with autograd.record():
            out = net_a(mx.nd.array(x))
            loss = loss_fn(out, mx.nd.array(y))
        loss.backward()
        trainer.step(x.shape[0])

    # fused sharded path: loss is mean over batch, rescale 1.0
    mesh = parallel.make_mesh({"data": 8})
    st = parallel.ShardedTrainer(net_b, loss_fn, "sgd",
                                 optimizer_params={"learning_rate": 0.1},
                                 mesh=mesh)
    for _ in range(3):
        st.step(x, y)

    for pa, pb in zip(net_a.collect_params().values(),
                      net_b.collect_params().values()):
        np.testing.assert_allclose(pa.data().asnumpy(), pb.data().asnumpy(),
                                   rtol=2e-4, atol=2e-5)


def test_sharded_trainer_adam_runs():
    net = _mlp(4)
    mesh = parallel.make_mesh({"data": 8})
    tr = parallel.ShardedTrainer(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                                 "adam", {"learning_rate": 1e-2}, mesh=mesh)
    x = np.random.randn(32, 12).astype(np.float32)
    y = np.random.randint(0, 4, (32,))
    l0 = tr.step(x, y).asscalar()
    for _ in range(5):
        l1 = tr.step(x, y).asscalar()
    assert l1 < l0


def test_evaluate_and_outputs():
    net = _mlp(6)
    mesh = parallel.make_mesh({"data": 8})
    tr = parallel.ShardedTrainer(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                                 "sgd", {"learning_rate": 0.1}, mesh=mesh)
    x = np.random.randn(16, 5).astype(np.float32)
    y = np.random.randint(0, 6, (16,))
    tr.step(x, y)
    ev = tr.evaluate(x, y)
    assert np.isfinite(ev.asscalar())
    assert tr.last_outputs[0].shape == (16, 6)


def test_graft_entry_dryrun():
    """The driver's multichip dry-run contract must keep working."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "__graft_entry__", "__graft_entry__.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    mod.dryrun_multichip(8)


def test_run_steps_matches_single_steps():
    """run_steps (lax.scan fused multi-step) must be bit-equal to N single
    steps for a deterministic model."""
    def make():
        net = gluon.nn.HybridSequential()
        with net.name_scope():
            net.add(gluon.nn.Dense(16, activation="tanh", in_units=8))
            net.add(gluon.nn.Dense(4, in_units=16))
        net.initialize(mx.init.Xavier())
        return net

    x = np.random.RandomState(0).randn(16, 8).astype(np.float32)
    y = np.random.RandomState(1).randint(0, 4, (16,))
    mx.random.seed(3)
    a = make()
    mx.random.seed(3)
    b = make()
    mesh = parallel.make_mesh({"data": 8})
    ta = parallel.ShardedTrainer(a, gluon.loss.SoftmaxCrossEntropyLoss(),
                                 "sgd", {"learning_rate": 0.1,
                                         "momentum": 0.9}, mesh=mesh)
    tb = parallel.ShardedTrainer(b, gluon.loss.SoftmaxCrossEntropyLoss(),
                                 "sgd", {"learning_rate": 0.1,
                                         "momentum": 0.9}, mesh=mesh)
    for _ in range(6):
        la = ta.step(x, y)
    lb = tb.run_steps(x, y, num_steps=6)
    assert abs(la.asscalar() - lb.asscalar()) < 1e-6
    for pa, pb in zip(a.collect_params().values(),
                      b.collect_params().values()):
        np.testing.assert_allclose(pa.data().asnumpy(),
                                   pb.data().asnumpy(), rtol=1e-5,
                                   atol=1e-6)


def test_pipeline_parallel_matches_sequential():
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.parallel import pipeline_apply
    P_, D, B = 4, 8, 16
    rng = np.random.RandomState(0)
    Ws = jnp.asarray(rng.randn(P_, D, D).astype(np.float32) * 0.3)
    bs = jnp.asarray(rng.randn(P_, D).astype(np.float32) * 0.1)
    x = jnp.asarray(rng.randn(B, D).astype(np.float32))

    def stage(params, h):
        W, b = params
        return jnp.tanh(h @ W + b)

    mesh = parallel.make_mesh({"pipe": 4, "data": 2})
    h = x
    for i in range(P_):
        h = stage((Ws[i], bs[i]), h)
    got = pipeline_apply(stage, (Ws, bs), x, mesh=mesh,
                         num_microbatches=8)
    np.testing.assert_allclose(np.asarray(h), np.asarray(got), atol=1e-6)

    def loss_seq(Ws, bs):
        h = x
        for i in range(P_):
            h = stage((Ws[i], bs[i]), h)
        return jnp.sum(h ** 2)

    def loss_pipe(Ws, bs):
        return jnp.sum(pipeline_apply(stage, (Ws, bs), x, mesh=mesh,
                                      num_microbatches=8) ** 2)

    g1 = jax.grad(loss_seq, argnums=(0, 1))(Ws, bs)
    g2 = jax.jit(jax.grad(loss_pipe, argnums=(0, 1)))(Ws, bs)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_moe_expert_parallel():
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.parallel import moe_apply
    E, D, B = 4, 6, 10
    rng = np.random.RandomState(0)
    Ws = jnp.asarray(rng.randn(E, D, D).astype(np.float32) * 0.4)
    x = jnp.asarray(rng.randn(B, D).astype(np.float32))
    gate = jnp.asarray(rng.randn(B, E).astype(np.float32))

    def expert(W, h):
        return jnp.tanh(h @ W)

    mesh = parallel.make_mesh({"expert": 4, "data": 2})
    got = moe_apply(expert, Ws, gate, x, mesh=mesh)
    probs = jax.nn.softmax(gate, -1)
    top = np.asarray(jnp.argmax(probs, -1))
    want = np.stack([np.asarray(probs[i, top[i]])
                     * np.asarray(expert(Ws[top[i]], x[i:i + 1])[0])
                     for i in range(B)])
    np.testing.assert_allclose(want, np.asarray(got), rtol=1e-5,
                               atol=1e-6)

"""Tests for mxnet_tpu.parallel — mesh construction and the fused SPMD
training step, run on the virtual 8-device CPU mesh (SURVEY §4: the TPU
analog of the reference's local-process fake cluster for kvstore tests)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, parallel
from mxnet_tpu.parallel import PartitionSpec as P


def _mlp(classes=10):
    net = gluon.nn.HybridSequential()
    with net.name_scope():
        net.add(gluon.nn.Dense(32, activation="relu"))
        net.add(gluon.nn.BatchNorm())
        net.add(gluon.nn.Dense(classes))
    net.initialize()
    return net


def test_make_mesh_axes():
    mesh = parallel.make_mesh({"data": 4, "model": 2})
    assert mesh.axis_names == ("data", "model")
    assert mesh.devices.shape == (4, 2)
    mesh2 = parallel.make_mesh({"data": -1, "model": 2})
    assert mesh2.devices.shape == (4, 2)
    with pytest.raises(mx.MXNetError):
        parallel.make_mesh({"data": 3, "model": 5})


def test_use_mesh_scope():
    mesh = parallel.make_mesh({"data": 4, "model": 2})
    with parallel.use_mesh(mesh) as m:
        assert parallel.current_mesh() is mesh
    # outside the scope the default (all-data) mesh is current again
    assert parallel.current_mesh().axis_names == ("data",)


def test_sharded_trainer_loss_decreases():
    net = _mlp()
    mesh = parallel.make_mesh({"data": 4, "model": 2})
    tr = parallel.ShardedTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        optimizer_params={"learning_rate": 0.5, "momentum": 0.9},
        mesh=mesh,
        param_rules=[(r".*dense0_weight", P("model", None))])
    rng = np.random.RandomState(0)
    x = rng.randn(64, 16).astype(np.float32)
    y = rng.randint(0, 10, (64,))
    losses = [tr.step(x, y).asscalar() for _ in range(10)]
    assert losses[-1] < losses[0] * 0.7
    assert np.isfinite(losses[-1])


def test_sharded_trainer_matches_eager_sgd():
    """The fused sharded step must produce the same result as the eager
    gluon.Trainer path (the reference's check_consistency method, §4)."""
    rng = np.random.RandomState(1)
    x = rng.randn(16, 8).astype(np.float32)
    y = rng.randint(0, 4, (16,))

    def make():
        net = gluon.nn.HybridSequential()
        with net.name_scope():
            net.add(gluon.nn.Dense(16, activation="tanh", in_units=8))
            net.add(gluon.nn.Dense(4, in_units=16))
        net.initialize(mx.init.Xavier(rnd_type="gaussian"))
        return net

    mx.random.seed(7)
    net_a = make()
    mx.random.seed(7)
    net_b = make()
    for pa, pb in zip(net_a.collect_params().values(),
                      net_b.collect_params().values()):
        np.testing.assert_allclose(pa.data().asnumpy(), pb.data().asnumpy())

    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    # eager path: forward/backward/step; grads divided by batch via step(B)
    trainer = gluon.Trainer(net_a.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    from mxnet_tpu import autograd
    for _ in range(3):
        with autograd.record():
            out = net_a(mx.nd.array(x))
            loss = loss_fn(out, mx.nd.array(y))
        loss.backward()
        trainer.step(x.shape[0])

    # fused sharded path: loss is mean over batch, rescale 1.0
    mesh = parallel.make_mesh({"data": 8})
    st = parallel.ShardedTrainer(net_b, loss_fn, "sgd",
                                 optimizer_params={"learning_rate": 0.1},
                                 mesh=mesh)
    for _ in range(3):
        st.step(x, y)

    for pa, pb in zip(net_a.collect_params().values(),
                      net_b.collect_params().values()):
        np.testing.assert_allclose(pa.data().asnumpy(), pb.data().asnumpy(),
                                   rtol=2e-4, atol=2e-5)


def test_sharded_trainer_adam_runs():
    net = _mlp(4)
    mesh = parallel.make_mesh({"data": 8})
    tr = parallel.ShardedTrainer(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                                 "adam", {"learning_rate": 1e-2}, mesh=mesh)
    x = np.random.randn(32, 12).astype(np.float32)
    y = np.random.randint(0, 4, (32,))
    l0 = tr.step(x, y).asscalar()
    for _ in range(5):
        l1 = tr.step(x, y).asscalar()
    assert l1 < l0


def test_evaluate_and_outputs():
    net = _mlp(6)
    mesh = parallel.make_mesh({"data": 8})
    tr = parallel.ShardedTrainer(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                                 "sgd", {"learning_rate": 0.1}, mesh=mesh)
    x = np.random.randn(16, 5).astype(np.float32)
    y = np.random.randint(0, 6, (16,))
    tr.step(x, y)
    ev = tr.evaluate(x, y)
    assert np.isfinite(ev.asscalar())
    assert tr.last_outputs[0].shape == (16, 6)


def test_graft_entry_dryrun():
    """The driver's multichip dry-run contract must keep working."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "__graft_entry__", "__graft_entry__.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    mod.dryrun_multichip(8)


def test_run_steps_matches_single_steps():
    """run_steps (lax.scan fused multi-step) must be bit-equal to N single
    steps for a deterministic model."""
    def make():
        net = gluon.nn.HybridSequential()
        with net.name_scope():
            net.add(gluon.nn.Dense(16, activation="tanh", in_units=8))
            net.add(gluon.nn.Dense(4, in_units=16))
        net.initialize(mx.init.Xavier())
        return net

    x = np.random.RandomState(0).randn(16, 8).astype(np.float32)
    y = np.random.RandomState(1).randint(0, 4, (16,))
    mx.random.seed(3)
    a = make()
    mx.random.seed(3)
    b = make()
    mesh = parallel.make_mesh({"data": 8})
    ta = parallel.ShardedTrainer(a, gluon.loss.SoftmaxCrossEntropyLoss(),
                                 "sgd", {"learning_rate": 0.1,
                                         "momentum": 0.9}, mesh=mesh)
    tb = parallel.ShardedTrainer(b, gluon.loss.SoftmaxCrossEntropyLoss(),
                                 "sgd", {"learning_rate": 0.1,
                                         "momentum": 0.9}, mesh=mesh)
    for _ in range(6):
        la = ta.step(x, y)
    lb = tb.run_steps(x, y, num_steps=6)
    assert abs(la.asscalar() - lb.asscalar()) < 1e-6
    for pa, pb in zip(a.collect_params().values(),
                      b.collect_params().values()):
        np.testing.assert_allclose(pa.data().asnumpy(),
                                   pb.data().asnumpy(), rtol=1e-5,
                                   atol=1e-6)


def test_pipeline_parallel_matches_sequential():
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.parallel import pipeline_apply
    P_, D, B = 4, 8, 16
    rng = np.random.RandomState(0)
    Ws = jnp.asarray(rng.randn(P_, D, D).astype(np.float32) * 0.3)
    bs = jnp.asarray(rng.randn(P_, D).astype(np.float32) * 0.1)
    x = jnp.asarray(rng.randn(B, D).astype(np.float32))

    def stage(params, h):
        W, b = params
        return jnp.tanh(h @ W + b)

    mesh = parallel.make_mesh({"pipe": 4, "data": 2})
    h = x
    for i in range(P_):
        h = stage((Ws[i], bs[i]), h)
    got = pipeline_apply(stage, (Ws, bs), x, mesh=mesh,
                         num_microbatches=8)
    np.testing.assert_allclose(np.asarray(h), np.asarray(got), atol=1e-6)

    def loss_seq(Ws, bs):
        h = x
        for i in range(P_):
            h = stage((Ws[i], bs[i]), h)
        return jnp.sum(h ** 2)

    def loss_pipe(Ws, bs):
        return jnp.sum(pipeline_apply(stage, (Ws, bs), x, mesh=mesh,
                                      num_microbatches=8) ** 2)

    g1 = jax.grad(loss_seq, argnums=(0, 1))(Ws, bs)
    g2 = jax.jit(jax.grad(loss_pipe, argnums=(0, 1)))(Ws, bs)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_moe_expert_parallel():
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.parallel import moe_apply
    E, D, B = 4, 6, 10
    rng = np.random.RandomState(0)
    Ws = jnp.asarray(rng.randn(E, D, D).astype(np.float32) * 0.4)
    x = jnp.asarray(rng.randn(B, D).astype(np.float32))
    gate = jnp.asarray(rng.randn(B, E).astype(np.float32))

    def expert(W, h):
        return jnp.tanh(h @ W)

    mesh = parallel.make_mesh({"expert": 4, "data": 2})
    got = moe_apply(expert, Ws, gate, x, mesh=mesh)
    probs = jax.nn.softmax(gate, -1)
    top = np.asarray(jnp.argmax(probs, -1))
    want = np.stack([np.asarray(probs[i, top[i]])
                     * np.asarray(expert(Ws[top[i]], x[i:i + 1])[0])
                     for i in range(B)])
    np.testing.assert_allclose(want, np.asarray(got), rtol=1e-5,
                               atol=1e-6)


def test_moe_topk_matches_dense_top1():
    """With k=1 and capacity ample, the all-to-all path must reproduce
    the dense-dispatch oracle exactly (VERDICT r3 #5 parity gate)."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.parallel import moe_apply, moe_apply_topk
    E, D, B = 4, 6, 16
    rng = np.random.RandomState(1)
    Ws = jnp.asarray(rng.randn(E, D, D).astype(np.float32) * 0.4)
    x = jnp.asarray(rng.randn(B, D).astype(np.float32))
    gate = jnp.asarray(rng.randn(B, E).astype(np.float32))

    def expert(W, h):
        return jnp.tanh(h @ W)

    mesh = parallel.make_mesh({"expert": 4, "data": 2})
    dense = moe_apply(expert, Ws, gate, x, mesh=mesh)
    sparse, aux, stats = moe_apply_topk(expert, Ws, gate, x, k=1,
                                        capacity_factor=float(E),
                                        mesh=mesh)
    assert float(stats["dropped"]) == 0.0
    np.testing.assert_allclose(np.asarray(sparse), np.asarray(dense),
                               rtol=1e-5, atol=1e-6)
    assert np.isfinite(float(aux))


def test_moe_topk_top2_oracle():
    """k=2 with ample capacity == softmax-top2-renormalized mixture,
    checked against a per-token numpy oracle."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.parallel import moe_apply_topk
    E, D, B = 4, 5, 8
    rng = np.random.RandomState(2)
    Ws = jnp.asarray(rng.randn(E, D, D).astype(np.float32) * 0.4)
    x = jnp.asarray(rng.randn(B, D).astype(np.float32))
    gate = jnp.asarray(rng.randn(B, E).astype(np.float32))

    def expert(W, h):
        return jnp.tanh(h @ W)

    mesh = parallel.make_mesh({"expert": 4, "data": 2})
    y, aux, stats = moe_apply_topk(expert, Ws, gate, x, k=2,
                                   capacity_factor=float(E), mesh=mesh)
    assert float(stats["dropped"]) == 0.0     # k>1 stat: per-slot fraction
    probs = np.asarray(jax.nn.softmax(gate, -1))
    want = np.zeros((B, D), np.float32)
    for i in range(B):
        top2 = np.argsort(-probs[i])[:2]
        w = probs[i, top2] / probs[i, top2].sum()
        for e, wi in zip(top2, w):
            want[i] += wi * np.asarray(expert(Ws[e], x[i:i + 1])[0])
    np.testing.assert_allclose(np.asarray(y), want, rtol=1e-4, atol=1e-5)


def test_moe_topk_per_device_compute_scales():
    """The defining property vs dense dispatch: each device's expert
    runs over k*B_local*cf tokens — O(tokens/E), not O(tokens)."""
    import jax.numpy as jnp
    from mxnet_tpu.parallel import moe_apply_topk
    D, B = 4, 32
    rng = np.random.RandomState(3)
    seen = {}

    for E, ax in ((2, {"expert": 2, "data": 4}),
                  (8, {"expert": 8})):
        Ws = jnp.asarray(rng.randn(E, D, D).astype(np.float32) * 0.3)
        x = jnp.asarray(rng.randn(B, D).astype(np.float32))
        gate = jnp.asarray(rng.randn(B, E).astype(np.float32))
        shapes = []

        def expert(W, h, _shapes=shapes):
            _shapes.append(h.shape)
            return h @ W

        mesh = parallel.make_mesh(ax)
        moe_apply_topk(expert, Ws, gate, x, k=1, capacity_factor=1.0,
                       mesh=mesh)
        seen[E] = shapes[0][0]
    # tokens processed per device = E * capacity = E * ceil(B/E^2)
    assert seen[2] == 2 * -(-32 // 4) == 16      # B/E with cf=1
    assert seen[8] == 8 * -(-32 // 64) == 8
    assert seen[8] < seen[2] < B


def test_moe_topk_capacity_drops_and_aux():
    """Adversarially skewed router: capacity 1.0 must drop overflow
    tokens (zero rows) and the Switch aux loss must exceed the balanced
    value of ~1."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.parallel import moe_apply_topk
    E, D, B = 4, 4, 16
    rng = np.random.RandomState(4)
    Ws = jnp.asarray(np.tile(np.eye(D, dtype=np.float32), (E, 1, 1)))
    x = jnp.asarray(rng.randn(B, D).astype(np.float32))
    # every token prefers expert 0
    gate = jnp.asarray(np.tile([8.0, 0.0, 0.0, 0.0],
                               (B, 1)).astype(np.float32))

    def expert(W, h):
        return h @ W

    mesh = parallel.make_mesh({"expert": 4, "data": 2})
    y, aux, stats = moe_apply_topk(expert, Ws, gate, x, k=1,
                                   capacity_factor=1.0, mesh=mesh)
    # capacity = ceil(1*4*1.0/4) = 1 per expert => 4 of 16 tokens kept
    assert abs(float(stats["dropped"]) - 12 / 16) < 1e-6
    kept_rows = (np.abs(np.asarray(y)).sum(-1) > 0).sum()
    assert kept_rows == 4
    assert float(aux) > 2.0          # skew >> balanced value 1.0

    # balanced router: aux ~ 1, nothing dropped at cf=1 with uniform
    # assignment pattern
    gate_b = jnp.asarray(np.tile(np.eye(E, dtype=np.float32) * 8.0,
                                 (B // E, 1)))
    y2, aux2, stats2 = moe_apply_topk(expert, Ws, gate_b, x, k=1,
                                      capacity_factor=1.0, mesh=mesh)
    assert float(stats2["dropped"]) == 0.0
    assert abs(float(aux2) - 1.0) < 0.05
    # identity experts at gate prob ~0.999 (softmax of logit 8):
    # outputs ~= inputs
    np.testing.assert_allclose(np.asarray(y2), np.asarray(x), rtol=2e-3,
                               atol=5e-3)


def test_moe_topk_gradients_flow():
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.parallel import moe_apply_topk
    E, D, B = 2, 4, 8
    rng = np.random.RandomState(5)
    Ws = jnp.asarray(rng.randn(E, D, D).astype(np.float32) * 0.3)
    x = jnp.asarray(rng.randn(B, D).astype(np.float32))
    gate = jnp.asarray(rng.randn(B, E).astype(np.float32))
    mesh = parallel.make_mesh({"expert": 2, "data": 4})

    def loss(Ws, gate):
        y, aux, _ = moe_apply_topk(lambda W, h: jnp.tanh(h @ W), Ws,
                                   gate, x, k=2, capacity_factor=2.0,
                                   mesh=mesh)
        return jnp.sum(y ** 2) + 0.01 * aux

    gW, gg = jax.jit(jax.grad(loss, argnums=(0, 1)))(Ws, gate)
    assert np.isfinite(np.asarray(gW)).all()
    assert np.isfinite(np.asarray(gg)).all()
    assert np.abs(np.asarray(gW)).sum() > 0
    assert np.abs(np.asarray(gg)).sum() > 0   # gate grads via combine


def test_pipeline_interleaved_matches_sequential():
    """Circular schedule with v virtual stages per device (VERDICT r3
    #6): same numerics as sequential layer application, smaller bubble."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.parallel import pipeline_apply, pipeline_schedule_info
    P_, V, D, B, M = 4, 2, 6, 16, 8
    L = P_ * V
    rng = np.random.RandomState(6)
    Ws = jnp.asarray(rng.randn(L, D, D).astype(np.float32) * 0.3)
    bs = jnp.asarray(rng.randn(L, D).astype(np.float32) * 0.1)
    x = jnp.asarray(rng.randn(B, D).astype(np.float32))

    def stage(params, h):
        W, b = params
        return jnp.tanh(h @ W + b)

    # device d owns layers {d, P+d}: ring order visits 0,1,2,3,4,...,7
    h = x
    for l in range(L):
        h = stage((Ws[l], bs[l]), h)

    mesh = parallel.make_mesh({"pipe": 4, "data": 2})
    got = pipeline_apply(stage, (Ws, bs), x, mesh=mesh,
                         num_microbatches=M, num_virtual_stages=V)
    np.testing.assert_allclose(np.asarray(h), np.asarray(got), atol=1e-6)

    # gradients transpose through the wrapped schedule too
    def loss_pipe(Ws, bs):
        return jnp.sum(pipeline_apply(stage, (Ws, bs), x, mesh=mesh,
                                      num_microbatches=M,
                                      num_virtual_stages=V) ** 2)

    def loss_seq(Ws, bs):
        h = x
        for l in range(L):
            h = stage((Ws[l], bs[l]), h)
        return jnp.sum(h ** 2)

    g1 = jax.grad(loss_seq, argnums=(0, 1))(Ws, bs)
    g2 = jax.jit(jax.grad(loss_pipe, argnums=(0, 1)))(Ws, bs)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)

    # bubble accounting: interleaving divides the bubble TIME by v at
    # fixed L (GPipe tick costs v layers; circular tick costs one)
    gpipe = pipeline_schedule_info(P_, M, 1)
    inter = pipeline_schedule_info(P_, M, V)
    gpipe_bubble_layers = (P_ - 1) * V          # v layers idle per slot
    inter_bubble_layers = P_ - 1
    assert inter_bubble_layers * V == gpipe_bubble_layers
    assert inter["bubble_fraction"] < gpipe["bubble_fraction"]


def test_pipeline_heterogeneous_embed_head_trains():
    """A REAL 4-stage model — embedding -> 4 transformer-ish blocks ->
    vocab head — trains to decreasing loss on the 8-device mesh
    (VERDICT r3 #6 'Done' gate)."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.parallel import pipeline_apply
    P_, D, V_TOK, B, S, M = 4, 16, 11, 8, 6, 4
    rng = np.random.RandomState(7)
    emb = jnp.asarray(rng.randn(V_TOK, D).astype(np.float32) * 0.3)
    Ws = jnp.asarray(rng.randn(P_, D, D).astype(np.float32) * 0.3)
    bs = jnp.asarray(np.zeros((P_, D), np.float32))
    head = jnp.asarray(rng.randn(D, V_TOK).astype(np.float32) * 0.3)
    toks = jnp.asarray(rng.randint(0, V_TOK, (B, S)).astype(np.int32))
    labels = jnp.asarray(rng.randint(0, V_TOK, (B, S)).astype(np.int32))

    def embed(p, t):
        return p[t]                             # (Bm, S, D)

    def block(params, h):
        W, b = params
        return h + jnp.tanh(h @ W + b)

    def head_fn(p, h):
        return h @ p                            # (N, S, V)

    mesh = parallel.make_mesh({"pipe": 4, "data": 2})

    def loss_fn(params):
        emb_p, Ws_p, bs_p, head_p = params
        logits = pipeline_apply(block, (Ws_p, bs_p), toks, mesh=mesh,
                                num_microbatches=M,
                                embed_fn=embed, embed_params=emb_p,
                                head_fn=head_fn, head_params=head_p)
        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.take_along_axis(logp, labels[..., None],
                                    axis=-1).mean()

    params = (emb, Ws, bs, head)
    step = jax.jit(jax.value_and_grad(loss_fn))
    losses = []
    for _ in range(20):
        l, g = step(params)
        params = jax.tree_util.tree_map(lambda p, gg: p - 0.5 * gg,
                                        params, g)
        losses.append(float(l))
    assert losses[-1] < losses[0] * 0.8, losses
    # every parameter group actually learned (nonzero grads)
    _, g = step(params)
    for t in jax.tree_util.tree_leaves(g):
        assert np.abs(np.asarray(t)).sum() > 0


def test_pipeline_heterogeneous_oracle():
    """Embed/head pipeline output equals the sequential oracle."""
    import jax.numpy as jnp
    from mxnet_tpu.parallel import pipeline_apply
    P_, D, V_TOK, B, S = 4, 8, 7, 8, 3
    rng = np.random.RandomState(8)
    emb = jnp.asarray(rng.randn(V_TOK, D).astype(np.float32) * 0.5)
    Ws = jnp.asarray(rng.randn(P_, D, D).astype(np.float32) * 0.3)
    bs = jnp.asarray(rng.randn(P_, D).astype(np.float32) * 0.1)
    head = jnp.asarray(rng.randn(D, V_TOK).astype(np.float32) * 0.5)
    toks = jnp.asarray(rng.randint(0, V_TOK, (B, S)).astype(np.int32))

    def block(params, h):
        W, b = params
        return jnp.tanh(h @ W + b)

    h = emb[toks]
    for i in range(P_):
        h = block((Ws[i], bs[i]), h)
    want = h @ head

    mesh = parallel.make_mesh({"pipe": 4, "data": 2})
    got = pipeline_apply(block, (Ws, bs), toks, mesh=mesh,
                         num_microbatches=4,
                         embed_fn=lambda p, t: p[t], embed_params=emb,
                         head_fn=lambda p, hh: hh @ p, head_params=head)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)


def test_sharded_run_steps_respects_lr_schedule():
    """The scanned multi-step path must apply the scheduler's per-step lr
    (regression: a frozen first-step lr changes warmup/decay math)."""
    import numpy as np
    from mxnet_tpu import lr_scheduler

    rng = np.random.RandomState(0)
    x = rng.randn(16, 6).astype(np.float32)
    y = rng.randint(0, 4, (16,))
    mesh = parallel.make_mesh({"data": 8})

    def build():
        mx.random.seed(17)
        net = gluon.nn.HybridSequential()
        net.add(gluon.nn.Dense(8, activation="relu"), gluon.nn.Dense(4))
        net.initialize()
        opt = mx.optimizer.create(
            "sgd", learning_rate=0.2, momentum=0.9,
            lr_scheduler=lr_scheduler.FactorScheduler(step=2, factor=0.5))
        return net, parallel.ShardedTrainer(
            net, gluon.loss.SoftmaxCrossEntropyLoss(), opt, mesh=mesh)

    net_a, tr_a = build()
    for _ in range(4):
        tr_a.step(x, y)
    wa = [np.asarray(p._data[0]._data) for p in tr_a._trainable]

    net_b, tr_b = build()
    tr_b.run_steps(x, y, num_steps=4)
    wb = [np.asarray(p._data[0]._data) for p in tr_b._trainable]
    for a, b in zip(wa, wb):
        np.testing.assert_allclose(a, b, rtol=2e-5, atol=2e-6)

"""mxnet_tpu.resilience: retry/backoff contract (delay bounds asserted
against the documented formula), journaled retries, preemption watch
(real SIGTERM), fit(checkpoint_prefix/resume) including corrupt-latest
fallback, do_checkpoint retention + prefix-dir creation, and the
kvstore coordination-service retry."""
import json
import os
import random

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import callback, model, sym
from mxnet_tpu.base import MXNetError
from mxnet_tpu.diagnostics import journal
from mxnet_tpu.resilience import preempt, retry
from mxnet_tpu.testing import faults
import mxnet_tpu.io as mio


# -- retry / backoff ---------------------------------------------------------

def test_backoff_delay_bounds_and_cap():
    """Delay i must lie in [b_i, b_i*(1+jitter)], b_i = min(base*2^i,
    max_s) — the documented bound drivers budget against."""
    rng = random.Random(42)
    base_s, max_s, jitter = 0.05, 2.0, 0.5
    delays = retry.backoff_delays(12, base_s, max_s, jitter, rng=rng)
    assert len(delays) == 12
    for i, d in enumerate(delays):
        b = min(base_s * 2 ** i, max_s)
        assert b <= d <= b * (1 + jitter), (i, d, b)
    # the cap engages: late delays never exceed max_s*(1+jitter)
    assert max(delays) <= max_s * (1 + jitter)
    # no jitter -> exact schedule
    assert retry.backoff_delays(3, 0.1, 2.0, jitter=0) == \
        [0.1, 0.2, 0.4]


def test_retry_call_retries_then_succeeds_and_journals(tmp_path):
    jf = str(tmp_path / "j.jsonl")
    journal.reset_journal(jf)
    try:
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise OSError(5, "transient")
            return "ok"

        slept = []
        assert retry.retry_call(flaky, retries=4, base_s=0.001,
                                sleep=slept.append) == "ok"
        assert len(calls) == 3 and len(slept) == 2
        recs = [json.loads(line) for line in open(jf)]
        assert [r["attempt"] for r in recs if r["kind"] == "retry"] == [1, 2]
    finally:
        journal.reset_journal()


def test_retry_exhaustion_reraises_original():
    def dead():
        raise OSError(5, "still dead")
    with pytest.raises(OSError, match="still dead"):
        retry.retry_call(dead, retries=2, base_s=0.0, sleep=lambda s: None)


def test_retry_never_absorbs_crashes():
    """SimulatedCrash is a BaseException: the retry layer must let it
    fly (a kill is not a transient fault)."""
    def boom():
        raise faults.SimulatedCrash("write", "x")
    calls = []
    with pytest.raises(faults.SimulatedCrash):
        retry.retry_call(lambda: (calls.append(1), boom()),
                         retries=5, base_s=0.0, sleep=lambda s: None)
    assert len(calls) == 1


def test_retry_env_defaults(monkeypatch):
    monkeypatch.setenv("MXNET_TPU_RETRIES", "0")
    calls = []

    def flaky():
        calls.append(1)
        raise OSError(5, "x")
    with pytest.raises(OSError):
        retry.retry_call(flaky, sleep=lambda s: None)
    assert len(calls) == 1                       # 0 retries honored


# -- preemption watch --------------------------------------------------------

def test_preempt_watch_real_sigterm_and_consume_once():
    watch = preempt.install()
    watch.clear()
    assert not watch.requested() and not watch.consume()
    faults.sigterm()                             # real signal, latched
    assert watch.requested()
    assert watch.consume()
    assert not watch.consume(), "consume must hand the save to ONE caller"
    assert watch.requested(), "requested() stays observable"
    watch.clear()
    assert not watch.requested()


# -- module.fit integration --------------------------------------------------

def _net():
    data = sym.var("data")
    fc = sym.FullyConnected(data, num_hidden=4, name="fc")
    return sym.SoftmaxOutput(fc, name="softmax")


def _iter(seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(16, 6).astype(np.float32)
    y = rng.randint(0, 4, (16,)).astype(np.float32)
    return mio.NDArrayIter(x, y, batch_size=8)


def test_fit_checkpoints_with_retention_and_created_dir(tmp_path):
    prefix = str(tmp_path / "made" / "dirs" / "mod")   # doesn't exist yet
    mod = mx.mod.Module(_net(), context=mx.cpu())
    mod.fit(_iter(), num_epoch=4, checkpoint_prefix=prefix, keep_last=2)
    assert model.list_checkpoint_epochs(prefix) == [3, 4]
    assert os.path.exists(prefix + "-symbol.json")


def test_fit_resume_skips_corrupt_latest_with_journal(tmp_path):
    jf = str(tmp_path / "j.jsonl")
    journal.reset_journal(jf)
    try:
        prefix = str(tmp_path / "mod")
        mod = mx.mod.Module(_net(), context=mx.cpu())
        mod.fit(_iter(), num_epoch=3, checkpoint_prefix=prefix)
        with open(prefix + "-0003.params", "r+b") as f:
            f.truncate(40)                       # torn newest
        mod2 = mx.mod.Module(_net(), context=mx.cpu())
        mod2.fit(_iter(), num_epoch=5, checkpoint_prefix=prefix,
                 resume=True)
        recs = [json.loads(line) for line in open(jf)]
        assert any(r["kind"] == "ckpt_fallback" and r["epoch"] == 3
                   for r in recs)
        assert any(r["kind"] == "resume" and r["epoch"] == 2
                   for r in recs)
        # epochs 3..5 re-ran and saved over the torn file
        assert model.list_checkpoint_epochs(prefix) == [1, 2, 3, 4, 5]
        arg, aux, epoch = model.load_latest_params(prefix)
        assert epoch == 5 and "fc_weight" in arg
    finally:
        journal.reset_journal()


def test_fit_resume_fresh_when_no_checkpoint(tmp_path):
    jf = str(tmp_path / "j.jsonl")
    journal.reset_journal(jf)
    try:
        prefix = str(tmp_path / "none" / "mod")
        mod = mx.mod.Module(_net(), context=mx.cpu())
        mod.fit(_iter(), num_epoch=1, checkpoint_prefix=prefix,
                resume=True)
        recs = [json.loads(line) for line in open(jf)]
        assert any(r["kind"] == "resume_fresh" for r in recs)
    finally:
        journal.reset_journal()


def test_fit_resume_requires_prefix():
    mod = mx.mod.Module(_net(), context=mx.cpu())
    with pytest.raises(MXNetError, match="checkpoint_prefix"):
        mod.fit(_iter(), num_epoch=1, resume=True)


def test_fit_preemption_saves_at_step_boundary_and_stops(tmp_path):
    """The preemption drill: SIGTERM mid-epoch -> one checkpoint at the
    next batch boundary, a preempt_checkpoint journal record, fit
    returns; resume then restarts the interrupted epoch."""
    jf = str(tmp_path / "j.jsonl")
    journal.reset_journal(jf)
    try:
        prefix = str(tmp_path / "p" / "mod")
        preempt.install().clear()
        fired = []

        def bomb(param):
            if not fired:
                fired.append(1)
                faults.sigterm()
        mod = mx.mod.Module(_net(), context=mx.cpu())
        mod.fit(_iter(), num_epoch=100, checkpoint_prefix=prefix,
                batch_end_callback=bomb)         # returns early, no kill
        recs = [json.loads(line) for line in open(jf)]
        pc = [r for r in recs if r["kind"] == "preempt_checkpoint"]
        assert len(pc) == 1 and pc[0]["epoch"] == 0
        assert any(r["kind"] == "preempt_requested" for r in recs)
        assert model.list_checkpoint_epochs(prefix) == [0]
        # resume re-runs the interrupted epoch 0
        preempt.install().clear()
        mod2 = mx.mod.Module(_net(), context=mx.cpu())
        mod2.fit(_iter(), num_epoch=2, checkpoint_prefix=prefix,
                 resume=True)
        recs = [json.loads(line) for line in open(jf)]
        assert any(r["kind"] == "resume" and r["epoch"] == 0 for r in recs)
        assert model.list_checkpoint_epochs(prefix) == [0, 1, 2]
    finally:
        preempt.install().clear()
        journal.reset_journal()


def test_fit_rearms_consumed_watch_across_runs(tmp_path):
    """A SIGTERM consumed by one fit() must not mute preemption
    handling for the next fit() in the same process — each run's entry
    re-arms the watch (a live unconsumed signal stays latched)."""
    jf = str(tmp_path / "j.jsonl")
    journal.reset_journal(jf)
    try:
        preempt.install().clear()
        for run in (1, 2):
            fired = []

            def bomb(param):
                if not fired:
                    fired.append(1)
                    faults.sigterm()
            mod = mx.mod.Module(_net(), context=mx.cpu())
            mod.fit(_iter(), num_epoch=100,
                    checkpoint_prefix=str(tmp_path / f"r{run}" / "mod"),
                    batch_end_callback=bomb)
            recs = [json.loads(line) for line in open(jf)]
            saves = [r for r in recs if r["kind"] == "preempt_checkpoint"]
            assert len(saves) == run, (run, [r["kind"] for r in recs])
        # and a live UNCONSUMED signal survives rearm: fit must save
        # immediately even though the SIGTERM predates the loop
        watch = preempt.install()
        watch.clear()
        faults.sigterm()
        mod = mx.mod.Module(_net(), context=mx.cpu())
        mod.fit(_iter(), num_epoch=100,
                checkpoint_prefix=str(tmp_path / "r3" / "mod"))
        recs = [json.loads(line) for line in open(jf)]
        assert len([r for r in recs
                    if r["kind"] == "preempt_checkpoint"]) == 3
    finally:
        preempt.install().clear()
        journal.reset_journal()


def test_fit_restores_sigterm_disposition(tmp_path):
    """After fit returns, nothing polls the watch — SIGTERM must fall
    back to the displaced disposition, not be silently latched forever
    (and bound-method identity must not defeat the restore)."""
    import signal
    mod = mx.mod.Module(_net(), context=mx.cpu())
    mod.fit(_iter(), num_epoch=1,
            checkpoint_prefix=str(tmp_path / "mod"))
    after = signal.getsignal(signal.SIGTERM)
    assert "PreemptionWatch" not in repr(after), after


def test_checkpoint_on_preempt_callback(tmp_path):
    prefix = str(tmp_path / "cb" / "mod")
    mod = mx.mod.Module(_net(), context=mx.cpu())
    cb = preempt.checkpoint_on_preempt(mod, prefix)
    preempt.install().clear()
    mod.bind(data_shapes=[("data", (8, 6))],
             label_shapes=[("softmax_label", (8,))])
    mod.init_params()

    class P:
        epoch, nbatch, eval_metric = 2, 5, None
    cb(P())                                      # no signal: no save
    assert model.list_checkpoint_epochs(prefix) == []
    faults.sigterm()
    cb(P())
    assert model.list_checkpoint_epochs(prefix) == [2]
    cb(P())                                      # consumed: saves once
    assert model.list_checkpoint_epochs(prefix) == [2]
    preempt.install().clear()


# -- do_checkpoint retention -------------------------------------------------

def test_do_checkpoint_keep_last_and_period(tmp_path):
    prefix = str(tmp_path / "sub" / "cls")
    net = _net()
    arg = {"fc_weight": mx.nd.ones((4, 6)), "fc_bias": mx.nd.zeros((4,))}
    cb = callback.do_checkpoint(prefix, period=2, keep_last=2)
    for epoch in range(8):
        cb(epoch, net, arg, {})
    # period=2 saved epochs 2,4,6,8; keep_last=2 kept 6,8
    assert model.list_checkpoint_epochs(prefix) == [6, 8]
    loaded_arg, _ = model.load_params(prefix, 8)
    assert np.array_equal(loaded_arg["fc_weight"].asnumpy(),
                          np.ones((4, 6), np.float32))


# -- kvstore coordination retry ---------------------------------------------

def test_ensure_distributed_retries_transient_connect(monkeypatch):
    import jax
    from mxnet_tpu import kvstore
    calls = []

    def flaky_init(**kw):
        calls.append(kw)
        if len(calls) < 3:
            raise ConnectionError("coordinator not up yet")

    monkeypatch.setattr(jax.distributed, "initialize", flaky_init)
    monkeypatch.setenv("MXTPU_COORD_ADDR", "127.0.0.1:1")
    monkeypatch.setenv("MXTPU_NUM_PROC", "1")
    monkeypatch.setenv("MXTPU_PROC_ID", "0")
    monkeypatch.setenv("MXNET_TPU_RETRY_BASE_S", "0.001")
    monkeypatch.setattr(kvstore, "_dist_initialized", False)
    try:
        kvstore._ensure_distributed()
        assert len(calls) == 3
        assert kvstore._dist_initialized
    finally:
        kvstore._dist_initialized = False

"""Native runtime tests (ref: tests/cpp/engine/threaded_engine_test.cc,
dmlc-core recordio tests — here driven from Python via ctypes)."""
import struct
import threading

import pytest

from mxnet_tpu import _native, recordio

_lib = _native.get_lib()
needs_native = pytest.mark.skipif(_lib is None,
                                  reason="native toolchain unavailable")


@needs_native
def test_native_recordio_roundtrip(tmp_path):
    path = str(tmp_path / "n.rec")
    w = _native.NativeWriter(path)
    payloads = [b"hello", b"x" * 1000,
                b"0123" + struct.pack("<I", 0xced7230a) + b"tail",
                b"", b"last"]
    for p in payloads:
        w.write(p)
    w.close()
    r = _native.NativeReader(path)
    for p in payloads:
        assert r.read() == p
    assert r.read() is None
    r.close()


@needs_native
def test_native_reads_python_written(tmp_path):
    """Cross-implementation byte compatibility, both directions."""
    path = str(tmp_path / "cross.rec")
    # python write (force fallback), native read
    w = recordio.MXRecordIO(path, "w")
    w._native = None
    w.fid = open(path, "wb")
    for i in range(5):
        w.write(f"rec{i}".encode())
    w.fid.close()
    r = _native.NativeReader(path)
    for i in range(5):
        assert r.read() == f"rec{i}".encode()
    r.close()
    # native write, python read
    path2 = str(tmp_path / "cross2.rec")
    w2 = _native.NativeWriter(path2)
    w2.write(b"abc")
    w2.close()
    r2 = recordio.MXRecordIO(path2, "r")
    r2._native and r2._native.close()
    r2._native = None
    r2.fid = open(path2, "rb")
    assert r2.read() == b"abc"


@needs_native
def test_native_prefetch_reader(tmp_path):
    path = str(tmp_path / "pf.rec")
    w = _native.NativeWriter(path)
    for i in range(100):
        w.write(f"record-{i:04d}".encode() * 10)
    w.close()
    r = _native.NativeReader(path, prefetch_depth=8)
    count = 0
    while True:
        rec = r.read()
        if rec is None:
            break
        assert rec.startswith(f"record-{count:04d}".encode())
        count += 1
    assert count == 100
    r.close()


@needs_native
def test_recordio_uses_native_by_default(tmp_path):
    path = str(tmp_path / "d.rec")
    w = recordio.MXRecordIO(path, "w")
    assert w._native is not None, "native writer should engage when built"
    w.write(b"payload")
    w.close()
    r = recordio.MXRecordIO(path, "r")
    assert r._native is not None
    assert r.read() == b"payload"
    r.close()


@needs_native
def test_engine_ordering_raw_war_waw():
    """The reference's engine-ordering stress (threaded_engine_test.cc):
    randomized dep graphs must execute in dependency order."""
    eng = _native.NativeEngine(num_workers=4)
    log = []
    lock = threading.Lock()

    def task(name):
        def run():
            with lock:
                log.append(name)
        return run

    a = eng.new_var()
    b = eng.new_var()
    # w1 writes a; r1,r2 read a; w2 writes a (waits for readers); w3 b
    eng.push(task("w1"), read_vars=[], write_vars=[a])
    eng.push(task("r1"), read_vars=[a], write_vars=[])
    eng.push(task("r2"), read_vars=[a], write_vars=[])
    eng.push(task("w2"), read_vars=[], write_vars=[a])
    eng.push(task("wb"), read_vars=[], write_vars=[b])
    eng.wait_all()
    assert set(log) == {"w1", "r1", "r2", "w2", "wb"}
    assert log.index("w1") < log.index("r1")
    assert log.index("w1") < log.index("r2")
    assert log.index("w2") > log.index("r1")
    assert log.index("w2") > log.index("r2")
    eng.close()


@needs_native
def test_engine_stress_counter():
    """Many sequential writes to one var must serialize (no lost updates
    without any Python-side locking)."""
    eng = _native.NativeEngine(num_workers=8)
    v = eng.new_var()
    state = {"x": 0}

    def incr():
        state["x"] = state["x"] + 1   # racy unless engine serializes

    for _ in range(200):
        eng.push(incr, read_vars=[], write_vars=[v])
    eng.wait_all()
    assert state["x"] == 200
    eng.close()


@needs_native
def test_engine_parallel_reads_do_run():
    eng = _native.NativeEngine(num_workers=4)
    v = eng.new_var()
    barrier = threading.Barrier(2, timeout=10)
    hits = []

    def reader():
        barrier.wait()     # both readers must be in flight simultaneously
        hits.append(1)

    eng.push(reader, read_vars=[v], write_vars=[])
    eng.push(reader, read_vars=[v], write_vars=[])
    eng.wait_all()
    assert len(hits) == 2
    eng.close()

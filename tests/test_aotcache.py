"""Persistent AOT compile cache (serving/aotcache.py, docs/serving.md).

The warm-restart acceptance criteria: a second Server start on the same
cache dir performs ZERO XLA compiles for the warmed bucket set
(``observability.compile_stats``), responses are bit-identical to the
cold-compiled run, and every corrupt/truncated/stale entry degrades to
a normal compile with a journaled ``aot_fallback`` — never a crash or
wrong output.  The crash-matrix-style fuzz drives the disk store
through truncation, bitflips, envelope mismatches, and concurrent
writers; the ``smoke`` tests run in CI tier 0.5.
"""
import json
import os
import threading

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import observability as obs
from mxnet_tpu.diagnostics.journal import reset_journal
from mxnet_tpu.gluon import nn
from mxnet_tpu.serving import AOTCache, Server, ServerConfig
from mxnet_tpu.serving import aot_report as fmt

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def journal_file(tmp_path):
    path = str(tmp_path / "journal.jsonl")
    reset_journal(path)
    try:
        yield path
    finally:
        reset_journal("stderr")


def _records(path, kind=None):
    out = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if kind is None or rec.get("kind") == kind:
                out.append(rec)
    return out


def _mlp(dim=16, activation="relu", seed=7):
    mx.random.seed(seed)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(32, activation=activation, in_units=dim))
        net.add(nn.Dense(8, in_units=32))
    net.initialize()
    return net


def _sync_params(src, dst):
    dst.load_dict({k: v.data() for k, v in
                   src._structural_names().items()}, ignore_extra=True)


def _one_entry(root):
    names = [n for n in os.listdir(root) if n.endswith(fmt.SUFFIX)]
    assert len(names) == 1, names
    return os.path.join(root, names[0])


# -- the warm-restart proof (CI tier 0.5) ------------------------------------

def test_aot_smoke_warm_restart_zero_compiles_bit_identical(
        tmp_path, journal_file):
    """serve -> stop -> restart on the same cache dir: the second start
    loads its whole warmed bucket set from disk (0 XLA compiles) and
    answers bit-identically to the cold-compiled run."""
    root = str(tmp_path / "aot")
    cfg = lambda: ServerConfig(max_batch=4, window_ms=1.0,    # noqa: E731
                               aot_dir=root, aot_prewarm=((16,),))
    xs = [np.arange(16, dtype=np.float32) * (i + 1) for i in range(3)]

    cold_net = _mlp()
    s1 = Server(cold_net, config=cfg()).start()
    cold = [np.asarray(s1.predict(x)) for x in xs]
    st1 = s1.stats()
    s1.stop()
    assert st1["aot"]["stores"] >= 3        # the lattice persisted
    assert st1["aot"]["fallbacks"] == 0

    obs.reset_metrics()
    warm_net = _mlp(seed=99)                # fresh block, same structure
    _sync_params(cold_net, warm_net)        # same checkpoint -> same answers
    s2 = Server(warm_net, config=cfg()).start()
    warm = [np.asarray(s2.predict(x)) for x in xs]
    st2 = s2.stats()
    s2.stop()

    cs = obs.compile_stats()
    assert cs["compiles"] == 0, cs          # the bounded-startup proof
    assert cs["aot_loads"] >= 3
    assert st2["aot"]["hits"] >= 3 and st2["aot"]["misses"] == 0
    for a, b in zip(cold, warm):
        assert np.array_equal(a, b)         # bit-identical, not close
    kinds = {r["kind"] for r in _records(journal_file)}
    assert "aot_store" in kinds and "aot_prewarm" in kinds
    assert "aot_fallback" not in kinds


def test_aot_smoke_corrupt_entry_degrades_to_compile(
        tmp_path, journal_file):
    """A bit-flipped entry (past the CRC staging) journals an
    ``aot_fallback``, compiles normally, and repairs the store —
    never a crash, never wrong output."""
    root = str(tmp_path / "aot")
    net = _mlp()
    cache = AOTCache(root)
    x = np.ones((2, 16), np.float32)
    p1 = cache.load_or_compile(net, (2, 16), np.float32)
    want, _ = p1(x)

    path = _one_entry(root)
    blob = bytearray(open(path, "rb").read())
    blob[-5] ^= 0xFF                        # body bitflip
    with open(path, "wb") as f:
        f.write(bytes(blob))

    c2 = AOTCache(root)
    p2 = c2.load_or_compile(net, (2, 16), np.float32)
    assert p2.aot == "compiled"             # degraded, then repaired
    got, _ = p2(x)
    assert all(np.array_equal(np.asarray(a), np.asarray(b))
               for a, b in zip(want, got))
    falls = _records(journal_file, "aot_fallback")
    assert falls and falls[-1]["reason"] == "section_crc"
    header, reason = fmt.validate_entry(path)
    assert reason is None and header is not None    # store repaired


# -- crash-matrix fuzz on the disk store -------------------------------------

def _corrupt(path, how):
    blob = bytearray(open(path, "rb").read())
    if how == "truncate_fixed":
        blob = blob[:8]
    elif how == "truncate_header":
        blob = blob[:20]
    elif how == "truncate_body":
        blob = blob[:len(blob) - 7]
    elif how == "bitflip_body":
        blob[-3] ^= 0x01
    elif how == "bitflip_header":
        blob[16] ^= 0x01
    elif how == "bad_magic":
        blob[:4] = b"NOPE"
    elif how == "garbage":
        blob = bytearray(os.urandom(64))
    elif how == "empty":
        blob = bytearray()
    with open(path, "wb") as f:
        f.write(bytes(blob))


_FUZZ_REASONS = {
    "truncate_fixed": {"truncated"},
    "truncate_header": {"truncated"},
    "truncate_body": {"section_len", "truncated"},
    "bitflip_body": {"section_crc"},
    "bitflip_header": {"header_crc", "header_json"},
    "bad_magic": {"magic"},
    "garbage": {"magic", "truncated"},
    "empty": {"truncated"},
}


@pytest.mark.parametrize("how", sorted(_FUZZ_REASONS))
def test_fuzz_reader_always_compiles_or_loads_valid(
        how, tmp_path, journal_file):
    """Every corruption shape: the reader either loads a CRC-valid
    entry or falls back to a compile with the fault journaled — the
    serving path never sees an exception or a half-read executable."""
    root = str(tmp_path / "aot")
    net = _mlp()
    AOTCache(root).load_or_compile(net, (1, 16), np.float32)
    path = _one_entry(root)
    _corrupt(path, how)

    cache = AOTCache(root)
    pred = cache.load_or_compile(net, (1, 16), np.float32)
    assert pred.aot == "compiled"
    outs, _ = pred(np.ones((1, 16), np.float32))
    assert np.asarray(outs[0]).shape == (1, 8)
    falls = _records(journal_file, "aot_fallback")
    assert falls, "fallback must be journaled"
    assert falls[-1]["reason"] in _FUZZ_REASONS[how], falls[-1]
    assert cache.stats()["fallbacks"] == 1


def test_envelope_mismatch_invalidates_never_loads(tmp_path,
                                                   journal_file):
    """An entry written by a different toolchain/topology re-packs as
    valid bytes but a MISMATCHED envelope: the reader must refuse to
    deserialize it (reason=envelope) and compile instead."""
    root = str(tmp_path / "aot")
    net = _mlp()
    AOTCache(root).load_or_compile(net, (1, 16), np.float32)
    path = _one_entry(root)
    header, sections, reason = fmt.read_entry(path)
    assert reason is None
    header["envelope"]["jaxlib"] = "0.0.1-other"    # stale toolchain
    with open(path, "wb") as f:
        f.write(fmt.pack_entry(
            {k: v for k, v in header.items()
             if k not in ("sections", "format")}, sections))

    cache = AOTCache(root)
    pred = cache.load_or_compile(net, (1, 16), np.float32)
    assert pred.aot == "compiled"
    falls = _records(journal_file, "aot_fallback")
    assert falls and falls[-1]["reason"] == "envelope"
    assert falls[-1]["entry_envelope"]["jaxlib"] == "0.0.1-other"


def test_concurrent_writers_pid_unique_staging(tmp_path):
    """N threads racing load_or_compile on the same key (fresh caches,
    one dir): the committed entry stays whole-document valid — atomic
    per-call-unique staging means no interleaved bytes, and replace
    order just picks a winner."""
    root = str(tmp_path / "aot")
    net = _mlp()
    errs = []

    def run():
        try:
            AOTCache(root).load_or_compile(net, (2, 16), np.float32)
        except Exception as e:             # pragma: no cover - must not
            errs.append(e)

    threads = [threading.Thread(target=run) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errs
    header, reason = fmt.validate_entry(_one_entry(root))
    assert reason is None
    assert header["key"]["shape"] == [2, 16]
    loaded = AOTCache(root).load(net, (2, 16), np.float32)
    assert loaded is not None and loaded.aot == "loaded"


# -- numerics: loaded == compiled, bit for bit -------------------------------

def test_loaded_vs_compiled_bit_parity_across_bucket_grid(tmp_path):
    """For EVERY cell of the bucket lattice: the deserialized
    executable answers bit-identically to the freshly compiled one on
    the same inputs."""
    root = str(tmp_path / "aot")
    net = _mlp()
    cache = AOTCache(root)
    rng = np.random.default_rng(3)
    for bucket in (1, 2, 4):
        shape = (bucket, 16)
        compiled = cache.load_or_compile(net, shape, np.float32)
        assert compiled.aot == "compiled"
        loaded = AOTCache(root).load(net, shape, np.float32)
        assert loaded is not None and loaded.aot == "loaded"
        x = rng.standard_normal(shape).astype(np.float32)
        a, _ = compiled(x)
        b, _ = loaded(x)
        for u, v in zip(a, b):
            assert np.array_equal(np.asarray(u), np.asarray(v))


# -- key schema --------------------------------------------------------------

def test_param_values_do_not_change_the_key_structure_does(tmp_path):
    root = str(tmp_path / "aot")
    cache = AOTCache(root)
    a = _mlp(seed=1)
    b = _mlp(seed=2)                       # same structure, new values
    assert cache.entry_path(a, (2, 16), np.float32) == \
        cache.entry_path(b, (2, 16), np.float32)
    # hot-reload keeps hitting: a reload swaps VALUES only
    c = _mlp(activation="tanh")            # different program
    assert cache.entry_path(a, (2, 16), np.float32) != \
        cache.entry_path(c, (2, 16), np.float32)
    # and shape/dtype split the key too
    assert cache.entry_path(a, (2, 16), np.float32) != \
        cache.entry_path(a, (4, 16), np.float32)


def test_structure_twin_with_different_program_never_cross_loads(
        tmp_path):
    """The relu and tanh MLPs share every parameter shape — only the
    fingerprint's block identity separates their entries.  A cross-load
    here would be wrong numerics, the one unforgivable failure."""
    root = str(tmp_path / "aot")
    relu = _mlp(activation="relu")
    tanh = _mlp(activation="tanh")
    _sync_params(relu, tanh)
    AOTCache(root).load_or_compile(relu, (1, 16), np.float32)
    assert AOTCache(root).load(tanh, (1, 16), np.float32) is None
    p = AOTCache(root).load_or_compile(tanh, (1, 16), np.float32)
    x = np.full((1, 16), 0.5, np.float32)
    got, _ = p(x)
    relu_out, _ = AOTCache(root).load(relu, (1, 16), np.float32)(x)
    assert not np.array_equal(np.asarray(got[0]),
                              np.asarray(relu_out[0]))


# -- GC + modes --------------------------------------------------------------

def test_gc_lru_under_byte_budget(tmp_path, journal_file):
    root = str(tmp_path / "aot")
    net = _mlp()
    one = AOTCache(root)
    one.load_or_compile(net, (1, 16), np.float32)
    entry_bytes = os.path.getsize(_one_entry(root))
    # budget fits ~2 entries; storing 4 shapes must evict the oldest
    cache = AOTCache(root, max_bytes=int(entry_bytes * 2.5))
    for bucket in (2, 4, 8):
        cache.load_or_compile(net, (bucket, 16), np.float32)
    names = [n for n in os.listdir(root) if n.endswith(fmt.SUFFIX)]
    total = sum(os.path.getsize(os.path.join(root, n)) for n in names)
    assert total <= int(entry_bytes * 2.5)
    assert cache.stats()["evictions"] >= 1
    gcs = _records(journal_file, "aot_gc")
    assert gcs and gcs[-1]["evicted"] >= 1


def test_ro_mode_loads_but_never_writes(tmp_path):
    root = str(tmp_path / "aot")
    net = _mlp()
    AOTCache(root).load_or_compile(net, (1, 16), np.float32)
    before = sorted(os.listdir(root))
    ro = AOTCache(root, mode="ro")
    assert ro.load(net, (1, 16), np.float32).aot == "loaded"
    p = ro.load_or_compile(net, (2, 16), np.float32)   # miss: compiles
    assert p.aot == "compiled"
    assert sorted(os.listdir(root)) == before           # nothing written
    assert ro.stats()["stores"] == 0


def test_kill_switch_and_bad_mode(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_TPU_AOT_CACHE", "off")
    assert AOTCache.maybe(str(tmp_path / "x")) is None
    assert AOTCache.maybe(None) is None
    monkeypatch.setenv("MXNET_TPU_AOT_CACHE", "bogus")
    cache = AOTCache(str(tmp_path / "y"))
    assert cache.mode == "rw"               # malformed degrades, journaled


# -- serving integration -----------------------------------------------------

def test_prewarm_report_and_doctor_surfaces(tmp_path, journal_file):
    root = str(tmp_path / "aot")
    cfg = ServerConfig(max_batch=4, aot_dir=root,
                       dim_buckets={0: [16]})
    server = Server(_mlp(), config=cfg)
    res = server.prewarm(((16,), (999,)))   # second shape exceeds grid
    assert res["compiled"] == 3 and res["loaded"] == 0
    assert res["skipped"] == [[999]]
    res2 = Server(_mlp(), config=cfg).prewarm(((16,),))
    assert res2["loaded"] == 3 and res2["compiled"] == 0

    # stdlib directory audit (doctor --aot-dir)
    rep = fmt.aot_report(root)
    assert rep["ok"] and rep["entries"] == 3 and rep["corrupt_total"] == 0
    # journal reduction (doctor --serving-journal): the report anchors
    # at the last serving_start, so the warm run's own prewarm (all
    # disk loads) is what lands in the aot section
    from mxnet_tpu.serving import serving_report
    cfg2 = ServerConfig(max_batch=4, aot_dir=root,
                        dim_buckets={0: [16]}, aot_prewarm=((16,),))
    server2 = Server(_mlp(), config=cfg2).start()
    server2.predict(np.ones(16, np.float32))
    server2.stop()
    sv = serving_report(journal_file)
    assert sv["ok"] and sv["aot"]["fallback_total"] == 0
    assert sv["aot"]["prewarmed"]["loaded"] >= 3
    assert sv["aot"]["prewarmed"]["compiled"] == 0


def test_prewarm_without_disk_tier_is_eager_and_counted(journal_file):
    """Prewarm with NO aot_dir still builds READY executables: the
    compiles happen (and are counted) at prewarm time, and the first
    real request must not smuggle an untimed compile into exec_ms
    behind a cache hit."""
    obs.reset_metrics()
    cfg = ServerConfig(max_batch=2, window_ms=1.0, aot_dir=None,
                       aot_prewarm=((16,),))
    server = Server(_mlp(), config=cfg).start()
    try:
        cs = obs.compile_stats()
        assert cs["compiles"] == 2 and cs["aot_loads"] == 0, cs
        pre = [r for r in _records(journal_file, "aot_prewarm")]
        assert pre[-1]["compiled"] == 2 and pre[-1]["loaded"] == 0
        obs.reset_metrics()
        server.predict(np.ones(16, np.float32))
        assert obs.compile_stats()["compiles"] == 0   # nothing hidden
    finally:
        server.stop()


def test_fleet_page_in_restores_executables(tmp_path, journal_file):
    """max_hot=1 fleet, two tenants: serving B pages A out (predictors
    dropped); serving A again pages it back in and RESTORES its warm
    shapes from disk — journaled in the page-in record, zero new
    compiles for the restored shape."""
    from mxnet_tpu.serving import Fleet, FleetConfig
    root = str(tmp_path / "aot")
    cfg = FleetConfig(max_batch=2, window_ms=1.0, aot_dir=root,
                      max_hot_tenants=1, reload_poll_s=-1.0)
    fleet = Fleet(cfg)
    net_a, net_b = _mlp(seed=1), _mlp(seed=2)
    fleet.add_tenant("a", block=net_a)
    fleet.add_tenant("b", block=net_b)
    fleet.start()
    try:
        x = np.ones(16, np.float32)
        first = np.asarray(fleet.predict(x, tenant="a"))
        np.asarray(fleet.predict(x, tenant="b"))   # pages a out
        obs.reset_metrics()
        again = np.asarray(fleet.predict(x, tenant="a"))  # pages a in
    finally:
        fleet.stop()
    assert np.array_equal(first, again)
    cs = obs.compile_stats()
    assert cs["compiles"] == 0, cs          # restore loaded, not compiled
    page_ins = _records(journal_file, "tenant_page_in")
    restored = [r for r in page_ins if r["tenant"] == "a"
                and r.get("predictors_restored", 0) >= 1]
    assert restored, page_ins
    assert restored[-1]["restore_ms"] is not None
    assert "restore_ms" in restored[-1] and "cost_ms" in restored[-1]


def test_fleet_restore_is_load_only_never_a_compile_storm(
        tmp_path, journal_file):
    """Page-in restore with a COLD disk (entries GC'd / store never
    seeded) must skip, not recompile: the warm-shape set is a hint,
    and paging back in must not stall the worker on eager compiles of
    shapes that may never recur."""
    from mxnet_tpu.serving import Fleet, FleetConfig
    root = str(tmp_path / "aot")
    cfg = FleetConfig(max_batch=2, window_ms=1.0, aot_dir=root,
                      max_hot_tenants=1, reload_poll_s=-1.0)
    fleet = Fleet(cfg)
    fleet.add_tenant("a", block=_mlp(seed=1))
    fleet.add_tenant("b", block=_mlp(seed=2))
    fleet.start()
    try:
        x = np.ones(16, np.float32)
        fleet.predict(x, tenant="a")
        fleet.predict(x, tenant="b")            # pages a out
        for n in os.listdir(root):              # wipe the disk tier
            if n.endswith(fmt.SUFFIX):
                os.unlink(os.path.join(root, n))
        fleet.predict(x, tenant="a")            # pages a back in
    finally:
        fleet.stop()
    page_ins = [r for r in _records(journal_file, "tenant_page_in")
                if r["tenant"] == "a"]
    assert page_ins[-1]["predictors_restored"] == 0, page_ins[-1]
    # the tenant still serves: its first post-page-in batch compiled
    # on demand (write-through repopulated the store)
    assert any(n.endswith(fmt.SUFFIX) for n in os.listdir(root))


def test_warm_shapes_capped_at_per_tenant_share(tmp_path):
    """One tenant's remembered warm set is bounded by its SHARE of the
    predictor cache (cache_entries / max_hot_tenants) — a page-in
    restore must not be able to evict every other tenant's
    executables."""
    from mxnet_tpu.serving import Fleet, FleetConfig
    cfg = FleetConfig(max_batch=8, window_ms=1.0, cache_entries=8,
                      max_hot_tenants=4, reload_poll_s=-1.0)
    fleet = Fleet(cfg)
    fleet.add_tenant("a", block=_mlp())
    ts = fleet.tenants["a"]
    fleet.start()
    try:
        for bucket in (1, 2, 4, 8):
            fleet.predict(np.ones(16, np.float32), tenant="a")
            # distinct buckets come from batch coalescing; force the
            # shapes directly instead of racing the window
        with fleet._tlock:
            for i in range(6):
                ts.warm_shapes[(1, (16 + i,))] = True
        fleet._acquire_predictor(
            [type("R", (), {"tenant": "a", "key": (16,)})()], 1, (16,))
    finally:
        fleet.stop()
    assert len(ts.warm_shapes) <= max(1, 8 // 4)


def test_pool_env_inherits_cache_dir(tmp_path):
    """ProcReplica workers get MXNET_TPU_AOT_CACHE_DIR stamped from
    PoolConfig.aot_dir — the rolling-reload warm-restart contract."""
    from mxnet_tpu.serving import PoolConfig, ReplicaPool
    root = str(tmp_path / "pool")
    aot = str(tmp_path / "aot")
    pool = ReplicaPool(root, PoolConfig(heartbeat_s=0.2, deadline_s=1.0,
                                        aot_dir=aot))
    pool.add_proc("r0", {"--model": "scale"})
    assert pool.replicas["r0"].env["MXNET_TPU_AOT_CACHE_DIR"] == aot


def test_warm_cli_refuses_unwritable_cache_before_compiling(
        tmp_path, monkeypatch, capsys):
    """`warm` with the kill switch (or ro mode) must fail BEFORE paying
    the lattice compile — a deploy trusting exit 0 would start cold."""
    from mxnet_tpu.serving.__main__ import main
    for mode in ("off", "ro"):
        monkeypatch.setenv("MXNET_TPU_AOT_CACHE", mode)
        rc = main(["warm", "--dir", str(tmp_path / mode), "--model",
                   "scale", "--dim", "4"])
        assert rc == 1
        doc = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert doc["error"] == "aot_cache_not_writable"
        made = str(tmp_path / mode)
        if os.path.isdir(made):        # ro constructs the dir; off doesn't
            assert not any(n.endswith(fmt.SUFFIX)
                           for n in os.listdir(made))


@pytest.mark.slow
def test_warm_cli_then_warm_server(tmp_path):
    """Offline `serving warm --dir` in a SUBPROCESS persists the
    lattice; a fresh process's Server then starts with zero compiles —
    the cross-process half of the warm-start story."""
    import subprocess
    import sys
    root = str(tmp_path / "aot")
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "PYTHONPATH": REPO}
    out = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.serving", "warm",
         "--dir", root, "--model", "mlp", "--dim", "16"],
        capture_output=True, text=True, timeout=240, env=env, cwd=REPO)
    assert out.returncode == 0, out.stderr[-2000:]
    doc = json.loads(out.stdout.strip().splitlines()[-1])
    assert doc["metric"] == "aot_warm_entries" and doc["value"] == 4
    assert doc["dir_report"]["entries"] == 4

    probe = subprocess.run(
        [sys.executable, "-c",
         "import numpy as np, json\n"
         "from mxnet_tpu.serving import Server, ServerConfig\n"
         "from mxnet_tpu.serving.worker import _build_block\n"
         "from mxnet_tpu import observability as obs\n"
         f"cfg = ServerConfig(max_batch=8, aot_dir={root!r},\n"
         "                   aot_prewarm=((16,),))\n"
         "s = Server(_build_block('mlp', 16), config=cfg).start()\n"
         "s.predict(np.ones(16, np.float32)); s.stop()\n"
         "print(json.dumps(obs.compile_stats()))"],
        capture_output=True, text=True, timeout=240, env=env, cwd=REPO)
    assert probe.returncode == 0, probe.stderr[-2000:]
    cs = json.loads(probe.stdout.strip().splitlines()[-1])
    assert cs["compiles"] == 0 and cs["aot_loads"] == 4, cs


# -- tensor-parallel keys (serving/shardplan.py joins the key material) ------

def _two_device_plan():
    import jax

    from mxnet_tpu.serving.shardplan import ShardPlan
    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices")
    return ShardPlan(axes={"model": 2}, devices=jax.devices()[:2])


def test_plan_joins_the_cache_key(tmp_path):
    """The same model served single-device and sharded must occupy two
    distinct entries: a tensor-parallel executable is only valid on its
    exact mesh shape."""
    cache = AOTCache.maybe(str(tmp_path / "aot"))
    net = _mlp()
    plan = _two_device_plan()
    fp_plain = cache.fingerprint(net, np.float32)
    fp_plan = cache.fingerprint(net, np.float32, plan=plan)
    assert fp_plain != fp_plan
    assert cache.entry_path(net, (8, 16), np.float32) != \
        cache.entry_path(net, (8, 16), np.float32, plan=plan)
    # a DIFFERENT mesh shape is a different key again
    import jax
    if len(jax.devices()) >= 4:
        from mxnet_tpu.serving.shardplan import ShardPlan
        plan4 = ShardPlan(axes={"model": 4}, devices=jax.devices()[:4])
        assert cache.fingerprint(net, np.float32, plan=plan4) != fp_plan


def test_plan_none_key_is_byte_compatible_with_the_historical_scheme(
        tmp_path):
    """``plan=None`` must contribute NOTHING to the hash — existing
    single-device caches stay warm across this change.  The expected
    digest below is the pre-plan recipe recomputed by hand; if this
    test breaks, every deployed cache goes cold on upgrade."""
    import hashlib

    from mxnet_tpu.serving.cache import key_spec
    cache = AOTCache.maybe(str(tmp_path / "aot"))
    net = _mlp()
    parts = [f"{type(net).__module__}.{type(net).__qualname__}",
             repr(net), str(np.dtype(np.float32))]
    names = net._structural_names()
    parts.append("|".join(
        f"{k}:{tuple(p.shape) if p.shape else ()}"
        for k, p in sorted(names.items())))
    trainable, aux = net._param_split()
    for tag, params in (("tr", trainable), ("aux", aux)):
        for p in params:
            d = p._data[0]._data
            parts.append(f"{tag}:{tuple(d.shape)}:{d.dtype}")
    parts.append(str(key_spec().dtype))
    expected = hashlib.sha1(
        "\x1f".join(parts).encode("utf-8", "replace")).hexdigest()
    assert cache.fingerprint(net, np.float32) == expected
    assert cache.fingerprint(net, np.float32, plan=None) == expected


def test_sharded_store_load_roundtrip_bit_identical(tmp_path):
    """A sharded executable stores and loads under its plan key, and
    the loaded predictor's outputs match the compiled one bitwise."""
    from mxnet_tpu.serving.cache import CompiledPredictor
    cache = AOTCache.maybe(str(tmp_path / "aot"))
    net = _mlp()
    plan = _two_device_plan()
    plan.place(net, site="test")
    pred = CompiledPredictor(net, plan=plan)
    pred.aot_compile((8, 16), np.float32)
    assert cache.store(pred, net, (8, 16), np.float32, plan=plan)
    got = cache.load(net, (8, 16), np.float32, plan=plan)
    assert got is not None
    x = np.random.default_rng(3).standard_normal((8, 16)) \
        .astype(np.float32)
    a, _ = pred(x)
    b, _ = got(x)
    for u, v in zip(a, b):
        assert np.array_equal(np.asarray(u), np.asarray(v))
    # the plain (no-plan) key does NOT see the sharded entry
    assert cache.load(net, (8, 16), np.float32) is None

"""Transformer NMT tests (driver config #4: Sockeye-style seq2seq —
a tiny copy task must be learnable)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon.model_zoo import transformer


def _tiny(src_vocab=16, tgt_vocab=16):
    return transformer.TransformerModel(
        src_vocab, tgt_vocab, num_layers=2, units=32, hidden_size=64,
        num_heads=4, max_length=32, dropout=0.0)


def test_forward_shapes():
    net = _tiny()
    net.initialize()
    src = mx.nd.array(np.random.randint(0, 16, (2, 7)))
    tgt = mx.nd.array(np.random.randint(0, 16, (2, 5)))
    logits = net(src, tgt)
    assert logits.shape == (2, 5, 16)


def test_causal_decoder():
    """Changing future target tokens must not affect earlier logits."""
    net = _tiny()
    net.initialize()
    src = mx.nd.array(np.random.randint(0, 16, (1, 6)))
    tgt1 = np.array([[1, 3, 5, 7]], dtype=np.int32)
    tgt2 = tgt1.copy()
    tgt2[0, -1] = 9           # change last token only
    l1 = net(src, mx.nd.array(tgt1)).asnumpy()
    l2 = net(src, mx.nd.array(tgt2)).asnumpy()
    np.testing.assert_allclose(l1[:, :-1], l2[:, :-1], atol=1e-5)
    assert np.abs(l1[:, -1] - l2[:, -1]).max() > 1e-6


def test_learns_copy_task():
    rng = np.random.RandomState(0)
    V, S, B = 12, 6, 16
    net = _tiny(V, V)
    net.initialize(mx.init.Xavier())
    net.hybridize()     # one jitted program per step — the fast path
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 1e-2})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    losses = []
    for step in range(100):
        src = rng.randint(3, V, (B, S))
        bos = np.full((B, 1), 1)
        tgt_in = np.concatenate([bos, src[:, :-1]], axis=1)
        with autograd.record():
            logits = net(mx.nd.array(src), mx.nd.array(tgt_in))
            loss = loss_fn(logits.reshape((-1, V)),
                           mx.nd.array(src.reshape(-1)))
        loss.backward()
        trainer.step(B * S)
        losses.append(loss.asnumpy().mean())
    assert losses[-1] < losses[0] * 0.6, losses[::10]


def test_greedy_translate_runs():
    net = _tiny()
    net.initialize()
    src = mx.nd.array(np.random.randint(3, 16, (2, 5)))
    out = net.translate(src, max_steps=8)
    assert out.shape[0] == 2
    assert out.shape[1] <= 8


def test_beam_search_translate():
    """Beam decode (the Sockeye inference mode): on a trained copy task
    the beam-search output must match the source at least as well as
    greedy, and beam_size=1 must equal the greedy path exactly."""
    rng = np.random.RandomState(0)
    mx.random.seed(0)                 # deterministic init: fixed outcome
    V, S, B = 12, 6, 16
    net = _tiny(V, V)
    net.initialize(mx.init.Xavier())
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 1e-2})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    for step in range(300):
        src = rng.randint(3, V, (B, S))
        bos = np.full((B, 1), 1)
        tgt_in = np.concatenate([bos, src[:, :-1]], axis=1)
        with autograd.record():
            logits = net(mx.nd.array(src), mx.nd.array(tgt_in))
            loss = loss_fn(logits.reshape((-1, V)),
                           mx.nd.array(src.reshape(-1)))
        loss.backward()
        trainer.step(B * S)
    src = rng.randint(3, V, (4, S))
    greedy = net.translate(mx.nd.array(src), max_steps=S)
    # beam_size=1 dispatches to the greedy path — assert that contract
    beam1 = net.translate(mx.nd.array(src), max_steps=S, beam_size=1)
    np.testing.assert_array_equal(greedy, beam1)
    # beam_size=2 exercises the BEAM branch proper; on a trained model
    # its top beam must be at least as good as greedy
    beam2 = net.translate(mx.nd.array(src), max_steps=S, beam_size=2)
    beam4 = net.translate(mx.nd.array(src), max_steps=S, beam_size=4)
    assert beam4.shape[0] == 4 and beam4.shape[1] <= S
    acc_g = (greedy[:, :S] == src[:, :greedy.shape[1]]).mean()
    for beam in (beam2, beam4):
        acc_b = (beam[:, :S] == src[:, :beam.shape[1]]).mean()
        assert acc_b >= acc_g - 0.05, (acc_g, acc_b)
        assert acc_b > 0.5, f"beam decode failed the copy task: {acc_b}"

"""Sparse training path: row-sparse Embedding gradients, lazy sparse
optimizer updates touching only active rows, kvstore row_sparse push/pull
(ref: tests/python/unittest/test_sparse_operator.py + test_module.py
sparse embedding tests; SURVEY §2 #2/#15/#27)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.ndarray.sparse import RowSparseNDArray

VOCAB, DIM = 50, 8


def _embed_net(sparse):
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Embedding(VOCAB, DIM, sparse_grad=sparse),
            gluon.nn.Dense(4, flatten=False))
    net.initialize(mx.init.Xavier())
    net(nd.array(np.zeros((1, 2))))     # resolve deferred shapes
    return net


def test_sparse_grad_is_row_sparse_touching_only_batch_rows():
    net = _embed_net(sparse=True)
    tokens = np.array([[3, 7, 7], [11, 3, 42]])
    with autograd.record():
        out = net(nd.array(tokens))
        loss = out.sum()
    loss.backward()
    emb_w = net[0].weight
    g = emb_w.grad()
    assert isinstance(g, RowSparseNDArray)
    assert set(g.indices.tolist()) == {3, 7, 11, 42}
    # duplicate index 3 and 7 contributions summed: compare to dense run
    net_d = _embed_net(sparse=False)
    net_d[0].weight.set_data(emb_w.data())
    net_d[1].weight.set_data(net[1].weight.data())
    net_d[1].bias.set_data(net[1].bias.data())
    with autograd.record():
        loss_d = net_d(nd.array(tokens)).sum()
    loss_d.backward()
    dense_g = net_d[0].weight.grad().asnumpy()
    np.testing.assert_allclose(g.asnumpy(), dense_g, rtol=1e-5,
                               atol=1e-6)


@pytest.mark.parametrize("optname,opt_kw", [
    ("sgd", {"learning_rate": 0.1}),
])
def test_sparse_training_matches_dense(optname, opt_kw):
    # plain SGD, wd=0: a zero-gradient row's dense update is a no-op, so
    # lazy row-sparse training is mathematically identical to dense.
    # (With momentum/adam the dense path decays state on EVERY row each
    # step; lazy sparse intentionally differs — covered by
    # test_lazy_momentum_reference below, the reference's lazy_update
    # semantics.)
    rng = np.random.RandomState(0)
    tokens = rng.randint(0, VOCAB, (6, 4, 3))
    targets = rng.randn(6, 4, 3, 4).astype(np.float32)

    def run(sparse):
        net = _embed_net(sparse)
        # identical init
        for p, q in zip(_ref_params, net.collect_params().values()):
            q.set_data(nd.array(p))
        tr = gluon.Trainer(net.collect_params(), optname, dict(opt_kw),
                           kvstore=None)
        lf = gluon.loss.L2Loss()
        for i in range(len(tokens)):
            with autograd.record():
                l = lf(net(nd.array(tokens[i])), nd.array(targets[i]))
            l.backward()
            tr.step(4)
        return [v.data().asnumpy()
                for v in net.collect_params().values()]

    global _ref_params
    ref_net = _embed_net(False)
    _ref_params = [v.data().asnumpy()
                   for v in ref_net.collect_params().values()]
    dense = run(False)
    sparse = run(True)
    for i, (s_arr, d_arr) in enumerate(zip(sparse, dense)):
        np.testing.assert_allclose(s_arr, d_arr, rtol=1e-5,
                                   atol=1e-6, err_msg=str(i))


def test_lazy_update_skips_untouched_rows():
    # with wd > 0 the dense path decays EVERY row; the sparse path must
    # leave untouched rows exactly as they were (reference lazy_update)
    net = _embed_net(sparse=True)
    w0 = net[0].weight.data().asnumpy().copy()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.5, "wd": 0.1}, kvstore=None)
    tokens = np.array([[1, 2, 3]])
    with autograd.record():
        l = net(nd.array(tokens)).sum()
    l.backward()
    tr.step(1)
    w1 = net[0].weight.data().asnumpy()
    touched = [1, 2, 3]
    untouched = [i for i in range(VOCAB) if i not in touched]
    np.testing.assert_array_equal(w1[untouched], w0[untouched])
    assert not np.allclose(w1[touched], w0[touched])


def test_momentum_state_only_touched_rows():
    net = _embed_net(sparse=True)
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1, "momentum": 0.9},
                       kvstore=None)
    tokens = np.array([[5, 9]])
    with autograd.record():
        net(nd.array(tokens)).sum().backward()
    tr.step(1)
    mom = tr._updaters[0].states[0]
    mom_np = (mom[0] if isinstance(mom, (tuple, list)) else mom).asnumpy()
    nz = np.nonzero(np.any(mom_np != 0, axis=1))[0]
    assert set(nz.tolist()) <= {5, 9}


def test_kvstore_row_sparse_push_pull():
    kv = mx.kv.create("local")
    w = np.random.randn(VOCAB, DIM).astype(np.float32)
    kv.init(0, nd.array(w))
    rows = np.array([4, 17])
    vals = np.ones((2, DIM), np.float32)
    # push replaces the touched rows (same semantics as the dense push)
    kv.push(0, RowSparseNDArray(vals, rows, (VOCAB, DIM)))
    got = kv.row_sparse_pull(0, row_ids=np.array([4, 17, 30]))
    assert isinstance(got, RowSparseNDArray)
    assert got.indices.tolist() == [4, 17, 30]
    np.testing.assert_allclose(got.data[0], np.ones(DIM), rtol=1e-6)
    np.testing.assert_allclose(got.data[2], w[30], rtol=1e-6)


def test_hybridized_sparse_embedding_falls_back_dense():
    # under jit tracing the dense scatter path applies; training must
    # still work and grads remain correct
    net = _embed_net(sparse=True)
    net.hybridize()
    tokens = np.array([[3, 7]])
    with autograd.record():
        net(nd.array(tokens)).sum().backward()
    g = net[0].weight.grad()
    # dense buffer (tracing path) — values still correct
    gn = g.asnumpy() if not isinstance(g, RowSparseNDArray) else g.asnumpy()
    nz = np.nonzero(np.any(gn != 0, axis=1))[0]
    assert set(nz.tolist()) <= {3, 7}


def test_lazy_momentum_reference():
    # sparse SGD+momentum equals a hand-computed LAZY update: momentum
    # decays only on rows present in that step's batch
    net = _embed_net(sparse=True)
    w = net[0].weight.data().asnumpy().copy()
    mom = np.zeros_like(w)
    lr, mu = 0.1, 0.9
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": lr, "momentum": mu},
                       kvstore=None)
    batches = [np.array([[1, 2]]), np.array([[2, 5]]),
               np.array([[1, 5]])]
    for tokens in batches:
        with autograd.record():
            out = net(nd.array(tokens))
            loss = out.sum()
        loss.backward()
        # expected gradient of embedding under sum() head: sum over
        # occurrences of dense-layer backprop; compute via dense twin
        twin = _embed_net(sparse=False)
        for p, q in zip(net.collect_params().values(),
                        twin.collect_params().values()):
            q.set_data(p.data())
        twin[0].weight.set_data(nd.array(w))
        with autograd.record():
            twin(nd.array(tokens)).sum().backward()
        g = twin[0].weight.grad().asnumpy()
        rows = np.unique(tokens)
        mom[rows] = mu * mom[rows] + g[rows]     # lazy: touched rows only
        w[rows] = w[rows] - lr * mom[rows]
        tr.step(1)
        np.testing.assert_allclose(net[0].weight.data().asnumpy(), w,
                                   rtol=1e-5, atol=1e-6)

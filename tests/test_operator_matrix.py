"""Generated per-op test matrix: dtype x shape-class x execution-mode
(ref: tests/python/unittest/test_operator.py — the reference's ~10k-line
table of per-op cases; same method, generated instead of hand-unrolled:
numpy forward parity on the base case, then sweeps over dtypes
(fp32/bf16/fp16/int32), shape edges (zero-size, zero-dim, 1-elem, large,
broadcast edges), and modes (eager / hybridized-jit / symbolic), asserting
cross-mode consistency the way the reference's CPU-vs-GPU
check_consistency does)."""
import zlib

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, nd
import mxnet_tpu.symbol as sym

RNG = np.random.RandomState(0)


class Case:
    """One op: a builder over the namespace F (nd or sym) + input specs."""

    def __init__(self, key, build, shapes, positive=False, int_ok=True,
                 dtypes=("float32", "bfloat16", "float16"),
                 edge_shapes=True, unit=False):
        self.key = key
        self.build = build
        self.shapes = shapes
        self.positive = positive
        self.unit = unit                  # domain (-0.9, 0.9)
        self.int_ok = int_ok
        self.dtypes = dtypes
        self.edge_shapes = edge_shapes

    def inputs(self, shapes=None, dtype="float32"):
        out = []
        for i, shp in enumerate(shapes or self.shapes):
            # stable across processes (hash() varies with PYTHONHASHSEED,
            # which would make failures irreproducible)
            rng = np.random.RandomState(
                zlib.crc32(self.key.encode()) % 10000 + i)
            if dtype == "int32":
                arr = rng.randint(1, 5, size=shp).astype(np.int32)
            else:
                lo, hi = (-0.9, 0.9) if self.unit else \
                    (0.3, 1.3) if self.positive else (-1.0, 1.3)
                arr = rng.uniform(lo, hi, size=shp).astype(np.float32)
                arr = arr.astype(dtype)
            out.append(arr)
        return out


def _u(name, positive=False, **kw):
    return Case(name, lambda F, x: getattr(F, name)(x), [(3, 4)],
                positive=positive, **kw)


def _b(name, positive=False, **kw):
    return Case(name, lambda F, a, b: getattr(F, name)(a, b),
                [(2, 1, 4), (1, 3, 4)], positive=positive, **kw)


def _r(name, **kw):
    return Case(name, lambda F, x: getattr(F, name)(x, axis=1),
                [(2, 3, 4)], **kw)


CASES = [c for c in [
    # ---- elemwise unary --------------------------------------------------
    _u("exp"), _u("log", positive=True), _u("log10", positive=True),
    _u("log2", positive=True), _u("log1p", positive=True),
    _u("expm1"), _u("sqrt", positive=True), _u("rsqrt", positive=True, int_ok=False),
    _u("cbrt"), _u("square"), _u("abs"), _u("sign"), _u("floor"),
    _u("ceil"), _u("round"), _u("trunc"), _u("negative"),
    _u("reciprocal", positive=True), _u("sin"), _u("cos"), _u("tan"),
    _u("arcsin", unit=True, int_ok=False),
    _u("arccos", unit=True, int_ok=False), _u("arctan"), _u("sinh"), _u("cosh"),
    _u("tanh"), _u("arctanh", unit=True, int_ok=False),
    _u("sigmoid", int_ok=False), _u("relu"),
    _u("softsign"), _u("erf"), _u("gamma", positive=True),
    _u("gammaln", positive=True),
    # ---- binary broadcast ------------------------------------------------
    _b("broadcast_add"), _b("broadcast_sub"), _b("broadcast_mul"),
    _b("broadcast_div", positive=True),
    _b("broadcast_power", positive=True),
    _b("broadcast_maximum"), _b("broadcast_minimum"),
    _b("broadcast_hypot"), _b("broadcast_equal"),
    _b("broadcast_not_equal"), _b("broadcast_greater"),
    _b("broadcast_lesser"),
    # ---- reductions ------------------------------------------------------
    _r("sum"), _r("mean"), _r("prod"), _r("max"), _r("min"),
    _r("argmax"), _r("argmin"),
    Case("norm", lambda F, x: F.norm(x, ord=2, axis=1), [(2, 3, 4)]),
    Case("logsumexp", lambda F, x: F.logsumexp(x, axis=-1), [(3, 5)]),
    # ---- shape manipulation ---------------------------------------------
    Case("reshape", lambda F, x: F.reshape(x, (4, 3)), [(3, 4)],
         edge_shapes=False),
    Case("transpose", lambda F, x: F.transpose(x, axes=(1, 0)), [(3, 4)]),
    Case("expand_dims", lambda F, x: F.expand_dims(x, axis=1), [(3, 4)]),
    Case("flip", lambda F, x: F.flip(x, axis=1), [(3, 4)]),
    Case("tile", lambda F, x: F.tile(x, reps=(2, 2)), [(3, 4)]),
    Case("repeat", lambda F, x: F.repeat(x, repeats=2, axis=1), [(3, 4)]),
    Case("clip", lambda F, x: F.clip(x, a_min=-0.5, a_max=0.5), [(3, 4)]),
    Case("slice", lambda F, x: F.slice(x, begin=(0, 1), end=(2, 3)),
         [(3, 4)]),
    Case("slice_axis",
         lambda F, x: F.slice_axis(x, axis=1, begin=1, end=3), [(3, 4)]),
    Case("concat", lambda F, a, b: F.concat(a, b, dim=1),
         [(3, 2), (3, 4)]),
    Case("stack", lambda F, a, b: F.stack(a, b, axis=1),
         [(3, 4), (3, 4)]),
    Case("split", lambda F, x: F.split(x, num_outputs=2, axis=1)[0],
         [(3, 4)], edge_shapes=False),
    Case("where", lambda F, c, a, b: F.where(c, a, b),
         [(3, 4), (3, 4), (3, 4)]),
    Case("cast", lambda F, x: F.cast(x, dtype="float32"), [(3, 4)]),
    Case("zeros_like", lambda F, x: F.zeros_like(x), [(3, 4)]),
    Case("ones_like", lambda F, x: F.ones_like(x), [(3, 4)]),
    # ---- indexing --------------------------------------------------------
    Case("take",
         lambda F, x: F.take(x, _const(F, [0, 2, 1]), axis=0), [(4, 3)],
         edge_shapes=False),
    Case("one_hot",
         lambda F, x: F.one_hot(x, depth=5), [(4,)],
         dtypes=("int32",), edge_shapes=False),
    Case("gather_nd",
         lambda F, x: F.gather_nd(x, _const(F, [[0, 1], [1, 0]])),
         [(2, 2, 3)], edge_shapes=False),
    Case("pick",
         lambda F, x: F.pick(x, _const(F, [1, 0, 2]), axis=1), [(3, 4)],
         edge_shapes=False),
    # ---- ordering --------------------------------------------------------
    Case("sort", lambda F, x: F.sort(x, axis=-1), [(3, 5)]),
    Case("argsort", lambda F, x: F.argsort(x, axis=-1), [(3, 5)]),
    Case("topk", lambda F, x: F.topk(x, k=2, axis=-1), [(3, 5)],
         edge_shapes=False),
    # ---- nn --------------------------------------------------------------
    Case("FullyConnected",
         lambda F, x, w, b: F.FullyConnected(x, w, b, num_hidden=3),
         [(2, 4), (3, 4), (3,)], edge_shapes=False),
    Case("Convolution",
         lambda F, x, w, b: F.Convolution(x, w, b, kernel=(3, 3),
                                          num_filter=2, pad=(1, 1)),
         [(1, 2, 5, 5), (2, 2, 3, 3), (2,)], edge_shapes=False),
    Case("Pooling",
         lambda F, x: F.Pooling(x, pool_type="max", kernel=(2, 2),
                                stride=(2, 2)),
         [(1, 2, 4, 4)], edge_shapes=False),
    Case("softmax", lambda F, x: F.softmax(x, axis=-1), [(3, 5)],
         int_ok=False),
    Case("log_softmax", lambda F, x: F.log_softmax(x, axis=-1), [(3, 5)],
         int_ok=False),
    Case("LayerNorm",
         lambda F, x, g, b: F.LayerNorm(x, g, b, axis=-1),
         [(3, 6), (6,), (6,)], edge_shapes=False, int_ok=False),
    Case("Activation",
         lambda F, x: F.Activation(x, act_type="relu"), [(3, 4)]),
    Case("LeakyReLU",
         lambda F, x: F.LeakyReLU(x, act_type="leaky", slope=0.1),
         [(3, 4)], int_ok=False),
    Case("Embedding",
         lambda F, x, w: F.Embedding(x, w, input_dim=5, output_dim=3),
         [(2, 3), (5, 3)], dtypes=("float32",), edge_shapes=False),
    Case("SequenceMask",
         lambda F, x: F.SequenceMask(x, _const(F, [1, 2]),
                                     use_sequence_length=True, value=0.0),
         [(3, 2, 4)], edge_shapes=False, int_ok=False),
    Case("smooth_l1",
         lambda F, x: F.smooth_l1(x, scalar=1.0), [(3, 4)],
         int_ok=False),
    # ---- linalg ----------------------------------------------------------
    Case("dot", lambda F, a, b: F.dot(a, b), [(3, 4), (4, 2)],
         edge_shapes=False),
    Case("batch_dot", lambda F, a, b: F.batch_dot(a, b),
         [(2, 3, 4), (2, 4, 2)], edge_shapes=False),
    Case("linalg_gemm2",
         lambda F, a, b: F.linalg_gemm2(a, b, transpose_a=True),
         [(4, 3), (4, 2)], edge_shapes=False),
    # ---- round-4 widening: the mechanical registry tail ------------------
    # unary transcendental / rounding
    Case("arccosh", lambda F, x: F.arccosh(x + 1.5), [(3, 4)],
         positive=True, int_ok=False),
    _u("arcsinh"), _u("degrees"), _u("radians"),
    _u("rcbrt", positive=True, int_ok=False),
    _u("rint"), _u("fix"),
    Case("erfinv", lambda F, x: F.erfinv(x * 0.9), [(3, 4)], unit=True,
         int_ok=False),
    _u("isfinite"), _u("isnan"), _u("isinf"), _u("logical_not"),
    _u("identity"), _u("stop_gradient"), _u("softmin"),
    Case("copy", lambda F, x: F._internal._copy(x), [(3, 4)]),
    Case("SoftmaxActivation", lambda F, x: F.SoftmaxActivation(x),
         [(3, 4)], int_ok=False),
    # shape / layout
    _u("flatten"),
    Case("squeeze", lambda F, x: F.squeeze(x, axis=1), [(3, 1, 4)],
         edge_shapes=False),
    Case("swapaxes", lambda F, x: F.swapaxes(x, 1, 2), [(2, 3, 4)]),
    Case("moveaxis", lambda F, x: F.moveaxis(x, 0, 2), [(2, 3, 4)]),
    Case("reverse", lambda F, x: F.reverse(x, axis=1), [(3, 4)]),
    Case("diag", lambda F, x: F.diag(x), [(4, 4)]),
    Case("depth_to_space", lambda F, x: F.depth_to_space(x, block_size=2),
         [(1, 8, 3, 3)], edge_shapes=False),
    Case("space_to_depth", lambda F, x: F.space_to_depth(x, block_size=2),
         [(1, 2, 4, 4)], edge_shapes=False),
    Case("broadcast_to", lambda F, x: F.broadcast_to(x, shape=(3, 4)),
         [(1, 4)], edge_shapes=False),
    Case("broadcast_axis",
         lambda F, x: F.broadcast_axis(x, axis=1, size=3), [(2, 1, 4)],
         edge_shapes=False),
    Case("Pad",
         lambda F, x: F.Pad(x, mode="constant",
                            pad_width=(0, 0, 0, 0, 1, 1, 2, 2)),
         [(1, 2, 3, 3)], edge_shapes=False),
    Case("shape_array", lambda F, x: F.shape_array(x), [(3, 4)],
         dtypes=("float32",), edge_shapes=False),
    Case("size_array", lambda F, x: F.size_array(x), [(3, 4)],
         dtypes=("float32",), edge_shapes=False),
    Case("reshape_like", lambda F, a, b: F.reshape_like(a, b),
         [(2, 6), (3, 4)], edge_shapes=False),
    Case("slice_like", lambda F, a, b: F.slice_like(a, b),
         [(4, 5), (2, 3)], edge_shapes=False),
    Case("broadcast_like", lambda F, a, b: F.broadcast_like(a, b),
         [(1, 4), (3, 4)], edge_shapes=False),
    Case("arange_like", lambda F, x: F.arange_like(x, axis=0), [(5, 2)]),
    Case("SliceChannel",
         lambda F, x: F.SliceChannel(x, num_outputs=2, axis=1)[1],
         [(2, 4, 3)], edge_shapes=False),
    Case("UpSampling",
         lambda F, x: F.UpSampling(x, scale=2, sample_type="nearest"),
         [(1, 2, 3, 3)], edge_shapes=False),
    # sequence family (T, N, ...)
    Case("SequenceReverse", lambda F, x: F.SequenceReverse(x),
         [(3, 2, 4)], edge_shapes=False),
    Case("SequenceLast", lambda F, x: F.SequenceLast(x), [(3, 2, 4)],
         edge_shapes=False),
    # binary elemwise + comparisons + logicals
    _b("broadcast_mod", positive=True),
    _b("broadcast_greater_equal"), _b("broadcast_lesser_equal"),
    _b("broadcast_logical_and"), _b("broadcast_logical_or"),
    _b("broadcast_logical_xor"),
    Case("arctan2", lambda F, a, b: F.arctan2(a, b),
         [(3, 4), (3, 4)], int_ok=False),
    Case("hypot", lambda F, a, b: F.hypot(a, b), [(3, 4), (3, 4)],
         int_ok=False),
    Case("ldexp", lambda F, a, b: F.ldexp(a, b), [(3, 4), (3, 4)],
         int_ok=False),
    Case("maximum", lambda F, a, b: F.maximum(a, b), [(3, 4), (3, 4)]),
    Case("minimum", lambda F, a, b: F.minimum(a, b), [(3, 4), (3, 4)]),
    Case("modulo", lambda F, a, b: F.modulo(a, b), [(3, 4), (3, 4)],
         positive=True),
    Case("power", lambda F, a, b: F.power(a, b), [(3, 4), (3, 4)],
         positive=True),
    Case("elemwise_add", lambda F, a, b: F.elemwise_add(a, b),
         [(3, 4), (3, 4)]),
    Case("elemwise_sub", lambda F, a, b: F.elemwise_sub(a, b),
         [(3, 4), (3, 4)]),
    Case("elemwise_mul", lambda F, a, b: F.elemwise_mul(a, b),
         [(3, 4), (3, 4)]),
    Case("elemwise_div", lambda F, a, b: F.elemwise_div(a, b),
         [(3, 4), (3, 4)], positive=True),
    Case("logical_and", lambda F, a, b: F.logical_and(a, b),
         [(3, 4), (3, 4)]),
    Case("logical_or", lambda F, a, b: F.logical_or(a, b),
         [(3, 4), (3, 4)]),
    Case("logical_xor", lambda F, a, b: F.logical_xor(a, b),
         [(3, 4), (3, 4)]),
    Case("equal", lambda F, a, b: F.equal(a, b), [(3, 4), (3, 4)]),
    Case("not_equal", lambda F, a, b: F.not_equal(a, b),
         [(3, 4), (3, 4)]),
    Case("greater", lambda F, a, b: F.greater(a, b), [(3, 4), (3, 4)]),
    Case("lesser", lambda F, a, b: F.lesser(a, b), [(3, 4), (3, 4)]),
    Case("add_n", lambda F, a, b, c: F.add_n(a, b, c),
         [(3, 4), (3, 4), (3, 4)]),
    # scalar variants (the generated _scalar registry surface)
    Case("plus_scalar", lambda F, x: F._internal._plus_scalar(x, scalar=1.5),
         [(3, 4)]),
    Case("rminus_scalar",
         lambda F, x: F._internal._rminus_scalar(x, scalar=1.5),
         [(3, 4)]),
    Case("rdiv_scalar",
         lambda F, x: F._internal._rdiv_scalar(x, scalar=2.0), [(3, 4)],
         positive=True),
    Case("rpower_scalar",
         lambda F, x: F._internal._rpower_scalar(x, scalar=2.0), [(3, 4)],
         int_ok=False),
    Case("maximum_scalar",
         lambda F, x: F.maximum(x, 0.25), [(3, 4)]),
    Case("mod_scalar", lambda F, x: F._internal._mod_scalar(x, scalar=0.7),
         [(3, 4)], positive=True),
    Case("greater_scalar", lambda F, x: F.greater(x, 0.5),
         [(3, 4)]),
    # nan-aware reductions
    _r("nansum"), _r("nanprod"),
    # misc
    Case("box_iou", lambda F, a, b: F.contrib.box_iou(a, b, format="corner"),
         [(3, 4), (2, 4)], unit=True, edge_shapes=False),
    Case("khatri_rao", lambda F, a, b: F.khatri_rao(a, b),
         [(3, 2), (3, 4)], edge_shapes=False),
    Case("scatter_nd",
         lambda F, x: F.scatter_nd(x, _const(F, [[0, 2], [1, 0]]),
                                   shape=(3, 4)),
         [(2,)], edge_shapes=False),
    Case("diag_offset", lambda F, x: F.diag(x, k=1), [(4, 4)]),
    Case("RMSNorm", lambda F, x, g: F.RMSNorm(x, g, axis=-1),
         [(3, 6), (6,)], edge_shapes=False, int_ok=False),
    Case("div_sqrt_dim", lambda F, x: F.div_sqrt_dim(x), [(3, 4)],
         int_ok=False),
] if c is not None]

BY_KEY = {c.key: c for c in CASES}


def _const(F, values):
    if F is sym:
        raise AssertionError("ops with constant-array inputs are in "
                             "_SYM_SKIP — symbolic coverage for them "
                             "lives in test_symbol_module.py")
    return nd.array(np.asarray(values, dtype=np.float32))


_SYM_SKIP = {"take", "one_hot", "gather_nd", "pick", "SequenceMask",
             "scatter_nd", "box_iou"}


def _run_eager(case, arrays):
    out = case.build(nd, *[nd.array(a) for a in arrays])
    return out


def _as_np(x):
    return x.asnumpy() if hasattr(x, "asnumpy") else np.asarray(x)


# ---------------------------------------------------------------------------
# sweep 1: dtype coverage — run each op under fp32/bf16/fp16/int32 and
# check shape/dtype sanity plus value agreement with the fp32 result
# ---------------------------------------------------------------------------

_DTYPE_PARAMS = [(c.key, dt) for c in CASES
                 for dt in (list(c.dtypes) + (["int32"] if c.int_ok and
                                              "int32" not in c.dtypes
                                              else []))]


@pytest.mark.parametrize("key,dtype", _DTYPE_PARAMS,
                         ids=[f"{k}-{d}" for k, d in _DTYPE_PARAMS])
def test_op_dtype(key, dtype):
    case = BY_KEY[key]
    arrays = case.inputs(dtype=dtype)
    out = _run_eager(case, arrays)
    got = _as_np(out)
    assert np.isfinite(got.astype(np.float64)).all() or \
        dtype in ("float16", "bfloat16"), f"{key}/{dtype} produced non-finite"
    if dtype == "float32":
        return
    # value check vs the fp32 run on the same (cast-back) inputs
    ref_inputs = [a.astype(np.float32) for a in arrays]
    ref = _as_np(case.build(nd, *[nd.array(a) for a in ref_inputs]))
    tol = {"bfloat16": 5e-2, "float16": 1e-2, "int32": 1e-6}[dtype]
    np.testing.assert_allclose(got.astype(np.float64),
                               ref.astype(np.float64),
                               rtol=tol, atol=tol * 5,
                               err_msg=f"{key} {dtype} vs fp32")


# ---------------------------------------------------------------------------
# sweep 2: shape classes — zero-size, 1-element, large; ops keep working
# at the edges the reference's matrix exercises
# ---------------------------------------------------------------------------

def _edge_variants(case):
    """Derive edge-shape input sets from the base shapes."""
    variants = {}
    base = case.shapes
    if not case.edge_shapes:
        return variants
    rank = len(base[0])
    if all(len(s) == rank for s in base):
        # zero-size along the first broadcast-safe axis
        variants["zero_size"] = [tuple(0 if i == 0 else d
                                       for i, d in enumerate(s))
                                 for s in base]
        # every axis zero — the fully-degenerate case (rank preserved, so
        # axis kwargs in the builders stay valid)
        variants["zero_all"] = [(0,) * rank for _ in base]
        variants["one_elem"] = [(1,) * rank for _ in base]
        variants["large"] = [tuple(97 if d > 1 else d for d in s)
                             for s in base]
    return variants


# each edge variant also runs under bf16 — the production compute dtype of
# every benchmark config must survive the same shape edges fp32 does (the
# reference's check_consistency swept fp16 the same way)
_SHAPE_PARAMS = [(c.key, variant, dt) for c in CASES
                 for variant in _edge_variants(c)
                 for dt in (["float32", "bfloat16"]
                            if "bfloat16" in c.dtypes else ["float32"])]


# reducing an EMPTY axis has no identity for these — the contract is a
# clear error, not an invented value (the reference errors here too:
# mshadow reduce with no elements)
_EMPTY_AXIS_ERRORS = {"max", "min", "argmax", "argmin", "logsumexp",
                      "log_softmax"}


@pytest.mark.parametrize("key,variant,dtype", _SHAPE_PARAMS,
                         ids=[f"{k}-{v}-{d}" for k, v, d in _SHAPE_PARAMS])
def test_op_shape_edges(key, variant, dtype):
    case = BY_KEY[key]
    shapes = _edge_variants(case)[variant]
    arrays = case.inputs(shapes=shapes, dtype=dtype)
    if variant == "zero_all" and key in _EMPTY_AXIS_ERRORS:
        with pytest.raises(Exception):
            _run_eager(case, arrays)
        return
    out = _run_eager(case, arrays)
    got = _as_np(out)
    if variant in ("zero_size", "zero_all"):
        # every input had axes zeroed, so the output must be empty too —
        # a non-empty result means the op invented data
        assert got.size == 0, \
            f"{key} {variant} output malformed: {got.shape}"
    else:
        assert np.isfinite(got.astype(np.float64)).all()


# ---------------------------------------------------------------------------
# sweep 3: mode consistency — eager vs hybridized-jit vs symbolic produce
# the same numbers (the reference's check_consistency retargeted from
# CPU-vs-GPU to mode-vs-mode)
# ---------------------------------------------------------------------------

class _Wrap(gluon.HybridBlock):
    def __init__(self, build, n):
        super().__init__()
        self._build = build
        self._n = n

    def hybrid_forward(self, F, *args):
        return self._build(F, *args)


@pytest.mark.parametrize("key", sorted(BY_KEY),
                         ids=sorted(BY_KEY))
def test_op_mode_consistency(key):
    case = BY_KEY[key]
    arrays = case.inputs()
    ref = _as_np(_run_eager(case, arrays))

    # hybridized: same builder traced under jit
    net = _Wrap(case.build, len(arrays))
    net.hybridize()
    jit_out = net(*[nd.array(a) for a in arrays])
    np.testing.assert_allclose(_as_np(jit_out), ref, rtol=1e-5,
                               atol=1e-6, err_msg=f"{key}: jit vs eager")

    if key in _SYM_SKIP:
        return
    # symbolic: compose over variables, eval with the same feeds
    vars_ = [sym.var(f"in{i}") for i in range(len(arrays))]
    out_sym = case.build(sym, *vars_)
    feeds = {f"in{i}": nd.array(a) for i, a in enumerate(arrays)}
    sym_out = out_sym.eval(**feeds)[0]
    np.testing.assert_allclose(_as_np(sym_out), ref, rtol=1e-5,
                               atol=1e-6, err_msg=f"{key}: sym vs eager")


# ---------------------------------------------------------------------------
# sweep 4: GRADIENT mode consistency — d(sum(w*op(x)))/dx under eager
# autograd vs the hybridized jit trace must match (the reference's
# check_consistency covers backward the same way; a vjp wired to the
# wrong primal or a trace-time constant folding bug shows up here)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("key", sorted(BY_KEY), ids=sorted(BY_KEY))
def test_op_grad_mode_consistency(key):
    from mxnet_tpu import autograd
    case = BY_KEY[key]
    arrays = case.inputs()
    if any(np.asarray(a).dtype.kind != "f" for a in arrays):
        pytest.skip("non-float inputs")

    weight = None

    def grads(hybridize):
        nonlocal weight
        net = _Wrap(case.build, len(arrays))
        if hybridize:
            net.hybridize()
        xs = [nd.array(a) for a in arrays]
        for x in xs:
            x.attach_grad()
        with autograd.record():
            out = net(*xs)
            if isinstance(out, (list, tuple)):
                out = out[0]
            if np.asarray(out.asnumpy()).dtype.kind != "f":
                pytest.skip("non-float output")
            if weight is None:
                weight = np.random.RandomState(
                    zlib.crc32(key.encode()) % 99991).rand(
                        *out.shape).astype(np.float32) + 0.5
            loss = nd.sum(out * nd.array(weight))
        try:
            loss.backward()
        except mx.base.MXNetError as e:
            if "no recorded graph" in str(e):
                # index/constant-valued outputs (argmax, topk indices,
                # ones_like, comparisons) never join the tape
                pytest.skip("output disconnected from inputs")
            raise
        return [x.grad.asnumpy() if x.grad is not None else None
                for x in xs]

    eager = grads(False)
    jit = grads(True)
    assert len(eager) == len(jit)
    for i, (ge, gj) in enumerate(zip(eager, jit)):
        if ge is None or gj is None:
            assert ge is None and gj is None, f"{key} input {i}"
            continue
        np.testing.assert_allclose(
            gj, ge, rtol=1e-5, atol=1e-6,
            err_msg=f"{key}: jit vs eager grad of input {i}")


# ---------------------------------------------------------------------------
# sweep 5: bf16 jit consistency — the production compute dtype must give
# the same numbers eager and hybridized (a cast dropped or added only on
# one path shows up here; the reference swept fp16 through
# check_consistency the same way)
# ---------------------------------------------------------------------------

_BF16_KEYS = sorted(c.key for c in CASES if "bfloat16" in c.dtypes)


@pytest.mark.parametrize("key", _BF16_KEYS, ids=_BF16_KEYS)
def test_op_bf16_jit_consistency(key):
    case = BY_KEY[key]
    arrays = case.inputs(dtype="bfloat16")
    ref = _as_np(_run_eager(case, arrays)).astype(np.float32)
    net = _Wrap(case.build, len(arrays))
    net.hybridize()
    jit_out = net(*[nd.array(a) for a in arrays])
    np.testing.assert_allclose(
        _as_np(jit_out).astype(np.float32), ref, rtol=2e-2, atol=2e-2,
        err_msg=f"{key}: bf16 jit vs eager")

"""Pod-scope distributed tracing (docs/observability.md).

The headline chaos drill (CI tier 0.5, ``-k smoke``): a 3-replica pool
under closed-loop load with a shared-FS trace run directory, SIGKILL
one replica mid-traffic, and assemble the full cross-process story from
the wreckage — ONE trace_id links the router's request root to the
worker-side request spans across the wire, the killed replica's
flight-recorder dump is present and parseable, and ``doctor
--timeline`` renders the merged critical path from per-process files
alone.

Around it: wire-level propagation units (attach/extract, Server.submit
re-anchoring), clock alignment with skewed anchors, Perfetto pid
disambiguation for replicas sharing a rank, trace-ring drop-count
visibility, and multi-survivor elastic recovery-trace adoption through
the epoch ledger.
"""
import json
import os
import threading
import time

import numpy as np
import pytest

from mxnet_tpu.diagnostics.journal import reset_journal
from mxnet_tpu.observability import aggregate, export, flight
from mxnet_tpu.observability import trace as obtrace
from mxnet_tpu.serving import wire

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def ring():
    tracer = obtrace.configure(mode="ring")
    try:
        yield tracer
    finally:
        obtrace.reset_tracer()


def _records(path, kind=None):
    out = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if kind is None or rec.get("kind") == kind:
                out.append(rec)
    return out


def _write_jsonl(path, records):
    with open(path, "w", encoding="utf-8") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")


# -- wire-level propagation units --------------------------------------------

def test_wire_attach_and_extract_trace_roundtrip(ring):
    with obtrace.span("router_request") as root:
        header = wire.attach_trace({"cmd": "predict"})
    assert header["v"] == wire.PROTOCOL_VERSION
    assert header["trace"] == {"trace_id": root.trace_id,
                               "span_id": root.span_id}
    ctx = wire.extract_parent(header)
    assert ctx.trace_id == root.trace_id
    assert ctx.span_id == root.span_id


def test_wire_attach_trace_off_and_garbage_degrade():
    obtrace.configure(mode="off")
    try:
        header = wire.attach_trace({"cmd": "predict"})
        assert header["v"] == wire.PROTOCOL_VERSION
        assert "trace" not in header       # bit-compatible with pre-trace
    finally:
        obtrace.reset_tracer()
    # malformed propagated contexts degrade to no parent, never an error
    assert wire.extract_parent({}) is None
    assert wire.extract_parent({"trace": "junk"}) is None
    assert wire.extract_parent({"trace": {"trace_id": 7}}) is None


def test_server_submit_reanchors_under_wire_parent(ring):
    """The worker-side half: a propagated SpanContext makes the
    serving_request root a true child of the remote router span."""
    from mxnet_tpu.gluon.block import HybridBlock
    from mxnet_tpu.serving import Server, ServerConfig

    class Scale(HybridBlock):
        def hybrid_forward(self, F, x):
            return x * 2.0

    net = Scale()
    net.initialize()
    srv = Server(net, ServerConfig(max_batch=2, window_ms=1.0)).start()
    parent = obtrace.SpanContext("feedc0de000001", "abcd1234")
    try:
        out = srv.submit(np.ones(3, np.float32),
                         parent=parent).result(timeout_s=30)
    finally:
        srv.stop()
    np.testing.assert_allclose(np.asarray(out), 2.0 * np.ones(3))
    roots = [s for s in obtrace.get_tracer().spans()
             if s["name"] == "serving_request"]
    assert roots, "no serving_request span recorded"
    assert roots[-1]["trace_id"] == "feedc0de000001"
    assert roots[-1]["parent_id"] == "abcd1234"
    # children stay in the adopted trace
    kids = [s for s in obtrace.get_tracer().spans()
            if s.get("parent_id") == roots[-1]["span_id"]]
    assert kids and all(s["trace_id"] == "feedc0de000001" for s in kids)


# -- clock alignment ----------------------------------------------------------

def _anchored_journal(path, replica, wall_s, perf_s, epoch_s, spans):
    recs = [{"kind": "trace_anchor", "ts": wall_s, "wall_s": wall_s,
             "perf_s": perf_s, "epoch_s": epoch_s, "rank": 0,
             "replica": replica, "pid": 100 + hash(replica) % 50,
             "run_id": "pod-test"}]
    for sp in spans:
        recs.append({"kind": "span", "rank": 0, "replica": replica,
                     "thread": "main", "ts": wall_s + 9.0, **sp})
    _write_jsonl(path, recs)


def test_clock_alignment_with_skewed_anchors(tmp_path):
    """Two processes whose monotonic clocks are wildly apart (different
    boot epochs) land on ONE wall timeline via their anchors: the
    worker's span starts 200 ms after the router's even though its raw
    start_s is numerically smaller."""
    # router: perf clock near 50 s, span at epoch+2.0 -> wall 992.0
    _anchored_journal(
        str(tmp_path / "journal-router.jsonl"), "router",
        wall_s=1000.0, perf_s=50.0, epoch_s=40.0,
        spans=[{"name": "router_request", "trace_id": "T1",
                "span_id": "a1", "parent_id": None,
                "start_s": 2.0, "dur_s": 0.5}])
    # worker: perf clock near 100k s (skew ~27 h), span -> wall 992.2
    _anchored_journal(
        str(tmp_path / "journal-w0.jsonl"), "w0",
        wall_s=1000.2, perf_s=99999.0, epoch_s=99990.0,
        spans=[{"name": "serving_request", "trace_id": "T1",
                "span_id": "b1", "parent_id": "a1",
                "start_s": 1.0, "dur_s": 0.3}])
    procs = aggregate.scan_run_dir(str(tmp_path))
    assert len(procs) == 2
    cp = aggregate.critical_path(procs, trace_id="T1")
    assert cp["ok"] and [s["name"] for s in cp["steps"]] == \
        ["router_request", "serving_request"]
    assert cp["steps"][0]["start_ms"] == 0.0
    assert abs(cp["steps"][1]["start_ms"] - 200.0) < 1.0
    assert abs(cp["wall_ms"] - 500.0) < 1.0      # router span bounds it
    assert sorted(cp["processes"]) == ["replica router", "replica w0"]
    # the merged Perfetto doc is ordered on the same wall timeline
    doc = aggregate.aggregate_chrome(str(tmp_path))
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert [e["name"] for e in xs] == ["router_request",
                                      "serving_request"]
    assert abs(xs[1]["ts"] - xs[0]["ts"] - 200e3) < 1e3


def test_clock_alignment_falls_back_to_record_ts(tmp_path):
    """A journal with no anchor (older writer, torn head) still places
    spans via each record's own write-time ts minus duration."""
    _write_jsonl(str(tmp_path / "journal-x.jsonl"), [
        {"kind": "span", "name": "serving_request", "trace_id": "T2",
         "span_id": "c1", "parent_id": None, "rank": 0,
         "thread": "main", "start_s": 5.0, "dur_s": 0.4, "ts": 2000.4}])
    procs = aggregate.scan_run_dir(str(tmp_path))
    assert len(procs) == 1 and procs[0].anchor is None
    cp = aggregate.critical_path(procs, trace_id="T2")
    assert cp["ok"] and cp["steps"][0]["name"] == "serving_request"
    assert abs(cp["wall_ms"] - 400.0) < 1.0


# -- Perfetto pid disambiguation (satellite) ----------------------------------

def test_spans_to_chrome_disambiguates_replicas_sharing_a_rank(ring):
    with obtrace.span("a"):
        pass
    base = obtrace.get_tracer().spans()
    r1 = [{**s, "replica": "r1"} for s in base]
    r2 = [{**s, "replica": "r2"} for s in base]
    doc = export.spans_to_chrome(base + r1 + r2)
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    pids = {e["args"].get("replica"): e["pid"] for e in xs}
    # three processes, three distinct tracks — rank alone keyed all of
    # these onto pid 0 before the fix
    assert len(set(pids.values())) == 3
    metas = {e["args"]["name"] for e in doc["traceEvents"]
             if e["ph"] == "M"}
    assert {"replica r1", "replica r2"} <= metas
    # rank-only single-process documents stay the pre-fix golden shape
    solo = export.spans_to_chrome(base)
    assert all(e["ph"] == "X" and e["pid"] == 0
               for e in solo["traceEvents"])


def test_aggregate_assigns_one_pid_per_process(tmp_path):
    for rep in ("a", "b"):
        _anchored_journal(
            str(tmp_path / f"journal-{rep}.jsonl"), rep,
            wall_s=500.0, perf_s=10.0, epoch_s=10.0,
            spans=[{"name": "serving_batch", "trace_id": f"T{rep}",
                    "span_id": f"s{rep}", "parent_id": None,
                    "start_s": 0.1, "dur_s": 0.1}])
    doc = aggregate.aggregate_chrome(str(tmp_path))
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert len({e["pid"] for e in xs}) == 2
    metas = {e["args"]["name"] for e in doc["traceEvents"]
             if e["ph"] == "M"}
    assert metas == {"replica a", "replica b"}


def test_dedupe_keeps_same_span_ids_across_incarnations(tmp_path):
    """A respawned worker restarts its span counter, and a trace id
    minted ELSEWHERE (the router's, propagated over the wire) can
    reach both incarnations — e.g. a retry of the same request after
    the respawn.  The two spans share (trace_id, span_id) but belong
    to different incarnations (different anchor epochs appended to the
    SAME journal): both must survive dedupe, while a true duplicate of
    one span (journal + flight flush, same incarnation) collapses."""
    jf = str(tmp_path / "journal-w.jsonl")
    span1 = {"name": "serving_request", "trace_id": "ROUTER-T",
             "span_id": "00000005", "parent_id": None,
             "start_s": 0.1, "dur_s": 0.1}
    span2 = dict(span1, start_s=0.2)     # incarnation 2, counter reset
    recs = [{"kind": "trace_anchor", "ts": 500.0, "wall_s": 500.0,
             "perf_s": 10.0, "epoch_s": 10.0, "rank": 0, "replica": "w",
             "pid": 111, "run_id": "pod-test"},
            {"kind": "span", "rank": 0, "replica": "w", "ts": 500.3,
             **span1},
            {"kind": "trace_anchor", "ts": 560.0, "wall_s": 560.0,
             "perf_s": 4.0, "epoch_s": 4.0, "rank": 0, "replica": "w",
             "pid": 222, "run_id": "pod-test"},
            {"kind": "span", "rank": 0, "replica": "w", "ts": 560.3,
             **span2},
            # same-incarnation duplicate of span2 (a periodic flight
            # flush replayed into the journal scanner's view) collapses
            {"kind": "span", "rank": 0, "replica": "w", "ts": 560.3,
             **span2}]
    _write_jsonl(jf, recs)
    (proc,) = aggregate.scan_run_dir(str(tmp_path))
    assert len(proc.spans) == 2
    # and they sit at their OWN incarnations' wall offsets
    walls = sorted(proc.span_wall_start(d) for d in proc.spans)
    assert walls == [pytest.approx(500.1), pytest.approx(560.2)]


def test_flight_dump_merges_with_journal_by_identity(tmp_path):
    """A flight dump whose label doesn't share the journal's filename
    stem — the recorder's default ``rank<r>-pid<pid>`` label next to a
    ``journal-r0.jsonl`` (elastic per-rank flow, no replica id) — is
    still the SAME process: the pod identity block joins them onto one
    pid, and the flight-flushed copy of a journaled span collapses
    instead of appearing twice on the merged timeline."""
    span = {"name": "elastic_recover", "trace_id": "T1",
            "span_id": "00000001", "parent_id": None,
            "start_s": 0.5, "dur_s": 0.2}
    ident = {"rank": 0, "pid": 1234, "run_id": "pod-test"}
    _write_jsonl(str(tmp_path / "journal-r0.jsonl"),
                 [{"kind": "trace_anchor", "ts": 500.0, "wall_s": 500.0,
                   "perf_s": 10.0, "epoch_s": 10.0, **ident},
                  {"kind": "span", "ts": 500.8, **ident, **span}])
    with open(tmp_path / "flight-rank0-pid1234.json", "w") as f:
        json.dump({"kind": "flight", "reason": "periodic", "seq": 3,
                   "label": "rank0-pid1234", "last_phase": "recover",
                   "anchor": {"wall_s": 500.9, "perf_s": 10.9,
                              "epoch_s": 10.0, **ident},
                   "trace": {"dropped": 0},
                   "spans": [dict(span)], "journal_tail": [], **ident}, f)
    (proc,) = aggregate.scan_run_dir(str(tmp_path))
    assert sorted(proc.sources) == ["flight-rank0-pid1234.json",
                                    "journal-r0.jsonl"]
    assert len(proc.spans) == 1          # journal copy == flight copy
    assert proc.flight and proc.flight["reason"] == "periodic"
    doc = aggregate.aggregate_chrome(str(tmp_path))
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert len(xs) == 1 and len({e["pid"] for e in xs}) == 1


# -- trace-ring drops (satellite) ---------------------------------------------

def test_ring_drops_counted_metric_and_doctor_visible(tmp_path):
    from mxnet_tpu.diagnostics.__main__ import _summ_trace
    from mxnet_tpu.observability.metrics import (default_registry,
                                                 reset_metrics)
    from mxnet_tpu.observability.report import trace_report
    jf = str(tmp_path / "j.jsonl")
    reset_journal(jf)
    reset_metrics()
    obtrace.configure(mode="journal", ring=2)
    try:
        for i in range(5):
            with obtrace.span(f"s{i}"):
                pass
        stats = obtrace.get_tracer().stats()
        assert stats["dropped"] == 3
        snap = default_registry().snapshot()
        fam = snap.get(obtrace.DROPS_METRIC)
        assert fam and sum(float(v)
                           for v in fam["values"].values()) == 3.0
    finally:
        obtrace.reset_tracer()
        reset_journal("stderr")
        reset_metrics()
    markers = _records(jf, "trace_ring_drops")
    assert markers and markers[0]["dropped"] == 1
    rep = trace_report(jf)
    assert rep["ok"] and rep["ring_drops"] >= 1
    assert "ring drops" in _summ_trace(rep)


def test_flight_dump_carries_ring_drop_counts(tmp_path, ring):
    obtrace.configure(mode="ring", ring=1)
    try:
        for _ in range(3):
            with obtrace.span("x"):
                pass
        fr = flight.FlightRecorder(str(tmp_path), label="t", flush_s=0)
        path = fr.dump("test")
    finally:
        obtrace.reset_tracer()
    doc = flight.read_flight(path)
    assert doc["trace"]["dropped"] == 2
    rep = aggregate.timeline_report(str(tmp_path))
    row = [p for p in rep["processes"] if "flight" in p][0]
    assert row["flight"]["ring_drops"] == 2


# -- elastic: multi-survivor recovery-trace adoption --------------------------

def test_flight_stop_dump_survives_process_exit(tmp_path, ring):
    """A clean ``stop(dump=True)`` dump is the component's own
    artifact: stop() must UNREGISTER the journal final_cb so the
    exit-time finalizer can't overwrite ``reason="stop"`` with
    ``reason="final"`` (pre-fix, every cleanly-stopped worker's dump
    read ``final``)."""
    from mxnet_tpu.diagnostics.journal import Journal

    j = Journal(str(tmp_path / "j.jsonl"))
    fr = flight.FlightRecorder(str(tmp_path), label="w", flush_s=0,
                               journal=j)
    fr.install()
    with obtrace.span("work"):
        pass
    fr.stop(dump=True)
    j._finalize("atexit")            # simulated not-clean process exit
    doc = flight.read_flight(fr.path)
    assert doc["reason"] == "stop"
    assert j._final_cbs == []        # stopped recorders unreachable


def test_two_survivors_adopt_leader_recovery_trace(tmp_path, ring):
    """The epoch ledger is the recovery-trace channel: the leader
    publishes epoch k+1 inside its elastic_recover span, the other
    survivor adopts the stamped trace id, and both spans (plus every
    record written after adoption) share ONE trace."""
    from mxnet_tpu.elastic.membership import Cohort, CohortConfig
    cfg = CohortConfig(heartbeat_s=0.1, deadline_s=5.0, barrier_s=30.0,
                       poll_s=0.01)
    root = str(tmp_path / "cohort")
    cohorts = {r: Cohort(root, r, cfg).start() for r in (0, 1)}
    results = {}

    def form(r):
        cohorts[r].form(2)

    threads = [threading.Thread(target=form, args=(r,)) for r in (0, 1)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)

    def recover(r):
        with obtrace.span("elastic_recover", rank_sim=r) as sp:
            cohorts[r].resize([])
            doc = cohorts[r].read_epoch_doc() or {}
            obtrace.adopt_trace(sp, doc.get("recovery_trace"))
            results[r] = {"trace_id": sp.trace_id,
                          "span_id": sp.span_id,
                          "recovery_trace": doc.get("recovery_trace")}

    threads = [threading.Thread(target=recover, args=(r,))
               for r in (0, 1)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    for c in cohorts.values():
        c.stop(resign=True)
    assert set(results) == {0, 1}, "a survivor never finished resize"
    stamped = results[0]["recovery_trace"]
    assert stamped, "leader did not stamp a recovery trace"
    # the leader kept its own trace; the survivor adopted it
    assert results[0]["trace_id"] == stamped
    assert results[1]["trace_id"] == stamped
    # the recorded spans agree (both survivors' elastic_recover spans
    # are in one trace)
    spans = [s for s in obtrace.get_tracer().spans()
             if s["name"] == "elastic_recover"]
    assert len(spans) == 2
    assert {s["trace_id"] for s in spans} == {stamped}


# -- the chaos headline (CI tier 0.5 smoke) -----------------------------------

def test_smoke_distributed_trace_sigkill_drill(tmp_path):
    """3 REAL replica workers + a traced router process sharing one run
    directory; SIGKILL one worker under load; assemble the merged
    cross-process trace from per-process files alone and prove: one
    trace_id spans the router and worker journals, the killed replica's
    flight-recorder dump survived and parses, and doctor --timeline
    renders the critical path including the wreckage."""
    from mxnet_tpu.diagnostics.__main__ import _summ_timeline
    from mxnet_tpu.serving import (PoolConfig, ReplicaPool, Router,
                                   RouterConfig, ServerOverloaded)

    run_dir = str(tmp_path / "run")
    os.makedirs(run_dir)
    # the router-side process journals into the SAME run dir
    reset_journal(os.path.join(run_dir, "journal-router.jsonl"))
    obtrace.configure(mode="journal")
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO,
           "MXNET_TPU_TRACE_FLIGHT_S": "0.25"}
    for k in ("XLA_FLAGS", "MXNET_TPU_JOURNAL", "MXNET_TPU_TRACE",
              "MXNET_TPU_TRACE_DIR"):
        env.pop(k, None)
    cfg = PoolConfig(heartbeat_s=0.25, deadline_s=1.5, monitor_s=0.3,
                     trace_dir=run_dir)
    pool = ReplicaPool(str(tmp_path / "pool"), cfg)
    for i in range(3):
        pool.add_proc(f"p{i}", {"--model": "scale", "--window-ms": 1.0},
                      env=env)
    router = Router(pool, RouterConfig(retries=3, breaker_k=2,
                                       breaker_cooldown_s=1.0))
    x = np.arange(4, dtype=np.float32)
    stop = threading.Event()
    unexpected = []

    def client():
        while not stop.is_set():
            try:
                router.call(x, deadline_ms=8000)
            except ServerOverloaded:
                time.sleep(0.01)
            except Exception as e:           # pragma: no cover - loud
                unexpected.append(repr(e))
                time.sleep(0.05)
            time.sleep(0.005)

    threads = [threading.Thread(target=client, daemon=True)
               for _ in range(2)]
    killed_flight = os.path.join(run_dir, "flight-replica-p1.json")
    try:
        pool.start()
        pool.monitor_start()
        for t in threads:
            t.start()
        time.sleep(1.5)                      # steady traced traffic
        assert router.stats()["served"] > 0
        # the periodic flush must have landed at least one dump before
        # the kill — that file IS the postmortem
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline and \
                not os.path.exists(killed_flight):
            time.sleep(0.05)
        assert os.path.exists(killed_flight), "no pre-kill flight flush"
        pool.replicas["p1"].kill()           # the host-vanished shape
        # detection: the monitor journals replica_lost in the router
        # journal (the run dir's router process file)
        router_journal = os.path.join(run_dir, "journal-router.jsonl")
        deadline = time.monotonic() + 30
        lost = []
        while time.monotonic() < deadline and not lost:
            lost = [r for r in _records(router_journal, "replica_lost")
                    if r.get("replica") == "p1"]
            time.sleep(0.05)
        assert lost, "SIGKILLed replica never detected"
        time.sleep(0.3)                      # a little post-kill traffic
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
        router.stop()
        pool.stop()
        obtrace.reset_tracer()
        reset_journal("stderr")
    assert not unexpected, unexpected[:5]

    # (1) ONE trace_id spans the wire: a router_request span in the
    # router journal shares its trace with a serving_request span in a
    # WORKER journal (different process, same trace)
    router_spans = _records(os.path.join(run_dir,
                                         "journal-router.jsonl"), "span")
    router_traces = {s["trace_id"] for s in router_spans
                     if s["name"] == "router_request"}
    assert router_traces
    worker_traces = set()
    for i in range(3):
        wj = os.path.join(run_dir, f"journal-p{i}.jsonl")
        if not os.path.exists(wj):
            continue
        worker_traces |= {s["trace_id"] for s in _records(wj, "span")
                          if s["name"] == "serving_request"}
    crossed = router_traces & worker_traces
    assert crossed, "no trace crossed the process boundary"

    # (2) the killed replica's flight dump is present and parseable,
    # with its span ring and clock anchor intact
    doc = flight.read_flight(killed_flight)
    assert doc["replica"] == "p1" and doc["run_id"] == pool.run_id
    assert isinstance(doc["spans"], list)
    assert {"wall_s", "perf_s", "epoch_s"} <= set(doc["anchor"])

    # (3) assembly from per-process files alone: every process present,
    # p1 contributes its flight wreckage, and the critical path of the
    # slowest routed request crosses processes
    rep = aggregate.timeline_report(run_dir)
    assert rep["ok"]
    labels = {p["proc"] for p in rep["processes"]}
    assert {"replica p0", "replica p1", "replica p2"} <= labels
    assert len(rep["processes"]) == 4        # + the router process
    assert "replica p1" in rep["flight_dumps"]
    cp = rep["critical_path"]
    assert cp["ok"] and len(cp["processes"]) >= 2
    names = [s["name"] for s in cp["steps"]]
    assert names[0] == "router_request"
    assert "serving_request" in names and "execute" in names

    # (4) the merged Perfetto doc keys one pid per process
    chrome = aggregate.aggregate_chrome(run_dir)
    xs = [e for e in chrome["traceEvents"] if e["ph"] == "X"]
    assert len({e["pid"] for e in xs}) >= 4
    metas = {e["args"]["name"] for e in chrome["traceEvents"]
             if e["ph"] == "M"}
    assert any("p1" in m and "flight" in m for m in metas)

    # (5) the doctor line tells the story in one sentence
    line = _summ_timeline(rep)
    assert "flight" in line and "processes" in line

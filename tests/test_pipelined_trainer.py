"""Gluon-level pipeline parallelism (VERDICT r4 Weak #4 / SURVEY §7 P7):
PipelinedTrainer partitions a real [embedding, N x TransformerEncoderCell,
head] model onto the pipe axis itself; training must match the dp-only
ShardedTrainer on the same model bit-for-bit up to fp reassociation."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, parallel
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon.model_zoo.bert import TransformerEncoderCell

V, D, H, HEADS, L, T, B = 32, 16, 32, 4, 4, 8, 16


def _build(seed=3):
    mx.random.seed(seed)
    emb = gluon.nn.Embedding(V, D)
    body = [TransformerEncoderCell(D, H, HEADS, dropout=0.0)
            for _ in range(L)]
    head = gluon.nn.Dense(V, flatten=False)
    for b in [emb] + body + [head]:
        b.initialize()
    h = emb(mx.nd.array(np.zeros((2, T), np.int32)))   # materialize deferred
    for blk in body:
        h = blk(h)
    head(h)
    return emb, body, head


class _SeqWrap(gluon.HybridBlock):
    """The same blocks run sequentially — the dp-only reference model."""

    def __init__(self, emb, body, head):
        super().__init__()
        self.emb, self.head = emb, head
        for i, blk in enumerate(body):
            setattr(self, f"cell{i}", blk)
        self._n = len(body)

    def hybrid_forward(self, F, x):
        h = self.emb(x)
        for i in range(self._n):
            h = getattr(self, f"cell{i}")(h)
        return self.head(h)


def _batches(n, seed=0):
    rng = np.random.RandomState(seed)
    W = rng.randn(V, V)
    out = []
    for _ in range(n):
        toks = rng.randint(0, V, (B, T))
        out.append((toks, W[toks].argmax(-1)))
    return out


def _snapshot(blocks):
    snap = []
    for blk in blocks:
        for p in blk.collect_params().values():
            snap.append((p, np.asarray(p._data[0]._data).copy()))
    return snap


def _restore(snap):
    import jax.numpy as jnp
    for p, arr in snap:
        p._data[0]._rebind(jnp.asarray(arr))


def test_pipelined_matches_dp_only_bert_tiny():
    emb, body, head = _build()
    snap = _snapshot([emb] + body + [head])
    batches = _batches(6)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    opt_kw = {"learning_rate": 2e-3}

    mesh_pp = parallel.make_mesh({"pipe": 2, "data": 4})
    tr_pp = parallel.PipelinedTrainer(
        emb, body, head, loss_fn, "adam", dict(opt_kw), mesh=mesh_pp,
        num_microbatches=4, num_virtual_stages=2)
    losses_pp = [float(tr_pp.step(x, y).asscalar()) for x, y in batches]
    tr_pp.unstack_to_blocks()
    w_pp = [np.asarray(p._data[0]._data).copy()
            for p, _ in _snapshot([emb] + body + [head])]

    _restore(snap)
    mesh_dp = parallel.make_mesh({"data": 8})
    tr_dp = parallel.ShardedTrainer(
        _SeqWrap(emb, body, head), loss_fn, "adam", dict(opt_kw),
        mesh=mesh_dp)
    losses_dp = [float(tr_dp.step(x, y).asscalar()) for x, y in batches]
    w_dp = [np.asarray(p._data[0]._data).copy()
            for p, _ in _snapshot([emb] + body + [head])]

    np.testing.assert_allclose(losses_pp, losses_dp, rtol=2e-4, atol=2e-4)
    assert losses_pp[-1] < losses_pp[0]          # it actually trains
    for a, b in zip(w_pp, w_dp):                 # post-training weights too
        np.testing.assert_allclose(a, b, rtol=3e-3, atol=3e-3)


def test_pipelined_gpipe_schedule_and_lr_api():
    # v=1 (plain GPipe), pipe=2 x data=2 sub-mesh shape
    emb, body, head = _build(seed=9)
    mesh = parallel.make_mesh({"pipe": 2, "data": 4})
    tr = parallel.PipelinedTrainer(
        emb, body[:2], head, gluon.loss.SoftmaxCrossEntropyLoss(),
        "sgd", {"learning_rate": 0.1, "momentum": 0.9}, mesh=mesh,
        num_microbatches=2)
    batches = _batches(8, seed=4)
    losses = [float(tr.step(x, y).asscalar()) for x, y in batches]
    assert losses[-1] < losses[0]
    tr.set_learning_rate(0.05)
    assert tr.learning_rate == 0.05


def test_pipelined_error_paths():
    emb, body, head = _build(seed=5)
    mesh = parallel.make_mesh({"pipe": 2, "data": 4})
    loss = gluon.loss.SoftmaxCrossEntropyLoss()
    with pytest.raises(MXNetError, match="tile onto"):
        parallel.PipelinedTrainer(emb, body[:3], head, loss, "sgd",
                                  mesh=mesh)
    # a BatchNorm body block (aux state) is rejected eagerly
    bn_body = [gluon.nn.BatchNorm() for _ in range(2)]
    for b in bn_body:
        b.initialize()
    tr = parallel.PipelinedTrainer(emb, bn_body, head, loss, "sgd",
                                   mesh=mesh)
    with pytest.raises(MXNetError, match="auxiliary"):
        tr.step(*_batches(1)[0])
    # shape-changing body blocks can't ride one ppermute ring
    sh_body = [gluon.nn.Dense(D + 1, flatten=False),
               gluon.nn.Dense(D + 1, flatten=False)]
    for b in sh_body:
        b.initialize()
    tr = parallel.PipelinedTrainer(emb, sh_body, head, loss, "sgd",
                                   mesh=mesh)
    with pytest.raises(MXNetError, match="activation shape"):
        tr.step(*_batches(1)[0])


def test_pipelined_checkpoint_resume_bitwise(tmp_path):
    """The pp trainer has the same resume story as the flagship: train k,
    save, train m ("uninterrupted"); fresh blocks + load + train m
    ("resumed") must match every stacked weight and state bitwise."""
    batches = _batches(6, seed=8)
    prefix = str(tmp_path / "pck")
    mesh = parallel.make_mesh({"pipe": 2, "data": 4})

    def build(seed):
        emb, body, head = _build(seed=seed)
        tr = parallel.PipelinedTrainer(
            emb, body, head, gluon.loss.SoftmaxCrossEntropyLoss(),
            "adam", {"learning_rate": 2e-3}, mesh=mesh,
            num_microbatches=4, num_virtual_stages=2)
        return tr

    tr_a = build(seed=21)
    for x, y in batches[:3]:
        tr_a.step(x, y)
    tr_a.save_checkpoint(prefix)
    for x, y in batches[3:]:
        tr_a.step(x, y)
    want = {k: np.asarray(v) for k, v in tr_a._ckpt_entries().items()}

    tr_b = build(seed=99)                 # different init: must not matter
    tr_b.prepare(batches[0][0])
    tr_b.load_checkpoint(prefix)
    assert tr_b._num_update == 3
    for x, y in batches[3:]:
        tr_b.step(x, y)
    got = {k: np.asarray(v) for k, v in tr_b._ckpt_entries().items()}
    assert set(want) == set(got)
    for k in want:
        assert np.array_equal(want[k], got[k]), f"{k} diverged"

    # layout mismatch is rejected at construction (4 blocks, pipe=2, v=1)
    emb, body, head = _build(seed=5)
    with pytest.raises(MXNetError, match="tile onto"):
        parallel.PipelinedTrainer(
            emb, body, head, gluon.loss.SoftmaxCrossEntropyLoss(),
            "adam", {"learning_rate": 2e-3}, mesh=mesh,
            num_microbatches=4, num_virtual_stages=1)
    tr_d = build(seed=7)
    tr_d.prepare(batches[0][0])
    tr_d._optimizer = __import__("mxnet_tpu").optimizer.create(
        "sgd", learning_rate=0.1)
    with pytest.raises(MXNetError, match="optimizer"):
        tr_d.load_checkpoint(prefix)


def test_pipelined_evaluate_matches_sequential_forward():
    emb, body, head = _build(seed=13)
    mesh = parallel.make_mesh({"pipe": 2, "data": 4})
    tr = parallel.PipelinedTrainer(
        emb, body, head, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": 0.1}, mesh=mesh, num_microbatches=4,
        num_virtual_stages=2)
    x, y = _batches(1, seed=6)[0]
    # sequential eager reference FIRST — prepare() re-commits the block
    # params onto the mesh, after which eager forwards can't run
    h = emb(mx.nd.array(x))
    for blk in body:
        h = blk(h)
    logits = head(h)
    ref = float(gluon.loss.SoftmaxCrossEntropyLoss()(
        logits, mx.nd.array(y)).mean().asscalar())
    ev = float(tr.evaluate(x, y).asscalar())
    assert abs(ev - ref) < 1e-4, (ev, ref)
    # evaluate must not advance the step counter or weights
    before = [np.asarray(w).copy() for w in tr._b_datas]
    tr.evaluate(x, y)
    assert tr._num_update == 0
    for a, b in zip(before, tr._b_datas):
        assert np.array_equal(a, np.asarray(b))


def test_pipelined_run_steps_matches_stepping():
    """k scanned steps (one program) must track k individual step() calls
    on the same reused batch — the dispatch-amortization path can't
    change the math."""
    x, y = _batches(1, seed=11)[0]
    mesh = parallel.make_mesh({"pipe": 2, "data": 4})

    def build():
        emb, body, head = _build(seed=31)
        return parallel.PipelinedTrainer(
            emb, body, head, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
            {"learning_rate": 0.05, "momentum": 0.9}, mesh=mesh,
            num_microbatches=4, num_virtual_stages=2)

    tr_a = build()
    for _ in range(4):
        loss_a = tr_a.step(x, y)
    tr_b = build()
    loss_b = tr_b.run_steps(x, y, num_steps=4)
    assert tr_b.num_update == 4
    # same math modulo scan-vs-loop fp reassociation and per-step RNG
    # keys (dropout=0 here, so keys are moot)
    np.testing.assert_allclose(float(loss_b.asscalar()),
                               float(loss_a.asscalar()), rtol=1e-4)
    wa = {k: np.asarray(v) for k, v in tr_a._ckpt_entries().items()}
    wb = {k: np.asarray(v) for k, v in tr_b._ckpt_entries().items()}
    for k in wa:
        np.testing.assert_allclose(wa[k], wb[k], rtol=2e-4, atol=2e-5,
                                   err_msg=k)


def test_run_steps_respects_lr_schedule():
    """The scanned multi-step path must apply the scheduler's per-step lr
    (a frozen first-step lr would silently change warmup math)."""
    from mxnet_tpu import lr_scheduler
    x, y = _batches(1, seed=12)[0]
    mesh = parallel.make_mesh({"pipe": 2, "data": 4})

    def build():
        emb, body, head = _build(seed=41)
        opt = __import__("mxnet_tpu").optimizer.create(
            "sgd", learning_rate=0.1,
            lr_scheduler=lr_scheduler.FactorScheduler(step=2, factor=0.5))
        return parallel.PipelinedTrainer(
            emb, body, head, gluon.loss.SoftmaxCrossEntropyLoss(), opt,
            mesh=mesh, num_microbatches=4, num_virtual_stages=2)

    tr_a = build()
    for _ in range(4):
        tr_a.step(x, y)
    tr_b = build()
    tr_b.run_steps(x, y, num_steps=4)
    wa = {k: np.asarray(v) for k, v in tr_a._ckpt_entries().items()}
    wb = {k: np.asarray(v) for k, v in tr_b._ckpt_entries().items()}
    for k in wa:
        np.testing.assert_allclose(wa[k], wb[k], rtol=2e-4, atol=2e-5,
                                   err_msg=k)


def test_pipeline_dropout_masks_independent_across_stages_and_ticks():
    """The scan body folds (layer, tick) into the stage key (ADVICE r5
    medium): two dropout stages must draw INDEPENDENT masks — one shared
    mask would zero ~50% of elements at rate 0.5 where independent masks
    zero ~75% — and the two microbatches of one layer must not share a
    zero pattern either. Deterministic: fixed base key, no flake."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.parallel.pipeline import pipeline_apply

    mesh = parallel.make_mesh({"pipe": 2, "data": 4})
    rate = 0.5
    base = jax.random.PRNGKey(7)

    def stage_fn(params, h, ctx):
        k = jax.random.fold_in(jax.random.fold_in(jax.random.fold_in(
            base, ctx["layer"]), ctx["tick"]), ctx["shard"])
        keep = jax.random.bernoulli(k, 1.0 - rate, h.shape)
        return jnp.where(keep, h / (1.0 - rate), 0.0)

    params = jnp.zeros((2, 1))               # L=2 dummy param stack
    x = jnp.ones((8, 64), jnp.float32)       # m=2 microbatches of 4 rows
    out = np.asarray(pipeline_apply(stage_fn, params, x, mesh=mesh,
                                    num_microbatches=2, stage_ctx=True))
    zero_frac = float((out == 0).mean())
    # independent masks: P(zero) = 1-(1-rate)^2 = 0.75; a single shared
    # mask gives 0.5. 512 elements puts 6+ sigma between the two.
    assert zero_frac > 0.65, f"masks look correlated: zero_frac={zero_frac}"
    # microbatch 0 (rows 0-3) and microbatch 1 (rows 4-7) run the same
    # layers at different ticks -> different masks -> different patterns
    assert not np.array_equal(out[:4] == 0, out[4:] == 0)
    # determinism: same keys -> bit-identical output
    out2 = np.asarray(pipeline_apply(stage_fn, params, x, mesh=mesh,
                                     num_microbatches=2, stage_ctx=True))
    assert np.array_equal(out, out2)

    # DATA-PARALLEL shards must not share masks either: with
    # data_axis="data" each of the 4 dp ranks owns one row per
    # microbatch, and ctx["shard"] separates their keys — without it
    # every rank would draw the identical mask for its slice
    out_dp = np.asarray(pipeline_apply(
        stage_fn, params, x, mesh=mesh, num_microbatches=2,
        data_axis="data", stage_ctx=True))
    mb0 = out_dp[:4] == 0                    # rows of microbatch 0,
    for i in range(1, 4):                    # one per dp shard
        assert not np.array_equal(mb0[0], mb0[i]), \
            f"dp shards 0 and {i} drew identical dropout masks"


def test_pipelined_trainer_with_dropout_trains_and_eval_parity():
    """dropout>0 extension of the dp-parity suite: the pipelined trainer
    must train (finite, decreasing loss) with active dropout, and
    ``evaluate`` (dropout off) must still match the sequential eager
    forward exactly — mode-off parity holds at any dropout rate."""
    mx.random.seed(17)
    emb = gluon.nn.Embedding(V, D)
    body = [TransformerEncoderCell(D, H, HEADS, dropout=0.2)
            for _ in range(L)]
    head = gluon.nn.Dense(V, flatten=False)
    for b in [emb] + body + [head]:
        b.initialize()
    h = emb(mx.nd.array(np.zeros((2, T), np.int32)))
    for blk in body:
        h = blk(h)
    head(h)
    batches = _batches(8, seed=15)
    x0, y0 = batches[0]
    # sequential eager reference BEFORE prepare() commits params
    h = emb(mx.nd.array(x0))
    for blk in body:
        h = blk(h)
    ref = float(gluon.loss.SoftmaxCrossEntropyLoss()(
        head(h), mx.nd.array(y0)).mean().asscalar())

    mesh = parallel.make_mesh({"pipe": 2, "data": 4})
    tr = parallel.PipelinedTrainer(
        emb, body, head, gluon.loss.SoftmaxCrossEntropyLoss(), "adam",
        {"learning_rate": 2e-3}, mesh=mesh, num_microbatches=4,
        num_virtual_stages=2)
    ev = float(tr.evaluate(x0, y0).asscalar())
    assert abs(ev - ref) < 1e-4, (ev, ref)

    losses = [float(tr.step(x, y).asscalar()) for x, y in batches]
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]            # trains through the noise
    # the scanned multi-step path folds per-step keys too
    loss_ms = tr.run_steps(x0, y0, num_steps=2)
    assert np.isfinite(float(loss_ms.asscalar()))

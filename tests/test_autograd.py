"""Autograd semantics (ref test: tests/python/unittest/test_autograd.py)."""

from mxnet_tpu import autograd, nd
from mxnet_tpu.test_utils import assert_almost_equal


def test_basic_backward():
    x = nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = (x * x).sum()
    y.backward()
    assert_almost_equal(x.grad, 2 * x.asnumpy())


def test_chain_and_shared_subexpression():
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * 3
        z = y * y + y     # dz/dx = (2y*3) + 3 = 39 at x=2
    z.backward()
    assert x.grad.asscalar() == 39.0


def test_grad_req_add_and_write():
    x = nd.ones((2,))
    x.attach_grad(grad_req="add")
    for _ in range(3):
        with autograd.record():
            y = (x * 2).sum()
        y.backward()
    assert x.grad.asnumpy().tolist() == [6, 6]
    x.attach_grad(grad_req="write")
    for _ in range(3):
        with autograd.record():
            y = (x * 2).sum()
        y.backward()
    assert x.grad.asnumpy().tolist() == [2, 2]


def test_is_recording_training_scopes():
    assert not autograd.is_recording()
    with autograd.record():
        assert autograd.is_recording()
        assert autograd.is_training()
        with autograd.pause():
            assert not autograd.is_recording()
        with autograd.predict_mode():
            assert not autograd.is_training()
    with autograd.record(train_mode=False):
        assert autograd.is_recording()
        assert not autograd.is_training()


def test_head_gradient():
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y = x * 2
    y.backward(nd.array([10.0, 100.0]))
    assert x.grad.asnumpy().tolist() == [20, 200]


def test_detach_blocks_gradient():
    x = nd.array([3.0])
    x.attach_grad()
    with autograd.record():
        y = x * 2
        z = y.detach() * x
    z.backward()
    assert x.grad.asscalar() == 6.0  # only the direct path


def test_stop_gradient_op():
    x = nd.array([3.0])
    x.attach_grad()
    with autograd.record():
        y = nd.BlockGrad(x * 2) * x
    y.backward()
    assert x.grad.asscalar() == 6.0


def test_autograd_grad_function():
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x * x
    (g,) = autograd.grad([y], [x])
    assert g.asscalar() == 12.0
    assert x.grad.asnumpy().tolist() == [0.0]  # .grad untouched by grad()


def test_mark_variables():
    x = nd.array([4.0])
    g = nd.zeros((1,))
    autograd.mark_variables([x], [g])
    with autograd.record():
        y = x * 5
    y.backward()
    assert x.grad.asscalar() == 5.0


def test_indexing_gradient():
    x = nd.array([[1.0, 2.0], [3.0, 4.0]])
    x.attach_grad()
    with autograd.record():
        y = x[0].sum() * 2
    y.backward()
    assert x.grad.asnumpy().tolist() == [[2, 2], [0, 0]]


def test_multi_head_backward():
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y1 = (x * 2).sum()
        y2 = (x * 3).sum()
    autograd.backward([y1, y2])
    assert x.grad.asnumpy().tolist() == [5, 5]


def test_second_use_after_backward():
    # backward with retain_graph allows a second pass
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
    y.backward(retain_graph=True)
    g1 = x.grad.asscalar()
    y.backward()
    assert x.grad.asscalar() == g1


def test_grad_does_not_clobber_other_leaves():
    x = nd.array([1.0]); x.attach_grad()
    w = nd.array([2.0]); w.attach_grad()
    with autograd.record():
        y = x * w
    (gw,) = autograd.grad([y], [w])
    assert gw.asscalar() == 1.0
    assert x.grad.asscalar() == 0.0   # untouched


def test_backward_frees_graph():
    import pytest
    x = nd.array([2.0]); x.attach_grad()
    with autograd.record():
        y = x * x
    y.backward()
    with pytest.raises(Exception):
        y.backward()   # graph freed; second pass must raise, not mis-compute


def test_moveaxis_records_gradient():
    x = nd.ones((2, 3)); x.attach_grad()
    with autograd.record():
        y = nd.moveaxis(x, 0, 1).sum()
    y.backward()
    assert x.grad.asnumpy().tolist() == [[1, 1, 1], [1, 1, 1]]

"""C predict API: the native (no-Python) inference path over exported
-symbol.json + .params (ref: src/c_api/c_predict_api.cc; example client
analog: the reference's predict-cpp image-classification example)."""
import ctypes
import os
import shutil
import subprocess

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu._native import get_lib

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _predict_native(lib, sym_path, params_path, x):
    lib.MXPredCreate.restype = ctypes.c_int
    lib.MXPredGetLastError.restype = ctypes.c_char_p
    handle = ctypes.c_void_p()
    sym = open(sym_path, "rb").read()
    params = open(params_path, "rb").read()
    rc = lib.MXPredCreate(ctypes.c_char_p(sym), params, len(params), 1, 0,
                          0, None, None, None, ctypes.byref(handle))
    assert rc == 0, lib.MXPredGetLastError().decode()
    shape = (ctypes.c_long * x.ndim)(*x.shape)
    assert lib.MXPredSetInputShape(handle, b"data", shape, x.ndim) == 0
    flat = np.ascontiguousarray(x, dtype=np.float32)
    assert lib.MXPredSetInput(
        handle, b"data",
        flat.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        flat.size) == 0, lib.MXPredGetLastError().decode()
    rc = lib.MXPredForward(handle)
    assert rc == 0, lib.MXPredGetLastError().decode()
    oshape = (ctypes.c_long * 8)()
    ondim = ctypes.c_uint()
    assert lib.MXPredGetOutputShape(handle, 0, oshape,
                                    ctypes.byref(ondim)) == 0
    out_shape = tuple(oshape[i] for i in range(ondim.value))
    out = np.zeros(out_shape, np.float32)
    assert lib.MXPredGetOutput(
        handle, 0, out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        out.size) == 0
    lib.MXPredFree(handle)
    return out


@pytest.fixture(scope="module")
def native_lib():
    lib = get_lib()
    if lib is None or not hasattr(lib, "MXPredCreate"):
        pytest.skip("native library unavailable")
    return lib


def test_lenet_matches_python(native_lib, tmp_path):
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(8, 5, activation="relu"),
            gluon.nn.MaxPool2D(2),
            gluon.nn.Conv2D(16, 5, activation="tanh"),
            gluon.nn.AvgPool2D(2),
            gluon.nn.Flatten(),
            gluon.nn.Dense(32, activation="relu"),
            gluon.nn.Dense(10))
    net.initialize(mx.init.Xavier())
    net.hybridize()
    x = np.random.rand(4, 1, 28, 28).astype(np.float32)
    want = net(nd.array(x)).asnumpy()
    prefix = str(tmp_path / "lenet")
    net.export(prefix)
    got = _predict_native(native_lib, f"{prefix}-symbol.json",
                          f"{prefix}-0000.params", x)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_resnet18_matches_python(native_lib, tmp_path):
    from mxnet_tpu.gluon.model_zoo import vision
    net = vision.resnet18_v1(classes=10)
    net.initialize()
    for _ in range(2):    # warm BN running stats
        with autograd.record():
            net(nd.array(np.random.randn(4, 3, 32, 32)
                         .astype(np.float32)))
    net.hybridize()
    x = np.random.randn(2, 3, 32, 32).astype(np.float32)
    want = net(nd.array(x)).asnumpy()
    prefix = str(tmp_path / "rn18")
    net.export(prefix)
    got = _predict_native(native_lib, f"{prefix}-symbol.json",
                          f"{prefix}-0000.params", x)
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-4)


def _predict_native_multi(lib, sym_path, params_path, inputs, n_out):
    """Multi-input / multi-output variant of the C driver."""
    lib.MXPredCreate.restype = ctypes.c_int
    lib.MXPredGetLastError.restype = ctypes.c_char_p
    handle = ctypes.c_void_p()
    sym = open(sym_path, "rb").read()
    params = open(params_path, "rb").read()
    rc = lib.MXPredCreate(ctypes.c_char_p(sym), params, len(params), 1, 0,
                          0, None, None, None, ctypes.byref(handle))
    assert rc == 0, lib.MXPredGetLastError().decode()
    for key, x in inputs.items():
        shape = (ctypes.c_long * x.ndim)(*x.shape)
        assert lib.MXPredSetInputShape(handle, key.encode(), shape,
                                       x.ndim) == 0
        flat = np.ascontiguousarray(x, dtype=np.float32)
        assert lib.MXPredSetInput(
            handle, key.encode(),
            flat.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            flat.size) == 0, lib.MXPredGetLastError().decode()
    assert lib.MXPredForward(handle) == 0, \
        lib.MXPredGetLastError().decode()
    outs = []
    for i in range(n_out):
        oshape = (ctypes.c_long * 8)()
        ondim = ctypes.c_uint()
        assert lib.MXPredGetOutputShape(handle, i, oshape,
                                        ctypes.byref(ondim)) == 0
        out = np.zeros(tuple(oshape[j] for j in range(ondim.value)),
                       np.float32)
        assert lib.MXPredGetOutput(
            handle, i, out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            out.size) == 0
        outs.append(out)
    lib.MXPredFree(handle)
    return outs


def test_bert_encoder_matches_python(native_lib, tmp_path):
    """Round-2 verdict #4: the repo's own flagship NLP export must be
    servable from C — full BERT (embeddings + encoder + pooler + MLM
    decoder head), bit-accurate vs Python."""
    from mxnet_tpu.gluon.model_zoo import bert
    net = bert.BERTModel(num_layers=2, units=32, hidden_size=64,
                         num_heads=4, max_length=64, vocab_size=97,
                         use_pooler=True, use_decoder=True,
                         use_classifier=False, dropout=0.0)
    net.initialize(mx.init.Normal(0.1))
    net.hybridize()
    toks = np.random.RandomState(0).randint(0, 97, (2, 12)) \
        .astype(np.float32)
    want = [o.asnumpy() for o in net(nd.array(toks))]
    prefix = str(tmp_path / "bert")
    net.export(prefix)
    got = _predict_native_multi(native_lib, f"{prefix}-symbol.json",
                                f"{prefix}-0000.params", {"data": toks},
                                len(want))
    for g, w in zip(got, want):
        np.testing.assert_allclose(g, w, rtol=1e-4, atol=1e-5)


def test_nmt_transformer_matches_python(native_lib, tmp_path):
    """Sockeye-style encoder-decoder transformer (two inputs, causal self
    attention + cross attention) served from C."""
    from mxnet_tpu.gluon.model_zoo import transformer
    net = transformer.TransformerModel(
        src_vocab=53, tgt_vocab=61, num_layers=2, units=32, hidden_size=64,
        num_heads=4, max_length=40, dropout=0.0)
    net.initialize(mx.init.Normal(0.1))
    net.hybridize()
    rng = np.random.RandomState(1)
    src = rng.randint(1, 53, (2, 9)).astype(np.float32)
    tgt = rng.randint(1, 61, (2, 7)).astype(np.float32)
    want = net(nd.array(src), nd.array(tgt)).asnumpy()
    prefix = str(tmp_path / "nmt")
    net.export(prefix)
    got = _predict_native_multi(native_lib, f"{prefix}-symbol.json",
                                f"{prefix}-0000.params",
                                {"data0": src, "data1": tgt}, 1)[0]
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_error_paths(native_lib, tmp_path):
    lib = native_lib
    handle = ctypes.c_void_p()
    rc = lib.MXPredCreate(b"not json at all", b"junk", 4, 1, 0, 0, None,
                          None, None, ctypes.byref(handle))
    assert rc != 0
    assert lib.MXPredGetLastError().decode()


def test_c_client_end_to_end(native_lib, tmp_path):
    cc = shutil.which("gcc") or shutil.which("cc")
    if cc is None:
        pytest.skip("no C compiler")
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(16, activation="relu"), gluon.nn.Dense(10))
    net.initialize(mx.init.Xavier())
    net.hybridize()
    x = np.random.rand(8, 784).astype(np.float32)
    want = net(nd.array(x)).asnumpy().argmax(1)
    prefix = str(tmp_path / "mlp")
    net.export(prefix)
    x.tofile(str(tmp_path / "in.f32"))
    exe = str(tmp_path / "client")
    native_dir = os.path.join(REPO, "native")
    subprocess.run(
        [cc, "-o", exe, os.path.join(native_dir, "test_predict.c"),
         f"-L{native_dir}", "-lmxtpu", f"-Wl,-rpath,{native_dir}"],
        check=True, capture_output=True, timeout=600)
    out = subprocess.run(
        [exe, f"{prefix}-symbol.json", f"{prefix}-0000.params",
         str(tmp_path / "in.f32"), "8"],
        check=True, capture_output=True, text=True, timeout=600)
    got = np.array([int(v) for v in out.stdout.split()])
    np.testing.assert_array_equal(got, want)


def test_cpp_client_end_to_end(native_lib, tmp_path):
    """The C++ RAII API (native/mxnet_tpu.hpp, the cpp-package analog)
    serves an exported model bit-identically to Python: build the C++
    client, run it, compare argmax rows; the client also asserts the
    exception error path and move semantics internally."""
    cxx = shutil.which("g++") or shutil.which("c++")
    if cxx is None:
        pytest.skip("no C++ compiler")
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(16, activation="relu"), gluon.nn.Dense(10))
    net.initialize(mx.init.Xavier())
    net.hybridize()
    x = np.random.rand(8, 784).astype(np.float32)
    want = net(nd.array(x)).asnumpy().argmax(1)
    prefix = str(tmp_path / "mlp")
    net.export(prefix)
    x.tofile(str(tmp_path / "in.f32"))
    exe = str(tmp_path / "client_cpp")
    native_dir = os.path.join(REPO, "native")
    subprocess.run(
        [cxx, "-std=c++17", "-o", exe,
         os.path.join(native_dir, "test_cpp_api.cc"),
         f"-I{native_dir}", f"-L{native_dir}", "-lmxtpu",
         f"-Wl,-rpath,{native_dir}"],
        check=True, capture_output=True, timeout=600)
    out = subprocess.run(
        [exe, f"{prefix}-symbol.json", f"{prefix}-0000.params",
         str(tmp_path / "in.f32"), "8", "784"],
        check=True, capture_output=True, text=True, timeout=600)
    got = np.array([int(v) for v in out.stdout.split()])
    np.testing.assert_array_equal(got, want)

"""mx.np / mx.npx namespace tests (ref: tests/python/unittest/
test_numpy_op.py / test_numpy_ndarray.py, shrunk to the semantics that
matter: numpy-identical results + autograd through the np namespace)."""
import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import autograd


def test_np_basic_functions_match_numpy():
    x = onp.random.RandomState(0).randn(4, 5).astype(onp.float32)
    a = mx.np.array(x)
    for fn in ["exp", "tanh", "abs", "floor", "sign"]:
        got = getattr(mx.np, fn)(a).asnumpy()
        want = getattr(onp, fn)(x)
        onp.testing.assert_allclose(got, want, rtol=1e-6)
    onp.testing.assert_allclose(mx.np.sum(a, axis=1).asnumpy(),
                                x.sum(axis=1), rtol=1e-6)
    onp.testing.assert_allclose(mx.np.mean(a, axis=0,
                                           keepdims=True).asnumpy(),
                                x.mean(axis=0, keepdims=True), rtol=1e-6)


def test_np_zero_dim_and_broadcasting():
    """The semantics the reference built mx.np for: 0-d arrays, numpy
    broadcasting, integer dtypes."""
    s = mx.np.array(3.0)
    assert s.shape == ()
    out = mx.np.add(s, mx.np.ones((2, 3)))
    assert out.shape == (2, 3)
    m = mx.np.arange(6).reshape((3, 2)) if hasattr(
        mx.np.arange(6), "reshape") else None
    a = mx.np.arange(6)
    assert a.dtype == onp.int32 or a.dtype == onp.int64


def test_np_matmul_einsum():
    rng = onp.random.RandomState(1)
    a = rng.randn(3, 4).astype(onp.float32)
    b = rng.randn(4, 5).astype(onp.float32)
    got = mx.np.matmul(mx.np.array(a), mx.np.array(b)).asnumpy()
    onp.testing.assert_allclose(got, a @ b, rtol=1e-5)
    got2 = mx.np.einsum("ij,jk->ik", mx.np.array(a), mx.np.array(b))
    onp.testing.assert_allclose(got2.asnumpy(), a @ b, rtol=1e-5)


def test_np_autograd():
    a = mx.np.array([[1.0, 2.0], [3.0, 4.0]])
    a.attach_grad()
    with autograd.record():
        y = mx.np.sum(mx.np.exp(a) * 2.0)
    y.backward()
    onp.testing.assert_allclose(a.grad.asnumpy(),
                                2.0 * onp.exp([[1, 2], [3, 4]]), rtol=1e-5)


def test_np_linalg_fft_random():
    m = onp.eye(3, dtype=onp.float32) * 4.0
    inv = mx.np.linalg.inv(mx.np.array(m))
    onp.testing.assert_allclose(inv.asnumpy(), onp.linalg.inv(m), rtol=1e-5)
    x = mx.np.random.normal(size=(16,))
    assert x.shape == (16,)
    f = mx.np.fft.fft(mx.np.array(onp.ones(8, onp.float32)))
    assert f.shape == (8,)


def test_np_sort_where_unique():
    x = mx.np.array([3.0, 1.0, 2.0, 1.0])
    onp.testing.assert_allclose(mx.np.sort(x).asnumpy(), [1, 1, 2, 3])
    w = mx.np.where(x > 1.5, x, mx.np.zeros_like(x))
    onp.testing.assert_allclose(w.asnumpy(), [3, 0, 2, 0])


def test_npx_ops():
    x = mx.np.array([[1.0, 2.0, 3.0]])
    sm = mx.npx.softmax(x)
    onp.testing.assert_allclose(sm.asnumpy().sum(), 1.0, rtol=1e-6)
    assert mx.npx.relu(mx.np.array([-1.0, 2.0])).asnumpy().tolist() == \
        [0.0, 2.0]
    oh = mx.npx.one_hot(mx.np.array([0, 2]), 3)
    onp.testing.assert_allclose(oh.asnumpy(),
                                [[1, 0, 0], [0, 0, 1]])
    mx.npx.set_np()
    assert mx.npx.is_np_array()
    mx.npx.reset_np()
    assert not mx.npx.is_np_array()


def test_npx_fully_connected_and_norm():
    x = mx.np.array(onp.random.randn(2, 4).astype(onp.float32))
    w = mx.np.array(onp.random.randn(3, 4).astype(onp.float32))
    out = mx.npx.fully_connected(x, w, num_hidden=3)
    assert out.shape == (2, 3)
    g = mx.np.ones((4,))
    b = mx.np.zeros((4,))
    ln = mx.npx.layer_norm(x, g, b, axis=-1)
    onp.testing.assert_allclose(ln.asnumpy().mean(axis=-1), [0, 0],
                                atol=1e-6)

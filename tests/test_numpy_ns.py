"""mx.np / mx.npx namespace tests (ref: tests/python/unittest/
test_numpy_op.py / test_numpy_ndarray.py, shrunk to the semantics that
matter: numpy-identical results + autograd through the np namespace)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd


def test_np_basic_functions_match_numpy():
    x = onp.random.RandomState(0).randn(4, 5).astype(onp.float32)
    a = mx.np.array(x)
    for fn in ["exp", "tanh", "abs", "floor", "sign"]:
        got = getattr(mx.np, fn)(a).asnumpy()
        want = getattr(onp, fn)(x)
        onp.testing.assert_allclose(got, want, rtol=1e-6)
    onp.testing.assert_allclose(mx.np.sum(a, axis=1).asnumpy(),
                                x.sum(axis=1), rtol=1e-6)
    onp.testing.assert_allclose(mx.np.mean(a, axis=0,
                                           keepdims=True).asnumpy(),
                                x.mean(axis=0, keepdims=True), rtol=1e-6)


def _r(*shape, seed=0, pos=False, scale=1.0):
    x = onp.random.RandomState(seed).randn(*shape).astype(onp.float32)
    x = x * scale
    return onp.abs(x) + 0.5 if pos else x


# systematic numpy-parity sweep (ref: test_numpy_op.py breadth): each row
# is (callable on mx.np + onp given numpy inputs, inputs). The same
# lambda body runs against both namespaces — any signature or semantics
# drift fails the row.
_SWEEP = {
    "log": (lambda np_, x: np_.log(x), [_r(3, 4, pos=True)]),
    "sqrt": (lambda np_, x: np_.sqrt(x), [_r(3, 4, pos=True)]),
    "square": (lambda np_, x: np_.square(x), [_r(3, 4)]),
    "cbrt": (lambda np_, x: np_.cbrt(x), [_r(3, 4, pos=True)]),
    "reciprocal": (lambda np_, x: np_.reciprocal(x),
                   [_r(3, 4, pos=True)]),
    "sin_cos": (lambda np_, x: np_.sin(x) + np_.cos(x), [_r(3, 4)]),
    "arctan2": (lambda np_, a, b: np_.arctan2(a, b),
                [_r(3, 4), _r(3, 4, seed=1, pos=True)]),
    "hypot": (lambda np_, a, b: np_.hypot(a, b),
              [_r(3, 4), _r(3, 4, seed=2)]),
    "maximum": (lambda np_, a, b: np_.maximum(a, b),
                [_r(3, 4), _r(3, 4, seed=3)]),
    "clip": (lambda np_, x: np_.clip(x, -0.5, 0.5), [_r(3, 4)]),
    "rint": (lambda np_, x: np_.rint(x), [_r(3, 4, scale=3.0)]),
    "trunc": (lambda np_, x: np_.trunc(x), [_r(3, 4, scale=3.0)]),
    "prod": (lambda np_, x: np_.prod(x, axis=1),
             [_r(3, 4, pos=True)]),
    "cumsum": (lambda np_, x: np_.cumsum(x, axis=1), [_r(3, 4)]),
    "std_var": (lambda np_, x: np_.std(x, axis=0) + np_.var(x, axis=0),
                [_r(5, 4)]),
    "argmax_argmin": (
        lambda np_, x: np_.argmax(x, axis=1) + np_.argmin(x, axis=1),
        [_r(3, 4)]),
    "sort": (lambda np_, x: np_.sort(x, axis=-1), [_r(3, 4)]),
    "argsort": (lambda np_, x: np_.argsort(x, axis=-1), [_r(3, 4)]),
    "where": (lambda np_, a, b: np_.where(a > 0, a, b),
              [_r(3, 4), _r(3, 4, seed=4)]),
    "concatenate": (
        lambda np_, a, b: np_.concatenate([a, b], axis=1),
        [_r(2, 3), _r(2, 4, seed=5)]),
    "stack": (
        lambda np_, a, b: np_.stack([a, b], axis=0),
        [_r(2, 3), _r(2, 3, seed=6)]),
    "split": (
        lambda np_, x: np_.split(x, 2, 1)[0] + np_.split(x, 2, 1)[1],
        [_r(3, 4)]),
    "take_kwarg": (
        lambda np_, x: np_.take(x, onp.array([0, 2]), axis=1)
        if np_ is onp else np_.take(x, np_.array([0, 2]), axis=1),
        [_r(3, 4)]),
    "transpose_swap": (
        lambda np_, x: np_.swapaxes(np_.transpose(x), 0, 1),
        [_r(3, 4)]),
    "expand_squeeze": (
        lambda np_, x: np_.squeeze(np_.expand_dims(x, 1), 1),
        [_r(3, 4)]),
    "tile_repeat": (lambda np_, x: np_.tile(x, (2, 1)), [_r(2, 3)]),
    "flip": (lambda np_, x: np_.flip(x, axis=1), [_r(3, 4)]),
    "roll": (lambda np_, x: np_.roll(x, 2, axis=1), [_r(3, 4)]),
    "dot_tensordot": (
        lambda np_, a, b: np_.tensordot(a, b, axes=([1], [0])),
        [_r(3, 4), _r(4, 2, seed=7)]),
    "outer_inner": (lambda np_, a, b: np_.outer(a, b),
                    [_r(3), _r(4, seed=8)]),
    "trace_diag": (
        lambda np_, x: np_.trace(x) + np_.sum(np_.diag(x)),
        [_r(4, 4)]),
    "tril_triu": (lambda np_, x: np_.tril(x) + np_.triu(x, 1),
                  [_r(4, 4)]),
    "eye_full": (
        lambda np_, x: x + np_.eye(4, dtype=onp.float32), [_r(4, 4)]),
    "linspace": (
        lambda np_, x: x + np_.linspace(
            0.0, 1.0, 4, dtype=onp.float32), [_r(3, 4)]),
    "isnan_isinf": (
        lambda np_, x: np_.isnan(x).astype(onp.float32)
        + np_.isinf(x).astype(onp.float32), [_r(3, 4)]),
    "logical": (
        lambda np_, a, b: np_.logical_and(a > 0, b > 0)
        .astype(onp.float32), [_r(3, 4), _r(3, 4, seed=9)]),
    "power_mod": (lambda np_, a, b: np_.power(a, 2.0) + np_.mod(b, 2.0),
                  [_r(3, 4, pos=True), _r(3, 4, seed=10, pos=True)]),
    "minmax_reduce": (
        lambda np_, x: np_.max(x, axis=0) - np_.min(x, axis=1,
                                                    keepdims=False)[:3],
        [_r(4, 3)]),
    "ravel_reshape": (
        lambda np_, x: np_.reshape(np_.ravel(x), (4, 3)), [_r(3, 4)]),
    "atleast_broadcast_to": (
        lambda np_, x: np_.broadcast_to(x, (2, 3, 4)), [_r(3, 4)]),
}


@pytest.mark.parametrize("name", sorted(_SWEEP))
def test_np_parity_sweep(name):
    fn, inputs = _SWEEP[name]
    want = fn(onp, *inputs)
    got = fn(mx.np, *[mx.np.array(x) for x in inputs])
    got = got.asnumpy() if hasattr(got, "asnumpy") else onp.asarray(got)
    onp.testing.assert_allclose(got, onp.asarray(want), rtol=1e-5,
                                atol=1e-6, err_msg=name)


def test_np_split_boxed_and_differentiable():
    """List-RETURNING ops (split family) box every part as NDArray and
    work on the tape."""
    x = mx.np.array(onp.arange(12, dtype=onp.float32).reshape(3, 4))
    parts = mx.np.split(x, 2, 1)
    assert all(hasattr(p, "asnumpy") for p in parts)
    onp.testing.assert_allclose(parts[1].asnumpy(),
                                onp.arange(12).reshape(3, 4)[:, 2:])
    x.attach_grad()
    with autograd.record():
        a, b = mx.np.split(x, 2, 1)
        loss = (a * 2).sum() + (b * 3).sum()
    loss.backward()
    want = onp.concatenate([onp.full((3, 2), 2.0),
                            onp.full((3, 2), 3.0)], axis=1)
    onp.testing.assert_allclose(x.grad.asnumpy(), want)


def test_np_kwarg_array_args_unboxed():
    """Array-valued keyword args (indices=, condition=) are unboxed."""
    x = mx.np.array(onp.arange(12, dtype=onp.float32).reshape(3, 4))
    got = mx.np.take(x, indices=mx.np.array([0, 2]), axis=1)
    onp.testing.assert_allclose(
        got.asnumpy(), onp.arange(12).reshape(3, 4)[:, [0, 2]])


def test_np_kwarg_array_gradient():
    """Tracked kwarg arrays are ON the tape (np.average's weights= is
    differentiable) — including when ONLY the kwarg array is tracked."""
    x_np = onp.array([1.0, 2.0, 3.0, 4.0], onp.float32)
    w = mx.np.array(onp.full(4, 0.25, onp.float32))
    w.attach_grad()
    with autograd.record():
        out = mx.np.average(mx.np.array(x_np), weights=w)
        loss = out * out
    loss.backward()
    # d/dw_i of (sum(w x)/sum(w))^2 at uniform w: 2*avg*(x_i - avg)
    avg = x_np.mean()
    want = 2 * avg * (x_np - avg)
    onp.testing.assert_allclose(w.grad.asnumpy(), want, rtol=1e-5)


def test_npx_extension_breadth():
    """npx adapters over the registry ops (ref: the `_npx_*` family)."""
    from mxnet_tpu import npx
    x = mx.np.array(onp.arange(24, dtype=onp.float32).reshape(2, 3, 4))
    assert npx.batch_dot(
        x, mx.np.array(onp.ones((2, 4, 2), onp.float32))).shape == (2, 3, 2)
    onp.testing.assert_allclose(
        npx.gather_nd(x, mx.nd.array([[0, 1], [1, 2]])).asnumpy(),
        onp.arange(24).reshape(2, 3, 4)[[0, 1], [1, 2]])
    assert npx.reshape_like(
        x, mx.np.array(onp.zeros((6, 4)))).shape == (6, 4)
    assert npx.slice(x, begin=(0, 1), end=(2, 3)).shape == (2, 2, 4)
    masked = npx.sequence_mask(x, mx.nd.array([1, 2]),
                               use_sequence_length=True, axis=1).asnumpy()
    assert masked.shape == (2, 3, 4)
    assert (masked[0, 1:] == 0).all() and (masked[1, 2:] == 0).all()
    # the flag is authoritative: False passes data through unmasked
    onp.testing.assert_allclose(
        npx.sequence_mask(x, mx.nd.array([1, 2]),
                          use_sequence_length=False, axis=1).asnumpy(),
        x.asnumpy())
    # True without lengths must fail loudly, not silently skip masking
    with pytest.raises(Exception, match="sequence_length"):
        npx.sequence_mask(x, use_sequence_length=True, axis=1)
    onp.testing.assert_allclose(npx.arange_like(x, axis=1).asnumpy(),
                                [0, 1, 2])
    onp.testing.assert_allclose(
        npx.smooth_l1(mx.np.array(onp.array([0.5, 2.0],
                                            onp.float32))).asnumpy(),
        [0.125, 1.5])
    npx.waitall()


def test_np_concatenate_gradient_through_sequence_args():
    """Tape support for sequence-of-arrays signatures: gradients flow to
    every NDArray inside the list argument."""
    a = mx.np.array(onp.ones((2, 3), onp.float32))
    b = mx.np.array(onp.full((2, 3), 2.0, onp.float32))
    a.attach_grad()
    b.attach_grad()
    with autograd.record():
        out = mx.np.concatenate([a, b], axis=1)
        loss = (out * out).sum()
    loss.backward()
    onp.testing.assert_allclose(a.grad.asnumpy(), 2 * onp.ones((2, 3)))
    onp.testing.assert_allclose(b.grad.asnumpy(),
                                4 * onp.ones((2, 3)))


def test_np_zero_dim_and_broadcasting():
    """The semantics the reference built mx.np for: 0-d arrays, numpy
    broadcasting, integer dtypes."""
    s = mx.np.array(3.0)
    assert s.shape == ()
    out = mx.np.add(s, mx.np.ones((2, 3)))
    assert out.shape == (2, 3)
    m = mx.np.arange(6).reshape((3, 2)) if hasattr(
        mx.np.arange(6), "reshape") else None
    a = mx.np.arange(6)
    assert a.dtype == onp.int32 or a.dtype == onp.int64


def test_np_matmul_einsum():
    rng = onp.random.RandomState(1)
    a = rng.randn(3, 4).astype(onp.float32)
    b = rng.randn(4, 5).astype(onp.float32)
    got = mx.np.matmul(mx.np.array(a), mx.np.array(b)).asnumpy()
    onp.testing.assert_allclose(got, a @ b, rtol=1e-5)
    got2 = mx.np.einsum("ij,jk->ik", mx.np.array(a), mx.np.array(b))
    onp.testing.assert_allclose(got2.asnumpy(), a @ b, rtol=1e-5)


def test_np_autograd():
    a = mx.np.array([[1.0, 2.0], [3.0, 4.0]])
    a.attach_grad()
    with autograd.record():
        y = mx.np.sum(mx.np.exp(a) * 2.0)
    y.backward()
    onp.testing.assert_allclose(a.grad.asnumpy(),
                                2.0 * onp.exp([[1, 2], [3, 4]]), rtol=1e-5)


def test_np_linalg_fft_random():
    m = onp.eye(3, dtype=onp.float32) * 4.0
    inv = mx.np.linalg.inv(mx.np.array(m))
    onp.testing.assert_allclose(inv.asnumpy(), onp.linalg.inv(m), rtol=1e-5)
    x = mx.np.random.normal(size=(16,))
    assert x.shape == (16,)
    f = mx.np.fft.fft(mx.np.array(onp.ones(8, onp.float32)))
    assert f.shape == (8,)


def test_np_sort_where_unique():
    x = mx.np.array([3.0, 1.0, 2.0, 1.0])
    onp.testing.assert_allclose(mx.np.sort(x).asnumpy(), [1, 1, 2, 3])
    w = mx.np.where(x > 1.5, x, mx.np.zeros_like(x))
    onp.testing.assert_allclose(w.asnumpy(), [3, 0, 2, 0])


def test_npx_ops():
    x = mx.np.array([[1.0, 2.0, 3.0]])
    sm = mx.npx.softmax(x)
    onp.testing.assert_allclose(sm.asnumpy().sum(), 1.0, rtol=1e-6)
    assert mx.npx.relu(mx.np.array([-1.0, 2.0])).asnumpy().tolist() == \
        [0.0, 2.0]
    oh = mx.npx.one_hot(mx.np.array([0, 2]), 3)
    onp.testing.assert_allclose(oh.asnumpy(),
                                [[1, 0, 0], [0, 0, 1]])
    mx.npx.set_np()
    assert mx.npx.is_np_array()
    mx.npx.reset_np()
    assert not mx.npx.is_np_array()


def test_npx_fully_connected_and_norm():
    x = mx.np.array(onp.random.randn(2, 4).astype(onp.float32))
    w = mx.np.array(onp.random.randn(3, 4).astype(onp.float32))
    out = mx.npx.fully_connected(x, w, num_hidden=3)
    assert out.shape == (2, 3)
    g = mx.np.ones((4,))
    b = mx.np.zeros((4,))
    ln = mx.npx.layer_norm(x, g, b, axis=-1)
    onp.testing.assert_allclose(ln.asnumpy().mean(axis=-1), [0, 0],
                                atol=1e-6)


def test_np_round5_tail():
    """Round-5 numpy-namespace tail: set ops, stats, selection,
    float-representation helpers (all jnp-backed, NDArray-wrapped)."""
    np = mx.np
    a = np.array([[1.0, 2, 3], [2, 4, 7]])
    onp.testing.assert_allclose(np.cov(a).asnumpy(),
                                onp.cov([[1., 2, 3], [2, 4, 7]]), rtol=1e-6)
    onp.testing.assert_allclose(
        np.corrcoef(a).asnumpy(), onp.corrcoef([[1., 2, 3], [2, 4, 7]]),
        rtol=1e-6)
    assert sorted(np.union1d(np.array([1, 2, 3]),
                             np.array([2, 5])).asnumpy().tolist()) == \
        [1, 2, 3, 5]
    assert np.setdiff1d(np.array([1, 2, 3]),
                        np.array([2])).asnumpy().tolist() == [1, 3]
    assert np.isin(np.array([1, 2, 4]),
                   np.array([2, 4])).asnumpy().tolist() == \
        [False, True, True]
    out = np.select([np.array([True, False]), np.array([False, True])],
                    [np.array([1, 1]), np.array([2, 2])])
    assert out.asnumpy().tolist() == [1, 2]
    onp.testing.assert_allclose(
        np.unwrap(np.array([0.0, 3.2, 6.3])).asnumpy(),
        onp.unwrap([0.0, 3.2, 6.3]), rtol=1e-6)
    assert float(np.fmod(np.array([5.0]), np.array([3.0]))
                 .asnumpy()[0]) == 2.0
    assert float(np.nanmedian(np.array([1.0, float("nan"), 3.0]))
                 .asnumpy()) == 2.0
    assert float(np.logaddexp(np.array([0.0]),
                              np.array([0.0])).asnumpy()[0]) == \
        pytest.approx(onp.logaddexp(0.0, 0.0))
    # gradients flow through the wrapped functions (tape-aware)
    x = mx.np.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = np.logaddexp(x, x).sum()
    y.backward()
    assert x.grad.asnumpy().shape == (3,)


def test_np_callback_functions_compose_with_mx_np():
    """apply_along_axis/apply_over_axes/piecewise accept callbacks written
    against mx.np itself (boxed in, unboxed out — a raw wrapper would leak
    vmap tracers into NDArrays)."""
    np = mx.np
    a = np.array([[1.0, 2, 3], [4, 5, 6]])
    out = np.apply_along_axis(lambda v: np.sum(v), 1, a)
    assert out.asnumpy().tolist() == [6.0, 15.0]
    out2 = np.apply_over_axes(lambda arr, ax: np.sum(arr, axis=ax,
                                                     keepdims=True),
                              a, [0])
    assert out2.asnumpy().ravel().tolist() == [5.0, 7.0, 9.0]
    x = np.array([-2.0, -1.0, 1.0, 2.0])
    out3 = np.piecewise(x, [x < 0, x >= 0],
                        [lambda v: -v, lambda v: np.multiply(v, 10.0)])
    assert out3.asnumpy().tolist() == [2.0, 1.0, 10.0, 20.0]


def test_npx_round5_tail():
    """npx thin-adapter tail: activation/cast/erf/deconv/norms/nms/rnn."""
    npx, nd = mx.npx, mx.nd
    x = nd.array(onp.random.RandomState(0).randn(2, 3, 8, 8)
                 .astype(onp.float32))
    assert npx.activation(x, "relu").shape == x.shape
    assert npx.cast(x, "float16").dtype == onp.float16
    assert float(npx.erf(nd.array([0.0])).asnumpy()[0]) == 0.0
    assert abs(float(npx.erfinv(npx.erf(nd.array([0.5])))
                     .asnumpy()[0]) - 0.5) < 1e-5
    g = nd.array(onp.ones(3, onp.float32))
    b = nd.array(onp.zeros(3, onp.float32))
    gn = npx.group_norm(x, g, b, num_groups=3)
    assert gn.shape == x.shape
    assert abs(float(gn.asnumpy().mean())) < 1e-5     # normalized
    assert npx.instance_norm(x, g, b).shape == x.shape
    w = nd.array(onp.random.RandomState(1).randn(3, 2, 3, 3)
                 .astype(onp.float32) * 0.1)
    y = npx.deconvolution(x, w, kernel=(3, 3), num_filter=2)
    assert y.shape[1] == 2
    boxes = nd.array(onp.array(
        [[[0, 0.9, 0, 0, 10, 10], [1, 0.8, 1, 1, 11, 11]]], onp.float32))
    out = npx.box_nms(boxes, overlap_thresh=0.5)
    assert out.shape == boxes.shape


def test_npx_deconv_bias_and_varlen_rnn():
    """Review-pinned adapter contracts: an explicit deconv bias must be
    APPLIED (the op default is no_bias=True), and npx.rnn reaches the
    variable-length path."""
    npx, nd = mx.npx, mx.nd
    x = nd.array(onp.random.RandomState(0).randn(1, 3, 5, 5)
                 .astype(onp.float32))
    w = nd.array(onp.random.RandomState(1).randn(3, 2, 3, 3)
                 .astype(onp.float32) * 0.1)
    b = nd.array(onp.array([10.0, -10.0], onp.float32))
    y0 = npx.deconvolution(x, w, kernel=(3, 3), num_filter=2)
    yb = npx.deconvolution(x, w, b, kernel=(3, 3), num_filter=2)
    diff = (yb - y0).asnumpy()
    onp.testing.assert_allclose(diff[0, 0], 10.0, rtol=1e-5)
    onp.testing.assert_allclose(diff[0, 1], -10.0, rtol=1e-5)

    T, B, I, H = 4, 2, 3, 5
    data = nd.array(onp.random.RandomState(2).randn(T, B, I)
                    .astype(onp.float32))
    n_params = 4 * H * (I + H + 2)
    params = nd.array(onp.random.RandomState(3).randn(n_params)
                      .astype(onp.float32) * 0.1)
    state = nd.zeros((1, B, H))
    cell = nd.zeros((1, B, H))
    seq_len = nd.array(onp.array([2, 4], onp.int32))
    out = npx.rnn(data, params, state, cell, sequence_length=seq_len,
                  mode="lstm", state_size=H, num_layers=1)
    assert out.shape == (T, B, H)

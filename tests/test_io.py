"""Data & I/O tests (ref: tests/python/unittest/test_io.py,
test_recordio.py, test_gluon_data.py)."""
import struct

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import io, recordio
from mxnet_tpu.gluon.data import ArrayDataset, BatchSampler, DataLoader, \
    SequentialSampler, SimpleDataset
from mxnet_tpu.gluon.data.vision import transforms


# -- recordio ----------------------------------------------------------------
def test_recordio_roundtrip(tmp_path):
    path = str(tmp_path / "test.rec")
    w = recordio.MXRecordIO(path, "w")
    for i in range(5):
        w.write(f"record{i}".encode())
    w.close()
    r = recordio.MXRecordIO(path, "r")
    for i in range(5):
        assert r.read() == f"record{i}".encode()
    assert r.read() is None
    r.close()


def test_recordio_byte_layout(tmp_path):
    """Byte framing matches dmlc recordio.h: magic, lrec, payload, pad."""
    path = str(tmp_path / "layout.rec")
    w = recordio.MXRecordIO(path, "w")
    w.write(b"abcde")             # 5 bytes → 3 pad bytes
    w.close()
    raw = open(path, "rb").read()
    magic, lrec = struct.unpack("<II", raw[:8])
    assert magic == 0xced7230a
    assert lrec >> 29 == 0        # cflag whole
    assert lrec & ((1 << 29) - 1) == 5
    assert raw[8:13] == b"abcde"
    assert len(raw) == 16         # 8 header + 5 data + 3 pad


def test_recordio_magic_in_payload(tmp_path):
    """Payload containing the magic at 4B alignment must round-trip via
    the multi-part split (ref: RecordIOWriter::WriteRecord)."""
    payload = b"0123" + struct.pack("<I", 0xced7230a) + b"tail"
    path = str(tmp_path / "split.rec")
    w = recordio.MXRecordIO(path, "w")
    w.write(payload)
    w.close()
    r = recordio.MXRecordIO(path, "r")
    assert r.read() == payload
    r.close()


def test_indexed_recordio(tmp_path):
    path = str(tmp_path / "test.rec")
    idx_path = str(tmp_path / "test.idx")
    w = recordio.MXIndexedRecordIO(idx_path, path, "w")
    for i in range(10):
        w.write_idx(i, f"data{i}".encode())
    w.close()
    r = recordio.MXIndexedRecordIO(idx_path, path, "r")
    assert r.read_idx(7) == b"data7"
    assert r.read_idx(2) == b"data2"
    assert sorted(r.keys) == list(range(10))
    r.close()


def test_pack_unpack_header():
    h = recordio.IRHeader(0, 3.0, 42, 0)
    s = recordio.pack(h, b"payload")
    h2, payload = recordio.unpack(s)
    assert payload == b"payload"
    assert h2.label == 3.0 and h2.id == 42
    # array label
    h = recordio.IRHeader(0, np.array([1.0, 2.0], dtype=np.float32), 7, 0)
    h2, payload = recordio.unpack(recordio.pack(h, b"x"))
    np.testing.assert_allclose(h2.label, [1.0, 2.0])


def test_pack_img_unpack_img():
    img = (np.random.rand(32, 32, 3) * 255).astype(np.uint8)
    s = recordio.pack_img(recordio.IRHeader(0, 1.0, 0, 0), img,
                          quality=100, img_fmt=".png")
    header, img2 = recordio.unpack_img(s)
    assert header.label == 1.0
    np.testing.assert_array_equal(img, img2)


# -- io iterators ------------------------------------------------------------
def test_ndarray_iter_basic():
    data = np.arange(40).reshape(10, 4).astype(np.float32)
    label = np.arange(10).astype(np.float32)
    it = io.NDArrayIter(data, label, batch_size=3, last_batch_handle="pad")
    batches = list(it)
    assert len(batches) == 4
    assert batches[0].data[0].shape == (3, 4)
    assert batches[-1].pad == 2
    it.reset()
    assert len(list(it)) == 4


def test_ndarray_iter_discard():
    data = np.zeros((10, 2), dtype=np.float32)
    it = io.NDArrayIter(data, None, batch_size=3,
                        last_batch_handle="discard")
    assert len(list(it)) == 3


def test_ndarray_iter_shuffle_covers_all():
    data = np.arange(8).reshape(8, 1).astype(np.float32)
    it = io.NDArrayIter(data, np.arange(8), batch_size=4, shuffle=True)
    seen = []
    for b in it:
        seen.extend(b.label[0].asnumpy().tolist())
    assert sorted(seen) == list(range(8))


def test_csv_iter(tmp_path):
    data_csv = str(tmp_path / "d.csv")
    np.savetxt(data_csv, np.arange(12).reshape(6, 2), delimiter=",")
    it = io.CSVIter(data_csv=data_csv, data_shape=(2,), batch_size=2)
    batches = list(it)
    assert len(batches) == 3
    assert batches[0].data[0].shape == (2, 2)


def test_image_record_iter(tmp_path):
    """Pack images with the reference tooling path, read with
    ImageRecordIter."""
    rec = str(tmp_path / "imgs.rec")
    idx = str(tmp_path / "imgs.idx")
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    for i in range(6):
        img = (np.random.rand(40, 40, 3) * 255).astype(np.uint8)
        w.write_idx(i, recordio.pack_img(
            recordio.IRHeader(0, float(i % 3), i, 0), img, img_fmt=".png"))
    w.close()
    it = io.ImageRecordIter(path_imgrec=rec, path_imgidx=idx,
                            data_shape=(3, 32, 32), batch_size=4,
                            shuffle=True, rand_crop=True, rand_mirror=True)
    batch = it.next()
    assert batch.data[0].shape == (4, 3, 32, 32)
    assert batch.label[0].shape == (4,)


def test_image_record_iter_threaded_matches_serial(tmp_path):
    """preprocess_threads must change throughput, never the stream: the
    pooled decode path yields identical batches in identical order to the
    serial path (deterministic per-record augmentation seeding), for any
    pool size, across reset()."""
    rec = str(tmp_path / "imgs.rec")
    idx = str(tmp_path / "imgs.idx")
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    rng = np.random.RandomState(7)
    for i in range(13):
        img = (rng.rand(48, 40, 3) * 255).astype(np.uint8)
        w.write_idx(i, recordio.pack_img(
            recordio.IRHeader(0, float(i), i, 0), img, img_fmt=".png"))
    w.close()

    def batches(threads):
        it = io.ImageRecordIter(path_imgrec=rec, path_imgidx=idx,
                                data_shape=(3, 32, 32), batch_size=4,
                                shuffle=True, rand_crop=True,
                                rand_mirror=True, seed=3,
                                preprocess_threads=threads)
        out = [(b.data[0].asnumpy(), b.label[0].asnumpy()) for b in it]
        it.reset()       # second epoch exercises pending-future cleanup
        out += [(b.data[0].asnumpy(), b.label[0].asnumpy()) for b in it]
        return out

    serial = batches(1)
    for threads in (2, 5):
        pooled = batches(threads)
        assert len(pooled) == len(serial)
        for (da, la), (db, lb) in zip(serial, pooled):
            np.testing.assert_array_equal(la, lb)
            np.testing.assert_array_equal(da, db)


def test_image_det_record_iter_mirror_flips_boxes(tmp_path):
    """Detection mirror must move the BOXES with the image (ref:
    src/io/image_det_aug_default.cc): a bright patch on the left with a
    box over it stays covered by its box after a random flip."""
    rec = str(tmp_path / "det.rec")
    idx = str(tmp_path / "det.idx")
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    n = 12
    for i in range(n):
        # size-mismatched pack (48x40): detection resizes the FULL frame
        # to data_shape — normalized boxes stay valid (a center-crop
        # would silently invalidate them)
        img = np.zeros((48, 40, 3), np.uint8)
        img[12:36, 2:12] = 255         # bright patch on the LEFT
        # det label: [header_width=2, obj_width=5, cls, x0, y0, x1, y1]
        label = [2, 5, 0.0, 2 / 40, 12 / 48, 12 / 40, 36 / 48]
        w.write_idx(i, recordio.pack_img(
            recordio.IRHeader(0, label, i, 0), img, img_fmt=".png"))
    w.close()
    it = io.ImageDetRecordIter(
        path_imgrec=rec, path_imgidx=idx, data_shape=(3, 32, 32),
        batch_size=n, rand_mirror=True, seed=5, label_pad_width=7)
    batch = it.next()
    data = batch.data[0].asnumpy()
    labels = batch.label[0].asnumpy()
    flipped_any = False
    for img, lab in zip(data, labels):
        x0, x1 = lab[3], lab[5]
        assert 0.0 <= x0 < x1 <= 1.0
        # the bright patch's horizontal center must sit inside the box
        cols = np.where(img.sum(axis=(0, 1)) > 0)[0]
        cx = cols.mean() / 32.0
        assert x0 <= cx <= x1, (x0, cx, x1)
        if x0 > 0.5:
            flipped_any = True
    assert flipped_any, "seeded mirror should flip some of 12 images"
    # rand_crop is rejected for detection packs (boxes would go stale)
    import pytest as _pytest
    with _pytest.raises(Exception, match="rand_crop"):
        io.ImageDetRecordIter(path_imgrec=rec, path_imgidx=idx,
                              data_shape=(3, 32, 32), batch_size=2,
                              rand_crop=True)


def test_prefetching_iter():
    data = np.random.randn(20, 3).astype(np.float32)
    inner = io.NDArrayIter(data, np.arange(20), batch_size=5)
    it = io.PrefetchingIter(inner)
    assert len(list(it)) == 4
    it.reset()
    assert len(list(it)) == 4


# -- gluon.data --------------------------------------------------------------
def test_array_dataset_and_loader():
    x = np.random.randn(10, 3).astype(np.float32)
    y = np.arange(10).astype(np.int32)
    ds = ArrayDataset(x, y)
    assert len(ds) == 10
    loader = DataLoader(ds, batch_size=4, shuffle=False)
    batches = list(loader)
    assert len(batches) == 3
    xb, yb = batches[0]
    assert xb.shape == (4, 3)
    np.testing.assert_allclose(xb.asnumpy(), x[:4], rtol=1e-6)


def test_dataloader_workers_match_serial():
    x = np.arange(24).reshape(12, 2).astype(np.float32)
    ds = ArrayDataset(x)
    serial = [b.asnumpy() for b in DataLoader(ds, 4, num_workers=0)]
    threaded = [b.asnumpy() for b in DataLoader(ds, 4, num_workers=3)]
    for a, b in zip(serial, threaded):
        np.testing.assert_array_equal(a, b)


def test_dataset_transform_and_shard():
    ds = SimpleDataset(list(range(10)))
    t = ds.transform(lambda x: x * 2)
    assert t[3] == 6
    sh = ds.shard(3, 0)
    assert len(sh) == 4   # 10 = 4+3+3
    assert len(ds.shard(3, 2)) == 3


def test_batch_sampler_rollover():
    bs = BatchSampler(SequentialSampler(10), 4, "rollover")
    first = list(bs)
    assert [len(b) for b in first] == [4, 4]
    second = list(bs)
    assert len(second[0]) == 4  # 2 rolled + 2 new


def test_transforms_compose():
    img = mx.nd.array((np.random.rand(40, 30, 3) * 255).astype(np.uint8))
    fn = transforms.Compose([
        transforms.Resize(36),
        transforms.CenterCrop(32),
        transforms.ToTensor(),
        transforms.Normalize(mean=(0.5, 0.5, 0.5), std=(0.2, 0.2, 0.2)),
    ])
    out = fn(img)
    assert out.shape == (3, 32, 32)
    assert out.dtype == np.float32


# -- mx.image ---------------------------------------------------------------
def test_image_imdecode_resize():
    import cv2
    img = (np.random.rand(48, 64, 3) * 255).astype(np.uint8)
    ok, buf = cv2.imencode(".png", img)
    arr = mx.image.imdecode(buf.tobytes())
    assert arr.shape == (48, 64, 3)
    small = mx.image.imresize(arr, 32, 24)
    assert small.shape == (24, 32, 3)
    short = mx.image.resize_short(arr, 32)
    assert min(short.shape[:2]) == 32


def test_image_augmenter_pipeline():
    auglist = mx.image.CreateAugmenter((3, 32, 32), resize=36,
                                       rand_crop=True, rand_mirror=True,
                                       mean=True, std=True)
    img = mx.nd.array((np.random.rand(50, 60, 3) * 255).astype(np.uint8))
    for aug in auglist:
        img = aug(img)
    assert img.shape == (32, 32, 3)
    assert img.dtype == np.float32


def test_image_jitter_augmenters():
    """Round-4: full CreateAugmenter parameter parity (ref: image.py —
    color jitter, hue, PCA lighting, random gray, random-sized crop)."""
    np.random.seed(0)
    auglist = mx.image.CreateAugmenter(
        (3, 24, 24), resize=28, rand_resize=True, rand_mirror=True,
        brightness=0.3, contrast=0.3, saturation=0.3, hue=0.1,
        pca_noise=0.1, rand_gray=0.5, mean=True, std=True)
    kinds = {a.__class__.__name__ for a in auglist}
    assert {"RandomSizedCropAug", "ColorJitterAug", "HueJitterAug",
            "LightingAug", "RandomGrayAug"} <= kinds
    img = mx.nd.array((np.random.rand(40, 52, 3) * 255).astype(np.uint8))
    for aug in auglist:
        img = aug(img)
    assert img.shape == (24, 24, 3) and img.dtype == np.float32
    assert np.isfinite(img.asnumpy()).all()
    # jitters keep gray images gray and preserve value ranges loosely
    gray_in = mx.nd.array(np.full((8, 8, 3), 128.0, np.float32))
    hue = mx.image.HueJitterAug(0.2)(gray_in).asnumpy()
    np.testing.assert_allclose(hue, 128.0, rtol=0.05)
    sat = mx.image.SaturationJitterAug(0.9)(gray_in).asnumpy()
    np.testing.assert_allclose(sat, 128.0, rtol=1e-4)


def test_image_iter_lst_roundtrip(tmp_path):
    """ImageIter reads a .lst + path_root layout, runs the aug pipeline,
    yields NCHW batches with pad semantics (ref: image.py ImageIter)."""
    import cv2
    root = tmp_path / "imgs"
    root.mkdir()
    rows = []
    for i in range(5):
        img = np.full((40, 40, 3), i * 10, np.uint8)
        cv2.imwrite(str(root / f"im{i}.png"), img)
        rows.append(f"{i}\t{float(i % 3)}\tim{i}.png")
    lst = tmp_path / "data.lst"
    lst.write_text("\n".join(rows) + "\n")
    it = mx.image.ImageIter(
        batch_size=2, data_shape=(3, 32, 32),
        path_imglist=str(lst), path_root=str(root),
        aug_list=mx.image.CreateAugmenter((3, 32, 32)))
    batches = list(it)
    assert len(batches) == 3
    assert batches[0].data[0].shape == (2, 3, 32, 32)
    assert batches[-1].pad == 1                 # 5 images, batch 2
    labels = np.concatenate([b.label[0].asnumpy() for b in batches])
    assert labels[:5].tolist() == [0.0, 1.0, 2.0, 0.0, 1.0]
    it.reset()
    assert len(list(it)) == 3                   # reset() restarts cleanly

    # last_batch_handle semantics (ref: image.py ImageIter)
    def make(handle):
        return mx.image.ImageIter(
            batch_size=2, data_shape=(3, 32, 32),
            path_imglist=str(lst), path_root=str(root),
            aug_list=mx.image.CreateAugmenter((3, 32, 32)),
            last_batch_handle=handle)
    assert len(list(make("discard"))) == 2      # partial batch dropped
    ro = make("roll_over")
    assert len(list(ro)) == 2                   # tail carried, not emitted
    ro.reset()
    assert len(list(ro)) == 3                   # 1 carried + 5 = 3 batches
    with pytest.raises(mx.base.MXNetError):
        mx.image.ImageIter(batch_size=2, data_shape=(3, 32, 32),
                           path_imglist=str(lst), path_root=str(root),
                           rand_crop=True)      # unknown kwarg must raise


# -- distributed read sharding (num_parts/part_index; VERDICT r4 Missing #1;
# ref: src/io/iter_image_recordio_2.cc kwargs over dmlc InputSplit) --------

def _coverage(parts):
    """Assert the per-part label streams form a disjoint, exhaustive
    partition; returns the union."""
    seen = []
    for p in parts:
        assert not (set(seen) & set(p)), "parts overlap"
        seen.extend(p)
    return sorted(seen)


def test_ndarray_iter_num_parts():
    data = np.arange(20, dtype=np.float32).reshape(20, 1)
    parts = []
    for r in range(3):
        it = io.NDArrayIter(data, data[:, 0], batch_size=2,
                            last_batch_handle="discard",
                            num_parts=3, part_index=r)
        parts.append([float(v) for b in it for v in b.label[0].asnumpy()])
    # 20 rows split 7+7+6 contiguously; discard trims each part to even
    assert parts[0] == [float(i) for i in range(0, 6)]
    assert parts[1] == [float(i) for i in range(7, 13)]
    assert parts[2] == [float(i) for i in range(14, 20)]


def test_image_record_iter_num_parts_indexed(tmp_path):
    rec, idx = str(tmp_path / "i.rec"), str(tmp_path / "i.idx")
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    n = 11
    for i in range(n):
        img = np.full((32, 32, 3), i, np.uint8)
        w.write_idx(i, recordio.pack_img(
            recordio.IRHeader(0, float(i), i, 0), img, img_fmt=".png"))
    w.close()
    parts = []
    for r in range(4):
        it = io.ImageRecordIter(path_imgrec=rec, path_imgidx=idx,
                                data_shape=(3, 32, 32), batch_size=1,
                                num_parts=4, part_index=r)
        labels = []
        try:
            while True:
                labels.append(float(it.next().label[0].asnumpy()[0]))
        except StopIteration:
            pass
        parts.append(labels)
    assert _coverage(parts) == [float(i) for i in range(n)]
    assert sorted(len(p) for p in parts) == [2, 3, 3, 3]


def test_image_record_iter_num_parts_sequential(tmp_path):
    # un-indexed pack: round-robin stream split, still disjoint+exhaustive
    rec = str(tmp_path / "s.rec")
    w = recordio.MXRecordIO(rec, "w")
    n = 10
    for i in range(n):
        img = np.full((32, 32, 3), i, np.uint8)
        w.write(recordio.pack_img(
            recordio.IRHeader(0, float(i), i, 0), img, img_fmt=".png"))
    w.close()
    parts = []
    for r in range(3):
        it = io.ImageRecordIter(path_imgrec=rec, data_shape=(3, 32, 32),
                                batch_size=1, num_parts=3, part_index=r)
        labels = []
        try:
            while True:
                labels.append(float(it.next().label[0].asnumpy()[0]))
        except StopIteration:
            pass
        parts.append(labels)
    assert _coverage(parts) == [float(i) for i in range(n)]


def test_csv_mnist_libsvm_iter_num_parts(tmp_path):
    # CSVIter
    csvf = tmp_path / "d.csv"
    csvf.write_text("\n".join(f"{i},{i}" for i in range(9)) + "\n")
    parts = []
    for r in range(2):
        it = io.CSVIter(data_csv=str(csvf), data_shape=(2,), batch_size=1,
                        round_batch=False, num_parts=2, part_index=r)
        parts.append([float(b.data[0].asnumpy()[0, 0]) for b in it])
    assert _coverage(parts) == [float(i) for i in range(9)]

    # LibSVMIter
    svmf = tmp_path / "d.svm"
    svmf.write_text("\n".join(f"{i} 0:{i}" for i in range(8)) + "\n")
    parts = []
    for r in range(2):
        it = io.LibSVMIter(data_libsvm=str(svmf), data_shape=(4,),
                           batch_size=1, num_parts=2, part_index=r)
        labels = []
        try:
            while True:
                labels.append(float(it.next().label[0].asnumpy()[0]))
        except StopIteration:
            pass
        parts.append(labels)
    assert _coverage(parts) == [float(i) for i in range(8)]

    # env wiring: MXTPU_NUM_PROC/MXTPU_PROC_ID shard with no kwargs
    import os
    old = {k: os.environ.get(k) for k in ("MXTPU_NUM_PROC", "MXTPU_PROC_ID")}
    try:
        os.environ["MXTPU_NUM_PROC"] = "2"
        os.environ["MXTPU_PROC_ID"] = "1"
        it = io.LibSVMIter(data_libsvm=str(svmf), data_shape=(4,),
                           batch_size=1)
        first = float(it.next().label[0].asnumpy()[0])
        assert first == 4.0    # second contiguous half starts at row 4
    finally:
        for k, v in old.items():
            os.environ.pop(k, None) if v is None else \
                os.environ.__setitem__(k, v)

    with pytest.raises(mx.base.MXNetError):
        io.NDArrayIter(np.zeros((4, 1)), num_parts=2, part_index=5)


def test_split_sampler():
    from mxnet_tpu.gluon.data import SplitSampler
    # disjoint + exhaustive, shared per-epoch permutation across ranks
    n = 23
    samplers = [SplitSampler(n, num_parts=4, part_index=r, shuffle=True,
                             seed=5) for r in range(4)]
    epoch1 = [list(s) for s in samplers]
    assert sorted(x for part in epoch1 for x in part) == list(range(n))
    assert sum(len(s) for s in samplers) == n
    # without set_epoch the order REPEATS (consistent across ranks) —
    # a rank-asymmetric extra sweep can no longer desync the shared
    # permutation (ADVICE r5: __iter__ must not auto-advance the epoch)
    assert [list(s) for s in samplers] == epoch1
    # an asymmetric extra iteration on one rank leaves the partition
    # intact for the next pinned epoch
    list(samplers[0])
    # explicit set_epoch reshuffles (and stays a partition)
    for s in samplers:
        s.set_epoch(1)
    epoch2 = [list(s) for s in samplers]
    assert sorted(x for part in epoch2 for x in part) == list(range(n))
    assert epoch1 != epoch2
    # it drives a DataLoader
    from mxnet_tpu.gluon.data import ArrayDataset, DataLoader
    ds = ArrayDataset(np.arange(n, dtype=np.float32))
    loader = DataLoader(ds, batch_size=4,
                        sampler=SplitSampler(n, num_parts=2, part_index=0))
    got = np.concatenate([np.asarray(b.asnumpy()).ravel() for b in loader])
    assert sorted(got.tolist()) == [float(i) for i in range(12)]

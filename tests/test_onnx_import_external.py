"""ONNX import of an EXTERNALLY-authored model (VERDICT r4 Missing #5 /
Next #8): tests/data/bert_tiny_hf.onnx is a HuggingFace ``BertModel``
(2 layers, hidden 32, 4 heads) exported by torch.onnx (TorchScript
exporter, opset 14) — separate Q/K/V projections, decomposed LayerNorm
(ReduceMean/Sub/Pow/Sqrt/Div), Erf-based GELU, Where/Equal/Expand/
ConstantOfShape attention-mask plumbing: none of it shaped like our own
exporter's output. The reference's deployment-facing import path is
python/mxnet/contrib/onnx/onnx2mx/import_model.py [H]."""
import os

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu.contrib import onnx as mxonnx

DATA = os.path.join(os.path.dirname(__file__), "data")
MODEL = os.path.join(DATA, "bert_tiny_hf.onnx")
REF = os.path.join(DATA, "bert_tiny_hf_ref.npz")


def _feeds(arg, ids, mask):
    feeds = {k: v for k, v in arg.items()}
    feeds["input_ids"] = mx.nd.array(ids)
    feeds["attention_mask"] = mx.nd.array(mask.astype(np.float32))
    return feeds


def test_import_external_bert_matches_torch_logits():
    ref = np.load(REF)
    sym, arg, aux = mxonnx.import_model(MODEL)
    assert not aux
    # only the true graph inputs remain unbound
    unbound = [a for a in sym.list_arguments() if a not in arg]
    assert sorted(unbound) == ["attention_mask", "input_ids"]
    outs = sym.eval(**_feeds(arg, ref["ids"], ref["mask"]))
    hidden, pooler = outs[0].asnumpy(), outs[1].asnumpy()
    # VERDICT bar: 1e-3; actual agreement is ~5e-7
    np.testing.assert_allclose(hidden, ref["hidden"], atol=1e-3)
    np.testing.assert_allclose(pooler, ref["pooler"], atol=1e-3)
    assert np.abs(hidden - ref["hidden"]).max() < 1e-5


def test_import_external_bert_respects_mask():
    # padding positions must not change unmasked outputs materially vs a
    # recomputation with a different pad region value
    ref = np.load(REF)
    sym, arg, _ = mxonnx.import_model(MODEL)
    ids = ref["ids"].copy()
    mask = ref["mask"].copy()
    mask[:, -3:] = 0                       # pad out the last 3 positions
    out_a = sym.eval(**_feeds(arg, ids, mask))[0].asnumpy()
    ids2 = ids.copy()
    ids2[:, -3:] = 1                       # different tokens under the pad
    out_b = sym.eval(**_feeds(arg, ids2, mask))[0].asnumpy()
    # content tokens see only masked attention, but their own embeddings
    # at padded slots differ — compare the UNPADDED region only
    np.testing.assert_allclose(out_a[:, :-3], out_b[:, :-3],
                               rtol=1e-4, atol=1e-4)


def test_constant_folding_unit():
    from mxnet_tpu.contrib.onnx.onnx2mx import _fold_numpy
    assert _fold_numpy("Where",
                       [np.array([True, False]), np.array([1.0, 1.0]),
                        np.array([2.0, 2.0])], {}).tolist() == [1.0, 2.0]
    out = _fold_numpy("ConstantOfShape", [np.array([2, 3])],
                      {"value": np.array([7.0], np.float32)})
    assert out.shape == (2, 3) and float(out[0, 0]) == 7.0
    out = _fold_numpy("Expand", [np.zeros((1, 4)), np.array([3, 1])], {})
    assert out.shape == (3, 4)
    assert _fold_numpy("Div", [np.array([7]), np.array([2])],
                       {}).dtype == np.array([7]).dtype


def test_import_to_gluon_external():
    ref = np.load(REF)
    block = mxonnx.import_to_gluon(MODEL)
    outs = block(mx.nd.array(ref["ids"]),
                 mx.nd.array(ref["mask"].astype(np.float32)))
    hidden = (outs[0] if isinstance(outs, (list, tuple))
              else outs).asnumpy()
    np.testing.assert_allclose(hidden, ref["hidden"], atol=1e-3)

"""Elastic multi-host training (docs/elastic.md): cohort liveness +
deadline barriers, survivor-safe collectives, survivor-mesh rebuild,
resharded restore, and the elastic driver — chaos-proven by killing a
real rank mid-run with ``testing.faults.sigterm``.

The ``*smoke*`` tests are CI's tier-0.5 elastic chaos smoke
(ci/run_tests.sh). The multi-process chaos test is the acceptance
proof: 2 worker processes (no jax.distributed — each is its own JAX
world coordinated only through the cohort ledger), rank 1 SIGTERMed
mid-run, rank 0 detects within the heartbeat deadline, resizes to a
1-member cohort, restores the newest committed checkpoint RESHARDED
from 2 shard files onto its survivor mesh, and trains to completion —
with ``rank_lost``/``cohort_resize``/``reshard_restore`` journal
records correlated under one trace and the restored tree bit-exact
against the committed step."""
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import elastic, gluon, parallel
from mxnet_tpu.base import MXNetError
from mxnet_tpu.diagnostics import journal
from mxnet_tpu.elastic import report as elastic_report_mod
from mxnet_tpu.parallel import _ckpt
from mxnet_tpu.resilience import commit as rcommit
from mxnet_tpu.testing import faults

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

FAST = dict(heartbeat_s=0.1, deadline_s=0.6, barrier_s=10.0, poll_s=0.01)


def _cfg(**over):
    return elastic.CohortConfig(**{**FAST, **over})


def _read_journal(path):
    with open(path, encoding="utf-8") as f:
        return [json.loads(line) for line in f if line.strip()]


@pytest.fixture
def jfile(tmp_path):
    jf = str(tmp_path / "journal.jsonl")
    journal.reset_journal(jf)
    try:
        yield jf
    finally:
        journal.reset_journal()


def _pair(tmp_path):
    root = str(tmp_path / "cohort")
    c0 = elastic.Cohort(root, 0, _cfg()).start()
    c1 = elastic.Cohort(root, 1, _cfg()).start()
    t = threading.Thread(target=lambda: c1.form(2))
    t.start()
    members = c0.form(2)
    t.join()
    assert members == [0, 1]
    return c0, c1


# -- membership: liveness, barriers, epochs ---------------------------------

def test_smoke_rank_loss_detected_within_deadline(tmp_path, jfile):
    """A resigned rank is detected lost, the barrier raises a structured
    RankLost (never hangs), and the leader's resize publishes the
    survivor epoch."""
    c0, c1 = _pair(tmp_path)
    try:
        t = threading.Thread(target=lambda: c1.barrier("warm"))
        t.start()
        c0.barrier("warm")
        t.join()
        c1.stop(resign=True)
        t0 = time.monotonic()
        with pytest.raises(elastic.RankLost) as ei:
            c0.barrier("doomed")
        detect_s = time.monotonic() - t0
        assert ei.value.lost == [1] and ei.value.survivors == [0]
        # detection bounded by the liveness deadline, not the barrier's
        assert detect_s < FAST["barrier_s"]
        members = c0.resize(ei.value.lost)
        assert members == [0] and c0.epoch == 1
        recs = _read_journal(jfile)
        rs = [r for r in recs if r["kind"] == "cohort_resize"]
        assert rs and rs[-1]["members"] == [0] and rs[-1]["lost"] == [1]
    finally:
        c0.stop()
        c1.stop()


def test_barrier_timeout_on_live_straggler(tmp_path):
    """A member that is alive but never arrives is a BarrierTimeout (a
    stall verdict), NOT a RankLost (a death verdict)."""
    c0, c1 = _pair(tmp_path)
    try:
        with pytest.raises(elastic.BarrierTimeout) as ei:
            c0.barrier("lonely", deadline_s=0.5)
        assert ei.value.waiting_for == [1]
    finally:
        c0.stop()
        c1.stop()


def test_barrier_tag_reuse_needs_fresh_arrivals(tmp_path):
    """The n-th barrier at a tag can't be satisfied by the (n-1)-th's
    files: reuse within an epoch is sequence-numbered."""
    c0, c1 = _pair(tmp_path)
    try:
        t = threading.Thread(target=lambda: c1.barrier("x"))
        t.start()
        c0.barrier("x")
        t.join()
        # second use of the same tag: rank 1 never arrives
        with pytest.raises(elastic.BarrierTimeout):
            c0.barrier("x", deadline_s=0.5)
    finally:
        c0.stop()
        c1.stop()


def test_scale_up_join_admitted_at_resize(tmp_path, jfile):
    """A new rank joins: request + heartbeat, admitted by the leader's
    next resize; both sides converge on the same member list."""
    c0, c1 = _pair(tmp_path)
    c2 = elastic.Cohort(str(tmp_path / "cohort"), 2, _cfg())
    try:
        got = {}
        t = threading.Thread(target=lambda: got.update(m=c2.join()))
        t.start()
        time.sleep(0.3)           # join request + heartbeat land
        t1 = threading.Thread(target=lambda: got.update(m1=c1.resize()))
        t1.start()
        members = c0.resize()
        t1.join()
        t.join()
        assert members == [0, 1, 2] and got["m"] == [0, 1, 2]
        assert got["m1"] == [0, 1, 2]
        assert c0.epoch == 1
        recs = _read_journal(jfile)
        joins = [r for r in recs if r["kind"] == "cohort_join"]
        assert joins and joins[-1]["rank"] == 2
    finally:
        for c in (c0, c1, c2):
            c.stop()


def test_config_rejects_deadline_inside_heartbeat():
    with pytest.raises(MXNetError):
        elastic.CohortConfig(heartbeat_s=2.0, deadline_s=1.0)


# -- survivor-safe collectives ----------------------------------------------

def test_collective_allreduce_and_broadcast(tmp_path):
    c0, c1 = _pair(tmp_path)
    try:
        out = {}
        t = threading.Thread(target=lambda: out.update(
            r=elastic.allreduce_mean(c1, "g", {"w": np.full(4, 2.0),
                                               "b": np.float32(1.0)})))
        t.start()
        mine = elastic.allreduce_mean(c0, "g", {"w": np.full(4, 4.0),
                                                "b": np.float32(3.0)})
        t.join()
        np.testing.assert_array_equal(mine["w"], np.full(4, 3.0))
        np.testing.assert_array_equal(out["r"]["w"], np.full(4, 3.0))
        assert float(mine["b"]) == float(out["r"]["b"]) == 2.0
        t = threading.Thread(target=lambda: out.update(
            j=elastic.broadcast_json(c1, "pick", None)))
        t.start()
        elastic.broadcast_json(c0, "pick", {"step": 42})
        t.join()
        assert out["j"] == {"step": 42}
    finally:
        c0.stop()
        c1.stop()


def test_collective_dead_member_raises_rank_lost(tmp_path):
    c0, c1 = _pair(tmp_path)
    try:
        c1.stop(resign=True)
        time.sleep(FAST["deadline_s"] + 0.3)
        with pytest.raises(elastic.RankLost):
            elastic.allreduce_mean(c0, "g", {"w": np.ones(2)})
    finally:
        c0.stop()
        c1.stop()


# -- resharded restore -------------------------------------------------------

def _make_net():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(16, activation="relu"),
            gluon.nn.BatchNorm(),
            gluon.nn.Dense(8))
    net.initialize()
    return net


def _make_trainer(mesh, optimizer="adam"):
    params = {"adam": {"learning_rate": 1e-3},
              "sgd": {"learning_rate": 0.1, "momentum": 0.9}}[optimizer]
    return parallel.ShardedTrainer(
        _make_net(), gluon.loss.SoftmaxCrossEntropyLoss(), optimizer,
        optimizer_params=params, mesh=mesh,
        param_rules=[(r"2\.weight",
                      parallel.PartitionSpec("model", None))])


def _snapshot(tr):
    snap = {}
    for p in tr._trainable:
        snap["arg:" + tr._struct_name(p)] = np.asarray(p._data[0]._data)
    for p in tr._aux:
        snap["aux:" + tr._struct_name(p)] = np.asarray(p._data[0]._data)
    for p, st in zip(tr._trainable, tr._states):
        for j, s in enumerate(st):
            snap[f"state:{tr._struct_name(p)}:{j}"] = np.asarray(s)
    return snap


def _batch(seed=0, batch=8):
    rng = np.random.RandomState(seed)
    return (rng.randn(batch, 12).astype(np.float32),
            rng.randint(0, 8, (batch,)))


def _committed_entries(root, step):
    prefix = os.path.join(rcommit.step_dir(root, step),
                          _ckpt.CKPT_BASENAME)
    _, params = elastic.read_global_entries(f"{prefix}.params")
    _, states = elastic.read_global_entries(f"{prefix}.states")
    return {**params, **states}


def test_smoke_reshard_scale_down_and_up_bit_exact(tmp_path):
    """The acceptance bit-exactness pair: a 2x2-mesh checkpoint restores
    bit-exactly onto a 1-device mesh (scale-down) AND onto a 4x2 mesh
    (scale-up), and both trainers keep training."""
    import jax
    D = jax.devices()
    root = str(tmp_path / "ckpt")
    x, y = _batch()
    mx.random.seed(3)
    tr_a = _make_trainer(parallel.make_mesh({"data": 2, "model": 2},
                                            devices=D[:4]))
    for _ in range(3):
        tr_a.step(x, y)
    step = tr_a.checkpoint(root, per_shard=True)
    want = _snapshot(tr_a)
    # the committed files themselves assemble to the live tree
    assert elastic.driver.np_tree_equal(want,
                                        _committed_entries(root, step))

    mx.random.seed(77)      # restore must not depend on the ambient seed
    tr_down = _make_trainer(parallel.make_mesh({"data": 1},
                                               devices=D[:1]))
    tr_down.prepare(x)
    assert tr_down.restore_resharded(root) == step
    assert elastic.driver.np_tree_equal(want, _snapshot(tr_down))

    mx.random.seed(99)
    tr_up = _make_trainer(parallel.make_mesh({"data": 4, "model": 2}))
    tr_up.prepare(x)
    assert tr_up.restore_resharded(root) == step
    assert elastic.driver.np_tree_equal(want, _snapshot(tr_up))

    # both topologies resume training from the restored state and agree
    # (2-device data splits vs 8-device: same global math)
    la = tr_down.step(x, y).asnumpy()
    lb = tr_up.step(x, y).asnumpy()
    np.testing.assert_allclose(la, lb, rtol=2e-5, atol=2e-5)


def test_reshard_refuses_incomplete_and_overlapping_sets(tmp_path):
    # missing shard file
    root = str(tmp_path / "ck1")
    x, y = _batch()
    tr = _make_trainer(parallel.current_mesh())
    tr.step(x, y)
    step = tr.checkpoint(root, per_shard=True)
    prefix = os.path.join(rcommit.step_dir(root, step),
                          _ckpt.CKPT_BASENAME)
    os.unlink(f"{prefix}.params.shard0")
    with pytest.raises(MXNetError, match="incomplete"):
        elastic.read_global_entries(f"{prefix}.params")
    # coverage proof: a missing piece is named, not zero-filled
    with pytest.raises(MXNetError, match="pieces cover"):
        elastic.assemble_entries(
            {"w": {"0:2,0:4": np.zeros((2, 4), np.float32)}
             | {"4:8,0:4": np.zeros((4, 8 - 4), np.float32).reshape(4, 4)}})
    # piece shaped differently than its index says
    with pytest.raises(MXNetError, match="torn or mislabeled"):
        elastic.assemble_entries({"w": {"0:4,0:4": np.zeros((2, 4))}})


def test_reshard_dtype_and_shape_guards():
    with pytest.raises(MXNetError, match="master_dtype|architecture"):
        import jax.numpy as jnp
        elastic.place_global("w", jnp.zeros((4, 4), jnp.float32),
                             np.zeros((4, 4), np.float64))


def test_rebuild_mesh_in_place_continues_training(tmp_path, jfile):
    """Survivor-mesh rebuild: re-place state onto a smaller mesh, drop
    compiled programs (journaled elastic_retrace), keep training with
    identical math."""
    import jax
    D = jax.devices()
    x, y = _batch()
    mx.random.seed(5)
    tr = _make_trainer(parallel.make_mesh({"data": 4, "model": 2}))
    tr.step(x, y)
    before = _snapshot(tr)
    tr.rebuild_mesh(parallel.make_mesh({"data": 2}, devices=D[:2]))
    assert elastic.driver.np_tree_equal(before, _snapshot(tr))
    assert tr._step_fn is None          # programs dropped, not reused
    tr.step(x, y)
    recs = [r for r in _read_journal(jfile)
            if r["kind"] == "elastic_retrace"]
    assert recs and recs[-1]["old_devices"] == 8 \
        and recs[-1]["new_devices"] == 2


def test_pipelined_restore_resharded(tmp_path):
    """PipelinedTrainer's topology-aware lane: a pipe=2/data=2 run
    restores bit-exactly onto a pipe=2/data=1 mesh (same pipe layout,
    different data parallelism)."""
    import jax
    D = jax.devices()
    root = str(tmp_path / "pck")
    rng = np.random.RandomState(0)
    x = rng.randn(8, 16).astype(np.float32)
    y = rng.randint(0, 8, (8,)).astype(np.float32)

    def build(mesh):
        mx.random.seed(11)
        embed = gluon.nn.Dense(32, in_units=16, flatten=False)
        body = [gluon.nn.Dense(32, in_units=32, activation="relu")
                for _ in range(2)]
        head = gluon.nn.Dense(8, in_units=32)
        for b in (embed, *body, head):
            b.initialize()
        return parallel.PipelinedTrainer(
            embed, body, head, gluon.loss.SoftmaxCrossEntropyLoss(),
            "sgd", optimizer_params={"learning_rate": 0.05},
            mesh=mesh, num_microbatches=2)

    tr_a = build(parallel.make_mesh({"pipe": 2, "data": 2},
                                    devices=D[:4]))
    for _ in range(2):
        tr_a.step(x, y)
    step = tr_a.checkpoint(root, per_shard=True)
    want = {k: np.asarray(v) for k, v in tr_a._ckpt_entries().items()}

    tr_b = build(parallel.make_mesh({"pipe": 2, "data": 1},
                                    devices=D[:2]))
    tr_b.prepare(x)
    assert tr_b.restore_resharded(root) == step
    got = {k: np.asarray(v) for k, v in tr_b._ckpt_entries().items()}
    assert elastic.driver.np_tree_equal(want, got)
    tr_b.step(x, y)


# -- crash matrix: every kill point during resize's restore→recommit --------

def _matrix_rules():
    """Kill points across the post-restore re-commit: the atomic write
    phases of the staged files plus the commit protocol's own points."""
    return [faults.crash("write", path_part="step-"),
            faults.crash("replace", path_part="step-"),
            faults.crash("fsync", path_part="step-"),
            faults.crash("publish"),
            faults.crash("gc")]


def test_reshard_crash_matrix_old_or_new(tmp_path):
    """Kill the N_old→N_new resize sequence (restore resharded, then
    re-commit on the new topology) at every write/publish/gc point: the
    root must always restore an intact step — the old one before the
    new commit point, the new one after."""
    import jax
    D = jax.devices()
    root = str(tmp_path / "ck")
    x, y = _batch()
    mx.random.seed(21)
    tr2 = _make_trainer(parallel.make_mesh({"data": 2}, devices=D[:2]))
    for _ in range(3):
        tr2.step(x, y)
    old_step = tr2.checkpoint(root, per_shard=True)
    old_tree = _committed_entries(root, old_step)

    for rule in _matrix_rules():
        mx.random.seed(33)
        tr1 = _make_trainer(parallel.make_mesh({"data": 1},
                                               devices=D[:1]))
        tr1.prepare(x)
        assert tr1.restore_resharded(root) == old_step   # read-only
        tr1.step(x, y)
        with faults.inject(rule) as plan:
            try:
                tr1.checkpoint(root, per_shard=True)
                killed = False
            except faults.SimulatedCrash:
                killed = True
        assert killed or not plan.log, rule.point
        # whatever the kill left behind, a fresh reader lands on an
        # intact old-or-new tree
        got = rcommit.find_restorable(root)
        assert got is not None
        landed = got[0]
        assert landed in (old_step, old_step + 1)
        tree = _committed_entries(root, landed)
        if landed == old_step:
            assert elastic.driver.np_tree_equal(tree, old_tree)
        # reset for the next kill point: wipe any committed new step
        import shutil
        new_dir = rcommit.step_dir(root, old_step + 1)
        if os.path.isdir(new_dir):
            shutil.rmtree(new_dir)
        for name in os.listdir(root):
            if name.endswith(".tmp") or name.startswith(".trash-"):
                shutil.rmtree(os.path.join(root, name),
                              ignore_errors=True)


def test_smoke_corrupt_shard_file_falls_back_journaled(tmp_path, jfile):
    """A corrupt shard file in the newest step: resharded restore skips
    it (journaled ckpt_fallback) and lands on the previous intact step."""
    import jax
    D = jax.devices()
    root = str(tmp_path / "ck")
    x, y = _batch()
    mx.random.seed(8)
    tr = _make_trainer(parallel.make_mesh({"data": 2}, devices=D[:2]))
    tr.step(x, y)
    s1 = tr.checkpoint(root, per_shard=True)
    good = _committed_entries(root, s1)
    tr.step(x, y)
    s2 = tr.checkpoint(root, per_shard=True)
    # flip bytes inside the newest step's shard file
    shard = os.path.join(rcommit.step_dir(root, s2),
                         f"{_ckpt.CKPT_BASENAME}.params.shard0")
    with open(shard, "r+b") as f:
        f.seek(os.path.getsize(shard) // 2)
        f.write(b"\xde\xad\xbe\xef")
    mx.random.seed(55)
    tr1 = _make_trainer(parallel.make_mesh({"data": 1}, devices=D[:1]))
    tr1.prepare(x)
    assert tr1.restore_resharded(root) == s1
    assert elastic.driver.np_tree_equal(good, _snapshot(tr1))
    recs = _read_journal(jfile)
    falls = [r for r in recs if r["kind"] == "ckpt_fallback"]
    assert falls and falls[-1]["step"] == s2


# -- the multi-process chaos proof ------------------------------------------

WORKER = r"""
import os, sys, json
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
rank = int(sys.argv[1]); world = int(sys.argv[2]); base = sys.argv[3]
os.environ["MXNET_TPU_JOURNAL"] = os.path.join(base, f"journal-{rank}.jsonl")
os.environ["MXNET_TPU_TRACE"] = "journal"
sys.path.insert(0, %(repo)r)
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import elastic, gluon, parallel
from mxnet_tpu.testing import faults

KILL_AT = 6
# deadline generous vs heartbeat: a loaded CI box stalling the writer
# thread must not produce a false RankLost on a live rank
cfg = elastic.CohortConfig(heartbeat_s=0.25, deadline_s=3.0,
                           barrier_s=60.0, poll_s=0.02)
cohort = elastic.Cohort(os.path.join(base, "cohort"), rank, cfg).start()
cohort.form(world)

def build(members):
    import jax
    mx.random.seed(42)                      # identical init on every rank
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(16, activation="relu"), gluon.nn.Dense(4))
    net.initialize()
    n_dev = 2 if len(members) > 1 else 1    # survivor mesh shrinks too
    mesh = parallel.make_mesh({"data": n_dev},
                              devices=jax.devices()[:n_dev])
    return parallel.ShardedTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        optimizer_params={"learning_rate": 0.05, "momentum": 0.9},
        mesh=mesh)

rng = np.random.RandomState(1234)           # same data table on all ranks
X = rng.randn(world * 8, 12).astype(np.float32)
Y = rng.randint(0, 4, (world * 8,))

def data_fn(step, members, index):
    if rank == 1 and step == KILL_AT:
        faults.sigterm()                    # this rank dies mid-run
    lo = index * 8
    return X[lo:lo + 8], Y[lo:lo + 8]

driver = elastic.ElasticDriver(cohort, os.path.join(base, "ckpt"), build,
                               checkpoint_every=4, keep_last=4)

def on_restore(trainer, step):
    snap = {}
    for p in trainer._trainable:
        snap["arg:" + trainer._struct_name(p)] = np.asarray(p._data[0]._data)
    for p in trainer._aux:
        snap["aux:" + trainer._struct_name(p)] = np.asarray(p._data[0]._data)
    for p, st in zip(trainer._trainable, trainer._states):
        for j, s in enumerate(st):
            snap[f"state:{trainer._struct_name(p)}:{j}"] = np.asarray(s)
    np.savez(os.path.join(base, f"post_restore-{rank}-{step}.npz"), **snap)

driver.on_restore = on_restore
trainer = driver.run(data_fn, num_steps=12)
cohort.stop(resign=True)
print(json.dumps({"rank": rank, "ok": True,
                  "restored_step": driver.restored_step,
                  "rebuilds": driver.rebuilds,
                  "num_update": int(trainer.num_update),
                  "members": cohort.members()}), flush=True)
"""


def test_smoke_elastic_chaos_rank_loss_survivor_continues(tmp_path):
    """THE acceptance chaos proof (see module docstring)."""
    base = str(tmp_path)
    script = tmp_path / "worker.py"
    script.write_text(WORKER % {"repo": REPO})
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env.pop("MXNET_TPU_JOURNAL", None)
    procs = [subprocess.Popen(
        [sys.executable, str(script), str(r), "2", base],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env) for r in range(2)]
    out0, err0 = procs[0].communicate(timeout=280)
    out1, err1 = procs[1].communicate(timeout=60)

    # rank 1 died by SIGTERM mid-run; rank 0 finished clean
    assert procs[1].returncode != 0
    assert procs[0].returncode == 0, \
        f"stdout:\n{out0}\nstderr:\n{err0[-3000:]}"
    doc = json.loads([ln for ln in out0.splitlines()
                      if ln.startswith("{")][-1])
    assert doc["ok"] and doc["num_update"] == 12
    assert doc["rebuilds"] >= 1 and doc["members"] == [0]
    restored = doc["restored_step"]
    assert restored is not None and restored >= 4

    # the survivor's journal: rank_lost -> cohort_resize ->
    # reshard_restore, correlated under ONE trace
    recs = _read_journal(os.path.join(base, "journal-0.jsonl"))
    by_kind = {}
    for r in recs:
        by_kind.setdefault(r["kind"], []).append(r)
    assert by_kind.get("rank_lost"), "no rank_lost record"
    assert by_kind["rank_lost"][-1]["lost"] == [1]
    assert by_kind.get("cohort_resize"), "no cohort_resize record"
    assert by_kind["cohort_resize"][-1]["members"] == [0]
    assert by_kind.get("reshard_restore"), "no reshard_restore record"
    rr = by_kind["reshard_restore"][-1]
    assert rr["n_old"] == 2 and rr["n_new"] == 1
    tid = by_kind["rank_lost"][-1].get("trace_id")
    assert tid, "rank_lost not correlated to a trace"
    assert by_kind["reshard_restore"][-1].get("trace_id") == tid
    assert any(r.get("trace_id") == tid
               for r in by_kind["cohort_resize"])
    # the leader stamped its recovery trace into the epoch ledger — the
    # channel every survivor adopts its elastic_recover span from
    # (multi-survivor adoption is unit-tested in
    # test_distributed_trace.py; here the leader IS the one survivor)
    epoch_docs = []
    epoch_dir = os.path.join(base, "cohort", "epoch")
    for name in sorted(os.listdir(epoch_dir)):
        with open(os.path.join(epoch_dir, name)) as f:
            epoch_docs.append(json.load(f))
    resizes = [d for d in epoch_docs if d.get("reason") == "resize"]
    assert resizes, "no resize epoch record on the ledger"
    assert resizes[-1].get("recovery_trace") == tid

    # bit-exactness: the tree the survivor restored equals the committed
    # step's assembled global tree (written by BOTH ranks as 2 shards)
    post = np.load(os.path.join(base,
                                f"post_restore-0-{restored}.npz"))
    committed = _committed_entries(os.path.join(base, "ckpt"), restored)
    assert set(post.files) == set(committed)
    for k in committed:
        assert np.array_equal(post[k], committed[k]), k
    # and that step really was written by the 2-member cohort
    man = rcommit.read_manifest(
        rcommit.step_dir(os.path.join(base, "ckpt"), restored))
    assert man["meta"].get("kind") == "cohort"
    assert man["meta"].get("cohort_members") == [0, 1]
    shard_files = [n for n in man["files"] if ".shard" in n]
    assert any(n.endswith(".shard0") for n in shard_files)
    assert any(n.endswith(".shard1") for n in shard_files)

    # doctor's elastic section reads the same story
    rep = elastic_report_mod.elastic_report(
        os.path.join(base, "journal-0.jsonl"))
    assert rep["ok"] and rep["counts"]["rank_lost"] >= 1
    assert rep["correlated_recoveries"] >= 1
    assert rep["last_resize"]["members"] == [0]


def test_smoke_sigterm_mid_reshard_leaves_disk_intact(tmp_path):
    """Mid-reshard SIGTERM: restore is read-only, so killing the restorer
    at any moment leaves every committed step intact — proven by killing
    a restore loop and re-validating + re-restoring."""
    import jax
    D = jax.devices()
    root = str(tmp_path / "ck")
    x, y = _batch()
    mx.random.seed(2)
    tr = _make_trainer(parallel.make_mesh({"data": 2}, devices=D[:2]))
    tr.step(x, y)
    s1 = tr.checkpoint(root, per_shard=True)
    tr.step(x, y)
    s2 = tr.checkpoint(root, per_shard=True)
    script = tmp_path / "restorer.py"
    script.write_text(
        "import os, sys\n"
        "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
        f"sys.path.insert(0, {REPO!r})\n"
        "from mxnet_tpu import elastic\n"
        f"prefix = {os.path.join(rcommit.step_dir(root, s2), _ckpt.CKPT_BASENAME)!r}\n"
        "print('RESTORING', flush=True)\n"
        "while True:\n"
        "    elastic.read_global_entries(prefix + '.params')\n")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    proc = subprocess.Popen([sys.executable, str(script)],
                            stdout=subprocess.PIPE, text=True, env=env)
    assert proc.stdout.readline().strip() == "RESTORING"
    time.sleep(0.2)           # mid-read with high probability
    proc.terminate()
    proc.wait(timeout=30)
    # both steps still validate and the newest still restores resharded
    rcommit.validate_step(root, s1)
    rcommit.validate_step(root, s2)
    mx.random.seed(91)
    tr1 = _make_trainer(parallel.make_mesh({"data": 1}, devices=D[:1]))
    tr1.prepare(x)
    assert tr1.restore_resharded(root) == s2


# -- reporting / misc --------------------------------------------------------

def test_spec_projection_keeps_tuple_axes():
    """Rule-spec projection onto a mesh: multi-axis tuple entries keep
    exactly the axes the mesh still has (a tuple must never silently
    degrade to full replication on a mesh that HAS those axes)."""
    import jax
    from mxnet_tpu.parallel import ShardedTrainer
    P = parallel.PartitionSpec
    full = parallel.make_mesh({"data": 4, "model": 2})
    sp = P(("data", "model"), None)
    assert ShardedTrainer._spec_on(full, sp) == sp
    solo = parallel.make_mesh({"data": 2}, devices=jax.devices()[:2])
    assert ShardedTrainer._spec_on(solo, sp) == P("data", None)
    other = parallel.make_mesh({"pipe": 8})
    assert ShardedTrainer._spec_on(other, sp) == P(None, None)
    assert ShardedTrainer._spec_on(solo, P("model", "data")) == \
        P(None, "data")


def test_mesh_signature():
    import jax
    mesh = parallel.make_mesh({"data": 4, "model": 2})
    assert parallel.mesh_signature(mesh) == \
        {"devices": 8, "axes": {"data": 4, "model": 2}}


def test_elastic_report_empty_and_garbage(tmp_path):
    p = tmp_path / "j.jsonl"
    p.write_text("not json\n{\"kind\": \"heartbeat\"}\n")
    rep = elastic_report_mod.elastic_report(str(p))
    assert rep["ok"] and rep["counts"]["rank_lost"] == 0
    rep2 = elastic_report_mod.elastic_report(str(tmp_path / "missing"))
    assert rep2["ok"] is False


def test_doctor_journal_gains_elastic_section(tmp_path):
    """doctor --journal: the guardrails report now carries the cohort
    events section, and the stderr summary mentions it."""
    from mxnet_tpu.diagnostics.__main__ import (_guardrails_report,
                                                _summ_guardrails)
    p = tmp_path / "j.jsonl"
    p.write_text("\n".join(json.dumps(r) for r in [
        {"kind": "rank_lost", "lost": [1], "survivors": [0], "epoch": 0,
         "step": 6, "trace_id": "t1"},
        {"kind": "cohort_resize", "epoch": 1, "old_members": [0, 1],
         "members": [0], "lost": [1], "joined": [], "trace_id": "t1"},
        {"kind": "reshard_restore", "step": 4, "n_old": 2, "n_new": 1,
         "entries": 10, "bytes": 123, "trace_id": "t1"},
    ]) + "\n")
    rep = _guardrails_report(str(p))
    assert rep["ok"] and rep["elastic"]["ok"]
    assert rep["elastic"]["counts"]["rank_lost"] == 1
    assert rep["elastic"]["correlated_recoveries"] == 1
    assert rep["elastic"]["last_resize"]["members"] == [0]
    summ = _summ_guardrails(rep)
    assert "elastic: 1 rank losses" in summ and "last -> [0]" in summ


def test_cohort_group_round_robin_pieces(tmp_path):
    c0 = elastic.Cohort(str(tmp_path / "c"), 0, _cfg()).start()
    try:
        c0._write_epoch(0, [0, 3], "form")
        g = elastic.CohortGroup(c0, [0, 3])
        assert g.index() == 0 and g.count() == 2
        assert [g.owns_piece(i) for i in range(4)] == \
            [True, False, True, False]
        meta = g.meta()
        assert meta["kind"] == "cohort" and meta["world"] == 2
    finally:
        c0.stop()

"""Detection path tests: MultiBox ops + SSD model (driver config #5;
ref: tests/python/unittest/test_contrib_operator.py multibox tests)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.gluon.model_zoo import ssd


def test_multibox_prior_counts():
    x = mx.nd.zeros((1, 3, 8, 8))
    anchors = mx.nd.contrib.MultiBoxPrior(x, sizes=(0.5, 0.25),
                                          ratios=(1, 2))
    # A = len(sizes) + len(ratios) - 1 = 3 per pixel
    assert anchors.shape == (1, 8 * 8 * 3, 4)


def test_multibox_target_assigns_gt():
    # two anchors: one matching the gt box, one far away
    anchors = mx.nd.array([[[0.1, 0.1, 0.4, 0.4],
                            [0.6, 0.6, 0.9, 0.9]]])
    # one gt: class 0 at the first anchor's location
    labels = mx.nd.array([[[0, 0.1, 0.1, 0.4, 0.4]]])
    cls_preds = mx.nd.zeros((1, 2, 2))   # (N, A, C+1) scores, unused here
    loc_t, loc_m, cls_t = mx.nd.contrib.MultiBoxTarget(
        anchors, labels, cls_preds)
    ct = cls_t.asnumpy()[0]
    assert ct[0] == 1.0      # positive: class 0 → target 1
    assert ct[1] == 0.0      # background
    lm = loc_m.asnumpy()[0].reshape(2, 4)
    assert lm[0].sum() == 4 and lm[1].sum() == 0
    # perfect match ⇒ zero encoded offsets
    lt = loc_t.asnumpy()[0].reshape(2, 4)
    np.testing.assert_allclose(lt[0], 0.0, atol=1e-5)


def test_multibox_target_detection_roundtrip():
    """Encode with MultiBoxTarget, decode with MultiBoxDetection — boxes
    must come back (the reference's numerical contract between the ops)."""
    rng = np.random.RandomState(0)
    anchors = mx.nd.array(rng.uniform(0.1, 0.4, (1, 6, 4)).astype(
        np.float32))
    a = anchors.asnumpy()[0].copy()
    a[:, 2:] = a[:, :2] + 0.3          # valid corner boxes
    anchors = mx.nd.array(a[None])
    gt = np.array([[[1, 0.15, 0.15, 0.45, 0.5]]], dtype=np.float32)
    labels = mx.nd.array(gt)
    cls_preds = mx.nd.zeros((1, 6, 3))
    loc_t, loc_m, cls_t = mx.nd.contrib.MultiBoxTarget(anchors, labels,
                                                       cls_preds)
    # build a fake perfect network output: probs one-hot to cls_t
    ct = cls_t.asnumpy()[0].astype(int)
    probs = np.zeros((1, 3, 6), dtype=np.float32)
    for i, c in enumerate(ct):
        probs[0, c, i] = 1.0
    out = mx.nd.contrib.MultiBoxDetection(
        mx.nd.array(probs), loc_t, anchors, nms_threshold=1.01)
    rows = out.asnumpy()[0]
    kept = rows[rows[:, 0] >= 0]
    assert len(kept) >= 1
    # the decoded box must match the gt box
    best = kept[np.argmax(kept[:, 1])]
    np.testing.assert_allclose(best[2:], gt[0, 0, 1:], atol=1e-3)
    assert best[0] == 1.0  # class id (background_id=0 shifts by 1... cls 1)


def test_ssd_forward_shapes():
    net = ssd.get_ssd("resnet18_v1", classes=4, num_scales=3,
                      thumbnail=True)
    net.initialize()
    x = mx.nd.random.normal(shape=(2, 3, 64, 64))
    anchors, cls_preds, box_preds = net(x)
    a = anchors.shape[1]
    assert anchors.shape == (1, a, 4)
    assert cls_preds.shape == (2, a, 5)
    assert box_preds.shape == (2, a * 4)


def test_ssd_train_step_runs():
    from mxnet_tpu import autograd
    net = ssd.get_ssd("resnet18_v1", classes=2, num_scales=2,
                      thumbnail=True)
    net.initialize()
    loss_fn = ssd.SSDMultiBoxLoss()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.01})
    x = mx.nd.random.normal(shape=(2, 3, 32, 32))
    labels = mx.nd.array(np.array(
        [[[0, 0.1, 0.1, 0.5, 0.5]], [[1, 0.3, 0.3, 0.8, 0.8]]],
        dtype=np.float32))
    with autograd.record():
        anchors, cls_preds, box_preds = net(x)
        loc_t, loc_m, cls_t = mx.nd.contrib.MultiBoxTarget(
            anchors, labels, cls_preds)
        loss = loss_fn(cls_preds, box_preds, cls_t, loc_t, loc_m)
    loss.backward()
    trainer.step(2)
    assert np.isfinite(loss.asnumpy()).all()


def test_proposal_op():
    """RPN proposal generation (Faster-RCNN path, SURVEY §2 #18)."""
    N, A, H, W = 1, 12, 4, 4     # 4 scales x 3 ratios
    rng = np.random.RandomState(0)
    cls_prob = rng.rand(N, 2 * A, H, W).astype(np.float32)
    bbox_pred = (rng.randn(N, 4 * A, H, W) * 0.1).astype(np.float32)
    im_info = np.array([[64.0, 64.0, 1.0]], dtype=np.float32)
    rois = mx.nd.contrib.Proposal(
        mx.nd.array(cls_prob), mx.nd.array(bbox_pred),
        mx.nd.array(im_info), rpn_pre_nms_top_n=50,
        rpn_post_nms_top_n=10, threshold=0.7, rpn_min_size=4)
    out = rois.asnumpy()
    assert out.shape == (10, 5)
    kept = out[out[:, 0] >= 0]
    assert len(kept) >= 1
    # rois clipped to the image
    assert (kept[:, 1] >= 0).all() and (kept[:, 3] <= 63.0 + 1e-3).all()
    assert (kept[:, 2] >= 0).all() and (kept[:, 4] <= 63.0 + 1e-3).all()
    # batch index column is 0 for the single image
    assert (kept[:, 0] == 0).all()


def test_proposal_with_scores():
    N, A, H, W = 2, 3, 3, 3      # 1 scale x 3 ratios
    rng = np.random.RandomState(1)
    rois, scores = mx.nd.contrib.Proposal(
        mx.nd.array(rng.rand(N, 2 * A, H, W).astype(np.float32)),
        mx.nd.array((rng.randn(N, 4 * A, H, W) * 0.05).astype(np.float32)),
        mx.nd.array(np.array([[48.0, 48.0, 1.0]] * N, dtype=np.float32)),
        scales=(8.0,), rpn_pre_nms_top_n=20, rpn_post_nms_top_n=5,
        rpn_min_size=2, output_score=True)
    assert rois.shape == (10, 5)
    assert scores.shape == (10, 1)

"""DeformableConvolution / ModulatedDeformableConvolution / count_sketch
(ref: src/operator/contrib/deformable_convolution.cc,
modulated_deformable_convolution.cc, count_sketch.cc; test analog
tests/python/unittest/test_contrib_operator.py)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd


def _setup(seed=0):
    r = np.random.RandomState(seed)
    x = r.randn(2, 4, 9, 9).astype(np.float32)
    w = r.randn(6, 4, 3, 3).astype(np.float32)
    b = r.randn(6).astype(np.float32)
    return x, w, b


def test_zero_offset_equals_convolution():
    x, w, b = _setup()
    off = np.zeros((2, 18, 9, 9), np.float32)
    got = nd.contrib.DeformableConvolution(
        nd.array(x), nd.array(off), nd.array(w), nd.array(b),
        kernel=(3, 3), pad=(1, 1), num_filter=6).asnumpy()
    want = nd.Convolution(nd.array(x), nd.array(w), nd.array(b),
                          kernel=(3, 3), pad=(1, 1),
                          num_filter=6).asnumpy()
    np.testing.assert_allclose(got, want, atol=1e-4)


def test_integer_offset_equals_shifted_image():
    x, w, b = _setup(1)
    off = np.zeros((2, 18, 9, 9), np.float32)
    off[:, 1::2] = 1.0                       # dx = +1 for every tap
    got = nd.contrib.DeformableConvolution(
        nd.array(x), nd.array(off), nd.array(w), nd.array(b),
        kernel=(3, 3), pad=(1, 1), num_filter=6).asnumpy()
    xs = np.zeros_like(x)
    xs[:, :, :, :-1] = x[:, :, :, 1:]
    want = nd.Convolution(nd.array(xs), nd.array(w), nd.array(b),
                          kernel=(3, 3), pad=(1, 1),
                          num_filter=6).asnumpy()
    np.testing.assert_allclose(got[:, :, 1:-1, 1:-1],
                               want[:, :, 1:-1, 1:-1], atol=1e-4)


def test_fractional_offset_bilinear():
    # constant 0.5 x-offset on a linear ramp image: sampled value is the
    # midpoint of neighbors, so a 1x1 kernel returns the average
    x = np.tile(np.arange(8, dtype=np.float32), (1, 1, 8, 1))
    w = np.ones((1, 1, 1, 1), np.float32)
    off = np.zeros((1, 2, 8, 8), np.float32)
    off[:, 1] = 0.5
    got = nd.contrib.DeformableConvolution(
        nd.array(x), nd.array(off), nd.array(w), kernel=(1, 1),
        num_filter=1, no_bias=True).asnumpy()
    want = x + 0.5
    np.testing.assert_allclose(got[..., :-1], want[..., :-1], atol=1e-5)


def test_modulated_mask_semantics():
    x, w, b = _setup(2)
    off = np.zeros((2, 18, 9, 9), np.float32)
    ones = np.ones((2, 9, 9, 9), np.float32)
    v1 = nd.contrib.DeformableConvolution(
        nd.array(x), nd.array(off), nd.array(w), nd.array(b),
        kernel=(3, 3), pad=(1, 1), num_filter=6).asnumpy()
    mod = nd.contrib.ModulatedDeformableConvolution(
        nd.array(x), nd.array(off), nd.array(ones), nd.array(w),
        nd.array(b), kernel=(3, 3), pad=(1, 1), num_filter=6).asnumpy()
    np.testing.assert_allclose(mod, v1, atol=1e-4)
    half = nd.contrib.ModulatedDeformableConvolution(
        nd.array(x), nd.array(off), nd.array(ones * 0.5), nd.array(w),
        nd.array(b), kernel=(3, 3), pad=(1, 1), num_filter=6,
        no_bias=True).asnumpy()
    nob = nd.contrib.DeformableConvolution(
        nd.array(x), nd.array(off), nd.array(w), kernel=(3, 3),
        pad=(1, 1), num_filter=6, no_bias=True).asnumpy()
    np.testing.assert_allclose(half, 0.5 * nob, atol=1e-4)


def test_groups_and_deformable_groups():
    r = np.random.RandomState(3)
    x = r.randn(1, 4, 7, 7).astype(np.float32)
    w = r.randn(4, 2, 3, 3).astype(np.float32)
    off = np.zeros((1, 36, 7, 7), np.float32)
    got = nd.contrib.DeformableConvolution(
        nd.array(x), nd.array(off), nd.array(w), kernel=(3, 3),
        pad=(1, 1), num_filter=4, num_group=2, num_deformable_group=2,
        no_bias=True).asnumpy()
    want = nd.Convolution(nd.array(x), nd.array(w), kernel=(3, 3),
                          pad=(1, 1), num_filter=4, num_group=2,
                          no_bias=True).asnumpy()
    np.testing.assert_allclose(got, want, atol=1e-4)


def test_stride_and_dilate():
    r = np.random.RandomState(4)
    x = r.randn(1, 3, 11, 11).astype(np.float32)
    w = r.randn(5, 3, 3, 3).astype(np.float32)
    off = np.zeros((1, 18, 6, 6), np.float32)
    got = nd.contrib.DeformableConvolution(
        nd.array(x), nd.array(off), nd.array(w), kernel=(3, 3),
        stride=(2, 2), dilate=(2, 2), pad=(2, 2), num_filter=5,
        no_bias=True).asnumpy()
    want = nd.Convolution(nd.array(x), nd.array(w), kernel=(3, 3),
                          stride=(2, 2), dilate=(2, 2), pad=(2, 2),
                          num_filter=5, no_bias=True).asnumpy()
    assert got.shape == want.shape == (1, 5, 6, 6)
    np.testing.assert_allclose(got, want, atol=1e-4)


def test_count_sketch_matches_loop():
    r = np.random.RandomState(5)
    d = r.randn(3, 10).astype(np.float32)
    h = r.randint(0, 6, (1, 10))
    s = r.choice([-1.0, 1.0], (1, 10)).astype(np.float32)
    got = nd.contrib.count_sketch(
        nd.array(d), nd.array(h.astype(np.float32)), nd.array(s),
        out_dim=6).asnumpy()
    want = np.zeros((3, 6), np.float32)
    for i in range(10):
        want[:, h[0, i]] += s[0, i] * d[:, i]
    np.testing.assert_allclose(got, want, atol=1e-5)


def test_symbol_path():
    x, w, b = _setup(6)
    ds = mx.sym.var("data")
    os_ = mx.sym.var("off")
    out = mx.sym.contrib.DeformableConvolution(
        ds, os_, kernel=(3, 3), pad=(1, 1), num_filter=6)
    args = out.list_arguments()
    assert "data" in args and "off" in args
    off = np.zeros((2, 18, 9, 9), np.float32)
    wname = [a for a in args if a.endswith("weight")][0]
    bname = [a for a in args if a.endswith("bias")][0]
    ex = out.bind(mx.cpu(), {"data": nd.array(x), "off": nd.array(off),
                             wname: nd.array(w), bname: nd.array(b)})
    got = ex.forward()[0].asnumpy()
    want = nd.Convolution(nd.array(x), nd.array(w), nd.array(b),
                          kernel=(3, 3), pad=(1, 1),
                          num_filter=6).asnumpy()
    np.testing.assert_allclose(got, want, atol=1e-4)

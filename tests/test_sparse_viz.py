"""Sparse storage + visualization tests (ref: tests/python/unittest/
test_sparse_ndarray.py shrunk to the supported surface)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import sym


def test_csr_roundtrip_and_dot():
    dense = np.array([[0, 1, 0], [2, 0, 3], [0, 0, 0]], dtype=np.float32)
    csr = mx.nd.csr_matrix(dense)
    assert csr.stype == "csr"
    np.testing.assert_array_equal(csr.asnumpy(), dense)
    back = csr.tostype("default")
    np.testing.assert_array_equal(back.asnumpy(), dense)
    rhs = np.random.RandomState(0).randn(3, 4).astype(np.float32)
    out = csr.dot(mx.nd.array(rhs))
    np.testing.assert_allclose(out.asnumpy(), dense @ rhs, rtol=1e-6)


def test_csr_from_tuple():
    csr = mx.nd.csr_matrix((np.array([1.0, 2.0]), np.array([1, 0]),
                            np.array([0, 1, 2])), shape=(2, 3))
    want = np.array([[0, 1, 0], [2, 0, 0]], dtype=np.float32)
    np.testing.assert_array_equal(csr.asnumpy(), want)


def test_row_sparse_roundtrip_retain():
    dense = np.zeros((5, 3), dtype=np.float32)
    dense[1] = 1.0
    dense[3] = 2.0
    rs = mx.nd.row_sparse_array(dense)
    assert rs.stype == "row_sparse"
    assert list(rs.indices) == [1, 3]
    np.testing.assert_array_equal(rs.asnumpy(), dense)
    kept = rs.retain([3])
    assert list(kept.indices) == [3]
    np.testing.assert_array_equal(kept.asnumpy()[3], dense[3])


def test_ndarray_tostype():
    x = mx.nd.array([[1.0, 0.0], [0.0, 2.0]])
    csr = x.tostype("csr")
    assert csr.stype == "csr"
    assert x.tostype("default") is x


def test_print_summary_and_plot(capsys):
    data = sym.var("data")
    fc1 = sym.FullyConnected(data, num_hidden=8, name="fc1")
    out = sym.SoftmaxOutput(fc1, name="softmax")
    total = mx.viz.print_summary(out, shape={"data": (2, 4),
                                             "softmax_label": (2,)})
    captured = capsys.readouterr().out
    assert "fc1" in captured
    assert total == 8 * 4 + 8          # weight + bias
    dot = mx.viz.plot_network(out)
    assert "fc1" in dot.source and "digraph" in dot.source

"""Multi-tenant model-fleet serving (docs/serving.md tenant matrix).

The headline chaos gate (CI tier 0.5, ``-k smoke``): tenant A is fed a
corrupt committed checkpoint + an oversized-shape flood + predictor
poison (``faults.tenant_poison`` on the ``serving_tenant`` trip site)
while tenant B runs closed-loop load on the SAME fleet — B's p99 stays
inside its SLO bound with zero structural-corruption errors, A fails
structurally with tenant-labeled errors and quarantines ITSELF, the
quarantine→half-open→re-admit trail is trace-correlated in the journal,
and ``doctor --serving-journal`` renders it.

Around it: SLO-classed admission (token-bucket rate budget, per-class
queue shares, deadline floors), weight paging (host-RAM tier → device
on demand, LRU hot set, journaled page-in cost), hot add/remove/reload,
mixed-version fleets on different commit roots reloading concurrently
(every response version-stamped with its OWN tenant's old-or-new step),
the ParamStore bad-step LRU cap, tenant-aware router placement over a
fleet replica pool, and the bench/report/metrics surfaces.
"""
import json
import os
import threading
import time

import numpy as np
import pytest

from mxnet_tpu import nd
from mxnet_tpu.diagnostics.journal import reset_journal
from mxnet_tpu.gluon.block import HybridBlock
from mxnet_tpu.resilience import atomic, commit
from mxnet_tpu.serving import (Fleet, FleetConfig, ParamStore,
                               RequestError, SLOClass, ServerOverloaded,
                               TenantQuarantined, serving_report)
from mxnet_tpu.testing import faults

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def journal_file(tmp_path):
    path = str(tmp_path / "journal.jsonl")
    reset_journal(path)
    try:
        yield path
    finally:
        reset_journal("stderr")


def _records(path, kind=None):
    out = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if kind is None or rec.get("kind") == kind:
                out.append(rec)
    return out


class Scale(HybridBlock):
    """y = x * w: shape-agnostic, and the weight value IS the served
    checkpoint's fingerprint (version-stamp and corruption assertions
    ride it)."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.w = self.params.get("w", shape=(1,), init="ones")

    def hybrid_forward(self, F, x, w):
        return x * w


def _scale_factory():
    net = Scale()
    net.initialize()
    return net


def _commit_scale(root, step, value):
    stage = commit.prepare_stage(root, step)
    nd.save(os.path.join(stage, "net.params"),
            {"w": nd.array(np.asarray([value], np.float32))})
    return commit.finalize(root, step)


def _fleet(**cfg_kw):
    cfg_kw.setdefault("max_batch", 4)
    cfg_kw.setdefault("window_ms", 1.0)
    cfg_kw.setdefault("reload_poll_s", 0.05)
    return Fleet(FleetConfig(**cfg_kw))


# -- the chaos gate (CI tier 0.5) --------------------------------------------

def test_fleet_smoke_tenant_isolation_chaos_gate(tmp_path, journal_file):
    """Corrupt checkpoint + oversized-shape flood + predictor poison on
    tenant A; closed-loop load on tenant B, same fleet.  B: p99 inside
    its SLO bound, ZERO structural-corruption errors (every response
    bit-exact from B's own valid step).  A: every failure structured
    and tenant-labeled, quarantine trips, half-open probe re-admits.
    The trail is trace-correlated in the journal and the doctor's
    serving-journal report renders it."""
    from mxnet_tpu.diagnostics.__main__ import _summ_serving
    from mxnet_tpu.observability import trace as obtrace
    obtrace.configure(mode="ring")
    try:
        root_a = str(tmp_path / "ckpt_a")
        root_b = str(tmp_path / "ckpt_b")
        _commit_scale(root_a, 101, 5.0)
        _commit_scale(root_b, 201, 2.0)
        # A's NEWER step is silently corrupted post-commit (bit flip
        # behind the CRC manifest): it must be skipped, journaled, and
        # fed to A's breaker — never served
        _commit_scale(root_a, 102, 9.0)
        faults.corrupt_params(root_a, 102)

        fleet = _fleet(tenant_breaker_k=3, tenant_cooldown_s=0.5,
                       max_queue=64, dim_buckets={0: (4, 16)})
        fleet.add_tenant("A", factory=_scale_factory, ckpt_root=root_a)
        fleet.add_tenant("B", factory=_scale_factory, ckpt_root=root_b)
        fleet.start()

        x = np.ones(4, np.float32)
        b_lat, b_errors = [], []
        stop = threading.Event()

        def b_loop():
            while not stop.is_set():
                t0 = time.perf_counter()
                try:
                    out = fleet.predict(x, tenant="B", deadline_ms=2000)
                except RequestError as e:     # structural = failure
                    b_errors.append(e)
                    continue
                b_lat.append((time.perf_counter() - t0) * 1000.0)
                if not np.array_equal(np.asarray(out),
                                      x * np.float32(2.0)):
                    b_errors.append(AssertionError(f"corrupt B: {out}"))

        bt = threading.Thread(target=b_loop, daemon=True)
        bt.start()

        # phase 1: A serves its newest VALID step (101, w=5), not the
        # corrupt 102
        out = np.asarray(fleet.predict(x, tenant="A"))
        assert np.array_equal(out, x * np.float32(5.0))

        # phase 2: oversized-shape flood + predictor poison on A
        a_errors = []
        plan = faults.FaultPlan(faults.tenant_poison("A", times=8))
        prev = atomic.set_fault_hook(plan)
        try:
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                try:
                    fleet.predict(np.ones(4096, np.float32), tenant="A")
                except RequestError as e:
                    a_errors.append(e)
                try:
                    fleet.predict(x, tenant="A")
                except RequestError as e:
                    a_errors.append(e)
                if fleet.tenant_stats()["A"]["state"] == "quarantined":
                    break
            else:
                pytest.fail("tenant A never quarantined under "
                            "shape-flood + poison")
        finally:
            atomic.set_fault_hook(prev)

        # quarantined: admission now rejects structurally
        with pytest.raises(TenantQuarantined) as qe:
            fleet.predict(x, tenant="A")
        a_errors.append(qe.value)

        # A's failures: ALL structured serving errors, ALL labeled A
        assert a_errors
        assert all(isinstance(e, RequestError) for e in a_errors)
        assert all(e.tenant == "A" for e in a_errors)
        assert any(isinstance(e, TenantQuarantined) for e in a_errors)

        # phase 3: cooldown -> half-open probe re-admits A (poison plan
        # is exhausted), and A still serves its valid step
        time.sleep(0.6)
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            try:
                out = fleet.predict(x, tenant="A", deadline_ms=2000)
                break
            except RequestError:
                time.sleep(0.1)
        else:
            pytest.fail("tenant A never re-admitted after cooldown")
        assert np.array_equal(np.asarray(out), x * np.float32(5.0))
        a_row = fleet.tenant_stats()["A"]
        assert a_row["state"] == "admitted"
        assert a_row["readmissions"] >= 1

        # tenant B rode through the whole drill untouched
        stop.set()
        bt.join(timeout=10)
        assert not b_errors, f"tenant B was degraded: {b_errors[:3]}"
        assert len(b_lat) >= 20
        p99 = sorted(b_lat)[int(0.99 * (len(b_lat) - 1))]
        assert p99 < 1500.0, f"tenant B p99 {p99:.0f}ms out of SLO"
        b_row = fleet.tenant_stats()["B"]
        assert b_row["state"] == "admitted"
        assert b_row["errors"] == 0 and b_row["quarantines"] == 0
        fleet.stop()

        # journal: corrupt candidate skipped + breaker-fed, and the
        # quarantine -> half_open -> admitted trail is present with
        # trace correlation on the worker-side transition
        fallbacks = [r for r in _records(journal_file, "ckpt_fallback")
                     if r.get("step") == 102]
        assert fallbacks, "corrupt step 102 never journaled"
        trail = _records(journal_file, "tenant_quarantine")
        a_trail = [(r["frm"], r["to"]) for r in trail
                   if r["tenant"] == "A"]
        assert ("admitted", "quarantined") in a_trail
        assert ("quarantined", "half_open") in a_trail
        assert ("half_open", "admitted") in a_trail
        assert all(r["tenant"] == "A" for r in trail)
        assert any(r.get("trace_id") for r in trail
                   if r["to"] == "quarantined"), \
            "quarantine transition not trace-correlated"

        # doctor renders the drill
        rep = serving_report(journal_file)
        assert rep["ok"]
        tn = rep["tenants"]
        assert tn["A"]["quarantine_trail"] and tn["A"]["readmitted"]
        assert tn["A"]["rejected_shape"] >= 1
        assert tn["B"]["served"] >= 20
        assert not tn["B"]["quarantine_trail"]
        summ = _summ_serving(rep)
        assert "fleet: 2 tenants" in summ and "re-admitted: ['A']" in summ
    finally:
        obtrace.reset_tracer()


# -- mixed-version fleets (satellite: rolling-reload x tenant axis) ----------

def test_fleet_smoke_mixed_version_reload_stamps_own_tenant_step(
        tmp_path, journal_file):
    """Two tenants on DIFFERENT commit roots hot-reload concurrently
    under traffic: every response is version-stamped with exactly its
    own tenant's old-or-new step — never the other tenant's, never a
    torn value."""
    root_a = str(tmp_path / "ckpt_a")
    root_b = str(tmp_path / "ckpt_b")
    _commit_scale(root_a, 100, 10.0)
    _commit_scale(root_b, 200, 20.0)
    fleet = _fleet(reload_poll_s=0.02)
    fleet.add_tenant("A", factory=_scale_factory, ckpt_root=root_a)
    fleet.add_tenant("B", factory=_scale_factory, ckpt_root=root_b)
    fleet.start()
    x = np.ones(2, np.float32)
    value_by_step = {100: 10.0, 101: 11.0, 200: 20.0, 201: 21.0}
    allowed = {"A": {100, 101}, "B": {200, 201}}
    bad = []
    stop = threading.Event()

    def client(tenant):
        while not stop.is_set():
            resp = fleet.submit(x, tenant=tenant, deadline_ms=4000)
            try:
                out = np.asarray(resp.result(10.0))
            except RequestError:
                continue              # startup race: not a stamp issue
            step = resp.params_step
            if step not in allowed[tenant]:
                bad.append((tenant, step, "foreign or missing step"))
            elif not np.array_equal(
                    out, x * np.float32(value_by_step[step])):
                bad.append((tenant, step, out.tolist()))

    threads = [threading.Thread(target=client, args=(t,), daemon=True)
               for t in ("A", "B") for _ in range(2)]
    for t in threads:
        t.start()
    time.sleep(0.4)
    # both roots publish a new step mid-traffic, concurrently
    ca = threading.Thread(
        target=lambda: _commit_scale(root_a, 101, 11.0), daemon=True)
    cb = threading.Thread(
        target=lambda: _commit_scale(root_b, 201, 21.0), daemon=True)
    ca.start(), cb.start()
    ca.join(10), cb.join(10)
    time.sleep(0.6)                   # let both reloads land under load
    stop.set()
    for t in threads:
        t.join(timeout=10)
    fleet.stop()
    assert not bad, f"version-stamp violations: {bad[:5]}"
    steps = {(r["tenant"], r["step"])
             for r in _records(journal_file, "serving_reload")}
    assert ("A", 101) in steps and ("B", 201) in steps
    stamped = {r["tenant"]: r.get("params_step")
               for r in _records(journal_file, "serving_batch")}
    assert stamped.get("A") in allowed["A"]
    assert stamped.get("B") in allowed["B"]


# -- ParamStore bad-step LRU (satellite) -------------------------------------

def test_param_store_bad_step_memory_lru_capped(tmp_path, journal_file):
    """A long-lived server scanning a churning commit root must not
    grow the remembered corrupt-candidate set without bound: the LRU
    cap holds, evictions journal a dedup note, and an evicted step that
    resurfaces is simply re-validated (and re-skipped)."""
    root = str(tmp_path / "ckpt")
    store = ParamStore(root, max_bad_steps=4)
    for step in range(1, 10):
        _commit_scale(root, step, float(step))
        faults.corrupt_params(root, step)
    assert store.poll() is None           # every candidate corrupt
    assert len(store._bad_steps) <= 4
    assert store.corrupt_seen == 9
    notes = [r for r in _records(journal_file, "ckpt_fallback")
             if r.get("note")]
    assert notes and notes[0]["cap"] == 4
    # a now-valid newest step still wins through the churn
    _commit_scale(root, 10, 10.0)
    step, loaded = store.poll()
    assert step == 10
    # poll again: nothing newer -> None, remembered steps stay capped
    assert store.poll() is None
    assert len(store._bad_steps) <= 4


def test_corrupt_params_flips_committed_shard_post_manifest(tmp_path):
    """``faults.corrupt_params`` corrupts the payload BEHIND the CRC
    manifest: commit listing still shows the step, validation fails,
    and a ParamStore skips it to the previous valid step."""
    root = str(tmp_path / "ckpt")
    _commit_scale(root, 1, 1.0)
    _commit_scale(root, 2, 2.0)
    path = faults.corrupt_params(root, 2)
    assert path.endswith("net.params")
    assert 2 in commit.committed_steps(root)
    with pytest.raises(ValueError):
        commit.validate_step(root, 2)
    store = ParamStore(root)
    step, loaded = store.poll()
    assert step == 1
    assert store.corrupt_seen == 1


# -- SLO-classed admission ---------------------------------------------------

def test_rate_budget_sheds_only_its_tenant(journal_file):
    """A tenant over its token-bucket rate budget sheds with a
    tenant-labeled ``rate_budget`` tier; the unlimited tenant on the
    same fleet is untouched."""
    fleet = _fleet(max_queue=64)
    fleet.add_tenant("greedy", factory=_scale_factory,
                     slo=SLOClass("capped", rate_rps=1.0, burst=2))
    fleet.add_tenant("calm", factory=_scale_factory)
    fleet.start()
    x = np.ones(2, np.float32)
    sheds = []
    for _ in range(6):
        try:
            fleet.predict(x, tenant="greedy")
        except ServerOverloaded as e:
            sheds.append(e)
    assert sheds and all(e.tenant == "greedy" for e in sheds)
    assert all(e.tier == "rate_budget" for e in sheds)
    for _ in range(4):                 # calm tenant admits freely
        fleet.predict(x, tenant="calm")
    st = fleet.tenant_stats()
    assert st["calm"]["shed"] == 0 and st["greedy"]["shed"] >= 1
    fleet.stop()
    tiers = {r.get("tier") for r in _records(journal_file, "serving_shed")}
    assert "rate_budget" in tiers


def test_class_budget_sheds_lower_priority_first(journal_file):
    """With the queue part-full, a bronze (priority-2) tenant loses its
    queue share and sheds ``class_budget`` while the gold tenant still
    admits — per-tenant-class shedding, never global."""
    fleet = _fleet(max_queue=16, window_ms=50.0, max_batch=2)
    fleet.add_tenant("gold", factory=_scale_factory, slo="gold")
    fleet.add_tenant("bronze", factory=_scale_factory, slo="bronze")
    # do NOT start the worker: requests pile in the queue
    x = np.ones(2, np.float32)
    pending = [fleet.submit(x, tenant="gold") for _ in range(6)]
    assert fleet.queue_depth() >= 4    # bronze share = 16/4 = 4
    with pytest.raises(ServerOverloaded) as ei:
        fleet.submit(x, tenant="bronze")
    assert ei.value.tier == "class_budget"
    assert ei.value.tenant == "bronze"
    # gold still admits at this depth
    pending.append(fleet.submit(x, tenant="gold"))
    st = fleet.tenant_stats()
    assert st["bronze"]["shed"] == 1 and st["gold"]["shed"] == 0
    fleet.start()                      # drain what we queued
    for p in pending:
        np.asarray(p.result(10.0))
    fleet.stop()


def test_deadline_floor_lifts_short_deadlines():
    """An SLO deadline floor lifts a shorter requested deadline (the
    class's latency promise is also its minimum patience)."""
    fleet = _fleet()
    fleet.add_tenant("floored", factory=_scale_factory,
                     slo=SLOClass("floored", deadline_floor_ms=500.0))
    fleet.start()
    x = np.ones(2, np.float32)
    resp = fleet.submit(x, tenant="floored", deadline_ms=1.0)
    # floor=500ms: a 1ms request deadline would have expired at
    # dequeue on any busy box; the floor makes it servable
    np.asarray(resp.result(10.0))
    fleet.stop()


def test_unknown_tenant_and_tenantless_submit_are_structured():
    fleet = _fleet()
    fleet.add_tenant("only", factory=_scale_factory)
    fleet.start()
    x = np.ones(2, np.float32)
    with pytest.raises(RequestError) as ei:
        fleet.predict(x, tenant="ghost")
    assert "ghost" in str(ei.value) and ei.value.tenant == "ghost"
    with pytest.raises(RequestError):
        fleet.predict(x)               # fleet requests must name one
    fleet.stop()


# -- weight paging -----------------------------------------------------------

def test_weight_paging_lru_respects_hot_bound_and_values(journal_file):
    """Three tenants, two hot slots: the LRU pages the stalest tenant
    to host RAM (predictors dropped, journaled with cost), page-in
    restores bit-exact parameters, and the hot set never exceeds the
    bound."""
    fleet = _fleet(max_hot_tenants=2)
    vals = {"a": 3.0, "b": 5.0, "c": 7.0}
    for name, v in vals.items():
        def factory(v=v):
            net = Scale()
            net.initialize()
            net.w.set_data(nd.array(np.asarray([v], np.float32)))
            return net
        fleet.add_tenant(name, factory=factory)
    fleet.start()
    x = np.ones(2, np.float32)
    for _ in range(3):
        for name, v in vals.items():
            out = np.asarray(fleet.predict(x, tenant=name))
            assert np.array_equal(out, x * np.float32(v)), name
    fleet.stop()
    st = fleet.tenant_stats()
    assert sum(1 for r in st.values() if r["hot"]) <= 2
    assert sum(r["page_outs"] for r in st.values()) >= 3
    pages = _records(journal_file, "tenant_page_in")
    assert pages and all("cost_ms" in r for r in pages)
    assert all(len(r["hot"]) <= 2 for r in pages)
    outs = _records(journal_file, "tenant_page_out")
    assert outs and all(r["n_params"] == 1 for r in outs)


def test_tenant_hot_add_remove_under_traffic(journal_file):
    """Tenants join and leave a RUNNING fleet: the new tenant serves
    immediately, the removed tenant's queued work resolves structurally
    and its executables are dropped."""
    fleet = _fleet()
    fleet.add_tenant("stay", factory=_scale_factory)
    fleet.start()
    x = np.ones(2, np.float32)
    fleet.predict(x, tenant="stay")
    fleet.add_tenant("late", factory=_scale_factory)   # hot add
    assert np.array_equal(np.asarray(fleet.predict(x, tenant="late")),
                          x)
    fleet.remove_tenant("late")
    with pytest.raises(RequestError) as ei:
        fleet.predict(x, tenant="late")
    assert ei.value.tenant == "late"
    fleet.predict(x, tenant="stay")    # survivor unaffected
    fleet.stop()
    kinds = {r["kind"] for r in _records(journal_file)}
    assert "tenant_add" in kinds and "tenant_remove" in kinds


# -- observability + router integration --------------------------------------

def test_fleet_metrics_text_tenant_families():
    fleet = _fleet()
    fleet.add_tenant("m0", factory=_scale_factory)
    fleet.add_tenant("m1", factory=_scale_factory, slo="silver")
    fleet.start()
    x = np.ones(2, np.float32)
    fleet.predict(x, tenant="m0")
    text = fleet.metrics_text()
    fleet.stop()
    assert 'mxnet_tpu_serving_tenant_events{tenant="m0",' \
           'event="served"} 1' in text
    assert 'mxnet_tpu_serving_tenant_state{tenant="m1"} 0' in text
    assert 'mxnet_tpu_serving_tenant_latency_ms{tenant="m0",' \
           'quantile="p99"}' in text


def test_router_places_tenant_aware_over_fleet_pool(tmp_path,
                                                    journal_file):
    """A pool of fleet replicas advertises served tenants in the
    heartbeat beacon; the router routes a tenant request only to a
    replica serving that tenant (and raises structured no-capacity for
    a tenant nobody serves)."""
    from mxnet_tpu.serving import PoolConfig, ReplicaPool, Router

    def fleet_factory(names):
        def factory():
            f = _fleet()
            for n in names:
                f.add_tenant(n, factory=_scale_factory)
            return f
        return factory

    pool = ReplicaPool(str(tmp_path / "pool"),
                       PoolConfig(heartbeat_s=0.1, deadline_s=0.6))
    pool.add_local("r0", fleet_factory(["alpha"]))
    pool.add_local("r1", fleet_factory(["beta"]))
    pool.start()
    router = Router(pool)
    try:
        x = np.ones(2, np.float32)
        for _ in range(4):
            resp = router.call(x, tenant="alpha")
            assert resp.replica == "r0"
            resp = router.call(x, tenant="beta")
            assert resp.replica == "r1"
        with pytest.raises(ServerOverloaded) as ei:
            router.call(x, tenant="nobody", deadline_ms=500)
        assert ei.value.tier == "no_capacity"
        assert ei.value.tenant == "nobody"
        st = router.stats()
        assert st["tenants"]["alpha"]["served"] == 4
        assert st["tenants"]["nobody"]["failures"] == 1
        assert "mxnet_tpu_router_tenant_events" in router.metrics_text()
    finally:
        router.stop()
        pool.stop()


def test_proc_worker_fleet_mode_serves_tenants_and_beacons(tmp_path):
    """A REAL subprocess worker in --tenants mode: requests carry the
    tenant header, failures come back tenant-labeled, and the beacon
    advertises the served tenants."""
    from mxnet_tpu.serving import PoolConfig, ReplicaPool
    root = str(tmp_path / "ckpt_a")
    _commit_scale(root, 7, 4.0)
    pool = ReplicaPool(str(tmp_path / "pool"),
                       PoolConfig(heartbeat_s=0.25, deadline_s=2.0))
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO,
           "MXNET_TPU_JOURNAL": "off"}
    env.pop("XLA_FLAGS", None)
    pool.add_proc("w0", {"--tenants": f"a=scale@{root},b=scale",
                         "--reload-poll-s": "0.2"}, env=env)
    try:
        pool.start()
        view = pool.view()[0]
        assert set(view.tenants) == {"a", "b"}
        rep = pool.replicas["w0"]
        x = np.ones(3, np.float32)
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:     # reload lands async
            out, meta = rep.predict(x, 2000, tenant="a")
            if meta["params_step"] == 7:
                break
            time.sleep(0.2)
        assert np.array_equal(out, x * np.float32(4.0))
        assert meta["params_step"] == 7
        out, meta = rep.predict(x, 2000, tenant="b")
        assert np.array_equal(out, x)          # initializer weights
        with pytest.raises(RequestError) as ei:
            rep.predict(x, 2000, tenant="ghost")
        assert ei.value.tenant == "ghost"
    finally:
        pool.stop()


def test_tenant_bench_cli_emits_artifact(tmp_path):
    """``python -m mxnet_tpu.serving bench --tenants 2`` emits the one
    JSON line + BENCH_serving_tenants artifact with per-tenant p99 and
    quarantine/shed counters and the observability snapshot."""
    import subprocess
    import sys
    artifact = str(tmp_path / "BENCH_serving_tenants.json")
    out = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.serving", "bench",
         "--seconds", "1", "--clients", "2", "--dim", "8",
         "--tenants", "2", "--out", artifact],
        cwd=REPO, capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "MXNET_TPU_JOURNAL": "off"})
    assert out.returncode == 0, out.stderr[-800:]
    line = [l for l in out.stdout.splitlines()
            if l.startswith("{") and '"metric"' in l][-1]
    doc = json.loads(line)
    assert doc["metric"] == "serving_tenant_requests_per_sec"
    assert doc["value"] and doc["value"] > 0
    assert doc["tenants"].keys() == {"t0", "t1"}
    for row in doc["tenants"].values():
        assert row["served"] > 0 and "p99_ms" in row
        assert "quarantines" in row and "shed" in row
    assert "metrics" in doc["observability"]
    with open(artifact, encoding="utf-8") as f:
        assert json.load(f)["metric"] == \
            "serving_tenant_requests_per_sec"

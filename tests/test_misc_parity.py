"""Misc parity: AttrScope, NameManager/Prefix, gradient compression,
BucketingModule+RNN bucketing end-to-end (Sockeye path, SURVEY §3.3)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import io, sym
from mxnet_tpu.attribute import AttrScope
from mxnet_tpu.name import Prefix


def test_attr_scope_attaches():
    with AttrScope(ctx_group="dev1", mood="testy"):
        a = sym.var("a")
        fc = sym.FullyConnected(a, num_hidden=4, name="fc")
    assert fc.attr("__ctx_group__") == "dev1"
    fc2 = sym.FullyConnected(sym.var("b"), num_hidden=4, name="fc2")
    assert fc2.attr("__ctx_group__") is None


def test_attr_scope_still_evaluates():
    with AttrScope(ctx_group="dev1"):
        a = sym.var("a")
        out = sym.FullyConnected(a, num_hidden=3, name="fq")
    exe = out.simple_bind(a=(2, 5))
    exe.forward()          # scoped attr must not leak into op kwargs


def test_name_prefix_scope():
    with Prefix("mynet_"):
        a = sym.var("x")
        fc = sym.FullyConnected(a, num_hidden=2)
    assert fc._node.name.startswith("mynet_")


def test_gradient_compression_2bit():
    from mxnet_tpu.gradient_compression import GradientCompression
    gc = GradientCompression(type="2bit", threshold=0.5)
    g = mx.nd.array([0.9, -0.7, 0.1, -0.2])._data
    q1 = np.asarray(gc.compress("k", g))
    np.testing.assert_allclose(q1, [0.5, -0.5, 0.0, 0.0])
    # error feedback: residual [0.4, -0.2, 0.1, -0.2] adds to next grad
    q2 = np.asarray(gc.compress("k", g))
    np.testing.assert_allclose(q2, [0.5, -0.5, 0.0, 0.0])
    # accumulated residual eventually pushes small values over threshold
    q3 = np.asarray(gc.compress("k", g))
    assert q3[2] == 0.0 and q3[3] == -0.5


def test_kvstore_compression_path():
    kv = mx.kv.create("device")
    kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    kv.init(0, mx.nd.zeros((3,)))
    kv.push(0, mx.nd.array([1.0, 0.2, -0.9]))
    out = mx.nd.zeros((3,))
    kv.pull(0, out=out)
    np.testing.assert_allclose(out.asnumpy(), [0.5, 0.0, -0.5])


def test_bucketing_module_rnn_shared_params():
    """The Sockeye-style path: per-seq-len buckets over one fused RNN,
    parameters shared across buckets (SURVEY §3.3 switch_bucket)."""
    H, V = 8, 20

    def sym_gen(seq_len):
        data = sym.var("data")                       # (T, N)
        embed = sym.Embedding(data, input_dim=V, output_dim=H,
                              name="embed")
        rnn = sym.RNN(embed, state_size=H, num_layers=1, mode="lstm",
                      name="lstm")
        last = sym.SequenceLast(rnn)
        fc = sym.FullyConnected(last, num_hidden=V, name="fc")
        return sym.SoftmaxOutput(fc, name="softmax"), ("data",), \
            ("softmax_label",)

    mod = mx.mod.BucketingModule(sym_gen, default_bucket_key=10)

    def batch(T):
        return io.DataBatch(
            data=[mx.nd.array(np.random.randint(0, V, (T, 4)))],
            label=[mx.nd.zeros((4,))], bucket_key=T,
            provide_data=[io.DataDesc("data", (T, 4))],
            provide_label=[io.DataDesc("softmax_label", (4,))])

    mod.bind(batch(10).provide_data, batch(10).provide_label)
    mod.init_params(mx.init.Uniform(0.1))
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1})
    for T in (10, 5, 10, 7):
        b = batch(T)
        mod.forward_backward(b)
        mod.update()
    # all buckets must share the SAME weight arrays (reference contract)
    default = mod._buckets[10]
    for key, m in mod._buckets.items():
        assert m._exec.arg_dict["lstm_parameters"] is \
            default._exec.arg_dict["lstm_parameters"], key

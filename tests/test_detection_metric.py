"""VOC mAP metrics (ref ecosystem: gluoncv.utils.metrics.voc_detection —
the evaluation half of the SSD/Faster-RCNN configs). AP values asserted
against hand-computed precision/recall integrals."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu.metric_det import VOC07MApMetric, VOCMApMetric


def _boxes():
    # one image, one class: 2 ground truths, 3 ranked detections.
    # det order by score: hit, miss, hit ->
    #   rank1 TP (p=1, r=0.5), rank2 FP (p=.5), rank3 TP (p=2/3, r=1.0)
    label = np.array([[0, 0, 0, 10, 10, 0],
                      [0, 20, 20, 30, 30, 0]], np.float32)
    pred = np.array([
        [0, 0.9, 0, 0, 10, 10],       # TP (IoU 1.0)
        [0, 0.8, 50, 50, 60, 60],     # FP (no overlap)
        [0, 0.7, 21, 21, 30, 30],     # TP (IoU ~0.8 with gt2)
    ], np.float32)
    return label, pred


def test_voc_map_all_points():
    m = VOCMApMetric(iou_thresh=0.5)
    label, pred = _boxes()
    m.update([label], [pred])
    name, value = m.get()
    # all-points AP: envelope p(r<=0.5)=1.0, p(0.5<r<=1.0)=2/3
    want = 0.5 * 1.0 + 0.5 * (2.0 / 3.0)
    assert name == "mAP"
    assert abs(value - want) < 1e-6, (value, want)


def test_voc07_11point():
    m = VOC07MApMetric(iou_thresh=0.5)
    label, pred = _boxes()
    m.update([label], [pred])
    _, value = m.get()
    # 11-point: max precision at r>=t is 1.0 for t in {0,.1..,.5} (6 pts)
    # and 2/3 for t in {.6,...,1.0} (5 pts)
    want = (6 * 1.0 + 5 * (2.0 / 3.0)) / 11.0
    assert abs(value - want) < 1e-6, (value, want)


def test_voc_map_multiclass_and_registry():
    m = mx.metric.create("voc07mapmetric",
                         class_names=["cat", "dog"])
    label = np.array([[0, 0, 0, 10, 10, 0],
                      [1, 20, 20, 30, 30, 0]], np.float32)
    pred = np.array([
        [0, 0.9, 0, 0, 10, 10],       # cat TP
        [1, 0.8, 40, 40, 50, 50],     # dog FP
    ], np.float32)
    m.update([label], [pred])
    names, values = m.get()
    per = dict(zip(names, values))
    assert abs(per["cat"] - 1.0) < 1e-6
    assert per["dog"] == 0.0
    assert abs(per["mAP"] - 0.5) < 1e-6
    # every configured class gets a row even if never observed
    m2 = mx.metric.VOCMApMetric(class_names=["cat", "dog", "bird"])
    m2.update([label], [pred])
    names2, values2 = m2.get()
    per2 = dict(zip(names2, values2))
    assert "bird" in per2 and np.isnan(per2["bird"])
    assert abs(per2["mAP"] - 0.5) < 1e-6   # NaN excluded from the mean


def test_voc_map_difficult_and_duplicates():
    m = VOCMApMetric(iou_thresh=0.5)
    # difficult GT: matching it is neither TP nor FP; duplicate match of
    # an already-taken GT counts FP (VOC protocol)
    label = np.array([[0, 0, 0, 10, 10, 1],        # difficult
                      [0, 20, 20, 30, 30, 0]], np.float32)
    pred = np.array([
        [0, 0.9, 0, 0, 10, 10],       # matches difficult: ignored
        [0, 0.8, 20, 20, 30, 30],     # TP
        [0, 0.7, 20, 20, 30, 30],     # duplicate -> FP
    ], np.float32)
    m.update([label], [pred])
    _, value = m.get()
    # npos=1 (difficult excluded); ranked: ignored, TP (p=1, r=1), FP
    assert abs(value - 1.0) < 1e-6, value


def test_voc_map_duplicates_on_difficult_ignored():
    """VOC devkit protocol: EVERY detection matching a difficult GT is
    ignored (not just the first), and difficult GTs are never 'taken'."""
    m = VOCMApMetric(iou_thresh=0.5)
    label = np.array([[0, 0, 0, 10, 10, 1],        # difficult
                      [0, 20, 20, 30, 30, 0]], np.float32)
    pred = np.array([
        [0, 0.9, 0, 0, 10, 10],       # matches difficult: ignored
        [0, 0.85, 0, 0, 10, 10],      # ALSO matches difficult: ignored
        [0, 0.8, 20, 20, 30, 30],     # TP on the real GT
    ], np.float32)
    m.update([label], [pred])
    _, value = m.get()
    assert abs(value - 1.0) < 1e-6, value


def test_voc_map_prediction_only_class_excluded():
    """A class with zero (non-difficult) ground truths has undefined AP
    and must not drag the mean down (gluoncv nanmean semantics)."""
    m = VOCMApMetric(iou_thresh=0.5)
    label = np.array([[0, 0, 0, 10, 10, 0]], np.float32)
    pred = np.array([
        [0, 0.9, 0, 0, 10, 10],       # class 0 TP
        [3, 0.8, 50, 50, 60, 60],     # spurious class-3 detection
    ], np.float32)
    m.update([label], [pred])
    _, value = m.get()
    assert abs(value - 1.0) < 1e-6, value


def test_map_iou_ladder_coco_style():
    """iou_thresh as a list averages AP over thresholds (the COCO-style
    mAP@[.5:.95] headline). A detection at IoU ~0.68 with its GT is TP
    at the thresholds below 0.68 and FP above -> AP = fraction of
    thresholds it clears."""
    ladder = [0.5, 0.6, 0.7, 0.8]
    m = VOCMApMetric(iou_thresh=ladder)
    label = np.array([[0, 0, 0, 10, 10, 0]], np.float32)
    # shifted box: inter = 8*8=64? use x-shift 2: inter=8*10=80,
    # union=2*100-80=120 -> IoU=2/3: clears 0.5 and 0.6 only
    pred = np.array([[0, 0.9, 2, 0, 12, 10]], np.float32)
    m.update([label], [pred])
    _, value = m.get()
    assert abs(value - 2.0 / 4.0) < 1e-6, value


def test_voc_map_batched_ndarray_inputs():
    m = VOCMApMetric()
    label, pred = _boxes()
    # batch dim + NDArray inputs + padding rows (cls = -1)
    pad_l = np.full((1, 1, 6), -1, np.float32)
    pad_p = np.full((1, 1, 6), -1, np.float32)
    lb = np.concatenate([label[None], pad_l], axis=1)
    pb = np.concatenate([pred[None], pad_p], axis=1)
    m.update(mx.nd.array(lb), mx.nd.array(pb))
    _, v1 = m.get()
    m2 = VOCMApMetric()
    m2.update([label], [pred])
    _, v2 = m2.get()
    assert abs(v1 - v2) < 1e-9

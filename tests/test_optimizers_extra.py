"""Extended optimizer coverage (ref: tests/python/unittest/
test_optimizer.py): every registered optimizer must reduce a quadratic."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon


@pytest.mark.parametrize("name,params,steps", [
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9}, 150),
    ("nag", {"learning_rate": 0.1, "momentum": 0.9}, 150),
    ("adam", {"learning_rate": 0.05}, 150),
    ("adamw", {"learning_rate": 0.05}, 150),
    ("nadam", {"learning_rate": 0.05}, 150),
    ("adadelta", {}, 1200),        # no lr: step grows adaptively
    ("adagrad", {"learning_rate": 0.3}, 150),
    ("rmsprop", {"learning_rate": 0.02}, 150),
    ("ftrl", {"learning_rate": 0.3}, 150),
    ("ftml", {"learning_rate": 0.1}, 150),
    ("dcasgd", {"learning_rate": 0.1}, 150),
    ("signum", {"learning_rate": 0.05}, 150),   # fixed ±lr steps
    ("lamb", {"learning_rate": 0.05}, 150),
])
def test_optimizer_minimizes_quadratic(name, params, steps):
    target = np.array([1.5, -2.0, 0.5, 3.0], dtype=np.float32)
    w = gluon.Parameter("w", shape=(4,))
    w.initialize(init="zeros")
    trainer = gluon.Trainer([w], name, dict(params))
    for step in range(steps):
        with autograd.record():
            diff = w.data() - mx.nd.array(target)
            loss = (diff * diff).sum()
        loss.backward()
        trainer.step(1)
    final = float(((w.data().asnumpy() - target) ** 2).sum())
    assert final < 0.35, f"{name}: final sq-dist {final}"


def test_updater_state_roundtrip_new_optimizers():
    from mxnet_tpu import optimizer as opt
    o = opt.create("nadam", learning_rate=0.01)
    upd = opt.get_updater(o)
    w = mx.nd.ones((3,))
    g = mx.nd.ones((3,)) * 0.1
    upd(0, g, w)
    blob = upd.get_states(dump_optimizer=True)
    upd2 = opt.get_updater(opt.create("nadam"))
    upd2.set_states(blob)
    assert 0 in upd2.states


@pytest.mark.parametrize("name,params,tol", [
    ("adadelta", {}, 5e-5),
    ("nadam", {"learning_rate": 1e-3}, 2e-3),   # per-param schedule: the
    # eager reference mutates its m_schedule once per parameter per step
    # (upstream quirk) — see the functional rule's note in sharded.py
    ("dcasgd", {"learning_rate": 0.05, "momentum": 0.9}, 5e-5),
    ("dcasgd", {"learning_rate": 0.05}, 5e-5),
    ("ftml", {"learning_rate": 2e-3}, 5e-5),
])
def test_sharded_functional_rule_matches_eager(name, params, tol):
    """Round-3 completeness: every registered optimizer has a functional
    rule in ShardedTrainer that tracks the eager Trainer trajectory."""
    from mxnet_tpu import parallel
    rng = np.random.RandomState(0)
    x = rng.randn(16, 6).astype(np.float32)
    y = rng.randn(16, 3).astype(np.float32)
    w0 = rng.randn(3, 6).astype(np.float32) * 0.3

    def make_net():
        net = gluon.nn.Dense(3, in_units=6)
        net.initialize()
        net.weight.set_data(mx.nd.array(w0))
        net.bias.set_data(mx.nd.zeros((3,)))
        return net

    lf = gluon.loss.L2Loss()
    n1 = make_net()
    tr_e = gluon.Trainer(n1.collect_params(), name, dict(params))
    for _ in range(4):
        with autograd.record():
            loss = lf(n1(mx.nd.array(x)), mx.nd.array(y))
        loss.backward()
        tr_e.step(16)

    n2 = make_net()
    tr_s = parallel.ShardedTrainer(
        n2, lf, name, dict(params),
        mesh=parallel.make_mesh({"data": 8}))
    for _ in range(4):
        tr_s.step(x, y)
    d = np.abs(n1.weight.data().asnumpy()
               - n2.weight.data().asnumpy()).max()
    assert d < tol, (name, d)

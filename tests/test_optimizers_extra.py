"""Extended optimizer coverage (ref: tests/python/unittest/
test_optimizer.py): every registered optimizer must reduce a quadratic."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon


@pytest.mark.parametrize("name,params,steps", [
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9}, 150),
    ("nag", {"learning_rate": 0.1, "momentum": 0.9}, 150),
    ("adam", {"learning_rate": 0.05}, 150),
    ("adamw", {"learning_rate": 0.05}, 150),
    ("nadam", {"learning_rate": 0.05}, 150),
    ("adadelta", {}, 1200),        # no lr: step grows adaptively
    ("adagrad", {"learning_rate": 0.3}, 150),
    ("rmsprop", {"learning_rate": 0.02}, 150),
    ("ftrl", {"learning_rate": 0.3}, 150),
    ("ftml", {"learning_rate": 0.1}, 150),
    ("dcasgd", {"learning_rate": 0.1}, 150),
    ("signum", {"learning_rate": 0.05}, 150),   # fixed ±lr steps
    ("lamb", {"learning_rate": 0.05}, 150),
])
def test_optimizer_minimizes_quadratic(name, params, steps):
    target = np.array([1.5, -2.0, 0.5, 3.0], dtype=np.float32)
    w = gluon.Parameter("w", shape=(4,))
    w.initialize(init="zeros")
    trainer = gluon.Trainer([w], name, dict(params))
    for step in range(steps):
        with autograd.record():
            diff = w.data() - mx.nd.array(target)
            loss = (diff * diff).sum()
        loss.backward()
        trainer.step(1)
    final = float(((w.data().asnumpy() - target) ** 2).sum())
    assert final < 0.35, f"{name}: final sq-dist {final}"


def test_updater_state_roundtrip_new_optimizers():
    from mxnet_tpu import optimizer as opt
    o = opt.create("nadam", learning_rate=0.01)
    upd = opt.get_updater(o)
    w = mx.nd.ones((3,))
    g = mx.nd.ones((3,)) * 0.1
    upd(0, g, w)
    blob = upd.get_states(dump_optimizer=True)
    upd2 = opt.get_updater(opt.create("nadam"))
    upd2.set_states(blob)
    assert 0 in upd2.states

"""Build backend hook: compile the native runtime into the wheel.

`pip install -e .` keeps the lazy in-tree build (mxnet_tpu/_native.py);
`pip wheel .` / `pip install .` runs this custom build_py step so the
binary wheel ships `mxnet_tpu/libmxtpu.so` (recordio + engine + predict,
ref: the reference's libmxnet.so wheel payload). Falls back to a pure-
Python wheel when no C++ toolchain is present — every native component
has a Python fallback.
"""
import os
import shutil
import subprocess

from setuptools import setup
from setuptools.command.build_py import build_py
from setuptools.dist import Distribution

HERE = os.path.dirname(os.path.abspath(__file__))
NATIVE = os.path.join(HERE, "native")
SOURCES = ["recordio.cc", "engine.cc", "predict.cc"]


class build_py_with_native(build_py):
    def run(self):
        super().run()
        srcs = [os.path.join(NATIVE, s) for s in SOURCES]
        if not all(os.path.exists(s) for s in srcs):
            return
        gxx = shutil.which("g++") or shutil.which("c++")
        if gxx is None:
            print("warning: no C++ compiler — building a pure-Python "
                  "wheel (native runtime will lazy-build at first use)")
            return
        out = os.path.join(self.build_lib, "mxnet_tpu", "libmxtpu.so")
        os.makedirs(os.path.dirname(out), exist_ok=True)
        cmd = [gxx, "-O2", "-std=c++17", "-fPIC", "-shared", "-pthread",
               "-o", out] + srcs
        print("building native runtime:", " ".join(cmd))
        subprocess.run(cmd, check=True, timeout=600)


class _BinaryDistribution(Distribution):
    """Platform-tag the wheel: it carries a compiled libmxtpu.so."""

    def has_ext_modules(self):
        return True


setup(cmdclass={"build_py": build_py_with_native},
      distclass=_BinaryDistribution,
      package_data={"mxnet_tpu": ["libmxtpu.so"]})

#!/usr/bin/env python
"""DCGAN (ref: example/gan/dcgan.py — the reference zoo's adversarial
family): Conv2DTranspose generator vs Conv2D discriminator, alternating
adam steps, trained here on a synthetic structured-image distribution so
the example is self-contained and CI-gateable.

TPU notes: both players train through ShardedTrainer-style fused steps?
No — GANs alternate two optimizers over two parameter sets with the
OTHER player frozen, which maps naturally onto two eager autograd loops
over hybridized blocks (each forward is one compiled program); the
batch-level compute dominates, so the two-dispatch structure costs ~0 on
real shapes.
"""
from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

if "jax" not in sys.modules and not os.environ.get("JAX_PLATFORMS") and \
        "host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["JAX_PLATFORMS"] = "cpu"

import mxnet_tpu as mx                                   # noqa: E402
from mxnet_tpu import autograd, gluon                    # noqa: E402


def build_generator(ngf=16, nz=16):
    net = gluon.nn.HybridSequential()
    net.add(
        gluon.nn.Dense(ngf * 2 * 4 * 4, use_bias=False),
        gluon.nn.HybridLambda(lambda F, x: F.reshape(x, (-1, 32, 4, 4))),
        gluon.nn.Conv2DTranspose(ngf, 4, strides=2, padding=1,
                                 use_bias=False),        # 8x8
        gluon.nn.Activation("relu"),
        gluon.nn.Conv2DTranspose(1, 4, strides=2, padding=1,
                                 use_bias=False),        # 16x16
        gluon.nn.Activation("tanh"))
    return net


def build_discriminator(ndf=16):
    net = gluon.nn.HybridSequential()
    net.add(
        gluon.nn.Conv2D(ndf, 4, strides=2, padding=1),   # 8x8
        gluon.nn.LeakyReLU(0.2),
        gluon.nn.Conv2D(ndf * 2, 4, strides=2, padding=1),  # 4x4
        gluon.nn.LeakyReLU(0.2),
        gluon.nn.Dense(1))
    return net


def real_batch(rng, n, size=16):
    """Structured 'real' images: soft blobs at random positions — a
    distribution with spatial statistics a generator must actually match
    (pure noise would let any G pass)."""
    yy, xx = np.mgrid[0:size, 0:size].astype(np.float32)
    cx = rng.uniform(4, size - 4, (n, 1, 1))
    cy = rng.uniform(4, size - 4, (n, 1, 1))
    r2 = (xx[None] - cx) ** 2 + (yy[None] - cy) ** 2
    img = np.exp(-r2 / 8.0) * 2.0 - 1.0                 # in [-1, 1)
    return img[:, None].astype(np.float32)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--steps", type=int, default=200)
    ap.add_argument("--nz", type=int, default=16)
    ap.add_argument("--lr", type=float, default=2e-3)
    args = ap.parse_args()

    mx.random.seed(0)
    rng = np.random.RandomState(0)
    gen, dis = build_generator(nz=args.nz), build_discriminator()
    gen.initialize(mx.init.Normal(0.05))
    dis.initialize(mx.init.Normal(0.05))
    gen.hybridize()
    dis.hybridize()
    gt = gluon.Trainer(gen.collect_params(), "adam",
                       {"learning_rate": args.lr, "beta1": 0.5})
    dt = gluon.Trainer(dis.collect_params(), "adam",
                       {"learning_rate": args.lr, "beta1": 0.5})
    bce = gluon.loss.SigmoidBinaryCrossEntropyLoss()
    ones = mx.nd.ones((args.batch,))
    zeros = mx.nd.zeros((args.batch,))

    t0 = time.time()
    g_last = d_last = None
    for step in range(args.steps):
        real = mx.nd.array(real_batch(rng, args.batch))
        z = mx.nd.array(rng.randn(args.batch, args.nz).astype(np.float32))
        # D step: real -> 1, fake -> 0 (G frozen: fake is a constant here)
        fake = gen(z).detach()
        with autograd.record():
            d_loss = (bce(dis(real).reshape(-1), ones)
                      + bce(dis(fake).reshape(-1), zeros)).mean()
        d_loss.backward()
        dt.step(args.batch)
        # G step: fool D (D frozen: its params get no trainer.step)
        with autograd.record():
            g_loss = bce(dis(gen(z)).reshape(-1), ones).mean()
        g_loss.backward()
        gt.step(args.batch)
        g_last, d_last = float(g_loss.asscalar()), float(d_loss.asscalar())
        if step % 50 == 0:
            print(f"step {step:4d}  d_loss {d_last:.3f}  g_loss {g_last:.3f}")

    # gate: the generated pixel-mean map matches the data's radial
    # structure far better than the init did (GAN losses oscillate, so
    # gate on sample statistics instead)
    z = mx.nd.array(rng.randn(256, args.nz).astype(np.float32))
    fake_mean = gen(z).asnumpy().mean(axis=0)[0]
    real_mean = real_batch(rng, 256).mean(axis=0)[0]
    err = float(np.abs(fake_mean - real_mean).mean())
    print(f"pixel-mean-map L1 {err:.4f}  d_loss {d_last:.3f} "
          f"g_loss {g_last:.3f}  {time.time()-t0:.1f}s")
    return {"mean_map_l1": err, "d_loss": d_last, "g_loss": g_last}


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Config #1 — LeNet-5 on MNIST (ref: example/image-classification/
train_mnist.py). Both worlds: Gluon (default) and symbolic Module
(--module). Uses real MNIST files under --data-dir when present, else a
synthetic stand-in so the script always runs.
"""
from __future__ import annotations

import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, io


def lenet_gluon():
    net = gluon.nn.HybridSequential()
    with net.name_scope():
        net.add(gluon.nn.Conv2D(20, 5, activation="tanh"),
                gluon.nn.MaxPool2D(2, 2),
                gluon.nn.Conv2D(50, 5, activation="tanh"),
                gluon.nn.MaxPool2D(2, 2),
                gluon.nn.Flatten(),
                gluon.nn.Dense(500, activation="tanh"),
                gluon.nn.Dense(10))
    return net


def lenet_symbol():
    from mxnet_tpu import sym
    data = sym.var("data")
    c1 = sym.Activation(sym.Convolution(data, kernel=(5, 5), num_filter=20),
                        act_type="tanh")
    p1 = sym.Pooling(c1, pool_type="max", kernel=(2, 2), stride=(2, 2))
    c2 = sym.Activation(sym.Convolution(p1, kernel=(5, 5), num_filter=50),
                        act_type="tanh")
    p2 = sym.Pooling(c2, pool_type="max", kernel=(2, 2), stride=(2, 2))
    f = sym.Flatten(p2)
    fc1 = sym.Activation(sym.FullyConnected(f, num_hidden=500),
                         act_type="tanh")
    fc2 = sym.FullyConnected(fc1, num_hidden=10)
    return sym.SoftmaxOutput(fc2, name="softmax")


def get_iters(args):
    img = os.path.join(args.data_dir, "train-images-idx3-ubyte")
    lbl = os.path.join(args.data_dir, "train-labels-idx1-ubyte")
    if os.path.exists(img) or os.path.exists(img + ".gz"):
        train = io.MNISTIter(image=img, label=lbl,
                             batch_size=args.batch_size)
        timg = os.path.join(args.data_dir, "t10k-images-idx3-ubyte")
        tlbl = os.path.join(args.data_dir, "t10k-labels-idx1-ubyte")
        val = io.MNISTIter(image=timg, label=tlbl,
                           batch_size=args.batch_size, shuffle=False)
        return train, val
    logging.warning("MNIST files not found under %s — synthetic data",
                    args.data_dir)
    rng = np.random.RandomState(0)
    n = 2048
    x = rng.rand(n, 1, 28, 28).astype(np.float32)
    y = rng.randint(0, 10, n).astype(np.float32)
    # make it learnable: brighten a quadrant per class
    for i in range(n):
        c = int(y[i])
        x[i, 0, (c // 4) * 7:(c // 4) * 7 + 7, (c % 4) * 7:(c % 4) * 7 + 7] += 2.0
    split = n - 512
    return (io.NDArrayIter(x[:split], y[:split], args.batch_size,
                           shuffle=True),
            io.NDArrayIter(x[split:], y[split:], args.batch_size))


def train_gluon(args, train, val):
    net = lenet_gluon()
    net.initialize(mx.init.Xavier())
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": args.lr, "momentum": 0.9})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    metric = mx.metric.Accuracy()
    for epoch in range(args.epochs):
        train.reset()
        metric.reset()
        for batch in train:
            x, y = batch.data[0], batch.label[0]
            with autograd.record():
                out = net(x)
                loss = loss_fn(out, y)
            loss.backward()
            trainer.step(x.shape[0])
            metric.update([y], [out])
        logging.info("Epoch[%d] Train-%s=%f", epoch, *metric.get())
        val.reset()
        metric.reset()
        for batch in val:
            metric.update([batch.label[0]], [net(batch.data[0])])
        logging.info("Epoch[%d] Validation-%s=%f", epoch, *metric.get())
    return metric.get()[1]


def train_module(args, train, val):
    mod = mx.mod.Module(lenet_symbol(), context=mx.context.current_context())
    mod.fit(train, eval_data=val, num_epoch=args.epochs, optimizer="sgd",
            optimizer_params={"learning_rate": args.lr, "momentum": 0.9},
            initializer=mx.init.Xavier(),
            batch_end_callback=mx.callback.Speedometer(args.batch_size, 50))
    return mod.score(val, "acc")[0][1]


def main():
    logging.basicConfig(level=logging.INFO)
    p = argparse.ArgumentParser()
    p.add_argument("--data-dir", default=os.path.expanduser(
        "~/.mxnet/datasets/mnist"))
    p.add_argument("--batch-size", type=int, default=128)
    p.add_argument("--epochs", type=int, default=3)
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--module", action="store_true",
                   help="use the symbolic Module API path")
    args = p.parse_args()
    train, val = get_iters(args)
    acc = (train_module if args.module else train_gluon)(args, train, val)
    print(f"final accuracy: {acc:.4f}")


if __name__ == "__main__":
    main()

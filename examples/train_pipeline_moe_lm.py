#!/usr/bin/env python
"""Gluon-level pipeline + expert parallelism (SURVEY §7 P7: pp/ep "exposed
as Gluon-level options"; net-new vs the reference, whose closest tool is
hand ``ctx_group`` placement in example/model-parallel-lstm).

Trains a small transformer LM two ways on one script:
  --mode pp    PipelinedTrainer: [Embedding, N x TransformerEncoderCell,
               Dense head] partitioned onto a pipe x data mesh — no
               hand-written stage closures
  --mode moe   ShardedTrainer over a data x expert mesh with the FFN
               replaced by gluon.contrib.nn.MoEFFN (top-k all-to-all
               dispatch + Switch aux loss, auto-added to the objective)

Synthetic word-LM data; CPU-mesh friendly (the same code drives a real
TPU pod by changing the mesh dict).
"""
from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# default to a virtual 8-device CPU mesh (the tests/conftest.py recipe)
# when nothing chose a platform — the default meshes need 8 devices; a
# real TPU run sets JAX_PLATFORMS/XLA_FLAGS itself and is left alone
if "jax" not in sys.modules and not os.environ.get("JAX_PLATFORMS") and \
        "host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                               " --xla_force_host_platform_device_count=8")

import mxnet_tpu as mx                                    # noqa: E402
from mxnet_tpu import gluon, parallel                     # noqa: E402
from mxnet_tpu.gluon.contrib.nn import MoEFFN             # noqa: E402
from mxnet_tpu.gluon.model_zoo.bert import (              # noqa: E402
    TransformerEncoderCell)
from mxnet_tpu.parallel import PartitionSpec as P         # noqa: E402


def synthetic_batches(vocab, batch, seq, steps, seed=0):
    rng = np.random.RandomState(seed)
    w = rng.randn(vocab, vocab)
    for _ in range(steps):
        toks = rng.randint(0, vocab, (batch, seq))
        yield toks, w[toks].argmax(-1)


def run_pp(args):
    mesh = parallel.make_mesh({"pipe": args.pipe, "data": args.data})
    mx.random.seed(1)
    emb = gluon.nn.Embedding(args.vocab, args.units)
    body = [TransformerEncoderCell(args.units, 2 * args.units, 4,
                                   dropout=0.0)
            for _ in range(args.layers)]
    head = gluon.nn.Dense(args.vocab, flatten=False)
    for b in [emb] + body + [head]:
        b.initialize()
    trainer = parallel.PipelinedTrainer(
        emb, body, head, gluon.loss.SoftmaxCrossEntropyLoss(), "adam",
        {"learning_rate": args.lr}, mesh=mesh,
        num_microbatches=args.microbatches,
        num_virtual_stages=args.layers // args.pipe)
    info = parallel.pipeline_schedule_info(
        args.pipe, args.microbatches, args.layers // args.pipe)
    print(f"pipeline schedule: {info}")
    return trainer, mesh


def run_moe(args):
    mesh = parallel.make_mesh({"data": args.data, "expert": args.experts})

    class MoELM(gluon.HybridBlock):
        def __init__(self):
            super().__init__()
            with self.name_scope():
                self.emb = gluon.nn.Embedding(args.vocab, args.units)
                self.cell = TransformerEncoderCell(args.units,
                                                   2 * args.units, 4,
                                                   dropout=0.0)
                self.moe = MoEFFN(units=args.units,
                                  hidden_size=2 * args.units,
                                  num_experts=args.experts, k=2,
                                  capacity_factor=2.0)
                self.head = gluon.nn.Dense(args.vocab, flatten=False)

        def hybrid_forward(self, F, x):
            h = self.cell(self.emb(x))
            return self.head(h + self.moe(h))

    mx.random.seed(1)
    net = MoELM()
    net.initialize()
    trainer = parallel.ShardedTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), "adam",
        {"learning_rate": args.lr}, mesh=mesh,
        param_rules=[(r".*expert_.*", P("expert"))])
    return trainer, mesh


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--mode", choices=["pp", "moe"], default="pp")
    ap.add_argument("--vocab", type=int, default=64)
    ap.add_argument("--units", type=int, default=32)
    ap.add_argument("--layers", type=int, default=4)
    ap.add_argument("--pipe", type=int, default=2)
    ap.add_argument("--data", type=int, default=None,
                    help="data-parallel ranks (default: 4 for pp, 2 for "
                         "moe — both fill the 8-device default mesh)")
    ap.add_argument("--experts", type=int, default=4)
    ap.add_argument("--microbatches", type=int, default=4)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--seq", type=int, default=16)
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--lr", type=float, default=2e-3)
    args = ap.parse_args()
    if args.data is None:
        args.data = 4 if args.mode == "pp" else 2

    trainer, mesh = run_pp(args) if args.mode == "pp" else run_moe(args)
    print(f"mode={args.mode} mesh={dict(zip(mesh.axis_names, mesh.shape.values()))}")
    t0, first = time.time(), None
    for i, (x, y) in enumerate(synthetic_batches(
            args.vocab, args.batch, args.seq, args.steps)):
        loss = float(trainer.step(x, y).asscalar())
        first = first if first is not None else loss
        if i % 10 == 0:
            print(f"step {i:3d}  loss {loss:.4f}")
    print(f"loss {first:.4f} -> {loss:.4f} in {time.time()-t0:.1f}s")
    assert loss < first, "loss did not decrease"


if __name__ == "__main__":
    main()

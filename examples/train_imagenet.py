#!/usr/bin/env python
"""Config #2 — ResNet-50 classification at scale (ref: example/
image-classification/train_imagenet.py).

The whole train step — forward, loss, backward, gradient all-reduce over
the `data` mesh axis, SGD update — is ONE jitted SPMD program
(parallel.ShardedTrainer). Feed real data with --rec (an ImageRecordIter
pack made by tools/im2rec.py); otherwise synthetic batches measure the
compute path like the reference's benchmark_score.py.
"""
from __future__ import annotations

import argparse
import logging
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import mxnet_tpu as mx
from mxnet_tpu import gluon, io, parallel
from mxnet_tpu.gluon.model_zoo import vision


def main():
    logging.basicConfig(level=logging.INFO)
    p = argparse.ArgumentParser()
    p.add_argument("--network", default="resnet50_v1")
    p.add_argument("--batch-size", type=int, default=256,
                   help="global batch size")
    p.add_argument("--num-classes", type=int, default=1000)
    p.add_argument("--image-shape", default="3,224,224")
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--wd", type=float, default=1e-4)
    p.add_argument("--epochs", type=int, default=1)
    p.add_argument("--steps-per-epoch", type=int, default=50)
    p.add_argument("--rec", default=None, help="path to .rec pack")
    p.add_argument("--idx", default=None)
    p.add_argument("--bf16", action="store_true", default=True)
    p.add_argument("--no-bf16", dest="bf16", action="store_false")
    p.add_argument("--model-parallel", type=int, default=1,
                   help="tensor-parallel mesh axis size")
    p.add_argument("--checkpoint", default=None,
                   help="prefix for periodic ShardedTrainer checkpoints "
                        "(bit-exact resume incl. optimizer state + RNG)")
    p.add_argument("--checkpoint-every", type=int, default=1,
                   help="epochs between checkpoints (>= 1)")
    p.add_argument("--resume", action="store_true",
                   help="load <prefix>.params/.states before training "
                        "(keep --steps-per-epoch identical to the saved "
                        "run: the resume epoch derives from it)")
    args = p.parse_args()
    if args.checkpoint and args.checkpoint_every < 1:
        p.error("--checkpoint-every must be >= 1")

    import jax
    shape = tuple(int(s) for s in args.image_shape.split(","))
    n_dev = len(jax.devices())
    mesh = parallel.make_mesh({"data": n_dev // args.model_parallel,
                               "model": args.model_parallel})
    net = vision.get_model(args.network, classes=args.num_classes)
    net.initialize(mx.init.Xavier())
    rules = []
    if args.model_parallel > 1:
        from mxnet_tpu.parallel import PartitionSpec as P
        rules = [(r".*dense\d+_weight", P("model", None)),
                 (r".*stage4_.*conv\d+_weight", P("model", None, None,
                                                  None))]
    trainer = parallel.ShardedTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        optimizer_params={"learning_rate": args.lr, "momentum": 0.9,
                          "wd": args.wd},
        mesh=mesh, param_rules=rules,
        compute_dtype="bfloat16" if args.bf16 else None)

    if args.rec:
        data = io.ImageRecordIter(
            path_imgrec=args.rec, path_imgidx=args.idx,
            data_shape=shape, batch_size=args.batch_size, shuffle=True,
            rand_crop=True, rand_mirror=True, resize=256,
            mean_r=123.68, mean_g=116.28, mean_b=103.53,
            std_r=58.4, std_g=57.1, std_b=57.4)
        data = io.PrefetchingIter(data)
    else:
        logging.warning("no --rec given: synthetic data (compute bench)")
        data = None
        rng = np.random.RandomState(0)   # fixed batch: CI gates on loss
        x = rng.randn(args.batch_size, *shape).astype(np.float32)
        y = rng.randint(0, args.num_classes, (args.batch_size,))

    import json
    start_epoch = 0
    if args.resume:
        if not args.checkpoint:
            p.error("--resume needs --checkpoint <prefix>")
        example = (x if data is None else
                   np.zeros((args.batch_size,) + shape, np.float32))
        trainer.prepare(example)
        trainer.load_checkpoint(args.checkpoint)
        # epoch count comes from the progress sidecar, NOT from
        # num_update // steps_per_epoch: a real-data epoch can end early
        # (iterator exhaustion), which would under-count completed epochs
        try:
            with open(args.checkpoint + ".progress") as f:
                start_epoch = json.load(f)["epoch"]
        except FileNotFoundError:
            start_epoch = trainer.num_update // args.steps_per_epoch
        logging.info("resumed from %s at update %d (epoch %d)",
                     args.checkpoint, trainer.num_update, start_epoch)

    def save(epoch):
        trainer.save_checkpoint(args.checkpoint)
        with open(args.checkpoint + ".progress", "w") as f:
            json.dump({"epoch": epoch + 1}, f)
        logging.info("checkpointed to %s.{params,states} (epoch %d done)",
                     args.checkpoint, epoch)

    for epoch in range(start_epoch, args.epochs):
        tic = time.time()
        seen = 0
        if data is not None:
            data.reset()
            it = iter(data)
        for step in range(args.steps_per_epoch):
            if data is not None:
                try:
                    batch = next(it)
                except StopIteration:
                    break
                loss = trainer.step(batch.data[0], batch.label[0])
            else:
                loss = trainer.step(x, y)
            seen += args.batch_size
            if step % 20 == 0:
                logging.info("Epoch[%d] Batch [%d]\tloss=%.4f", epoch,
                             step, loss.asscalar())
        dt = time.time() - tic
        logging.info("Epoch[%d] final loss=%.4f", epoch, loss.asscalar())
        logging.info("Epoch[%d] Speed: %.2f samples/sec (%d chips)",
                     epoch, seen / dt, n_dev)
        if args.checkpoint and ((epoch + 1) % args.checkpoint_every == 0
                                or epoch + 1 == args.epochs):
            save(epoch)   # always checkpoint the final epoch too


if __name__ == "__main__":
    main()

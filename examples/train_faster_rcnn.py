#!/usr/bin/env python
"""Faster R-CNN training (driver config #5, second family; ref: the
reference's example/rcnn). Synthetic boxes by default — swap in an
ImageDetRecordIter pack for real data (see train_ssd.py).

Usage: python examples/train_faster_rcnn.py [--steps 50] [--image-size 128]
"""
from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=50)
    ap.add_argument("--batch", type=int, default=2)
    ap.add_argument("--image-size", type=int, default=128)
    ap.add_argument("--classes", type=int, default=3)
    ap.add_argument("--lr", type=float, default=5e-4)
    args = ap.parse_args()

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd
    from mxnet_tpu.gluon.model_zoo.faster_rcnn import (FasterRCNNLoss,
                                                       faster_rcnn_resnet)

    np.random.seed(0)
    H = args.image_size
    net = faster_rcnn_resnet(classes=args.classes,
                             rpn_pre_nms_top_n=200,
                             rpn_post_nms_top_n=32)
    net.initialize(mx.init.Xavier())
    net.hybridize()   # loss matching is in-graph since round 4
    loss_fn = FasterRCNNLoss(net)
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})

    def synth_batch():
        x = np.random.rand(args.batch, 3, H, H).astype(np.float32)
        gt = np.full((args.batch, 2, 5), -1.0, np.float32)
        for i in range(args.batch):
            cls = np.random.randint(0, args.classes)
            x0, y0 = np.random.randint(0, H // 2, 2)
            w, h = np.random.randint(H // 4, H // 2, 2)
            gt[i, 0] = [cls, x0, y0, min(x0 + w, H - 1),
                        min(y0 + h, H - 1)]
            # paint the object region so there is signal to localize
            x[i, cls % 3, y0:y0 + h, x0:x0 + w] += 1.0
        return x, gt

    im_info = np.array([[H, H, 1.0]] * args.batch, np.float32)
    t0 = time.time()
    for step in range(args.steps):
        x, gt = synth_batch()
        with autograd.record():
            outs = net(nd.array(x), nd.array(im_info))
            loss = loss_fn(outs, nd.array(gt), (H, H))
        loss.backward()
        trainer.step(args.batch)
        if step % 10 == 0 or step == args.steps - 1:
            print(f"step {step:4d}  loss {float(loss.asscalar()):8.4f}  "
                  f"({time.time() - t0:.1f}s)")
    print("done")


if __name__ == "__main__":
    main()

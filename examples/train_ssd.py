#!/usr/bin/env python
"""Config #5 — SSD detection training (ref ecosystem: gluoncv
scripts/detection/ssd/train_ssd.py). Static-shape TPU path: anchors and
target assignment are jit-compatible ops. Synthetic boxes by default;
--rec consumes an ImageDetRecordIter-style pack.
"""
from __future__ import annotations

import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon.model_zoo import ssd


def synthetic_batch(rng, batch_size, size, classes):
    x = rng.rand(batch_size, 3, size, size).astype(np.float32)
    labels = np.full((batch_size, 2, 5), -1, np.float32)
    for i in range(batch_size):
        cls = rng.randint(0, classes)
        x0, y0 = rng.uniform(0.05, 0.5, 2)
        w, h = rng.uniform(0.2, 0.45, 2)
        labels[i, 0] = [cls, x0, y0, min(x0 + w, 1.0), min(y0 + h, 1.0)]
        # paint the object so it is learnable
        H = int(y0 * size), int(min(y0 + h, 1.0) * size)
        W = int(x0 * size), int(min(x0 + w, 1.0) * size)
        x[i, cls % 3, H[0]:H[1], W[0]:W[1]] += 1.5
    return x, labels


def main():
    logging.basicConfig(level=logging.INFO)
    p = argparse.ArgumentParser()
    p.add_argument("--network", default="resnet18_v1")
    p.add_argument("--data-shape", type=int, default=128)
    p.add_argument("--num-classes", type=int, default=4)
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--lr", type=float, default=0.005)
    args = p.parse_args()

    net = ssd.get_ssd(args.network, classes=args.num_classes,
                      num_scales=3, thumbnail=args.data_shape <= 128)
    net.initialize(mx.init.Xavier())
    loss_fn = ssd.SSDMultiBoxLoss()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": args.lr, "momentum": 0.9,
                             "wd": 5e-4})
    rng = np.random.RandomState(0)
    for step in range(args.steps):
        x, labels = synthetic_batch(rng, args.batch_size, args.data_shape,
                                    args.num_classes)
        with autograd.record():
            anchors, cls_preds, box_preds = net(mx.nd.array(x))
            loc_t, loc_m, cls_t = mx.nd.contrib.MultiBoxTarget(
                anchors, mx.nd.array(labels), cls_preds,
                negative_mining_ratio=3.0)
            loss = loss_fn(cls_preds, box_preds, cls_t, loc_t, loc_m)
        loss.backward()
        trainer.step(args.batch_size)
        if step % 20 == 0:
            logging.info("Batch [%d]\tloss=%.4f", step,
                         float(loss.asnumpy().mean()))
    # inference + VOC07 mAP scoring (gluoncv-parity evaluation)
    x, labels = synthetic_batch(rng, 2, args.data_shape,
                                args.num_classes)
    anchors, cls_preds, box_preds = net(mx.nd.array(x))
    probs = mx.nd.softmax(cls_preds, axis=-1)
    probs = mx.nd.transpose(probs, axes=(0, 2, 1))
    det = mx.nd.contrib.MultiBoxDetection(probs, box_preds, anchors,
                                          nms_threshold=0.45)
    rows = det.asnumpy()[0]
    kept = rows[rows[:, 0] >= 0]
    logging.info("detections (top 3): %s", kept[:3])
    logging.info("final loss=%.4f", float(loss.asnumpy().mean()))
    metric = mx.metric.VOC07MApMetric(iou_thresh=0.5)
    metric.update(mx.nd.array(labels), det)
    name, value = metric.get()
    logging.info("%s: %.4f", name, value)


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Config #4 — transformer NMT (Sockeye shape: sockeye.train). Trains the
base transformer on a synthetic reversal task and greedy-decodes samples;
swap in real parallel text by replacing ``make_batch``.
"""
from __future__ import annotations

import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon.model_zoo import transformer

BOS, EOS = 1, 2


def make_batch(rng, batch_size, seq_len, vocab):
    src = rng.randint(3, vocab, (batch_size, seq_len))
    tgt = src[:, ::-1].copy()                     # reversal task
    tgt_in = np.concatenate(
        [np.full((batch_size, 1), BOS), tgt[:, :-1]], axis=1)
    return src, tgt_in, tgt


def main():
    logging.basicConfig(level=logging.INFO)
    p = argparse.ArgumentParser()
    p.add_argument("--vocab", type=int, default=64)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--seq-len", type=int, default=10)
    p.add_argument("--num-layers", type=int, default=2)
    p.add_argument("--units", type=int, default=128)
    p.add_argument("--steps", type=int, default=300)
    p.add_argument("--lr", type=float, default=3e-3)
    p.add_argument("--label-smoothing", type=float, default=0.0,
                   help="Sockeye-style smoothed CE (e.g. 0.1)")
    p.add_argument("--beam", type=int, default=1,
                   help="beam size for the sample decode (1 = greedy)")
    args = p.parse_args()

    net = transformer.TransformerModel(
        args.vocab, args.vocab, num_layers=args.num_layers,
        units=args.units, hidden_size=args.units * 4, num_heads=8,
        max_length=64, dropout=0.1)
    net.initialize(mx.init.Xavier())
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss(
        label_smoothing=args.label_smoothing)
    rng = np.random.RandomState(0)
    for step in range(args.steps):
        src, tgt_in, tgt = make_batch(rng, args.batch_size, args.seq_len,
                                      args.vocab)
        with autograd.record():
            logits = net(mx.nd.array(src), mx.nd.array(tgt_in))
            loss = loss_fn(logits.reshape((-1, args.vocab)),
                           mx.nd.array(tgt.reshape(-1)))
        loss.backward()
        trainer.step(tgt.size)
        if step % 50 == 0:
            logging.info("Batch [%d]\tloss=%.4f", step,
                         float(loss.asnumpy().mean()))
    # sample decode (greedy by default; --beam K runs beam search)
    src, _, tgt = make_batch(rng, 2, args.seq_len, args.vocab)
    out = net.translate(mx.nd.array(src), bos_id=BOS, eos_id=EOS,
                        max_steps=args.seq_len, beam_size=args.beam)
    acc = float((out[:, :args.seq_len] == tgt[:, :out.shape[1]]).mean())
    mode = "greedy" if args.beam <= 1 else f"beam-{args.beam}"
    # test_examples.py parses the "greedy-decode" line; keep it for the
    # default mode and label beam runs by their actual mode
    if args.beam <= 1:
        logging.info("greedy-decode token accuracy: %.3f", acc)
    else:
        logging.info("%s decode token accuracy: %.3f", mode, acc)


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Word-level LSTM language model (ref: example/rnn/word_lm/train.py —
embedding → multi-layer LSTM → tied/untied softmax over the vocab,
truncated-BPTT training with perplexity reporting).

Synthetic corpus by default: a fixed random "grammar" (each token
deterministically keyed to its predecessor pair) so the model's
perplexity floor is ~1 when it learns and stays near vocab-size when it
doesn't — the CI gate reads the printed final perplexity. The fused
lax.scan LSTM op is the compute path (SURVEY §2 row 14).
"""
from __future__ import annotations

import argparse
import logging
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd


class WordLM(gluon.HybridBlock):
    def __init__(self, vocab, embed, hidden, layers, dropout=0.2,
                 tie_weights=False, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.embedding = gluon.nn.Embedding(vocab, embed)
            self.lstm = gluon.rnn.LSTM(hidden, num_layers=layers,
                                       layout="NTC", dropout=dropout)
            self.drop = gluon.nn.Dropout(dropout) if dropout else None
            if tie_weights and embed != hidden:
                raise mx.base.MXNetError(
                    "tie_weights needs embed == hidden")
            self.decoder = gluon.nn.Dense(vocab, flatten=False,
                                          params=self.embedding.params
                                          if tie_weights else None)

    def hybrid_forward(self, F, tokens):
        x = self.embedding(tokens)            # (N, T, E)
        h = self.lstm(x)                      # (N, T, H)
        if self.drop is not None:
            h = self.drop(h)
        return self.decoder(h)                # (N, T, V) — 3-D logits


def synthetic_corpus(vocab, n_tokens, seed=0):
    """Deterministic bigram chain: next = perm[(cur + prev) % vocab].
    Fully learnable by a 2-token context model; chance ppl = vocab."""
    rng = np.random.RandomState(seed)
    perm = rng.permutation(vocab)
    toks = np.zeros(n_tokens, np.int64)
    toks[0], toks[1] = 1, 2
    for i in range(2, n_tokens):
        toks[i] = perm[(toks[i - 1] + toks[i - 2]) % vocab]
    return toks


def batchify(toks, batch, seq):
    n = (len(toks) - 1) // (batch * seq) * (batch * seq)
    x = toks[:n].reshape(batch, -1)
    y = toks[1:n + 1].reshape(batch, -1)
    for i in range(0, x.shape[1] - seq + 1, seq):
        yield x[:, i:i + seq], y[:, i:i + seq]


def main():
    logging.basicConfig(level=logging.INFO)
    p = argparse.ArgumentParser()
    p.add_argument("--vocab", type=int, default=50)
    p.add_argument("--embed", type=int, default=64)
    p.add_argument("--hidden", type=int, default=128)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--batch-size", type=int, default=16)
    p.add_argument("--seq-len", type=int, default=32)
    p.add_argument("--epochs", type=int, default=6)
    p.add_argument("--tokens", type=int, default=20000)
    p.add_argument("--lr", type=float, default=2e-3)
    p.add_argument("--dropout", type=float, default=0.0)
    p.add_argument("--tied", action="store_true")
    args = p.parse_args()

    net = WordLM(args.vocab, args.embed, args.hidden, args.layers,
                 dropout=args.dropout, tie_weights=args.tied)
    net.initialize(mx.init.Xavier())
    net.hybridize()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})
    toks = synthetic_corpus(args.vocab, args.tokens)
    for epoch in range(args.epochs):
        total, count, tic = 0.0, 0, time.time()
        for x, y in batchify(toks, args.batch_size, args.seq_len):
            xb = nd.array(x.astype(np.float32))
            yb = nd.array(y.astype(np.float32))
            with autograd.record():
                loss = loss_fn(net(xb), yb)
            loss.backward()
            trainer.step(args.batch_size)
            total += float(loss.mean().asscalar()) * x.size
            count += x.size
        ppl = float(np.exp(min(total / count, 20.0)))
        logging.info("Epoch [%d] train ppl=%.2f (%.1fs)", epoch, ppl,
                     time.time() - tic)
    logging.info("final perplexity=%.2f", ppl)


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Config #3 — BERT-base masked-LM pretraining (GluonNLP's
scripts/bert/run_pretraining.py shape).

Runs the fused SPMD step over a dp(×sp) mesh; --seq-parallel shards long
sequences over the `seq` axis with ring attention (net-new TPU capability,
SURVEY §5.7). Synthetic corpus by default.
"""
from __future__ import annotations

import argparse
import logging
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import mxnet_tpu as mx
from mxnet_tpu import gluon, parallel
from mxnet_tpu.gluon.model_zoo import bert


class MLMWrapper(gluon.HybridBlock):
    def __init__(self, inner, vocab):
        super().__init__()
        self.inner = inner
        self._vocab = vocab

    def hybrid_forward(self, F, tokens):
        seq, mlm = self.inner(tokens)
        return F.reshape(mlm, (-1, self._vocab))


class FlatCE(gluon.loss.Loss):
    def __init__(self):
        super().__init__(None, 0)
        self._ce = gluon.loss.SoftmaxCrossEntropyLoss()

    def hybrid_forward(self, F, pred, label):
        return self._ce(pred, F.reshape(label, (-1,)))


def main():
    logging.basicConfig(level=logging.INFO)
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="bert_12_768_12")
    p.add_argument("--vocab-size", type=int, default=30522)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--seq-length", type=int, default=128)
    p.add_argument("--lr", type=float, default=1e-4)
    p.add_argument("--steps", type=int, default=30)
    p.add_argument("--seq-parallel", type=int, default=1,
                   help="size of the seq mesh axis (ring attention)")
    p.add_argument("--bf16", action="store_true", default=True)
    p.add_argument("--no-bf16", dest="bf16", action="store_false")
    args = p.parse_args()

    import jax
    n_dev = len(jax.devices())
    axes = {"data": n_dev // args.seq_parallel}
    if args.seq_parallel > 1:
        axes["seq"] = args.seq_parallel
    mesh = parallel.make_mesh(axes)

    net = bert.get_bert_model(
        args.model, vocab_size=args.vocab_size,
        max_length=max(512, args.seq_length),
        use_pooler=False, use_classifier=False,
        seq_parallel=args.seq_parallel > 1)
    net.initialize(mx.init.Normal(0.02))
    trainer = parallel.ShardedTrainer(
        MLMWrapper(net, args.vocab_size), FlatCE(), "adam",
        optimizer_params={"learning_rate": args.lr},
        mesh=mesh, compute_dtype="bfloat16" if args.bf16 else None)

    rng = np.random.RandomState(0)
    tokens = rng.randint(0, args.vocab_size,
                         (args.batch_size, args.seq_length))
    tic, seen = time.time(), 0
    for step in range(args.steps):
        loss = trainer.step(tokens, tokens)
        seen += args.batch_size
        if step == 2:            # drop compile time from throughput
            tic, seen = time.time(), 0
        if step % 10 == 0:
            logging.info("Batch [%d]\tmlm_loss=%.4f", step,
                         loss.asscalar())
    dt = time.time() - tic
    logging.info("Speed: %.2f samples/sec (%d chips, seq=%d)",
                 seen / dt, n_dev, args.seq_length)


if __name__ == "__main__":
    main()

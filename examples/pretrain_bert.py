#!/usr/bin/env python
"""Config #3 — BERT-base masked-LM pretraining (GluonNLP's
scripts/bert/run_pretraining.py shape).

Runs the fused SPMD step over a dp(×sp) mesh; --seq-parallel shards long
sequences over the `seq` axis with ring attention (net-new TPU capability,
SURVEY §5.7). Synthetic corpus by default.
"""
from __future__ import annotations

import argparse
import logging
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import mxnet_tpu as mx
from mxnet_tpu import gluon, parallel
from mxnet_tpu.gluon.model_zoo import bert


class MLMWrapper(gluon.HybridBlock):
    """Keeps the logits 3-D (B, S, V): the CE loss reduces over the last
    axis in place — flattening forced a logits relayout on TPU
    (docs/perf_notes.md round 4)."""

    def __init__(self, inner):
        super().__init__()
        self.inner = inner

    def hybrid_forward(self, F, tokens):
        seq, mlm = self.inner(tokens)
        return mlm


def main():
    logging.basicConfig(level=logging.INFO)
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="bert_12_768_12")
    p.add_argument("--vocab-size", type=int, default=30522)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--seq-length", type=int, default=128)
    p.add_argument("--lr", type=float, default=1e-4)
    p.add_argument("--steps", type=int, default=30)
    p.add_argument("--num-layers", type=int, default=None,
                   help="override the config (tiny CI runs)")
    p.add_argument("--units", type=int, default=None)
    p.add_argument("--num-heads", type=int, default=None)
    p.add_argument("--hidden-size", type=int, default=None)
    p.add_argument("--seq-parallel", type=int, default=1,
                   help="size of the seq mesh axis (ring attention)")
    p.add_argument("--bf16", action="store_true", default=True)
    p.add_argument("--no-bf16", dest="bf16", action="store_false")
    args = p.parse_args()

    import jax
    n_dev = len(jax.devices())
    axes = {"data": n_dev // args.seq_parallel}
    if args.seq_parallel > 1:
        axes["seq"] = args.seq_parallel
    mesh = parallel.make_mesh(axes)

    overrides = {k: v for k, v in dict(
        num_layers=args.num_layers, units=args.units,
        num_heads=args.num_heads, hidden_size=args.hidden_size).items()
        if v is not None}
    net = bert.get_bert_model(
        args.model, vocab_size=args.vocab_size,
        max_length=max(512, args.seq_length),
        use_pooler=False, use_classifier=False,
        seq_parallel=args.seq_parallel > 1, **overrides)
    net.initialize(mx.init.Normal(0.02))
    trainer = parallel.ShardedTrainer(
        MLMWrapper(net),
        gluon.loss.SoftmaxCrossEntropyLoss(), "adam",
        optimizer_params={"learning_rate": args.lr},
        mesh=mesh, compute_dtype="bfloat16" if args.bf16 else None)

    rng = np.random.RandomState(0)
    tokens = rng.randint(0, args.vocab_size,
                         (args.batch_size, args.seq_length))
    tic, seen = time.time(), 0
    for step in range(args.steps):
        loss = trainer.step(tokens, tokens)
        seen += args.batch_size
        if step == 2:            # drop compile time from throughput
            tic, seen = time.time(), 0
        if step % 10 == 0:
            logging.info("Batch [%d]\tmlm_loss=%.4f", step,
                         loss.asscalar())
    dt = time.time() - tic
    logging.info("final mlm_loss=%.4f", loss.asscalar())
    logging.info("Speed: %.2f samples/sec (%d chips, seq=%d)",
                 seen / dt, n_dev, args.seq_length)


if __name__ == "__main__":
    main()

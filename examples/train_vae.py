#!/usr/bin/env python
"""Variational autoencoder (ref: example/autoencoder + the VAE idiom the
reference zoo ships): conv encoder → reparameterized latent → deconv
decoder, trained with the ELBO (reconstruction + KL) under one
hybridized program per player-free step — the generative-family
counterpart to train_dcgan.py's adversarial one.

Synthetic blob images (same distribution as the DCGAN example) keep it
hermetic; the CI gate is reconstruction error + a finite, shrinking KL.
"""
from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

if "jax" not in sys.modules and not os.environ.get("JAX_PLATFORMS") and \
        "host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["JAX_PLATFORMS"] = "cpu"

import mxnet_tpu as mx                                   # noqa: E402
from mxnet_tpu import autograd, gluon                    # noqa: E402
from train_dcgan import real_batch                       # noqa: E402
# (one shared data distribution — the cross-example L1 gates compare)


class VAE(gluon.HybridBlock):
    def __init__(self, nz=8, nf=16):
        super().__init__()
        self._nz = nz
        with self.name_scope():
            self.enc = gluon.nn.HybridSequential()
            self.enc.add(
                gluon.nn.Conv2D(nf, 4, strides=2, padding=1),       # 8x8
                gluon.nn.Activation("relu"),
                gluon.nn.Conv2D(nf * 2, 4, strides=2, padding=1),   # 4x4
                gluon.nn.Activation("relu"),
                gluon.nn.Dense(2 * nz))
            self.dec = gluon.nn.HybridSequential()
            self.dec.add(
                gluon.nn.Dense(nf * 2 * 4 * 4, activation="relu"),
                gluon.nn.HybridLambda(
                    lambda F, x: F.reshape(x, (-1, nf * 2, 4, 4))),
                gluon.nn.Conv2DTranspose(nf, 4, strides=2, padding=1),
                gluon.nn.Activation("relu"),
                gluon.nn.Conv2DTranspose(1, 4, strides=2, padding=1),
                gluon.nn.Activation("tanh"))

    def hybrid_forward(self, F, x, eps):
        h = self.enc(x)
        mu = F.slice_axis(h, axis=1, begin=0, end=self._nz)
        logvar = F.slice_axis(h, axis=1, begin=self._nz, end=2 * self._nz)
        z = mu + F.exp(0.5 * logvar) * eps      # reparameterization
        return self.dec(z), mu, logvar



def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--steps", type=int, default=400)
    ap.add_argument("--nz", type=int, default=8)
    ap.add_argument("--lr", type=float, default=2e-3)
    ap.add_argument("--kl-weight", type=float, default=5e-3)
    args = ap.parse_args()

    mx.random.seed(0)
    rng = np.random.RandomState(0)
    net = VAE(nz=args.nz)
    net.initialize(mx.init.Xavier())
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})

    t0 = time.time()
    rec = kl = None
    for step in range(args.steps):
        x = mx.nd.array(real_batch(rng, args.batch))
        eps = mx.nd.array(rng.randn(args.batch, args.nz)
                          .astype(np.float32))
        with autograd.record():
            xh, mu, logvar = net(x, eps)
            rec_l = ((xh - x) ** 2).mean()
            kl_l = (-0.5 * (1 + logvar - mu * mu -
                            mx.nd.exp(logvar))).sum(axis=1).mean()
            loss = rec_l + args.kl_weight * kl_l
        loss.backward()
        trainer.step(args.batch)
        rec, kl = float(rec_l.asscalar()), float(kl_l.asscalar())
        if step % 50 == 0:
            print(f"step {step:4d}  rec {rec:.4f}  kl {kl:.2f}")

    # generative check: decode pure prior samples and compare their
    # pixel-mean map to the data's (same gate family as the DCGAN example)
    z = mx.nd.array(rng.randn(256, args.nz).astype(np.float32))
    gen = net.dec(z).asnumpy().mean(axis=0)[0]
    real_mean = real_batch(rng, 256).mean(axis=0)[0]
    l1 = float(np.abs(gen - real_mean).mean())
    print(f"final rec {rec:.4f}  kl {kl:.2f}  prior-sample L1 {l1:.4f}  "
          f"{time.time()-t0:.1f}s")


if __name__ == "__main__":
    main()

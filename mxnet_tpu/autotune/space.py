"""Typed search spaces over the runtime's REAL knobs (docs/autotune.md).

A space is a small set of named :class:`Choice` axes plus a validity
predicate — the same contracts the runtime enforces, reused at
search time so the tuner can only propose configurations the runtime
would accept:

- Pallas block shapes must tile the kernel's 2D view exactly (the
  ``grid=(r // br, c // bc)`` contract in pallas/kernels.py — a
  non-divisor block would leave remainder rows unwritten, which is why
  the kernels clamp invalid tuned blocks back to the default);
- bucket lattices must keep :meth:`BucketGrid.grid_bound` under the
  compile budget (the PR-4 bounded-compile guarantee);
- serving/router/decode scalars must stay in their documented ranges.

Stdlib-only: spaces are data + predicates, importable without jax.
"""
from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional, Tuple

__all__ = ["Choice", "Space", "divisors", "pallas_block_space",
           "serving_space", "router_space", "decode_space",
           "bucket_space"]


@dataclass(frozen=True)
class Choice:
    """One categorical axis: a finite, ordered value set."""

    name: str
    values: Tuple

    def __post_init__(self):
        if not self.values:
            raise ValueError(f"choice {self.name!r} has no values")


@dataclass
class Space:
    """A named product of :class:`Choice` axes with a validity
    predicate (``validate(config) -> None | reason``) and the built-in
    default configuration — the A/B baseline every search includes."""

    name: str
    params: Dict[str, Choice]
    default: Dict
    validate: Optional[Callable] = None
    # how a winning config lands in the tuned table:
    # (family, key) — e.g. ("serving", "window_ms") — or a callable for
    # structured families (pallas blocks); see runner.table_patch
    table_map: Dict[str, Tuple[str, str]] = field(default_factory=dict)

    def __post_init__(self):
        bad = sorted(set(self.default) - set(self.params))
        if bad:
            raise ValueError(f"space {self.name!r}: default names "
                             f"unknown params {bad}")

    def reason(self, config: Dict) -> Optional[str]:
        """Why ``config`` is invalid (None = valid)."""
        for name, value in config.items():
            ch = self.params.get(name)
            if ch is None:
                return f"unknown_param:{name}"
            if value not in ch.values:
                return f"out_of_domain:{name}={value!r}"
        if self.validate is not None:
            return self.validate(config)
        return None

    def sample(self, rng) -> Dict:
        """One valid configuration (rejection sampling, bounded — a
        space whose predicate rejects everything raises instead of
        spinning)."""
        for _ in range(256):
            cfg = {n: ch.values[rng.randrange(len(ch.values))]
                   for n, ch in self.params.items()}
            if self.reason(cfg) is None:
                return cfg
        raise ValueError(f"space {self.name!r}: no valid sample in 256 "
                         "draws — the validity predicate rejects the "
                         "whole domain")

    def neighbors(self, config: Dict, name: str):
        """All valid single-axis perturbations of ``config`` along
        ``name`` (coordinate descent's move set)."""
        out = []
        for v in self.params[name].values:
            if v == config.get(name):
                continue
            cand = dict(config)
            cand[name] = v
            if self.reason(cand) is None:
                out.append(cand)
        return out

    def grid(self):
        """Every valid configuration (small spaces only — used by
        successive halving's rung-0 seeding when the domain is tiny)."""
        names = sorted(self.params)
        for combo in itertools.product(
                *(self.params[n].values for n in names)):
            cfg = dict(zip(names, combo))
            if self.reason(cfg) is None:
                yield cfg


# ---------------------------------------------------------------------------
# concrete spaces
# ---------------------------------------------------------------------------
def divisors(n: int, cap: int, floor: int = 1) -> Tuple[int, ...]:
    """Divisors of ``n`` in ``[floor, cap]`` — the exact-tiling domain
    of a Pallas block axis."""
    return tuple(d for d in range(1, min(int(n), int(cap)) + 1)
                 if n % d == 0 and d >= floor)


def _default_block(n: int, cap: int) -> int:
    """Mirror of pallas/kernels.py ``_block``: largest divisor <= cap."""
    for b in range(min(cap, n), 0, -1):
        if n % b == 0:
            return b
    return 1


def pallas_block_space(kernel: str, r: int, c: int, row_cap: int = 512,
                       col_cap: int = 256) -> Space:
    """Block-shape space for one epilogue kernel at one (r, c) shape
    class.  Validity = the kernel's own grid contract: each block axis
    must divide its dim exactly (and a degenerate 1-wide minor block is
    excluded — the repack-debt shapes perf_notes.md flags are exactly
    the ones whose best divisor is tiny)."""
    r, c = int(r), int(c)
    rows = divisors(r, row_cap) or (1,)
    cols = divisors(c, col_cap) or (1,)

    def validate(cfg):
        br, bc = cfg["block_r"], cfg["block_c"]
        if r % br or c % bc:
            return f"block_not_divisor:{br}x{bc}_vs_{r}x{c}"
        return None

    return Space(
        name=f"pallas:{kernel}:{r}x{c}",
        params={"block_r": Choice("block_r", rows),
                "block_c": Choice("block_c", cols)},
        default={"block_r": _default_block(r, row_cap),
                 "block_c": _default_block(c, col_cap)},
        validate=validate,
        table_map={"block_r": ("pallas", f"{kernel}.{r}x{c}.block_r"),
                   "block_c": ("pallas", f"{kernel}.{r}x{c}.block_c")})


def serving_space(window_ms=(1.0, 2.0, 5.0, 10.0, 20.0),
                  max_queue=(32, 64, 128, 256)) -> Space:
    """Serving coalescing window + admission bound (the ``Server``
    consumers of the tuned table)."""
    def validate(cfg):
        if cfg["window_ms"] < 0:
            return "window_ms_negative"
        if cfg["max_queue"] <= 0:
            return "max_queue_nonpositive"
        return None

    return Space(
        name="serving",
        params={"window_ms": Choice("window_ms", tuple(window_ms)),
                "max_queue": Choice("max_queue", tuple(max_queue))},
        default={"window_ms": 5.0, "max_queue": 128},
        validate=validate,
        table_map={"window_ms": ("serving", "window_ms"),
                   "max_queue": ("serving", "max_queue")})


def router_space(hedge_ms=(0.0, 5.0, 10.0, 25.0, 50.0)) -> Space:
    """Router tail-latency hedge delay (0 = hedging off)."""
    return Space(
        name="router",
        params={"hedge_ms": Choice("hedge_ms", tuple(hedge_ms))},
        default={"hedge_ms": 0.0},
        validate=lambda cfg: ("hedge_ms_negative"
                              if cfg["hedge_ms"] < 0 else None),
        table_map={"hedge_ms": ("router", "hedge_ms")})


def decode_space(slots=(2, 4, 8, 16)) -> Space:
    """Continuous-batching decode slot pool size."""
    return Space(
        name="decode",
        params={"slots": Choice("slots", tuple(slots))},
        default={"slots": 8},
        validate=lambda cfg: ("slots_nonpositive"
                              if cfg["slots"] <= 0 else None),
        table_map={"slots": ("decode", "slots")})


def bucket_space(max_batch: int = 8, compile_cap: int = 32) -> Space:
    """Batch-bucket lattice candidates, validity-gated by the REAL
    compile bound: a lattice whose ``BucketGrid.grid_bound()`` exceeds
    ``compile_cap`` is invalid (the PR-4 bounded-compile guarantee is a
    constraint the tuner must never trade away)."""
    cands = []
    pow2 = tuple(b for b in (1, 2, 4, 8, 16, 32, 64) if b <= max_batch)
    for lattice in (pow2, pow2[::2] or pow2, (max_batch,),
                    tuple(range(1, max_batch + 1))):
        lat = tuple(sorted(set(lattice)))
        if lat and lat not in cands:
            cands.append(lat)

    def validate(cfg):
        from ..serving.buckets import BucketGrid
        lat = cfg["batch_buckets"]
        if max(lat) > max_batch:
            return f"bucket_exceeds_max_batch:{max(lat)}>{max_batch}"
        bound = BucketGrid(max_batch, lat).grid_bound()
        if bound > compile_cap:
            return f"grid_bound:{bound}>{compile_cap}"
        return None

    return Space(
        name="buckets",
        params={"batch_buckets": Choice("batch_buckets", tuple(cands))},
        default={"batch_buckets": pow2},
        validate=validate,
        table_map={"batch_buckets": ("buckets", "batch")})

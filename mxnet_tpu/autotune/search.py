"""Search strategies over knob spaces (stdlib-only; docs/autotune.md).

Three composable strategies, all budget-bounded (trial count AND
wall-clock, monotonic — G11) and seeded (reproducible searches):

- :func:`random_search` — seeded uniform sampling over the valid
  domain, always including the built-in default configuration (the
  A/B baseline: the committed winner can never measure worse than the
  default on the same harness, because the default is in the pool);
- :func:`successive_halving` — evaluate a wide rung cheaply (a
  fraction of the full trial resource), keep the top half, re-evaluate
  the survivors with more resource; noise-robust on short benches;
- :func:`coordinate_descent` — single-axis refinement around the
  incumbent using :meth:`Space.neighbors` (only valid moves exist).

``evaluate(config, resource=1.0)`` is the trial runner's closure; it
returns an object with ``.fitness`` (higher is better; None = the
configuration failed its gate and never competes).
"""
from __future__ import annotations

import random
import time
from dataclasses import dataclass, field
from typing import List, Optional

__all__ = ["Budget", "random_search", "successive_halving",
           "coordinate_descent", "run_search"]

_NEG_INF = float("-inf")


def _fit(result) -> float:
    f = getattr(result, "fitness", None)
    return _NEG_INF if f is None else float(f)


@dataclass
class Budget:
    """Hard bounds on a search: trial count and wall-clock seconds.
    ``start()`` arms the monotonic deadline; strategies call
    :meth:`allow` before every trial."""

    max_trials: int = 16
    wall_s: float = 120.0
    spent: int = 0
    _deadline: Optional[float] = field(default=None, repr=False)

    def start(self) -> "Budget":
        if self._deadline is None:
            self._deadline = time.monotonic() + float(self.wall_s)
        return self

    def exhausted(self) -> Optional[str]:
        if self.spent >= self.max_trials:
            return f"trials:{self.spent}/{self.max_trials}"
        if self._deadline is not None \
                and time.monotonic() >= self._deadline:
            return f"wall_clock:{self.wall_s:g}s"
        return None

    def allow(self) -> bool:
        if self.exhausted() is not None:
            return False
        self.spent += 1
        return True


def _key(config: dict):
    return tuple(sorted((k, tuple(v) if isinstance(v, (list, tuple))
                         else v) for k, v in config.items()))


def _dedup(seen: set, config: dict) -> bool:
    k = _key(config)
    if k in seen:
        return False
    seen.add(k)
    return True


def random_search(space, evaluate, budget: Budget, rng: random.Random,
                  include_default: bool = True,
                  resource: float = 1.0) -> List:
    """Seeded random sampling (deduplicated).  The built-in default is
    trial #1 so every search's history contains the A/B baseline."""
    budget.start()
    results, seen = [], set()
    if include_default and space.reason(dict(space.default)) is None:
        if _dedup(seen, space.default) and budget.allow():
            results.append(evaluate(dict(space.default),
                                    resource=resource))
    for _ in range(64 * budget.max_trials):
        if budget.exhausted() is not None:
            break
        cfg = space.sample(rng)
        if not _dedup(seen, cfg):
            continue
        if not budget.allow():
            break
        results.append(evaluate(cfg, resource=resource))
    return results


def successive_halving(space, evaluate, budget: Budget,
                       rng: random.Random, n0: int = 8,
                       keep: float = 0.5, resource0: float = 0.25,
                       grow: float = 2.0) -> List:
    """Rung 0 evaluates up to ``n0`` sampled configs (default included)
    at ``resource0`` of the full trial resource; each rung keeps the
    top ``keep`` fraction and multiplies the resource by ``grow`` until
    one survivor remains or the budget runs dry."""
    budget.start()
    results, seen = [], set()
    pool = []
    if space.reason(dict(space.default)) is None:
        pool.append(dict(space.default))
        _dedup(seen, space.default)
    for _ in range(64 * n0):
        if len(pool) >= n0:
            break
        cfg = space.sample(rng)
        if _dedup(seen, cfg):
            pool.append(cfg)
    resource = resource0
    while pool and budget.exhausted() is None:
        rung = []
        for cfg in pool:
            if not budget.allow():
                break
            res = evaluate(dict(cfg), resource=min(resource, 1.0))
            results.append(res)
            rung.append((res, cfg))
        rung.sort(key=lambda rc: _fit(rc[0]), reverse=True)
        survivors = [cfg for res, cfg in rung if _fit(res) > _NEG_INF]
        if len(survivors) <= 1:
            break
        pool = survivors[:max(1, int(len(survivors) * keep))]
        if len(pool) == len(survivors):   # keep=1.0 would never shrink
            pool = pool[:-1] or pool[:1]
        if resource >= 1.0 and len(pool) <= 1:
            break
        resource = min(1.0, resource * grow)
    return results


def coordinate_descent(space, evaluate, budget: Budget, start: dict,
                       rounds: int = 2, resource: float = 1.0,
                       start_fitness: Optional[float] = None) -> List:
    """Greedy single-axis refinement from ``start``: sweep each axis's
    valid neighbors, adopt any strict improvement, stop after a full
    round without one (or at the budget)."""
    budget.start()
    results = []
    best_cfg = dict(start)
    best_fit = _NEG_INF if start_fitness is None else float(start_fitness)
    if start_fitness is None:
        if not budget.allow():
            return results
        res = evaluate(dict(best_cfg), resource=resource)
        results.append(res)
        best_fit = _fit(res)
    for _ in range(max(1, rounds)):
        improved = False
        for name in sorted(space.params):
            for cand in space.neighbors(best_cfg, name):
                if not budget.allow():
                    return results
                res = evaluate(cand, resource=resource)
                results.append(res)
                if _fit(res) > best_fit:
                    best_fit, best_cfg = _fit(res), dict(cand)
                    improved = True
        if not improved:
            break
    return results


def run_search(space, evaluate, budget: Budget, seed: int = 0,
               halving_n0: int = 0, descent_rounds: int = 1) -> List:
    """The composed pipeline one knob family runs: random sampling
    (default first) — or successive halving when ``halving_n0`` > 0 —
    then coordinate descent from the incumbent.  Returns the full
    trial history; the caller picks ``max(history, key=fitness)``."""
    rng = random.Random(int(seed))
    budget.start()
    if halving_n0 > 0:
        history = successive_halving(space, evaluate, budget, rng,
                                     n0=halving_n0)
    else:
        history = random_search(space, evaluate, budget, rng)
    scored = [r for r in history if _fit(r) > _NEG_INF]
    if scored and descent_rounds > 0 and budget.exhausted() is None:
        best = max(scored, key=_fit)
        history += coordinate_descent(
            space, evaluate, budget, dict(best.config),
            rounds=descent_rounds, start_fitness=_fit(best))
    return history

"""Trial runner: evaluate one knob configuration against the REAL
objective, in a deadlined subprocess (docs/autotune.md).

The fitness a trial reports is the number the runtime actually cares
about, measured by the harnesses the repo already trusts:

- **kernel trials** drive the Pallas parity harness (``python -m
  mxnet_tpu.autotune _trial``): the candidate block shape runs the
  registered kernel (interpret mode on CPU — the same path as the CI
  parity gate) against its XLA reference; the parity gate is ENFORCED
  (max abs error within the registered tolerance, else the trial is
  gated out) and fitness is element throughput;
- **serving trials** drive ``python -m mxnet_tpu.serving bench`` — the
  existing closed-loop generator (optionally replaying a recorded
  ``--arrival`` trace) — under the candidate ``window_ms``/queue/hedge
  knobs; fitness is −p99 under a shed-rate ceiling (a config that
  sheds its way to a good tail is gated out, not rewarded).

Every trial is a child process under a hard deadline (the bench.py
wedge-proof contract, graftlint G5): the parent parses exactly ONE
JSON metric line from stdout, a wedged/dead child becomes a gated
trial with a structured reason, never a hang.  Trials share one AOT
cache dir (the PR-13 store) so revisited serving configurations
re-evaluate warm, and every trial journals an ``autotune_trial``
record inside a trace span — the provenance the committed table
references.
"""
from __future__ import annotations

import itertools
import json
import os
import subprocess
import sys
import tempfile
import time
from dataclasses import dataclass, field

from ..diagnostics.journal import get_journal
from ..observability import trace as _trace

__all__ = ["TrialResult", "TrialRunner", "KernelObjective",
           "ServingObjective"]

_trial_seq = itertools.count()

# children run ``python -m mxnet_tpu...``: make the import root explicit
# so trials work from any parent cwd (the tree is not pip-installed)
_IMPORT_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))


def _child_env() -> dict:
    env = dict(os.environ)
    pp = env.get("PYTHONPATH")
    env["PYTHONPATH"] = (_IMPORT_ROOT if not pp
                         else _IMPORT_ROOT + os.pathsep + pp)
    return env


@dataclass
class TrialResult:
    """One evaluated configuration.  ``fitness`` is None when the trial
    failed its gate (parity, shed ceiling, deadline, crash) — a gated
    config never competes, whatever its raw numbers said."""

    trial_id: int
    objective: str
    config: dict
    fitness: float | None
    ok: bool
    gate: str | None            # failure reason when not ok
    metrics: dict = field(default_factory=dict)
    cached: bool = False
    resource: float = 1.0
    duration_s: float = 0.0


def _last_json_line(text: str):
    """The artifact contract: children print exactly one JSON object
    line on stdout; scan from the end so stray prints can't break it."""
    for line in reversed((text or "").strip().splitlines()):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            return json.loads(line)
        except ValueError:
            continue
    return None


class _Objective:
    """Shared child-process machinery for concrete objectives."""

    name = "objective"
    # the objective's gate knobs live on the instance; subclasses
    # implement argv()/score()

    def __init__(self, deadline_s: float = 120.0):
        self.deadline_s = float(deadline_s)

    def argv(self, config: dict, resource: float, workdir: str) -> list:
        raise NotImplementedError

    def env(self, config: dict, workdir: str) -> dict:
        return _child_env()

    def score(self, doc: dict, config: dict, workdir: str):
        """(fitness, gate_reason, metrics) from the child's JSON line."""
        raise NotImplementedError

    def run(self, config: dict, resource: float, workdir: str):
        argv = self.argv(config, resource, workdir)
        try:
            out = subprocess.run(          # hard deadline: G5 — a wedged
                argv, capture_output=True, text=True,   # child is killed,
                timeout=self.deadline_s,                # never waited on
                env=self.env(config, workdir))
        except subprocess.TimeoutExpired:
            return None, f"deadline:{self.deadline_s:g}s", {}
        doc = _last_json_line(out.stdout)
        if doc is None:
            tail = (out.stderr or "").strip()[-300:]
            return None, f"no_metric_line:rc={out.returncode}", \
                {"stderr_tail": tail}
        if doc.get("error"):
            return None, f"child:{doc['error']}", doc
        return self.score(doc, config, workdir)


class KernelObjective(_Objective):
    """Throughput of one registered Pallas kernel at one shape class
    under a candidate block, parity-gated against the XLA reference."""

    name = "kernel"

    def __init__(self, kernel: str = "conv_epilogue", r: int = 256,
                 c: int = 128, iters: int = 30, deadline_s: float = 120.0,
                 interpret: bool = True):
        super().__init__(deadline_s)
        self.kernel = kernel
        self.r, self.c = int(r), int(c)
        self.iters = int(iters)
        self.interpret = bool(interpret)

    def argv(self, config, resource, workdir):
        iters = max(3, int(round(self.iters * float(resource))))
        argv = [sys.executable, "-m", "mxnet_tpu.autotune", "_trial",
                "--kernel", self.kernel,
                "--shape", f"{self.r}x{self.c}",
                "--iters", str(iters)]
        if config.get("block_r") and config.get("block_c"):
            argv += ["--block",
                     f"{int(config['block_r'])}x{int(config['block_c'])}"]
        if self.interpret:
            argv.append("--interpret")
        return argv

    def env(self, config, workdir):
        env = _child_env()
        env.setdefault("JAX_PLATFORMS", "cpu")
        # the trial must measure the candidate, not an ambient table
        env.pop("MXNET_TPU_TUNED_TABLE", None)
        return env

    def score(self, doc, config, workdir):
        metrics = {k: doc.get(k) for k in
                   ("value", "unit", "max_err", "tolerance", "iters",
                    "compiles")}
        if not doc.get("parity_ok", False):
            return None, f"parity:max_err={doc.get('max_err')}", metrics
        value = doc.get("value")
        if not isinstance(value, (int, float)):
            return None, "no_value", metrics
        return float(value), None, metrics


class ServingObjective(_Objective):
    """p99 (lower is better → fitness is −p99) of the closed-loop
    serving bench under a candidate config, gated on the shed rate."""

    name = "serving"

    def __init__(self, seconds: float = 2.0, clients: int = 4,
                 dim: int = 16, max_batch: int = 8,
                 shed_ceiling: float = 0.2, arrival: str | None = None,
                 deadline_s: float = 180.0, hedge: bool = False):
        super().__init__(deadline_s)
        self.seconds = float(seconds)
        self.clients = int(clients)
        self.dim = int(dim)
        self.max_batch = int(max_batch)
        self.shed_ceiling = float(shed_ceiling)
        self.arrival = arrival
        self.hedge = bool(hedge)

    def argv(self, config, resource, workdir):
        seconds = max(0.3, self.seconds * float(resource))
        out = os.path.join(workdir, "trial_bench.json")
        argv = [sys.executable, "-m", "mxnet_tpu.serving", "bench",
                "--seconds", f"{seconds:g}",
                "--clients", str(self.clients),
                "--dim", str(self.dim),
                "--max-batch", str(self.max_batch),
                "--out", out]
        if "window_ms" in config:
            argv += ["--window-ms", f"{float(config['window_ms']):g}"]
        if "max_queue" in config:
            argv += ["--queue", str(int(config["max_queue"]))]
        if self.hedge and "hedge_ms" in config:
            argv += ["--replicas", "2",
                     "--hedge-ms", f"{float(config['hedge_ms']):g}"]
        if self.arrival:
            argv += ["--arrival", str(self.arrival)]
        return argv

    def env(self, config, workdir):
        env = _child_env()
        env.setdefault("JAX_PLATFORMS", "cpu")
        env.pop("MXNET_TPU_TUNED_TABLE", None)
        # PR-13 store as the trial cache: every trial of this objective
        # shares one AOT dir, so a revisited bucket lattice loads its
        # executables instead of recompiling them
        env.setdefault("MXNET_TPU_AOT_CACHE_DIR",
                       os.path.join(workdir, "aot-trial-cache"))
        return env

    def score(self, doc, config, workdir):
        lat = doc.get("latency_ms") or {}
        completed = doc.get("completed") or 0
        shed = doc.get("client_shed") or 0
        denom = completed + shed
        shed_rate = (shed / denom) if denom else 1.0
        metrics = {"value": doc.get("value"), "p50": lat.get("p50"),
                   "p99": lat.get("p99"), "completed": completed,
                   "client_shed": shed,
                   "shed_rate": round(shed_rate, 4),
                   "compiles": doc.get("compiles"),
                   "compile_bound_ok": doc.get("compile_bound_ok")}
        cp = (doc.get("distributed_trace") or {}).get("critical_path")
        if cp:
            metrics["critical_path"] = cp
        if not completed:
            return None, "no_completions", metrics
        if shed_rate > self.shed_ceiling:
            return None, (f"shed_ceiling:{shed_rate:.3f}"
                          f">{self.shed_ceiling:g}"), metrics
        p99 = lat.get("p99")
        if p99 is None:
            return None, "no_p99", metrics
        return -float(p99), None, metrics


class TrialRunner:
    """Evaluates configurations for one objective: deadline, journal,
    memo.  ``evaluate(config, resource=1.0)`` is the closure handed to
    :mod:`.search`; identical (config, resource) pairs return the
    memoized result (journaled as ``cached`` — coordinate descent
    revisits incumbents freely)."""

    def __init__(self, objective: _Objective, workdir: str | None = None):
        self.objective = objective
        self.workdir = workdir or tempfile.mkdtemp(prefix="mxtpu-autotune-")
        os.makedirs(self.workdir, exist_ok=True)
        self.history: list = []
        self._memo: dict = {}

    @staticmethod
    def _memo_key(config: dict, resource: float):
        return (tuple(sorted((k, tuple(v) if isinstance(v, (list, tuple))
                              else v) for k, v in config.items())),
                round(float(resource), 4))

    def evaluate(self, config: dict, resource: float = 1.0) -> TrialResult:
        key = self._memo_key(config, resource)
        prior = self._memo.get(key)
        tid = next(_trial_seq)
        if prior is not None:
            res = TrialResult(
                trial_id=tid, objective=self.objective.name,
                config=dict(config), fitness=prior.fitness, ok=prior.ok,
                gate=prior.gate, metrics=dict(prior.metrics), cached=True,
                resource=float(resource), duration_s=0.0)
            self._journal(res)
            self.history.append(res)
            return res
        t0 = time.monotonic()
        with _trace.span("autotune_trial", objective=self.objective.name,
                         trial=tid):
            fitness, gate, metrics = self.objective.run(
                config, float(resource), self.workdir)
        res = TrialResult(
            trial_id=tid, objective=self.objective.name,
            config=dict(config), fitness=fitness, ok=gate is None,
            gate=gate, metrics=metrics, cached=False,
            resource=float(resource),
            duration_s=round(time.monotonic() - t0, 3))
        self._memo[key] = res
        self._journal(res)
        self.history.append(res)
        return res

    def _journal(self, res: TrialResult) -> None:
        get_journal().event(
            "autotune_trial", trial=res.trial_id,
            objective=res.objective, config=res.config,
            fitness=res.fitness, ok=res.ok, gate=res.gate,
            cached=res.cached, resource=res.resource,
            duration_s=res.duration_s,
            **{k: v for k, v in res.metrics.items()
               if isinstance(v, (int, float, str, bool))})

    def best(self) -> TrialResult | None:
        scored = [r for r in self.history if r.fitness is not None]
        return max(scored, key=lambda r: r.fitness) if scored else None

    def baseline(self, default_config: dict) -> TrialResult | None:
        """The default configuration's own trial (the A/B anchor)."""
        key_cfg = self._memo_key(default_config, 0.0)[0]
        for r in self.history:
            if self._memo_key(r.config, 0.0)[0] == key_cfg:
                return r
        return None

    def summary(self) -> dict:
        gated = [r for r in self.history if not r.ok]
        return {"objective": self.objective.name,
                "trials": len(self.history),
                "cached": sum(r.cached for r in self.history),
                "gated": len(gated),
                "gate_reasons": sorted({r.gate for r in gated if r.gate}),
                "trial_ids": [r.trial_id for r in self.history]}

"""Autotuner CLI: ``python -m mxnet_tpu.autotune search|show|apply``.

``search``  — closed-loop search over ≥2 knob families (Pallas block
              shape for one kernel×shape-class + the serving window/
              queue knobs) against the real harnesses; commits a tuned
              table + a BENCH-schema artifact.  Budget-bounded (trial
              count AND wall-clock), seeded, every trial journaled.
``show``    — stdlib audit of a table (the ``doctor --tuned`` body).
``apply``   — validate a candidate table end to end, then atomically
              install it at the active path (old-or-new under any
              crash or concurrent reader).
``_trial``  — internal: one kernel trial in a child process (the
              deadlined-subprocess contract's far side).

Artifact contract (bench.py): exactly ONE JSON line on stdout;
failures emit a structured error line, never a hang.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

METRIC = "autotune_search_trials"


def _emit(obj: dict) -> None:
    print(json.dumps(obj), flush=True)


def _diagnostic(error: str, detail: str) -> dict:
    return {"metric": METRIC, "value": None, "unit": "trials",
            "error": error, "detail": detail}


def _parse_rc(spec: str):
    try:
        r, c = (int(v) for v in str(spec).lower().split("x"))
        if r <= 0 or c <= 0:
            raise ValueError
        return r, c
    except ValueError:
        raise ValueError(f"bad RxC spec {spec!r}") from None


# ---------------------------------------------------------------------------
# _trial: one kernel evaluation in THIS (child) process
# ---------------------------------------------------------------------------
def cmd_trial(args) -> int:
    import jax.numpy as jnp
    import numpy as np

    from ..observability import compile_stats
    from ..pallas import registry

    spec = registry.get_kernel(args.kernel)
    r, c = _parse_rc(args.shape)
    rng = np.random.RandomState(0)
    params = {}
    if args.kernel == "conv_epilogue":
        call_args = (jnp.asarray(rng.randn(r, c), jnp.float32),
                     jnp.asarray(rng.rand(1, c) + 0.5, jnp.float32),
                     jnp.asarray(rng.randn(1, c) * 0.1, jnp.float32),
                     None)
        params["act_type"] = "relu"
    elif args.kernel == "matmul_epilogue":
        call_args = (jnp.asarray(rng.randn(r, c), jnp.float32),
                     jnp.asarray(rng.randn(1, c) * 0.1, jnp.float32),
                     None)
        params["act_type"] = "gelu"
    else:
        _emit({"metric": "autotune_kernel_elems_per_sec", "value": None,
               "error": "unknown_kernel", "detail": args.kernel})
        return 1
    block = None
    if args.block:
        block = _parse_rc(args.block)
        params["block"] = block

    def run():
        return registry.dispatch(args.kernel, *call_args,
                                 interpret=args.interpret, **params)

    out = run()
    ref = spec.xla_reference(*call_args, **{k: v for k, v in params.items()
                                            if k != "block"})
    max_err = float(jnp.max(jnp.abs(out.astype(jnp.float32)
                                    - ref.astype(jnp.float32))))
    parity_ok = bool(max_err <= spec.tolerance)
    iters = max(1, int(args.iters))
    t0 = time.perf_counter()
    for _ in range(iters):
        run().block_until_ready()
    elapsed = time.perf_counter() - t0
    value = round(r * c * iters / elapsed, 2) if elapsed else None
    prov = registry.tier_provenance().get(args.kernel, {})
    _emit({"metric": "autotune_kernel_elems_per_sec", "value": value,
           "unit": f"elems/s ({args.kernel} {r}x{c}, "
                   f"block={block}, iters={iters})",
           "max_err": max_err, "tolerance": spec.tolerance,
           "parity_ok": parity_ok, "iters": iters,
           "block": list(block) if block else None,
           "pallas_dispatches": prov.get("pallas", 0),
           "xla_dispatches": prov.get("xla", 0),
           "compiles": compile_stats().get("compiles", 0)})
    return 0 if parity_ok else 1


# ---------------------------------------------------------------------------
# search
# ---------------------------------------------------------------------------
def cmd_search(args) -> int:
    from ..diagnostics import get_journal
    from ..resilience.atomic import atomic_write
    from . import runner as _runner
    from . import search as _search
    from . import space as _space
    from . import table as _table

    j = get_journal()
    j.install_handlers(final_cb=lambda: _emit(_diagnostic(
        "search_killed", f"killed at phase {j.last_phase!r} before "
        "completion; see the journal for autotune_trial breadcrumbs")))
    j.set_phase("autotune_setup")
    t_start = time.monotonic()
    deadline = t_start + args.budget_s
    r, c = _parse_rc(args.kernel_shape)
    families = [f.strip() for f in args.families.split(",") if f.strip()]
    unknown = sorted(set(families) - {"kernel", "serving"})
    if unknown:
        _emit(_diagnostic("bad_families", f"unknown families {unknown}"))
        return 1
    per_family = max(2, args.trials // max(1, len(families)))
    j.event("autotune_search_start", families=families,
            trials=args.trials, budget_s=args.budget_s, seed=args.seed,
            kernel=args.kernel, kernel_shape=f"{r}x{c}")

    plans = {}
    if "kernel" in families:
        plans["kernel"] = (
            _space.pallas_block_space(args.kernel, r, c),
            _runner.TrialRunner(_runner.KernelObjective(
                kernel=args.kernel, r=r, c=c, iters=args.kernel_iters,
                deadline_s=args.trial_deadline_s), workdir=args.workdir))
    if "serving" in families:
        plans["serving"] = (
            _space.serving_space(),
            _runner.TrialRunner(_runner.ServingObjective(
                seconds=args.bench_seconds, clients=args.clients,
                dim=args.dim, max_batch=args.max_batch,
                shed_ceiling=args.shed_ceiling, arrival=args.arrival,
                deadline_s=args.trial_deadline_s), workdir=args.workdir))

    results, knobs = {}, {}
    for family, (space, trunner) in plans.items():
        j.set_phase(f"autotune_search_{family}")
        wall_left = max(1.0, deadline - time.monotonic())
        budget = _search.Budget(max_trials=per_family, wall_s=wall_left)
        _search.run_search(space, trunner.evaluate, budget,
                           seed=args.seed, halving_n0=args.halving,
                           descent_rounds=args.descent_rounds)
        best = trunner.best()
        base = trunner.baseline(space.default)
        results[family] = {
            "space": space.name,
            **trunner.summary(),
            "budget_exhausted": budget.exhausted(),
            "baseline": None if base is None else {
                "config": base.config, "fitness": base.fitness,
                "trial": base.trial_id},
            "best": None if best is None else {
                "config": best.config, "fitness": best.fitness,
                "trial": best.trial_id},
            "tuned_ge_default": (
                best is not None
                and (base is None or base.fitness is None
                     or best.fitness >= base.fitness)),
        }
        if best is None:
            continue
        if family == "kernel":
            knobs.setdefault("pallas", {})[args.kernel] = {
                f"{r}x{c}": {"block": [int(best.config["block_r"]),
                                       int(best.config["block_c"])]}}
        else:
            knobs["serving"] = {
                "window_ms": float(best.config["window_ms"]),
                "max_queue": int(best.config["max_queue"])}

    j.set_phase("autotune_commit")
    elapsed = round(time.monotonic() - t_start, 2)
    total = sum(f["trials"] for f in results.values())
    table_path = None
    if knobs:
        provenance = {
            "search": {"seed": args.seed, "trials": args.trials,
                       "budget_s": args.budget_s,
                       "halving": args.halving,
                       "descent_rounds": args.descent_rounds},
            "trials": total,
            "trial_ids": {f: results[f]["trial_ids"] for f in results},
            "journal": os.environ.get("MXNET_TPU_JOURNAL", "stderr"),
            "artifact": args.out or None,
        }
        doc = _table.build_table(knobs, provenance=provenance)
        table_path = _table.commit_table(doc, args.table)

    j.set_phase("autotune_report")
    artifact = {
        "metric": METRIC, "value": total, "unit": "trials",
        "elapsed_s": elapsed, "budget_s": args.budget_s,
        "seed": args.seed, "families": results,
        "table": table_path,
        "tuned_ge_default": all(f.get("tuned_ge_default")
                                for f in results.values()),
    }
    if args.out:
        with atomic_write(args.out, "w") as f:
            json.dump(artifact, f, indent=1, sort_keys=True)
        print(f"autotune search: artifact written to {args.out}",
              file=sys.stderr)
    _emit(artifact)
    j.mark_clean()
    return 0 if table_path is not None else 1


# ---------------------------------------------------------------------------
# show / apply
# ---------------------------------------------------------------------------
def cmd_show(args) -> int:
    from . import table as _table
    path = args.table or os.environ.get(_table.ENV_TABLE, "")
    if not path:
        _emit({"ok": False, "error": "no_table",
               "detail": f"pass --table or set {_table.ENV_TABLE}"})
        return 1
    report = _table.audit_table(path)
    _emit(report)
    return 0 if report.get("ok") else 1


def cmd_apply(args) -> int:
    from ..diagnostics import get_journal
    from . import table as _table
    doc, reason = _table.read_table(args.src)
    if doc is None:
        _emit({"ok": False, "error": f"invalid_table:{reason}",
               "src": args.src})
        return 1
    if args.check_envelope:
        _doc, reason = _table.read_table(
            args.src, envelope=_table.current_envelope())
        if reason is not None:
            _emit({"ok": False, "error": f"envelope:{reason}",
                   "src": args.src,
                   "table_envelope": doc.get("envelope"),
                   "host_envelope": _table.current_envelope()})
            return 1
    dest = args.dest or os.environ.get(_table.ENV_TABLE, "")
    if not dest:
        _emit({"ok": False, "error": "no_dest",
               "detail": f"pass --dest or set {_table.ENV_TABLE}"})
        return 1
    _table.commit_table(doc, dest)
    get_journal().event("tuned_apply", src=args.src, dest=dest,
                        crc32=doc["crc32"])
    _emit({"ok": True, "src": args.src, "dest": dest,
           "crc32": doc["crc32"], "families": sorted(doc["knobs"])})
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m mxnet_tpu.autotune",
        description="closed-loop autotuner (docs/autotune.md)")
    sub = ap.add_subparsers(dest="cmd", required=True)

    s = sub.add_parser("search", help="search the knob space against "
                                      "the real harnesses; commit a "
                                      "tuned table + BENCH artifact")
    s.add_argument("--table", default="tuned_table.json",
                   help="tuned-table output path (the file "
                        "MXNET_TPU_TUNED_TABLE should point at)")
    s.add_argument("--out", default="BENCH_autotune.json",
                   help="BENCH-schema artifact path ('' disables)")
    s.add_argument("--trials", type=int,
                   default=int(os.environ.get(
                       "MXNET_TPU_AUTOTUNE_TRIALS", 16)),
                   help="total trial budget across families (default "
                        "MXNET_TPU_AUTOTUNE_TRIALS or 16)")
    s.add_argument("--budget-s", type=float,
                   default=float(os.environ.get(
                       "MXNET_TPU_AUTOTUNE_BUDGET_S", 120.0)),
                   help="wall-clock budget in seconds (default "
                        "MXNET_TPU_AUTOTUNE_BUDGET_S or 120)")
    s.add_argument("--seed", type=int, default=0)
    s.add_argument("--families", default="kernel,serving",
                   help="comma list of knob families to search "
                        "(kernel, serving)")
    s.add_argument("--kernel", default="conv_epilogue",
                   help="registered Pallas kernel to tune")
    s.add_argument("--kernel-shape", default="256x128",
                   help="RxC shape class to tune the kernel at")
    s.add_argument("--kernel-iters", type=int, default=30)
    s.add_argument("--bench-seconds", type=float, default=1.5,
                   help="closed-loop serving bench seconds per trial")
    s.add_argument("--clients", type=int, default=4)
    s.add_argument("--dim", type=int, default=16)
    s.add_argument("--max-batch", type=int, default=8)
    s.add_argument("--shed-ceiling", type=float, default=0.2,
                   help="serving gate: max tolerated shed rate")
    s.add_argument("--arrival", default=None,
                   help="recorded arrival trace for the serving trials "
                        "(serving bench --arrival)")
    s.add_argument("--halving", type=int, default=0,
                   help="> 0 seeds successive halving with N configs "
                        "instead of plain random sampling")
    s.add_argument("--descent-rounds", type=int, default=1)
    s.add_argument("--trial-deadline-s", type=float, default=150.0,
                   help="hard per-trial subprocess deadline")
    s.add_argument("--workdir", default=None,
                   help="trial scratch dir (shared AOT trial cache "
                        "lives here; default a fresh tempdir)")
    s.set_defaults(fn=cmd_search)

    sh = sub.add_parser("show", help="stdlib audit of a tuned table "
                                     "(no backend dial, nothing applied)")
    sh.add_argument("--table", default=None,
                    help="table path (default MXNET_TPU_TUNED_TABLE)")
    sh.set_defaults(fn=cmd_show)

    a = sub.add_parser("apply", help="validate a candidate table and "
                                     "atomically install it at the "
                                     "active path")
    a.add_argument("--src", required=True, help="candidate table path")
    a.add_argument("--dest", default=None,
                   help="install path (default MXNET_TPU_TUNED_TABLE)")
    a.add_argument("--check-envelope", action="store_true",
                   help="also require the table's envelope to match "
                        "THIS host (one guarded backend dial)")
    a.set_defaults(fn=cmd_apply)

    t = sub.add_parser("_trial")   # internal: runner.py's child
    t.add_argument("--kernel", required=True)
    t.add_argument("--shape", required=True)
    t.add_argument("--block", default=None)
    t.add_argument("--iters", type=int, default=30)
    t.add_argument("--interpret", action="store_true")
    t.set_defaults(fn=cmd_trial)

    args = ap.parse_args(argv)
    try:
        return args.fn(args)
    except Exception as e:          # structured line, never a bare crash
        from ..diagnostics import get_journal
        get_journal().crash(e)
        _emit(_diagnostic("autotune_crashed", f"{type(e).__name__}: {e}"))
        get_journal().mark_clean()
        return 1


if __name__ == "__main__":
    sys.exit(main())

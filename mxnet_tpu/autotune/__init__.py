"""Closed-loop autotuner: search the runtime's knob space against the
real harnesses and commit versioned tuned tables the runtime loads
(docs/autotune.md).

- :mod:`.space`  — typed, validity-gated search spaces over real knobs
- :mod:`.runner` — deadlined-subprocess trial evaluation + journaling
- :mod:`.search` — seeded random / successive-halving / coordinate
  descent, budget-bounded
- :mod:`.table`  — versioned CRC'd tuned tables (commit, load, audit)

CLI: ``python -m mxnet_tpu.autotune search|show|apply``.  All four
modules are stdlib-importable (no jax at import time) so ``doctor
--tuned`` can audit a table on a wedged host.
"""
from . import search, space, table
from .search import Budget, run_search
from .space import (Space, bucket_space, decode_space,
                    pallas_block_space, router_space, serving_space)
from .table import (ENV_TABLE, TABLE_FORMAT, audit_table, build_table,
                    commit_table, read_table, tuned_for)

__all__ = [
    "Budget", "ENV_TABLE", "Space", "TABLE_FORMAT", "audit_table",
    "bucket_space", "build_table", "commit_table", "decode_space",
    "pallas_block_space", "read_table", "router_space", "run_search",
    "search", "serving_space", "space", "table", "tuned_for",
]

"""Tuned tables: versioned, CRC-guarded knob documents the runtime loads.

The autotuner's output is data, not code edits: one JSON document
holding machine-chosen values for the real knobs — per-op×shape-class
Pallas block shapes, serving ``window_ms``/queue bound, router hedge
delay, decode slot count, bucket lattices — committed atomically via
``resilience.atomic`` and loaded at runtime by ``pallas.dispatch()``,
``Server``/``BucketGrid``, and ``Router`` (``MXNET_TPU_TUNED_TABLE``).

Discipline mirrors the AOT cache (serving/aot_report.py, graftlint
G21): the document carries a format tag, a CRC over its canonical
serialization, and a compatibility envelope (platform, device kind,
jax version) — a table tuned on one toolchain/topology never applies
on another.  The read path validates bounds, JSON, format, CRC,
schema, and envelope **before** any knob value is believed; every
failure degrades to the built-in defaults with ONE journaled
``tuned_fallback{reason}`` per (path, reason) — never a crash, never
silently wrong.  Successful consumers journal ``tuned_load`` with the
values they applied, so a run's effective configuration is always in
the journal.

Stdlib-only except :func:`current_envelope` (one lazy guarded backend
dial); :func:`audit_table` never dials — ``doctor --tuned`` works while
jax itself is wedged.
"""
from __future__ import annotations

import json
import os
import threading
import time
import zlib

from ..diagnostics.journal import get_journal
from ..resilience import atomic as _atomic

__all__ = ["TABLE_FORMAT", "ENV_TABLE", "KNOB_FAMILIES", "build_table",
           "commit_table", "read_table", "validate_schema", "table_crc",
           "current_envelope", "tuned_for", "knob", "pallas_entry",
           "audit_table", "reset_cache"]

TABLE_FORMAT = "mxtpu-tuned-v1"
ENV_TABLE = "MXNET_TPU_TUNED_TABLE"
# a tuned table is a small document; a multi-megabyte file at this path
# is some other artifact (or garbage) — reject before json.loads sees it
MAX_TABLE_BYTES = 1 << 20
KNOB_FAMILIES = ("pallas", "serving", "router", "decode", "buckets")
_SCALARS = {"serving": ("window_ms", "max_queue"),
            "router": ("hedge_ms",),
            "decode": ("slots",)}
# re-stat throttle for the cached runtime loader: dispatch() consults
# the table per dispatch decision, which must not cost a stat() each —
# a freshly applied table is picked up within this window
_RECHECK_S = 1.0

_lock = threading.Lock()
_cache: dict = {}          # path -> {stat, doc, reason, checked}
_journaled: set = set()    # (path, reason) tuned_fallback dedupe
_envelope = None


# ---------------------------------------------------------------------------
# document construction
# ---------------------------------------------------------------------------
def canonical_bytes(doc: dict) -> bytes:
    """Canonical serialization (sorted keys, no whitespace) of ``doc``
    WITHOUT its ``crc32`` field — the bytes the CRC covers."""
    body = {k: v for k, v in doc.items() if k != "crc32"}
    return json.dumps(body, sort_keys=True,
                      separators=(",", ":")).encode("utf-8")


def table_crc(doc: dict) -> int:
    return zlib.crc32(canonical_bytes(doc)) & 0xFFFFFFFF


def current_envelope() -> dict:
    """Compatibility envelope of THIS process (one guarded backend
    dial, memoized): the platform/device-kind/jax-version triple a
    table must match to apply."""
    global _envelope
    if _envelope is None:
        import jax

        from ..diagnostics import guard
        dev = guard.devices(local=True)
        _envelope = {"platform": dev[0].platform,
                     "device_kind": dev[0].device_kind,
                     "jax": jax.__version__}
    return _envelope


def build_table(knobs: dict, provenance: dict | None = None,
                envelope: dict | None = None,
                created: float | None = None) -> dict:
    """Assemble a tuned-table document (validated; raises ValueError on
    a malformed knob set — the WRITER must not produce a table the
    reader would reject)."""
    doc = {"format": TABLE_FORMAT,
           "created": time.time() if created is None else float(created),
           "envelope": dict(envelope if envelope is not None
                            else current_envelope()),
           "provenance": dict(provenance or {}),
           "knobs": knobs}
    reason = validate_schema(doc)
    if reason is not None:
        raise ValueError(f"refusing to build invalid tuned table: {reason}")
    doc["crc32"] = table_crc(doc)
    return doc


def commit_table(doc: dict, path: str) -> str:
    """Atomically commit ``doc`` to ``path`` (tmp + fsync + replace —
    a racing reader observes complete old or complete new bytes, never
    a torn table).  Journals ``tuned_commit``."""
    reason = validate_schema(doc)
    if reason is not None:
        raise ValueError(f"refusing to commit invalid tuned table: {reason}")
    if doc.get("crc32") != table_crc(doc):
        raise ValueError("refusing to commit tuned table with stale crc32")
    path = os.fspath(path)
    with _atomic.atomic_write(path, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
    get_journal().event("tuned_commit", path=path,
                        families=sorted(doc["knobs"]),
                        crc32=doc["crc32"])
    return path


# ---------------------------------------------------------------------------
# validation (pure; shared by writer, loader, and the doctor audit)
# ---------------------------------------------------------------------------
def _pos_int(v) -> bool:
    return isinstance(v, int) and not isinstance(v, bool) and v > 0


def _num(v) -> bool:
    return (isinstance(v, (int, float)) and not isinstance(v, bool)
            and v >= 0)


def validate_schema(doc) -> str | None:
    """Structural validity of a parsed table document; returns a
    ``schema:<detail>`` reason or None.  Does NOT check CRC/envelope —
    the read path layers those."""
    if not isinstance(doc, dict):
        return "schema:not_object"
    if not isinstance(doc.get("envelope"), dict):
        return "schema:envelope"
    knobs = doc.get("knobs")
    if not isinstance(knobs, dict) or not knobs:
        return "schema:knobs"
    for family, body in knobs.items():
        if family not in KNOB_FAMILIES:
            return f"schema:family:{family}"
        if family in _SCALARS:
            if not isinstance(body, dict):
                return f"schema:{family}"
            for name, v in body.items():
                if name not in _SCALARS[family] or not _num(v):
                    return f"schema:{family}.{name}"
        elif family == "pallas":
            if not isinstance(body, dict):
                return "schema:pallas"
            for kernel, classes in body.items():
                if not isinstance(classes, dict):
                    return f"schema:pallas.{kernel}"
                for cls, entry in classes.items():
                    block = (entry or {}).get("block") \
                        if isinstance(entry, dict) else None
                    if (not isinstance(block, list) or len(block) != 2
                            or not all(_pos_int(b) for b in block)):
                        return f"schema:pallas.{kernel}.{cls}"
        elif family == "buckets":
            if not isinstance(body, dict):
                return "schema:buckets"
            batch = body.get("batch")
            if batch is not None:
                if (not isinstance(batch, list) or not batch
                        or not all(_pos_int(b) for b in batch)
                        or sorted(batch) != batch):
                    return "schema:buckets.batch"
    return None


# ---------------------------------------------------------------------------
# read path
# ---------------------------------------------------------------------------
def read_table(path: str, envelope: dict | None = None):
    """Read + fully validate one table file: returns ``(doc, None)`` or
    ``(None, reason)`` with reason in {missing, unreadable, too_large,
    json, format, crc, schema:*, envelope, stale}.  With ``envelope``,
    platform/device-kind mismatch is ``envelope`` and a jax-version
    drift is ``stale`` — performance data from another toolchain never
    applies silently.  Never raises for a bad file."""
    try:
        size = os.path.getsize(path)
    except OSError:
        return None, "missing"
    if size > MAX_TABLE_BYTES:
        return None, "too_large"
    try:
        with open(path, "rb") as f:
            raw = f.read(MAX_TABLE_BYTES + 1)
    except OSError:
        return None, "unreadable"
    if len(raw) > MAX_TABLE_BYTES:
        return None, "too_large"
    try:
        doc = json.loads(raw.decode("utf-8"))
    except (ValueError, UnicodeDecodeError):
        return None, "json"
    if not isinstance(doc, dict) or doc.get("format") != TABLE_FORMAT:
        return None, "format"
    if doc.get("crc32") != table_crc(doc):
        return None, "crc"
    reason = validate_schema(doc)
    if reason is not None:
        return None, reason
    if envelope is not None:
        have = doc["envelope"]
        for key in ("platform", "device_kind"):
            if have.get(key) != envelope.get(key):
                return None, "envelope"
        if have.get("jax") != envelope.get("jax"):
            return None, "stale"
    return doc, None


def _journal_fallback(path: str, reason: str, site: str) -> None:
    key = (path, reason)
    with _lock:
        if key in _journaled:
            return
        _journaled.add(key)
    get_journal().event("tuned_fallback", path=path, reason=reason,
                        site=site, fallback="builtin_defaults")


def tuned_for(site: str = "runtime"):
    """The active tuned table (``MXNET_TPU_TUNED_TABLE``) or None.

    Cached per path with a ``stat()`` no more than once per second —
    cheap enough for ``dispatch()``'s per-decision consult, fresh
    enough that an ``apply`` lands within a second.  Invalid/stale/
    mismatched tables return None with a deduped journaled
    ``tuned_fallback{reason}``; the caller keeps built-in defaults."""
    path = os.environ.get(ENV_TABLE, "").strip()
    if not path:
        return None
    now = time.monotonic()
    with _lock:
        ent = _cache.get(path)
        if ent is not None and now - ent["checked"] < _RECHECK_S:
            return ent["doc"]
    # all file I/O (stat, read, the backend dial for the envelope) runs
    # OUTSIDE the lock (graftlint G15); worst case two racing threads
    # both read the file once
    try:
        st = os.stat(path)
        stat_key = (st.st_mtime_ns, st.st_size)
    except OSError:
        stat_key = None
    with _lock:
        ent = _cache.get(path)
        if ent is not None and ent["stat"] == stat_key:
            ent["checked"] = now
            return ent["doc"]
    if stat_key is None:
        doc, reason = None, "missing"
    else:
        doc, reason = read_table(path, envelope=current_envelope())
    with _lock:
        _cache[path] = {"stat": stat_key, "doc": doc, "reason": reason,
                        "checked": now}
    if reason is not None:
        _journal_fallback(path, reason, site)
    return doc


def knob(doc, family: str, name: str, default=None):
    """One scalar knob from a loaded table (None-safe)."""
    if doc is None:
        return default
    body = doc.get("knobs", {}).get(family)
    if not isinstance(body, dict):
        return default
    return body.get(name, default)


def pallas_entry(doc, kernel: str, shape_class: str):
    """Per-kernel tuned entry for one shape class (exact class first,
    then the ``*`` wildcard); None when untuned."""
    if doc is None:
        return None
    classes = doc.get("knobs", {}).get("pallas", {}).get(kernel)
    if not isinstance(classes, dict):
        return None
    return classes.get(shape_class) or classes.get("*")


def reset_cache() -> None:
    """Drop the loader cache + journal dedupe (tests; also lets one
    process observe a re-commit immediately)."""
    global _envelope
    with _lock:
        _cache.clear()
        _journaled.clear()
        _envelope = None


# ---------------------------------------------------------------------------
# doctor audit (stdlib-only: no jax, no envelope dial)
# ---------------------------------------------------------------------------
def _flatten_knobs(knobs: dict) -> dict:
    flat = {}
    for family, body in sorted(knobs.items()):
        if family == "pallas":
            for kernel, classes in sorted(body.items()):
                for cls, entry in sorted(classes.items()):
                    block = entry.get("block")
                    flat[f"pallas.{kernel}.{cls}"] = \
                        f"block={block[0]}x{block[1]}"
        elif family == "buckets":
            for name, v in sorted(body.items()):
                flat[f"buckets.{name}"] = v
        else:
            for name, v in sorted(body.items()):
                flat[f"{family}.{name}"] = v
    return flat


def audit_table(path: str) -> dict:
    """``doctor --tuned`` body: validate format/CRC/schema and report
    the table's own envelope, provenance refs, and per-knob values —
    WITHOUT comparing the envelope (no backend dial; the audit must run
    while jax is wedged) and without applying anything."""
    path = os.fspath(path)
    doc, reason = read_table(path)      # no envelope: stdlib-only
    if doc is None:
        return {"ok": False, "path": path, "error": reason}
    prov = doc.get("provenance", {})
    return {"ok": True, "path": path, "format": doc["format"],
            "created": doc.get("created"), "crc32": doc.get("crc32"),
            "envelope": doc["envelope"],
            "envelope_checked": False,
            "trials": prov.get("trials"),
            "journal": prov.get("journal"),
            "artifact": prov.get("artifact"),
            "search": prov.get("search"),
            "knobs": _flatten_knobs(doc["knobs"])}

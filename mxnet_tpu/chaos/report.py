"""``doctor --chaos`` reporter: summarize a directory of campaign
artifacts.

Stdlib-only (the doctor must be able to audit chaos results while jax
is wedged): reads every ``CHAOS_rNN.json`` under the directory, counts
pass/fail per scenario, and surfaces the newest failure's failed
invariants + shrunk reproducer size — the triage entry point after a
red CI chaos gate.
"""
from __future__ import annotations

import os

from .artifact import _revs, read_artifact

__all__ = ["chaos_report", "summarize"]


def chaos_report(dirpath) -> dict:
    """Digest of all chaos artifacts under ``dirpath`` (doctor --chaos
    row; shape mirrors the other stdlib-only doctor reporters)."""
    revs = _revs(dirpath)
    if not revs:
        return {"ok": False, "error": "no_artifacts",
                "detail": f"no CHAOS_r*.json under {dirpath!r}"}
    campaigns = []
    unreadable = []
    for rev, name in revs:
        path = os.path.join(dirpath, name)
        try:
            doc = read_artifact(path)
        except ValueError as exc:
            unreadable.append({"rev": rev, "error": str(exc)})
            continue
        failed = [v["name"] for v in doc.get("verdicts", [])
                  if not v.get("ok")]
        campaigns.append({
            "rev": rev,
            "scenario": doc.get("scenario"),
            "seed": doc.get("seed"),
            "ok": bool(doc.get("ok")),
            "n_faults": len(doc.get("schedule") or []),
            "classes": sorted({s.get("cls") for s in
                               (doc.get("schedule") or [])} - {None}),
            "failed": failed,
            "shrunk_to": (len(doc["shrunk"]) if doc.get("shrunk")
                          else None),
        })
    fails = [c for c in campaigns if not c["ok"]]
    return {"ok": True, "path": dirpath,
            "campaigns": len(campaigns), "failures": len(fails),
            "unreadable": unreadable,
            "last": campaigns[-1] if campaigns else None,
            "last_failure": fails[-1] if fails else None,
            "rows": campaigns}


def summarize(rep) -> str:
    """One stderr line for the doctor (mirrors _summ_* shape)."""
    base = (f"chaos: {rep['campaigns']} campaign(s), "
            f"{rep['failures']} failed")
    last = rep.get("last")
    if last:
        base += (f"; last: r{last['rev']:02d} {last['scenario']} "
                 f"seed={last['seed']} "
                 f"{len(last['classes'])} fault classes "
                 f"({'PASS' if last['ok'] else 'FAIL'})")
    lf = rep.get("last_failure")
    if lf:
        base += (f"; newest failure: {', '.join(lf['failed'])}"
                 + (f", shrunk to {lf['shrunk_to']} fault(s)"
                    if lf.get("shrunk_to") else ""))
    if rep.get("unreadable"):
        base += f"; {len(rep['unreadable'])} unreadable artifact(s)"
    return base

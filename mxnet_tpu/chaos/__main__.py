"""Campaign CLI: ``python -m mxnet_tpu.chaos run|replay|report``.

``run``     — generate a seeded schedule for a registered scenario,
              execute it under load, evaluate every declared invariant,
              shrink on failure, and write ``CHAOS_rNN.json``.
              rc 0 = all invariants held, 1 = a campaign failed.
``replay``  — re-run an artifact's schedule (shrunk reproducer by
              default, ``--full`` for the original) from its recorded
              seed.  rc mirrors ``run``.
``report``  — summarize a directory of artifacts (the ``doctor
              --chaos`` digest).  rc 0 = no failures recorded.

One JSON line on stdout (the artifact/report document); human detail on
stderr — same contract as ``python -m mxnet_tpu.diagnostics``.

Env defaults: ``MXNET_TPU_CHAOS_SEED`` (seed when ``--seed`` is
omitted; falls back to a time-derived seed, printed so any run is
reproducible after the fact) and ``MXNET_TPU_CHAOS_BUDGET_S`` (load
window + shrink-probe budget per execution, default 8).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

from . import artifact, conductor, report, scenarios, schedule

__all__ = ["main"]


def _emit(obj) -> None:
    print(json.dumps(obj, default=str), flush=True)


def _default_seed() -> int:
    env = os.environ.get("MXNET_TPU_CHAOS_SEED")
    if env:
        return int(env)
    return int(time.time() * 1000) % (1 << 31)


def _default_budget() -> float:
    try:
        return float(os.environ.get("MXNET_TPU_CHAOS_BUDGET_S", 8.0))
    except ValueError:
        return 8.0


def cmd_run(args) -> int:
    seed = args.seed if args.seed is not None else _default_seed()
    classes = None
    if args.classes:
        classes = [c.strip() for c in args.classes.split(",") if c.strip()]
        bad = [c for c in classes if c not in schedule.FAULT_CLASSES]
        if bad:
            print(f"chaos: unknown fault class(es) {bad} (choose from "
                  f"{', '.join(schedule.FAULT_CLASSES)})", file=sys.stderr)
            return 2
    print(f"chaos: scenario={args.scenario} seed={seed} "
          f"faults={args.faults} budget={args.budget:g}s",
          file=sys.stderr)
    doc = conductor.run_campaign(
        args.scenario, seed, n_faults=args.faults, classes=classes,
        budget_s=args.budget, out_dir=args.out_dir,
        shrink=not args.no_shrink)
    for line in doc["schedule_human"]:
        print(f"chaos:   {line}", file=sys.stderr)
    for v in doc["verdicts"]:
        mark = "ok " if v["ok"] else "FAIL"
        print(f"chaos: [{mark}] {v['name']}: {v['detail']}",
              file=sys.stderr)
    if doc.get("shrunk"):
        print(f"chaos: shrunk reproducer ({len(doc['shrunk'])} fault(s)):",
              file=sys.stderr)
        for line in doc["shrunk_human"]:
            print(f"chaos:   {line}", file=sys.stderr)
    print(f"chaos: artifact {doc['path']}", file=sys.stderr)
    _emit(doc)
    return 0 if doc["ok"] else 1


def cmd_replay(args) -> int:
    doc = artifact.read_artifact(args.artifact)
    specs = doc["schedule"] if (args.full or not doc.get("shrunk")) \
        else doc["shrunk"]
    print(f"chaos: replaying {args.artifact}: scenario={doc['scenario']} "
          f"seed={doc['seed']} ({len(specs)} fault(s), "
          f"{'full' if specs is doc['schedule'] else 'shrunk'})",
          file=sys.stderr)
    out = conductor.run_campaign(
        doc["scenario"], doc["seed"], schedule=specs,
        budget_s=args.budget if args.budget is not None
        else float(doc.get("budget_s", _default_budget())),
        out_dir=args.out_dir, shrink=False)
    for v in out["verdicts"]:
        mark = "ok " if v["ok"] else "FAIL"
        print(f"chaos: [{mark}] {v['name']}: {v['detail']}",
              file=sys.stderr)
    _emit(out)
    return 0 if out["ok"] else 1


def cmd_report(args) -> int:
    rep = report.chaos_report(args.dir)
    _emit(rep)
    if not rep.get("ok"):
        print(f"chaos: {rep.get('detail', rep.get('error'))}",
              file=sys.stderr)
        return 1
    print(f"chaos: {report.summarize(rep)}", file=sys.stderr)
    return 0 if rep["failures"] == 0 else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m mxnet_tpu.chaos",
        description="seeded chaos campaigns over registered scenarios "
                    "(docs/chaos.md)")
    sub = ap.add_subparsers(dest="cmd", required=True)

    r = sub.add_parser("run", help="run one seeded campaign")
    r.add_argument("scenario", choices=scenarios.names(),
                   help="registered scenario")
    r.add_argument("--seed", type=int, default=None,
                   help="schedule seed (default MXNET_TPU_CHAOS_SEED "
                        "or time-derived, echoed to stderr)")
    r.add_argument("--faults", type=int, default=4,
                   help="schedule size (default 4: one per fault class)")
    r.add_argument("--classes", default=None,
                   help="comma list of fault classes the first draws "
                        "must cover (default: every class the scenario "
                        "supports, in catalog order)")
    r.add_argument("--budget", type=float, default=_default_budget(),
                   help="load-window seconds per execution (default "
                        "MXNET_TPU_CHAOS_BUDGET_S or 8)")
    r.add_argument("--out-dir", default=".",
                   help="artifact + workdir root (default CWD)")
    r.add_argument("--no-shrink", action="store_true",
                   help="skip delta-debugging on failure")
    r.set_defaults(fn=cmd_run)

    p = sub.add_parser("replay", help="re-run an artifact's schedule")
    p.add_argument("artifact", help="CHAOS_rNN.json path")
    p.add_argument("--full", action="store_true",
                   help="replay the original schedule, not the shrunk "
                        "reproducer")
    p.add_argument("--budget", type=float, default=None)
    p.add_argument("--out-dir", default=".")
    p.set_defaults(fn=cmd_replay)

    d = sub.add_parser("report", help="summarize a directory of "
                                      "artifacts")
    d.add_argument("dir", help="directory holding CHAOS_r*.json")
    d.set_defaults(fn=cmd_report)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())

"""Registered campaign scenarios — the live systems faults compose over.

Each scenario wraps one of the repo's existing chaos-drill setups (the
tier-0.5 smokes in ``ci/run_tests.sh``) as a uniform runner the
conductor can drive:

- ``pool``          — 3-replica health-routed pool under closed-loop
                      load (tests/test_serving_pool.py's headline drill,
                      in-process so the fault hook reaches every layer);
- ``crash_matrix``  — the checkpoint commit loop with a concurrent
                      old-or-new reader (tests/test_crash_matrix.py);
- ``fleet``         — two tenants on one fleet, poison/latency on one,
                      the other's traffic protected
                      (tests/test_serving_fleet.py);
- ``deploy``        — canary deployment of a CRC-valid regressed step
                      under load; the parity gate must roll back
                      (tests/test_serving_deploy.py);
- ``elastic``       — a 2-member in-process cohort losing a rank
                      mid-run; the survivor resizes and continues
                      (tests/test_elastic.py).

A scenario declares fault ``targets`` (what the schedule generator may
draw: replica ids, latency/partition trip sites, path fragments) and
``invariants`` (chaos/invariants.py names + params, ALL evaluated after
every campaign).  Runners follow one protocol::

    run = scenario.build(workdir)   # heavyweight deps imported here
    run.start()
    run.tick()                      # ONE closed-loop client step
    run.kill(target)                # process-fault lever (optional)
    run.stop()
    obs = run.observations()

Adding a scenario = subclass :class:`ScenarioRun`, declare targets +
invariants, call :func:`register` (docs/chaos.md walks through it).
"""
from __future__ import annotations

import os
import threading
import time

__all__ = ["Scenario", "ScenarioRun", "SCENARIOS", "Counters", "get",
           "names", "register"]


class Counters:
    """Thread-safe closed-loop client accounting (N client threads)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.ok = 0
        self.shed = 0
        self.degraded = 0
        self.corrupt: list = []
        self.unexpected: list = []

    def bump(self, field):
        with self._lock:
            setattr(self, field, getattr(self, field) + 1)

    def add(self, field, item, cap=16):
        with self._lock:
            lst = getattr(self, field)
            if len(lst) < cap:
                lst.append(item)

    def snapshot(self) -> dict:
        with self._lock:
            return {"ok": self.ok, "shed": self.shed,
                    "degraded": self.degraded,
                    "corrupt": list(self.corrupt),
                    "unexpected": list(self.unexpected)}


class Scenario:
    """Registry row: construction is lazy (``build`` imports the heavy
    serving/elastic stacks only when a campaign actually runs)."""

    def __init__(self, name, doc, builder, targets, invariants,
                 clients=2):
        self.name = name
        self.doc = doc
        self.builder = builder
        self.targets = dict(targets)
        self.invariants = list(invariants)
        self.clients = int(clients)

    def build(self, workdir):
        return self.builder(workdir)


SCENARIOS: dict = {}


def register(scenario) -> Scenario:
    SCENARIOS[scenario.name] = scenario
    return scenario


def get(name) -> Scenario:
    try:
        return SCENARIOS[name]
    except KeyError:
        raise ValueError(f"unknown scenario {name!r} "
                         f"(registered: {', '.join(names())})") from None


def names() -> list:
    return sorted(SCENARIOS)


class ScenarioRun:
    """Base runner: subclasses fill in start/tick/stop (+ kill when the
    scenario supports process faults)."""

    def __init__(self, workdir):
        self.workdir = str(workdir)
        self.counters = Counters()
        self.kills: list = []
        self.cfg_doc: dict = {}

    def start(self):
        raise NotImplementedError

    def tick(self):
        raise NotImplementedError

    def kill(self, target):
        raise NotImplementedError(f"{type(self).__name__} has no "
                                  "process-kill lever")

    def stop(self):
        raise NotImplementedError

    def observations(self) -> dict:
        return {"counters": self.counters.snapshot(),
                "kills": list(self.kills), "cfg": dict(self.cfg_doc),
                "workdir": self.workdir}


# -- shared fixtures ---------------------------------------------------------

def _scale_net():
    """y = x*w — the weight value IS the served step's fingerprint."""
    from ..gluon.block import HybridBlock

    class Scale(HybridBlock):
        def __init__(self, **kwargs):
            super().__init__(**kwargs)
            with self.name_scope():
                self.w = self.params.get("w", shape=(1,), init="ones")

        def hybrid_forward(self, F, x, w):
            return x * w

    net = Scale()
    net.initialize()
    return net


def commit_scale(root, step, value):
    """Commit one Scale checkpoint whose weight is ``value``."""
    import numpy as np
    from .. import nd
    from ..resilience import commit
    stage = commit.prepare_stage(root, step)
    nd.save(os.path.join(stage, "net.params"),
            {"w": nd.array(np.asarray([float(value)], np.float32))})
    return commit.finalize(root, step)


# -- pool: the flagship (3 replicas, closed-loop, full fault surface) --------

class PoolRun(ScenarioRun):
    def __init__(self, workdir):
        super().__init__(workdir)
        import numpy as np
        from ..serving import (ParamStore, PoolConfig, ReplicaPool,
                               Router, RouterConfig, Server, ServerConfig)
        self._np = np
        self.ckpt = os.path.join(self.workdir, "ckpt")
        commit_scale(self.ckpt, 1, 3.0)
        cfg = PoolConfig(heartbeat_s=0.1, deadline_s=0.6, monitor_s=0.15,
                         spawn_s=3.0, max_respawns=8, drain_s=2.0)
        self.cfg_doc = {"deadline_s": cfg.deadline_s,
                        "monitor_s": cfg.monitor_s}
        self.pool = ReplicaPool(os.path.join(self.workdir, "pool"), cfg)

        def factory(_Server=Server, _SC=ServerConfig, _PS=ParamStore):
            return _Server(_scale_net(),
                           config=_SC(max_batch=4, window_ms=1.0,
                                      reload_poll_s=0.1),
                           param_store=_PS(self.ckpt))

        for i in range(3):
            self.pool.add_local(f"r{i}", factory)
        self.router_cls = (Router, RouterConfig)
        self.router = None
        self.x = np.arange(4, dtype=np.float32)

    def start(self):
        Router, RouterConfig = self.router_cls
        self.pool.start()
        self.pool.monitor_start()
        self.router = Router(self.pool, RouterConfig(
            retries=3, breaker_k=2, breaker_cooldown_s=0.5))

    def tick(self):
        from ..serving import ServerOverloaded
        from ..serving.batcher import RequestError
        np, c = self._np, self.counters
        try:
            resp = self.router.call(self.x, deadline_ms=2000)
        except ServerOverloaded:
            c.bump("shed")
            time.sleep(0.01)
            return
        except RequestError:
            c.bump("degraded")
            time.sleep(0.01)
            return
        except Exception as exc:
            c.add("unexpected", repr(exc))
            time.sleep(0.02)
            return
        v = np.asarray(resp.value)
        if not np.allclose(v, self.x * 3.0, atol=1e-5):
            c.add("corrupt", v.tolist())
        c.bump("ok")
        time.sleep(0.004)

    def kill(self, target):
        self.kills.append({"target": str(target), "t_kill": time.time(),
                           "t_mono": time.monotonic()})
        self.pool.replicas[str(target)].kill()

    def stop(self):
        if self.router is not None:
            self.router.stop()
        self.pool.stop()

    def observations(self):
        obs = super().observations()
        obs["ckpt_root"] = self.ckpt
        return obs


register(Scenario(
    "pool",
    "3-replica health-routed pool under closed-loop load",
    PoolRun,
    targets={"replicas": ["r0", "r1", "r2"], "kill": True,
             "latency_site": "router_attempt",
             "partition_site": "router_attempt",
             "hb_path_part": "hb/",
             "classes": ("process", "durability", "latency", "resource")},
    invariants=[("progress", {}), ("zero_corrupt", {}),
                ("structured_only", {}), ("shed_rate", {"ceiling": 0.5}),
                ("recovery_deadline", {"slack_s": 4.0}),
                ("store_old_or_new", {}), ("no_litter", {}),
                ("degrades_journaled", {})],
    clients=3))


# -- crash_matrix: the commit loop + old-or-new reader -----------------------

class CrashMatrixRun(ScenarioRun):
    def __init__(self, workdir):
        super().__init__(workdir)
        self.ckpt = os.path.join(self.workdir, "ckpt")
        commit_scale(self.ckpt, 1, 1.0)
        self.step = 1
        self.reads: list = []
        self.cfg_doc = {}
        self._lock = threading.Lock()

    def start(self):
        pass

    def tick(self):
        import numpy as np
        from .. import nd
        from ..base import MXNetError
        from ..resilience import commit
        from ..testing.faults import SimulatedCrash
        c = self.counters
        with self._lock:
            nxt = self.step + 1
            try:
                commit_scale(self.ckpt, nxt, float(nxt))
                self.step = nxt
                c.bump("ok")
            except SimulatedCrash:
                c.bump("degraded")       # the kill shape: litter is GC'd
            except (OSError, ValueError, MXNetError):
                c.bump("degraded")
            except Exception as exc:
                c.add("unexpected", repr(exc))
            # the reader: newest restorable step must load bit-exact
            try:
                found = commit.find_restorable(self.ckpt)
                if found is None:
                    self.reads.append({"valid": False,
                                       "error": "no restorable step"})
                else:
                    step = found[0]
                    d = commit.step_dir(self.ckpt, step)
                    w = nd.load(os.path.join(d, "net.params"))["w"]
                    val = float(np.asarray(w.asnumpy()).reshape(-1)[0])
                    self.reads.append({"step": step,
                                       "valid": val == float(step)})
            except Exception as exc:
                self.reads.append({"valid": False, "error": repr(exc)})
        time.sleep(0.002)

    def stop(self):
        from ..resilience import commit
        from ..resilience.atomic import sweep_tmp
        # the GC a recovering trainer runs: stale staging + tmp litter
        commit.gc_steps(self.ckpt, keep_last=None)
        for step in commit.committed_steps(self.ckpt):
            sweep_tmp(commit.step_dir(self.ckpt, step))

    def observations(self):
        obs = super().observations()
        obs["ckpt_root"] = self.ckpt
        obs["reads"] = list(self.reads)
        return obs


register(Scenario(
    "crash_matrix",
    "checkpoint commit loop with a concurrent old-or-new reader",
    CrashMatrixRun,
    targets={"classes": ("durability", "resource"),
             "crash_path_part": "ckpt"},
    invariants=[("progress", {}), ("structured_only", {}),
                ("reads_old_or_new", {}), ("store_old_or_new", {}),
                ("degrades_journaled", {})],
    clients=1))


# -- fleet: tenant isolation under poison ------------------------------------

class FleetRun(ScenarioRun):
    def __init__(self, workdir):
        super().__init__(workdir)
        import numpy as np
        from ..serving import Fleet, FleetConfig
        self._np = np
        root_a = os.path.join(self.workdir, "ckpt_a")
        root_b = os.path.join(self.workdir, "ckpt_b")
        commit_scale(root_a, 101, 5.0)
        commit_scale(root_b, 201, 2.0)
        # tenant factories build initialized BLOCKS; the fleet wraps
        # them and hot-reloads each tenant from its own commit root
        self.fleet = Fleet(FleetConfig(max_batch=4, window_ms=1.0,
                                       reload_poll_s=0.05,
                                       tenant_breaker_k=3,
                                       tenant_cooldown_s=0.5))
        self.fleet.add_tenant("A", factory=_scale_net, ckpt_root=root_a)
        self.fleet.add_tenant("B", factory=_scale_net, ckpt_root=root_b)
        self.x = np.ones(4, np.float32)
        self.w_by_step = {"A": {101: 5.0}, "B": {201: 2.0}}
        self.tenant_ok = {"A": 0, "B": 0}
        self._flip = 0
        self._lock = threading.Lock()

    def start(self):
        from ..serving.batcher import RequestError
        np = self._np
        self.fleet.start()
        # warm-up OUTSIDE the judged window: each tenant must stamp its
        # own committed step before responses are held to old-or-new
        deadline = time.monotonic() + 15.0
        for tenant, steps in self.w_by_step.items():
            while time.monotonic() < deadline:
                try:
                    resp = self.fleet.submit(self.x, tenant=tenant,
                                             deadline_ms=2000)
                    np.asarray(resp.result(5.0))
                except RequestError:
                    time.sleep(0.02)
                    continue
                if resp.params_step in steps:
                    break
                time.sleep(0.02)

    def tick(self):
        from ..serving.batcher import RequestError
        np, c = self._np, self.counters
        with self._lock:
            self._flip += 1
            tenant = "A" if self._flip % 2 else "B"
        try:
            resp = self.fleet.submit(self.x, tenant=tenant,
                                     deadline_ms=2000)
            out = np.asarray(resp.result(10.0))
        except RequestError:
            c.bump("degraded")       # poison/quarantine: structured
            time.sleep(0.01)
            return
        except Exception as exc:
            c.add("unexpected", repr(exc))
            time.sleep(0.02)
            return
        w = self.w_by_step[tenant].get(resp.params_step)
        if w is None or not np.allclose(out, self.x * w, atol=1e-5):
            c.add("corrupt", [tenant, resp.params_step, out.tolist()])
        else:
            with self._lock:
                # keys are the fixed two-tenant roster, not open-ended
                self.tenant_ok[tenant] += 1  # graftlint: disable=G14 bounded roster
            c.bump("ok")
        time.sleep(0.004)

    def stop(self):
        self.fleet.stop()

    def observations(self):
        obs = super().observations()
        obs["tenant_ok"] = dict(self.tenant_ok)
        return obs


register(Scenario(
    "fleet",
    "two tenants on one fleet; poison on A must not touch B",
    FleetRun,
    targets={"poison_tenants": ["A"], "latency_site": "serving_tenant",
             "latency_path_part": "A",
             "classes": ("process", "latency", "resource")},
    invariants=[("progress", {}), ("zero_corrupt", {}),
                ("structured_only", {}), ("shed_rate", {"ceiling": 0.5}),
                ("protected_tenant", {"tenant": "B"}),
                ("no_litter", {}), ("degrades_journaled", {})],
    clients=2))


# -- deploy: canary a regressed step; parity gate must roll back -------------

class DeployRun(ScenarioRun):
    def __init__(self, workdir):
        super().__init__(workdir)
        import numpy as np
        from ..serving import (DeployConfig, DeployController, ParamStore,
                               PoolConfig, ReplicaPool, Router,
                               RouterConfig, Server, ServerConfig)
        from ..testing import faults as _faults
        self._np = np
        self.ckpt = os.path.join(self.workdir, "ckpt")
        commit_scale(self.ckpt, 1, 3.0)
        cfg = PoolConfig(heartbeat_s=0.1, deadline_s=0.6, monitor_s=0.15,
                         drain_s=2.0)
        self.cfg_doc = {"deadline_s": cfg.deadline_s,
                        "monitor_s": cfg.monitor_s}
        self.pool = ReplicaPool(os.path.join(self.workdir, "pool"), cfg)

        def factory(_Server=Server, _SC=ServerConfig, _PS=ParamStore):
            return _Server(_scale_net(),
                           config=_SC(max_batch=4, window_ms=1.0,
                                      reload_poll_s=-1.0),
                           param_store=_PS(self.ckpt))

        for i in range(3):
            self.pool.add_local(f"r{i}", factory)
        self._deploy_cls = (DeployConfig, DeployController)
        self._router_cls = (Router, RouterConfig)
        self._faults = _faults
        self.router = None
        self.w_by_step = {1: 3.0, 2: 30.0}
        self.result: dict = {}
        self._deploy_thread = None

    def start(self):
        Router, RouterConfig = self._router_cls
        DeployConfig, DeployController = self._deploy_cls
        self.pool.start()
        self.router = Router(self.pool, RouterConfig(retries=3))
        # the regression lands mid-flight, CRC-valid: only parity sees it
        commit_scale(self.ckpt, 2, 3.0)
        self._faults.regress_params(self.ckpt, 2, scale=10.0)
        ctl = DeployController(self.pool, self.router, self.ckpt,
                               DeployConfig(canary_k=1, window_s=0.3,
                                            promote_after=3,
                                            min_samples=5,
                                            mirror_fraction=0.25,
                                            mismatch_budget=0,
                                            rollback_s=10.0,
                                            deadline_s=45.0))

        def _run():
            try:
                self.result.update(ctl.deploy(2))
            except Exception as exc:
                self.result["error"] = repr(exc)

        self._deploy_thread = threading.Thread(target=_run, daemon=True)
        self._deploy_thread.start()

    def tick(self):
        from ..serving import ServerOverloaded
        from ..serving.batcher import RequestError
        np, c = self._np, self.counters
        x = np.arange(4, dtype=np.float32)
        try:
            resp = self.router.call(x, deadline_ms=4000)
        except ServerOverloaded:
            c.bump("shed")
            time.sleep(0.01)
            return
        except RequestError:
            c.bump("degraded")
            time.sleep(0.01)
            return
        except Exception as exc:
            c.add("unexpected", repr(exc))
            time.sleep(0.02)
            return
        w = self.w_by_step.get(resp.params_step)
        if w is None or not np.allclose(np.asarray(resp.value), x * w,
                                        rtol=1e-4, atol=1e-5):
            c.add("corrupt", [resp.params_step,
                              np.asarray(resp.value).tolist()])
        c.bump("ok")
        time.sleep(0.003)

    def stop(self):
        if self._deploy_thread is not None:
            self._deploy_thread.join(timeout=60.0)
        if self.router is not None:
            self.router.stop()
        self.pool.stop()

    def observations(self):
        obs = super().observations()
        obs["deploy"] = dict(self.result)
        return obs


register(Scenario(
    "deploy",
    "canary a CRC-valid regressed step; the parity gate rolls back",
    DeployRun,
    targets={"replicas": ["r0", "r1", "r2"], "kill": False,
             "latency_site": "deploy_canary", "hb_path_part": "hb/",
             "classes": ("durability", "latency", "resource")},
    invariants=[("progress", {}), ("zero_corrupt", {}),
                ("structured_only", {}),
                ("canary_rolled_back", {}), ("no_litter", {}),
                ("degrades_journaled", {})],
    clients=2))


# -- elastic: 2-member cohort, rank loss -> resized survivor -----------------

class CohortRun(ScenarioRun):
    def __init__(self, workdir):
        super().__init__(workdir)
        from .. import elastic
        # barrier_s must be SHORT relative to the campaign window: a
        # one-sided barrier-write failure parks the healthy peer until
        # the barrier deadline, and a 10s park would eat the window
        cfg = dict(heartbeat_s=0.1, deadline_s=0.6, barrier_s=2.0,
                   poll_s=0.01)
        self.cfg_doc = {"deadline_s": cfg["deadline_s"], "monitor_s": 0.0}
        root = os.path.join(self.workdir, "cohort")
        self.c0 = elastic.Cohort(root, 0, elastic.CohortConfig(**cfg))
        self.c1 = elastic.Cohort(root, 1, elastic.CohortConfig(**cfg))
        self._elastic = elastic
        self.solo = False
        self.dead = False
        self.round = 0
        self.resize: dict = {}
        self._lock = threading.Lock()

    def start(self):
        self.c0.start()
        self.c1.start()
        t = threading.Thread(target=lambda: self.c1.form(2), daemon=True)
        t.start()
        self.c0.form(2)
        t.join(timeout=30.0)

    def tick(self):
        elastic, c = self._elastic, self.counters
        # the lock guards only the scenario's bookkeeping (round, solo,
        # resize); the barriers/joins/sleeps run outside it — the
        # single client and the conductor's kill lever must never queue
        # behind a blocked barrier
        with self._lock:
            self.round += 1
            tag = f"chaos-{self.round}"
            solo, dead = self.solo, self.dead
        if solo:
            try:
                self.c0.barrier(tag)
                c.bump("ok")
            except (OSError, elastic.BarrierTimeout):
                c.bump("degraded")         # injected barrier-write I/O
            except Exception as exc:
                c.add("unexpected", repr(exc))
            time.sleep(0.01)
            return
        t = None
        if not dead:
            # a killed rank's process is GONE: it must stop
            # dropping barrier files or the loss is undetectable
            t = threading.Thread(
                target=lambda: self._quiet_barrier(self.c1, tag),
                daemon=True)
            t.start()
        try:
            self.c0.barrier(tag)
            c.bump("ok")
        except elastic.RankLost as e:
            detect_s = (time.monotonic() - self.kills[-1]["t_mono"]
                        if self.kills else None)
            try:
                members = self.c0.resize(e.lost)
            except OSError:
                # injected I/O failure mid-epoch-publish: the next
                # barrier raises RankLost again and resize retries
                c.bump("degraded")
            else:
                with self._lock:
                    self.resize = {"lost": list(e.lost),
                                   "members": list(members),
                                   "detect_s": detect_s}
                    self.solo = True
                c.bump("degraded")
        except (OSError, elastic.BarrierTimeout):
            # injected barrier-write I/O, or the round expired because
            # a peer's (faulted) barrier file never landed — both are
            # structured degrades; the next round starts a fresh tag
            c.bump("degraded")
        except Exception as exc:
            c.add("unexpected", repr(exc))
        if t is not None:
            t.join(timeout=4.0)
        time.sleep(0.01)

    @staticmethod
    def _quiet_barrier(cohort, tag):
        try:
            cohort.barrier(tag)
        except Exception:
            pass                 # the doomed rank's view is not the story

    def kill(self, target):
        # host-vanished for rank 1: heartbeat stalls without resigning,
        # and the rank stops answering barriers (tick checks .dead)
        self.kills.append({"target": str(target), "t_kill": time.time(),
                           "t_mono": time.monotonic()})
        self.dead = True
        self.c1._hb.stop(resign=False)

    def stop(self):
        self.c0.stop()
        try:
            self.c1.stop()
        except Exception:
            pass

    def observations(self):
        obs = super().observations()
        obs["resize"] = dict(self.resize)
        return obs


register(Scenario(
    "elastic",
    "2-member cohort loses a rank; survivor resizes and continues",
    CohortRun,
    targets={"replicas": ["1"], "kill": True, "hb_path_part": "hb",
             "classes": ("process", "durability", "resource")},
    invariants=[("progress", {}), ("structured_only", {}),
                ("cohort_resized", {}), ("degrades_journaled", {})],
    clients=1))

"""Schedule shrinking — delta-debugging a failing fault schedule.

``ddmin`` (Zeller's minimizing delta debugging) over the spec list:
split into ``n`` chunks, try each chunk alone, then each complement;
recurse on whichever still fails with finer granularity until no
smaller subset reproduces.  The test predicate is "same-seed replay of
this subset still violates (one of) the original failed invariants" —
the conductor supplies it as a closure over :func:`chaos.conductor
.execute` with a fresh workdir per probe.

The result is 1-minimal: removing ANY single remaining fault makes the
failure disappear.  That is what turns a 6-fault war story into a
"kill r1 + disk_full at replace" reproducer a human can actually debug.
"""
from __future__ import annotations

__all__ = ["ddmin"]


def ddmin(items, still_fails, max_probes=64) -> list:
    """Minimize ``items`` (a list) under ``still_fails(subset) -> bool``.

    ``still_fails`` must be True for the full list (the caller only
    shrinks schedules that already failed); probes are capped by
    ``max_probes`` — on budget exhaustion the smallest failing subset
    found so far is returned (still a valid reproducer, maybe not
    1-minimal)."""
    current = list(items)
    n = 2
    probes = 0
    while len(current) >= 2 and probes < int(max_probes):
        chunk = max(1, len(current) // n)
        subsets = [current[i:i + chunk]
                   for i in range(0, len(current), chunk)]
        reduced = False
        # each chunk alone, then each complement
        candidates = list(subsets)
        if len(subsets) > 2:
            candidates += [[x for s in subsets[:i] + subsets[i + 1:]
                            for x in s]
                           for i in range(len(subsets))]
        for cand in candidates:
            if not cand or len(cand) >= len(current):
                continue
            probes += 1
            if still_fails(cand):
                current = cand
                n = max(n - 1, 2)
                reduced = True
                break
            if probes >= int(max_probes):
                break
        if not reduced:
            if n >= len(current):
                break
            n = min(n * 2, len(current))
    return current

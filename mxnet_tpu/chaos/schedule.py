"""Seeded fault-schedule generation — the campaign's randomness, bottled.

A schedule is a plain JSON list of fault *specs*.  Every spec carries
its catalog ``kind``, its fault ``cls`` (one of :data:`FAULT_CLASSES`),
a fire offset ``at_s`` inside the load window, and kind-specific args.
:func:`generate` draws one from ``random.Random(seed)`` against a
scenario's declared targets — same seed + same targets → byte-identical
schedule, which is what makes a ``CHAOS_rNN.json`` artifact a
*reproducer* instead of a war story.  :func:`build` turns specs back
into live :mod:`mxnet_tpu.testing.faults` rules plus timed conductor
actions (process kills, budget heals); replay and delta-debugging
shrink both go through it, so a shrunk sub-schedule executes exactly
like the slice of the original it came from.
"""
from __future__ import annotations

import random

from ..testing import faults

__all__ = ["FAULT_CLASSES", "build", "describe", "generate"]

# one fault per class is the composition floor the conductor aims for:
# a kill, a torn/errored durable write, injected latency, and resource
# exhaustion — the production composition single-fault drills never see
FAULT_CLASSES = ("process", "durability", "latency", "resource")

# catalog kind -> fault class (generation + coverage accounting).
# tenant_poison is the fleet's process-fault analog: a sick predictor
# in a pool the scenario cannot SIGKILL ranks of.
CATALOG = {
    "kill": "process",
    "tenant_poison": "process",
    "io_error": "durability",
    "torn_heartbeat": "durability",
    "crash": "durability",
    "slow_call": "latency",
    "partition": "latency",
    "disk_full": "resource",
    "disk_budget": "resource",
    "fd_exhaust": "resource",
}


def _gen_spec(rng, kind, targets, window_s):
    """One catalog draw against the scenario's declared targets."""
    at_s = round(rng.uniform(0.15, 0.6) * window_s, 3)
    spec = {"kind": kind, "cls": CATALOG[kind], "at_s": at_s}
    replicas = list(targets.get("replicas") or ())
    if kind == "kill":
        spec["target"] = rng.choice(replicas)
    elif kind == "tenant_poison":
        spec["tenant"] = rng.choice(list(targets["poison_tenants"]))
        spec["times"] = rng.randint(4, 8)
    elif kind == "io_error":
        spec["point"] = rng.choice(("fsync", "replace"))
        spec["times"] = rng.randint(1, 2)
    elif kind == "torn_heartbeat":
        spec["path_part"] = targets.get("hb_path_part", "hb/")
        spec["times"] = 1
    elif kind == "crash":
        spec["point"] = rng.choice(("write", "fsync", "replace"))
        spec["path_part"] = targets.get("crash_path_part")
        spec["times"] = 1
    elif kind == "slow_call":
        spec["site"] = targets.get("latency_site", "serving_predict")
        spec["delay_s"] = round(rng.uniform(0.05, 0.2), 3)
        spec["path_part"] = targets.get("latency_path_part")
        spec["times"] = rng.randint(2, 5)
    elif kind == "partition":
        spec["site"] = targets.get("partition_site", "wire_send")
        spec["peer"] = rng.choice(replicas) if replicas else None
        spec["stall_s"] = round(rng.uniform(0.3, 0.8), 3)
        spec["times"] = 1
    elif kind == "disk_full":
        spec["point"] = rng.choice(("write", "fsync", "replace"))
        spec["path_part"] = targets.get("disk_path_part")
        spec["times"] = rng.randint(1, 2)
    elif kind == "disk_budget":
        spec["free_bytes"] = rng.randrange(512, 8192)
        spec["heal_after_s"] = round(rng.uniform(0.3, 0.6) * window_s, 3)
    elif kind == "fd_exhaust":
        spec["site"] = rng.choice(
            tuple(targets.get("fd_sites") or ("open",)))
        spec["times"] = rng.randint(1, 3)
    return spec


def generate(seed, targets, n_faults=4, classes=None,
             window_s=8.0) -> list:
    """Draw ``n_faults`` specs from the catalog, deterministically from
    ``seed``.  The first draws cover ``classes`` (default: every class
    the scenario supports, in :data:`FAULT_CLASSES` order — the ≥4-class
    composition floor); the rest are free draws.  Only kinds the
    scenario declared targets for are eligible."""
    rng = random.Random(int(seed))
    supported = set(targets.get("classes") or FAULT_CLASSES)
    kinds = [k for k, c in sorted(CATALOG.items())
             if c in supported and _eligible(k, targets)]
    if not kinds:
        raise ValueError("scenario declares no usable fault targets")
    want = [c for c in (classes or FAULT_CLASSES) if c in supported]
    specs = []
    for cls in want[:int(n_faults)]:
        pool = [k for k in kinds if CATALOG[k] == cls]
        if pool:
            specs.append(_gen_spec(rng, rng.choice(pool), targets,
                                   window_s))
    while len(specs) < int(n_faults):
        specs.append(_gen_spec(rng, rng.choice(kinds), targets,
                               window_s))
    return specs


def _eligible(kind, targets):
    if kind == "kill":
        return bool(targets.get("replicas")) and targets.get("kill", True)
    if kind == "tenant_poison":
        return bool(targets.get("poison_tenants"))
    if kind == "partition":
        return bool(targets.get("partition_site"))
    if kind == "slow_call":
        return bool(targets.get("latency_site"))
    if kind == "crash":
        return bool(targets.get("crash_path_part"))
    return True


class BuiltSchedule:
    """A schedule lowered to executables.

    ``rules`` is ``[(at_s, label, FaultRule)]`` — each rule is ARMED at
    its ``at_s`` on the campaign clock (the conductor appends it to the
    live, initially-empty :class:`~mxnet_tpu.testing.faults.FaultPlan`),
    so a fault drawn "at 4.6s" really does land mid-run instead of
    tripping the scenario's warm-up.  ``timed`` is the remaining action
    list ``[(at_s, label, callable)]``: process kills and disk-budget
    heals.  Order on both is index-aligned with the non-kill /
    kill-spec slices of the input, so firing counts can be attributed
    back to specs."""

    def __init__(self, rules, timed):
        self.rules = rules
        self.timed = sorted(timed, key=lambda t: t[0])


def _lower_rule(spec):
    kind = spec["kind"]
    if kind == "tenant_poison":
        return faults.tenant_poison(spec["tenant"],
                                    times=spec.get("times"))
    if kind == "io_error":
        return faults.io_error(spec["point"],
                               times=spec.get("times", 1))
    if kind == "torn_heartbeat":
        return faults.torn_heartbeat(
            path_part=spec.get("path_part", "hb/"),
            times=spec.get("times", 1))
    if kind == "crash":
        return faults.crash(spec["point"],
                            path_part=spec.get("path_part"),
                            times=spec.get("times", 1))
    if kind == "slow_call":
        return faults.slow_call(spec["site"], spec["delay_s"],
                                path_part=spec.get("path_part"),
                                times=spec.get("times"))
    if kind == "partition":
        return faults.partition(peer=spec.get("peer"),
                                stall_s=spec["stall_s"],
                                site=spec["site"],
                                times=spec.get("times", 1))
    if kind == "disk_full":
        return faults.disk_full(spec["point"],
                                path_part=spec.get("path_part"),
                                times=spec.get("times", 1))
    if kind == "disk_budget":
        return faults.disk_budget(spec["free_bytes"])
    if kind == "fd_exhaust":
        return faults.fd_exhaust(spec["site"],
                                 path_part=spec.get("path_part"),
                                 times=spec.get("times", 1))
    raise ValueError(f"unknown fault kind {kind!r}")


def build(specs, kill=None) -> BuiltSchedule:
    """Lower specs to armed-at rules + timed actions.  ``kill`` is the
    scenario's process-kill lever (``kill(target)``); required only
    when the schedule contains a ``kill`` spec."""
    rules, timed = [], []
    for spec in specs:
        kind = spec["kind"]
        at_s = float(spec.get("at_s", 0.0))
        if kind == "kill":
            if kill is None:
                raise ValueError("schedule has a kill but the scenario "
                                 "offers no kill lever")
            target = spec["target"]
            timed.append((at_s, f"kill:{target}",
                          lambda t=target: kill(t)))
            continue
        rule = _lower_rule(spec)
        rules.append((at_s, f"arm:{kind}", rule))
        if kind == "disk_budget":
            heal = spec.get("heal_after_s")
            if heal is not None:
                timed.append((float(heal), "heal:disk_budget",
                              lambda r=rule: r.budget.heal(1 << 40)))
    return BuiltSchedule(rules, timed)


def describe(spec) -> str:
    """One human line per spec (artifact summaries, doctor --chaos)."""
    extra = {k: v for k, v in spec.items()
             if k not in ("kind", "cls", "at_s")}
    inner = ", ".join(f"{k}={v}" for k, v in sorted(extra.items()))
    return f"{spec['kind']}[{spec['cls']}] @{spec['at_s']}s ({inner})"

"""Declared campaign invariants — what "survived the faults" *means*.

A scenario declares its invariants as ``(name, params)`` pairs; after
the load window closes the conductor evaluates every one against the
run's observations (client-side response accounting, the run journal,
the on-disk checkpoint store) and records a verdict per invariant::

    {"name": ..., "ok": bool, "detail": <one line>, "params": {...}}

Every declared invariant is ALWAYS evaluated — a campaign artifact with
a missing verdict is a bug, not a pass — and any ``ok: false`` verdict
sends the schedule to the shrinker (chaos/shrink.py).

Observations contract (what scenario runners put in ``obs``):

- ``counters``: ``{"ok", "shed", "degraded"}`` ints + ``corrupt`` /
  ``unexpected`` sample lists from the closed-loop clients;
- ``journal``: the run's JSONL journal path;
- ``kills``: ``[{"target", "t_kill"}]`` (wall-clock ts, matches record
  ``ts``);
- ``fired``: the FaultPlan's firing log ``[(point, path, nbytes)]``;
- ``cfg``: scenario timing (``deadline_s``, ``monitor_s``, ...);
- ``workdir`` / optional ``ckpt_root`` / scenario-specific extras
  (``reads`` for the crash-matrix store audit, ``deploy`` for the
  canary result, ``resize`` for the cohort).
"""
from __future__ import annotations

import json
import os

__all__ = ["INVARIANTS", "evaluate", "journal_records", "register"]

INVARIANTS: dict = {}


def register(name):
    def deco(fn):
        INVARIANTS[name] = fn
        return fn
    return deco


def journal_records(path, kind=None) -> list:
    """All (well-formed) records of the run journal, optionally one
    kind — torn lines read as absent, never as a reader crash."""
    out = []
    try:
        with open(path, encoding="utf-8") as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if kind is None or rec.get("kind") == kind:
                    out.append(rec)
    except OSError:
        pass
    return out


def evaluate(declared, obs) -> list:
    """Run every declared invariant; unknown names are a failing
    verdict (a typo must not read as a pass)."""
    verdicts = []
    for name, params in declared:
        fn = INVARIANTS.get(name)
        if fn is None:
            verdicts.append({"name": name, "ok": False, "params": params,
                             "detail": "unknown invariant"})
            continue
        try:
            ok, detail = fn(obs, **params)
        except Exception as exc:
            ok, detail = False, f"evaluator crashed: {exc!r}"
        verdicts.append({"name": name, "ok": bool(ok), "params": params,
                         "detail": detail})
    return verdicts


@register("progress")
def _progress(obs, min_ok=1):
    """The system kept serving: the clients completed requests."""
    ok = obs["counters"]["ok"]
    return ok >= int(min_ok), f"{ok} ok responses (need >= {min_ok})"


@register("zero_corrupt")
def _zero_corrupt(obs):
    """No response ever carried a wrong/corrupt value — degrade to
    sheds and structured errors, never to corruption."""
    bad = obs["counters"].get("corrupt") or []
    return not bad, (f"{len(bad)} corrupt responses; first: {bad[:2]}"
                     if bad else "0 corrupt responses")


@register("structured_only")
def _structured_only(obs):
    """Every client-visible failure was a structured serving error."""
    bad = obs["counters"].get("unexpected") or []
    return not bad, (f"{len(bad)} unstructured errors; first: {bad[:3]}"
                     if bad else "all failures structured")


@register("shed_rate")
def _shed_rate(obs, ceiling=0.5):
    """Load shedding stayed under the declared ceiling."""
    c = obs["counters"]
    total = c["ok"] + c["shed"] + c.get("degraded", 0)
    if total == 0:
        return False, "no requests completed at all"
    rate = c["shed"] / total
    return rate <= float(ceiling), \
        f"shed rate {rate:.3f} (ceiling {ceiling}, {c['shed']}/{total})"


@register("recovery_deadline")
def _recovery_deadline(obs, slack_s=3.0):
    """Every killed replica's loss was detected (journaled
    ``replica_lost``) within heartbeat deadline + monitor tick +
    slack."""
    kills = obs.get("kills") or []
    if not kills:
        return True, "no kills scheduled"
    cfg = obs.get("cfg") or {}
    bound = (float(cfg.get("deadline_s", 3.0))
             + float(cfg.get("monitor_s", 0.5)) + float(slack_s))
    lost = journal_records(obs["journal"], "replica_lost")
    lines = []
    ok = True
    for k in kills:
        hits = [r for r in lost if r.get("replica") == k["target"]
                and r.get("ts", 0) >= k["t_kill"]]
        if not hits:
            ok = False
            lines.append(f"{k['target']}: never detected")
            continue
        dt = hits[0]["ts"] - k["t_kill"]
        if dt > bound:
            ok = False
        lines.append(f"{k['target']}: detected in {dt:.2f}s "
                     f"(bound {bound:.2f}s)")
    return ok, "; ".join(lines)


@register("no_litter")
def _no_litter(obs, subdir=None):
    """No staged ``.tmp.*`` litter survived the campaign (ENOSPC and
    recoverable-error cleanup both unlink their temp)."""
    root = obs["workdir"] if subdir is None \
        else os.path.join(obs["workdir"], subdir)
    litter = []
    for dirpath, _dirnames, filenames in os.walk(root):
        litter += [os.path.join(dirpath, n) for n in filenames
                   if ".tmp." in n]
    return not litter, (f"{len(litter)} staged temp(s): {litter[:3]}"
                        if litter else "no staged litter")


@register("store_old_or_new")
def _store_old_or_new(obs):
    """The checkpoint store is bit-exact old-or-new: every committed
    step still validates against its CRC manifest and at least one
    restorable step exists."""
    from ..resilience import commit
    root = obs.get("ckpt_root")
    if not root:
        return False, "scenario observations carry no ckpt_root"
    steps = commit.committed_steps(root)
    if not steps:
        return False, "no committed steps survived"
    bad = []
    for s in steps:
        try:
            commit.validate_step(root, s)
        except ValueError as exc:
            bad.append(f"step {s}: {exc}")
    return not bad, ("; ".join(bad[:3]) if bad
                     else f"{len(steps)} committed steps all validate")


@register("reads_old_or_new")
def _reads_old_or_new(obs):
    """Every mid-campaign reader observation was a complete committed
    value (the crash-matrix audit: old or new, never torn)."""
    reads = obs.get("reads") or []
    bad = [r for r in reads if not r.get("valid")]
    if not reads:
        return False, "no reader observations recorded"
    return not bad, (f"{len(bad)}/{len(reads)} torn/invalid reads; "
                     f"first: {bad[:2]}" if bad
                     else f"{len(reads)} reads all old-or-new")


@register("canary_rolled_back")
def _canary_rolled_back(obs):
    """The deploy scenario's gate: a regressed candidate must have been
    caught (rolled back) by the parity mirror, never promoted."""
    dep = obs.get("deploy") or {}
    if dep.get("error"):
        return False, f"deploy controller crashed: {dep['error']}"
    if not dep:
        return False, "deploy produced no result inside the window"
    result = dep.get("result")
    return result == "rolled_back", \
        f"deploy result {result!r} (reason {dep.get('reason')!r})"


@register("cohort_resized")
def _cohort_resized(obs):
    """The elastic scenario's gate: after the scheduled rank kill the
    survivor resized to a working smaller cohort (journaled
    ``cohort_resize``) instead of hanging or crashing."""
    if not obs.get("kills"):
        return True, "no rank kill scheduled"
    rz = obs.get("resize") or {}
    if not rz.get("members"):
        return False, "rank killed but the survivor never resized"
    recs = journal_records(obs["journal"], "cohort_resize")
    if not recs:
        return False, "resize happened but was never journaled"
    detect = rz.get("detect_s")
    return True, (f"resized to {rz['members']} (lost {rz.get('lost')}"
                  + (f", detected in {detect:.2f}s" if detect else "")
                  + ")")


@register("protected_tenant")
def _protected_tenant(obs, tenant):
    """The fleet scenario's isolation gate: the NON-targeted tenant
    kept serving while its neighbor was poisoned/slowed."""
    ok_by_tenant = obs.get("tenant_ok") or {}
    n = ok_by_tenant.get(tenant, 0)
    return n >= 1, (f"protected tenant {tenant!r}: {n} ok responses"
                    if n else f"protected tenant {tenant!r} served "
                              "NOTHING — isolation failed")


@register("degrades_journaled")
def _degrades_journaled(obs):
    """Silent degrades are forbidden: injected disk exhaustion that
    fired must have its deduped ``disk_full`` journal record, and the
    router's degrade trail (retries/breaker flips), when present,
    carries trace ids."""
    lines = []
    ok = True
    if obs.get("disk_fired", 0) > 0:
        recs = journal_records(obs["journal"], "disk_full")
        if not recs:
            ok = False
            lines.append("disk exhaustion fired but no disk_full record")
        else:
            lines.append(f"{len(recs)} disk_full record(s)")
    for kind in ("router_retry", "router_breaker"):
        recs = journal_records(obs["journal"], kind)
        if recs and not any(r.get("trace_id") for r in recs):
            ok = False
            lines.append(f"{kind} records carry no trace ids")
    return ok, "; ".join(lines) or "no degrade trail to audit"

"""The campaign conductor — run, judge, shrink, report.

One campaign = one seeded fault schedule composed over one registered
scenario (chaos/scenarios.py) under closed-loop client load:

1. **generate** — draw the schedule from ``random.Random(seed)``
   against the scenario's declared targets (≥1 fault per supported
   class by default: process kill, durability, latency, resource
   exhaustion composed in ONE window, not one-at-a-time drills);
2. **execute** — fresh workdir + fresh journal, the schedule's fault
   rules live in :func:`mxnet_tpu.testing.faults.inject` while client
   threads hammer ``run.tick()`` and a timeline thread fires the timed
   actions (kills, disk-budget heals) on the campaign clock;
3. **evaluate** — every declared invariant gets a verdict
   (chaos/invariants.py); a campaign with an unevaluated invariant is
   a bug, not a pass;
4. **shrink** — on any failed invariant, delta-debug the schedule
   (chaos/shrink.py) down to a minimal failing subset by same-seed
   replay, so the artifact ships a reproducer measured in faults, not
   a haystack;
5. **artifact** — persist ``CHAOS_rNN.json`` (seed, schedule,
   verdicts, shrunk reproducer, observability snapshot) for
   ``python -m mxnet_tpu.chaos replay|report`` and ``doctor --chaos``.

Determinism contract: everything random flows from the seed through
:func:`chaos.schedule.generate`; execution replays the SAME spec list,
so ``replay(artifact)`` and every shrink probe run the schedule the
original campaign ran (modulo thread timing — faults fire on
deterministic trip predicates, not wall clock, except the explicitly
timed actions).
"""
from __future__ import annotations

import os
import shutil
import threading
import time

from ..diagnostics.journal import get_journal, reset_journal
from ..observability import trace as obtrace
from ..resilience.retry import reset_disk_full_notes
from ..testing import faults
from . import invariants as inv
from . import scenarios as scen
from . import schedule as sched
from .shrink import ddmin

__all__ = ["execute", "run_campaign"]

# kinds whose firings must leave a deduped disk_full journal record
# (fd_exhaust is EMFILE, not ENOSPC — it degrades as an ordinary
# I/O error, outside the fail-fast + note_disk_full contract)
_DISK_KINDS = ("disk_full", "disk_budget")


def _campaign_dir(base, tag):
    """Fresh campaign root: a rerun with the same scenario+seed must not
    inherit the previous run's ledger/journal/checkpoints (stale cohort
    epochs would silently change what the faults land on)."""
    d = os.path.join(base, tag)
    if os.path.isdir(d):
        shutil.rmtree(d, ignore_errors=True)
    os.makedirs(d, exist_ok=True)
    return d


def execute(scenario, specs, *, workdir, budget_s=8.0,
            window_s=None) -> dict:
    """One full execution: build the scenario in ``workdir``, inject the
    schedule, drive the closed-loop clients for the load window, stop,
    and return observations + verdicts.  Fully re-entrant: every call
    gets its own journal sink and a clean disk-full dedup set, so a
    shrink probe observes exactly what a fresh campaign would."""
    os.makedirs(workdir, exist_ok=True)
    journal_path = os.path.join(workdir, "journal.jsonl")
    reset_journal(journal_path)
    obtrace.reset_tracer()
    obtrace.configure(mode="journal")
    reset_disk_full_notes()
    window_s = float(budget_s if window_s is None else window_s)
    built = None
    run = None
    stopped = False
    stop = threading.Event()
    threads = []
    try:
        run = scenario.build(workdir)
        needs_kill = any(s["kind"] == "kill" for s in specs)
        built = sched.build(specs, kill=run.kill if needs_kill else None)
        get_journal().event("chaos_campaign", scenario=scenario.name,
                            n_faults=len(specs),
                            kinds=[s["kind"] for s in specs])
        # the plan starts EMPTY: each rule is armed at its at_s on the
        # campaign clock, so warm-up runs clean and "a disk fills at
        # 2.7s" means exactly that — in the original run, in replay,
        # and in every shrink probe
        with faults.inject() as plan:
            run.start()

            def client():
                while not stop.is_set():
                    try:
                        run.tick()
                    except Exception as exc:
                        # an exception ESCAPING tick() is exactly what
                        # structured_only exists to catch — record it
                        # and keep the client alive (a silently dead
                        # client would read as a hang, not a finding)
                        run.counters.add("unexpected",
                                         f"tick escaped: {exc!r}")
                        time.sleep(0.05)

            for i in range(max(1, scenario.clients)):
                t = threading.Thread(target=client, daemon=True,
                                     name=f"chaos-client-{i}")
                t.start()
                threads.append(t)
            timeline = sorted(
                [(at_s, label,
                  (lambda r=rule: plan.rules.append(r)))
                 for at_s, label, rule in built.rules] + built.timed,
                key=lambda t: t[0])
            t0 = time.monotonic()
            for at_s, label, action in timeline:
                delay = at_s - (time.monotonic() - t0)
                if delay > 0 and stop.wait(min(delay, window_s)):
                    break
                try:
                    action()
                except Exception as exc:     # a dead lever is a finding,
                    get_journal().event(     # not a conductor crash
                        "chaos_action_failed", action=label,
                        error=repr(exc))
            remaining = window_s - (time.monotonic() - t0)
            if remaining > 0:
                stop.wait(remaining)
            stop.set()
            for t in threads:
                t.join(timeout=10.0)
        # teardown runs with the faults DISARMED: drain/GC is the
        # recovery path, not part of the injected window
        run.stop()
        stopped = True
        fired = list(plan.log)
    finally:
        stop.set()
        if run is not None and not stopped:
            try:
                run.stop()
            except Exception:
                pass             # best-effort cleanup after a crash
        obtrace.reset_tracer()
        reset_journal("stderr")
    obs = run.observations()
    obs["journal"] = journal_path
    obs["fired"] = fired
    obs["disk_fired"] = sum(
        1 for spec, (_at, _label, rule)
        in zip(specs_without_timed(specs), built.rules)
        if spec["kind"] in _DISK_KINDS and getattr(rule, "fired", 0))
    verdicts = inv.evaluate(scenario.invariants, obs)
    failed = [v["name"] for v in verdicts if not v["ok"]]
    return {"ok": not failed, "failed": failed, "verdicts": verdicts,
            "observations": obs, "specs": list(specs)}


def specs_without_timed(specs):
    """The sub-list of specs that lowered into live fault RULES (kill
    specs lower into timed actions instead) — index-aligned with
    ``BuiltSchedule.rules``."""
    return [s for s in specs if s["kind"] != "kill"]


def run_campaign(scenario_name, seed, *, n_faults=4, classes=None,
                 budget_s=8.0, out_dir=".", schedule=None,
                 shrink=True) -> dict:
    """Run one campaign end-to-end and write its ``CHAOS_rNN.json``.

    ``schedule`` (a spec list) overrides generation — that is the
    replay path; otherwise :func:`chaos.schedule.generate` draws it
    from ``seed``.  Returns the artifact document (with ``"path"``
    added when it was persisted)."""
    from .artifact import write_artifact
    scenario = scen.get(scenario_name)
    specs = list(schedule) if schedule is not None else sched.generate(
        seed, scenario.targets, n_faults=n_faults, classes=classes)
    base = _campaign_dir(out_dir, f"chaos-{scenario_name}-{int(seed)}")
    result = execute(scenario, specs, budget_s=budget_s,
                     workdir=os.path.join(base, "run"))
    shrunk = None
    if not result["ok"] and shrink and len(specs) > 1:
        failed = set(result["failed"])
        probe_n = [0]

        def still_fails(subset):
            probe_n[0] += 1
            sub = execute(scenario, subset, budget_s=budget_s,
                          workdir=os.path.join(
                              base, f"shrink-{probe_n[0]:02d}"))
            return bool(failed & set(sub["failed"]))

        shrunk = ddmin(specs, still_fails)
    doc = {
        "kind": "chaos",
        "scenario": scenario_name,
        "seed": int(seed),
        "budget_s": float(budget_s),
        "ok": result["ok"],
        "failed": result["failed"],
        "schedule": specs,
        "schedule_human": [sched.describe(s) for s in specs],
        "verdicts": result["verdicts"],
        "shrunk": shrunk,
        "shrunk_human": ([sched.describe(s) for s in shrunk]
                         if shrunk else None),
        "observability": _snapshot(result["observations"]),
    }
    doc["path"] = write_artifact(out_dir, doc)
    return doc


def _snapshot(obs) -> dict:
    """The artifact's observability digest: counters, firing log, the
    scenario extras — everything JSON-serializable, nothing huge."""
    snap = {"counters": obs.get("counters"),
            "fired": [list(t) for t in (obs.get("fired") or [])],
            "disk_fired": obs.get("disk_fired", 0),
            "kills": obs.get("kills"),
            "journal": obs.get("journal")}
    for key in ("deploy", "resize", "tenant_ok"):
        if key in obs:
            snap[key] = obs[key]
    reads = obs.get("reads")
    if reads is not None:
        bad = [r for r in reads if not r.get("valid")]
        snap["reads"] = {"total": len(reads), "invalid": len(bad),
                         "invalid_sample": bad[:4]}
    # the journal's degrade trail, summarized by kind
    kinds: dict = {}
    for rec in inv.journal_records(obs.get("journal", "")):
        k = rec.get("kind", "?")
        kinds[k] = kinds.get(k, 0) + 1
    snap["journal_kinds"] = dict(sorted(kinds.items()))
    return snap

"""Chaos campaign engine: seeded fault schedules over live scenarios.

The repo's resilience claims each grew up with a bespoke drill — a
pool test that SIGKILLs a replica, a crash matrix for the commit
protocol, a canary regression, a cohort losing a rank.  Production
does not schedule faults one at a time: a host dies WHILE a disk fills
WHILE a deploy is mid-canary.  This package runs those same live
setups under *composed*, seeded fault schedules and judges the runs
against declared invariants:

- :mod:`.schedule` — seeded generation + spec↔rule lowering (the
  resource-exhaustion family — ``disk_full``, ``disk_budget``,
  ``fd_exhaust``, ``partition`` — lives in
  :mod:`mxnet_tpu.testing.faults` with the rest of the catalog);
- :mod:`.scenarios` — the registered live systems (pool, crash_matrix,
  fleet, deploy, elastic);
- :mod:`.invariants` — what "survived" means, one verdict each;
- :mod:`.conductor` — run → judge → shrink → artifact;
- :mod:`.shrink` — ddmin to a minimal failing schedule;
- :mod:`.artifact` / :mod:`.report` — ``CHAOS_rNN.json`` +
  ``doctor --chaos``;
- ``python -m mxnet_tpu.chaos run|replay|report`` — the CLI
  (docs/chaos.md).
"""
from __future__ import annotations

from .artifact import latest_artifact, read_artifact, write_artifact
from .conductor import execute, run_campaign
from .invariants import INVARIANTS, evaluate
from .report import chaos_report
from .schedule import FAULT_CLASSES, build, describe, generate
from .scenarios import SCENARIOS, Scenario, ScenarioRun, get, names, \
    register
from .shrink import ddmin

__all__ = ["FAULT_CLASSES", "INVARIANTS", "SCENARIOS", "Scenario",
           "ScenarioRun", "build", "chaos_report", "ddmin", "describe",
           "evaluate", "execute", "generate", "get", "latest_artifact",
           "names", "read_artifact", "register", "run_campaign",
           "write_artifact"]

"""``CHAOS_rNN.json`` artifacts — a campaign you can hand someone.

Same revisioned-artifact convention as the repo's bench/tuner outputs:
``next_rev`` scans for the highest existing ``CHAOS_r*.json`` and the
document is written atomically, so a campaign interrupted mid-report
never leaves a torn artifact (the chaos engine holds itself to the
invariants it gates everyone else on).

An artifact is a *reproducer*: ``python -m mxnet_tpu.chaos replay
CHAOS_r01.json`` re-runs the shrunk schedule (or, with ``--full``, the
original) against the same scenario from the recorded seed.
"""
from __future__ import annotations

import json
import os
import re

from ..resilience.atomic import atomic_write

__all__ = ["latest_artifact", "next_rev", "read_artifact",
           "write_artifact"]

_PAT = re.compile(r"^CHAOS_r(\d+)\.json$")


def _revs(dirpath) -> list:
    try:
        names = os.listdir(dirpath)
    except OSError:
        return []
    out = []
    for name in names:
        m = _PAT.match(name)
        if m:
            out.append((int(m.group(1)), name))
    return sorted(out)


def next_rev(dirpath) -> int:
    revs = _revs(dirpath)
    return (revs[-1][0] + 1) if revs else 1


def latest_artifact(dirpath):
    """Path of the newest ``CHAOS_rNN.json`` under ``dirpath`` (or
    None)."""
    revs = _revs(dirpath)
    return os.path.join(dirpath, revs[-1][1]) if revs else None


def write_artifact(dirpath, doc) -> str:
    os.makedirs(dirpath, exist_ok=True)
    path = os.path.join(dirpath, f"CHAOS_r{next_rev(dirpath):02d}.json")
    with atomic_write(path, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True, default=str)
    return path


def read_artifact(path) -> dict:
    """Parse + schema-check one artifact; raises ValueError naming the
    defect (a replay must fail loudly on a torn/foreign file)."""
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except OSError as e:
        raise ValueError(f"{path}: unreadable ({e.strerror or e})") from e
    except ValueError as e:
        raise ValueError(f"{path}: not valid JSON ({e})") from e
    if not isinstance(doc, dict) or doc.get("kind") != "chaos":
        raise ValueError(f"{path}: not a chaos artifact")
    for key in ("scenario", "seed", "schedule", "verdicts"):
        if key not in doc:
            raise ValueError(f"{path}: missing {key!r}")
    if not isinstance(doc["schedule"], list):
        raise ValueError(f"{path}: schedule is not a list")
    return doc

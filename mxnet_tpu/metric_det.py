"""Detection evaluation metrics (ref ecosystem: gluoncv.utils.metrics.
voc_detection.VOC07MApMetric / VOCMApMetric — the evaluation half of the
SSD / Faster-RCNN driver configs; upstream MXNet ships the models, the
GluonCV side ships the mAP scoring).

Host-side numpy (evaluation is not a jit surface): accumulate per-image
detections + ground truths, then per-class AP by ranked precision/recall
with greedy IoU matching — VOC07's 11-point interpolation or the
all-points (area-under-PR) integral.
"""
from __future__ import annotations

import numpy as np

from .metric import EvalMetric, register

__all__ = ["VOCMApMetric", "VOC07MApMetric"]


def _iou_matrix(boxes_a, boxes_b):
    """IoU between (N,4) and (M,4) corner boxes."""
    if boxes_a.size == 0 or boxes_b.size == 0:
        return np.zeros((boxes_a.shape[0], boxes_b.shape[0]))
    tl = np.maximum(boxes_a[:, None, :2], boxes_b[None, :, :2])
    br = np.minimum(boxes_a[:, None, 2:], boxes_b[None, :, 2:])
    wh = np.clip(br - tl, 0, None)
    inter = wh[..., 0] * wh[..., 1]
    area_a = np.prod(boxes_a[:, 2:] - boxes_a[:, :2], axis=1)
    area_b = np.prod(boxes_b[:, 2:] - boxes_b[:, :2], axis=1)
    union = area_a[:, None] + area_b[None, :] - inter
    return np.where(union > 0, inter / np.maximum(union, 1e-12), 0.0)


@register
class VOCMApMetric(EvalMetric):
    """Pascal-VOC mean average precision.

    ``update(labels, preds)`` per batch:
      labels: (B, M, 5+) ``[cls, x0, y0, x1, y1, (difficult)]`` rows,
        cls < 0 padding;
      preds:  (B, N, 6) ``[cls, score, x0, y0, x1, y1]`` rows, cls < 0
        padding — the layout SSD/Faster-RCNN inference emits.
    """

    def __init__(self, iou_thresh=0.5, class_names=None,
                 name="mAP", use_07_metric=False):
        # scalar -> VOC protocol; a LIST of thresholds averages AP over
        # them (pass np.arange(0.5, 1.0, 0.05) for the COCO-style
        # mAP@[.5:.95] headline number)
        if isinstance(iou_thresh, (list, tuple, np.ndarray)):
            # dedupe (order-preserving): a repeated threshold would
            # append to the same (thr, class) record list twice
            self._ious = list(dict.fromkeys(float(t) for t in iou_thresh))
        else:
            self._ious = [float(iou_thresh)]
        self._use07 = use_07_metric
        self._class_names = list(class_names) if class_names else None
        super().__init__(name)

    def reset(self):
        # per (iou_thresh, class): list of (score, tp); npos per class
        self._records = {}
        self._npos = {}
        self.num_inst = 0
        self.sum_metric = 0.0

    def update(self, labels, preds):
        from .metric import _as_list, _to_numpy
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label = _to_numpy(label)
            pred = _to_numpy(pred)
            if label.ndim == 2:
                label = label[None]
            if pred.ndim == 2:
                pred = pred[None]
            for lb, pd in zip(label, pred):
                self._update_one(lb, pd)

    def _update_one(self, label, pred):
        label = label[label[:, 0] >= 0]
        pred = pred[pred[:, 0] >= 0]
        difficult = label[:, 5].astype(bool) if label.shape[1] > 5 \
            else np.zeros(label.shape[0], bool)
        classes = set(label[:, 0].astype(int)) | \
            set(pred[:, 0].astype(int))
        for c in classes:
            gt = label[label[:, 0].astype(int) == c]
            gt_diff = difficult[label[:, 0].astype(int) == c]
            dt = pred[pred[:, 0].astype(int) == c]
            self._npos[c] = self._npos.get(c, 0) + int((~gt_diff).sum())
            order = np.argsort(-dt[:, 1]) if dt.shape[0] else []
            dt = dt[order] if dt.shape[0] else dt
            iou = _iou_matrix(dt[:, 2:6], gt[:, 1:5]) if dt.shape[0] \
                else None
            # threshold-independent best-match per detection, hoisted
            # out of the ladder loop
            jbest = iou.argmax(axis=1) if iou is not None and gt.shape[0] \
                else None
            for thr in self._ious:
                recs = self._records.setdefault((thr, c), [])
                if dt.shape[0] == 0:
                    continue
                taken = np.zeros(gt.shape[0], bool)
                for i in range(dt.shape[0]):
                    if gt.shape[0] == 0:
                        recs.append((float(dt[i, 1]), 0))
                        continue
                    j = int(jbest[i])
                    if iou[i, j] >= thr and gt_diff[j]:
                        # difficult GT: every matching detection is
                        # ignored (neither TP nor FP, never "taken" —
                        # VOC devkit / gluoncv protocol)
                        continue
                    if iou[i, j] >= thr and not taken[j]:
                        taken[j] = True
                        recs.append((float(dt[i, 1]), 1))
                    else:
                        recs.append((float(dt[i, 1]), 0))

    def _average_precision(self, rec, prec):
        if self._use07:
            ap = 0.0
            for t in np.arange(0.0, 1.01, 0.1):     # 11-point VOC07
                p = prec[rec >= t].max() if (rec >= t).any() else 0.0
                ap += p / 11.0
            return ap
        # all-points: area under the monotone precision envelope
        mrec = np.concatenate([[0.0], rec, [1.0]])
        mpre = np.concatenate([[0.0], prec, [0.0]])
        for i in range(mpre.size - 2, -1, -1):
            mpre[i] = max(mpre[i], mpre[i + 1])
        idx = np.where(mrec[1:] != mrec[:-1])[0]
        return float(((mrec[idx + 1] - mrec[idx]) * mpre[idx + 1]).sum())

    def get(self):
        aps = []
        names = []
        # report every configured class (gluoncv parity): names absent
        # from all updates still get a row (NaN — undefined AP)
        all_classes = set(self._npos)
        if self._class_names:
            all_classes |= set(range(len(self._class_names)))
        for c in sorted(all_classes):
            npos = self._npos.get(c, 0)
            if npos == 0:
                # prediction-only / all-difficult class: AP undefined —
                # excluded from the mean (gluoncv nanmean semantics)
                if self._class_names:
                    aps.append(float("nan"))
                    names.append(self._cname(c))
                continue
            per_thr = []
            for thr in self._ious:
                recs = self._records.get((thr, c), [])
                if not recs:
                    per_thr.append(0.0)
                    continue
                recs = sorted(recs, key=lambda r: -r[0])
                tp = np.array([r[1] for r in recs], np.float64)
                fp = 1.0 - tp
                tp_c = np.cumsum(tp)
                fp_c = np.cumsum(fp)
                rec = tp_c / npos
                prec = tp_c / np.maximum(tp_c + fp_c, 1e-12)
                per_thr.append(self._average_precision(rec, prec))
            aps.append(float(np.mean(per_thr)))
            names.append(self._cname(c))
        defined = [a for a in aps if not np.isnan(a)]
        mean_ap = float(np.mean(defined)) if defined else float("nan")
        if self._class_names:
            return (names + [self.name],
                    [float(a) for a in aps] + [mean_ap])
        return self.name, mean_ap

    def _cname(self, c):
        if self._class_names and 0 <= c < len(self._class_names):
            return self._class_names[c]
        return f"class{c}"


@register
class VOC07MApMetric(VOCMApMetric):
    """VOC07 11-point interpolated mAP (ref ecosystem: gluoncv
    VOC07MApMetric — the SSD paper's protocol)."""

    def __init__(self, iou_thresh=0.5, class_names=None, name="mAP"):
        super().__init__(iou_thresh=iou_thresh, class_names=class_names,
                         name=name, use_07_metric=True)

"""BaseModule — the TF1-style high-level training loop
(ref: python/mxnet/module/base_module.py)."""
from __future__ import annotations

import logging
import os
import re
import time

from .. import metric as metric_mod
from ..base import MXNetError
from ..observability import instrument as _obs

__all__ = ["BaseModule"]


class BaseModule:
    """ref: base_module.py BaseModule — fit/score/predict skeleton."""

    def __init__(self, logger=logging):
        self.logger = logger
        self.binded = False
        self.for_training = False
        self.params_initialized = False
        self.optimizer_initialized = False
        self._symbol = None

    # -- abstract ------------------------------------------------------------
    def forward(self, data_batch, is_train=None):
        raise NotImplementedError

    def backward(self, out_grads=None):
        raise NotImplementedError

    def update(self):
        raise NotImplementedError

    def get_outputs(self):
        raise NotImplementedError

    def update_metric(self, eval_metric, labels):
        raise NotImplementedError

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        raise NotImplementedError

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False,
                    allow_extra=False):
        raise NotImplementedError

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=None, force_init=False):
        raise NotImplementedError

    # -- composite -----------------------------------------------------------
    def forward_backward(self, data_batch):
        self.forward(data_batch, is_train=True)
        self.backward()

    def _grad_datas(self):
        """Device arrays of the PARAMETER gradient buffers, or None when
        the concrete module type does not expose them (guardrails then
        skip the finiteness check rather than guess). Data-input grads
        (``inputs_need_grad=True``) are excluded: the optimizer never
        consumes them, so they must not veto the step or inflate the
        journaled global norm."""
        exec_ = getattr(self, "_exec", None)
        if exec_ is None:
            return None
        names = getattr(self, "_param_names", None)
        grads = (exec_.grad_dict.values() if names is None
                 else (exec_.grad_dict.get(n) for n in names))
        return [g._data for g in grads if g is not None]

    def _guard_optimizers(self):
        """Live optimizer object(s) the guard's rollback LR backoff
        must land on (composite module types override — e.g. a chained
        SequentialModule has one per inner module)."""
        opt = getattr(self, "_optimizer", None)
        return [opt] if opt is not None else []

    def _guard_reinit_updaters(self):
        """Drop the diverged trajectory's updater state (often
        saturated moments) while keeping the same optimizer object —
        the rollback's LR backoff lands on it right after."""
        opt = getattr(self, "_optimizer", None)
        if opt is not None:
            self.init_optimizer(optimizer=opt, force_init=True)

    def score(self, eval_data, eval_metric, num_batch=None,
              batch_end_callback=None, reset=True, epoch=0):
        """ref: BaseModule.score."""
        if reset:
            eval_data.reset()
        if not isinstance(eval_metric, metric_mod.EvalMetric):
            eval_metric = metric_mod.create(eval_metric)
        eval_metric.reset()
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            self.update_metric(eval_metric, eval_batch.label)
            if batch_end_callback is not None:
                for cb in _as_list(batch_end_callback):
                    cb(_BatchEndParam(epoch, nbatch, eval_metric, locals()))
        return eval_metric.get_name_value()

    def predict(self, eval_data, num_batch=None, merge_batches=True,
                reset=True, always_output_list=False):
        """ref: BaseModule.predict."""
        from .. import ndarray as nd
        if reset:
            eval_data.reset()
        outputs = []
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            pad = eval_batch.pad or 0
            outs = [o[:o.shape[0] - pad] for o in self.get_outputs()]
            outputs.append(outs)
        if not outputs:
            return []
        num_out = len(outputs[0])
        if merge_batches:
            merged = [nd.concat(*[b[i] for b in outputs], dim=0)
                      for i in range(num_out)]
            if num_out == 1 and not always_output_list:
                return merged[0]
            return merged
        return outputs

    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None,
            kvstore="local", optimizer="sgd",
            optimizer_params=(("learning_rate", 0.01),),
            eval_end_callback=None, eval_batch_end_callback=None,
            initializer=None, arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None,
            monitor=None, sparse_row_id_fn=None,
            checkpoint_prefix=None, checkpoint_period=1, keep_last=None,
            resume=False, guard=None):
        """The reference's canonical symbolic training loop
        (ref: base_module.py BaseModule.fit, SURVEY §3.3).

        Crash consistency (docs/checkpointing.md): with
        ``checkpoint_prefix`` set, fit installs an atomic epoch-end
        checkpoint (``keep_last``-bounded retention) and a SIGTERM
        preemption watch — a preemption saves one checkpoint at the
        next batch boundary, journals ``preempt_checkpoint``, and
        returns. ``resume=True`` restarts from the newest *valid*
        checkpoint under the prefix, skipping torn/corrupt files with a
        journaled ``ckpt_fallback`` (a fresh start when none exists).

        Anomaly guardrails (docs/guardrails.md): ``guard=True`` (or a
        :class:`~mxnet_tpu.guardrails.GuardConfig`) checks the batch's
        gradients with ONE fused device-side finiteness reduction before
        ``update()`` — a non-finite batch is skipped and journaled
        (``nonfinite_grad``), never trained on. Past the anomaly budget,
        fit rolls back to the newest valid checkpoint under
        ``checkpoint_prefix`` with an LR backoff (bounded retries),
        else raises :class:`~mxnet_tpu.guardrails.TrainingDiverged`."""
        from ..diagnostics.journal import get_journal
        if num_epoch is None:
            raise MXNetError("fit() requires num_epoch")
        watch = None
        if resume and not checkpoint_prefix:
            raise MXNetError("fit(resume=True) needs checkpoint_prefix=")
        if checkpoint_prefix:
            from .. import callback as callback_mod
            from ..resilience import preempt
            cbs = list(_as_list(epoch_end_callback or []))
            cbs.append(callback_mod.do_checkpoint(
                checkpoint_prefix, checkpoint_period, keep_last=keep_last))
            epoch_end_callback = cbs
            # re-arm: a SIGTERM consumed by a previous fit() in this
            # process must not mute preemption handling for this run
            # (a live unconsumed signal stays latched)
            watch = preempt.install()
            watch.rearm()
        if resume:
            from .. import model
            found = model.load_latest_params(checkpoint_prefix)
            if found is not None:
                arg_params, aux_params, begin_epoch = found
                force_init = True
                get_journal().event("resume", prefix=checkpoint_prefix,
                                    epoch=begin_epoch)
                self.logger.info("fit(resume=True): resuming from epoch "
                                 "%d of %s", begin_epoch, checkpoint_prefix)
            else:
                get_journal().event("resume_fresh",
                                    prefix=checkpoint_prefix)
        # bind builds the symbolic executor — the module path's compile
        # event (counted/timed/traced like the trainers' jit misses)
        with _obs.maybe_compile_span(
                not self.binded or force_rebind, "module_bind",
                shapes=[list(d[1]) for d in train_data.provide_data]):
            self.bind(data_shapes=train_data.provide_data,
                      label_shapes=train_data.provide_label,
                      for_training=True, force_rebind=force_rebind)
        if initializer is None:
            from .. import initializer as init_mod
            initializer = init_mod.Uniform(0.01)
        self.init_params(initializer=initializer, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=dict(optimizer_params))
        if validation_metric is None:
            validation_metric = eval_metric
        if not isinstance(eval_metric, metric_mod.EvalMetric):
            eval_metric = metric_mod.create(eval_metric)
        if monitor is not None:
            self.install_monitor(monitor)
        anomaly_monitor = None
        if guard is not None:
            from ..guardrails.monitor import AnomalyMonitor, GuardConfig
            guard_cfg = GuardConfig.coerce(guard)
            if guard_cfg is not None and guard_cfg.mode == "deferred":
                # same contract as the eager Trainer: fit decides every
                # batch on the host, deferred cannot hold here
                raise MXNetError(
                    "GuardConfig(mode='deferred') needs a fused trainer "
                    "(parallel.ShardedTrainer / PipelinedTrainer); "
                    "module.fit checks every batch on the host — use "
                    "mode='step' (docs/guardrails.md)")
            if guard_cfg is not None:
                # fit adapts the config (_guarded_veto points ckpt_root
                # at checkpoint_prefix on divergence) — copy so a
                # caller-shared GuardConfig is never mutated
                anomaly_monitor = AnomalyMonitor(guard_cfg.copy(),
                                                 consumer="module_fit")
        global_step = 0

        try:
            for epoch in range(begin_epoch, num_epoch):
                # monotonic, not wall clock: an NTP step mid-epoch must
                # not produce a negative Time cost (G11)
                tic = time.monotonic()
                eval_metric.reset()
                train_data.reset()
                # the epoch span covers the whole epoch including the
                # end-of-epoch callbacks — a do_checkpoint commit nests
                # under the epoch it belongs to
                with _obs.trace.span("module_fit.epoch", epoch=epoch):
                    stop = self._fit_epoch(
                        train_data, eval_metric, epoch, monitor,
                        anomaly_monitor, checkpoint_prefix,
                        batch_end_callback, watch, global_step)
                    global_step = stop[1]
                    if stop[0]:
                        return
                    for name, val in eval_metric.get_name_value():
                        self.logger.info("Epoch[%d] Train-%s=%f", epoch,
                                         name, val)
                    self.logger.info("Epoch[%d] Time cost=%.3f", epoch,
                                     time.monotonic() - tic)
                    if epoch_end_callback is not None:
                        arg_params, aux_params = self.get_params()
                        for cb in _as_list(epoch_end_callback):
                            cb(epoch, self.symbol, arg_params, aux_params)
                    if eval_data is not None:
                        res = self.score(
                            eval_data, validation_metric,
                            batch_end_callback=eval_batch_end_callback,
                            epoch=epoch)
                        for name, val in res:
                            self.logger.info("Epoch[%d] Validation-%s=%f",
                                             epoch, name, val)
        finally:
            if watch is not None:
                # nothing polls the watch after fit: restore the
                # displaced SIGTERM disposition (else the process would
                # silently ignore termination forever)
                watch.uninstall()

    def _fit_epoch(self, train_data, eval_metric, epoch, monitor,
                   anomaly_monitor, checkpoint_prefix, batch_end_callback,
                   watch, global_step):
        """One fit() epoch's batch loop, instrumented with the step
        phases (data_wait / forward_backward / guard_fetch / update —
        docs/observability.md).  Returns ``(stopped, global_step)``;
        ``stopped`` is True on a preemption checkpoint."""
        from ..diagnostics.journal import get_journal
        batches = enumerate(train_data)
        while True:
            with _obs.step_phase("module_fit", "data_wait"):
                try:
                    nbatch, data_batch = next(batches)
                except StopIteration:
                    break
            with _obs.trace.span("module_fit.step", epoch=epoch,
                                 nbatch=nbatch, step=global_step + 1):
                if monitor is not None:
                    monitor.tic()
                with _obs.step_phase("module_fit", "forward_backward"):
                    self.forward_backward(data_batch)
                global_step += 1
                if anomaly_monitor is not None:
                    with _obs.step_phase("module_fit", "guard_fetch"):
                        vetoed = self._guarded_veto(
                            anomaly_monitor, global_step,
                            checkpoint_prefix)
                else:
                    vetoed = False
                if not vetoed:
                    with _obs.step_phase("module_fit", "update"):
                        self.update()
                if monitor is not None:
                    monitor.toc_print()
                if not vetoed:
                    # a vetoed batch's forward outputs are the
                    # anomaly (NaN) — one poisoned batch must not
                    # poison the epoch's running training metric
                    self.update_metric(eval_metric, data_batch.label)
                if batch_end_callback is not None:
                    for cb in _as_list(batch_end_callback):
                        cb(_BatchEndParam(epoch, nbatch, eval_metric,
                                          locals()))
                if watch is not None and watch.consume():
                    # preemption: save at this step boundary and
                    # stop. Saving with the CURRENT epoch number
                    # means resume re-runs this (partial) epoch —
                    # conservative, never skips data.
                    arg_p, aux_p = self.get_params()
                    from .. import model
                    model.save_checkpoint(checkpoint_prefix, epoch,
                                          self.symbol, arg_p, aux_p)
                    get_journal().event(
                        "preempt_checkpoint",
                        prefix=checkpoint_prefix,
                        epoch=epoch, nbatch=nbatch)
                    self.logger.warning(
                        "SIGTERM: checkpoint saved at epoch %d batch "
                        "%d (%s); stopping fit", epoch, nbatch,
                        checkpoint_prefix)
                    return True, global_step
        return False, global_step

    def _guarded_veto(self, anomaly_monitor, global_step,
                      checkpoint_prefix):
        """Guardrails decision for one fit() batch: True vetoes the
        update (non-finite gradients — skip-step). Divergence rolls the
        module back to the newest valid epoch checkpoint with an LR
        backoff, or raises TrainingDiverged once the budget is spent."""
        from ..guardrails import fused
        from ..guardrails.monitor import handle_divergence
        grads = self._grad_datas()
        if not grads:
            if not getattr(self, "_guard_blind_warned", False):
                # a guard that silently protects nothing is worse than
                # none — tell the user once per module
                self._guard_blind_warned = True
                import warnings
                warnings.warn(
                    f"fit(guard=...) on {type(self).__name__}: gradient "
                    "buffers are not visible (_grad_datas returned "
                    "nothing), so the anomaly guard cannot check this "
                    "module's steps (docs/guardrails.md)")
            return False
        finite_dev, gnorm_dev = fused.guard_stats(grads)
        ok, gn = fused.host_fetch(finite_dev, gnorm_dev)
        verdict = anomaly_monitor.observe(global_step, bool(ok),
                                          grad_norm=gn)
        if verdict == "diverged":
            if checkpoint_prefix and anomaly_monitor.cfg.ckpt_root is None:
                # fit's checkpoints are epoch files under the prefix —
                # point the rollback there unless a commit root was
                # explicitly configured
                anomaly_monitor.cfg.ckpt_root = checkpoint_prefix

            def restore_fn():
                from .. import model
                root = anomaly_monitor.cfg.ckpt_root
                found = model.load_latest_params(root)
                if found is None:
                    # lenient layout sniff (committed dirs are strictly
                    # step-%08d, but a hand-built or half-migrated root
                    # deserves the same explanation)
                    try:
                        entries = os.listdir(root)
                    except OSError:
                        entries = []
                    looks_like_commit_root = any(
                        e == "latest" or
                        (re.match(r"^step-\d+$", e) and
                         os.path.isdir(os.path.join(root, e)))
                        for e in entries)
                    if looks_like_commit_root:
                        raise MXNetError(
                            f"ckpt_root {root!r} is a resilience.commit "
                            "directory, but module.fit rolls back to "
                            "EPOCH checkpoints (`prefix-NNNN.params` "
                            "files written under checkpoint_prefix=) — "
                            "point ckpt_root at an epoch-file prefix, "
                            "or leave it unset to use "
                            "checkpoint_prefix; the commit protocol is "
                            "the fused trainers' checkpoint()/restore() "
                            "format (docs/guardrails.md)")
                    raise MXNetError(
                        f"no loadable checkpoint under {root!r} to roll "
                        "back to")
                arg_params, aux_params, ckpt_epoch = found
                self.set_params(arg_params, aux_params, force_init=True)
                # epoch checkpoints hold params only — the diverged
                # trajectory's updater moments (often saturated) must
                # not survive into the restored world, or the run can
                # re-diverge immediately and burn the rollback budget.
                # Re-deriving the updater from the SAME optimizer object
                # resets its state while keeping the LR-backoff target
                # (handle_divergence backs off the optimizers after
                # this returns).
                self._guard_reinit_updaters()
                return ckpt_epoch

            handle_divergence(anomaly_monitor, global_step, restore_fn,
                              optimizer=self._guard_optimizers)
            return True
        return not bool(ok)

    @property
    def symbol(self):
        return self._symbol


class _BatchEndParam:
    def __init__(self, epoch, nbatch, eval_metric, local_vars):
        self.epoch = epoch
        self.nbatch = nbatch
        self.eval_metric = eval_metric
        self.locals = local_vars


def _as_list(obj):
    if isinstance(obj, (list, tuple)):
        return obj
    return [obj]

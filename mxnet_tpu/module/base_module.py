"""BaseModule — the TF1-style high-level training loop
(ref: python/mxnet/module/base_module.py)."""
from __future__ import annotations

import logging
import time

from .. import metric as metric_mod
from ..base import MXNetError

__all__ = ["BaseModule"]


class BaseModule:
    """ref: base_module.py BaseModule — fit/score/predict skeleton."""

    def __init__(self, logger=logging):
        self.logger = logger
        self.binded = False
        self.for_training = False
        self.params_initialized = False
        self.optimizer_initialized = False
        self._symbol = None

    # -- abstract ------------------------------------------------------------
    def forward(self, data_batch, is_train=None):
        raise NotImplementedError

    def backward(self, out_grads=None):
        raise NotImplementedError

    def update(self):
        raise NotImplementedError

    def get_outputs(self):
        raise NotImplementedError

    def update_metric(self, eval_metric, labels):
        raise NotImplementedError

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        raise NotImplementedError

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False,
                    allow_extra=False):
        raise NotImplementedError

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=None, force_init=False):
        raise NotImplementedError

    # -- composite -----------------------------------------------------------
    def forward_backward(self, data_batch):
        self.forward(data_batch, is_train=True)
        self.backward()

    def score(self, eval_data, eval_metric, num_batch=None,
              batch_end_callback=None, reset=True, epoch=0):
        """ref: BaseModule.score."""
        if reset:
            eval_data.reset()
        if not isinstance(eval_metric, metric_mod.EvalMetric):
            eval_metric = metric_mod.create(eval_metric)
        eval_metric.reset()
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            self.update_metric(eval_metric, eval_batch.label)
            if batch_end_callback is not None:
                for cb in _as_list(batch_end_callback):
                    cb(_BatchEndParam(epoch, nbatch, eval_metric, locals()))
        return eval_metric.get_name_value()

    def predict(self, eval_data, num_batch=None, merge_batches=True,
                reset=True, always_output_list=False):
        """ref: BaseModule.predict."""
        from .. import ndarray as nd
        if reset:
            eval_data.reset()
        outputs = []
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            pad = eval_batch.pad or 0
            outs = [o[:o.shape[0] - pad] for o in self.get_outputs()]
            outputs.append(outs)
        if not outputs:
            return []
        num_out = len(outputs[0])
        if merge_batches:
            merged = [nd.concat(*[b[i] for b in outputs], dim=0)
                      for i in range(num_out)]
            if num_out == 1 and not always_output_list:
                return merged[0]
            return merged
        return outputs

    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None,
            kvstore="local", optimizer="sgd",
            optimizer_params=(("learning_rate", 0.01),),
            eval_end_callback=None, eval_batch_end_callback=None,
            initializer=None, arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None,
            monitor=None, sparse_row_id_fn=None,
            checkpoint_prefix=None, checkpoint_period=1, keep_last=None,
            resume=False):
        """The reference's canonical symbolic training loop
        (ref: base_module.py BaseModule.fit, SURVEY §3.3).

        Crash consistency (docs/checkpointing.md): with
        ``checkpoint_prefix`` set, fit installs an atomic epoch-end
        checkpoint (``keep_last``-bounded retention) and a SIGTERM
        preemption watch — a preemption saves one checkpoint at the
        next batch boundary, journals ``preempt_checkpoint``, and
        returns. ``resume=True`` restarts from the newest *valid*
        checkpoint under the prefix, skipping torn/corrupt files with a
        journaled ``ckpt_fallback`` (a fresh start when none exists)."""
        from ..diagnostics.journal import get_journal
        if num_epoch is None:
            raise MXNetError("fit() requires num_epoch")
        watch = None
        if resume and not checkpoint_prefix:
            raise MXNetError("fit(resume=True) needs checkpoint_prefix=")
        if checkpoint_prefix:
            from .. import callback as callback_mod
            from ..resilience import preempt
            cbs = list(_as_list(epoch_end_callback or []))
            cbs.append(callback_mod.do_checkpoint(
                checkpoint_prefix, checkpoint_period, keep_last=keep_last))
            epoch_end_callback = cbs
            # re-arm: a SIGTERM consumed by a previous fit() in this
            # process must not mute preemption handling for this run
            # (a live unconsumed signal stays latched)
            watch = preempt.install()
            watch.rearm()
        if resume:
            from .. import model
            found = model.load_latest_params(checkpoint_prefix)
            if found is not None:
                arg_params, aux_params, begin_epoch = found
                force_init = True
                get_journal().event("resume", prefix=checkpoint_prefix,
                                    epoch=begin_epoch)
                self.logger.info("fit(resume=True): resuming from epoch "
                                 "%d of %s", begin_epoch, checkpoint_prefix)
            else:
                get_journal().event("resume_fresh",
                                    prefix=checkpoint_prefix)
        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label,
                  for_training=True, force_rebind=force_rebind)
        if initializer is None:
            from .. import initializer as init_mod
            initializer = init_mod.Uniform(0.01)
        self.init_params(initializer=initializer, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=dict(optimizer_params))
        if validation_metric is None:
            validation_metric = eval_metric
        if not isinstance(eval_metric, metric_mod.EvalMetric):
            eval_metric = metric_mod.create(eval_metric)
        if monitor is not None:
            self.install_monitor(monitor)

        try:
            for epoch in range(begin_epoch, num_epoch):
                tic = time.time()
                eval_metric.reset()
                train_data.reset()
                for nbatch, data_batch in enumerate(train_data):
                    if monitor is not None:
                        monitor.tic()
                    self.forward_backward(data_batch)
                    self.update()
                    if monitor is not None:
                        monitor.toc_print()
                    self.update_metric(eval_metric, data_batch.label)
                    if batch_end_callback is not None:
                        for cb in _as_list(batch_end_callback):
                            cb(_BatchEndParam(epoch, nbatch, eval_metric,
                                              locals()))
                    if watch is not None and watch.consume():
                        # preemption: save at this step boundary and
                        # stop. Saving with the CURRENT epoch number
                        # means resume re-runs this (partial) epoch —
                        # conservative, never skips data.
                        arg_p, aux_p = self.get_params()
                        from .. import model
                        model.save_checkpoint(checkpoint_prefix, epoch,
                                              self.symbol, arg_p, aux_p)
                        get_journal().event(
                            "preempt_checkpoint",
                            prefix=checkpoint_prefix,
                            epoch=epoch, nbatch=nbatch)
                        self.logger.warning(
                            "SIGTERM: checkpoint saved at epoch %d batch "
                            "%d (%s); stopping fit", epoch, nbatch,
                            checkpoint_prefix)
                        return
                for name, val in eval_metric.get_name_value():
                    self.logger.info("Epoch[%d] Train-%s=%f", epoch, name,
                                     val)
                self.logger.info("Epoch[%d] Time cost=%.3f", epoch,
                                 time.time() - tic)
                if epoch_end_callback is not None:
                    arg_params, aux_params = self.get_params()
                    for cb in _as_list(epoch_end_callback):
                        cb(epoch, self.symbol, arg_params, aux_params)
                if eval_data is not None:
                    res = self.score(
                        eval_data, validation_metric,
                        batch_end_callback=eval_batch_end_callback,
                        epoch=epoch)
                    for name, val in res:
                        self.logger.info("Epoch[%d] Validation-%s=%f",
                                         epoch, name, val)
        finally:
            if watch is not None:
                # nothing polls the watch after fit: restore the
                # displaced SIGTERM disposition (else the process would
                # silently ignore termination forever)
                watch.uninstall()

    @property
    def symbol(self):
        return self._symbol


class _BatchEndParam:
    def __init__(self, epoch, nbatch, eval_metric, local_vars):
        self.epoch = epoch
        self.nbatch = nbatch
        self.eval_metric = eval_metric
        self.locals = local_vars


def _as_list(obj):
    if isinstance(obj, (list, tuple)):
        return obj
    return [obj]

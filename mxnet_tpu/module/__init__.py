"""Module API (ref: python/mxnet/module/__init__.py)."""
from .base_module import BaseModule
from .bucketing_module import BucketingModule
from .module import Module
from .sequential_module import SequentialModule

__all__ = ["BaseModule", "Module", "BucketingModule", "SequentialModule"]

"""Module — symbolic trainer bound to one compiled executor
(ref: python/mxnet/module/module.py Module).

The reference's ``DataParallelExecutorGroup`` copies one executor per GPU
and splits each batch (ref: python/mxnet/module/executor_group.py). On TPU
the equivalent data parallelism is a GSPMD sharding of the SAME executor
over the mesh (SURVEY §2.4 #32) — so Module binds one executor; scale-out
goes through mxnet_tpu.parallel.ShardedTrainer or a ``data``-sharded mesh
context, not executor replication.
"""
from __future__ import annotations

import logging

from .. import initializer as init_mod
from .. import optimizer as opt_mod
from ..base import MXNetError
from ..context import current_context
from .base_module import BaseModule

__all__ = ["Module"]


class Module(BaseModule):
    def __init__(self, symbol, data_names=("data",),
                 label_names=("softmax_label",), logger=logging,
                 context=None, work_load_list=None, fixed_param_names=None,
                 state_names=None, group2ctxs=None,
                 compression_params=None):
        super().__init__(logger=logger)
        self._symbol = symbol
        if isinstance(context, (list, tuple)):
            if len(context) > 1:
                self.logger.warning(
                    "Module got %d contexts; TPU data parallelism shards one "
                    "executor over the mesh instead of replicating per "
                    "device — using the first context", len(context))
            context = context[0] if context else None
        self._context = context or current_context()
        self._data_names = list(data_names or [])
        self._label_names = list(label_names or [])
        self._fixed_param_names = list(fixed_param_names or [])
        arg_names = symbol.list_arguments()
        input_names = self._data_names + self._label_names
        self._param_names = [n for n in arg_names if n not in input_names]
        self._aux_names = symbol.list_auxiliary_states()
        self._exec = None
        self._optimizer = None
        self._updater = None
        self._kvstore = None
        self._grad_req = "write"

    # -- binding -------------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        from .. import ndarray as nd
        if self.binded and not force_rebind:
            return
        self.for_training = for_training
        self._grad_req = grad_req
        shapes = {}
        for desc in data_shapes:
            name, shape = desc[0], desc[1]
            shapes[name] = tuple(shape)
        if label_shapes:
            for desc in label_shapes:
                name, shape = desc[0], desc[1]
                shapes[name] = tuple(shape)
        self._data_shapes = data_shapes
        self._label_shapes = label_shapes
        arg_shapes, _, aux_shapes = self._symbol.infer_shape(**shapes)
        arg_names = self._symbol.list_arguments()
        args = {}
        for name, shape in zip(arg_names, arg_shapes):
            if shape is None:
                raise MXNetError(f"bind: cannot infer shape of {name!r}; "
                                 f"the reference would also fail here — "
                                 f"provide input shapes that determine it")
            args[name] = nd.zeros(shape, ctx=self._context)
        aux = {}
        for name, shape in zip(self._aux_names, aux_shapes):
            aux[name] = nd.zeros(shape, ctx=self._context)
        req = {}
        for name in arg_names:
            if name in self._data_names:
                req[name] = "write" if inputs_need_grad else "null"
            elif name in self._label_names or \
                    name in self._fixed_param_names:
                req[name] = "null"
            else:
                req[name] = grad_req if for_training else "null"
        # BatchNorm gamma/beta on fixed nets etc. keep reference behavior
        self._exec = self._symbol.bind(self._context, args,
                                       grad_req=req, aux_states=aux)
        if shared_module is not None and shared_module._exec is not None:
            self._exec.copy_params_from(
                {k: v for k, v in shared_module._exec.arg_dict.items()
                 if k in self._param_names},
                shared_module._exec.aux_dict, allow_extra_params=True)
        self.binded = True

    # -- params --------------------------------------------------------------
    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        if self.params_initialized and not force_init:
            return
        if not self.binded:
            raise MXNetError("call bind before init_params")
        if initializer is None:
            initializer = init_mod.Uniform(0.01)
        for name in self._param_names:
            arr = self._exec.arg_dict[name]
            if arg_params is not None and name in arg_params:
                src = arg_params[name]
                arr._rebind(src._data if hasattr(src, "_data")
                            else __import__("numpy").asarray(src))
            else:
                if arg_params is not None and not allow_missing:
                    raise MXNetError(f"arg_params given but {name!r} missing "
                                     f"(allow_missing=False)")
                desc = init_mod.InitDesc(name)
                initializer(desc, arr)
        for name in self._aux_names:
            arr = self._exec.aux_dict[name]
            if aux_params is not None and name in aux_params:
                src = aux_params[name]
                arr._rebind(src._data if hasattr(src, "_data")
                            else __import__("numpy").asarray(src))
            else:
                desc = init_mod.InitDesc(name)
                initializer(desc, arr)
        self.params_initialized = True

    def get_params(self):
        arg = {n: self._exec.arg_dict[n].copy() for n in self._param_names}
        aux = {n: self._exec.aux_dict[n].copy() for n in self._aux_names}
        return arg, aux

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        self.init_params(initializer=None, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init, allow_extra=allow_extra)

    # -- optimizer -----------------------------------------------------------
    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=None, force_init=False):
        if self.optimizer_initialized and not force_init:
            return
        optimizer_params = dict(optimizer_params or {})
        if not isinstance(optimizer, opt_mod.Optimizer):
            if "rescale_grad" not in optimizer_params and \
                    getattr(self, "_data_shapes", None):
                # the reference divides by the batch size here
                # (ref: module.py Module.init_optimizer rescale_grad)
                batch = self._data_shapes[0][1][0]
                optimizer_params["rescale_grad"] = 1.0 / batch
            idx2name = {i: n for i, n in enumerate(self._param_names)}
            optimizer = opt_mod.create(optimizer, param_idx2name=idx2name,
                                       **optimizer_params)
        self._optimizer = optimizer
        self._updater = opt_mod.get_updater(optimizer)
        self.optimizer_initialized = True

    def install_monitor(self, mon):
        """ref: module.py Module.install_monitor — watch this module's
        executor with an mx.monitor.Monitor."""
        if not self.binded:
            raise MXNetError("call bind before install_monitor")
        mon.install(self._exec)

    # -- execution -----------------------------------------------------------
    def forward(self, data_batch, is_train=None):
        if not self.binded:
            raise MXNetError("call bind before forward")
        if is_train is None:
            is_train = self.for_training
        feeds = {}
        for name, arr in zip(self._data_names, data_batch.data):
            feeds[name] = arr
        if data_batch.label is not None:
            for name, arr in zip(self._label_names, data_batch.label):
                feeds[name] = arr
        self._exec.forward(is_train=is_train, **feeds)

    def backward(self, out_grads=None):
        self._exec.backward(out_grads)

    def update(self):
        if self._updater is None:
            raise MXNetError("call init_optimizer before update")
        for i, name in enumerate(self._param_names):
            grad = self._exec.grad_dict.get(name)
            if grad is None:
                continue
            self._updater(i, grad, self._exec.arg_dict[name])

    def get_outputs(self, merge_multi_context=True):
        return self._exec.outputs

    def get_input_grads(self, merge_multi_context=True):
        return [self._exec.grad_dict.get(n) for n in self._data_names]

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        eval_metric.update_dict(
            dict(zip(self._label_names, labels)),
            dict(zip(self._symbol.list_outputs(), self._exec.outputs)))

    # -- checkpoint (ref: module.py save_checkpoint / load) ------------------
    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False):
        from .. import model
        arg_params, aux_params = self.get_params()
        model.save_checkpoint(prefix, epoch, self._symbol, arg_params,
                              aux_params)
        if save_optimizer_states:
            from ..resilience.atomic import atomic_write
            with atomic_write(f"{prefix}-{epoch:04d}.states", "wb") as f:
                f.write(self._updater.get_states(dump_optimizer=True))

    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        from .. import model
        sym, arg_params, aux_params = model.load_checkpoint(prefix, epoch)
        mod = Module(sym, **kwargs)
        mod._preloaded = (arg_params, aux_params)
        mod._preloaded_states = f"{prefix}-{epoch:04d}.states" \
            if load_optimizer_states else None
        return mod

    def load_optimizer_states(self, fname):
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())

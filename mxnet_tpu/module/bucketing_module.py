"""BucketingModule — per-sequence-length executors
(ref: python/mxnet/module/bucketing_module.py).

The reference binds one GraphExecutor per bucket, sharing memory with the
largest bucket (``shared_exec``). Here each bucket is a shape-keyed compiled
program — XLA's compilation cache is the memory-sharing analog (SURVEY §2.2
#11: "bucketing ≡ per-shape jit cache") — and parameters are shared by
construction since every bucket executor binds the same arrays.
"""
from __future__ import annotations

import logging

from ..base import MXNetError
from .base_module import BaseModule
from .module import Module

__all__ = ["BucketingModule"]


class BucketingModule(BaseModule):
    def __init__(self, sym_gen, default_bucket_key=None, logger=logging,
                 context=None, work_load_list=None, fixed_param_names=None,
                 state_names=None, group2ctxs=None, compression_params=None):
        super().__init__(logger=logger)
        if default_bucket_key is None:
            raise MXNetError("default_bucket_key is required")
        self._sym_gen = sym_gen
        self._default_bucket_key = default_bucket_key
        self._context = context
        self._fixed_param_names = fixed_param_names
        self._buckets = {}
        self._curr_module = None
        self._curr_bucket_key = None
        self._bind_kwargs = {}

    @property
    def symbol(self):
        return self._curr_module.symbol

    def _gen_module(self, bucket_key):
        sym, data_names, label_names = self._sym_gen(bucket_key)
        return Module(sym, data_names=data_names, label_names=label_names,
                      logger=self.logger, context=self._context,
                      fixed_param_names=self._fixed_param_names)

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if self.binded and not force_rebind:
            return
        self.for_training = for_training
        self._bind_kwargs = dict(for_training=for_training,
                                 inputs_need_grad=inputs_need_grad,
                                 grad_req=grad_req)
        module = self._gen_module(self._default_bucket_key)
        module.bind(data_shapes, label_shapes, **self._bind_kwargs)
        self._buckets[self._default_bucket_key] = module
        self._curr_module = module
        self._curr_bucket_key = self._default_bucket_key
        self.binded = True

    def switch_bucket(self, bucket_key, data_shapes, label_shapes=None):
        """ref: BucketingModule.switch_bucket — bind a new bucket sharing
        parameters with the default bucket."""
        if bucket_key not in self._buckets:
            module = self._gen_module(bucket_key)
            module.bind(data_shapes, label_shapes, **self._bind_kwargs)
            default = self._buckets[self._default_bucket_key]
            # share parameter arrays with the default bucket (the
            # reference's shared_exec memory sharing)
            for name in module._param_names:
                if name in default._exec.arg_dict and \
                        default._exec.arg_dict[name].shape == \
                        module._exec.arg_dict[name].shape:
                    module._exec.arg_dict[name] = \
                        default._exec.arg_dict[name]
                    if name in default._exec.grad_dict:
                        module._exec.grad_dict[name] = \
                            default._exec.grad_dict[name]
            for name in module._aux_names:
                if name in default._exec.aux_dict and \
                        default._exec.aux_dict[name].shape == \
                        module._exec.aux_dict[name].shape:
                    module._exec.aux_dict[name] = \
                        default._exec.aux_dict[name]
            module.params_initialized = True
            module._updater = default._updater
            module._optimizer = default._optimizer
            module.optimizer_initialized = default.optimizer_initialized
            self._buckets[bucket_key] = module
        self._curr_module = self._buckets[bucket_key]
        self._curr_bucket_key = bucket_key
        if getattr(self, "_monitor", None) is not None:
            self._curr_module.install_monitor(self._monitor)

    def install_monitor(self, mon):
        """ref: BucketingModule.install_monitor — every bucket's executor
        reports to the same Monitor (new buckets pick it up on switch)."""
        if not self.binded:
            from ..base import MXNetError
            raise MXNetError("call bind before install_monitor")
        self._monitor = mon
        for module in self._buckets.values():
            module.install_monitor(mon)

    def init_params(self, *args, **kwargs):
        self._buckets[self._default_bucket_key].init_params(*args, **kwargs)
        self.params_initialized = True

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        """ref: BucketingModule.set_params — applied via the current
        bucket; buckets share parameter storage by name with the default
        bucket (switch_bucket), so shared entries update everywhere."""
        self._curr_module.set_params(arg_params, aux_params,
                                     allow_missing=allow_missing,
                                     force_init=force_init,
                                     allow_extra=allow_extra)
        self.params_initialized = True

    def init_optimizer(self, *args, **kwargs):
        default = self._buckets[self._default_bucket_key]
        default.init_optimizer(*args, **kwargs)
        for key, mod in self._buckets.items():
            if key != self._default_bucket_key:
                mod._updater = default._updater
                mod._optimizer = default._optimizer
                mod.optimizer_initialized = True
        self.optimizer_initialized = True

    def get_params(self):
        return self._buckets[self._default_bucket_key].get_params()

    def forward(self, data_batch, is_train=None):
        key = data_batch.bucket_key
        if key is None:
            key = self._curr_bucket_key
        if key != self._curr_bucket_key or key not in self._buckets:
            self.switch_bucket(key, data_batch.provide_data,
                               data_batch.provide_label)
        self._curr_module.forward(data_batch, is_train=is_train)

    def backward(self, out_grads=None):
        self._curr_module.backward(out_grads)

    def update(self):
        self._curr_module.update()

    def _grad_datas(self):
        # guardrails see the active bucket's executor — the one whose
        # gradients the next update() would apply
        if self._curr_module is None:
            return None
        return self._curr_module._grad_datas()

    def _guard_optimizers(self):
        # every bucket shares the default bucket's optimizer object
        # (init_optimizer/switch_bucket above), so one backoff covers all
        default = self._buckets.get(self._default_bucket_key) \
            if self._buckets else None
        return default._guard_optimizers() if default is not None else []

    def _guard_reinit_updaters(self):
        default = self._buckets.get(self._default_bucket_key) \
            if self._buckets else None
        if default is None:
            return
        default._guard_reinit_updaters()
        for key, mod in self._buckets.items():
            if mod is not default:
                # re-share the fresh updater exactly as init_optimizer does
                mod._updater = default._updater
                mod._optimizer = default._optimizer

    def get_outputs(self, merge_multi_context=True):
        return self._curr_module.get_outputs(merge_multi_context)

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        self._curr_module.update_metric(eval_metric, labels)

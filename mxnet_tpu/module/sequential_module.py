"""SequentialModule — a container chaining child modules
(ref: python/mxnet/module/sequential_module.py SequentialModule).

The reference threads each module's output NDArrays into the next
module's data slots and propagates input gradients back through the
chain. The TPU build keeps that contract exactly: every child is an
independently bound/compiled executor, the chain glue is host-side.
(For a fused single-program alternative, compose the symbols and use
one Module — XLA then optimizes across the boundary; SequentialModule
exists for script parity with the reference API.)
"""
from __future__ import annotations

import logging

from ..base import MXNetError
from .base_module import BaseModule

__all__ = ["SequentialModule"]


class SequentialModule(BaseModule):
    META_TAKE_LABELS = "take_labels"
    META_AUTO_WIRING = "auto_wiring"

    def __init__(self, logger=logging):
        super().__init__(logger=logger)
        self._modules = []
        self._metas = []
        self._label_shapes = None

    def add(self, module, **kwargs):
        """Add a module to the chain. kwargs: ``take_labels`` (this module
        needs the data batch's labels, e.g. the one holding the loss) and
        ``auto_wiring`` (rename the previous module's outputs, in order,
        to this module's data names)."""
        bad = set(kwargs) - {self.META_TAKE_LABELS, self.META_AUTO_WIRING}
        if bad:
            raise MXNetError(f"SequentialModule.add: unknown meta {bad}")
        self._modules.append(module)
        self._metas.append(dict(kwargs))
        self.binded = False
        self.params_initialized = False
        self.optimizer_initialized = False
        return self          # chaining, like the reference

    # -- introspection -------------------------------------------------------
    @property
    def data_names(self):
        return self._modules[0]._data_names if self._modules else []

    @property
    def output_names(self):
        return self._modules[-1]._symbol.list_outputs() if self._modules else []

    # -- binding -------------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if self.binded and not force_rebind:
            return
        if not self._modules:
            raise MXNetError("SequentialModule is empty — call add() first")
        if shared_module is not None:
            raise MXNetError("SequentialModule does not support shared_module "
                             "(same as the reference)")
        self.for_training = for_training
        self._label_shapes = label_shapes
        cur_shapes = list(data_shapes)
        n = len(self._modules)
        for i, (mod, meta) in enumerate(zip(self._modules, self._metas)):
            take_labels = meta.get(self.META_TAKE_LABELS, False)
            # intermediate modules need input grads so backward can chain
            need_grad = inputs_need_grad if i == 0 else True
            mod.bind(cur_shapes,
                     label_shapes=label_shapes if take_labels else None,
                     for_training=for_training,
                     inputs_need_grad=need_grad,
                     force_rebind=force_rebind, grad_req=grad_req)
            if i < n - 1:
                # output shapes of this module feed the next
                shapes = {name: tuple(shape) for name, shape in
                          [(d[0], d[1]) for d in cur_shapes]}
                if take_labels and label_shapes:
                    shapes.update({d[0]: tuple(d[1]) for d in label_shapes})
                _, out_shapes, _ = mod._symbol.infer_shape(**shapes)
                out_names = mod._symbol.list_outputs()
                nxt = self._modules[i + 1]
                if self._metas[i + 1].get(self.META_AUTO_WIRING, False):
                    names = nxt._data_names
                    if len(names) != len(out_names):
                        raise MXNetError(
                            f"auto_wiring: module {i} emits "
                            f"{len(out_names)} outputs but module {i+1} "
                            f"takes {len(names)} inputs")
                    cur_shapes = list(zip(names, out_shapes))
                else:
                    cur_shapes = list(zip(out_names, out_shapes))
        self.binded = True

    # -- params --------------------------------------------------------------
    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        if self.params_initialized and not force_init:
            return
        if not self.binded:
            raise MXNetError("call bind before init_params")
        seen = set()
        for mod in self._modules:
            mod.init_params(initializer=initializer, arg_params=arg_params,
                            aux_params=aux_params, allow_missing=True,
                            force_init=force_init, allow_extra=True)
            dup = seen & set(mod._param_names)
            if dup:
                raise MXNetError(f"duplicate parameter names across chained "
                                 f"modules: {sorted(dup)} (the reference "
                                 f"forbids this too)")
            seen |= set(mod._param_names)
        self.params_initialized = True

    def get_params(self):
        arg, aux = {}, {}
        for mod in self._modules:
            a, x = mod.get_params()
            arg.update(a)
            aux.update(x)
        return arg, aux

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        for mod in self._modules:
            mod.set_params(arg_params, aux_params, allow_missing=True,
                           force_init=force_init, allow_extra=True)
        self.params_initialized = True

    # -- optimizer -----------------------------------------------------------
    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=None, force_init=False):
        if self.optimizer_initialized and not force_init:
            return
        for mod in self._modules:
            mod.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                               optimizer_params=optimizer_params,
                               force_init=force_init)
        self.optimizer_initialized = True

    # -- execution -----------------------------------------------------------
    def install_monitor(self, mon):
        """ref: SequentialModule.install_monitor — every sub-module's
        executor reports to the same Monitor."""
        if not self.binded:
            raise MXNetError("call bind before install_monitor")
        for module in self._modules:
            module.install_monitor(mon)

    def forward(self, data_batch, is_train=None):
        from ..io import DataBatch
        if not self.binded:
            raise MXNetError("call bind before forward")
        data = data_batch.data
        for i, (mod, meta) in enumerate(zip(self._modules, self._metas)):
            take_labels = meta.get(self.META_TAKE_LABELS, False)
            label = data_batch.label if take_labels else None
            mod.forward(DataBatch(data=data, label=label),
                        is_train=is_train)
            if i < len(self._modules) - 1:
                data = mod.get_outputs()

    def backward(self, out_grads=None):
        grads = out_grads
        for i in range(len(self._modules) - 1, -1, -1):
            self._modules[i].backward(grads)
            grads = self._modules[i].get_input_grads()

    def update(self):
        for mod in self._modules:
            mod.update()

    def _grad_datas(self):
        # guardrails see every chained module's gradients: update()
        # applies them all, so a NaN anywhere must veto the whole step
        out = []
        for mod in self._modules:
            g = mod._grad_datas()
            if g is None:
                return None
            out.extend(g)
        return out or None

    def _guard_optimizers(self):
        # chained modules may each own an optimizer (init_optimizer
        # above creates one per module from a string spec): the rollback
        # LR backoff must land on every distinct one
        out, seen = [], set()
        for mod in self._modules:
            for opt in mod._guard_optimizers():
                if id(opt) not in seen:
                    seen.add(id(opt))
                    out.append(opt)
        return out

    def _guard_reinit_updaters(self):
        for mod in self._modules:
            mod._guard_reinit_updaters()

    def get_outputs(self, merge_multi_context=True):
        return self._modules[-1].get_outputs(merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        return self._modules[0].get_input_grads(merge_multi_context)

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        for mod, meta in zip(self._modules, self._metas):
            if meta.get(self.META_TAKE_LABELS, False):
                mod.update_metric(eval_metric, labels, pre_sliced=pre_sliced)

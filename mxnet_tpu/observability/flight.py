"""Crash flight recorder — the postmortem ring that survives the kill.

A pod drill's most valuable process is the one that can no longer be
asked: the SIGKILLed replica, the wedged rank the driver timed out.
This module keeps the two always-on bounded rings the runtime already
maintains — the tracer's span ring (observability/trace.py) and the
journal's recent-records ring (diagnostics/journal.py) — and writes
them, plus a clock-alignment anchor and the pod identity block, as ONE
atomic JSON dump other processes can read after this one is gone::

    <out_dir>/flight-<label>.json

Dump triggers (the existing diagnostics hooks, per the journal/watchdog
contracts):

- **SIGTERM / normal exit** — ``journal.install_handlers`` finalizer
  (reason ``sigterm``/``atexit``);
- **crash** — the finalizer again: an unhandled exception reaches
  atexit with the crash record already in the journal ring;
- **wedge** — the watchdog's stall hook (reason ``stall``), captured
  BEFORE the driver's outer kill lands;
- **SIGKILL** — nothing runs, so the recorder also flushes
  periodically (``MXNET_TPU_TRACE_FLIGHT_S``, default 2 s): the last
  periodic dump IS the postmortem, at most one flush interval stale.

Every dump is a whole-file atomic replace (``resilience.atomic``), so a
kill mid-flush leaves the previous complete dump, never half a JSON.
``observability/aggregate.py`` folds flight dumps into the merged
cross-process trace exactly like journal span records — the killed
replica's tail appears on the shared timeline.

Knobs (docs/env_vars.md): ``MXNET_TPU_TRACE_DIR`` (the shared-FS run
directory; unset = recorder off), ``MXNET_TPU_TRACE_FLIGHT_S``
(periodic flush interval; ``0`` disables the periodic thread, dumps
still fire on the event hooks).

Stdlib-only, no jax — a flight recorder that needs the runtime healthy
would miss exactly the flights it exists for.
"""
from __future__ import annotations

import json
import os
import threading

from ..diagnostics import watchdog as _watchdog
from ..diagnostics.journal import get_journal
from . import trace as _trace

__all__ = ["FlightRecorder", "DEFAULT_FLUSH_S", "flight_path",
           "install_from_env", "read_flight"]

DEFAULT_FLUSH_S = 2.0
DUMP_SPANS_CAP = 512          # last-N spans per dump (bounded file size)


def _env_float(name, default):
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def _label() -> str:
    """Stable per-process dump label: replica id when the pool stamped
    one, else rank-qualified pid — two processes of one pod can never
    clobber each other's dump file."""
    ident = _trace.identity()
    if ident.get("replica") is not None:
        return f"replica-{ident['replica']}"
    return f"rank{ident['rank']}-pid{ident['pid']}"


def flight_path(out_dir, label=None) -> str:
    return os.path.join(str(out_dir), f"flight-{label or _label()}.json")


def read_flight(path) -> dict:
    """Load one dump (the aggregator/tests' reader).  Raises OSError /
    ValueError on an unreadable file — callers decide what a missing
    postmortem means."""
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or doc.get("kind") != "flight":
        raise ValueError(f"{path} is not a flight-recorder dump")
    return doc


class FlightRecorder:
    """One process's dump writer: event-hook dumps + optional periodic
    flush.  ``install()`` wires the diagnostics hooks; ``stop(dump=
    True)`` writes the clean-exit dump and detaches the wedge hook."""

    def __init__(self, out_dir, label=None, flush_s=None, journal=None):
        self.out_dir = str(out_dir)
        self.label = label or _label()
        self.flush_s = (_env_float("MXNET_TPU_TRACE_FLIGHT_S",
                                   DEFAULT_FLUSH_S)
                        if flush_s is None else float(flush_s))
        self._journal = journal if journal is not None else get_journal()
        self._stop = threading.Event()
        self._thread = None
        self._installed = False
        self._on_stall = lambda: self.dump("stall")
        self._on_final = lambda: self.dump("final")
        self.dumps = 0
        self.drops = 0

    @property
    def path(self) -> str:
        return flight_path(self.out_dir, self.label)

    MAX_PREV = 3

    def _rotate_existing(self) -> None:
        """A fresh incarnation must not clobber its predecessor's
        postmortem: a respawned replica reuses the label, so the
        existing dump rotates to ``flight-<label>.prev-1.json`` (a
        bounded history — the aggregator folds the prevs into the same
        process identity by their own anchors)."""
        path = self.path
        if not os.path.exists(path):
            return
        base = path[:-len(".json")]
        try:
            for n in range(self.MAX_PREV, 1, -1):
                older = f"{base}.prev-{n - 1}.json"
                if os.path.exists(older):
                    os.replace(older, f"{base}.prev-{n}.json")
            os.replace(path, f"{base}.prev-1.json")
        except OSError:
            pass             # rotation is best-effort; dumping must win

    # -- the dump --------------------------------------------------------
    def dump(self, reason: str) -> str | None:
        """Write the rings atomically; returns the path (None when the
        write failed — a flight recorder must never take the plane
        down with it)."""
        tracer = _trace.get_tracer()
        spans = tracer.spans()
        doc = {"kind": "flight", "reason": reason, "label": self.label,
               "seq": self.dumps + 1,
               "anchor": _trace.anchor_doc(tracer),
               "trace": tracer.stats(),
               "spans": spans[-DUMP_SPANS_CAP:],
               "journal_tail": self._journal.recent(),
               "last_phase": self._journal.last_phase,
               **_trace.identity()}
        try:
            from ..resilience.atomic import atomic_write
            os.makedirs(self.out_dir, exist_ok=True)
            with atomic_write(self.path, "w", durable=False) as f:
                json.dump(doc, f, default=str)
        except (OSError, ValueError) as exc:
            self._note_drop(exc)
            return None
        self.dumps += 1
        return self.path

    def _note_drop(self, exc) -> None:
        """A dump write failed (full/unwritable run dir): degrade to
        drop-and-count — bump the drops metric, journal ONE marker per
        recorder (the journal itself degrades under the same disk), and
        keep flying.  The previous complete dump stays on disk."""
        self.drops += 1
        try:
            from .metrics import default_registry
            default_registry().counter(
                "mxnet_tpu_flight_dump_drops_total",
                "flight-recorder dumps dropped because the run-dir "
                "write failed (full/unwritable disk)").inc()
        except Exception:
            pass                 # accounting must never ground the recorder
        if self.drops == 1:
            self._journal.event("flight_dump_failed", path=self.path,
                                error=type(exc).__name__,
                                detail=str(exc)[:200])

    # -- lifecycle -------------------------------------------------------
    def install(self) -> "FlightRecorder":
        """Wire the diagnostics hooks (idempotent): the journal's
        SIGTERM/atexit finalizer and the watchdog's stall callback; then
        start the periodic flush thread (when ``flush_s > 0``)."""
        if self._installed:
            return self
        self._installed = True
        self._rotate_existing()
        # final_cb fires on SIGTERM/atexit UNLESS mark_clean() was
        # called — but a clean exit should keep its dump too, so the
        # worker calls stop(dump=True) explicitly on its shutdown path
        # (stop also UNREGISTERS this callback: the exit-time "final"
        # dump must not overwrite the clean "stop" one)
        self._journal.install_handlers(final_cb=self._on_final)
        _watchdog.add_stall_callback(self._on_stall)
        if self.flush_s > 0:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, daemon=True,
                name=f"mxtpu-flight-{self.label}")
            self._thread.start()
        self._journal.event("flight_recorder_start", path=self.path,
                            flush_s=self.flush_s)
        return self

    def _run(self):
        while not self._stop.wait(self.flush_s):
            self.dump("periodic")

    def stop(self, dump=True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.flush_s + 5.0)
            self._thread = None
        _watchdog.remove_stall_callback(self._on_stall)
        self._journal.remove_final_cb(self._on_final)
        self._installed = False      # a later install() rewires cleanly
        if dump:
            self.dump("stop")


def install_from_env(journal=None) -> FlightRecorder | None:
    """Start a recorder when ``MXNET_TPU_TRACE_DIR`` names a run
    directory; None (and zero cost) otherwise — the always-off default
    keeps the off-is-free contract for processes outside a pod run."""
    out_dir = os.environ.get("MXNET_TPU_TRACE_DIR")
    if not out_dir:
        return None
    return FlightRecorder(out_dir, journal=journal).install()

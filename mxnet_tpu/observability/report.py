"""Stdlib-only trace/metrics summaries (``doctor --trace`` /
``doctor --metrics``).

``trace_report`` reduces a JSONL journal's ``kind="span"`` records
(written with ``MXNET_TPU_TRACE=journal``) to the operator signals:
span/trace counts, per-name duration stats, the slowest spans.
``metrics_report`` reads a metrics snapshot back out of a JSON file —
either a raw ``observability.snapshot()`` dump or a BENCH artifact
carrying one under ``"observability"`` — and summarizes compile
counts/times and step-phase percentiles.

Same contract as serving/guardrails reports: no jax, junk lines
tolerated, always returns a dict with ``ok``.
"""
from __future__ import annotations

import json

__all__ = ["metrics_report", "read_span_records", "trace_report"]


def _iter_records(path):
    """Parsed dict records of a JSONL journal, junk/torn lines
    tolerated.  Raises OSError when the file is unreadable."""
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue                     # torn tail of a killed writer
            if isinstance(rec, dict):
                yield rec


def read_span_records(path) -> list:
    """``kind="span"`` records of a JSONL journal, junk/torn lines
    tolerated — THE span scanner, shared with the Perfetto exporter
    (export.chrome_trace_from_journal) so the doctor report and the
    dump can never diverge on what counts as a span.  Raises OSError
    when the file is unreadable."""
    return [r for r in _iter_records(path) if r.get("kind") == "span"]


def trace_report(path) -> dict:
    """Summarize the ``span`` records of a journal file.  One pass
    collects both the spans and the run's highest journaled
    ``trace_ring_drops`` marker (the counts are cumulative so
    max == total) — journals are unbounded, the report must not scale
    at 2x the file."""
    spans: list = []
    ring_drops = 0
    try:
        for rec in _iter_records(path):
            kind = rec.get("kind")
            if kind == "span":
                spans.append(rec)
            elif kind == "trace_ring_drops":
                try:
                    ring_drops = max(ring_drops,
                                     int(rec.get("dropped") or 0))
                except (TypeError, ValueError):
                    pass         # junk-tolerant, like every other line
    except OSError as e:
        return {"ok": False, "path": path,
                "error": f"cannot read {path}: {e.strerror or e}"}
    if not spans:
        return {"ok": False, "path": path,
                "error": "no span records in journal (was "
                         "MXNET_TPU_TRACE=journal set?)"}
    by_name: dict = {}
    traces = set()
    for s in spans:
        traces.add(s.get("trace_id"))
        durs = by_name.setdefault(s.get("name", "?"), [])
        if s.get("dur_s") is not None:
            durs.append(float(s["dur_s"]))

    def _stats(durs):
        if not durs:
            return {"count": 0}
        ds = sorted(durs)
        return {"count": len(ds),
                "total_s": round(sum(ds), 6),
                "p50_s": round(ds[len(ds) // 2], 6),
                "max_s": round(ds[-1], 6)}

    slowest = sorted((s for s in spans if s.get("dur_s") is not None),
                     key=lambda s: -float(s["dur_s"]))[:5]
    return {"ok": True, "path": path,
            "spans": len(spans), "traces": len(traces),
            "ring_drops": ring_drops,
            "by_name": {n: _stats(d) for n, d in sorted(by_name.items())},
            "slowest": [{"name": s.get("name"),
                         "dur_s": round(float(s["dur_s"]), 6),
                         "trace_id": s.get("trace_id")}
                        for s in slowest]}


def metrics_report(path) -> dict:
    """Summarize a metrics snapshot JSON file (raw ``snapshot()`` dump
    or a BENCH artifact with an ``observability`` section)."""
    try:
        with open(path, encoding="utf-8") as f:
            text = f.read()
    except OSError as e:
        return {"ok": False, "path": path,
                "error": f"cannot read {path}: {e.strerror or e}"}
    # whole-file parse first (a pretty-printed snapshot dump), then a
    # per-line scan (a JSONL artifact stream / one-line-per-record file)
    doc = None
    try:
        parsed = json.loads(text)
        if isinstance(parsed, dict):
            doc = parsed
    except ValueError:
        pass
    if doc is None:
        for candidate in text.splitlines():
            candidate = candidate.strip()
            if not candidate.startswith("{"):
                continue
            try:
                parsed = json.loads(candidate)
            except ValueError:
                continue
            if isinstance(parsed, dict):
                doc = parsed
                break
    if doc is None:
        return {"ok": False, "path": path, "error": "no JSON object found"}
    obs = doc.get("observability", doc)
    metrics = obs.get("metrics", obs) if isinstance(obs, dict) else {}
    if not isinstance(metrics, dict) or not metrics:
        return {"ok": False, "path": path,
                "error": "no metrics snapshot in file"}
    out = {"ok": True, "path": path, "families": len(metrics)}
    compiles = metrics.get("mxnet_tpu_xla_compiles_total", {})
    if isinstance(compiles.get("values"), dict):
        out["compiles"] = {k or "total": v
                           for k, v in compiles["values"].items()}
        out["compiles_total"] = sum(
            float(v) for v in compiles["values"].values())
    compile_ms = metrics.get("mxnet_tpu_xla_compile_ms", {})
    if isinstance(compile_ms.get("values"), dict):
        out["compile_ms"] = compile_ms["values"]
    phases = metrics.get("mxnet_tpu_step_phase_ms", {})
    if isinstance(phases.get("values"), dict):
        out["step_phase_ms"] = phases["values"]
    return out

"""Trace CLI: ``python -m mxnet_tpu.observability
dump|report|aggregate|timeline``.

``dump``       convert ONE JSONL journal's ``kind="span"`` records
               (written with ``MXNET_TPU_TRACE=journal``) to Chrome
               trace-event JSON loadable in Perfetto
               (ui.perfetto.dev → Open trace).
``report``     print the stdlib trace summary (``doctor --trace`` body)
               as one JSON line.
``aggregate``  merge a POD RUN DIRECTORY (per-process journals +
               flight-recorder dumps, ``MXNET_TPU_TRACE_DIR`` during
               the run) into one anchor-aligned Perfetto trace — one
               pid per process, SIGKILLed replicas' flight tails
               included (docs/observability.md).
``timeline``   the cross-process critical-path summary of one trace
               (default: the slowest routed request) as ONE JSON line —
               the ``doctor --timeline`` body.

All read files only — no jax, usable from a wedged environment.
"""
from __future__ import annotations

import argparse
import json
import sys

from . import aggregate, export, report


def _write_doc(doc, out) -> None:
    if out:
        from ..resilience.atomic import atomic_write
        with atomic_write(out, "w") as f:
            json.dump(doc, f)
        print(json.dumps({"ok": True, "out": out,
                          "events": len(doc["traceEvents"])}),
              flush=True)
    else:
        json.dump(doc, sys.stdout)
        sys.stdout.write("\n")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m mxnet_tpu.observability",
        description="trace export/report tools (docs/observability.md)")
    sub = ap.add_subparsers(dest="cmd", required=True)
    d = sub.add_parser("dump", help="journal span records -> Chrome "
                                    "trace-event JSON (Perfetto)")
    d.add_argument("--journal", required=True,
                   help="JSONL journal path (MXNET_TPU_JOURNAL=<file> + "
                        "MXNET_TPU_TRACE=journal during the run)")
    d.add_argument("--out", default=None,
                   help="output path (default: stdout)")
    r = sub.add_parser("report", help="summarize journal span records; "
                                      "ONE JSON line on stdout")
    r.add_argument("--journal", required=True)
    a = sub.add_parser("aggregate",
                       help="merge a pod run dir (per-process journals "
                            "+ flight dumps) into one Perfetto trace")
    a.add_argument("--dir", required=True,
                   help="run directory (MXNET_TPU_TRACE_DIR of the run)")
    a.add_argument("--out", default=None,
                   help="output path (default: stdout)")
    t = sub.add_parser("timeline",
                       help="cross-process critical path of one trace; "
                            "ONE JSON line on stdout")
    t.add_argument("--dir", required=True)
    t.add_argument("--trace-id", default=None,
                   help="trace to follow (default: slowest routed "
                        "request)")
    args = ap.parse_args(argv)

    if args.cmd == "dump":
        try:
            doc = export.chrome_trace_from_journal(args.journal)
        except OSError as e:
            print(json.dumps({"ok": False, "error": str(e)}), flush=True)
            return 1
        _write_doc(doc, args.out)
        return 0

    if args.cmd == "aggregate":
        try:
            doc = aggregate.aggregate_chrome(args.dir)
        except OSError as e:
            print(json.dumps({"ok": False, "error": str(e)}), flush=True)
            return 1
        _write_doc(doc, args.out)
        return 0

    if args.cmd == "timeline":
        rep = aggregate.timeline_report(args.dir, trace_id=args.trace_id)
        print(json.dumps(rep), flush=True)
        return 0 if rep.get("ok") else 1

    rep = report.trace_report(args.journal)
    print(json.dumps(rep), flush=True)
    return 0 if rep.get("ok") else 1


if __name__ == "__main__":
    sys.exit(main())

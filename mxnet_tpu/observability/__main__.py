"""Trace CLI: ``python -m mxnet_tpu.observability dump|report``.

``dump``    convert a JSONL journal's ``kind="span"`` records (written
            with ``MXNET_TPU_TRACE=journal``) to Chrome trace-event
            JSON loadable in Perfetto (ui.perfetto.dev → Open trace).
``report``  print the stdlib trace summary (``doctor --trace`` body)
            as one JSON line.

Both read journals only — no jax, usable from a wedged environment.
"""
from __future__ import annotations

import argparse
import json
import sys

from . import export, report


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m mxnet_tpu.observability",
        description="trace export/report tools (docs/observability.md)")
    sub = ap.add_subparsers(dest="cmd", required=True)
    d = sub.add_parser("dump", help="journal span records -> Chrome "
                                    "trace-event JSON (Perfetto)")
    d.add_argument("--journal", required=True,
                   help="JSONL journal path (MXNET_TPU_JOURNAL=<file> + "
                        "MXNET_TPU_TRACE=journal during the run)")
    d.add_argument("--out", default=None,
                   help="output path (default: stdout)")
    r = sub.add_parser("report", help="summarize journal span records; "
                                      "ONE JSON line on stdout")
    r.add_argument("--journal", required=True)
    args = ap.parse_args(argv)

    if args.cmd == "dump":
        try:
            doc = export.chrome_trace_from_journal(args.journal)
        except OSError as e:
            print(json.dumps({"ok": False, "error": str(e)}), flush=True)
            return 1
        if args.out:
            from ..resilience.atomic import atomic_write
            with atomic_write(args.out, "w") as f:
                json.dump(doc, f)
            print(json.dumps({"ok": True, "out": args.out,
                              "events": len(doc["traceEvents"])}),
                  flush=True)
        else:
            json.dump(doc, sys.stdout)
            sys.stdout.write("\n")
        return 0

    rep = report.trace_report(args.journal)
    print(json.dumps(rep), flush=True)
    return 0 if rep.get("ok") else 1


if __name__ == "__main__":
    sys.exit(main())

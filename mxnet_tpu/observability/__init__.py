"""mxnet_tpu.observability — unified telemetry: span tracing, a metrics
registry, and exporters (docs/observability.md).

One substrate every subsystem records into:

- :mod:`.trace` — ``span(name, **attrs)`` context managers with
  process-unique trace/span IDs, cross-thread parent propagation and
  rank tagging; bounded in-memory ring + optional JSONL journal
  streaming (``MXNET_TPU_TRACE=off|ring|journal``).  Off-by-default
  cheap: disabled tracing is one shared no-op and zero device reads.
- :mod:`.metrics` — counters, gauges and histogram summaries
  (``LatencySummary`` as the backend) with labeled families and a
  process-wide default registry; always-on host counters feed the
  compile/step-phase provenance even with tracing off.
- :mod:`.export` — Chrome trace-event JSON (Perfetto-loadable) from the
  ring or a journal file; a stdlib ``/metrics`` HTTP endpoint.
- :mod:`.report` — stdlib ``doctor --trace`` / ``doctor --metrics``
  summaries.
- :mod:`.instrument` — the shared step-phase / compile-span helpers the
  four trainers, serving and checkpointing use.

Every journal record written inside a span carries ``trace_id``/
``span_id`` (the provider hook in diagnostics.journal), so the
historically separate journals — ``serving_batch``, ``nonfinite_grad``,
``ckpt_fallback``, ``pallas_fallback`` — correlate against one trace.

Stdlib-only: importable (and exportable) while jax or the backend is
wedged.
"""
from __future__ import annotations

from . import aggregate, export, flight, instrument, metrics, report, trace
from .aggregate import (aggregate_chrome, critical_path, scan_run_dir,
                        timeline_report)
from .export import (chrome_trace_from_journal, export_chrome,
                     serve_metrics, to_chrome_trace)
from .flight import FlightRecorder, install_from_env
from .metrics import (Counter, Gauge, LatencySummary, MetricsRegistry,
                      Summary, default_registry, prometheus_text,
                      reset_metrics)
from .trace import (SpanContext, Tracer, adopt_trace, annotate, configure,
                    current_context, current_ids, current_span, enabled,
                    event, get_tracer, identity, reset_tracer, span,
                    start_span)

__all__ = [
    "Counter", "FlightRecorder", "Gauge", "LatencySummary",
    "MetricsRegistry", "Summary", "SpanContext", "Tracer", "adopt_trace",
    "aggregate", "aggregate_chrome", "annotate",
    "chrome_trace_from_journal", "compile_stats", "configure",
    "critical_path", "current_context", "current_ids", "current_span",
    "default_registry", "enabled", "event", "export", "export_chrome",
    "flight", "get_tracer", "identity", "install_from_env", "instrument",
    "metrics", "prometheus_text", "report", "reset_metrics",
    "reset_tracer", "scan_run_dir", "serve_metrics", "snapshot", "span",
    "start_span", "timeline_report", "to_chrome_trace", "trace",
]


def snapshot() -> dict:
    """One JSON-able telemetry snapshot: the full metrics registry plus
    tracer accounting — the provenance block ``bench.py`` embeds in
    BENCH artifacts (``"observability": ...``) and ``doctor --metrics``
    reads back."""
    return {"metrics": default_registry().snapshot(),
            "trace": get_tracer().stats()}


def _site_family(metrics_d, count_metric, ms_metric):
    """(total count, total ms, per-site counts) for one count+ms
    metric-family pair out of a snapshot dict."""
    counts = (metrics_d.get(count_metric) or {}).get("values") or {}
    times = (metrics_d.get(ms_metric) or {}).get("values") or {}
    total_ms = 0.0
    for v in times.values():
        if isinstance(v, dict) and v.get("count"):
            if v.get("sum") is not None:
                total_ms += v["sum"]
            else:          # pre-sum snapshot (old BENCH artifact)
                total_ms += v["count"] * (v.get("mean") or 0.0)
    return (int(sum(float(v) for v in counts.values())),
            round(total_ms, 1),
            {k.replace("site=", "", 1): int(v)
             for k, v in sorted(counts.items())})


def compile_stats(snap=None) -> dict:
    """Compile accounting out of a snapshot (default: the live
    registry): total count, total ms, and the per-site split — the
    one-line summary a bench run prints.  Deserialized AOT-cache loads
    are reported as their OWN family (``aot_loads``/``aot_load_ms``/
    ``aot_by_site``), never folded into ``compiles`` — a warm start's
    zero-compile claim stays honest (docs/observability.md)."""
    snap = snap if snap is not None else snapshot()
    metrics_d = snap.get("metrics", snap)
    compiles, total_ms, by_site = _site_family(
        metrics_d, instrument.COMPILE_COUNT_METRIC,
        instrument.COMPILE_MS_METRIC)
    aot_loads, aot_ms, aot_by_site = _site_family(
        metrics_d, instrument.AOT_LOAD_COUNT_METRIC,
        instrument.AOT_LOAD_MS_METRIC)
    return {"compiles": compiles, "total_ms": total_ms,
            "by_site": by_site,
            "aot_loads": aot_loads, "aot_load_ms": aot_ms,
            "aot_by_site": aot_by_site}

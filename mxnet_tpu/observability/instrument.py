"""Shared instrumentation helpers for the hot paths.

The four trainers, the serving predictor cache and the checkpoint
commit protocol all record the same two shapes of signal:

- **step phases** (data wait / compiled step / guard fetch): a
  monotonic-timed scope observed into the always-on
  ``mxnet_tpu_step_phase_ms{trainer,phase}`` summary (host arithmetic
  only — the per-step cost is two ``perf_counter`` reads and one lock),
  plus a nested trace span when ``MXNET_TPU_TRACE`` is on;
- **compile events**: every jit-cache-miss site wraps its build in
  :func:`compile_span`, so XLA trace/lower/compile time lands in
  ``mxnet_tpu_xla_compiles_total{site}`` /
  ``mxnet_tpu_xla_compile_ms{site}`` and, when tracing, as an
  ``xla_compile`` span with the shapes attached.

Zero-device-read contract: nothing here touches a device value —
tests/test_observability.py runs the compiled step paths of all four
trainers under ``jax.transfer_guard_device_to_host("disallow")``.
"""
from __future__ import annotations

import contextlib
import time

from . import trace
from .metrics import default_registry

__all__ = ["aot_load_span", "compile_span", "maybe_compile_span",
           "step_phase", "PHASE_METRIC", "COMPILE_COUNT_METRIC",
           "COMPILE_MS_METRIC", "AOT_LOAD_COUNT_METRIC",
           "AOT_LOAD_MS_METRIC"]

PHASE_METRIC = "mxnet_tpu_step_phase_ms"
COMPILE_COUNT_METRIC = "mxnet_tpu_xla_compiles_total"
COMPILE_MS_METRIC = "mxnet_tpu_xla_compile_ms"
AOT_LOAD_COUNT_METRIC = "mxnet_tpu_aot_loads_total"
AOT_LOAD_MS_METRIC = "mxnet_tpu_aot_load_ms"


_phase_cache = None


def _phase_summary():
    # per-registry memo: the family lookup (name validation + registry
    # lock) would otherwise run four times per training step; the cache
    # keys on registry identity so reset_metrics() (tests) invalidates
    global _phase_cache
    reg = default_registry()
    cached = _phase_cache
    if cached is not None and cached[0] is reg:
        return cached[1]
    fam = reg.summary(
        PHASE_METRIC, "per-phase training-step wall time (monotonic), ms",
        ("trainer", "phase"))
    _phase_cache = (reg, fam)
    return fam


@contextlib.contextmanager
def step_phase(trainer, phase, **attrs):
    """One training-step phase: always observed into the phase summary,
    traced as ``<trainer>.<phase>`` when tracing is on."""
    t0 = time.perf_counter()
    with trace.span(f"{trainer}.{phase}", **attrs):
        try:
            yield
        finally:
            _phase_summary().labels(trainer=trainer, phase=phase).observe(
                (time.perf_counter() - t0) * 1000.0)


@contextlib.contextmanager
def compile_span(site, **attrs):
    """One compile event (jit cache miss / executable build) at
    ``site``: counted, timed, and traced as ``xla_compile``."""
    reg = default_registry()
    t0 = time.perf_counter()
    with trace.span("xla_compile", site=site, **attrs):
        try:
            yield
        finally:
            ms = (time.perf_counter() - t0) * 1000.0
            reg.counter(COMPILE_COUNT_METRIC,
                        "XLA trace/lower/compile events",
                        ("site",)).labels(site=site).inc()
            reg.summary(COMPILE_MS_METRIC, "XLA compile wall time, ms",
                        ("site",)).labels(site=site).observe(ms)


@contextlib.contextmanager
def aot_load_span(site, **attrs):
    """One deserialized-executable load at ``site``: counted, timed,
    and traced as ``aot_load`` — deliberately a DIFFERENT site family
    from ``xla_compile`` so a warm start's ``compile_stats()`` reads
    zero compiles honestly (docs/observability.md)."""
    reg = default_registry()
    t0 = time.perf_counter()
    with trace.span("aot_load", site=site, **attrs):
        try:
            yield
        finally:
            ms = (time.perf_counter() - t0) * 1000.0
            reg.counter(AOT_LOAD_COUNT_METRIC,
                        "deserialized AOT executable loads",
                        ("site",)).labels(site=site).inc()
            reg.summary(AOT_LOAD_MS_METRIC,
                        "AOT executable load wall time, ms",
                        ("site",)).labels(site=site).observe(ms)


def maybe_compile_span(pending, site, **attrs):
    """``compile_span`` when ``pending`` (this dispatch includes the
    compile), else a null context — the first-call pattern at the
    trainers' jit sites."""
    if pending:
        return compile_span(site, **attrs)
    return contextlib.nullcontext()

"""Span tracing — the correlation substrate every subsystem records into.

Six PRs each grew their own observability dialect (diagnostics JSONL
breadcrumbs, ``serving_batch`` records, ``guard_poll`` events,
``pallas_fallback`` records) with nothing correlating them.  This module
is the spine of the fix: a ``span(name, **attrs)`` context manager with
process-unique trace/span IDs, explicit parent propagation across
threads (the serving worker, watchdog, prefetch workers), rank tagging,
and monotonic-clock durations — recorded into a bounded in-memory ring
and, optionally, streamed to the existing diagnostics JSONL journal as
``kind="span"`` records so one ``tail`` carries both worlds.

Off-by-default-cheap contract (the guardrails discipline): with tracing
disabled, :func:`span` returns ONE shared no-op object — no allocation
beyond the call, no contextvar writes, and **never** a device read
(attrs must be host scalars; the instrumentation sites only pass ints,
strings and shape tuples).  tests/test_observability.py proves the
compiled step paths of all four trainers run under
``jax.transfer_guard_device_to_host("disallow")`` with tracing off.

Knobs::

    MXNET_TPU_TRACE       off (default) | ring | journal
                          ring    = bounded in-memory ring only
                          journal = ring + one JSONL record per span
    MXNET_TPU_TRACE_RING  ring capacity in spans (default 4096)

Pod attribution (docs/observability.md distributed tracing): spans are
tagged with the process rank (``MXTPU_PROC_ID``), the serving replica
identity (``MXNET_TPU_REPLICA_ID``, stamped by the replica pool into
every worker's environment) and, in anchor/flight records, the pod run
id (``MXNET_TPU_POD_RUN_ID``) — so a run directory of per-process
journals assembles into ONE attributable cross-process trace
(observability/aggregate.py).  Journal mode emits one ``trace_anchor``
record pairing this process's wall clock with its ``perf_counter``
timeline, the alignment point the aggregator uses to place every
process's monotonic span timestamps on a shared wall clock.

Import-light by the journal's own contract: stdlib only, no jax, no
mxnet_tpu runtime — exporters must work while everything else is wedged.
"""
from __future__ import annotations

import contextvars
import itertools
import os
import threading
import time
from collections import deque

__all__ = ["MODES", "Span", "SpanContext", "Tracer", "adopt_trace",
           "annotate", "configure", "current_context", "current_ids",
           "current_span", "enabled", "event", "get_tracer", "identity",
           "mode", "record", "reset_tracer", "span", "start_span"]

MODES = ("off", "ring", "journal")
DEFAULT_RING = 4096
DROPS_METRIC = "mxnet_tpu_trace_ring_drops_total"


def anchor_doc(tracer=None) -> dict:
    """The clock-alignment payload (shared by the journal
    ``trace_anchor`` record and the flight-recorder dump): an atomic
    wall/perf_counter sample pair, the tracer's span-timeline epoch, and
    the pod identity block."""
    tracer = tracer if tracer is not None else get_tracer()
    return {"wall_s": round(time.time(), 6),
            "perf_s": round(time.perf_counter(), 6),
            "epoch_s": round(tracer.epoch, 6), **identity()}

# process-unique trace-id prefix: two traces from two processes (multi-
# host ranks sharing one journal file) can never collide
_PROC_TOKEN = os.urandom(4).hex()
_ids = itertools.count(1)            # GIL-atomic; one sequence per process


def _rank() -> int:
    """Process rank for span tagging — env-derived (MXTPU_PROC_ID is set
    by tools/launch.py), never a jax call: tracing must not dial the
    backend."""
    try:
        return int(os.environ.get("MXTPU_PROC_ID", "0"))
    except ValueError:
        return 0


def _replica():
    """Serving-replica identity for span tagging — the replica pool
    stamps ``MXNET_TPU_REPLICA_ID`` into every worker's environment so
    two replicas that share a rank (two workers on one host) stay
    distinguishable in a merged trace (the Perfetto pid-collision fix).
    None outside a pool worker."""
    return os.environ.get("MXNET_TPU_REPLICA_ID") or None


def identity() -> dict:
    """This process's pod-attribution block: rank, replica (when the
    pool stamped one), pid, and the pod run id — the fields anchor and
    flight-recorder records carry so ``observability/aggregate.py`` can
    attribute every per-process file (docs/observability.md)."""
    doc = {"rank": _rank(), "pid": os.getpid()}
    rep = _replica()
    if rep is not None:
        doc["replica"] = rep
    run_id = os.environ.get("MXNET_TPU_POD_RUN_ID")
    if run_id:
        doc["run_id"] = run_id
    return doc


class SpanContext:
    """The cross-thread propagation token: just the two IDs.  Capture
    with :func:`current_context` on the submitting thread, pass as
    ``span(..., parent=ctx)`` on the worker thread."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id, span_id):
        self.trace_id = trace_id
        self.span_id = span_id

    def __repr__(self):
        return f"SpanContext({self.trace_id}, {self.span_id})"


class Span:
    """One timed scope.  Created by :func:`span`/:func:`start_span`;
    durations come from ``time.perf_counter`` (monotonic — wall-clock
    steps under NTP cannot produce negative durations, the G11 class)."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "attrs",
                 "rank", "replica", "thread", "t0", "dur_s", "_token",
                 "_ended")

    def __init__(self, name, trace_id, parent_id, attrs, t0=None):
        self.name = name
        self.trace_id = trace_id
        self.span_id = f"{next(_ids):08x}"
        self.parent_id = parent_id
        self.attrs = attrs
        self.rank = _rank()
        self.replica = _replica()
        self.thread = threading.current_thread().name
        self.t0 = time.perf_counter() if t0 is None else t0
        self.dur_s = None
        self._token = None
        self._ended = False

    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id)

    def set_attrs(self, **attrs):
        self.attrs.update(attrs)

    def end(self, _t1=None, **attrs) -> "Span":
        """Close a manually-started span (cross-thread lifecycles — the
        serving request root); idempotent so error paths can race the
        success path without double-recording."""
        if self._ended:
            return self
        self._ended = True
        if attrs:
            self.attrs.update(attrs)
        self.dur_s = (time.perf_counter() if _t1 is None else _t1) - self.t0
        get_tracer()._record(self)
        return self

    # -- context-manager protocol (the common single-thread case) ------------
    def __enter__(self):
        self._token = _current.set(self)
        return self

    def __exit__(self, exc_type, exc, tb):
        if self._token is not None:
            _current.reset(self._token)
            self._token = None
        if exc_type is not None:
            self.attrs.setdefault("error", exc_type.__name__)
        self.end()
        return False

    def to_dict(self) -> dict:
        d = {"name": self.name, "trace_id": self.trace_id,
             "span_id": self.span_id, "parent_id": self.parent_id,
             "start_s": round(self.t0 - get_tracer().epoch, 6),
             "dur_s": (round(self.dur_s, 6)
                       if self.dur_s is not None else None),
             "rank": self.rank, "thread": self.thread}
        if self.replica is not None:
            d["replica"] = self.replica
        if self.attrs:
            d["attrs"] = self.attrs
        return d


class _NoopSpan:
    """The disabled tier: one shared instance, every operation a no-op.
    ``trace_id``/``span_id`` are None so ``current_ids()`` consumers can
    treat it uniformly."""

    __slots__ = ()
    trace_id = span_id = parent_id = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set_attrs(self, **attrs):
        pass

    def end(self, **attrs):
        return self

    def context(self):
        return None


_NOOP = _NoopSpan()
_current: contextvars.ContextVar = contextvars.ContextVar(
    "mxnet_tpu_current_span", default=None)


class Tracer:
    """Process-wide span sink: a bounded ring plus optional journal
    streaming.  ``mode`` resolves from ``MXNET_TPU_TRACE`` at
    construction; :func:`configure` replaces the tracer (tests, drivers
    that flip tracing on mid-process)."""

    def __init__(self, mode=None, ring=None):
        if mode is None:
            raw = os.environ.get("MXNET_TPU_TRACE", "off").strip().lower()
            mode = raw if raw in MODES else "off"
            if raw and raw not in MODES and raw != "off":
                self._bad_mode = raw     # journaled below, once
            else:
                self._bad_mode = None
        else:
            if mode not in MODES:
                raise ValueError(f"trace mode must be one of {MODES}; "
                                 f"got {mode!r}")
            self._bad_mode = None
        if ring is None:
            try:
                ring = int(os.environ.get("MXNET_TPU_TRACE_RING",
                                          DEFAULT_RING))
            except ValueError:
                ring = DEFAULT_RING
        self.mode = mode
        self.ring_size = max(int(ring), 1)
        self.epoch = time.perf_counter()    # span timeline origin
        self._ring: deque = deque(maxlen=self.ring_size)
        self._lock = threading.Lock()
        self.recorded = 0
        self.dropped = 0
        # one clock-alignment anchor per journal-mode tracer: written by
        # journal_startup() (after the tracer lock releases, like the
        # bad-mode note) so the aggregator can map this process's
        # perf_counter span timeline onto the shared wall clock
        self._anchor_pending = mode == "journal"

    def journal_startup(self) -> None:
        """Journal the once-per-tracer startup records — a rejected
        ``MXNET_TPU_TRACE`` value and, in journal mode, the
        ``trace_anchor`` clock-alignment record.  A separate step (not
        ``__init__``) because construction happens under
        ``_tracer_lock`` and the journal is file I/O no lock may hold
        across (G15); get_tracer/configure call this after release."""
        with self._lock:     # claim-once: two first-users must not
            bad = self._bad_mode          # both journal the same note
            self._bad_mode = None
            anchor = self._anchor_pending
            self._anchor_pending = False
        if bad is not None:
            from ..diagnostics.journal import get_journal
            get_journal().event(
                "trace_bad_mode", value=bad,
                detail=f"MXNET_TPU_TRACE={bad!r} not in "
                       f"{MODES}; tracing stays off")
        if anchor:
            self.journal_anchor()

    def journal_anchor(self) -> dict:
        """Write this process's clock-alignment anchor: one wall-clock /
        perf_counter sample pair plus the tracer epoch and the pod
        identity block.  The aggregator computes ``wall = wall_s -
        perf_s + epoch_s + span.start_s`` from it — intra-process span
        precision stays monotonic, only ONE wall sample is trusted per
        process (the G11 discipline applied across processes)."""
        from ..diagnostics.journal import get_journal
        return get_journal().event("trace_anchor", **anchor_doc(self))

    def _record(self, sp: Span) -> None:
        d = sp.to_dict()
        with self._lock:
            if len(self._ring) == self.ring_size:
                self.dropped += 1
                dropped = self.dropped
            else:
                dropped = None
            self._ring.append(d)
            self.recorded += 1
        if dropped is not None:
            self._note_drop(dropped)
        if self.mode == "journal":
            from ..diagnostics.journal import get_journal
            get_journal().event("span", **d)

    def _note_drop(self, dropped: int) -> None:
        """Ring-overflow accounting (outside the ring lock): bump the
        ``mxnet_tpu_trace_ring_drops_total`` metric family, and journal
        a marker on the first drop (then every 1000th) so silent span
        loss under load is visible in ``doctor --trace`` without a
        per-drop journal write."""
        try:
            from .metrics import default_registry
            default_registry().counter(
                DROPS_METRIC,
                "spans dropped from the bounded trace ring "
                "(raise MXNET_TPU_TRACE_RING)").inc()
        except Exception:
            pass                     # accounting must never kill tracing
        if dropped == 1 or dropped % 1000 == 0:
            from ..diagnostics.journal import get_journal
            get_journal().event("trace_ring_drops", dropped=dropped,
                                ring_size=self.ring_size)

    def spans(self) -> list:
        """Snapshot of the ring (oldest first), as plain dicts."""
        with self._lock:
            return list(self._ring)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    def stats(self) -> dict:
        with self._lock:
            return {"mode": self.mode, "ring_size": self.ring_size,
                    "in_ring": len(self._ring),
                    "recorded": self.recorded, "dropped": self.dropped}


_tracer_lock = threading.Lock()
_tracer: Tracer | None = None


def get_tracer() -> Tracer:
    global _tracer
    # lock-free fast path: span() runs on every instrumented hot-path
    # call, and a populated module global is safe to read un-locked
    t = _tracer
    if t is not None:
        return t
    with _tracer_lock:
        if _tracer is None:
            _tracer = Tracer()
        t = _tracer
    t.journal_startup()             # journal I/O: after the lock
    return t


def configure(mode=None, ring=None) -> Tracer:
    """Replace the process tracer (explicit mode beats the env knob).
    Returns the new tracer."""
    global _tracer
    with _tracer_lock:
        _tracer = Tracer(mode=mode, ring=ring)
        t = _tracer
    t.journal_startup()             # journal I/O: after the lock
    return t


def reset_tracer() -> Tracer:
    """Re-resolve from the environment (tests)."""
    return configure(mode=None, ring=None)


def mode() -> str:
    return get_tracer().mode


def enabled() -> bool:
    return get_tracer().mode != "off"


# -- span creation ----------------------------------------------------------

def _parent_of(parent):
    """(trace_id, parent_span_id) for a new span: explicit parent
    (Span/SpanContext) wins, else the context-local current span, else a
    fresh trace root."""
    if parent is None:
        parent = _current.get()
    if parent is None or parent is _NOOP:
        return f"{_PROC_TOKEN}{next(_ids):06x}", None
    return parent.trace_id, parent.span_id


def _new_span(name, parent, attrs, t0=None):
    """The ONE creation preamble every span flavor shares: off-mode
    fast path, parent resolution, Span construction."""
    if get_tracer().mode == "off":
        return _NOOP
    trace_id, parent_id = _parent_of(parent)
    return Span(name, trace_id, parent_id, attrs, t0=t0)


def span(name, parent=None, **attrs):
    """Open a traced scope::

        with trace.span("step", step=t) as sp:
            ...

    ``parent`` re-parents explicitly (a Span or SpanContext captured on
    another thread); default is the calling context's current span.
    Disabled tracing returns the shared no-op — near-zero cost, and by
    contract no host reads (pass only host scalars as attrs)."""
    return _new_span(name, parent, attrs)


def start_span(name, parent=None, **attrs):
    """Manually-managed span for lifecycles that cross threads (the
    serving request: opened at submit, ended by the worker).  Same
    creation semantics as :func:`span`, but only entered as the
    context-local current span if used as a context manager; close it
    with ``sp.end(**attrs)``."""
    return _new_span(name, parent, attrs)


def record(name, parent=None, t0=None, t1=None, **attrs):
    """Emit a completed span with explicit perf_counter endpoints — for
    work measured once but attributed to several traces (the serving
    batch's execution window, recorded under each request's root)."""
    return _new_span(name, parent, attrs, t0=t0).end(_t1=t1)


def event(name, parent=None, **attrs):
    """Zero-duration instant span (a point annotation on the timeline —
    the pallas dispatch decision, a reload)."""
    sp = _new_span(name, parent, attrs)
    return sp.end(_t1=sp.t0) if sp is not _NOOP else sp


def current_span():
    sp = _current.get()
    return sp if sp is not None else None


def current_context() -> SpanContext | None:
    """Capture token for cross-thread propagation (None outside any
    span or with tracing off)."""
    sp = _current.get()
    return sp.context() if sp is not None else None


def adopt_trace(sp, trace_id) -> bool:
    """Re-stamp an OPEN span onto another process's trace — the elastic
    recovery join: every survivor opens its own ``elastic_recover``
    span, the leader publishes its trace id through the epoch ledger,
    and survivors adopt it so the whole pod's recovery records share
    ONE trace (docs/elastic.md).  Only the span's trace lineage changes;
    child spans and journal records created AFTER adoption inherit the
    adopted id (``current_ids`` reads the live span).  No-op (False) on
    the disabled no-op span, a closed span, or a null/identical id."""
    if not trace_id or sp is None or sp is _NOOP:
        return False
    if getattr(sp, "_ended", True) or sp.trace_id == trace_id:
        return False
    sp.trace_id = trace_id
    return True


def annotate(**attrs) -> bool:
    """Attach attrs to the innermost active span, if any (the pallas
    dispatch hook).  No-op (False) when tracing is off or no span is
    open."""
    sp = _current.get()
    if sp is None:
        return False
    sp.set_attrs(**attrs)
    return True


def current_ids() -> dict:
    """``{"trace_id": ..., "span_id": ...}`` of the innermost active
    span, or ``{}`` — the journal correlation hook: every JSONL record
    written inside a span carries these two fields, so the historically
    separate journals (serving, guardrails, checkpoint fallback, pallas)
    correlate against one trace.  With tracing off this is always ``{}``
    and journal records stay bit-identical to the pre-trace schema."""
    sp = _current.get()
    if sp is None:
        return {}
    return {"trace_id": sp.trace_id, "span_id": sp.span_id}


# register the correlation hook: the journal must stay import-light (it
# cannot import this module), so it exposes a provider slot instead
from ..diagnostics import journal as _journal  # noqa: E402

_journal.set_trace_ids_provider(current_ids)

"""Metrics registry — counters, gauges, and histogram summaries with
labeled families and a process-wide default registry.

The histogram backend is :class:`LatencySummary` (moved here from
``metric.py``, which re-exports it for compatibility): a bounded
reservoir keeps p50/p95/p99 over an unbounded stream in fixed memory,
with exact count/mean/min/max.  Counters and gauges are plain locked
floats — always-on-cheap by design (host arithmetic only, never a
device read), so the compile counters and step-phase summaries feed
``bench.py``'s artifact even with span tracing off.

Exposition: :meth:`MetricsRegistry.prometheus_text` renders the
Prometheus text format (``Server.metrics_text()`` and the ``/metrics``
endpoint serve it); :meth:`MetricsRegistry.snapshot` is the JSON-able
dict ``bench.py`` embeds in BENCH artifacts and ``doctor --metrics``
reads back.

Stdlib-only (no jax, no numpy): importable from a wedged environment,
the same contract as diagnostics/resilience.
"""
from __future__ import annotations

import math
import random as _random
import re
import threading

__all__ = ["Counter", "Gauge", "LatencySummary", "MetricsRegistry",
           "Summary", "default_registry", "prometheus_text",
           "reset_metrics", "snapshot"]

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")


def _err(msg):
    """MXNetError when the runtime package is importable, ValueError
    otherwise — this module must not hard-depend on the package root."""
    try:
        from ..base import MXNetError
        return MXNetError(msg)
    except Exception:
        return ValueError(msg)


class LatencySummary:
    """Streaming latency summary over a bounded reservoir.

    One helper for every site that needs count/mean/p50/p95/p99 over an
    unbounded stream of observations in bounded memory — the serving
    batcher, the ``python -m mxnet_tpu.serving bench`` load generator,
    the metrics registry's :class:`Summary` children, and tests.
    Vitter's algorithm R keeps a uniform sample of the whole stream in
    ``reservoir_size`` slots, so a long soak neither grows memory nor
    forgets its early tail; count/mean/min/max are exact.

    Thread-safe (one lock per observe/snapshot): load-generator clients
    observe from many threads.  Percentiles use the nearest-rank method
    over the sorted reservoir.  The sampling RNG is seeded
    deterministically per instance so tests see reproducible summaries;
    pass ``rng=random.Random()`` for independent streams.
    """

    def __init__(self, name="latency_ms", reservoir_size=2048, rng=None):
        if reservoir_size < 1:
            raise _err("LatencySummary needs reservoir_size >= 1")
        self.name = str(name)
        self._cap = int(reservoir_size)
        self._rng = rng if rng is not None else _random.Random(0xC0FFEE)
        self._lock = threading.Lock()
        self.reset()

    def reset(self):
        with self._lock:
            self._buf = []
            self._count = 0
            self._sum = 0.0
            self._min = None
            self._max = None

    def observe(self, value):
        """Record one observation (any real number, e.g. latency in ms)."""
        v = float(value)
        with self._lock:
            self._count += 1
            self._sum += v
            self._min = v if self._min is None else min(self._min, v)
            self._max = v if self._max is None else max(self._max, v)
            if len(self._buf) < self._cap:
                self._buf.append(v)
            else:
                # algorithm R: keep each of the n seen so far with p=cap/n
                j = self._rng.randrange(self._count)
                if j < self._cap:
                    self._buf[j] = v

    @property
    def count(self):
        return self._count

    @property
    def sum(self):
        return self._sum

    def percentile(self, p):
        """Nearest-rank percentile over the reservoir; None when empty."""
        with self._lock:
            buf = sorted(self._buf)
        if not buf:
            return None
        rank = max(int(math.ceil((float(p) / 100.0) * len(buf))) - 1, 0)
        return buf[min(rank, len(buf) - 1)]

    def summary(self):
        """One dict: count/mean/min/max + p50/p95/p99 (values rounded to
        3 decimals; all None when nothing was observed)."""
        with self._lock:
            buf = sorted(self._buf)
            count, total = self._count, self._sum
            lo, hi = self._min, self._max
        if not count:
            return {"count": 0, "mean": None, "sum": 0.0, "min": None,
                    "max": None, "p50": None, "p95": None, "p99": None}

        def rank(p):
            r = max(int(math.ceil((p / 100.0) * len(buf))) - 1, 0)
            return round(buf[min(r, len(buf) - 1)], 3)

        return {"count": count, "mean": round(total / count, 3),
                "sum": round(total, 3),
                "min": round(lo, 3), "max": round(hi, 3),
                "p50": rank(50), "p95": rank(95), "p99": rank(99)}

    def get(self):
        """EvalMetric-flavored accessor: (name, mean)."""
        return self.name, (self._sum / self._count if self._count else None)


# -- family children ---------------------------------------------------------

class Counter:
    """Monotonic count.  ``set(v)`` exists for mirroring an externally-
    tracked monotonic total (the serving server's counters dict) into
    the exposition — it refuses to go backwards."""

    __slots__ = ("_value", "_lock")

    def __init__(self):
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, amount=1.0):
        if amount < 0:
            raise _err("Counter.inc() amount must be >= 0")
        with self._lock:
            self._value += amount

    def set(self, value):
        value = float(value)
        with self._lock:
            if value < self._value:
                raise _err(f"Counter.set({value}) would move a monotonic "
                           f"counter backwards (at {self._value})")
            self._value = value

    @property
    def value(self):
        with self._lock:
            return self._value


class Gauge:
    """Point-in-time value."""

    __slots__ = ("_value", "_lock")

    def __init__(self):
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, value):
        with self._lock:
            self._value = float(value)

    def inc(self, amount=1.0):
        with self._lock:
            self._value += amount

    def dec(self, amount=1.0):
        with self._lock:
            self._value -= amount

    @property
    def value(self):
        with self._lock:
            return self._value


class Summary:
    """Histogram summary child — a thin veneer over LatencySummary."""

    __slots__ = ("_ls",)

    def __init__(self, reservoir_size=2048):
        self._ls = LatencySummary(reservoir_size=reservoir_size)

    def observe(self, value):
        self._ls.observe(value)

    @property
    def count(self):
        return self._ls.count

    @property
    def sum(self):
        return self._ls.sum

    def percentile(self, p):
        return self._ls.percentile(p)

    def summary(self):
        return self._ls.summary()


_KINDS = {"counter": Counter, "gauge": Gauge, "summary": Summary}


class _Family:
    """One named metric family: fixed label names, children per label
    values.  ``family.labels(phase="data_wait").observe(...)``; a
    label-less family proxies child methods directly."""

    def __init__(self, name, kind, help="", labelnames=()):
        self.name = name
        self.kind = kind
        self.help = help
        self.labelnames = tuple(labelnames)
        for ln in self.labelnames:
            if not _LABEL_RE.match(ln):
                raise _err(f"invalid label name {ln!r} for metric {name!r}")
        self._children: dict = {}
        self._lock = threading.Lock()

    def labels(self, **labelvalues):
        if set(labelvalues) != set(self.labelnames):
            raise _err(f"metric {self.name!r} takes labels "
                       f"{self.labelnames}, got {tuple(labelvalues)}")
        key = tuple(str(labelvalues[ln]) for ln in self.labelnames)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = _KINDS[self.kind]()
                self._children[key] = child
            return child

    def _default_child(self):
        if self.labelnames:
            raise _err(f"metric {self.name!r} is labeled "
                       f"{self.labelnames}: call .labels(...) first")
        return self.labels()

    # label-less convenience: family.inc() / .set() / .observe()
    def inc(self, amount=1.0):
        self._default_child().inc(amount)

    def set(self, value):
        self._default_child().set(value)

    def dec(self, amount=1.0):
        self._default_child().dec(amount)

    def observe(self, value):
        self._default_child().observe(value)

    def children(self) -> dict:
        with self._lock:
            return dict(self._children)


class MetricsRegistry:
    """Named families, one per metric; getters are idempotent (the same
    (name, kind) returns the existing family; a kind or label mismatch
    is a structural error, not a silent second family)."""

    def __init__(self):
        self._families: dict = {}
        self._lock = threading.Lock()

    def _family(self, name, kind, help, labelnames):
        if not _NAME_RE.match(name):
            raise _err(f"invalid metric name {name!r}")
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if fam.kind != kind:
                    raise _err(f"metric {name!r} already registered as "
                               f"{fam.kind}, not {kind}")
                if labelnames and tuple(labelnames) != fam.labelnames:
                    raise _err(f"metric {name!r} already registered with "
                               f"labels {fam.labelnames}, not "
                               f"{tuple(labelnames)}")
                return fam
            fam = _Family(name, kind, help, labelnames)
            self._families[name] = fam
            return fam

    def counter(self, name, help="", labelnames=()):
        return self._family(name, "counter", help, labelnames)

    def gauge(self, name, help="", labelnames=()):
        return self._family(name, "gauge", help, labelnames)

    def summary(self, name, help="", labelnames=()):
        return self._family(name, "summary", help, labelnames)

    def families(self) -> dict:
        with self._lock:
            return dict(sorted(self._families.items()))

    # -- read-out -------------------------------------------------------------
    def snapshot(self) -> dict:
        """JSON-able state of every family: scalar values for counters/
        gauges, the LatencySummary dict for summaries.  Label values key
        a nested dict as ``"k=v,k2=v2"`` (or ``""`` for label-less)."""
        out = {}
        for name, fam in self.families().items():
            values = {}
            for key, child in sorted(fam.children().items()):
                label_key = ",".join(f"{ln}={lv}" for ln, lv
                                     in zip(fam.labelnames, key))
                if fam.kind == "summary":
                    values[label_key] = child.summary()
                else:
                    values[label_key] = child.value
            out[name] = {"type": fam.kind, "help": fam.help,
                         "values": values}
        return out

    def prometheus_text(self) -> str:
        """Prometheus text exposition format, version 0.0.4."""
        lines = []
        for name, fam in self.families().items():
            if fam.help:
                lines.append(f"# HELP {name} {_esc_help(fam.help)}")
            lines.append(f"# TYPE {name} {fam.kind}")
            for key, child in sorted(fam.children().items()):
                pairs = list(zip(fam.labelnames, key))
                if fam.kind == "summary":
                    for q, p in (("0.5", 50), ("0.95", 95), ("0.99", 99)):
                        v = child.percentile(p)
                        if v is None:
                            v = float("nan")
                        lines.append(f"{name}"
                                     f"{_labels(pairs + [('quantile', q)])}"
                                     f" {_num(v)}")
                    lines.append(f"{name}_sum{_labels(pairs)} "
                                 f"{_num(child.sum)}")
                    lines.append(f"{name}_count{_labels(pairs)} "
                                 f"{_num(child.count)}")
                else:
                    lines.append(f"{name}{_labels(pairs)} "
                                 f"{_num(child.value)}")
        return "\n".join(lines) + ("\n" if lines else "")


def _esc_help(s: str) -> str:
    return s.replace("\\", "\\\\").replace("\n", "\\n")


def _esc_label(s: str) -> str:
    return (s.replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _labels(pairs) -> str:
    if not pairs:
        return ""
    inner = ",".join(f'{k}="{_esc_label(str(v))}"' for k, v in pairs)
    return "{" + inner + "}"


def _num(v) -> str:
    f = float(v)
    if math.isnan(f):
        return "NaN"
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


_default_lock = threading.Lock()
_default: MetricsRegistry | None = None


def default_registry() -> MetricsRegistry:
    global _default
    # lock-free fast path (the step-phase observers call this per phase)
    reg = _default
    if reg is not None:
        return reg
    with _default_lock:
        if _default is None:
            _default = MetricsRegistry()
        return _default


def reset_metrics() -> MetricsRegistry:
    """Fresh default registry (tests)."""
    global _default
    with _default_lock:
        _default = MetricsRegistry()
        return _default


def prometheus_text() -> str:
    return default_registry().prometheus_text()


def snapshot() -> dict:
    return default_registry().snapshot()

"""Pod-scope trace assembly — merge per-process files into one story.

A pod run leaves a shared-FS run directory of per-process evidence
(``MXNET_TPU_TRACE_DIR``; the replica pool wires it for its workers):

- ``journal-*.jsonl`` / ``*.jsonl`` — one diagnostics journal PER
  process, carrying ``kind="span"`` records (``MXNET_TPU_TRACE=
  journal``), the ``trace_anchor`` clock-alignment record, and every
  correlated journal record;
- ``flight-*.json`` — crash flight-recorder dumps
  (observability/flight.py): the bounded span/journal rings of a
  process that was SIGKILLed, wedged, or exited, each with its own
  anchor.

This module folds them into ONE timeline:

- **clock alignment** — every process's spans sit on a monotonic
  ``perf_counter`` timeline whose zero is arbitrary; the anchor record
  pairs one wall-clock sample with one perf_counter sample, so
  ``wall = anchor.wall_s - anchor.perf_s + epoch_s + span.start_s``
  places all processes on one shared wall clock while keeping each
  process's INTRA-process precision purely monotonic (one trusted wall
  sample per process — the G11 no-wall-durations discipline, applied
  across processes).  A journal without an anchor falls back to each
  span record's own write-time ``ts`` minus its duration (coarser:
  per-record wall sampling);
- **merged Perfetto trace** (:func:`aggregate_chrome`) — one pid per
  PROCESS (never per rank: two replicas on one host share a rank) with
  ``process_name`` metadata, ``tid`` = thread;
- **cross-process critical path** (:func:`critical_path` /
  :func:`timeline_report`, surfaced as ``doctor --timeline``) — for one
  trace id (default: the slowest routed request), the ordered
  router-attempt → wire → dequeue/execute → respond chain with
  per-step wall offsets and the inter-step gaps (the wire/queue time
  no single process's profile can see).

Stdlib-only, journal-reader tolerant (torn tails of killed writers are
skipped, the PR-7 contract) — assembly must work on wreckage.
"""
from __future__ import annotations

import json
import os
import re

from . import export as _export

_PREV_RE = re.compile(r"\.prev-\d+$")

__all__ = ["ProcessTrace", "aggregate_chrome", "critical_path",
           "scan_run_dir", "timeline_report"]

# span names in priority order for picking the "interesting" trace when
# the caller doesn't name one: a routed request beats a bare serving one
_ROOT_PREFERENCE = ("router_request", "serving_request", "elastic_recover")


class ProcessTrace:
    """One process's assembled evidence: spans (journal ∪ flight,
    deduped), the newest clock anchor, journal records, and provenance
    (which files fed it, whether a flight dump is present)."""

    __slots__ = ("label", "sources", "spans", "anchor", "records",
                 "flight", "identity")

    def __init__(self, label):
        self.label = label
        self.sources = []
        self.spans = []          # span dicts (journal schema)
        self.anchor = None       # newest anchor doc
        self.records = []        # non-span journal records
        self.flight = None       # flight dump doc (reason etc.)
        self.identity = {}       # rank/replica/pid/run_id

    # -- clock alignment -------------------------------------------------
    def span_wall_start(self, d):
        """Wall-clock start of one span dict: the ``_wall`` the scanner
        pinned from the span's OWN incarnation's anchor (a respawned
        worker appends a second incarnation — second anchor, new
        monotonic epoch — to the same journal file, so per-span anchor
        association matters), else this process's newest anchor, else
        the record's own write-time ts minus duration."""
        if d.get("_wall") is not None:
            return float(d["_wall"])
        off = _anchor_offset(self.anchor)
        if off is not None and d.get("start_s") is not None:
            return off + float(d["start_s"])
        ts = d.get("ts")            # journal write time (= span end)
        if ts is None:
            return None
        return float(ts) - float(d.get("dur_s") or 0.0)

    def dedupe(self):
        # (trace_id, span_id, incarnation): span counters restart per
        # process incarnation, and a trace id minted ELSEWHERE (the
        # router's, propagated over the wire) can reach two
        # incarnations of one replica — e.g. a retry of the same
        # request after a respawn — so the pair alone can collide
        # across incarnations.  The anchor epoch pinned at scan time
        # disambiguates them, while periodic-flight + journal
        # duplicates of the SAME span (same incarnation, same epoch)
        # still collapse.
        seen = set()
        out = []
        for d in self.spans:
            key = (d.get("trace_id"), d.get("span_id"), d.get("_inc"))
            if key in seen:
                continue
            seen.add(key)
            out.append(d)
        self.spans = out
        # journal records have no ids; a flight dump's journal_tail is
        # the last-N of the records already scanned from the journal
        # file (the common both-files case), so collapse by content or
        # every report count inflates by the duplicated tail
        seen_r = set()
        recs = []
        for r in self.records:
            key = json.dumps(r, sort_keys=True, default=str)
            if key in seen_r:
                continue
            seen_r.add(key)
            recs.append(r)
        self.records = recs


def _anchor_offset(anchor):
    """``wall_s - perf_s + epoch_s`` — add ``span.start_s`` for the
    span's wall start.  None for a missing/malformed anchor."""
    if not anchor:
        return None
    try:
        return (float(anchor["wall_s"]) - float(anchor["perf_s"])
                + float(anchor["epoch_s"]))
    except (KeyError, TypeError, ValueError):
        return None


def _pin_wall(span, anchor) -> dict:
    """Stamp ``_wall`` (and the incarnation tag ``_inc`` dedupe keys
    on) on a span from ITS incarnation's anchor (the anchor in effect
    where the span was read).  Internal keys never reach the chrome
    output — ``_chrome_event`` builds its args explicitly."""
    off = _anchor_offset(anchor)
    if off is None:
        return span
    span = dict(span)
    span["_inc"] = anchor.get("epoch_s")
    if span.get("start_s") is not None:
        span["_wall"] = off + float(span["start_s"])
    return span


def _scan_jsonl(path, proc):
    """Fold one journal file into ``proc`` (torn/junk lines skipped).
    Anchor association is positional: a span aligns with the newest
    anchor ABOVE it in the file — its own incarnation's."""
    current_anchor = None
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if not isinstance(rec, dict):
                continue
            kind = rec.get("kind")
            if kind == "span":
                proc.spans.append(_pin_wall(rec, current_anchor))
            elif kind == "trace_anchor":
                current_anchor = rec
                proc.anchor = rec       # newest wins (the fallback)
                for k in ("rank", "replica", "pid", "run_id"):
                    if rec.get(k) is not None:
                        proc.identity[k] = rec[k]
            else:
                proc.records.append(rec)


def _fold_flight(doc, proc):
    anchor = doc.get("anchor") if isinstance(doc.get("anchor"), dict) \
        else None
    if proc.flight is None:     # the CURRENT dump sorts first; rotated
        proc.flight = {"reason": doc.get("reason"),    # .prev-N dumps
                       "seq": doc.get("seq"),          # only add spans
                       "last_phase": doc.get("last_phase"),
                       "trace": doc.get("trace")}
    if anchor is not None and proc.anchor is None:
        proc.anchor = anchor
    for k in ("rank", "replica", "pid", "run_id"):
        if doc.get(k) is not None:
            proc.identity.setdefault(k, doc[k])
    proc.spans.extend(_pin_wall(d, anchor)
                      for d in doc.get("spans") or []
                      if isinstance(d, dict))
    proc.records.extend(r for r in doc.get("journal_tail") or []
                        if isinstance(r, dict) and r.get("kind") != "span")
    # spans that only survived in the journal_tail ring (trace mode
    # journal + a dump between writes) still join the timeline
    proc.spans.extend(_pin_wall(r, anchor)
                      for r in doc.get("journal_tail") or []
                      if isinstance(r, dict) and r.get("kind") == "span")


def _proc_label(stem, proc):
    ident = proc.identity
    if ident.get("replica") is not None:
        return f"replica {ident['replica']}"
    if ident.get("rank") is not None and ident.get("pid") is not None:
        return f"rank {ident['rank']} (pid {ident['pid']})"
    return stem


def scan_run_dir(run_dir) -> list:
    """Assemble one :class:`ProcessTrace` per process from a run
    directory.  A journal file IS a process; a ``flight-<label>.json``
    merges into the journal of the same label when one exists
    (``journal-<label>.jsonl``), else stands alone — the SIGKILLed
    worker whose journal went down with it.  Raises OSError when the
    directory itself is unreadable."""
    names = sorted(os.listdir(run_dir))
    procs: dict = {}

    def get(stem):
        p = procs.get(stem)
        if p is None:
            p = procs[stem] = ProcessTrace(stem)
        return p

    for name in names:
        path = os.path.join(run_dir, name)
        if name.endswith(".jsonl"):
            stem = name[:-len(".jsonl")]
            if stem.startswith("journal-"):
                stem = stem[len("journal-"):]
            p = get(stem)
            p.sources.append(name)
            try:
                _scan_jsonl(path, p)
            except OSError:
                continue
        elif name.startswith("flight-") and name.endswith(".json"):
            stem = name[len("flight-"):-len(".json")]
            # rotated previous-incarnation dumps (flight.py install
            # rotation) fold into the same process identity
            stem = _PREV_RE.sub("", stem)
            # the pool names journals by replica id, the recorder by
            # "replica-<id>" — normalize so they merge
            if stem.startswith("replica-"):
                stem = stem[len("replica-"):]
            p = get(stem)
            p.sources.append(name)
            try:
                from .flight import read_flight
                _fold_flight(read_flight(path), p)
            except (OSError, ValueError):
                continue
    _merge_by_identity(procs)
    out = []
    for stem in sorted(procs):
        p = procs[stem]
        if not (p.spans or p.records or p.flight):
            continue                 # an empty shell says nothing
        p.dedupe()
        p.label = _proc_label(stem, p)
        out.append(p)
    return out


def _merge_by_identity(procs: dict) -> None:
    """Fold ProcessTraces that are the SAME process under two filename
    stems: a flight dump whose label doesn't share the journal's stem
    — e.g. ``journal-r0.jsonl`` next to the recorder's default
    ``flight-rank0-pid1234.json`` when ``MXNET_TPU_REPLICA_ID`` is
    unset (the elastic per-rank flow) — would otherwise land on its
    own pid with every flight-flushed span DUPLICATED beside its
    journal copy (dedupe is per-ProcessTrace).  The pod identity block
    both files carry is the join key; pid-less shells stay separate."""
    by_ident: dict = {}
    for stem in sorted(procs):
        p = procs[stem]
        ident = p.identity
        if ident.get("pid") is None:
            continue
        key = (ident.get("run_id"), ident.get("rank"),
               ident.get("replica"), ident["pid"])
        first = by_ident.get(key)
        if first is None:
            by_ident[key] = p
            continue
        first.sources.extend(p.sources)
        first.spans.extend(p.spans)
        first.records.extend(p.records)
        if first.anchor is None:
            first.anchor = p.anchor
        if first.flight is None:
            first.flight = p.flight
        del procs[stem]


def aggregate_chrome(run_dir) -> dict:
    """The merged Perfetto document: every process's spans on one
    anchor-aligned wall timeline, one pid per process (collision-free
    by construction), ``process_name`` metadata naming each track."""
    procs = scan_run_dir(run_dir)
    placed = []                     # (proc, span, wall_start)
    for p in procs:
        for d in p.spans:
            w = p.span_wall_start(d)
            if w is not None:
                placed.append((p, d, w))
    t0 = min((w for _p, _d, w in placed), default=0.0)
    events = []
    for i, p in enumerate(procs):
        events.append(_export._metadata_event(
            i + 1, p.label + (f" [flight:{p.flight['reason']}]"
                              if p.flight else "")))
    pid_of = {id(p): i + 1 for i, p in enumerate(procs)}
    for p, d, w in sorted(placed, key=lambda t: t[2]):
        rebased = dict(d)
        rebased["start_s"] = w - t0
        events.append(_export._chrome_event(rebased, pid_of[id(p)]))
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "metadata": {"run_dir": str(run_dir),
                         "processes": [p.label for p in procs],
                         "wall_t0": round(t0, 6)}}


# -- critical path -----------------------------------------------------------

def _pick_trace(placed):
    """Default trace choice: the slowest instance of the most
    interesting root kind present (routed request > bare serving
    request > elastic recovery)."""
    for root_name in _ROOT_PREFERENCE:
        best = None
        for _p, d, _w in placed:
            if d.get("name") != root_name:
                continue
            dur = float(d.get("dur_s") or 0.0)
            if best is None or dur > best[1]:
                best = (d.get("trace_id"), dur)
        if best is not None:
            return best[0]
    return None


def critical_path(procs, trace_id=None) -> dict:
    """One request's cross-process story: every span of ``trace_id``
    (default: the slowest routed request) ordered on the shared wall
    clock, each step naming its process, with the gap to the previous
    step — the wire/queue time that lives BETWEEN processes."""
    placed = []
    for p in procs:
        for d in p.spans:
            w = p.span_wall_start(d)
            if w is not None:
                placed.append((p, d, w))
    if trace_id is None:
        trace_id = _pick_trace(placed)
    if trace_id is None:
        return {"ok": False, "error": "no spans with a trace id found"}
    mine = sorted(((p, d, w) for p, d, w in placed
                   if d.get("trace_id") == trace_id),
                  key=lambda t: (t[2], t[1].get("span_id") or ""))
    if not mine:
        return {"ok": False, "trace_id": trace_id,
                "error": f"no spans for trace {trace_id!r}"}
    t0 = mine[0][2]
    steps = []
    prev_end = None
    for p, d, w in mine:
        dur_ms = round(float(d.get("dur_s") or 0.0) * 1000.0, 3)
        step = {"name": d.get("name"), "proc": p.label,
                "start_ms": round((w - t0) * 1000.0, 3),
                "dur_ms": dur_ms, "span_id": d.get("span_id"),
                "parent_id": d.get("parent_id")}
        if d.get("attrs"):
            status = d["attrs"].get("status")
            if status is not None:
                step["status"] = status
        if prev_end is not None:
            step["gap_ms"] = round((w - prev_end) * 1000.0, 3)
        this_end = w + dur_ms / 1000.0
        prev_end = this_end if prev_end is None \
            else max(prev_end, this_end)
        steps.append(step)
    end = max(w + float(d.get("dur_s") or 0.0) for _p, d, w in mine)
    return {"ok": True, "trace_id": trace_id, "steps": steps,
            "wall_ms": round((end - t0) * 1000.0, 3),
            "processes": sorted({p.label for p, _d, _w in mine})}


def timeline_report(run_dir, trace_id=None) -> dict:
    """``doctor --timeline`` body: per-process assembly facts (span
    counts, anchor presence, flight-dump reason) plus the critical path
    of one trace.  Same contract as every report surface: no jax, junk
    tolerated, always a dict with ``ok``."""
    try:
        procs = scan_run_dir(run_dir)
    except OSError as e:
        return {"ok": False, "path": str(run_dir),
                "error": f"cannot read {run_dir}: {e.strerror or e}"}
    if not procs:
        return {"ok": False, "path": str(run_dir),
                "error": "no journals or flight dumps in run dir (was "
                         "MXNET_TPU_TRACE_DIR set for the run?)"}
    proc_rows = []
    for p in procs:
        row = {"proc": p.label, "sources": list(p.sources),
               "spans": len(p.spans), "records": len(p.records),
               "anchored": p.anchor is not None}
        if p.flight:
            row["flight"] = {"reason": p.flight.get("reason"),
                             "last_phase": p.flight.get("last_phase")}
            tr = p.flight.get("trace") or {}
            if tr.get("dropped"):
                row["flight"]["ring_drops"] = tr["dropped"]
        proc_rows.append(row)
    cross = sum(1 for r in proc_rows if r["spans"])
    out = {"ok": True, "path": str(run_dir), "processes": proc_rows,
           "traced_processes": cross,
           "flight_dumps": [r["proc"] for r in proc_rows
                            if "flight" in r]}
    out["critical_path"] = critical_path(procs, trace_id=trace_id)
    return out

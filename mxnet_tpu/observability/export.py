"""Exporters: Chrome trace-event JSON (Perfetto-loadable) and a tiny
stdlib ``/metrics`` HTTP endpoint.

Chrome trace-event format (the subset Perfetto's JSON importer
accepts): one complete event (``"ph": "X"``) per finished span with
microsecond ``ts``/``dur``, ``tid`` = thread name, and the
trace/span/parent IDs under ``args`` so the Perfetto query engine can
reconstruct the tree and join against journal records.

Track identity: ``pid`` is the span's rank UNLESS any span in the
document carries a ``replica`` tag — two replicas on one host share a
rank, and keying pid on rank alone interleaved them into one unreadable
track (the PR-12 pid-collision fix).  With replicas present, each
distinct (rank, replica) process gets its own synthetic pid plus a
``process_name`` metadata event (``"ph": "M"``) naming it, so Perfetto
shows one labeled track group per process.

Sources: the live tracer ring (:func:`to_chrome_trace` /
:func:`export_chrome`) or a diagnostics JSONL journal written with
``MXNET_TPU_TRACE=journal`` (:func:`chrome_trace_from_journal` — the
``python -m mxnet_tpu.observability dump`` CLI), so a killed process's
trace is still recoverable from its journal file.

Stdlib-only.
"""
from __future__ import annotations

import json
import threading

from . import trace as _trace

__all__ = ["chrome_trace_from_journal", "export_chrome", "serve_metrics",
           "spans_to_chrome", "to_chrome_trace"]


def _chrome_event(d: dict, pid: int) -> dict:
    args = dict(d.get("attrs") or {})
    args["trace_id"] = d.get("trace_id")
    args["span_id"] = d.get("span_id")
    if d.get("parent_id"):
        args["parent_id"] = d["parent_id"]
    if d.get("replica") is not None:
        args["replica"] = d["replica"]
    start = float(d.get("start_s") or 0.0)
    dur = d.get("dur_s")
    return {"name": str(d.get("name", "?")),
            "cat": "mxnet_tpu",
            "ph": "X",
            "ts": round(start * 1e6, 3),
            "dur": round(float(dur or 0.0) * 1e6, 3),
            "pid": pid,
            "tid": str(d.get("thread") or "main"),
            "args": args}


def process_key(d: dict) -> tuple:
    """The process identity a span belongs to: (rank, replica).  Rank
    alone is NOT enough — two subprocess replicas on one host both
    read rank 0 (the merged-trace pid collision this keying fixes)."""
    return (int(d.get("rank") or 0), d.get("replica"))


def process_label(key: tuple) -> str:
    rank, replica = key
    if replica is not None:
        return f"replica {replica}"
    return f"rank {rank}"


def assign_pids(keys) -> dict:
    """Stable pid per process key.  Rank-only processes keep
    ``pid == rank`` (the pre-replica documents stay bit-identical);
    replica-tagged processes get synthetic pids above every rank so
    no two processes ever share a track."""
    keys = sorted(keys, key=lambda k: (k[1] is not None, k))
    pids, used = {}, set()
    for key in keys:
        rank, replica = key
        if replica is None and rank not in used:
            pids[key] = rank
            used.add(rank)
    nxt = max(used, default=-1) + 1
    for key in keys:
        if key in pids:
            continue
        pids[key] = nxt
        used.add(nxt)
        nxt += 1
    return pids


def _metadata_event(pid: int, label: str) -> dict:
    return {"name": "process_name", "ph": "M", "pid": pid,
            "args": {"name": label}}


def spans_to_chrome(spans, labels=None) -> dict:
    """Span dicts (``Span.to_dict`` / journal ``span`` records) → a
    Chrome trace-event document (``{"traceEvents": [...]}``).

    ``labels`` (optional ``{process_key: str}``) overrides the track
    names.  Metadata ``process_name`` events are emitted only when the
    document spans more than one process or any span carries a replica
    tag — single-process rank-keyed documents stay exactly the
    pre-PR-12 golden shape."""
    spans = list(spans)
    keys = {process_key(d) for d in spans}
    pids = assign_pids(keys)
    events = []
    if labels or len(keys) > 1 or any(k[1] is not None for k in keys):
        for key in sorted(pids, key=lambda k: pids[k]):
            label = (labels or {}).get(key) or process_label(key)
            events.append(_metadata_event(pids[key], label))
    events.extend(_chrome_event(d, pids[process_key(d)]) for d in spans)
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def to_chrome_trace(tracer=None) -> dict:
    """The live tracer ring as a Chrome trace-event document."""
    tracer = tracer or _trace.get_tracer()
    return spans_to_chrome(tracer.spans())


def export_chrome(path, tracer=None) -> int:
    """Write the ring to ``path`` as Chrome trace JSON (atomically — a
    kill mid-export must not leave a torn half-trace that Perfetto
    rejects); returns the event count."""
    from ..resilience.atomic import atomic_write
    doc = to_chrome_trace(tracer)
    with atomic_write(path, "w") as f:
        json.dump(doc, f)
    return len(doc["traceEvents"])


def chrome_trace_from_journal(path) -> dict:
    """Convert a JSONL journal's ``kind="span"`` records to a Chrome
    trace-event document.  Junk/truncated lines are tolerated (the torn
    tail of a killed writer must not hide the healthy prefix) — the
    scan is report.read_span_records, shared with ``doctor --trace``."""
    from .report import read_span_records
    return spans_to_chrome(read_span_records(path))


# -- /metrics endpoint -------------------------------------------------------

def serve_metrics(render, host="127.0.0.1", port=0):
    """Start a daemon-thread HTTP server exposing ``GET /metrics``
    rendered by ``render()`` (Prometheus text).  Returns the
    ``http.server`` instance — read the bound port from
    ``httpd.server_address[1]`` (``port=0`` picks a free one), stop with
    ``httpd.shutdown()``.  Loopback by default: this is an operator
    scrape target, not a public surface."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):
            if self.path.split("?", 1)[0] not in ("/metrics", "/"):
                self.send_error(404)
                return
            try:
                body = render().encode("utf-8")
            except Exception as e:          # scrape must not kill serving
                self.send_error(500, str(e)[:100])
                return
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):       # no stderr chatter per scrape
            pass

    httpd = ThreadingHTTPServer((host, port), Handler)
    t = threading.Thread(target=httpd.serve_forever,
                         name="mxtpu-metrics-http", daemon=True)
    t.start()
    return httpd

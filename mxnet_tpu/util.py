"""``mx.util`` — misc user-facing utilities (ref: python/mxnet/util.py:
the numpy-semantics switches and decorators the reference exposes here;
the CUDA-specific helpers have no TPU meaning and are omitted)."""
from __future__ import annotations

import functools

from . import numpy_extension as _npx

__all__ = ["is_np_array", "set_np", "reset_np", "use_np", "np_array",
           "getenv", "setenv"]

is_np_array = _npx.is_np_array
set_np = _npx.set_np
reset_np = _npx.reset_np


class np_array:
    """Scoped numpy-semantics activation (ref: util.py np_array) —
    usable as context manager or decorator."""

    def __init__(self, active=True):
        self._active = active
        self._prev = None

    def __enter__(self):
        # save BOTH flags — restoring via set_np() defaults would
        # clobber a caller's set_np(shape=False, array=True) state
        self._prev = dict(_npx._np_mode)
        (_npx.set_np if self._active else _npx.reset_np)()
        return self

    def __exit__(self, *exc):
        _npx.set_np(shape=self._prev["shape"], array=self._prev["array"])

    def __call__(self, fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with np_array(self._active):
                return fn(*args, **kwargs)
        return wrapper


def use_np(fn):
    """Decorator running ``fn`` under numpy semantics (ref: util.py
    use_np; the shape/array split collapses here — one flag). Applied
    to a CLASS, it wraps the methods the reference wraps (__init__,
    forward, hybrid_forward, __call__) and returns the same class, so
    isinstance/subclassing keep working."""
    if isinstance(fn, type):
        for name in ("__init__", "forward", "hybrid_forward",
                     "__call__"):
            meth = fn.__dict__.get(name)
            if callable(meth):
                setattr(fn, name, np_array(True)(meth))
        return fn
    return np_array(True)(fn)


def getenv(name):
    """ref: util.py getenv over MXGetEnv."""
    import os
    return os.environ.get(name)


def setenv(name, value):
    """ref: util.py setenv over MXSetEnv."""
    import os
    if value is None:
        os.environ.pop(name, None)
    else:
        os.environ[name] = str(value)

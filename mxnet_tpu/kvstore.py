"""KVStore — the parameter-synchronization facade.

TPU-native re-design of the reference's key→value store
(ref: include/mxnet/kvstore.h KVStore::Create; src/kvstore/kvstore_local.h,
comm.h CommDevice, kvstore_nccl.h, kvstore_dist.h). Mapping (SURVEY §5.8):

- ``local``/``device``/``nccl``: single-process aggregation. The reference
  reduces gradients across GPU replicas with P2P copies or NCCL rings; here
  replica arrays live on one process and XLA's ``psum`` handles the *sharded*
  fast path (mxnet_tpu.parallel.Trainer runs it inside the jitted step over
  ICI). This facade keeps the push/pull API for script compatibility.
- ``dist_sync``/``dist_device_sync``: multi-host data parallel. The reference
  uses a ZMQ parameter server (ps-lite); the TPU path is
  ``jax.distributed.initialize`` + GSPMD collectives over DCN. Server-side
  optimizer semantics are preserved (``set_optimizer`` installs an updater
  applied at push time — exactly the reference's DataHandleEx flow).
- ``dist_async`` (fully asynchronous PS) has NO TPU analog and raises — the
  documented intentional divergence (SURVEY §2.4 #27).
"""
from __future__ import annotations

import pickle

from . import ndarray as nd
from . import optimizer as opt
from .base import MXNetError

__all__ = ["KVStore", "create"]


def create(name="local"):
    """ref: mx.kv.create(type)."""
    return KVStore(name)


_dist_initialized = False


def _ensure_distributed():
    """Join the multi-host job described by the launcher env
    (tools/launch.py sets MXTPU_COORD_ADDR/NUM_PROC/PROC_ID): the JAX
    coordination service replaces the ps-lite scheduler (SURVEY §5.8).
    No-op in single-process runs."""
    global _dist_initialized
    import os
    if _dist_initialized:
        return
    addr = os.environ.get("MXTPU_COORD_ADDR")
    if not addr:
        return
    import jax
    from .resilience.retry import retry_call

    def _join():
        try:
            jax.distributed.initialize(
                coordinator_address=addr,
                num_processes=int(os.environ["MXTPU_NUM_PROC"]),
                process_id=int(os.environ["MXTPU_PROC_ID"]))
        except RuntimeError as e:
            # ONLY the already-joined double-init is benign (package
            # import joins first; jax words it "should only be called
            # once" / "already initialized" across versions). Connect
            # and deadline failures surface as XlaRuntimeError — also a
            # RuntimeError — and must NOT be mistaken for success:
            # re-raise into the retry loop.
            msg = str(e).lower()
            if "already" in msg or "only be called once" in msg:
                return
            raise

    # the coordinator may still be restarting after a preemption:
    # transient connect failures get a bounded, journaled backoff
    retry_call(_join, retry_on=(OSError, ConnectionError, RuntimeError),
               what="jax.distributed.initialize")
    _dist_initialized = True


class KVStore:
    def __init__(self, kv_type="local"):
        kv_type = kv_type.lower()
        known = ("local", "local_allreduce_cpu", "local_allreduce_device",
                 "device", "nccl", "dist_sync", "dist_device_sync", "dist",
                 "horovod", "p3", "dist_sync_device")
        if kv_type == "dist_async":
            raise MXNetError(
                "kvstore 'dist_async' (asynchronous parameter server) has no "
                "TPU analog: XLA collectives are bulk-synchronous. Use "
                "'dist_sync' (sync data parallel over DCN). This divergence "
                "is documented in SURVEY §2.4 #27.")
        if kv_type not in known:
            raise MXNetError(f"unknown kvstore type {kv_type!r}")
        self._type = kv_type
        if kv_type.startswith("dist"):
            _ensure_distributed()
        self._store = {}
        self._updater = None
        self._optimizer = None
        self._states = {}
        self._compression = None

    @property
    def type(self):
        return self._type

    @property
    def rank(self):
        """Worker rank (ref: KVStore::get_rank). Multi-host: process index."""
        if self._type.startswith("dist"):
            import jax
            return jax.process_index()
        return 0

    @property
    def num_workers(self):
        if self._type.startswith("dist"):
            import jax
            return jax.process_count()
        return 1

    # -- core API ------------------------------------------------------------
    def _norm_keys(self, key):
        single = not isinstance(key, (list, tuple))
        keys = [key] if single else list(key)
        return single, [str(k) for k in keys]

    def _norm_vals(self, value, n):
        from .ndarray.sparse import BaseSparseNDArray
        kinds = (nd.NDArray, BaseSparseNDArray)
        if isinstance(value, kinds):
            return [[value]] * 1 if n == 1 else [[value]]
        if n == 1 and isinstance(value, (list, tuple)) and \
                all(isinstance(v, kinds) for v in value):
            return [list(value)]
        return [v if isinstance(v, (list, tuple)) else [v] for v in value]

    def init(self, key, value):
        """ref: KVStore::Init — register initial weights."""
        single, keys = self._norm_keys(key)
        vals = self._norm_vals(value, len(keys))
        for k, vlist in zip(keys, vals):
            if k in self._store:
                continue
            self._store[k] = vlist[0].copy()

    def push(self, key, value, priority=0):
        """Aggregate gradients into the store; if an optimizer is installed
        the update is applied here (the reference's server-side update)."""
        from .ndarray.sparse import RowSparseNDArray, _RowSparseCT, \
            dedupe_rows
        single, keys = self._norm_keys(key)
        vals = self._norm_vals(value, len(keys))
        for k, vlist in zip(keys, vals):
            if k not in self._store:
                raise MXNetError(f"kvstore: key {k} was not init()ed")
            if any(isinstance(v, RowSparseNDArray) for v in vlist):
                if not all(isinstance(v, RowSparseNDArray) for v in vlist):
                    raise MXNetError(
                        f"kvstore.push key {k}: mixed dense and "
                        f"row_sparse values in one push are not "
                        f"supported — convert with tostype()")
                # row-sparse push: aggregate the devices' touched rows
                # (ref: kvstore_dist.h row_sparse push path)
                import numpy as np
                rows = np.concatenate(
                    [np.asarray(v.indices) for v in vlist])
                data = np.concatenate(
                    [np.asarray(v.data) for v in vlist])
                rs = dedupe_rows(_RowSparseCT(rows, data,
                                              vlist[0].shape))
                if self.num_workers > 1:
                    # cross-host sparse reduce (ref: kvstore_dist.h sparse
                    # push/pull over ps-lite): allgather the touched rows
                    # + values over DCN, then segment-sum duplicates —
                    # only touched rows ride the wire, not the table
                    rs = self._allgather_row_sparse(rs)
                if self._updater is not None:
                    self._updater(k, rs, self._store[k])
                else:
                    # same replace semantics as the dense push: the store
                    # holds the latest pushed value on the touched rows
                    dst = self._store[k]
                    dst._rebind(dst._data.at[np.asarray(rs.indices)].set(
                        np.asarray(rs.data)))
                continue
            agg = vlist[0]
            for v in vlist[1:]:
                agg = agg + v.as_in_context(agg.ctx)
            if self._compression is not None:
                agg = nd.NDArray(
                    self._compression.compress(k, agg._data),
                    ctx=agg.ctx, _skip_device_put=True)
            agg = self._allreduce_dcn(agg)
            if self._updater is not None:
                self._updater(k, agg, self._store[k])
            else:
                self._store[k]._rebind(agg.as_in_context(
                    self._store[k].ctx)._data)

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        """ref: KVStore::Pull — broadcast current values into `out`."""
        if out is None:
            raise MXNetError("kvstore.pull requires out=")
        single, keys = self._norm_keys(key)
        outs = self._norm_vals(out, len(keys))
        for k, olist in zip(keys, outs):
            if k not in self._store:
                raise MXNetError(f"kvstore: key {k} was not init()ed")
            src = self._store[k]
            for o in olist:
                o._rebind(src.as_in_context(o.ctx)._data)

    def pushpull(self, key, value, out=None, priority=0):
        """Fused push+pull (ref: KVStore::PushPull, the 1.6+ API)."""
        self.push(key, value, priority)
        if out is not None:
            self.pull(key, out=out, priority=priority)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Pull ONLY the requested rows (ref: KVStore::PullRowSparse /
        kvstore_dist.h PullRowSparseImpl). With ``row_ids`` given,
        returns RowSparseNDArray(s) of those rows; without, falls back
        to a dense pull."""
        if row_ids is None:
            return self.pull(key, out=out, priority=priority)
        import numpy as np

        from .ndarray.sparse import RowSparseNDArray
        single, keys = self._norm_keys(key)
        if isinstance(row_ids, (list, tuple)) and len(row_ids) == len(keys):
            rid_list = list(row_ids)
        else:
            # one row_ids set broadcast to every key
            rid_list = [row_ids] * len(keys)
        results = []
        for k, rids in zip(keys, rid_list):
            if k not in self._store:
                raise MXNetError(f"kvstore: key {k} was not init()ed")
            rids_np = np.unique(np.asarray(
                rids.asnumpy() if isinstance(rids, nd.NDArray) else rids,
                dtype=np.int64))
            src = self._store[k]
            rows = np.asarray(src._data)[rids_np]
            results.append(RowSparseNDArray(rows, rids_np, src.shape))
        if out is not None:
            raise MXNetError("row_sparse_pull with row_ids returns the "
                             "rows; out= is not supported on this build")
        return results[0] if single else results

    def broadcast(self, key, value, out, priority=0):
        self.init(key, value)
        self.pull(key, out=out, priority=priority)

    # -- optimizer on the store (ref: kv.set_optimizer → server pickle) ------
    def set_optimizer(self, optimizer):
        # the reference pickles the optimizer to ship it to SERVER
        # processes (ref: kvstore.py set_optimizer -> _send_command_to_
        # servers); keep that as a shippability check, but hold the LIVE
        # object: this store's updater runs in-process, so Trainer.step's
        # rescale_grad/learning-rate mutations must reach it (the
        # reference's in-process 'device' mode shares the object the
        # same way)
        pickle.dumps(optimizer)
        self._optimizer = optimizer
        self._updater = opt.get_updater(self._optimizer)

    def _set_updater(self, updater):
        self._updater = updater

    def set_gradient_compression(self, compression_params):
        """ref: kv.set_gradient_compression({'type': '2bit',
        'threshold': t}) — 2-bit quantization + error feedback around the
        cross-worker reduce."""
        from .gradient_compression import GradientCompression
        self._compression = GradientCompression(**compression_params)

    # -- multi-host ----------------------------------------------------------
    def _allreduce_dcn(self, arr):
        """dist_*: sum across worker processes over DCN. Single-process runs
        (including the driver's virtual mesh) are the identity."""
        if not self._type.startswith("dist"):
            return arr
        import jax
        if jax.process_count() == 1:
            return arr
        # cross-process eager all-reduce: route through a tiny pjit'ed psum
        # over the global device mesh (SURVEY §5.8 TPU-native equivalent)
        from .parallel import allreduce_across_processes
        return allreduce_across_processes(arr)

    def _allgather_row_sparse(self, rs):
        """Sparse DCN reduce: every process contributes its (rows, vals),
        padded to the max row count so the allgather is same-shape, then
        the union is dedupe-summed. The dense table never crosses DCN —
        the point of the reference's sparse PS push (kvstore_dist.h)."""
        import jax.numpy as jnp
        import numpy as np
        from jax.experimental import multihost_utils

        from .ndarray.sparse import _RowSparseCT, dedupe_rows
        rows = np.asarray(rs.indices, dtype=np.int64)
        vals = np.asarray(rs.data)
        counts = np.asarray(multihost_utils.process_allgather(
            jnp.asarray([rows.shape[0]], dtype=jnp.int32)))
        m = int(counts.max())
        if m == 0:
            return rs
        rows_p = np.full((m,), -1, np.int64)
        rows_p[:rows.shape[0]] = rows
        vals_p = np.zeros((m,) + vals.shape[1:], vals.dtype)
        vals_p[:rows.shape[0]] = vals
        all_rows = np.asarray(
            multihost_utils.process_allgather(jnp.asarray(rows_p)))
        all_vals = np.asarray(
            multihost_utils.process_allgather(jnp.asarray(vals_p)))
        flat_rows = all_rows.reshape(-1)
        keep = flat_rows >= 0
        return dedupe_rows(_RowSparseCT(
            flat_rows[keep],
            all_vals.reshape((-1,) + vals.shape[1:])[keep], rs.shape))

    def barrier(self):
        """ref: KVStore::Barrier (ps-lite barrier)."""
        nd.waitall()

    # -- checkpointing of optimizer state (ref: kv.save/load_optimizer_states)
    def save_optimizer_states(self, fname, dump_optimizer=False):
        if self._updater is None:
            raise MXNetError("no optimizer installed on this kvstore")
        from .resilience.atomic import atomic_write
        with atomic_write(fname, "wb") as f:
            f.write(self._updater.get_states(dump_optimizer))

    def load_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("no optimizer installed on this kvstore")
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())

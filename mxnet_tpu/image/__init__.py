"""``mx.image`` — image decode & augmentation
(ref: python/mxnet/image/image.py; cv2 backend matches the reference's
src/io/image_aug_default.cc OpenCV augmenters)."""
from __future__ import annotations

import os

import numpy as np

from .. import ndarray as nd
from ..base import MXNetError

__all__ = ["imdecode", "imread", "imresize", "resize_short", "fixed_crop",
           "random_crop", "center_crop", "random_size_crop", "scale_down",
           "color_normalize", "HorizontalFlipAug",
           "CastAug", "ColorNormalizeAug", "ResizeAug", "ForceResizeAug",
           "RandomCropAug", "CenterCropAug", "RandomSizedCropAug",
           "SequentialAug", "RandomOrderAug", "BrightnessJitterAug",
           "ContrastJitterAug", "SaturationJitterAug", "HueJitterAug",
           "ColorJitterAug", "LightingAug", "RandomGrayAug",
           "CreateAugmenter", "Augmenter", "ImageIter"]


def _cv2():
    import cv2
    return cv2


def imdecode(buf, flag=1, to_rgb=True, out=None):
    """ref: image.py imdecode (cv2 path)."""
    cv2 = _cv2()
    img = cv2.imdecode(np.frombuffer(bytes(buf), dtype=np.uint8),
                       cv2.IMREAD_COLOR if flag else cv2.IMREAD_GRAYSCALE)
    if img is None:
        raise MXNetError("imdecode failed")
    if flag and to_rgb:
        img = img[:, :, ::-1]
    if img.ndim == 2:
        img = img[:, :, None]
    arr = nd.array(np.ascontiguousarray(img))
    if out is not None:
        out._rebind(arr._data)
        return out
    return arr


def imread(filename, flag=1, to_rgb=True):
    cv2 = _cv2()
    img = cv2.imread(filename, cv2.IMREAD_COLOR if flag
                     else cv2.IMREAD_GRAYSCALE)
    if img is None:
        raise MXNetError(f"imread failed for {filename}")
    if flag and to_rgb:
        img = img[:, :, ::-1]
    if img.ndim == 2:
        img = img[:, :, None]
    return nd.array(np.ascontiguousarray(img))


def imresize(src, w, h, interp=1):
    cv2 = _cv2()
    arr = src.asnumpy() if isinstance(src, nd.NDArray) else np.asarray(src)
    out = cv2.resize(arr, (w, h), interpolation=interp)
    if out.ndim == 2:
        out = out[:, :, None]
    return nd.array(out)


def resize_short(src, size, interp=1):
    """Resize so the short side equals size (ref: image.py resize_short)."""
    h, w = src.shape[:2]
    if h > w:
        new_w, new_h = size, int(h * size / w)
    else:
        new_w, new_h = int(w * size / h), size
    return imresize(src, new_w, new_h, interp)


def fixed_crop(src, x0, y0, w, h, size=None, interp=1):
    out = nd.array(src.asnumpy()[y0:y0 + h, x0:x0 + w])
    if size is not None and (w, h) != size:
        out = imresize(out, size[0], size[1], interp)
    return out


def random_crop(src, size, interp=1):
    h, w = src.shape[:2]
    new_w, new_h = min(size[0], w), min(size[1], h)
    x0 = np.random.randint(0, w - new_w + 1)
    y0 = np.random.randint(0, h - new_h + 1)
    out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def center_crop(src, size, interp=1):
    h, w = src.shape[:2]
    new_w, new_h = min(size[0], w), min(size[1], h)
    x0 = (w - new_w) // 2
    y0 = (h - new_h) // 2
    out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def scale_down(src_size, size):
    """Scale the crop size down to fit in src (ref: image.py
    scale_down — keeps aspect)."""
    w, h = size
    sw, sh = src_size
    if sh < h:
        w, h = float(w * sh) / h, sh
    if sw < w:
        w, h = sw, float(h * sw) / w
    return int(w), int(h)


def random_size_crop(src, size, area, ratio, interp=1):
    """Random crop with area ∈ area·src_area and aspect ∈ ratio, resized
    to ``size`` (ref: image.py random_size_crop — the inception-style
    training crop)."""
    h, w = src.shape[:2]
    src_area = h * w
    if not isinstance(area, (list, tuple)):
        area = (area, 1.0)
    for _ in range(10):
        target_area = np.random.uniform(*area) * src_area
        log_ratio = (np.log(ratio[0]), np.log(ratio[1]))
        new_ratio = np.exp(np.random.uniform(*log_ratio))
        new_w = int(round(np.sqrt(target_area * new_ratio)))
        new_h = int(round(np.sqrt(target_area / new_ratio)))
        if new_w <= w and new_h <= h:
            x0 = np.random.randint(0, w - new_w + 1)
            y0 = np.random.randint(0, h - new_h + 1)
            out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
            return out, (x0, y0, new_w, new_h)
    return center_crop(src, size, interp)   # fallback (reference behavior)


def color_normalize(src, mean, std=None):
    src = src.astype("float32") if isinstance(src, nd.NDArray) else \
        nd.array(src, dtype="float32")
    out = src - (mean if isinstance(mean, nd.NDArray) else nd.array(mean))
    if std is not None:
        out = out / (std if isinstance(std, nd.NDArray) else nd.array(std))
    return out


class Augmenter:
    """ref: image.py Augmenter."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        import json
        return json.dumps([self.__class__.__name__, self._kwargs])

    def __call__(self, src):
        raise NotImplementedError


class ResizeAug(Augmenter):
    def __init__(self, size, interp=1):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return resize_short(src, self.size, self.interp)


class ForceResizeAug(Augmenter):
    def __init__(self, size, interp=1):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return imresize(src, self.size[0], self.size[1], self.interp)


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=1):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return random_crop(src, self.size, self.interp)[0]


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=1):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return center_crop(src, self.size, self.interp)[0]


class HorizontalFlipAug(Augmenter):
    def __init__(self, p=0.5):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if np.random.rand() < self.p:
            return nd.array(src.asnumpy()[:, ::-1].copy())
        return src


class CastAug(Augmenter):
    def __init__(self, typ="float32"):
        super().__init__(type=typ)
        self.typ = typ

    def __call__(self, src):
        return src.astype(self.typ)


class RandomSizedCropAug(Augmenter):
    """ref: image.py RandomSizedCropAug (inception-style area+ratio
    jittered crop)."""

    def __init__(self, size, area, ratio, interp=1):
        super().__init__(size=size, area=area, ratio=ratio, interp=interp)
        self.size, self.area, self.ratio, self.interp = \
            size, area, ratio, interp

    def __call__(self, src):
        return random_size_crop(src, self.size, self.area, self.ratio,
                                self.interp)[0]


class SequentialAug(Augmenter):
    """ref: image.py SequentialAug — apply a list in order."""

    def __init__(self, ts):
        super().__init__()
        self.ts = list(ts)

    def __call__(self, src):
        for t in self.ts:
            src = t(src)
        return src


class RandomOrderAug(Augmenter):
    """ref: image.py RandomOrderAug — apply a list in random order."""

    def __init__(self, ts):
        super().__init__()
        self.ts = list(ts)

    def __call__(self, src):
        for i in np.random.permutation(len(self.ts)):
            src = self.ts[i](src)
        return src


def _as_f32(src):
    return src.astype("float32") if src.dtype != np.float32 else src


class BrightnessJitterAug(Augmenter):
    """ref: image.py BrightnessJitterAug — scale by U(1−b, 1+b)."""

    def __init__(self, brightness):
        super().__init__(brightness=brightness)
        self.brightness = brightness

    def __call__(self, src):
        alpha = 1.0 + np.random.uniform(-self.brightness, self.brightness)
        return _as_f32(src) * alpha


_GRAY = np.array([0.299, 0.587, 0.114], np.float32)   # ITU-R BT.601


class ContrastJitterAug(Augmenter):
    """ref: image.py ContrastJitterAug — blend with the mean gray."""

    def __init__(self, contrast):
        super().__init__(contrast=contrast)
        self.contrast = contrast

    def __call__(self, src):
        alpha = 1.0 + np.random.uniform(-self.contrast, self.contrast)
        x = _as_f32(src).asnumpy()
        gray = (x * _GRAY).sum(axis=2).mean()
        return nd.array(x * alpha + gray * (1.0 - alpha))


class SaturationJitterAug(Augmenter):
    """ref: image.py SaturationJitterAug — blend with per-pixel gray."""

    def __init__(self, saturation):
        super().__init__(saturation=saturation)
        self.saturation = saturation

    def __call__(self, src):
        alpha = 1.0 + np.random.uniform(-self.saturation, self.saturation)
        x = _as_f32(src).asnumpy()
        gray = (x * _GRAY).sum(axis=2, keepdims=True)
        return nd.array(x * alpha + gray * (1.0 - alpha))


class HueJitterAug(Augmenter):
    """ref: image.py HueJitterAug — rotate hue in YIQ space."""

    def __init__(self, hue):
        super().__init__(hue=hue)
        self.hue = hue
        self._tyiq = np.array([[0.299, 0.587, 0.114],
                               [0.596, -0.274, -0.321],
                               [0.211, -0.523, 0.311]], np.float32)
        self._ityiq = np.array([[1.0, 0.956, 0.621],
                                [1.0, -0.272, -0.647],
                                [1.0, -1.107, 1.705]], np.float32)

    def __call__(self, src):
        alpha = np.random.uniform(-self.hue, self.hue)
        u, w = np.cos(alpha * np.pi), np.sin(alpha * np.pi)
        bt = np.array([[1.0, 0.0, 0.0], [0.0, u, -w], [0.0, w, u]],
                      np.float32)
        t = self._ityiq @ bt @ self._tyiq
        return nd.array(_as_f32(src).asnumpy() @ t.T)


class ColorJitterAug(RandomOrderAug):
    """ref: image.py ColorJitterAug — brightness/contrast/saturation in
    random order."""

    def __init__(self, brightness, contrast, saturation):
        ts = []
        if brightness > 0:
            ts.append(BrightnessJitterAug(brightness))
        if contrast > 0:
            ts.append(ContrastJitterAug(contrast))
        if saturation > 0:
            ts.append(SaturationJitterAug(saturation))
        super().__init__(ts)


class LightingAug(Augmenter):
    """ref: image.py LightingAug — AlexNet-style PCA lighting noise."""

    def __init__(self, alphastd, eigval, eigvec):
        super().__init__(alphastd=alphastd)
        self.alphastd = alphastd
        self.eigval = np.asarray(eigval, np.float32)
        self.eigvec = np.asarray(eigvec, np.float32)

    def __call__(self, src):
        alpha = np.random.normal(0, self.alphastd, size=(3,))
        rgb = (self.eigvec * alpha * self.eigval).sum(axis=1)
        return _as_f32(src) + nd.array(rgb.astype(np.float32))


class RandomGrayAug(Augmenter):
    """ref: image.py RandomGrayAug — grayscale with probability p."""

    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if np.random.rand() < self.p:
            x = _as_f32(src).asnumpy()
            gray = (x * _GRAY).sum(axis=2, keepdims=True)
            return nd.array(np.broadcast_to(gray, x.shape).copy())
        return src


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super().__init__(mean=list(np.ravel(mean)), std=list(np.ravel(std)))
        self.mean = nd.array(mean)
        self.std = nd.array(std) if std is not None else None

    def __call__(self, src):
        return color_normalize(src, self.mean, self.std)


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, hue=0, pca_noise=0,
                    rand_gray=0, inter_method=2):
    """ref: image.py CreateAugmenter — the common aug pipeline factory,
    full parameter parity (crop/resize, mirror, color jitter, PCA
    lighting, random gray, normalize)."""
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_resize:          # implies random crop (reference semantics)
        auglist.append(RandomSizedCropAug(crop_size, (0.08, 1.0),
                                          (3.0 / 4.0, 4.0 / 3.0),
                                          inter_method))
    elif rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if brightness or contrast or saturation:
        auglist.append(ColorJitterAug(brightness, contrast, saturation))
    if hue:
        auglist.append(HueJitterAug(hue))
    if pca_noise > 0:
        eigval = np.array([55.46, 4.794, 1.148])
        eigvec = np.array([[-0.5675, 0.7192, 0.4009],
                           [-0.5808, -0.0045, -0.8140],
                           [-0.5836, -0.6948, 0.4203]])
        auglist.append(LightingAug(pca_noise, eigval, eigvec))
    if rand_gray > 0:
        auglist.append(RandomGrayAug(rand_gray))
    if mean is True:
        mean = np.array([123.68, 116.28, 103.53])
    if std is True:
        std = np.array([58.395, 57.12, 57.375])
    if mean is not None and np.any(np.asarray(mean)):
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


class ImageIter:
    """ref: image.py ImageIter — python-level batching iterator over raw
    image files (an ``imglist`` of [label, path] rows or a ``.lst`` file
    + ``path_root``), running the Augmenter pipeline per image and
    yielding NCHW ``DataBatch``es. The RecordIO-backed fast path is
    ``io.ImageRecordIter``; this is the flexible-file-layout sibling."""

    def __init__(self, batch_size, data_shape, label_width=1,
                 path_imglist=None, path_root="", imglist=None,
                 shuffle=False, aug_list=None, last_batch_handle="pad",
                 data_name="data", label_name="softmax_label", **kwargs):
        from ..io import DataBatch, DataDesc
        if kwargs:
            raise MXNetError(
                f"ImageIter: unsupported arguments {sorted(kwargs)} — "
                "pass augmentations explicitly via aug_list="
                "CreateAugmenter(...)")
        if last_batch_handle not in ("pad", "discard", "roll_over"):
            raise MXNetError(
                f"last_batch_handle must be pad/discard/roll_over, got "
                f"{last_batch_handle!r}")
        if len(data_shape) != 3:
            raise MXNetError("data_shape must be (C, H, W)")
        self._last_batch = last_batch_handle
        self._DataBatch = DataBatch
        self.batch_size = batch_size
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self._shuffle = shuffle
        entries = []
        if imglist is not None:
            for row in imglist:
                label, path = row[:-1], row[-1]
                entries.append((np.array(label, np.float32).ravel(), path))
        elif path_imglist:
            with open(path_imglist) as f:
                for line in f:
                    parts = line.strip().split("\t")
                    if len(parts) < 3:
                        continue
                    # .lst rows: index \t label... \t relpath
                    label = np.array([float(v) for v in parts[1:-1]],
                                     np.float32)
                    entries.append((label, os.path.join(path_root,
                                                        parts[-1])))
        else:
            raise MXNetError("ImageIter needs imglist or path_imglist")
        if not entries:
            raise MXNetError("ImageIter: empty image list")
        self._entries = entries
        self.auglist = aug_list if aug_list is not None else \
            CreateAugmenter(data_shape)
        self.provide_data = [DataDesc(data_name,
                                      (batch_size,) + self.data_shape)]
        self.provide_label = [DataDesc(label_name,
                                       (batch_size, label_width)
                                       if label_width > 1
                                       else (batch_size,))]
        self._leftover = []             # roll_over carry across resets
        self.reset()

    def reset(self):
        order = np.arange(len(self._entries))
        if self._shuffle:
            np.random.shuffle(order)
        # pending indices this epoch; roll_over prepends last epoch's
        # tail. Consumed via a cursor (pop(0) would be O(N^2) per epoch)
        self._pending = self._leftover + order.tolist()
        self._cursor = 0
        self._leftover = []

    def __iter__(self):
        return self

    def _read_one(self, idx):
        label, path = self._entries[idx]
        img = imread(path)
        for aug in self.auglist:
            img = aug(img)
        arr = img.asnumpy() if isinstance(img, nd.NDArray) else \
            np.asarray(img)
        chw = np.transpose(arr.astype(np.float32), (2, 0, 1))
        if chw.shape != self.data_shape:
            raise MXNetError(
                f"augmented image shape {chw.shape} != data_shape "
                f"{self.data_shape} for {path}")
        return chw, label

    def next(self):
        remaining = len(self._pending) - self._cursor
        if remaining <= 0:
            raise StopIteration
        if remaining < self.batch_size:
            if self._last_batch == "discard":
                self._cursor = len(self._pending)
                raise StopIteration
            if self._last_batch == "roll_over":
                # keep the tail for after the next reset()
                self._leftover = self._pending[self._cursor:]
                self._cursor = len(self._pending)
                raise StopIteration
        data = np.zeros((self.batch_size,) + self.data_shape, np.float32)
        labels = np.zeros((self.batch_size, self.label_width), np.float32)
        filled = 0
        while filled < self.batch_size and self._cursor < len(self._pending):
            chw, label = self._read_one(self._pending[self._cursor])
            self._cursor += 1
            data[filled] = chw
            labels[filled, :len(label)] = label[:self.label_width]
            filled += 1
        lab = labels[:, 0] if self.label_width == 1 else labels
        return self._DataBatch(data=[nd.array(data)],
                               label=[nd.array(lab)],
                               pad=self.batch_size - filled)

    def __next__(self):
        return self.next()

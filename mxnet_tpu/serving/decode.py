"""Continuous-batching autoregressive decode engine.

The one-shot batcher (serving/server.py) coalesces *independent*
requests into micro-batches; autoregressive generation breaks its model:
a sequence is hundreds of tiny dependent steps, and batching whole
sequences start-to-finish would make every caller wait for the longest
one.  This engine is the serving tier's second executor, run beside the
one-shot worker:

- a fixed **slot pool** (``MXNET_TPU_DECODE_SLOTS``) of resident
  per-sequence state (the KV-cache analog) admits streams — admission is
  against slots, not traffic, so device memory is bounded by
  configuration;
- **prefill/decode split**: a newly admitted prompt is absorbed in
  padded chunks on a small prefill lattice (powers of two up to
  ``MXNET_TPU_DECODE_PREFILL_CHUNK``), then the stream joins the
  resident step batch;
- **per-step rebatching**: every decode step runs ONE executable over
  the full ``(slots, 1)`` token tensor with an active mask — a stream
  finishing frees its slot for the next queued prompt *between steps*,
  never by restarting the batch.  The step shape snaps onto the
  dedicated decode lattice (:meth:`~.buckets.BucketGrid.for_decode`,
  ``grid_bound() == 1``), never onto the smallest prefill bucket;
- **exact compile accounting**: programs are AOT-lowered
  (``jit(fn).lower(...).compile()``) into an explicit program cache, so
  ``stats()["compiles"]`` counts every XLA build and the zero-mid-run-
  compile guarantee is a checkable number, not a hope;
- **deadlines + cancellation**: per-stream absolute deadlines are
  checked at admission and every step (a mid-decode expiry preempts the
  stream and frees its slot); ``DecodeStream.cancel()`` frees the slot
  at the next step boundary.  Failures are the structured batcher
  errors the pool router already classifies — ``SlotsExhausted`` is
  retryable (another replica may have a free slot), a deadline miss is
  not;
- every step journals ``decode_step`` (occupancy, step latency);
  admissions/finishes/cancels/preempts journal their own records — the
  doctor's ``decode`` section summarizes them (serving/report.py).

With a :class:`~.shardplan.ShardPlan` the resident state and the step
batch are committed to the plan's mesh (replicated — the toy state is
tiny; a model's ``DecodeModel`` impl can shard its own state), so a
decode engine co-exists with tensor-parallel predictors on one fleet.
"""
from __future__ import annotations

import itertools
import queue
import threading
import time
from dataclasses import dataclass, field

import numpy as np

from ..diagnostics.journal import get_journal
from ..metric import LatencySummary
from ..observability import instrument as _obs
from .batcher import (DeadlineExceeded, RequestError, ServerOverloaded,
                      ServerStopped, SlotsExhausted)
from .buckets import BucketGrid
from .server import _env_float, _env_int

__all__ = ["DecodeConfig", "DecodeEngine", "DecodeModel", "DecodeStream",
           "TinyLM"]

_STOP = object()
_engine_seq = itertools.count()


def _pow2_up_to(n):
    out, b = [], 1
    while b < n:
        out.append(b)
        b *= 2
    out.append(int(n))
    return tuple(out)


class DecodeModel:
    """The contract the engine drives — three PURE, jax-traceable
    functions over a slot-resident state pytree (a dict of arrays whose
    leading dim is the slot count).  ``max_len`` bounds per-slot
    positions; admission enforces ``prompt + max_new_tokens <= max_len``.

    ``init_state(slots)``
        The resident pool: {name: array[(slots, ...)]} — the KV-cache
        analog, allocated once and reused across stream generations.
    ``prefill_fn(state, slot, tokens, length, start)``
        Absorb one padded prompt chunk (``tokens[(chunk,)]``, valid
        prefix ``length``) into ``slot`` at absolute offset ``start``;
        ``start == 0`` must RESET the slot (a freed slot's stale state
        can never leak into its next occupant).  Returns the new state.
    ``step_fn(state, tokens, active)``
        One decode step over the whole pool: absorb ``tokens[(slots,
        1)]`` (each stream's previously emitted token) where ``active``,
        and return ``(state, next_tokens[(slots,)])``.
    """

    max_len = 256

    def init_state(self, slots):
        raise NotImplementedError

    def prefill_fn(self, state, slot, tokens, length, start):
        raise NotImplementedError

    def step_fn(self, state, tokens, active):
        raise NotImplementedError


class TinyLM(DecodeModel):
    """Deterministic toy LM — integer hash-chain "attention".

    Next token is a pure function of (running hash, position), both
    updated by exact int32 arithmetic, so the engine's output is
    bit-checkable against :meth:`reference` (a pure-python replay) —
    the decode analog of the Scale block's value-fingerprint trick.
    The ``kv`` buffer records absorbed tokens per slot: a genuinely
    resident per-sequence array that makes slot occupancy (and the
    start==0 reset contract) real rather than notional.
    """

    def __init__(self, vocab=251, max_len=256):
        self.vocab = int(vocab)
        self.max_len = int(max_len)

    def init_state(self, slots):
        return {"pos": np.zeros((slots,), np.int32),
                "acc": np.zeros((slots,), np.int32),
                "kv": np.zeros((slots, self.max_len), np.int32)}

    def prefill_fn(self, state, slot, tokens, length, start):
        import jax
        import jax.numpy as jnp
        V = self.vocab
        fresh = start == 0
        acc0 = jnp.where(fresh, 0, state["acc"][slot])
        row0 = jnp.where(fresh, jnp.zeros_like(state["kv"][slot]),
                         state["kv"][slot])

        def body(i, carry):
            acc, row = carry
            use = i < length
            tok = tokens[i]
            idx = jnp.where(use, start + i, row.shape[0])   # OOB → drop
            row = row.at[idx].set(tok, mode="drop")
            acc = jnp.where(use, (acc * 31 + tok + 1) % V, acc)
            return acc, row

        acc, row = jax.lax.fori_loop(0, tokens.shape[0], body, (acc0, row0))
        return {"pos": state["pos"].at[slot].set(start + length),
                "acc": state["acc"].at[slot].set(acc),
                "kv": state["kv"].at[slot].set(row)}

    def step_fn(self, state, tokens, active):
        import jax.numpy as jnp
        V = self.vocab
        tok = tokens[:, 0]
        acc = jnp.where(active, (state["acc"] * 31 + tok + 1) % V,
                        state["acc"])
        pos = state["pos"]
        slots = tok.shape[0]
        idx = jnp.where(active, pos, state["kv"].shape[1])   # OOB → drop
        kv = state["kv"].at[(jnp.arange(slots), idx)].set(tok, mode="drop")
        pos = jnp.where(active, pos + 1, pos)
        nxt = ((acc * 33 + pos * 7 + 5) % V).astype(jnp.int32)
        return {"pos": pos, "acc": acc, "kv": kv}, nxt

    def reference(self, prompt, n):
        """Pure-python replay of prefill(prompt[:-1]) + n steps — the
        bit-exact oracle for engine tests."""
        V = self.vocab
        acc = pos = 0
        for t in prompt[:-1]:
            acc = (acc * 31 + int(t) + 1) % V
            pos += 1
        out, tok = [], int(prompt[-1])
        for _ in range(n):
            acc = (acc * 31 + tok + 1) % V
            pos += 1
            tok = (acc * 33 + pos * 7 + 5) % V
            out.append(tok)
        return out


@dataclass
class DecodeConfig:
    """Decode-engine knobs (docs/serving.md; ``MXNET_TPU_DECODE_*`` env
    vars set fleet-wide defaults)."""

    slots: int = field(default_factory=lambda: _env_int(
        "MXNET_TPU_DECODE_SLOTS", 8))
    prefill_chunk: int = field(default_factory=lambda: _env_int(
        "MXNET_TPU_DECODE_PREFILL_CHUNK", 32))
    # idle admission window: how long the worker waits for a first
    # stream when NO slot is occupied.  With streams active, admission
    # is non-blocking between steps (waiting would tax every token).
    window_ms: float = field(default_factory=lambda: _env_float(
        "MXNET_TPU_DECODE_WINDOW_MS", 20.0))
    max_queue: int = 64                      # bounded slot-wait queue
    max_new_tokens: int = 64                 # per-stream default cap
    default_deadline_ms: float = 10000.0
    queue_on_busy: bool = True               # False: SlotsExhausted now
    result_timeout_s: float = 60.0

    def summary(self) -> dict:
        return {"slots": self.slots, "prefill_chunk": self.prefill_chunk,
                "window_ms": self.window_ms, "max_queue": self.max_queue,
                "max_new_tokens": self.max_new_tokens,
                "default_deadline_ms": self.default_deadline_ms,
                "queue_on_busy": self.queue_on_busy}


class DecodeStream:
    """Caller-side handle for one admitted stream.

    ``result(timeout_s)`` blocks (bounded) until the stream finishes,
    then returns the generated token list or raises the structured
    error; ``tokens`` snapshots partial progress; ``cancel()`` frees
    the slot at the next step boundary (or drops the stream from the
    queue before admission)."""

    __slots__ = ("prompt", "max_new", "deadline_ts", "enq_t", "tenant",
                 "done", "error", "slot", "pending_tok", "_generated",
                 "_timeout_s", "admit_t", "finish_t", "cancel_evt")

    def __init__(self, prompt, max_new, deadline_s, tenant, timeout_s):
        now = time.monotonic()
        self.prompt = prompt
        self.max_new = max_new
        self.deadline_ts = None if deadline_s is None else now + deadline_s
        self.enq_t = now
        self.tenant = tenant
        self.done = threading.Event()
        self.error = None
        self.slot = None
        self.pending_tok = int(prompt[-1])   # next step's input token
        self._generated = []
        self._timeout_s = timeout_s
        self.admit_t = None
        self.finish_t = None
        self.cancel_evt = threading.Event()

    # -- caller surface --------------------------------------------------
    def cancel(self):
        self.cancel_evt.set()

    def cancelled(self) -> bool:
        return self.cancel_evt.is_set()

    @property
    def tokens(self):
        return list(self._generated)

    def result(self, timeout_s=None):
        timeout_s = self._timeout_s if timeout_s is None else timeout_s
        if not self.done.wait(timeout=timeout_s):
            raise RequestError(
                f"decode stream unresolved within {timeout_s:g}s (engine "
                "stopped or wedged — check the serving journal)")
        if self.error is not None:
            raise self.error
        return list(self._generated)

    # -- engine side -----------------------------------------------------
    def expired(self, now=None) -> bool:
        return self.deadline_ts is not None and \
            (time.monotonic() if now is None else now) > self.deadline_ts

    def late_ms(self, now=None) -> float:
        if self.deadline_ts is None:
            return 0.0
        now = time.monotonic() if now is None else now
        return max(now - self.deadline_ts, 0.0) * 1000.0

    def _finish(self, now=None):
        self.finish_t = time.monotonic() if now is None else now
        self.done.set()

    def _fail(self, exc, now=None):
        self.error = exc
        self._finish(now)


class DecodeEngine:
    """The continuous batcher: one worker thread owns the slot pool and
    the device, callers enqueue prompts into a bounded queue (or bounce
    with :class:`SlotsExhausted` when ``queue_on_busy=False``)."""

    def __init__(self, model, config=None, plan=None):
        self.model = model
        self.config = config or DecodeConfig()
        cfg = self.config
        if cfg.slots < 1:
            raise ValueError(f"DecodeEngine needs slots >= 1, got "
                             f"{cfg.slots}")
        self.plan = plan
        # the two lattices: a dedicated single-cell decode grid for the
        # (slots, 1) step tensor, a pow2 chunk grid for prefill.  The
        # snap invariant is asserted once here, not trusted per step.
        self.grid = BucketGrid.for_decode(cfg.slots)
        assert (self.grid.batch_bucket(cfg.slots),) + \
            self.grid.feature_key((1,)) == (cfg.slots, 1)
        self.prefill_buckets = _pow2_up_to(cfg.prefill_chunk)
        self._id = f"dec{next(_engine_seq)}"
        self._queue = queue.Queue(maxsize=cfg.max_queue)
        self._slots = [None] * cfg.slots     # slot -> DecodeStream
        self._state = None                   # resident model state
        self._programs = {}                  # ("step",)|("prefill", b)
        self._worker = None
        self._stopping = threading.Event()
        self._closed = False
        self._admit_lock = threading.Lock()
        self._lock = threading.Lock()
        self.step_latency = LatencySummary("decode_step_ms")
        self.counters = {"submitted": 0, "admitted": 0, "completed": 0,
                         "cancelled": 0, "preempted": 0, "shed": 0,
                         "rejected": 0, "steps": 0, "compiles": 0,
                         "tokens_out": 0}

    # -- programs (explicit AOT cache: compiles are counted, never
    #    implicit — the zero-mid-run-compile invariant is checkable) ----
    def _spec(self, a):
        import jax
        if self.plan is None:
            return jax.ShapeDtypeStruct(a.shape, a.dtype)
        return jax.ShapeDtypeStruct(a.shape, a.dtype,
                                    sharding=self.plan.replicated())

    def _commit(self, a):
        """Place one host/device array per the plan (identity without
        one) — AOT executables are strict about input placements."""
        if self.plan is None:
            return a
        import jax
        return jax.device_put(a, self.plan.replicated())

    def _ensure_state(self):
        if self._state is None:
            st = self.model.init_state(self.config.slots)
            self._state = {k: self._commit(np.asarray(v))
                           for k, v in st.items()}
        return self._state

    def _state_specs(self):
        return {k: self._spec(v) for k, v in self._ensure_state().items()}

    def _program(self, key):
        prog = self._programs.get(key)
        if prog is not None:
            return prog
        import jax
        i32 = np.dtype(np.int32)
        sspec = self._state_specs()

        def scalar():
            return self._spec(np.zeros((), i32))

        if key[0] == "step":
            fn = jax.jit(self.model.step_fn)
            args = (sspec,
                    self._spec(np.zeros((self.config.slots, 1), i32)),
                    self._spec(np.zeros((self.config.slots,), bool)))
        else:
            fn = jax.jit(self.model.prefill_fn)
            args = (sspec, scalar(),
                    self._spec(np.zeros((key[1],), i32)),
                    scalar(), scalar())
        with _obs.compile_span("decode_program", program=list(key),
                               engine=self._id):
            prog = fn.lower(*args).compile()
        with self._lock:
            self.counters["compiles"] += 1
        self._programs[key] = prog
        return prog

    def warmup(self) -> dict:
        """Build the WHOLE program set (one step executable + one
        prefill executable per chunk bucket) ahead of traffic — after
        this, a compile during decode is a defect, and the tier-0.5
        smoke asserts exactly that.  Returns {programs, compiled, ms}
        and journals ``decode_warmup``."""
        t0 = time.perf_counter()
        before = self.counters["compiles"]
        self._program(("step",))
        for b in self.prefill_buckets:
            self._program(("prefill", b))
        out = {"programs": len(self._programs),
               "compiled": self.counters["compiles"] - before,
               "ms": round((time.perf_counter() - t0) * 1000.0, 2)}
        get_journal().event("decode_warmup", engine=self._id, **out)
        return out

    # -- lifecycle -------------------------------------------------------
    def start(self):
        if self._worker is not None and self._worker.is_alive():
            return self
        self._stopping.clear()
        with self._admit_lock:
            self._closed = False
        self._ensure_state()
        get_journal().event("decode_start", engine=self._id,
                            config=self.config.summary(),
                            grid=repr(self.grid),
                            prefill_buckets=list(self.prefill_buckets))
        self._worker = threading.Thread(
            target=self._run, name="mxtpu-decode-worker", daemon=True)
        self._worker.start()
        return self

    def stop(self, timeout_s=30.0, drain=True):
        """With ``drain``, every admitted stream (active or queued) runs
        to completion before the worker exits; without, all resolve with
        :class:`ServerStopped`.  Admission closes first; bounded join."""
        if self._worker is None:
            return
        with self._admit_lock:
            self._closed = True
        if not drain:
            self._stopping.set()
        try:
            self._queue.put(_STOP, timeout=timeout_s)
        except queue.Full:
            self._stopping.set()
        self._worker.join(timeout=timeout_s)
        stuck = self._worker.is_alive()
        if not stuck:
            leftovers = []
            with self._admit_lock:
                self._drain_queue(leftovers)
            self._fail_streams(leftovers)
        get_journal().event("decode_stop", engine=self._id,
                            drained=bool(drain), stuck=stuck,
                            **self.stats())
        if stuck:
            raise RequestError(
                f"decode worker did not stop within {timeout_s:g}s "
                "(device wedged mid-step? see the journal)")
        self._worker = None

    # -- client surface --------------------------------------------------
    def submit(self, tokens, max_new_tokens=None, deadline_ms=None,
               tenant=None) -> DecodeStream:
        """Admit one prompt (1-D int token sequence).  Raises
        :class:`RequestError` for an empty/oversized prompt (not
        retryable — every replica shares ``max_len``),
        :class:`SlotsExhausted` when ``queue_on_busy=False`` and no
        slot is free (retryable: placement miss),
        :class:`ServerOverloaded` when the slot-wait queue is full, and
        :class:`ServerStopped` after ``stop()``."""
        cfg = self.config
        prompt = [int(t) for t in np.asarray(tokens).reshape(-1)]
        max_new = cfg.max_new_tokens if max_new_tokens is None \
            else int(max_new_tokens)
        with self._lock:
            self.counters["submitted"] += 1
        if not prompt or max_new < 1 or \
                len(prompt) + max_new > self.model.max_len:
            with self._lock:
                self.counters["rejected"] += 1
            err = RequestError(
                f"decode request rejected: prompt={len(prompt)} tokens + "
                f"max_new={max_new} exceeds max_len="
                f"{self.model.max_len} (or is empty) — oversized streams "
                "are rejected, never compiled")
            err.retryable = False
            err.tenant = tenant
            raise err
        deadline_ms = cfg.default_deadline_ms if deadline_ms is None \
            else deadline_ms
        deadline_s = None if deadline_ms is None or deadline_ms <= 0 \
            else deadline_ms / 1000.0
        stream = DecodeStream(prompt, max_new, deadline_s, tenant,
                              cfg.result_timeout_s)
        if not cfg.queue_on_busy:
            free = sum(1 for s in self._slots if s is None)
            queued = self._queue.qsize()
            if free == 0 or queued > 0:
                with self._lock:
                    self.counters["shed"] += 1
                raise SlotsExhausted(cfg.slots, queued=queued,
                                     tenant=tenant)
        try:
            with self._admit_lock:
                stopped = self._closed
                if not stopped:
                    self._queue.put_nowait(stream)
        except queue.Full:
            with self._lock:
                self.counters["shed"] += 1
            get_journal().event("decode_shed", engine=self._id,
                                depth=self._queue.qsize(),
                                limit=cfg.max_queue, tenant=tenant)
            raise ServerOverloaded(self._queue.qsize(), cfg.max_queue,
                                   tier="decode_queue",
                                   tenant=tenant) from None
        if stopped:
            raise ServerStopped("decode engine is stopping")
        return stream

    def generate(self, tokens, max_new_tokens=None, deadline_ms=None,
                 timeout_s=None, tenant=None):
        """Synchronous convenience: submit + wait → token list."""
        return self.submit(tokens, max_new_tokens=max_new_tokens,
                           deadline_ms=deadline_ms,
                           tenant=tenant).result(timeout_s)

    def occupancy(self) -> int:
        return sum(1 for s in self._slots if s is not None)

    def queue_depth(self) -> int:
        return self._queue.qsize()

    def stats(self) -> dict:
        with self._lock:
            counters = dict(self.counters)
        return {"slots": self.config.slots,
                "occupied": self.occupancy(),
                "queue_depth": self.queue_depth(),
                "programs": sorted("/".join(str(p) for p in k)
                                   for k in self._programs),
                "grid_bound": self.grid.grid_bound(),
                "step_ms": self.step_latency.summary(),
                **counters}

    # -- worker ----------------------------------------------------------
    def _run(self):
        j = get_journal()
        draining = False
        try:
            while True:
                if self._stopping.is_set():
                    break
                draining = self._admit(draining)
                active = [i for i, s in enumerate(self._slots)
                          if s is not None]
                if not active:
                    if draining and self._queue.qsize() == 0:
                        break
                    if not draining:
                        # idle: block (bounded) for the first stream
                        try:
                            item = self._queue.get(
                                timeout=self.config.window_ms / 1000.0)
                        except queue.Empty:
                            continue
                        if item is _STOP:
                            draining = True
                            continue
                        self._admit_one(item)
                    continue
                self._step(active)
        except BaseException as exc:        # worker must die loudly
            j.crash(exc, where="decode_worker")
            raise
        finally:
            leftovers = [s for s in self._slots if s is not None]
            self._slots = [None] * self.config.slots
            self._drain_queue(leftovers)
            self._fail_streams(leftovers)

    def _drain_queue(self, out):
        while True:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                return
            if item is not _STOP:
                out.append(item)

    def _fail_streams(self, streams):
        for s in streams:
            s._fail(ServerStopped("decode engine stopped before this "
                                  "stream finished"))
        streams.clear()

    def _admit(self, draining):
        """Fill free slots from the queue (non-blocking — with active
        streams, waiting here would tax every token of every stream).
        Returns the updated draining flag."""
        while any(s is None for s in self._slots):
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                return draining
            if item is _STOP:
                draining = True
                continue
            self._admit_one(item)
        return draining

    def _admit_one(self, stream):
        now = time.monotonic()
        if stream.cancelled():
            with self._lock:
                self.counters["cancelled"] += 1
            get_journal().event("decode_cancel", engine=self._id,
                                stage="queued", generated=0,
                                tenant=stream.tenant)
            stream._fail(RequestError("decode stream cancelled before "
                                      "admission"), now)
            stream.error.retryable = False
            return
        if stream.expired(now):
            with self._lock:
                self.counters["preempted"] += 1
            get_journal().event("decode_deadline_miss", engine=self._id,
                                stage="admit",
                                late_ms=round(stream.late_ms(now), 2),
                                tenant=stream.tenant)
            stream._fail(DeadlineExceeded("decode_admit",
                                          stream.late_ms(now),
                                          tenant=stream.tenant), now)
            return
        slot = self._slots.index(None)
        t0 = time.perf_counter()
        chunks = self._prefill(slot, stream.prompt[:-1])
        stream.slot = slot
        stream.admit_t = now
        self._slots[slot] = stream
        with self._lock:
            self.counters["admitted"] += 1
        get_journal().event(
            "decode_admit", engine=self._id, slot=slot,
            prompt=len(stream.prompt), chunks=chunks,
            max_new=stream.max_new, occupancy=self.occupancy(),
            queue_depth=self.queue_depth(), tenant=stream.tenant,
            prefill_ms=round((time.perf_counter() - t0) * 1000.0, 2))

    def _prefill(self, slot, toks) -> int:
        """Absorb a prompt prefix into ``slot`` in padded chunks on the
        prefill lattice.  ``start == 0`` on the first chunk resets the
        slot (the model contract).  Returns the chunk count."""
        i32 = np.int32
        chunk = self.config.prefill_chunk
        off, chunks = 0, 0
        state = self._ensure_state()
        if not toks:
            # single-token prompt: no prefix, but the slot must still
            # reset — run one empty chunk (length 0, start 0)
            toks = []
        while True:
            take = min(chunk, len(toks) - off)
            if chunks and take <= 0:
                break
            take = max(take, 0)
            bucket = self.prefill_buckets[0]
            for b in self.prefill_buckets:
                if take <= b:
                    bucket = b
                    break
            padded = np.zeros((bucket,), i32)
            padded[:take] = toks[off:off + take]
            prog = self._program(("prefill", bucket))
            state = prog(state, self._commit(np.asarray(slot, i32)),
                         self._commit(padded),
                         self._commit(np.asarray(take, i32)),
                         self._commit(np.asarray(off, i32)))
            off += take
            chunks += 1
            if off >= len(toks):
                break
        self._state = state
        return chunks

    def _step(self, active):
        """One continuous-batching step: sweep cancels/deadlines, run
        the ``(slots, 1)`` executable, scatter tokens, finish/free."""
        cfg = self.config
        now = time.monotonic()
        live = []
        for i in active:
            s = self._slots[i]
            if s.cancelled():
                self._slots[i] = None
                with self._lock:
                    self.counters["cancelled"] += 1
                get_journal().event("decode_cancel", engine=self._id,
                                    stage="active", slot=i,
                                    generated=len(s._generated),
                                    occupancy=self.occupancy(),
                                    tenant=s.tenant)
                err = RequestError(
                    f"decode stream cancelled after "
                    f"{len(s._generated)} tokens")
                err.retryable = False
                s._fail(err, now)
            elif s.expired(now):
                self._slots[i] = None
                with self._lock:
                    self.counters["preempted"] += 1
                get_journal().event("decode_preempt", engine=self._id,
                                    slot=i,
                                    late_ms=round(s.late_ms(now), 2),
                                    generated=len(s._generated),
                                    occupancy=self.occupancy(),
                                    tenant=s.tenant)
                s._fail(DeadlineExceeded("decode_step", s.late_ms(now),
                                         tenant=s.tenant), now)
            else:
                live.append(i)
        if not live:
            return
        toks = np.zeros((cfg.slots, 1), np.int32)
        mask = np.zeros((cfg.slots,), bool)
        for i in live:
            toks[i, 0] = self._slots[i].pending_tok
            mask[i] = True
        prog = self._program(("step",))
        t0 = time.perf_counter()
        state, nxt = prog(self._ensure_state(), self._commit(toks),
                          self._commit(mask))
        nxt = np.asarray(nxt)
        step_ms = (time.perf_counter() - t0) * 1000.0
        self._state = state
        self.step_latency.observe(step_ms)
        finished = 0
        now = time.monotonic()
        for i in live:
            s = self._slots[i]
            tok = int(nxt[i])
            s._generated.append(tok)
            s.pending_tok = tok
            if len(s._generated) >= s.max_new:
                self._slots[i] = None
                finished += 1
                get_journal().event(
                    "decode_finish", engine=self._id, slot=i,
                    generated=len(s._generated),
                    ms=round((now - s.enq_t) * 1000.0, 2),
                    occupancy=self.occupancy(), tenant=s.tenant)
                s._finish(now)
        with self._lock:
            self.counters["steps"] += 1
            self.counters["tokens_out"] += len(live)
            self.counters["completed"] += finished
        lat = self.step_latency.summary()
        get_journal().event(
            "decode_step", engine=self._id, active=len(live),
            slots=cfg.slots,
            occupancy=round(len(live) / cfg.slots, 4),
            step_ms=round(step_ms, 3), finished=finished,
            queue_depth=self.queue_depth(),
            p50_ms=lat["p50"], p95_ms=lat["p95"])

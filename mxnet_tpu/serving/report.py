"""Serving-journal summarizer — stdlib-only, consumed by the doctor CLI.

Parses a JSONL diagnostics journal (``MXNET_TPU_JOURNAL=<file>`` during
a serving run) and reduces the ``serving_*`` records of the LAST run
(everything after the final ``serving_start``) to the operator signals:
shed-rate, compile-cache hit-rate, deadline-miss counts, reload history.
Junk/truncated lines are tolerated — a crashed writer's torn tail must
not hide the healthy prefix.

Importable from ``python -m mxnet_tpu.diagnostics doctor`` without jax
(same contract as ``resilience.commit``): import this module directly,
never through heavy siblings.
"""
from __future__ import annotations

import json

__all__ = ["serving_report"]

_KINDS = ("serving_start", "serving_stop", "serving_batch", "serving_shed",
          "serving_reject", "serving_deadline_miss", "serving_reload",
          "serving_reload_failed", "serving_stopped_reject",
          "serving_cancelled",
          # the replica-pool tier (serving/pool.py + router.py)
          "pool_start", "pool_stop", "pool_spawn", "pool_drain",
          "pool_restart", "pool_reload", "replica_lost",
          "replica_respawn_exhausted", "router_start", "router_stop",
          "router_retry", "router_hedge", "router_breaker", "router_shed",
          "router_budget_exhausted",
          # the tenant-fleet tier (serving/fleet.py)
          "tenant_add", "tenant_remove", "tenant_quarantine",
          "tenant_page_in", "tenant_page_out",
          # the persistent AOT executable cache (serving/aotcache.py)
          "aot_store", "aot_store_failed", "aot_fallback",
          "aot_prewarm", "aot_gc",
          # the continuous-batching decode engine (serving/decode.py)
          "decode_start", "decode_stop", "decode_warmup", "decode_admit",
          "decode_step", "decode_finish", "decode_cancel",
          "decode_preempt", "decode_deadline_miss", "decode_shed",
          # the tensor-parallel plan (serving/shardplan.py)
          "shard_place",
          # the canary deployment controller (serving/deploy.py)
          "deploy_start", "canary_up", "gate_eval", "promote",
          "rollback", "deploy_done", "deploy_mirror_mismatch",
          "pool_pin")

_DEPLOY_KINDS = ("deploy_start", "canary_up", "gate_eval", "promote",
                 "rollback", "deploy_done", "deploy_mirror_mismatch",
                 "pool_pin")

_AOT_KINDS = ("aot_store", "aot_store_failed", "aot_fallback",
              "aot_prewarm", "aot_gc")

_TENANT_KINDS = ("tenant_add", "tenant_remove", "tenant_quarantine",
                 "tenant_page_in", "tenant_page_out")

_DECODE_KINDS = ("decode_start", "decode_stop", "decode_warmup",
                 "decode_admit", "decode_step", "decode_finish",
                 "decode_cancel", "decode_preempt",
                 "decode_deadline_miss", "decode_shed")

_POOL_KINDS = ("pool_start", "pool_stop", "pool_spawn", "pool_drain",
               "pool_restart", "pool_reload", "replica_lost",
               "replica_respawn_exhausted", "router_start", "router_stop",
               "router_retry", "router_hedge", "router_breaker",
               "router_shed", "router_budget_exhausted")


def _read_records(path):
    records = []
    try:
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line.startswith("{"):
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue                 # torn tail of a killed writer
                if isinstance(rec, dict) and rec.get("kind") in _KINDS:
                    records.append(rec)
    except OSError as e:
        return None, f"cannot read {path}: {e.strerror or e}"
    return records, None


def _last_run_start(records) -> int:
    """Index where the last run begins (see the caller's comment).

    Known limit: a pool drill that CRASHED (no ``pool_stop``) followed
    by a solo Server run in the same journal file still anchors at the
    crashed drill's ``pool_start`` — a healthy pool run is thousands of
    worker ``serving_batch``/``serving_start`` records with *no* pool-
    kind records between them, so "a serving_start after the last pool
    record" cannot distinguish the solo run without misanchoring the
    healthy fleet case. Use one journal file per run (what every test
    and the bench do) and the question does not arise."""
    def last(kind):
        for i in range(len(records) - 1, -1, -1):
            if records[i]["kind"] == kind:
                return i
        return None

    i_pool = last("pool_start")
    if i_pool is None:
        i_start = last("serving_start")
        return 0 if i_start is None else i_start
    i_stop = last("pool_stop")
    if i_stop is not None and i_stop > i_pool:
        # the pool run closed; a serving_start after the close is a new
        # solo run and wins the anchor
        solo = [i for i in range(i_stop + 1, len(records))
                if records[i]["kind"] == "serving_start"]
        if solo:
            return solo[-1]
    return i_pool


def serving_report(path) -> dict:
    """Summarize the last serving run's journal records (see module
    docstring).  Always returns a dict; ``ok`` is False with an
    ``error`` when the file is unreadable or holds no serving records."""
    records, err = _read_records(path)
    if records is None:
        return {"ok": False, "path": path, "error": err}
    # last run = records after the final pool_start when the pool run is
    # the LAST run (every worker replica contributes its own
    # serving_start — slicing at the last of those would hide the rest
    # of the fleet). A pool run that already closed (pool_stop) followed
    # by a later solo serving_start is a finished drill: anchor at the
    # newer solo run instead of resurrecting the stale fleet records.
    records = records[_last_run_start(records):]
    if not records:
        return {"ok": False, "path": path,
                "error": "no serving records in journal"}

    batches = [r for r in records if r["kind"] == "serving_batch"]
    sheds = sum(1 for r in records if r["kind"] == "serving_shed")
    rejects = sum(1 for r in records if r["kind"] == "serving_reject")
    misses = {"dequeue": 0, "post_batch": 0}
    for r in records:
        if r["kind"] == "serving_deadline_miss":
            misses[r.get("stage", "dequeue")] = \
                misses.get(r.get("stage", "dequeue"), 0) + 1
    reloads = [r for r in records if r["kind"] == "serving_reload"]
    reload_failures = sum(1 for r in records
                          if r["kind"] == "serving_reload_failed")

    # delivered excludes post_batch deadline misses (they are inside
    # `batch` but got an error response); older records without the
    # field fall back to the batch size
    served = sum(int(r.get("delivered", r.get("batch", 0)))
                 for r in batches)
    admitted = sum(int(r.get("batch", 0)) for r in batches) + \
        misses.get("dequeue", 0)
    offered = admitted + sheds
    out = {"ok": True, "path": path,
           "batches": len(batches), "served": served,
           "shed": sheds, "rejected_shape": rejects,
           "shed_rate": round(sheds / offered, 4) if offered else None,
           "deadline_miss": misses,
           "deadline_miss_total": sum(misses.values()),
           "reloads": [{"step": r.get("step"),
                        "prev_step": r.get("prev_step")} for r in reloads],
           "reload_failures": reload_failures}
    if batches:
        last = batches[-1]
        hits, miss = int(last.get("hits", 0)), int(last.get("misses", 0))
        out["compiles"] = miss
        out["cache_hit_rate"] = round(hits / (hits + miss), 4) \
            if hits + miss else None
        out["last_batch"] = {
            k: last.get(k) for k in ("queue_depth", "batch", "bucket",
                                     "fill", "pad_waste", "params_step",
                                     "p50_ms", "p95_ms", "p99_ms")}
        fills = [float(r.get("fill", 0)) for r in batches]
        out["mean_fill"] = round(sum(fills) / len(fills), 4)
        waste = [float(r.get("pad_waste", 0)) for r in batches]
        out["mean_pad_waste"] = round(sum(waste) / len(waste), 4)
    else:
        out["compiles"] = 0
        out["cache_hit_rate"] = None
    stops = [r for r in records if r["kind"] == "serving_stop"]
    out["clean_stop"] = bool(stops) and not stops[-1].get("stuck", False)
    router = _router_section(records)
    if router is not None:
        out["router"] = router
    tenants = _tenant_section(records)
    if tenants is not None:
        out["tenants"] = tenants
    aot = _aot_section(records)
    if aot is not None:
        out["aot"] = aot
    decode = _decode_section(records)
    if decode is not None:
        out["decode"] = decode
    deploy = _deploy_section(records)
    if deploy is not None:
        out["deploy"] = deploy
    placements = [r for r in records if r["kind"] == "shard_place"]
    if placements:
        last_place = placements[-1]
        out["sharding"] = {"mesh": last_place.get("mesh"),
                           "params": last_place.get("params"),
                           "site": last_place.get("site"),
                           "placements": len(placements)}
    return out


def _decode_section(records) -> dict | None:
    """Continuous-batching reduction of the last run: slot-occupancy
    histogram (how full the pool actually ran), steps/s throughput,
    admit/finish/preempt/cancel/shed ledger, and warmup compile counts
    — the operator view of one decode run (docs/serving.md continuous
    batching)."""
    dec = [r for r in records if r["kind"] in _DECODE_KINDS]
    if not dec:
        return None
    count = lambda k: sum(1 for r in dec if r["kind"] == k)  # noqa: E731
    steps = [r for r in dec if r["kind"] == "decode_step"]
    finishes = [r for r in dec if r["kind"] == "decode_finish"]
    # occupancy histogram keyed by ACTIVE slot count: {"3": 41} reads
    # "41 steps ran with 3 slots live" — the fill story for the pool
    occupancy: dict = {}
    for r in steps:
        k = str(int(r.get("active", 0)))
        occupancy[k] = occupancy.get(k, 0) + 1
    span_s = (float(steps[-1].get("ts", 0.0)) -
              float(steps[0].get("ts", 0.0))) if len(steps) > 1 else 0.0
    cancels = {"queued": 0, "active": 0}
    for r in dec:
        if r["kind"] == "decode_cancel":
            stage = str(r.get("stage", "active"))
            cancels[stage] = cancels.get(stage, 0) + 1
    warmups = [r for r in dec if r["kind"] == "decode_warmup"]
    out = {
        "steps": len(steps),
        "steps_per_s": round(len(steps) / span_s, 2) if span_s > 0
        else None,
        "occupancy_hist": occupancy,
        "admitted": count("decode_admit"),
        "finished": len(finishes),
        "tokens_out": sum(int(r.get("generated", 0)) for r in finishes),
        "preempted": count("decode_preempt"),
        "cancelled": cancels,
        "cancelled_total": sum(cancels.values()),
        "deadline_miss_admit": count("decode_deadline_miss"),
        "shed": count("decode_shed"),
        "warmup_programs": sum(int(r.get("programs", 0))
                               for r in warmups),
    }
    if steps:
        last = steps[-1]
        out["last_step"] = {k: last.get(k) for k in
                            ("active", "slots", "occupancy", "step_ms",
                             "queue_depth", "p50_ms", "p95_ms")}
    stops = [r for r in dec if r["kind"] == "decode_stop"]
    if stops:
        out["clean_stop"] = not stops[-1].get("stuck", False)
    return out


def _aot_section(records) -> dict | None:
    """AOT-cache reduction of the last run: stores, fallbacks by
    reason (the corrupt/stale/truncated ledger), prewarm loaded-vs-
    compiled split, and GC evictions — the warm-start story one journal
    tells (docs/serving.md AOT cache)."""
    aot = [r for r in records if r["kind"] in _AOT_KINDS]
    if not aot:
        return None
    fallbacks: dict = {}
    for r in aot:
        if r["kind"] == "aot_fallback":
            reason = str(r.get("reason", "unknown"))
            fallbacks[reason] = fallbacks.get(reason, 0) + 1
    prewarms = [r for r in aot if r["kind"] == "aot_prewarm"]
    return {
        "stores": sum(1 for r in aot if r["kind"] == "aot_store"),
        "store_failures": sum(1 for r in aot
                              if r["kind"] == "aot_store_failed"),
        "fallbacks": fallbacks,
        "fallback_total": sum(fallbacks.values()),
        "prewarmed": {
            "loaded": sum(int(r.get("loaded", 0)) for r in prewarms),
            "compiled": sum(int(r.get("compiled", 0)) for r in prewarms),
            "ms": round(sum(float(r.get("ms", 0.0)) for r in prewarms),
                        2)},
        "gc_evicted": sum(int(r.get("evicted", 0)) for r in aot
                          if r["kind"] == "aot_gc"),
    }


def _tenant_section(records) -> dict | None:
    """Tenant-fleet reduction of the last run: per tenant — traffic
    counts, tenant-classed sheds, the quarantine→half-open→re-admit
    trail in order (with trace ids), paging counts + total page-in cost
    (so paging can be told apart from tail latency), and reload steps.
    The operator view of one tenant-isolation chaos drill
    (docs/serving.md failure matrix)."""
    named = [r for r in records
             if r["kind"] in _TENANT_KINDS or r.get("tenant") is not None]
    if not any(r["kind"] in _TENANT_KINDS for r in records):
        return None
    out: dict = {}

    def row(name):
        if name not in out:
            out[name] = {"batches": 0, "served": 0, "shed": 0,
                         "sheds_by_tier": {}, "rejected_shape": 0,
                         "deadline_miss": 0, "quarantine_trail": [],
                         "readmitted": False, "page_ins": 0,
                         "page_in_cost_ms": 0.0, "page_outs": 0,
                         "reload_steps": [], "removed": False,
                         "last_p99_ms": None}
        return out[name]

    for r in named:
        name = r.get("tenant")
        if name is None:
            continue
        kind = r["kind"]
        t = row(name)
        if kind == "serving_batch":
            t["batches"] += 1
            t["served"] += int(r.get("delivered", r.get("batch", 0)))
            # tenant_p99_ms is THIS tenant's own summary (the record's
            # p99_ms is fleet-wide and would attribute other tenants'
            # tails to this one)
            t["last_p99_ms"] = r.get("tenant_p99_ms")
        elif kind == "serving_shed":
            t["shed"] += 1
            tier = r.get("tier", "queue_full")
            t["sheds_by_tier"][tier] = t["sheds_by_tier"].get(tier, 0) + 1
        elif kind == "serving_reject":
            t["rejected_shape"] += 1
        elif kind == "serving_deadline_miss":
            t["deadline_miss"] += 1
        elif kind == "tenant_quarantine":
            t["quarantine_trail"].append(
                {"frm": r.get("frm"), "to": r.get("to"),
                 "reason": r.get("reason"),
                 "trace_id": r.get("trace_id")})
            if r.get("frm") == "half_open" and r.get("to") == "admitted":
                t["readmitted"] = True
        elif kind == "tenant_page_in":
            t["page_ins"] += 1
            t["page_in_cost_ms"] = round(
                t["page_in_cost_ms"] + float(r.get("cost_ms") or 0.0), 2)
        elif kind == "tenant_page_out":
            t["page_outs"] += 1
        elif kind == "serving_reload":
            t["reload_steps"].append(r.get("step"))
        elif kind == "tenant_remove":
            t["removed"] = True
    return out


def _deploy_section(records) -> dict | None:
    """Canary-deployment reduction of the last run: the full
    deploy_start→canary_up→gate_eval…→promote/rollback→deploy_done
    trail in order (with trace ids — one ``deploy`` span covers it),
    gate-breach/mirror-mismatch counters, and the last deployment's
    outcome.  The operator view of one deploy drill (docs/serving.md,
    canary deployment)."""
    dep = [r for r in records if r["kind"] in _DEPLOY_KINDS]
    if not any(r["kind"] == "deploy_start" for r in dep) \
            and not any(r["kind"] == "deploy_done" for r in dep):
        return None
    count = lambda k: sum(1 for r in dep if r["kind"] == k)  # noqa: E731
    trail = []
    for r in dep:
        if r["kind"] == "pool_pin":
            continue                     # pins are counted, not trailed
        row = {"kind": r["kind"], "trace_id": r.get("trace_id")}
        for k in ("from_step", "to_step", "step", "verdict", "reasons",
                  "reason", "result", "replicas", "n", "canary",
                  "rollback_ms"):
            if r.get(k) is not None:
                row[k] = r.get(k)
        trail.append(row)
    dones = [r for r in dep if r["kind"] == "deploy_done"]
    evals = [r for r in dep if r["kind"] == "gate_eval"]
    out = {
        "deploys": count("deploy_start"),
        "gate_evals": len(evals),
        "gate_breaches": sum(1 for r in evals
                             if r.get("verdict") == "breach"),
        "mirror_mismatches": count("deploy_mirror_mismatch"),
        "promotions": count("promote"),
        "rollbacks": count("rollback"),
        "pins": count("pool_pin"),
        "trail": trail,
    }
    if dones:
        last = dones[-1]
        out["last"] = {k: last.get(k) for k in
                       ("result", "reason", "from_step", "to_step",
                        "canary", "gate_evals", "rollback_ms",
                        "converged", "deploy_ms")
                       if last.get(k) is not None}
    return out


def _router_section(records) -> dict | None:
    """Replica-pool/router reduction of the last run: retry/hedge/shed
    counts, every breaker transition in order, replica losses/restarts
    and half-open re-admissions — the operator view of one chaos drill
    (docs/serving.md failure matrix)."""
    pool = [r for r in records if r["kind"] in _POOL_KINDS]
    if not pool:
        return None
    count = lambda k: sum(1 for r in pool if r["kind"] == k)  # noqa: E731
    transitions = [
        {"replica": r.get("replica"), "frm": r.get("frm"),
         "to": r.get("to"), "reason": r.get("reason"),
         "trace_id": r.get("trace_id")}
        for r in pool if r["kind"] == "router_breaker"]
    sheds: dict = {}
    for r in pool:
        if r["kind"] == "router_shed":
            t = r.get("tier", "unknown")
            sheds[t] = sheds.get(t, 0) + 1
    readmitted = sorted({t["replica"] for t in transitions
                         if t["frm"] == "half_open"
                         and t["to"] == "closed"})
    return {
        "retries": count("router_retry"),
        "hedges": count("router_hedge"),
        "budget_exhausted": count("router_budget_exhausted"),
        "sheds_by_tier": sheds,
        "breaker_transitions": transitions,
        "replicas_lost": [
            {"replica": r.get("replica"), "idle_s": r.get("idle_s")}
            for r in pool if r["kind"] == "replica_lost"],
        "restarts": count("pool_restart"),
        "drains": count("pool_drain"),
        "reload_rolls": sum(1 for r in pool if r["kind"] == "pool_reload"
                            and r.get("phase") == "end"),
        "readmitted": readmitted,
        "respawn_exhausted": count("replica_respawn_exhausted"),
    }

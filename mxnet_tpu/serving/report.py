"""Serving-journal summarizer — stdlib-only, consumed by the doctor CLI.

Parses a JSONL diagnostics journal (``MXNET_TPU_JOURNAL=<file>`` during
a serving run) and reduces the ``serving_*`` records of the LAST run
(everything after the final ``serving_start``) to the operator signals:
shed-rate, compile-cache hit-rate, deadline-miss counts, reload history.
Junk/truncated lines are tolerated — a crashed writer's torn tail must
not hide the healthy prefix.

Importable from ``python -m mxnet_tpu.diagnostics doctor`` without jax
(same contract as ``resilience.commit``): import this module directly,
never through heavy siblings.
"""
from __future__ import annotations

import json

__all__ = ["serving_report"]

_KINDS = ("serving_start", "serving_stop", "serving_batch", "serving_shed",
          "serving_reject", "serving_deadline_miss", "serving_reload",
          "serving_reload_failed")


def _read_records(path):
    records = []
    try:
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line.startswith("{"):
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue                 # torn tail of a killed writer
                if isinstance(rec, dict) and rec.get("kind") in _KINDS:
                    records.append(rec)
    except OSError as e:
        return None, f"cannot read {path}: {e.strerror or e}"
    return records, None


def serving_report(path) -> dict:
    """Summarize the last serving run's journal records (see module
    docstring).  Always returns a dict; ``ok`` is False with an
    ``error`` when the file is unreadable or holds no serving records."""
    records, err = _read_records(path)
    if records is None:
        return {"ok": False, "path": path, "error": err}
    # last run = records after the final serving_start (if any)
    for i in range(len(records) - 1, -1, -1):
        if records[i]["kind"] == "serving_start":
            records = records[i:]
            break
    if not records:
        return {"ok": False, "path": path,
                "error": "no serving records in journal"}

    batches = [r for r in records if r["kind"] == "serving_batch"]
    sheds = sum(1 for r in records if r["kind"] == "serving_shed")
    rejects = sum(1 for r in records if r["kind"] == "serving_reject")
    misses = {"dequeue": 0, "post_batch": 0}
    for r in records:
        if r["kind"] == "serving_deadline_miss":
            misses[r.get("stage", "dequeue")] = \
                misses.get(r.get("stage", "dequeue"), 0) + 1
    reloads = [r for r in records if r["kind"] == "serving_reload"]
    reload_failures = sum(1 for r in records
                          if r["kind"] == "serving_reload_failed")

    # delivered excludes post_batch deadline misses (they are inside
    # `batch` but got an error response); older records without the
    # field fall back to the batch size
    served = sum(int(r.get("delivered", r.get("batch", 0)))
                 for r in batches)
    admitted = sum(int(r.get("batch", 0)) for r in batches) + \
        misses.get("dequeue", 0)
    offered = admitted + sheds
    out = {"ok": True, "path": path,
           "batches": len(batches), "served": served,
           "shed": sheds, "rejected_shape": rejects,
           "shed_rate": round(sheds / offered, 4) if offered else None,
           "deadline_miss": misses,
           "deadline_miss_total": sum(misses.values()),
           "reloads": [{"step": r.get("step"),
                        "prev_step": r.get("prev_step")} for r in reloads],
           "reload_failures": reload_failures}
    if batches:
        last = batches[-1]
        hits, miss = int(last.get("hits", 0)), int(last.get("misses", 0))
        out["compiles"] = miss
        out["cache_hit_rate"] = round(hits / (hits + miss), 4) \
            if hits + miss else None
        out["last_batch"] = {
            k: last.get(k) for k in ("queue_depth", "batch", "bucket",
                                     "fill", "pad_waste", "params_step",
                                     "p50_ms", "p95_ms", "p99_ms")}
        fills = [float(r.get("fill", 0)) for r in batches]
        out["mean_fill"] = round(sum(fills) / len(fills), 4)
        waste = [float(r.get("pad_waste", 0)) for r in batches]
        out["mean_pad_waste"] = round(sum(waste) / len(waste), 4)
    else:
        out["compiles"] = 0
        out["cache_hit_rate"] = None
    stops = [r for r in records if r["kind"] == "serving_stop"]
    out["clean_stop"] = bool(stops) and not stops[-1].get("stuck", False)
    return out

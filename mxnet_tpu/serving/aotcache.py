"""Persistent AOT executable cache — zero-cold-start serving restarts.

The bucket grid bounds how many XLA programs a serving process compiles
(PR 4), but every process still pays them from scratch: a rolling
``restart()`` recompiles the whole bucket lattice under live traffic,
and a cold tenant's page-in repays multi-second compiles the page-out
threw away.  This module turns the bounded-*compile* guarantee into a
bounded-*startup* guarantee: the in-memory :class:`~.cache.PredictorCache`
LRU is backed by an on-disk store of serialized AOT executables
(``jax.experimental.serialize_executable`` under the hood), so a
restart — or a tenant page-in, or a fresh pool worker — *loads* its
executables instead of compiling them.

Key schema (docs/serving.md): an entry is addressed by

- the **padded input shape** ``(batch bucket,) + feature key`` and
  request **dtype** — one executable per bucket-grid cell, exactly the
  in-memory cache's granularity;
- a **param-tree structure fingerprint** — block class + repr + the
  structural parameter names/shapes/dtypes + the PRNG key dtype.
  Parameter *values* stay runtime arguments (the PR-4 zero-retrace
  contract), so a hot-reload keeps hitting the same entries.

Every entry carries a **compatibility envelope** (jax/jaxlib versions,
backend platform, device kind, local device count): an entry written by
a different toolchain or topology is *invalidated* (degrades to a
compile), never loaded.  Entries commit atomically via
``resilience.atomic`` with CRC section manifests (serving/aot_report.py
owns the byte format); the read path validates magic, bounds, header
CRC, envelope, and section CRCs **before** any deserializer sees a byte
(graftlint G21).  A corrupt, truncated, or stale entry journals an
``aot_fallback`` and compiles normally — never wrong numerics
(loaded-vs-compiled bit parity is test-gated).  The directory is LRU
garbage-collected under a byte budget.

Knobs: ``MXNET_TPU_AOT_CACHE_DIR`` (the store root; unset = disabled),
``MXNET_TPU_AOT_CACHE_BYTES`` (GC budget, default 1 GiB),
``MXNET_TPU_AOT_CACHE`` = ``rw|ro|off`` (``ro`` loads but never writes
— immutable deploy images; ``off`` is the kill switch; malformed
degrades to ``rw``, journaled).
"""
from __future__ import annotations

import hashlib
import os
import threading
import time

import numpy as np

from ..diagnostics.journal import get_journal
from ..observability import instrument as _obs
from ..resilience import atomic as _atomic
from . import aot_report as _fmt
from .cache import CompiledPredictor

__all__ = ["AOTCache"]

_MODES = ("rw", "ro", "off")
DEFAULT_BUDGET = 1 << 30


def _env_bytes():
    try:
        return int(os.environ.get("MXNET_TPU_AOT_CACHE_BYTES",
                                  DEFAULT_BUDGET))
    except ValueError:
        return DEFAULT_BUDGET


def _bump(event: str) -> None:
    """One ``mxnet_tpu_aot_cache_events{event}`` counter tick (lazy
    registry import: the module stays cheap when the cache is idle)."""
    from ..observability.metrics import default_registry
    default_registry().counter(
        "mxnet_tpu_aot_cache_events",
        "persistent AOT executable cache counters "
        "(hit/miss/store/fallback/evict)",
        ("event",)).labels(event=event).inc()


class AOTCache:
    """On-disk tier behind the in-memory predictor LRU (see module
    docstring).  One instance per Server/Fleet; safe for concurrent
    processes on one directory (pid-unique atomic staging, whole-file
    commits, CRC-checked reads)."""

    def __init__(self, root, max_bytes=None, mode=None):
        self.root = os.fspath(root)
        os.makedirs(self.root, exist_ok=True)
        raw_mode = mode if mode is not None else \
            os.environ.get("MXNET_TPU_AOT_CACHE", "rw")
        if raw_mode not in _MODES:
            get_journal().event("aot_cache_bad_mode", mode=str(raw_mode),
                                fallback="rw")
            raw_mode = "rw"
        self.mode = raw_mode
        self.max_bytes = _env_bytes() if max_bytes is None \
            else int(max_bytes)
        self._envelope = None
        self._lock = threading.Lock()
        self.counters = {"hits": 0, "misses": 0, "stores": 0,
                         "store_failures": 0, "fallbacks": 0,
                         "evictions": 0}
        # crashed writers' staging litter from a previous incarnation
        _atomic.sweep_tmp(self.root)

    @classmethod
    def maybe(cls, root) -> "AOTCache | None":
        """Construct unless disabled: falsy root or the ``off`` kill
        switch return None (callers keep the compile-only path)."""
        if not root:
            return None
        if os.environ.get("MXNET_TPU_AOT_CACHE") == "off":
            return None
        return cls(root)

    # -- identity ----------------------------------------------------------
    def envelope(self) -> dict:
        """The compatibility envelope stamped on every entry — computed
        once per instance (one guarded backend dial)."""
        if self._envelope is None:
            import jax
            import jaxlib

            from ..diagnostics import guard
            dev = guard.devices(local=True)
            self._envelope = {
                "jax": jax.__version__,
                "jaxlib": jaxlib.__version__,
                "platform": dev[0].platform,
                "device_kind": dev[0].device_kind,
                "n_local": len(dev),
            }
        return self._envelope

    @staticmethod
    def fingerprint(block, x_dtype, plan=None) -> str:
        """Param-tree *structure* fingerprint: block identity (class +
        repr — layer configs/activations print there) + structural
        parameter names/shapes + the runtime array shapes/dtypes in
        ``_param_split`` order + the PRNG key dtype (the impl bakes a
        different program).  Parameter VALUES are absent by design:
        hot-reload swaps values, never the program.

        With a shard plan the mesh signature + rule set join the key
        material (``plan.fingerprint_token``) — a tensor-parallel
        executable is only valid on its exact mesh shape, and the same
        model served single-device and sharded must occupy two entries.
        ``plan=None`` contributes NOTHING to the hash, byte-identical to
        the pre-plan scheme, so existing caches stay warm.

        Memoized on the block (``__dict__`` directly — bypasses Block's
        attribute registration): page-in restores call this once per
        warm shape on the worker thread, and repr + a full param walk
        per call is real stall time.  The memo dies with the block;
        post-hoc structural mutation (``cast``, added children) changes
        the runtime arg avals, which the AOT executable's own argument
        check rejects loudly — staleness can't reach numerics."""
        plan_token = None if plan is None else plan.fingerprint_token()
        dt_key = (str(np.dtype(x_dtype)), plan_token)
        memo = block.__dict__.setdefault("_aot_fp_memo", {})
        got = memo.get(dt_key)
        if got is not None:
            return got
        from .cache import key_spec
        parts = [f"{type(block).__module__}.{type(block).__qualname__}",
                 repr(block), dt_key[0]]
        if plan_token is not None:
            parts.append(f"plan:{plan_token}")
        names = block._structural_names()
        parts.append("|".join(
            f"{k}:{tuple(p.shape) if p.shape else ()}"
            for k, p in sorted(names.items())))
        trainable, aux = block._param_split()
        for tag, params in (("tr", trainable), ("aux", aux)):
            for p in params:
                d = p._data[0]._data
                parts.append(f"{tag}:{tuple(d.shape)}:{d.dtype}")
        parts.append(str(key_spec().dtype))
        raw = "\x1f".join(parts).encode("utf-8", "replace")
        memo[dt_key] = hashlib.sha1(raw).hexdigest()
        return memo[dt_key]

    def entry_path(self, block, shape, dtype, plan=None) -> str:
        fp = self.fingerprint(block, dtype, plan=plan)
        digest = hashlib.sha1(
            f"{fp}|{tuple(shape)}|{np.dtype(dtype)}".encode()).hexdigest()
        return os.path.join(self.root, f"aot-{digest[:24]}{_fmt.SUFFIX}")

    # -- read path ---------------------------------------------------------
    def load(self, block, shape, dtype, ctx=None,
             site="serving_predictor", plan=None):
        """Return a loaded :class:`CompiledPredictor` or None (cold
        miss / invalidated entry).  Never raises for a bad entry: every
        failure past existence journals an ``aot_fallback`` with its
        reason and the caller compiles normally."""
        path = self.entry_path(block, shape, dtype, plan=plan)
        if not os.path.exists(path):
            self._note("misses", "miss")
            return None
        header, sections, reason = _fmt.read_entry(path)
        if header is None:
            return self._fallback(path, reason)
        if header.get("envelope") != self.envelope():
            return self._fallback(path, "envelope",
                                  entry_envelope=header.get("envelope"))
        payload = sections.get("exec")
        trees = sections.get("trees")
        if payload is None or trees is None:
            return self._fallback(path, "missing_section")
        try:
            from ..diagnostics import guard
            backend = guard.devices(local=True)[0].client
            with _obs.aot_load_span(site, path=path,
                                    bytes=len(payload) + len(trees),
                                    shape=list(shape)):
                pred = CompiledPredictor.from_serialized(
                    block, payload, trees, ctx=ctx, backend=backend,
                    plan=plan)
        except Exception as exc:
            return self._fallback(path,
                                  f"deserialize:{type(exc).__name__}")
        self._note("hits", "hit")
        self._touch(path)
        return pred

    def _fallback(self, path, reason, **extra):
        self._note("fallbacks", "fallback")
        with self._lock:
            self.counters["misses"] += 1
        _bump("miss")
        get_journal().event("aot_fallback", path=path, reason=reason,
                            **extra)
        return None

    @staticmethod
    def _touch(path) -> None:
        """Refresh mtime so the LRU GC sees a load as recency (best
        effort — a read-only image just stays in FIFO order)."""
        try:
            os.utime(path)
        except OSError:
            pass

    # -- write path --------------------------------------------------------
    def store(self, pred, block, shape, dtype, plan=None) -> bool:
        """Persist one AOT-compiled predictor (no-op in ``ro`` mode).
        A backend that cannot serialize its executables degrades to
        memory-only caching, journaled once per store attempt."""
        if self.mode != "rw":
            return False
        path = self.entry_path(block, shape, dtype, plan=plan)
        t0 = time.perf_counter()
        try:
            payload, trees = pred.serialize_aot()
            key_doc = {"shape": list(shape),
                       "dtype": str(np.dtype(dtype)),
                       "fingerprint": self.fingerprint(block, dtype,
                                                       plan=plan)}
            if plan is not None:
                key_doc["shard_plan"] = plan.fingerprint_token()
            blob = _fmt.pack_entry(
                {"envelope": self.envelope(), "key": key_doc,
                 "created": time.time()},
                {"exec": payload, "trees": trees})
            with _atomic.atomic_write(path, "wb") as f:
                f.write(blob)
        except Exception as exc:
            self._note("store_failures", "store_failure")
            get_journal().event("aot_store_failed", path=path,
                                error=type(exc).__name__,
                                detail=str(exc)[:300])
            return False
        self._note("stores", "store")
        get_journal().event("aot_store", path=path, bytes=len(blob),
                            shape=list(shape),
                            ms=round((time.perf_counter() - t0) * 1e3, 2))
        self.gc()
        return True

    # -- the one entry point the serving cache uses ------------------------
    def load_or_compile(self, block, shape, dtype, ctx=None,
                        site="serving_predictor", plan=None):
        """Disk-first predictor build: a valid entry loads (``aot_load``
        span, no compile); otherwise compile eagerly at the padded shape
        (``xla_compile`` span, same site family as the lazy path) and
        write through.  ``plan`` keys (and shards) the executable — a
        tensor-parallel replica restarting on the same mesh loads its
        partitioned programs with zero XLA compiles."""
        pred = self.load(block, shape, dtype, ctx=ctx, site=site,
                         plan=plan)
        if pred is not None:
            return pred
        pred = CompiledPredictor(block, ctx=ctx, plan=plan)
        with _obs.compile_span(site, shape=list(shape),
                               dtype=str(np.dtype(dtype)), aot=True):
            pred.aot_compile(tuple(shape), dtype)
        self.store(pred, block, shape, dtype, plan=plan)
        return pred

    # -- bookkeeping -------------------------------------------------------
    def _note(self, counter, event) -> None:
        with self._lock:
            self.counters[counter] += 1
        _bump(event)

    def gc(self) -> dict:
        """Evict least-recently-used entries until the directory fits
        the byte budget.  Concurrent writers/GCs tolerate each other
        (unlink races are suppressed; atomic staging litter is not an
        entry)."""
        entries = []
        total = 0
        try:
            names = os.listdir(self.root)
        except OSError:
            return {"evicted": 0, "bytes": 0}
        for name in names:
            if not name.endswith(_fmt.SUFFIX):
                continue
            path = os.path.join(self.root, name)
            try:
                st = os.stat(path)
            except OSError:
                continue
            entries.append((st.st_mtime, st.st_size, path))
            total += st.st_size
        evicted = freed = 0
        if total > self.max_bytes:
            for _mtime, size, path in sorted(entries):
                if total - freed <= self.max_bytes:
                    break
                try:
                    os.unlink(path)
                except OSError:
                    continue
                evicted += 1
                freed += size
            if evicted:
                with self._lock:
                    self.counters["evictions"] += evicted
                for _ in range(evicted):
                    _bump("evict")
                get_journal().event("aot_gc", evicted=evicted,
                                    bytes_freed=freed,
                                    budget=self.max_bytes)
        return {"evicted": evicted, "bytes": total - freed}

    def stats(self) -> dict:
        with self._lock:
            c = dict(self.counters)
        return {"dir": self.root, "mode": self.mode,
                "max_bytes": self.max_bytes, **c}

"""Health-routed front door — placement, retries, hedging, breakers.

The router multiplexes requests across a :class:`~.pool.ReplicaPool`.
Its placement decision is derived ONLY from the pool's heartbeat ledger
(:meth:`ReplicaPool.view` — live + ready, least queue depth), so every
router thread (and any other reader of the same ledger) sees the same
picture; the only router-local overlay is the per-replica circuit
breaker, which exists precisely to react FASTER than the heartbeat
deadline when a replica starts failing requests.

Per-request robustness budget (docs/serving.md):

- **deadline-scoped retries** — a retryable failure (transport error,
  stopped/overloaded replica, predictor fault) moves to a different
  replica with ``resilience.retry`` backoff bounds, always inside the
  request's own deadline; when the budget runs out the caller gets
  ``DeadlineExceeded(stage="router_budget")`` naming the tier that
  acted, never a silent hang;
- **tail-latency hedging** (optional) — if the first attempt hasn't
  answered after a p99-derived delay, a second attempt starts on a
  different replica; first response wins, the loser is cancelled at
  dequeue (in-process replicas) or its reply discarded (subprocess);
- **circuit breaker per replica** — K consecutive failures or a
  heartbeat stall opens the breaker (requests stop routing there);
  after a cooldown it goes half-open and ONE probe request re-admits
  (success → closed) or re-opens it.  Every transition is journaled
  (``router_breaker``) with trace correlation;
- **graceful degradation** — when live capacity falls below the
  configured floor, the router sheds by admission class (lowest
  priority first) instead of failing everyone: ``ServerOverloaded``
  carries the tier that acted.

Metric families (``Router.metrics_text``): ``mxnet_tpu_router_events``
(attempts/retries/hedges/sheds), ``mxnet_tpu_router_breaker_state`` and
``mxnet_tpu_router_replica_p99_ms`` per replica.
"""
from __future__ import annotations

import itertools
import os
import queue as _queue
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field

import numpy as np

from ..diagnostics.journal import get_journal
from ..observability import trace as _trace
from ..observability.metrics import LatencySummary
from ..resilience import atomic as _atomic
from ..resilience.retry import backoff_delays
from .batcher import DeadlineExceeded, RequestError, ServerOverloaded

__all__ = ["Router", "RouterConfig", "RouterResponse"]


def _env_float(name, default):
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def _env_int(name, default):
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


@dataclass
class RouterConfig:
    """Front-door knobs (docs/serving.md; ``MXNET_TPU_POOL_*`` env vars
    set fleet-wide defaults)."""

    default_deadline_ms: float = field(default_factory=lambda: _env_float(
        "MXNET_TPU_SERVING_DEADLINE_MS", 2000.0))
    retries: int = field(default_factory=lambda: _env_int(
        "MXNET_TPU_POOL_RETRIES", 2))
    retry_base_s: float = 0.02               # resilience.retry bounds
    retry_max_s: float = 0.5
    retry_jitter: float = 0.5
    hedge_ms: float = field(default_factory=lambda: _env_float(
        "MXNET_TPU_POOL_HEDGE_MS", 0.0))     # <= 0 disables hedging
    hedge_p99_factor: float = 1.0            # delay = max(hedge_ms, p99*f)
    hedge_min_samples: int = 20              # p99 trustworthy after this
    breaker_k: int = field(default_factory=lambda: _env_int(
        "MXNET_TPU_POOL_BREAKER_K", 3))
    breaker_cooldown_s: float = field(default_factory=lambda: _env_float(
        "MXNET_TPU_POOL_BREAKER_COOLDOWN_S", 5.0))
    capacity_floor: float = field(default_factory=lambda: _env_float(
        "MXNET_TPU_POOL_CAPACITY_FLOOR", 0.0))   # 0 disables degradation


CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"
_BREAKER_CODE = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


class _Breaker:
    """Per-replica failure bookkeeping.  ``closed`` routes normally;
    ``open`` routes nothing until the cooldown passes; ``half_open``
    admits exactly ONE probe request whose outcome decides re-admission
    (success → closed) or another cooldown (failure → open)."""

    __slots__ = ("state", "failures", "opened_t", "probing", "reason")

    def __init__(self):
        self.state = CLOSED
        self.failures = 0
        self.opened_t = None
        self.probing = False
        self.reason = None


class RouterResponse:
    """One routed result plus its provenance: which replica answered,
    which checkpoint step served it (the rolling-reload version stamp),
    how many attempts it took, and whether a hedge fired.  During a
    canary deployment ``deploy_role`` tags the placement arm ("canary"
    or "control"); None outside a deploy."""

    __slots__ = ("value", "replica", "params_step", "attempts", "hedged",
                 "latency_ms", "deploy_role")

    def __init__(self, value, replica, params_step, attempts, hedged,
                 latency_ms, deploy_role=None):
        self.value = value
        self.replica = replica
        self.params_step = params_step
        self.attempts = attempts
        self.hedged = hedged
        self.latency_ms = latency_ms
        self.deploy_role = deploy_role


class _DeployTap:
    """Canary/control bookkeeping for ONE deployment, installed by the
    DeployController via :meth:`Router.set_deploy` and torn down on
    promote/rollback.  Counters are guarded by the router lock; the two
    latency summaries are internally thread-safe.  The tap is a fresh
    window — it observes only traffic DURING the deploy, so the gate
    comparison is live canary-vs-control, not polluted by pre-deploy
    history."""

    __slots__ = ("canary", "mirror_every", "rtol", "atol", "_n",
                 "lat_canary", "lat_control", "served", "failures",
                 "mirrors", "mirror_mismatch", "mirror_errors",
                 "mirror_skipped", "mirror_inflight", "max_inflight")

    def __init__(self, canary, mirror_fraction, rtol, atol,
                 max_inflight=4):
        self.canary = frozenset(map(str, canary))
        # deterministic 1-in-N sampling (no RNG on the request path);
        # fraction <= 0 disables mirroring
        self.mirror_every = (0 if mirror_fraction <= 0
                             else max(int(round(1.0 / mirror_fraction)), 1))
        self.rtol = float(rtol)
        self.atol = float(atol)
        self._n = 0
        self.lat_canary = LatencySummary("deploy_canary_ms")
        self.lat_control = LatencySummary("deploy_control_ms")
        self.served = {"canary": 0, "control": 0}
        self.failures = {"canary": 0, "control": 0}
        self.mirrors = 0
        self.mirror_mismatch = 0
        self.mirror_errors = 0
        self.mirror_skipped = 0
        self.mirror_inflight = 0
        self.max_inflight = int(max_inflight)   # bounded mirror threads

    def role(self, rid) -> str:
        return "canary" if str(rid) in self.canary else "control"


def _apply_tuned_router(cfg) -> None:
    """Fill the router hedge delay from the active tuned table — same
    precedence as the Server's knobs (explicit env var or constructor
    value off the built-in default wins; applied values journal one
    ``tuned_load``)."""
    from ..autotune import table as _tt
    doc = _tt.tuned_for("router")
    if doc is None:
        return
    if "MXNET_TPU_POOL_HEDGE_MS" in os.environ or cfg.hedge_ms != 0.0:
        return
    h = _tt.knob(doc, "router", "hedge_ms")
    if h is None or float(h) == cfg.hedge_ms:
        return
    cfg.hedge_ms = float(h)
    get_journal().event("tuned_load", site="router",
                        hedge_ms=cfg.hedge_ms)


class Router:
    """The front door over one :class:`~.pool.ReplicaPool` (thread-safe;
    call :meth:`predict` / :meth:`call` from any number of client
    threads)."""

    def __init__(self, pool, config=None):
        self.pool = pool
        self.config = config or RouterConfig()
        _apply_tuned_router(self.config)
        # serializes counters/breakers/placement.  No I/O ever runs
        # under it: breaker transitions mutate inside and journal via
        # _emit_breaker after release (graftlint G15)
        self._lock = threading.RLock()
        self._rr = itertools.count()         # least-loaded tiebreak
        self._deploy = None                  # _DeployTap while a canary
                                             # deployment is live (guarded
                                             # by _lock)
        self._breakers: dict = {}            # rid -> _Breaker
        self._latency: dict = {}             # rid -> LatencySummary
        self._attempt_counts: dict = {}      # rid -> attempts routed
        # tenant -> request/served/failure counts; LRU-capped (the keys
        # are request-supplied tenant names — see _note_tenant)
        self._tenant_counts: OrderedDict = OrderedDict()
        self.counters = {"requests": 0, "served": 0, "attempts": 0,
                         "retries": 0, "hedges": 0, "hedge_wins": 0,
                         "shed": 0, "no_capacity": 0, "failures": 0,
                         "breaker_opens": 0, "readmissions": 0}
        get_journal().event(
            "router_start", replicas=sorted(pool.replicas),
            retries=self.config.retries, hedge_ms=self.config.hedge_ms,
            breaker_k=self.config.breaker_k,
            capacity_floor=self.config.capacity_floor)

    # -- client surface --------------------------------------------------
    def predict(self, x, deadline_ms=None, priority=0, tenant=None):
        """Route one sample; returns the result value.  Raises the same
        structured errors a single Server does, plus the router tiers
        (``ServerOverloaded(tier=...)``, ``DeadlineExceeded(
        stage='router_budget')``).  ``tenant`` targets a fleet tenant:
        placement prefers replicas whose beacon advertises it
        un-quarantined, and the tenant rides the wire frame."""
        return self.call(x, deadline_ms=deadline_ms,
                         priority=priority, tenant=tenant).value

    def call(self, x, deadline_ms=None, priority=0,
             tenant=None) -> RouterResponse:
        cfg = self.config
        if deadline_ms is None:
            deadline_ms = cfg.default_deadline_ms
        deadline_ts = time.monotonic() + deadline_ms / 1000.0
        x = np.asarray(x)
        with self._lock:
            self.counters["requests"] += 1
        self._note_tenant(tenant, "requests")
        with _trace.span("router_request", priority=priority,
                         tenant=tenant):
            return self._call_traced(x, deadline_ms, deadline_ts,
                                     priority, tenant)

    def _call_traced(self, x, deadline_ms, deadline_ts, priority,
                     tenant=None):
        cfg = self.config
        t0 = time.monotonic()
        self._admit(priority)
        delays = backoff_delays(cfg.retries, cfg.retry_base_s,
                                cfg.retry_max_s, cfg.retry_jitter)
        tried: set = set()
        attempts = 0
        hedged_any = False
        last_exc = None
        for attempt in range(cfg.retries + 1):
            remaining = deadline_ts - time.monotonic()
            if remaining <= 0:
                break
            state = self._pick(exclude=tried, tenant=tenant)
            if state is None and tried:
                # every untried replica is unroutable: widen back out
                # rather than fail a retryable request early
                state = self._pick(exclude=set(), tenant=tenant)
            if state is None:
                self._note_tenant(tenant, "failures")
                self._shed("no_capacity", priority, tenant=tenant)
            tried.add(state.id)
            attempts += 1
            try:
                value, meta, hedged = self._attempt(
                    state, x, remaining, attempt, tenant)
            except RequestError as exc:
                last_exc = exc
                hedged_any = hedged_any or getattr(exc, "_hedged", False)
                self._record_failure(getattr(exc, "_replica", state.id),
                                     exc)
                if not getattr(exc, "retryable", False) \
                        or attempt >= cfg.retries:
                    self._note_tenant(tenant, "failures")
                    raise
                with self._lock:
                    self.counters["retries"] += 1
                get_journal().event(
                    "router_retry", replica=state.id, attempt=attempt + 1,
                    error=type(exc).__name__, detail=str(exc)[:200],
                    tenant=tenant)
                pause = min(delays[attempt],
                            max(deadline_ts - time.monotonic(), 0.0))
                if pause > 0:
                    time.sleep(pause)
                continue
            hedged_any = hedged_any or hedged
            latency_ms = (time.monotonic() - t0) * 1000.0
            self._record_success(meta["replica"], latency_ms)
            with self._lock:
                self.counters["served"] += 1
                tap = self._deploy
            role = None
            if tap is not None:
                role = tap.role(meta["replica"])
                (tap.lat_canary if role == "canary"
                 else tap.lat_control).observe(latency_ms)
                with self._lock:
                    tap.served[role] += 1
                if role == "control":
                    # parity sampling: mirror a fraction of control-served
                    # requests onto a canary replica and compare outputs
                    self._maybe_mirror(tap, x, value, deadline_ms, tenant)
            self._note_tenant(tenant, "served")
            return RouterResponse(
                value, meta["replica"], meta.get("params_step"),
                attempts, hedged_any,
                round((time.monotonic() - t0) * 1000.0, 3),
                deploy_role=role)
        # deadline budget exhausted across retries
        late_ms = max(time.monotonic() - deadline_ts, 0.0) * 1000.0
        err = DeadlineExceeded("router_budget", late_ms,
                               tier="retry_budget", tenant=tenant)
        err.__cause__ = last_exc
        self._note_tenant(tenant, "failures")
        get_journal().event("router_budget_exhausted",
                            attempts=attempts, tenant=tenant,
                            last_error=type(last_exc).__name__
                            if last_exc else None)
        raise err

    # -- decode streams (serving/decode.py) ------------------------------
    def decode(self, tokens, max_new_tokens=None, deadline_ms=None,
               priority=0, tenant=None):
        """Route one autoregressive stream to a replica's continuous
        batcher; returns the generated token list."""
        return self.decode_call(tokens, max_new_tokens=max_new_tokens,
                                deadline_ms=deadline_ms, priority=priority,
                                tenant=tenant).value

    def decode_call(self, tokens, max_new_tokens=None, deadline_ms=None,
                    priority=0, tenant=None) -> RouterResponse:
        """Decode through the same placement/retry/breaker machinery as
        :meth:`call`, with one deliberate difference: NO hedging.  A
        decode stream is stateful on its replica (it occupies a KV slot
        and generates token by token), so a hedged twin would double-
        generate and double-occupy slots for the whole stream, not just
        one batch — the tail-latency lever for decode is the slot pool
        and per-step deadline, not a second copy.  ``SlotsExhausted``
        is retryable: a replica with a full slot pool is a placement
        miss, and the retry loop moves the stream to another replica
        (feeding the breaker nothing — busy is not broken)."""
        cfg = self.config
        if deadline_ms is None:
            deadline_ms = cfg.default_deadline_ms
        deadline_ts = time.monotonic() + deadline_ms / 1000.0
        t0 = time.monotonic()
        with self._lock:
            self.counters["requests"] += 1
        self._note_tenant(tenant, "requests")
        with _trace.span("router_decode", priority=priority,
                         tenant=tenant):
            self._admit(priority)
            delays = backoff_delays(cfg.retries, cfg.retry_base_s,
                                    cfg.retry_max_s, cfg.retry_jitter)
            tried: set = set()
            attempts = 0
            last_exc = None
            for attempt in range(cfg.retries + 1):
                remaining = deadline_ts - time.monotonic()
                if remaining <= 0:
                    break
                state = self._pick(exclude=tried, tenant=tenant)
                if state is None and tried:
                    state = self._pick(exclude=set(), tenant=tenant)
                if state is None:
                    self._note_tenant(tenant, "failures")
                    self._shed("no_capacity", priority, tenant=tenant)
                tried.add(state.id)
                attempts += 1
                _atomic.trip("router_attempt", state.id)
                with self._lock:
                    self.counters["attempts"] += 1
                    self._attempt_counts[state.id] = \
                        self._attempt_counts.get(state.id, 0) + 1
                replica = self.pool.replicas[state.id]
                try:
                    with _trace.span("router_attempt", replica=state.id,
                                     tenant=tenant, op="decode"):
                        value, meta = replica.decode(
                            tokens, max_new_tokens=max_new_tokens,
                            deadline_ms=remaining * 1000.0,
                            tenant=tenant)
                except RequestError as exc:
                    last_exc = exc
                    self._record_failure(state.id, exc)
                    if not getattr(exc, "retryable", False) \
                            or attempt >= cfg.retries:
                        self._note_tenant(tenant, "failures")
                        raise
                    with self._lock:
                        self.counters["retries"] += 1
                    get_journal().event(
                        "router_retry", replica=state.id, op="decode",
                        attempt=attempt + 1, error=type(exc).__name__,
                        detail=str(exc)[:200], tenant=tenant)
                    pause = min(delays[attempt],
                                max(deadline_ts - time.monotonic(), 0.0))
                    if pause > 0:
                        time.sleep(pause)
                    continue
                self._record_success(meta["replica"],
                                     (time.monotonic() - t0) * 1000.0)
                with self._lock:
                    self.counters["served"] += 1
                self._note_tenant(tenant, "served")
                return RouterResponse(
                    value, meta["replica"], meta.get("params_step"),
                    attempts, False,
                    round((time.monotonic() - t0) * 1000.0, 3))
            late_ms = max(time.monotonic() - deadline_ts, 0.0) * 1000.0
            err = DeadlineExceeded("router_budget", late_ms,
                                   tier="retry_budget", tenant=tenant)
            err.__cause__ = last_exc
            self._note_tenant(tenant, "failures")
            get_journal().event("router_budget_exhausted", op="decode",
                                attempts=attempts, tenant=tenant,
                                last_error=type(last_exc).__name__
                                if last_exc else None)
            raise err

    # -- per-tenant bookkeeping ------------------------------------------
    _TENANT_CAP = 256          # LRU bound: tenant names arrive on the
                               # request path, so this registry must not
                               # grow one entry per novel string forever

    def _note_tenant(self, tenant, key):
        if tenant is None:
            return
        with self._lock:
            row = self._tenant_counts.get(tenant)
            if row is None:
                row = self._tenant_counts[tenant] = {
                    "requests": 0, "served": 0, "failures": 0}
                while len(self._tenant_counts) > self._TENANT_CAP:
                    self._tenant_counts.pop(
                        next(iter(self._tenant_counts)))
            else:
                self._tenant_counts.move_to_end(tenant)
            row[key] += 1

    # -- admission tiers -------------------------------------------------
    def _shed(self, tier, priority, usable=0, total=None, tenant=None):
        total = len(self.pool.replicas) if total is None else total
        key = "no_capacity" if tier == "no_capacity" else "shed"
        with self._lock:
            self.counters[key] += 1
        get_journal().event("router_shed", tier=tier, priority=priority,
                            usable=usable, total=total, tenant=tenant)
        raise ServerOverloaded(usable, total, tier=tier, tenant=tenant)

    def _admit(self, priority):
        """Graceful degradation: when live+ready capacity is below the
        floor, shed lowest-priority first (only priority-0 traffic is
        admitted) instead of failing every class uniformly."""
        floor = self.config.capacity_floor
        if floor <= 0 or priority <= 0:
            return
        usable = sum(1 for s in self.pool.view()
                     if s.alive and s.ready
                     and self._breaker(s.id).state != OPEN)
        total = max(len(self.pool.replicas), 1)
        if usable / total < floor:
            self._shed("capacity_floor", priority, usable, total)

    # -- placement -------------------------------------------------------
    def _breaker(self, rid) -> _Breaker:
        br = self._breakers.get(rid)
        if br is None:
            br = self._breakers.setdefault(rid, _Breaker())
        return br

    def _transition(self, rid, br, to, reason):
        """Mutate one breaker (caller holds ``_lock``) and return the
        journal payload.  The journal write is file I/O every router
        thread would serialize behind, so callers emit the payload via
        :meth:`_emit_breaker` AFTER releasing the lock (G15) — the
        pre-fix shape journaled from inside the placement/counter
        critical sections."""
        frm, br.state = br.state, to
        if to == OPEN:
            br.opened_t = time.monotonic()
            br.probing = False
            self.counters["breaker_opens"] += 1
        if to == CLOSED:
            br.failures = 0
            br.probing = False
            if frm == HALF_OPEN:
                self.counters["readmissions"] += 1
        br.reason = reason
        return {"replica": rid, "frm": frm, "to": to, "reason": reason,
                "failures": br.failures}

    @staticmethod
    def _emit_breaker(events) -> None:
        """Journal deferred breaker transitions (outside every lock)."""
        for ev in events:
            get_journal().event("router_breaker", **ev)

    def _allow(self, rid, alive, ready, events) -> bool:
        """Breaker gate for one candidate (caller holds ``_lock``;
        transition payloads append to ``events`` for post-lock
        emission).  Only a heartbeat STALL opens the breaker here — a
        merely not-ready replica (draining, mid-restart) is out of
        rotation without being declared broken.  The half-open probe
        slot is claimed by ``_pick`` for the replica actually SELECTED,
        never during candidate enumeration."""
        br = self._breaker(rid)
        if br.state == CLOSED:
            if not alive:
                events.append(
                    self._transition(rid, br, OPEN, "heartbeat_stall"))
                return False
            return ready
        if not alive or not ready:
            return False
        if br.state == OPEN:
            if br.opened_t is not None and time.monotonic() - br.opened_t \
                    >= self.config.breaker_cooldown_s:
                events.append(self._transition(rid, br, HALF_OPEN,
                                               "cooldown_elapsed"))
            else:
                return False
        # half-open: admissible only while no probe is in flight
        return not br.probing

    @staticmethod
    def _serves_tenant(state, tenant) -> bool:
        """Tenant-aware placement gate: a fleet replica advertises its
        tenants (+ quarantine state) in the beacon; route a tenant
        request only where the tenant is present and un-quarantined.
        Replicas without a tenant table are tenant-agnostic (a
        single-tenant worker behind a fleet-free pool)."""
        if tenant is None or state.tenants is None:
            return True
        row = state.tenants.get(str(tenant))
        if row is None:
            return False
        return (row or {}).get("state") != "quarantined"

    def _pick(self, exclude, tenant=None):
        """Least-loaded among live + ready + breaker-admitted replicas
        that serve the tenant (queue depth from the ledger; ties rotate
        round-robin)."""
        view = self.pool.view()            # ledger file I/O: OUTSIDE the
        candidates = []                    # lock — a slow shared FS must
        events: list = []                  # not stall every router thread
        with self._lock:
            for s in view:
                if s.id in exclude:
                    continue
                if not self._serves_tenant(s, tenant):
                    continue
                if not self._allow(s.id, s.alive, s.ready, events):
                    continue
                candidates.append(s)
        self._emit_breaker(events)         # journal I/O: after release
        if not candidates:
            return None
        depth = min(s.queue_depth for s in candidates)
        tied = sorted((s for s in candidates if s.queue_depth == depth),
                      key=lambda s: s.id)
        pick = tied[next(self._rr) % len(tied)]
        with self._lock:
            br = self._breaker(pick.id)
            if br.state == HALF_OPEN:
                br.probing = True          # this dispatch IS the probe
        return pick

    def _record_failure(self, rid, exc):
        with self._lock:
            self.counters["failures"] += 1
            tap = self._deploy
            if tap is not None:
                tap.failures[tap.role(rid)] += 1
        # busy is not broken, and a non-retryable caller error (shape
        # reject, cancelled hedge) says nothing about replica health;
        # deadline misses DO count — a replica too slow to answer in
        # budget is exactly what the breaker should take out of rotation
        harmless = isinstance(exc, ServerOverloaded) or (
            not getattr(exc, "retryable", True)
            and not isinstance(exc, DeadlineExceeded))
        if harmless:
            self._release_probe(rid)
            return
        br = self._breaker(rid)
        events: list = []
        with self._lock:
            br.failures += 1
            if br.state == HALF_OPEN:
                events.append(
                    self._transition(rid, br, OPEN, "probe_failed"))
            elif br.state == CLOSED \
                    and br.failures >= self.config.breaker_k:
                events.append(self._transition(rid, br, OPEN,
                                               "consecutive_failures"))
        self._emit_breaker(events)

    def _record_success(self, rid, latency_ms):
        br = self._breaker(rid)
        events: list = []
        with self._lock:
            if br.state == HALF_OPEN:
                events.append(
                    self._transition(rid, br, CLOSED, "probe_succeeded"))
            else:
                br.failures = 0
            lat = self._latency.get(rid)
            if lat is None:
                lat = self._latency.setdefault(
                    rid, LatencySummary(f"router_{rid}_ms"))
        self._emit_breaker(events)
        lat.observe(latency_ms)

    def _release_probe(self, rid):
        br = self._breaker(rid)
        with self._lock:
            if br.state == HALF_OPEN:
                br.probing = False

    # -- attempts + hedging ----------------------------------------------
    def _hedge_delay_s(self, rid):
        cfg = self.config
        if cfg.hedge_ms <= 0:
            return None
        delay_ms = cfg.hedge_ms
        lat = self._latency.get(rid)
        if lat is not None and lat.count >= cfg.hedge_min_samples:
            p99 = lat.percentile(99)
            if p99 is not None:
                delay_ms = max(delay_ms, p99 * cfg.hedge_p99_factor)
        return delay_ms / 1000.0

    def _dispatch(self, state, x, budget_s, cancel, tenant=None):
        """One attempt on one replica (runs in the caller thread or a
        hedge thread).  The trip site is the slow-replica chaos seam —
        path carries the replica id so ``faults.slow_call`` can target
        one replica."""
        _atomic.trip("router_attempt", state.id)
        with self._lock:
            self.counters["attempts"] += 1
            self._attempt_counts[state.id] = \
                self._attempt_counts.get(state.id, 0) + 1
            tap = self._deploy
        if tap is not None and state.id in tap.canary:
            # distinct chaos seam from router_attempt: faults.slow_canary
            # targets exactly canary-bound dispatches (live or mirrored)
            _atomic.trip("deploy_canary", state.id)
        replica = self.pool.replicas[state.id]
        deadline_ms = budget_s * 1000.0
        with _trace.span("router_attempt", replica=state.id,
                         tenant=tenant):
            return replica.predict(x, deadline_ms, cancel=cancel,
                                   tenant=tenant)

    def _attempt(self, state, x, budget_s, attempt_no, tenant=None):
        """Primary attempt with optional hedging; returns
        ``(value, meta, hedged)`` or raises the decisive error."""
        hedge_s = self._hedge_delay_s(state.id)
        if hedge_s is None or hedge_s >= budget_s:
            value, meta = self._dispatch(state, x, budget_s, None,
                                         tenant)
            return value, meta, False

        results = _queue.Queue(maxsize=4)    # bounded: <= 2 writers
        cancels = {}
        ctx = _trace.current_context()
        t_start = time.monotonic()

        def run(st):
            # arm threads re-anchor under the request span explicitly
            # (contextvars don't cross threads; docs/observability.md)
            # — entered as the thread's current span so the nested
            # router_attempt span AND the wire frame's propagated trace
            # context both join the request's trace, and the span ends
            # on every exception path (the G20 leaked-open-span shape)
            with _trace.start_span("router_hedge_arm", parent=ctx,
                                   replica=st.id) as arm:
                try:
                    remaining = budget_s - (time.monotonic() - t_start)
                    v, m = self._dispatch(st, x, max(remaining, 0.01),
                                          cancels[st.id], tenant)
                    results.put_nowait((st, None, v, m))
                    arm.set_attrs(status="ok")
                except BaseException as e:
                    results.put_nowait((st, e, None, None))
                    arm.set_attrs(status=type(e).__name__)

        def launch(st):
            cancels[st.id] = threading.Event()
            threading.Thread(target=run, args=(st,), daemon=True,
                             name=f"mxtpu-router-attempt-{st.id}").start()

        launch(state)
        in_flight = {state.id: state}
        hedged = False
        try:
            first = results.get(timeout=min(hedge_s, budget_s))
        except _queue.Empty:
            first = None
        if first is None:
            hedge_state = self._pick(exclude=set(in_flight),
                                     tenant=tenant)
            if hedge_state is not None:
                hedged = True
                with self._lock:
                    self.counters["hedges"] += 1
                get_journal().event(
                    "router_hedge", primary=state.id,
                    hedge=hedge_state.id,
                    delay_ms=round(hedge_s * 1000.0, 1))
                launch(hedge_state)
                in_flight[hedge_state.id] = hedge_state
        # first response wins; a failed response yields to the survivor
        last_exc = None
        while in_flight:
            if first is None:
                remaining = budget_s - (time.monotonic() - t_start)
                if remaining <= 0:
                    break
                try:
                    first = results.get(timeout=remaining)
                except _queue.Empty:
                    break
            st, exc, value, meta = first
            first = None
            in_flight.pop(st.id, None)
            if exc is None:
                for rid, ev in cancels.items():
                    if rid != st.id:
                        ev.set()           # loser cancelled at dequeue
                for rid in in_flight:
                    # the loser's result is never consumed — if it held
                    # its replica's half-open probe slot, free it or the
                    # replica is silently out of rotation forever
                    self._release_probe(rid)
                if hedged and st.id != state.id:
                    with self._lock:
                        self.counters["hedge_wins"] += 1
                return value, meta, hedged
            last_exc = exc
            last_exc._replica = st.id
            if in_flight and isinstance(exc, RequestError):
                # the loser's failure still feeds its replica's breaker
                # while the survivor keeps running
                self._record_failure(st.id, exc)
        for ev in cancels.values():
            ev.set()                       # nobody won: recall them all
        for rid in in_flight:              # unresolved attempts: free any
            self._release_probe(rid)       # probe slot they were holding
        if last_exc is not None:
            last_exc._hedged = hedged
            raise last_exc
        late_ms = max((time.monotonic() - t_start) - budget_s, 0) * 1000.0
        err = DeadlineExceeded("router_wait", late_ms)
        err._hedged = hedged
        raise err

    # -- canary deployment tap (serving/deploy.py) -----------------------
    def set_deploy(self, canary, mirror_fraction=0.0, rtol=1e-5,
                   atol=1e-6) -> "_DeployTap":
        """Install the canary/control tap for one deployment: responses
        gain ``deploy_role``, canary-bound dispatches trip the
        ``deploy_canary`` chaos site, and (``mirror_fraction`` > 0) a
        deterministic 1-in-N sample of control-served requests is
        mirrored onto a canary replica and compared tolerance-gated.
        One deploy at a time — installing over a live tap is a bug in
        the caller (the pool's deploy ownership already serializes)."""
        tap = _DeployTap(canary, mirror_fraction, rtol, atol)
        with self._lock:
            self._deploy = tap
        return tap

    def clear_deploy(self) -> None:
        with self._lock:
            self._deploy = None

    def deploy_stats(self):
        """One consistent snapshot of the live tap (None outside a
        deploy) — the DeployController's gate-evaluation source."""
        with self._lock:
            tap = self._deploy
            if tap is None:
                return None
            out = {"canary": sorted(tap.canary),
                   "served": dict(tap.served),
                   "failures": dict(tap.failures),
                   "mirrors": tap.mirrors,
                   "mirror_mismatch": tap.mirror_mismatch,
                   "mirror_errors": tap.mirror_errors,
                   "mirror_skipped": tap.mirror_skipped}
        for arm, lat in (("canary", tap.lat_canary),
                         ("control", tap.lat_control)):
            out[f"{arm}_count"] = lat.count
            out[f"{arm}_p99_ms"] = lat.percentile(99) if lat.count else None
        return out

    def _maybe_mirror(self, tap, x, expect, deadline_ms, tenant):
        """Sampling + in-flight-cap gate for one mirror candidate; the
        actual duplicate dispatch runs on a bounded daemon thread so the
        client never pays the second attempt's latency."""
        with self._lock:
            if tap is not self._deploy or tap.mirror_every <= 0:
                return
            tap._n += 1
            if tap._n % tap.mirror_every:
                return
            if tap.mirror_inflight >= tap.max_inflight:
                tap.mirror_skipped += 1    # bounded, never queued: a slow
                return                     # canary must not pile threads
            tap.mirror_inflight += 1
        ctx = _trace.current_context()
        threading.Thread(
            target=self._run_mirror,
            args=(tap, x, expect, deadline_ms, tenant, ctx),
            daemon=True, name="mxtpu-router-mirror").start()

    def _run_mirror(self, tap, x, expect, deadline_ms, tenant, ctx):
        """One mirrored parity probe: duplicate the request onto an
        alive+ready canary replica, compare against the control answer
        bit-wise within (rtol, atol).  A mismatch journals
        ``deploy_mirror_mismatch`` (trace-correlated under the request
        span); a transport/predict failure counts as a mirror error —
        the gate reads both."""
        try:
            with _trace.start_span("deploy_mirror", parent=ctx) as sp:
                view = self.pool.view()
                cands = [s for s in view if s.id in tap.canary
                         and s.alive and s.ready]
                if not cands:
                    with self._lock:
                        tap.mirrors += 1
                        tap.mirror_errors += 1
                    sp.set_attrs(status="no_canary")
                    return
                st = cands[next(self._rr) % len(cands)]
                _atomic.trip("deploy_canary", st.id)
                try:
                    got, meta = self.pool.replicas[st.id].predict(
                        x, deadline_ms, cancel=None, tenant=tenant)
                except Exception as e:
                    with self._lock:
                        tap.mirrors += 1
                        tap.mirror_errors += 1
                    sp.set_attrs(status=type(e).__name__)
                    return
                a = np.asarray(got, dtype=np.float64)
                b = np.asarray(expect, dtype=np.float64)
                ok = a.shape == b.shape and bool(
                    np.allclose(a, b, rtol=tap.rtol, atol=tap.atol))
                with self._lock:
                    tap.mirrors += 1
                    if not ok:
                        tap.mirror_mismatch += 1
                sp.set_attrs(status="ok" if ok else "mismatch",
                             replica=st.id)
                if not ok:
                    delta = (float(np.max(np.abs(a - b)))
                             if a.shape == b.shape else None)
                    get_journal().event(
                        "deploy_mirror_mismatch", replica=st.id,
                        step=meta.get("params_step"),
                        max_abs_delta=delta)
        finally:
            with self._lock:
                tap.mirror_inflight -= 1

    # -- reporting -------------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            counters = dict(self.counters)
            attempts = dict(self._attempt_counts)
            tenants = {t: dict(row)
                       for t, row in self._tenant_counts.items()}
        per_replica = {}
        for rid in self.pool.replicas:
            br = self._breakers.get(rid)
            lat = self._latency.get(rid)
            per_replica[rid] = {
                "attempts": attempts.get(rid, 0),
                "breaker": br.state if br else CLOSED,
                "p99_ms": lat.percentile(99) if lat is not None
                and lat.count else None}
        out = {**counters, "replicas": per_replica}
        if tenants:
            out["tenants"] = tenants
        deploy = self.deploy_stats()
        if deploy is not None:
            out["deploy"] = deploy
        return out

    def metrics_text(self) -> str:
        """Prometheus exposition: the router counters/breaker/latency
        mirrored into the process default registry at call time (gauge
        mirrors, same contract as ``Server.metrics_text``)."""
        from ..observability import metrics as _m
        reg = _m.default_registry()
        st = self.stats()
        ev = reg.gauge("mxnet_tpu_router_events",
                       "router counters (cumulative)", ("event",))
        for k, v in st.items():
            if k not in ("replicas", "tenants", "deploy"):
                ev.labels(event=k).set(v)
        dep = st.get("deploy")
        if dep:
            dg = reg.gauge("mxnet_tpu_deploy_arm",
                           "live canary-vs-control stats for the active "
                           "deployment", ("arm", "stat"))
            for arm in ("canary", "control"):
                dg.labels(arm=arm, stat="served").set(dep["served"][arm])
                dg.labels(arm=arm, stat="failures").set(
                    dep["failures"][arm])
                if dep.get(f"{arm}_p99_ms") is not None:
                    dg.labels(arm=arm, stat="p99_ms").set(
                        dep[f"{arm}_p99_ms"])
            mg = reg.gauge("mxnet_tpu_deploy_mirrors",
                           "mirrored parity probes for the active "
                           "deployment", ("outcome",))
            mg.labels(outcome="total").set(dep["mirrors"])
            mg.labels(outcome="mismatch").set(dep["mirror_mismatch"])
            mg.labels(outcome="error").set(dep["mirror_errors"])
        if st.get("tenants"):
            tev = reg.gauge("mxnet_tpu_router_tenant_events",
                            "per-tenant router counters (cumulative)",
                            ("tenant", "event"))
            for t, row in st["tenants"].items():
                for k, v in row.items():
                    tev.labels(tenant=t, event=k).set(v)
        brg = reg.gauge("mxnet_tpu_router_breaker_state",
                        "per-replica breaker (0 closed, 1 half-open, "
                        "2 open)", ("replica",))
        att = reg.gauge("mxnet_tpu_router_attempts_total",
                        "attempts routed per replica", ("replica",))
        p99 = reg.gauge("mxnet_tpu_router_replica_p99_ms",
                        "per-replica end-to-end p99 as seen by the "
                        "router", ("replica",))
        for rid, row in st["replicas"].items():
            brg.labels(replica=rid).set(_BREAKER_CODE[row["breaker"]])
            att.labels(replica=rid).set(row["attempts"])
            if row["p99_ms"] is not None:
                p99.labels(replica=rid).set(row["p99_ms"])
        return reg.prometheus_text()

    def stop(self) -> None:
        get_journal().event("router_stop", **{
            k: v for k, v in self.stats().items() if k != "replicas"})

"""Tenant fleet — N model families on one serving worker, isolated.

Production traffic is never one model (ROADMAP item 2): a :class:`Fleet`
generalizes :class:`~.server.Server` from one predictor family to a
**tenant registry** — each tenant is a model (any Block/HybridBlock/
imported SymbolBlock), its own commit root (:class:`~.reload.ParamStore`
per tenant), and an SLO class — multiplexed on the SAME bounded queue,
worker thread, and compiled-predictor cache.  Tenants hot add/remove/
reload at runtime; batches group per ``(tenant, feature_key)`` so two
tenants never share an executable (pjit/named-sharding inside the
predictor stays the substrate — no application-code change per tenant).

The robustness contract (docs/serving.md failure matrix):

- **SLO-classed admission** — each tenant's class carries a priority,
  a deadline floor, and a token-bucket rate budget.  Shedding is
  per-tenant-class FIRST, never global: a lower-priority class loses
  queue room as depth grows (its share of the bound halves per
  priority tier) while priority-0 tenants keep the full queue; a
  tenant over its rate budget sheds only itself.  Every
  ``ServerOverloaded``/``DeadlineExceeded`` carries the tenant + tier.
- **Per-tenant fault domains** — a tenant whose committed checkpoint
  fails CRC, whose shapes reject, or whose predictor throws
  non-transient errors feeds a per-tenant breaker; at the threshold the
  tenant is **quarantined** (structured :class:`TenantQuarantined` at
  admission, queued requests resolved at dequeue without spending batch
  slots).  After a cooldown the breaker goes half-open: ONE probe
  request re-admits (success → admitted) or re-quarantines.  Every
  transition is journaled (``tenant_quarantine``) with trace ids.
- **Weight paging** — at most ``max_hot_tenants`` tenants keep device
  parameters + compiled predictors; a cold tenant's parameters live in
  a host-RAM snapshot and page onto the device on demand (LRU evicts
  the stalest hot tenant, its executables dropped from the bounded
  ``PredictorCache``).  Page-in cost is journaled (``tenant_page_in``)
  and excluded from the batch's ``exec_ms`` so it can never masquerade
  as a hot tenant's tail latency.

Chaos seam: every tenant predictor call trips the ``serving_tenant``
site with the tenant name as its path, so ``faults.slow_call``/
``io_error``/``tenant_poison`` target ONE tenant, composing with the
existing ``serving_predict``/``router_attempt`` seams.
"""
from __future__ import annotations

import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field

import numpy as np

from ..diagnostics.journal import get_journal
from ..metric import LatencySummary
from ..observability import trace as _trace
from ..resilience import atomic as _atomic
from .batcher import RequestError, ServerOverloaded
from .reload import ParamStore
from .server import (Server, ServerConfig, _end_span, _env_float,
                     _env_int)

__all__ = ["Fleet", "FleetConfig", "SLOClass", "TenantQuarantined",
           "SLO_CLASSES"]

ADMITTED, QUARANTINED, HALF_OPEN = "admitted", "quarantined", "half_open"


class TenantQuarantined(RequestError):
    """The tenant's per-tenant breaker is open: its checkpoint, shapes,
    or predictor faulted past the threshold and the tenant is out of
    admission until a half-open probe succeeds.  Not retryable — the
    fault is the tenant's own artifact (shared commit root / model),
    so another replica would fail the same way."""

    retryable = False

    def __init__(self, tenant, reason, state=QUARANTINED):
        super().__init__(
            f"tenant {tenant!r} quarantined ({reason}) — its own "
            "checkpoint/shape/predictor faults tripped the per-tenant "
            "breaker; other tenants are unaffected")
        self.tenant = tenant
        self.reason = reason
        self.state = state


@dataclass(frozen=True)
class SLOClass:
    """One admission class: ``priority`` 0 is highest (keeps the full
    queue bound; each tier below halves its share), ``deadline_floor_ms``
    lifts any shorter requested deadline (the class's latency promise is
    also its minimum patience), ``rate_rps``/``burst`` arm a per-tenant
    token bucket (0 = unlimited)."""

    name: str = "standard"
    priority: int = 0
    deadline_floor_ms: float = 0.0
    rate_rps: float = 0.0
    burst: float = 8.0


SLO_CLASSES = {
    "gold": SLOClass("gold", priority=0),
    "silver": SLOClass("silver", priority=1),
    "bronze": SLOClass("bronze", priority=2),
}


@dataclass
class FleetConfig(ServerConfig):
    """Fleet knobs on top of :class:`ServerConfig` (docs/serving.md;
    ``MXNET_TPU_TENANT_*`` env vars set fleet-wide defaults)."""

    max_hot_tenants: int = field(default_factory=lambda: _env_int(
        "MXNET_TPU_TENANT_MAX_HOT", 4))
    tenant_breaker_k: int = field(default_factory=lambda: _env_int(
        "MXNET_TPU_TENANT_BREAKER_K", 3))
    tenant_cooldown_s: float = field(default_factory=lambda: _env_float(
        "MXNET_TPU_TENANT_COOLDOWN_S", 5.0))


class _TokenBucket:
    """Per-tenant rate budget: ``rate_rps`` tokens/s up to ``burst``;
    an admission costs one token.  0 rate = unlimited."""

    __slots__ = ("rate", "burst", "tokens", "stamp")

    def __init__(self, rate_rps, burst):
        self.rate = float(rate_rps)
        self.burst = max(float(burst), 1.0)
        self.tokens = self.burst
        self.stamp = time.monotonic()

    def allow(self) -> bool:
        if self.rate <= 0:
            return True
        now = time.monotonic()
        self.tokens = min(self.burst,
                          self.tokens + (now - self.stamp) * self.rate)
        self.stamp = now
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return True
        return False


class TenantState:
    """One tenant's fault domain: model handle (device block when hot,
    host-RAM parameter snapshot when cold), ParamStore, SLO class,
    breaker, rate bucket, counters, and latency summary."""

    def __init__(self, name, factory, store, slo):
        self.name = name
        self.factory = factory
        self.store = store
        self.slo = slo
        self.block = None              # device-resident only while hot
        self.host_params = None        # name -> np.ndarray cold snapshot
        self.params_step = None
        self.last_reload_check = None
        self.bucket = _TokenBucket(slo.rate_rps, slo.burst)
        self.latency = LatencySummary(f"tenant_{name}_ms")
        # padded shapes this tenant served while hot, LRU order — the
        # page-in executable-restore set (bounded: grid cells, capped)
        self.warm_shapes: "OrderedDict[tuple, bool]" = OrderedDict()
        # breaker
        self.state = ADMITTED
        self.failures = 0
        self.opened_t = None
        self.probing = False
        self.reason = None
        self.removed = False
        self.reload_forced = False     # reload_tenant() -> worker applies
        self.counters = {"accepted": 0, "served": 0, "shed": 0,
                         "rejected_shape": 0, "quarantine_rejects": 0,
                         "errors": 0, "deadline_miss": 0, "reloads": 0,
                         "page_ins": 0, "page_outs": 0, "quarantines": 0,
                         "readmissions": 0}


class Fleet(Server):
    """Multi-tenant serving engine: one worker thread, one bounded
    queue, N isolated tenant families.  ``submit(x, tenant=...)`` is
    the whole client-side difference from a single-tenant Server."""

    def __init__(self, config=None, ctx=None):
        super().__init__(block=None, config=config or FleetConfig(),
                         ctx=ctx)
        if not isinstance(self.config, FleetConfig):
            # a plain ServerConfig still works: fleet knobs fall back
            # to the env/default values
            base, self.config = self.config, FleetConfig()
            for f in base.__dataclass_fields__:
                setattr(self.config, f, getattr(base, f))
        self.tenants: "OrderedDict[str, TenantState]" = OrderedDict()
        self._hot: "OrderedDict[str, bool]" = OrderedDict()  # LRU, newest last
        self._tlock = threading.RLock()
        self._group_key = lambda r: (r.tenant, r.key)

    # -- tenant registry (hot add/remove/reload) -------------------------
    def add_tenant(self, name, factory=None, block=None, ckpt_root=None,
                   slo=None, params_file=None) -> "Fleet":
        """Register (or hot-add, while serving) one tenant.  ``factory``
        builds its initialized block on page-in; a prebuilt ``block``
        is wrapped into a factory and starts hot-eligible.  ``slo`` is
        an :class:`SLOClass` or a preset name (``gold|silver|bronze``,
        default gold)."""
        name = str(name)
        if factory is None and block is None:
            raise ValueError(f"tenant {name!r} needs factory= or block=")
        if factory is None:
            factory = lambda: block                      # noqa: E731
        if isinstance(slo, str):
            slo = SLO_CLASSES[slo]
        slo = slo or SLO_CLASSES["gold"]
        store = ParamStore(ckpt_root, params_file=params_file) \
            if ckpt_root else None
        with self._tlock:
            if name in self.tenants and not self.tenants[name].removed:
                raise ValueError(f"tenant {name!r} already registered")
            self.tenants[name] = TenantState(name, factory, store, slo)
        get_journal().event("tenant_add", tenant=name, slo=slo.name,
                            priority=slo.priority, ckpt_root=ckpt_root,
                            rate_rps=slo.rate_rps)
        return self

    def remove_tenant(self, name) -> None:
        """Hot-remove: admission rejects immediately; queued requests
        are resolved structurally at dequeue; device parameters and
        compiled predictors are dropped."""
        name = str(name)
        with self._tlock:
            ts = self.tenants.pop(name, None)
            if ts is None:
                raise KeyError(f"unknown tenant {name!r}")
            ts.removed = True
            ts.block = None
            ts.host_params = None
            self._hot.pop(name, None)
        dropped = self.cache.drop_where(lambda k: k[0] == name)
        get_journal().event("tenant_remove", tenant=name,
                            predictors_dropped=dropped,
                            **ts.counters)

    def reload_tenant(self, name) -> None:
        """Request an immediate hot-reload poll for one tenant.  The
        reload is applied by the WORKER between batches (the hot-reload
        contract) — never on the caller's thread, where it could swap
        parameter arrays under a predictor that reads them per call
        (torn old/new mix).  A cold tenant picks up the newest valid
        step at page-in regardless."""
        with self._tlock:
            self.tenants[str(name)].reload_forced = True

    # -- admission (tenant hooks on Server.submit) -----------------------
    def _admit_tenant(self, tenant, payload):
        if tenant is None:
            err = RequestError("fleet requests must name a tenant "
                               "(submit(x, tenant=...))")
            err.retryable = False
            raise err
        events: list = []
        shed = False
        try:
            with self._tlock:
                ts = self.tenants.get(str(tenant))
                if ts is None or ts.removed:
                    err = RequestError(f"unknown tenant {tenant!r} — "
                                       "not in this fleet's registry")
                    err.retryable = True   # another replica may serve it
                    err.tenant = tenant
                    raise err
                self._breaker_gate(ts, events)
                if not ts.bucket.allow():
                    ts.counters["shed"] += 1
                    self._release_probe(ts)
                    shed = True
        finally:
            # the quarantine gate raises THROUGH this admission path:
            # its transitions must journal either way, outside _tlock
            self._emit_quarantine(events)
        if shed:
            with self._lock:
                self.counters["shed"] += 1
            get_journal().event("serving_shed", tenant=ts.name,
                                tier="rate_budget",
                                rate_rps=ts.slo.rate_rps)
            raise ServerOverloaded(
                self._queue.qsize(), self.config.max_queue,
                tier="rate_budget", tenant=ts.name)
        return ts

    def _release_probe(self, ts):
        """A half-open probe that never reaches the device (shed,
        cancelled, deadline-missed) frees the probe slot — or the
        tenant would silently stay half-open forever."""
        if ts.state == HALF_OPEN:
            ts.probing = False

    def _breaker_gate(self, ts, events):
        """Quarantine gate at admission (caller holds ``_tlock``;
        transition payloads append to ``events`` for post-lock
        emission): a quarantined tenant rejects until the cooldown
        elapses, then goes half-open and admits exactly ONE probe."""
        if ts.state == ADMITTED:
            return
        if ts.state == QUARANTINED:
            cooldown = self.config.tenant_cooldown_s
            if ts.opened_t is None or \
                    time.monotonic() - ts.opened_t < cooldown:
                ts.counters["quarantine_rejects"] += 1
                raise TenantQuarantined(ts.name, ts.reason or "faulted")
            events.append(
                self._transition(ts, HALF_OPEN, "cooldown_elapsed"))
        # half-open: one probe in flight at a time.  A probe-slot
        # rejection is RETRYABLE — it says this replica's slot is busy,
        # not that the tenant's artifact is broken, so the router may
        # try a replica where the tenant is fully admitted.
        if ts.probing:
            ts.counters["quarantine_rejects"] += 1
            err = TenantQuarantined(ts.name, "probe in flight", HALF_OPEN)
            err.retryable = True
            raise err
        ts.probing = True

    def _transition(self, ts, to, reason):
        """Mutate one tenant breaker (caller holds ``_tlock``) and
        return the journal payload.  Emission happens via
        :meth:`_emit_quarantine` AFTER the lock releases (G15): the
        pre-fix shape opened a span and wrote the journal from inside
        the admission/bookkeeping critical sections, so one slow
        journal write stalled every tenant's admission."""
        frm, ts.state = ts.state, to
        if to == QUARANTINED:
            ts.opened_t = time.monotonic()
            ts.probing = False
            ts.counters["quarantines"] += 1
        if to == ADMITTED:
            ts.failures = 0
            ts.probing = False
            if frm == HALF_OPEN:
                ts.counters["readmissions"] += 1
        ts.reason = reason
        return {"tenant": ts.name, "frm": frm, "to": to,
                "reason": reason, "failures": ts.failures}

    @staticmethod
    def _emit_quarantine(events) -> None:
        """Journal deferred quarantine transitions (outside ``_tlock``).
        Each gets its own span (inheriting the request/batch trace when
        one is active, a fresh root otherwise) so the quarantine ->
        half-open -> re-admit trail is ALWAYS trace-correlated in the
        journal, whichever thread trips it."""
        for ev in events:
            attrs = {k: v for k, v in ev.items() if k != "failures"}
            with _trace.span("tenant_quarantine", **attrs):
                get_journal().event("tenant_quarantine", **ev)

    def _tenant_failure(self, ts, reason):
        """One breaker feed: shape reject, corrupt committed checkpoint,
        or non-transient predictor error.  K consecutive failures — or
        any failure while half-open — quarantine the tenant (only)."""
        events: list = []
        with self._tlock:
            ts.failures += 1
            if ts.state == HALF_OPEN:
                events.append(self._transition(
                    ts, QUARANTINED, f"probe_failed:{reason}"))
            elif ts.state == ADMITTED and \
                    ts.failures >= self.config.tenant_breaker_k:
                events.append(self._transition(ts, QUARANTINED, reason))
        self._emit_quarantine(events)

    def _note_reject(self, tenant):
        with self._tlock:
            ts = self.tenants.get(str(tenant)) \
                if tenant is not None else None
            if ts is None:
                return
            ts.counters["rejected_shape"] += 1
        self._tenant_failure(ts, "shape_reject")

    def _note_shed(self, tenant):
        with self._tlock:
            ts = self.tenants.get(str(tenant)) \
                if tenant is not None else None
            if ts is not None:
                ts.counters["shed"] += 1
                self._release_probe(ts)

    def _note_accept(self, tenant):
        with self._tlock:
            ts = self.tenants.get(str(tenant)) \
                if tenant is not None else None
            if ts is not None:
                ts.counters["accepted"] += 1

    def _note_cancelled(self, tenant):
        with self._tlock:
            ts = self.tenants.get(str(tenant)) \
                if tenant is not None else None
            if ts is not None:
                self._release_probe(ts)

    def _note_deadline_miss(self, tenant):
        with self._tlock:
            ts = self.tenants.get(str(tenant)) \
                if tenant is not None else None
            if ts is not None:
                ts.counters["deadline_miss"] += 1
                self._release_probe(ts)

    def _effective_deadline(self, deadline_ms, ts):
        if deadline_ms is None:
            deadline_ms = self.config.default_deadline_ms
        floor = ts.slo.deadline_floor_ms if ts is not None else 0.0
        if floor and deadline_ms is not None and 0 < deadline_ms < floor:
            return floor
        return deadline_ms

    def _class_gate(self, ts, tenant):
        """Shed per tenant CLASS first, never global: priority p keeps
        ``max_queue / 2**p`` of the shared bound, so as depth grows the
        lowest classes shed while priority-0 traffic still lands."""
        if ts is None or ts.slo.priority <= 0:
            return
        share = int(self.config.max_queue / (2 ** ts.slo.priority))
        depth = self._queue.qsize()
        if depth >= max(share, 1):
            with self._tlock:
                ts.counters["shed"] += 1
                self._release_probe(ts)
            with self._lock:
                self.counters["shed"] += 1
            get_journal().event("serving_shed", tenant=ts.name,
                                tier="class_budget", depth=depth,
                                share=share, priority=ts.slo.priority)
            raise ServerOverloaded(depth, share, tier="class_budget",
                                   tenant=ts.name)

    # -- worker-side sweeps ----------------------------------------------
    def _sweep_unroutable(self, pending):
        """Resolve queued requests of quarantined/removed tenants at
        dequeue — a poisoned flood must not keep spending batch slots
        (the half-open probe is the one exception)."""
        keep = []
        for req in pending:
            with self._tlock:
                ts = self.tenants.get(req.tenant)
                drop = None
                if ts is None or ts.removed:
                    drop = RequestError(
                        f"tenant {req.tenant!r} removed while queued")
                    drop.tenant = req.tenant
                elif ts.state == QUARANTINED:
                    ts.counters["quarantine_rejects"] += 1
                    drop = TenantQuarantined(ts.name,
                                             ts.reason or "faulted")
            if drop is None:
                keep.append(req)
            else:
                _end_span(req, "quarantined")
                req.set_error(drop)
        pending[:] = keep

    # -- predictor acquisition + weight paging ---------------------------
    def _acquire_predictor(self, batch, bucket, key):
        tenant = batch[0].tenant
        with self._tlock:
            ts = self.tenants.get(tenant)
            if ts is None or ts.removed:
                raise RequestError(f"tenant {tenant!r} removed")
            # remember the shape for the page-in executable restore
            # (LRU, capped at this tenant's SHARE of the predictor
            # cache — restoring a full cache_entries worth would evict
            # every other hot tenant's executables on one page-in)
            ts.warm_shapes[(bucket, key)] = True
            ts.warm_shapes.move_to_end((bucket, key))
            share = max(1, self.config.cache_entries
                        // max(self.config.max_hot_tenants, 1))
            while len(ts.warm_shapes) > share:
                ts.warm_shapes.popitem(last=False)
        block = self._page_in(ts)
        cache_key = (tenant, bucket, key, self._dtype.str)
        return self.cache.get(
            cache_key,
            lambda: self._build_predictor(block, bucket, key))

    def _page_in(self, ts):
        """Device-residency for one tenant (worker thread only): hot
        tenants just refresh LRU position; a cold tenant builds its
        block, restores the host-RAM snapshot, catches up to the newest
        valid committed step, and may page out the stalest hot tenant.
        The heavy build runs OUTSIDE ``_tlock`` so admission on other
        tenants never waits on a page-in; the cost is journaled so
        paging reads as paging — never as a hot tenant's tail latency
        (the batch's ``exec_ms`` excludes this window)."""
        with self._tlock:
            if ts.block is not None:
                self._hot[ts.name] = True
                self._hot.move_to_end(ts.name)
                return ts.block
            host = ts.host_params
        t0 = time.perf_counter()
        block = ts.factory()
        if host:
            from .. import ndarray as nd
            block.load_dict({k: nd.array(v) for k, v in host.items()},
                            ctx=self._ctx, ignore_extra=True)
        if self.plan is not None:
            # land THIS tenant's weights on the serving mesh before any
            # executable restore — the AOT entries were lowered against
            # the plan's shardings, so a single-device block here would
            # fault every restored predictor's first batch
            self.plan.place(block, site="tenant_page_in")
        doomed = []
        with self._tlock:
            if ts.removed:
                # remove_tenant raced the build: do not resurrect the
                # tenant into the hot set off a stale handle
                raise RequestError(f"tenant {ts.name!r} removed")
            ts.host_params = None
            ts.block = block
            ts.counters["page_ins"] += 1
            self._hot[ts.name] = True
            self._hot.move_to_end(ts.name)
            while len(self._hot) > max(self.config.max_hot_tenants, 1):
                cold_name, _ = self._hot.popitem(last=False)
                cold = self.tenants.get(cold_name)
                if cold is not None:
                    doomed.append(cold)
            hot_now = list(self._hot)
        # the device->host snapshot of evicted tenants runs OUTSIDE the
        # lock: a page-out must not stall admission on other tenants
        # (cold blocks are only ever touched by this worker thread)
        for cold in doomed:
            self._page_out(cold)
        self._reload_tenant(ts, force=True)    # newest valid step now
        cost_ms = round((time.perf_counter() - t0) * 1000.0, 2)
        # executable restore rides the AOT disk tier: the shapes this
        # tenant served while hot reload in milliseconds instead of
        # recompiling on its first post-page-in batches.  Timed
        # SEPARATELY from cost_ms (the weight-restore cost) so neither
        # masquerades as the other in the paging ledger.
        restored, restore_ms = self._restore_predictors(ts, block)
        get_journal().event(
            "tenant_page_in", tenant=ts.name, cost_ms=cost_ms,
            predictors_restored=restored, restore_ms=restore_ms,
            evicted=[c.name for c in doomed], hot=hot_now)
        return block

    def _restore_predictors(self, ts, block):
        """Reload this tenant's warm-shape executables from the AOT
        disk cache (worker thread, outside ``_tlock``).  Strictly
        LOAD-only: a disk miss (entry GC'd, store failed, ro store
        never seeded) is skipped, never compiled — proactively
        recompiling shapes that may not recur would turn paging into a
        compile storm that stalls every tenant's batches.  Without the
        disk tier this is a no-op for the same reason."""
        if self.aot is None:
            return 0, 0.0
        with self._tlock:
            shapes = list(ts.warm_shapes)
        t0 = time.perf_counter()
        restored = 0
        for bucket, key in shapes:
            pred = self.aot.load(block, (bucket,) + key, self._dtype,
                                 ctx=self._ctx, plan=self.plan)
            if pred is None:
                continue               # cold disk: first batch compiles
            _entry, hit = self.cache.get(
                (ts.name, bucket, key, self._dtype.str), lambda: pred)
            if not hit:
                restored += 1
        return restored, round((time.perf_counter() - t0) * 1000.0, 2)

    def _page_out(self, ts):
        """Snapshot parameters to host RAM, release the device block,
        and drop the tenant's compiled predictors.  Worker thread only;
        operates on a local block handle so a concurrent
        ``remove_tenant`` (which nulls ``ts.block``) can't trip it."""
        block = ts.block
        if block is None:
            return
        snap = {}
        for name, param in block._structural_names().items():
            try:
                arr = param.data(param.list_ctx()[0])
            except Exception:
                continue               # uninitialized: factory rebuilds it
            snap[name] = np.asarray(getattr(arr, "_data", arr))
        with self._tlock:
            if not ts.removed:
                ts.host_params = snap
            ts.block = None
            ts.counters["page_outs"] += 1
        dropped = self.cache.drop_where(lambda k: k[0] == ts.name)
        get_journal().event("tenant_page_out", tenant=ts.name,
                            n_params=len(snap),
                            predictors_dropped=dropped)

    # -- execution hooks --------------------------------------------------
    def _trip_sites(self, batch):
        _atomic.trip("serving_predict", self._metrics_id)
        # per-tenant chaos seam: path carries the tenant name so
        # faults.slow_call/io_error/tenant_poison target one tenant
        _atomic.trip("serving_tenant", batch[0].tenant)

    def _note_predict_error(self, batch, exc):
        ts = self.tenants.get(batch[0].tenant)
        if ts is None:
            return
        ts.counters["errors"] += len(batch)
        self._tenant_failure(ts, f"predictor_error:{type(exc).__name__}")

    def _batch_step(self, batch):
        ts = self.tenants.get(batch[0].tenant)
        return None if ts is None else ts.params_step

    def _batch_fields(self, batch):
        ts = self.tenants.get(batch[0].tenant)
        # the serving_batch record's p50/p95/p99 are FLEET-wide (the
        # shared latency summary); stamp this tenant's own p99 too so
        # the per-tenant report never attributes another tenant's tail
        # to this one
        p99 = None if ts is None or not ts.latency.count \
            else ts.latency.percentile(99)
        return {"tenant": batch[0].tenant, "tenant_p99_ms": p99}

    def _observe_latency(self, req, ms):
        self.latency.observe(ms)
        ts = self.tenants.get(req.tenant)
        if ts is not None:
            ts.latency.observe(ms)

    def _batch_succeeded(self, batch):
        ts = self.tenants.get(batch[0].tenant)
        if ts is None:
            return
        ts.counters["served"] += sum(1 for r in batch
                                     if r.error is None)
        events: list = []
        with self._tlock:
            if ts.state == HALF_OPEN:
                events.append(
                    self._transition(ts, ADMITTED, "probe_succeeded"))
            else:
                ts.failures = 0        # consecutive-failure semantics
                ts.probing = False
        self._emit_quarantine(events)

    # -- hot-reload (per tenant) ------------------------------------------
    def _maybe_reload(self, force=False):
        poll_s = self.config.reload_poll_s
        if poll_s < 0 and not force:
            return False
        now = time.monotonic()
        any_reloaded = False
        with self._tlock:
            states = [ts for ts in self.tenants.values()
                      if ts.store is not None and ts.block is not None]
        for ts in states:
            forced = ts.reload_forced
            if not force and not forced and \
                    ts.last_reload_check is not None and \
                    now - ts.last_reload_check < poll_s:
                continue
            ts.reload_forced = False
            any_reloaded |= self._reload_tenant(ts, force=force or forced)
        return any_reloaded

    def _reload_tenant(self, ts, force=False):
        """One tenant's poll/validate/apply cycle.  A corrupt committed
        candidate (CRC fail — ``ckpt_fallback`` journaled by the store)
        or an inapplicable dict (architecture drift) feeds THIS tenant's
        breaker and nobody else's."""
        store = ts.store
        if store is None or ts.block is None:
            return False
        ts.last_reload_check = time.monotonic()
        corrupt_before = store.corrupt_seen
        got = store.poll()
        corrupt_delta = store.corrupt_seen - corrupt_before
        for _ in range(corrupt_delta):
            self._tenant_failure(ts, "ckpt_corrupt")
        if got is None:
            return False
        step, loaded = got
        prev = ts.params_step
        loaded = {k: v for k, v in loaded.items()
                  if not k.startswith("__")}
        try:
            norm = self._check_reloadable_block(ts.block, loaded)
            if self.plan is not None:
                # sharded lane mirrors Server._maybe_reload: re-drop each
                # host entry onto the live array's NamedSharding so the
                # tenant's compiled predictors keep their placements
                self.plan.adopt_entries(
                    ts.block, {k: v.asnumpy() if hasattr(v, "asnumpy")
                               else np.asarray(v) for k, v in norm.items()})
            else:
                ts.block.load_dict(loaded, ctx=self._ctx,
                                   ignore_extra=True)
        except Exception as e:
            store.mark_bad(step, revert_to=prev)
            get_journal().event("serving_reload_failed", tenant=ts.name,
                                step=step, error=type(e).__name__,
                                detail=str(e)[:300])
            self._tenant_failure(ts, "ckpt_inapplicable")
            return False
        ts.params_step = step
        ts.counters["reloads"] += 1
        with self._lock:
            self.counters["reloads"] += 1
        get_journal().event("serving_reload", tenant=ts.name, step=step,
                            n_params=len(loaded), prev_step=prev)
        return True

    def _check_reloadable_block(self, block, loaded):
        """``Server._check_reloadable`` against an explicit block (the
        fleet has N of them)."""
        saved_block, self.block = self.block, block
        try:
            return self._check_reloadable(loaded)
        finally:
            self.block = saved_block

    # -- bucket-lattice prewarm (per tenant) -------------------------------
    def prewarm(self, shapes=None, tenants=None) -> dict:
        """Fleet prewarm: page in up to ``max_hot_tenants`` tenants
        (``tenants`` names them; default registration order) and build
        each one's batch-bucket × feature-shape lattice — disk loads
        when the AOT cache has the entries, compiles otherwise.  Runs
        on the caller's thread before the worker starts (the
        ``Server.start`` hook) or between batches."""
        shapes = shapes if shapes is not None else self.config.aot_prewarm
        t0 = time.perf_counter()
        with self._tlock:
            names = [str(n) for n in tenants] if tenants is not None \
                else list(self.tenants)
            names = names[:max(self.config.max_hot_tenants, 1)]
        warmed = loaded = compiled = 0
        skipped = []
        for name in names:
            with self._tlock:
                ts = self.tenants.get(name)
                if ts is None or ts.removed:
                    continue
            block = self._page_in(ts)
            for shape in shapes or ():
                key = self.grid.feature_key(tuple(shape))
                if key is None:
                    skipped.append(list(shape))
                    continue
                for bucket in self.grid.batch_buckets:
                    entry, hit = self.cache.get(
                        (name, bucket, key, self._dtype.str),
                        lambda b=bucket, k=key:
                            self._build_ready_predictor(block, b, k))
                    if hit:
                        continue
                    warmed += 1
                    if entry.aot == "loaded":
                        loaded += 1
                    else:
                        compiled += 1
        out = {"warmed": warmed, "loaded": loaded, "compiled": compiled,
               "skipped": skipped, "tenants": names,
               "ms": round((time.perf_counter() - t0) * 1000.0, 2)}
        get_journal().event("aot_prewarm", **out)
        return out

    # -- reporting ---------------------------------------------------------
    def tenant_stats(self) -> dict:
        out = {}
        with self._tlock:
            states = list(self.tenants.values())
        for ts in states:
            out[ts.name] = {
                "state": ts.state, "reason": ts.reason,
                "slo": ts.slo.name, "priority": ts.slo.priority,
                "hot": ts.block is not None,
                "params_step": ts.params_step,
                "latency_ms": ts.latency.summary(),
                **ts.counters}
        return out

    def stats(self) -> dict:
        st = super().stats()
        st["tenants"] = self.tenant_stats()
        return st

    def beacon(self) -> dict:
        """Readiness beacon + served-tenant advertisement: the replica
        pool's heartbeat ledger (elastic.membership) carries which
        tenants this replica serves and their quarantine state, so a
        tenant-aware router can place around a quarantined tenant
        without touching the replica."""
        doc = super().beacon()
        with self._tlock:
            doc["tenants"] = {ts.name: {"state": ts.state,
                                        "step": ts.params_step}
                              for ts in self.tenants.values()}
        return doc

    def metrics_text(self) -> str:
        """Server families plus the tenant-labeled families:
        ``mxnet_tpu_serving_tenant_events{tenant,event}``,
        ``..._tenant_state`` (0 admitted / 1 half-open / 2 quarantined),
        and ``..._tenant_latency_ms{tenant,quantile}``."""
        from ..observability import metrics as _m
        super().metrics_text()         # mirrors the fleet-wide families
        reg = _m.default_registry()
        code = {ADMITTED: 0, HALF_OPEN: 1, QUARANTINED: 2}
        ev = reg.gauge("mxnet_tpu_serving_tenant_events",
                       "per-tenant serving counters (cumulative)",
                       ("tenant", "event"))
        stg = reg.gauge("mxnet_tpu_serving_tenant_state",
                        "tenant breaker (0 admitted, 1 half-open, "
                        "2 quarantined)", ("tenant",))
        lq = reg.gauge("mxnet_tpu_serving_tenant_latency_ms",
                       "per-tenant end-to-end latency percentiles",
                       ("tenant", "quantile"))
        counter_keys = ("accepted", "served", "shed", "rejected_shape",
                        "quarantine_rejects", "errors", "deadline_miss",
                        "reloads", "page_ins", "page_outs",
                        "quarantines", "readmissions")
        for name, row in self.tenant_stats().items():
            stg.labels(tenant=name).set(code.get(row["state"], 0))
            for k in counter_keys:
                ev.labels(tenant=name, event=k).set(row[k])
            lat = row["latency_ms"]
            if lat["count"]:
                for q in ("p50", "p95", "p99"):
                    lq.labels(tenant=name, quantile=q).set(lat[q])
        return reg.prometheus_text()

"""AOT-cache entry format + directory report — stdlib-only.

One persisted executable per file under the cache root
(``MXNET_TPU_AOT_CACHE_DIR``), committed atomically by
``serving/aotcache.py``::

    MAGIC(4) | u32 header_len | u32 header_crc32 | header_json | body

The JSON header is the entry's CRC manifest: a ``format`` version, the
compatibility ``envelope`` (jax/jaxlib versions, backend platform,
device kind, local topology), the cache ``key`` (padded shape, dtype,
param-tree structure fingerprint), and a ``sections`` list naming each
body section with its byte length and CRC32.  A reader validates magic,
bounds, header CRC, format, envelope, and every section CRC **before**
any bytes reach a deserializer (graftlint G21's contract) — any failure
degrades to a normal compile, never to wrong numerics.

This module owns the byte-level read/validate half so the doctor
(``python -m mxnet_tpu.diagnostics doctor --aot-dir DIR``) can audit a
cache directory — entry/byte counts, envelope versions, stale and
corrupt entries — without importing jax (the same wedged-backend
contract as ``serving/report.py``); ``aotcache.py`` imports the format
constants from here.
"""
from __future__ import annotations

import json
import os
import struct
import zlib

__all__ = ["MAGIC", "FORMAT_VERSION", "SUFFIX", "aot_report",
           "pack_entry", "read_entry", "validate_entry"]

MAGIC = b"MXAO"
FORMAT_VERSION = 1
SUFFIX = ".aot"
_FIXED = struct.Struct("<4sII")          # magic, header_len, header_crc
_MAX_HEADER = 1 << 20                    # a sane header is a few KB


def pack_entry(header: dict, sections: dict) -> bytes:
    """Serialize one entry: ``sections`` (name -> bytes) are CRC'd into
    the header manifest and concatenated in sorted-name order."""
    manifest = []
    body = b""
    for name in sorted(sections):
        data = sections[name]
        manifest.append({"name": name, "len": len(data),
                         "crc32": zlib.crc32(data) & 0xFFFFFFFF})
        body += data
    doc = dict(header)
    doc["format"] = FORMAT_VERSION
    doc["sections"] = manifest
    hdr = json.dumps(doc, sort_keys=True).encode("utf-8")
    return _FIXED.pack(MAGIC, len(hdr),
                       zlib.crc32(hdr) & 0xFFFFFFFF) + hdr + body


def read_entry(path: str):
    """Validate + parse one entry file.  Returns ``(header, sections,
    None)`` on success (``sections``: name -> bytes) or ``(None, None,
    reason)`` — reason one of ``unreadable|truncated|magic|header_crc|
    header_json|format|section_len|section_crc``.  Every length is
    bounds-checked and every CRC verified before a byte is returned, so
    callers may hand sections straight to a deserializer."""
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except OSError:
        return None, None, "unreadable"
    if len(raw) < _FIXED.size:
        return None, None, "truncated"
    magic, hlen, hcrc = _FIXED.unpack_from(raw)
    if magic != MAGIC:
        return None, None, "magic"
    if hlen > _MAX_HEADER or len(raw) < _FIXED.size + hlen:
        return None, None, "truncated"
    hdr = raw[_FIXED.size:_FIXED.size + hlen]
    if (zlib.crc32(hdr) & 0xFFFFFFFF) != hcrc:
        return None, None, "header_crc"
    try:
        header = json.loads(hdr.decode("utf-8"))
    except (ValueError, UnicodeDecodeError):
        return None, None, "header_json"
    if not isinstance(header, dict) or \
            header.get("format") != FORMAT_VERSION:
        return None, None, "format"
    sections = {}
    off = _FIXED.size + hlen
    for sec in header.get("sections") or ():
        if not isinstance(sec, dict):
            return None, None, "header_json"
        try:
            n = int(sec["len"])
            crc = int(sec["crc32"])
            name = str(sec["name"])
        except (KeyError, TypeError, ValueError):
            return None, None, "header_json"
        if n < 0 or off + n > len(raw):
            return None, None, "section_len"
        data = raw[off:off + n]
        if (zlib.crc32(data) & 0xFFFFFFFF) != crc:
            return None, None, "section_crc"
        sections[name] = data
        off += n
    return header, sections, None


def validate_entry(path: str):
    """``read_entry`` without keeping the bytes: ``(header, None)`` or
    ``(None, reason)`` — the doctor's audit primitive."""
    header, _sections, reason = read_entry(path)
    return header, reason


def _iter_entries(dirpath):
    try:
        names = os.listdir(dirpath)
    except OSError as e:
        return None, f"cannot read {dirpath}: {e.strerror or e}"
    return sorted(n for n in names if n.endswith(SUFFIX)), None


def aot_report(dirpath) -> dict:
    """Audit one cache directory: entry/byte counts, the envelope
    version histogram, corrupt entries by reason, and how many entries
    are stale relative to the NEWEST entry's envelope (a partial
    upgrade leaves old-envelope entries behind; they are never loaded,
    only GC'd).  Always returns a dict; ``ok`` False + ``error`` when
    the directory is unreadable or empty."""
    names, err = _iter_entries(dirpath)
    if names is None:
        return {"ok": False, "dir": str(dirpath), "error": err}
    entries = []
    corrupt: dict = {}
    total_bytes = 0
    for name in names:
        path = os.path.join(dirpath, name)
        try:
            st = os.stat(path)
        except OSError:
            continue
        total_bytes += st.st_size
        header, reason = validate_entry(path)
        if header is None:
            corrupt[reason] = corrupt.get(reason, 0) + 1
            continue
        entries.append({"name": name, "bytes": st.st_size,
                        "mtime": st.st_mtime,
                        "envelope": header.get("envelope") or {},
                        "key": header.get("key") or {}})
    if not names:
        return {"ok": False, "dir": str(dirpath),
                "error": "no cache entries"}
    envelopes: dict = {}
    for e in entries:
        tag = json.dumps(e["envelope"], sort_keys=True)
        envelopes[tag] = envelopes.get(tag, 0) + 1
    stale = 0
    if entries:
        newest = max(entries, key=lambda e: e["mtime"])
        current = json.dumps(newest["envelope"], sort_keys=True)
        stale = sum(1 for e in entries
                    if json.dumps(e["envelope"], sort_keys=True) != current)
    return {"ok": True, "dir": str(dirpath),
            "entries": len(entries),
            "bytes": total_bytes,
            "corrupt": corrupt,
            "corrupt_total": sum(corrupt.values()),
            "stale": stale,
            "envelopes": envelopes,
            "keys": [e["key"] for e in entries]}

"""Compiled-predictor cache — a bounded LRU of jitted executables.

The serving analog of ``CachedOp``: each entry is ONE jitted XLA
program for one padded shape ``(batch_bucket,) + feature_key``, built
through :func:`gluon.block.functional_apply` (the same predictor-
extraction primitive the sharded/pipelined trainers compile through).
Parameters enter the program as **runtime arguments**, so a hot-reload
that swaps parameter values retraces nothing — only a novel padded
shape compiles, and the bucket grid bounds how many of those exist.

The LRU bound makes the executable population bounded even when the
configured grid is large (a misconfigured 10^3-cell grid must degrade to
evictions, not to unbounded device-memory growth).  Counters
(hits/misses/evictions; misses == compiles) feed the per-batch journal
record and the compile-bound acceptance test.
"""
from __future__ import annotations

import threading
import time
from collections import OrderedDict

import jax

from .. import _rng
from ..gluon.block import functional_apply

__all__ = ["CompiledPredictor", "PredictorCache"]

_key_spec_memo = None


def key_spec():
    """Abstract (shape, dtype) of one serving PRNG key, computed ONCE
    per process (the first call consumes a single global-stream key).
    Both the AOT arg signature and the cache fingerprint read it, so a
    cold start and a warm start advance the global PRNG stream by the
    same amount — a per-operation ``next_key()`` here would skew the
    stream cold-vs-warm and cost a backend dial per cache lookup.  The
    impl (and so the dtype) is fixed per process by ``MXNET_PRNG_IMPL``;
    a mid-process reseed keeps it."""
    global _key_spec_memo
    if _key_spec_memo is None:
        k = _rng.next_key()
        _key_spec_memo = jax.ShapeDtypeStruct(k.shape, k.dtype)
    return _key_spec_memo


class CompiledPredictor:
    """One jitted inference program at one padded shape.

    ``__call__(x_padded)`` fetches the block's *current* parameter
    arrays (so a between-batches hot-reload is picked up with no
    recompile), threads a fresh PRNG key, and returns the flat tuple of
    output device arrays plus the traced output treedef.

    Two dispatch paths share one calling convention: the lazy
    ``jax.jit`` closure (compiles at first call — the historical path)
    and an ahead-of-time ``jax.stages.Compiled`` executable installed by
    :meth:`aot_compile` (an eager lower+compile) or
    :meth:`from_serialized` (a deserialized on-disk executable,
    ``serving/aotcache.py``).  Parameters stay runtime arguments on both
    paths, so the zero-retrace hot-reload contract is unchanged.

    With a :class:`~.shardplan.ShardPlan` the SAME program becomes a
    GSPMD tensor-parallel executable: parameters arrive already placed
    on the plan's mesh (their ``NamedSharding`` rides the runtime
    arguments on the lazy path and the abstract arg specs on the AOT
    path), the padded input is committed to the plan's activation
    sharding before dispatch, and XLA partitions the computation —
    no second code path, exactly one executable per padded shape.
    """

    def __init__(self, block, ctx=None, plan=None):
        self._block = block
        self._ctx = ctx
        self.plan = plan
        self._treedef = None
        self._compiled = None          # AOT executable when present
        self.aot = None                # None | "compiled" | "loaded"

        def fn(key, tr_datas, aux_datas, x):
            outs, treedef, _aux_new = functional_apply(
                block, key, tr_datas, aux_datas, [x],
                training=False, ctx=ctx)
            # inference never writes aux state back (BatchNorm running
            # stats stay frozen); treedef is captured at trace time
            self._treedef = treedef
            return tuple(outs)

        self._jitted = jax.jit(fn)

    def _runtime_args(self):
        trainable, aux = self._block._param_split()
        return ([p._data[0]._data for p in trainable],
                [p._data[0]._data for p in aux])

    @property
    def ready(self) -> bool:
        """True once an executable exists — a first call will NOT pay
        an XLA compile (the server's compile-span gate reads this)."""
        return self._compiled is not None

    def __call__(self, x_padded):
        tr_datas, aux_datas = self._runtime_args()
        key = _rng.next_key()
        if self.plan is not None:
            # commit the padded batch (and the key) to the plan's
            # shardings BEFORE dispatch so the lazy and AOT paths see
            # identical arg placements (one executable, either way in)
            x_padded = jax.device_put(
                x_padded, self.plan.activation_sharding(x_padded.shape))
            key = jax.device_put(key, self.plan.replicated())
        fn = self._compiled if self._compiled is not None else self._jitted
        outs = fn(key, tr_datas, aux_datas, x_padded)
        return outs, self._treedef

    # -- ahead-of-time path (serving/aotcache.py) ---------------------------
    def _arg_specs(self, x_shape, x_dtype):
        """Abstract arg signature of one padded-shape call: (key,
        trainable arrays, aux arrays, x) as ShapeDtypeStructs matching
        what ``__call__`` passes at runtime.  The key spec comes from
        the process-memoized :func:`key_spec` so its (impl-dependent)
        dtype is exact without consuming a stream key per build.

        Under a shard plan the specs carry shardings: parameters use the
        LIVE arrays' placements (the plan already landed them on the
        mesh), the input uses the plan's activation sharding, and the
        key replicates — so an AOT lowering partitions exactly like the
        lazy path's first call."""
        tr_datas, aux_datas = self._runtime_args()
        plan = self.plan

        def spec(a):
            if plan is None:
                return jax.ShapeDtypeStruct(a.shape, a.dtype)
            return jax.ShapeDtypeStruct(a.shape, a.dtype,
                                        sharding=a.sharding)

        ks = key_spec()
        if plan is not None:
            ks = jax.ShapeDtypeStruct(ks.shape, ks.dtype,
                                      sharding=plan.replicated())
            x_spec = jax.ShapeDtypeStruct(
                tuple(x_shape), x_dtype,
                sharding=plan.activation_sharding(tuple(x_shape)))
        else:
            x_spec = jax.ShapeDtypeStruct(tuple(x_shape), x_dtype)
        return (ks, [spec(a) for a in tr_datas],
                [spec(a) for a in aux_datas], x_spec)

    def aot_compile(self, x_shape, x_dtype) -> "CompiledPredictor":
        """Lower + compile at the padded shape ahead of the first call
        (tracing captures the output treedef as a side effect).  The
        resulting executable is bit-identical to what the lazy path
        would build — and is what :meth:`serialize_aot` persists."""
        lowered = self._jitted.lower(*self._arg_specs(x_shape, x_dtype))
        self._compiled = lowered.compile()
        self.aot = "compiled"
        return self

    def serialize_aot(self):
        """(executable payload bytes, pytree blob bytes) for the disk
        store.  Raises when the backend's compilation does not support
        serialization — the cache degrades to memory-only."""
        import pickle

        from jax.experimental import serialize_executable as _se
        if self._compiled is None:
            raise ValueError("predictor has no AOT executable to "
                             "serialize (call aot_compile first)")
        payload, in_tree, out_tree = _se.serialize(self._compiled)
        trees = pickle.dumps((in_tree, out_tree, self._treedef))
        return payload, trees

    @classmethod
    def from_serialized(cls, block, payload, trees, ctx=None,
                        backend=None, plan=None):
        """Rebuild a predictor from persisted bytes WITHOUT tracing or
        compiling.  ``payload``/``trees`` must already be CRC- and
        envelope-validated by the caller (serving/aotcache.py is the one
        read path; graftlint G21 enforces the discipline)."""
        import pickle

        from jax.experimental import serialize_executable as _se
        obj = cls(block, ctx=ctx, plan=plan)
        in_tree, out_tree, treedef = pickle.loads(trees)
        obj._compiled = _se.deserialize_and_load(
            payload, in_tree, out_tree, backend=backend)
        obj._treedef = treedef
        obj.aot = "loaded"
        return obj


class PredictorCache:
    """Bounded LRU over :class:`CompiledPredictor` entries.

    ``get(key, builder)`` returns ``(entry, hit)``; a miss invokes
    ``builder()`` (the compile) and may evict the least-recently-used
    entry.  Dropping an entry releases the jitted closure, so the
    underlying XLA executable becomes collectable — the cache is the one
    owner.  Thread-safe, though the serving worker is the only caller in
    steady state."""

    def __init__(self, max_entries=16):
        if max_entries < 1:
            raise ValueError("PredictorCache needs max_entries >= 1")
        self.max_entries = int(max_entries)
        self._lru = OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.last_build_s = None

    def get(self, key, builder):
        with self._lock:
            entry = self._lru.get(key)
            if entry is not None:
                self._lru.move_to_end(key)
                self.hits += 1
                return entry, True
        # build outside the lock: a multi-second XLA compile must not
        # block a stats() snapshot from another thread. (The XLA compile
        # itself happens at the entry's FIRST CALL — the server wraps
        # that in the timed compile_span; this build is just the trace
        # closure.)
        t0 = time.perf_counter()
        entry = builder()
        build_s = time.perf_counter() - t0
        with self._lock:
            raced = self._lru.get(key)
            if raced is not None:         # concurrent builder won
                self._lru.move_to_end(key)
                self.hits += 1
                return raced, True
            self.misses += 1
            self.last_build_s = round(build_s, 4)
            self._lru[key] = entry
            while len(self._lru) > self.max_entries:
                self._lru.popitem(last=False)
                self.evictions += 1
        return entry, False

    def drop_where(self, predicate) -> int:
        """Drop every entry whose key satisfies ``predicate`` (counted
        as evictions) — the tenant fleet's page-out/remove path: a cold
        or removed tenant's executables must not occupy LRU slots the
        hot tenants need.  Returns how many entries were dropped."""
        with self._lock:
            doomed = [k for k in self._lru if predicate(k)]
            for k in doomed:
                del self._lru[k]
            self.evictions += len(doomed)
            return len(doomed)

    def __len__(self):
        with self._lock:
            return len(self._lru)

    def clear(self):
        with self._lock:
            self._lru.clear()

    def stats(self) -> dict:
        with self._lock:
            total = self.hits + self.misses
            return {"entries": len(self._lru),
                    "max_entries": self.max_entries,
                    "hits": self.hits, "misses": self.misses,
                    "evictions": self.evictions,
                    "hit_rate": round(self.hits / total, 4) if total else None,
                    "last_build_s": self.last_build_s}

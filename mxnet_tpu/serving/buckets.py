"""Bucket grid — the shape discretization that bounds XLA compiles.

XLA compiles one executable per concrete input shape, so a serving
frontend that forwards raw request shapes pays a multi-second compile on
every novel (batch, dims) combination — the latency cliff SNIPPETS.md's
``pjit``-lowering exemplar exists to avoid.  The grid maps every
admissible request shape onto a small lattice of padded shapes:

- the **batch axis** (number of coalesced requests) rounds up to the
  smallest configured batch bucket;
- each **bucketed feature axis** rounds up to the smallest configured
  size for that axis; unbucketed axes must match exactly across requests
  and each distinct size compiles its own executable — fixed-dim models
  (an MLP's feature width) simply leave them unbucketed;
- a shape that exceeds the largest bucket on any axis is **rejected** at
  admission (structured error, never a fresh compile).

The number of distinct compiled shapes is then bounded by
``grid_bound()`` = |batch buckets| x prod(|axis buckets|) per distinct
unbucketed-dims signature — bounded by configuration, never by traffic.

Autoregressive decode gets its own lattice (:meth:`BucketGrid.for_decode`):
a decode step is always ``(slots, step_width)`` — the slot pool is a
fixed-size resident batch, not a traffic-dependent one — so snapping it
onto the prefill grid would pad the one-token step axis up to the
smallest prefill bucket (a 4x-16x compute waste every step) and alias
decode executables with prefill ones.  The decode grid has exactly one
cell; ``grid_bound() == 1`` is the decode engine's zero-mid-run-compile
guarantee.

Stdlib-only: the grid is pure shape math, imported by the doctor and
tests without touching jax.
"""
from __future__ import annotations

__all__ = ["BucketGrid"]


def _pow2_buckets(max_value):
    out, b = [], 1
    while b < max_value:
        out.append(b)
        b *= 2
    out.append(int(max_value))
    return out


class BucketGrid:
    """The serving shape lattice (see module docstring).

    ``batch_buckets``: ascending sizes for the coalesced-batch axis
    (default: powers of two up to ``max_batch``).
    ``dim_buckets``: {feature-axis-index: ascending sizes} for axes whose
    request sizes vary (axis 0 = first axis *after* the batch axis).
    """

    def __init__(self, max_batch=8, batch_buckets=None, dim_buckets=None):
        if batch_buckets is None:
            batch_buckets = _pow2_buckets(int(max_batch))
        self.batch_buckets = tuple(sorted({int(b) for b in batch_buckets}))
        if not self.batch_buckets or self.batch_buckets[0] < 1:
            raise ValueError(f"batch_buckets must be positive ints, got "
                             f"{self.batch_buckets}")
        self.dim_buckets = {}
        for axis, sizes in (dim_buckets or {}).items():
            sizes = tuple(sorted({int(s) for s in sizes}))
            if not sizes or sizes[0] < 1 or int(axis) < 0:
                raise ValueError(f"dim_buckets[{axis}] must be positive "
                                 f"ints, got {sizes}")
            self.dim_buckets[int(axis)] = sizes

    @classmethod
    def for_decode(cls, slots, step_width=1):
        """The dedicated decode-step lattice: ONE cell, ``(slots,
        step_width)``.  A ``(slots, 1)`` step tensor snaps to itself —
        never to the smallest prefill bucket — and ``grid_bound() == 1``
        makes 'decode steps never compile outside the lattice' a
        checkable invariant rather than a hope."""
        if int(slots) < 1 or int(step_width) < 1:
            raise ValueError(f"decode grid needs slots >= 1 and "
                             f"step_width >= 1, got ({slots}, {step_width})")
        return cls(max_batch=int(slots), batch_buckets=(int(slots),),
                   dim_buckets={0: (int(step_width),)})

    @property
    def max_batch(self) -> int:
        return self.batch_buckets[-1]

    @staticmethod
    def _round_up(buckets, n):
        for b in buckets:
            if n <= b:
                return b
        return None

    def batch_bucket(self, n: int):
        """Smallest batch bucket >= n, or None when n exceeds the grid."""
        return self._round_up(self.batch_buckets, int(n))

    def feature_key(self, shape):
        """Bucketed feature shape (without the batch axis) a request of
        ``shape`` pads to, or None when any bucketed axis exceeds its
        largest bucket (the admission-reject signal)."""
        out = []
        for i, s in enumerate(shape):
            buckets = self.dim_buckets.get(i)
            if buckets is None:
                out.append(int(s))
                continue
            b = self._round_up(buckets, int(s))
            if b is None:
                return None
            out.append(b)
        return tuple(out)

    def grid_bound(self) -> int:
        """Upper bound on distinct compiled shapes per unbucketed-dims
        signature: |batch buckets| x prod(|axis buckets|)."""
        bound = len(self.batch_buckets)
        for sizes in self.dim_buckets.values():
            bound *= len(sizes)
        return bound

    @staticmethod
    def pad_waste(n_real, batch_bucket, real_shapes, padded_shape) -> float:
        """Fraction of the padded batch's elements that are padding —
        the journal's per-batch HBM-waste signal."""
        padded_elems = batch_bucket
        for d in padded_shape:
            padded_elems *= d
        real_elems = 0
        for shape in real_shapes:
            e = 1
            for d in shape:
                e *= d
            real_elems += e
        if padded_elems <= 0:
            return 0.0
        return round(1.0 - real_elems / padded_elems, 4)

    def __repr__(self):
        return (f"BucketGrid(batch={list(self.batch_buckets)}, "
                f"dims={self.dim_buckets}, bound={self.grid_bound()})")

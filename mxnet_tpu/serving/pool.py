"""Replica pool — N serving replicas behind one health ledger.

The millions-of-users shape (ROADMAP item 1): one `Server` per replica
— in-process (:class:`LocalReplica`) or its own OS process
(:class:`ProcReplica`, serving/worker.py) — each heartbeating a
readiness beacon onto a shared-filesystem ledger via
``elastic.membership.Heartbeat``, exactly the control plane that
detects a dead training rank (PR 8).  The pool owns replica LIFECYCLE
(spawn, drain, restart, rolling reload, auto-respawn); the router
(serving/router.py) owns per-request placement and robustness, reading
replica health ONLY through :meth:`ReplicaPool.view` — i.e. only from
the ledger — so every router thread (and every separate router process
pointed at the same ledger) derives the same picture.

Failure semantics (docs/serving.md failure matrix):

- a SIGKILLed/wedged replica's heartbeat seq stalls; ``view()`` flips
  ``alive`` False within the observer-clock deadline (the G11/G12
  lessons: no cross-host wall clock, no reader-local membership
  decisions) and the monitor respawns it under a bounded crash-loop
  budget;
- ``drain()`` stops admission FIRST (the beacon flips not-ready), then
  lets the queue empty under a bounded deadline — in-flight work
  finishes, nothing new lands;
- ``restart()`` = drain + replace the worker; the fresh worker loads
  the newest CRC-valid committed step from its ``ParamStore`` root, so
  a restart is also the upgrade path;
- ``reload()`` rolls a restart across the fleet, at most ``surge``
  replicas out of rotation at once — zero shed beyond the surge margin
  while the router routes around the hole.
"""
from __future__ import annotations

import os
import socket
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field

import numpy as np

from ..base import MXNetError
from ..diagnostics.journal import get_journal
from ..elastic.membership import Heartbeat, LivenessReader
from ..resilience import atomic as _atomic
from .batcher import (DeadlineExceeded, RequestError, ServerOverloaded,
                      ServerStopped, SlotsExhausted)
from . import wire

__all__ = ["DeployInProgress", "LocalReplica", "PoolConfig", "ProcReplica",
           "ReplicaPool", "ReplicaState", "ReplicaUnavailable"]


def _env_float(name, default):
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def _env_int(name, default):
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


class DeployInProgress(MXNetError):
    """A canary deployment owns the pool: fleet-mutating lifecycle ops
    (``reload``, another ``deploy``) are REFUSED, not queued — two
    concurrent version rollouts would tear the old-xor-new response
    contract mid-flight (docs/serving.md, canary deployment)."""

    def __init__(self, owner, op):
        super().__init__(
            f"{op} refused: deployment {owner!r} is in progress — wait "
            "for it to promote or roll back (DeployController serializes "
            "fleet version changes)")
        self.owner = owner
        self.op = op


class ReplicaUnavailable(RequestError):
    """The replica could not be reached (connection refused/reset, no
    port in the beacon yet, torn reply): the transport twin of a dead
    rank.  Always retryable on a different replica."""

    retryable = True

    def __init__(self, replica, detail):
        super().__init__(f"replica {replica!r} unavailable: {detail}")
        self.replica = replica


@dataclass
class PoolConfig:
    """Replica-pool knobs (docs/serving.md; ``MXNET_TPU_POOL_*`` env
    vars set fleet-wide defaults)."""

    heartbeat_s: float = field(default_factory=lambda: _env_float(
        "MXNET_TPU_POOL_HEARTBEAT_S", 0.5))
    deadline_s: float = field(default_factory=lambda: _env_float(
        "MXNET_TPU_POOL_DEADLINE_S", 3.0))      # hb stall -> replica lost
    drain_s: float = field(default_factory=lambda: _env_float(
        "MXNET_TPU_POOL_DRAIN_S", 20.0))        # bounded drain deadline
    spawn_s: float = 120.0                      # worker start -> ready
    surge: int = 1                              # reload() out-of-rotation cap
    max_respawns: int = 3                       # crash-loop budget/replica
    monitor_s: float = 0.5                      # auto-respawn poll interval
    poll_s: float = 0.05
    # shared-FS run directory for pod-scope tracing: when set, every
    # subprocess worker streams spans+journal to its OWN
    # <trace_dir>/journal-<rid>.jsonl and runs the flight recorder
    # there, the input observability/aggregate.py assembles into one
    # cross-process Perfetto trace (docs/observability.md)
    trace_dir: object = field(default_factory=lambda: os.environ.get(
        "MXNET_TPU_TRACE_DIR") or None)
    # shared AOT executable-cache root (serving/aotcache.py): every
    # subprocess worker inherits it, so a rolling reload(surge=k)'s
    # fresh workers LOAD their bucket lattice from disk instead of
    # recompiling it under live traffic — the zero-cold-start restart
    aot_dir: object = field(default_factory=lambda: os.environ.get(
        "MXNET_TPU_AOT_CACHE_DIR") or None)

    def __post_init__(self):
        if self.deadline_s <= self.heartbeat_s:
            raise MXNetError(
                f"pool deadline_s ({self.deadline_s:g}) must exceed "
                f"heartbeat_s ({self.heartbeat_s:g}) — a deadline inside "
                "one heartbeat interval declares healthy replicas dead")
        if self.surge < 1:
            raise MXNetError("pool surge must be >= 1")


@dataclass
class ReplicaState:
    """One ledger-derived row of :meth:`ReplicaPool.view` — everything
    the router is allowed to know about a replica."""

    id: str
    alive: bool
    ready: bool
    draining: bool = False
    queue_depth: int = 0
    params_step: object = None
    last_batch_age_s: object = None
    port: object = None
    pid: object = None
    idle_s: float = 0.0
    # served-tenant advertisement from a fleet replica's beacon:
    # {tenant: {"state": admitted|half_open|quarantined, "step": N}};
    # None = single-tenant replica (tenant-agnostic placement)
    tenants: object = None


def _wait_for(predicate, deadline_s, poll_s=0.05, what="condition"):
    """Bounded poll: True when ``predicate()`` held before the deadline,
    else False (callers decide whether that is fatal)."""
    deadline = time.monotonic() + max(float(deadline_s), 0.0)
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(poll_s)
    return bool(predicate())


class LocalReplica:
    """In-process replica: a :class:`~.server.Server` built by
    ``factory()`` plus its own beacon thread.  The cheap unit for router
    logic tests and single-process deployments — same ledger contract
    as a subprocess worker, minus the process isolation."""

    kind = "local"

    def __init__(self, rid, factory, hb_dir, config):
        self.id = str(rid)
        self.factory = factory
        self.cfg = config
        self.server = None
        self._draining = False
        self._pin = None               # deploy pin; survives restart()
        self._hb = Heartbeat(hb_dir, self.id, config.heartbeat_s,
                             payload=self._beacon, prefix="replica")

    def _beacon(self):
        srv = self.server
        if srv is None:
            return {"ready": False, "draining": self._draining}
        doc = srv.beacon()
        doc["draining"] = self._draining
        doc["ready"] = bool(doc["ready"]) and not self._draining
        return doc

    def start(self):
        if self.server is None:
            self.server = self.factory()
        if self._pin is not None:
            # pin BEFORE start: the initial force-reload then lands on
            # the pinned step, not the newest committed one
            self.server.pin_params(self._pin)
        self.server.start()
        self._draining = False
        self._hb.start()
        return self

    def pin(self, step):
        """Pin (or with None unpin) this replica's ParamStore to one
        step.  The pin is remembered on the HANDLE too, so a later
        ``restart()``'s fresh factory build starts pinned — a respawned
        canary/rolled-back replica cannot drift off its assigned
        version.  Returns True when a live server took the pin now."""
        self._pin = None if step is None else int(step)
        srv = self.server
        if srv is None:
            return False
        return bool(srv.pin_params(self._pin))

    def predict(self, x, deadline_ms, cancel=None, tenant=None):
        """One attempt on this replica; returns ``(array, meta)`` or
        raises a structured serving error."""
        srv = self.server
        if srv is None:
            raise ReplicaUnavailable(self.id, "not started")
        budget_s = (deadline_ms / 1000.0 if deadline_ms
                    else srv.config.result_timeout_s)
        resp = srv.submit(x, deadline_ms=deadline_ms, cancel=cancel,
                          tenant=tenant)
        value = resp.result(timeout_s=budget_s + 5.0)
        return value, {"replica": self.id,
                       "params_step": resp.params_step}

    def decode(self, tokens, max_new_tokens=None, deadline_ms=None,
               cancel=None, tenant=None):
        """One decode attempt on this replica's continuous batcher;
        returns ``(token list, meta)`` or raises a structured serving
        error (``SlotsExhausted`` → the router tries another replica)."""
        srv = self.server
        if srv is None:
            raise ReplicaUnavailable(self.id, "not started")
        budget_s = (deadline_ms / 1000.0 if deadline_ms
                    else srv.config.result_timeout_s)
        stream = srv.decode_submit(tokens, max_new_tokens=max_new_tokens,
                                   deadline_ms=deadline_ms, tenant=tenant)
        if cancel is not None and cancel.is_set():
            stream.cancel()
        toks = stream.result(timeout_s=budget_s + 5.0)
        return toks, {"replica": self.id, "generated": len(toks)}

    def drain(self, deadline_s) -> int:
        self._draining = True
        self._hb.beat()                    # publish not-ready immediately
        srv = self.server
        if srv is None:
            return 0
        _wait_for(lambda: srv.queue_depth() == 0, deadline_s,
                  self.cfg.poll_s)
        return srv.queue_depth()

    def restart(self, deadline_s=None):
        """Replace the server with a fresh ``factory()`` build — which
        re-reads the newest valid committed step from its ParamStore at
        ``start()`` (the upgrade path).  ``deadline_s`` bounds the old
        server's stop."""
        if self.server is not None:
            self.server.stop(timeout_s=30.0 if deadline_s is None
                             else max(float(deadline_s), 1.0))
        self.server = self.factory()
        if self._pin is not None:
            self.server.pin_params(self._pin)
        self.server.start()
        self._draining = False
        # a replica whose beacon daemon died with it (the chaos
        # conductor's in-process kill stops the heartbeat thread without
        # resigning, the host-vanished shape) must come back BEATING, or
        # the monitor re-detects it as lost every deadline and burns the
        # crash-loop budget on a healthy server; start() is a no-op when
        # the daemon is still running and beats once either way
        self._hb.start()
        self._hb.beat()

    def stop(self):
        if self.server is not None:
            self.server.stop(timeout_s=30.0)
        self._hb.stop(resign=True)

    def kill(self):
        """In-process stand-in for the host-vanished shape (the chaos
        conductor's process-kill on a local pool): the beacon daemon
        stops WITHOUT resigning — the seq file goes stale exactly as a
        SIGKILLed worker's would — and the server handle is torn away so
        dispatches fail structured (``ReplicaUnavailable``).  The pool
        monitor must detect, journal ``replica_lost`` and restart it
        with zero cooperation from this handle.  The orphaned server
        winds down on a background thread: a kill must not block the
        killer, and in-flight requests fail over like the process died."""
        self._hb.stop(resign=False)
        srv, self.server = self.server, None
        if srv is not None:
            threading.Thread(target=lambda: srv.stop(timeout_s=5.0),
                             daemon=True,
                             name=f"mxtpu-kill-{self.id}").start()

    def pid(self):
        return os.getpid()


class ProcReplica:
    """Subprocess replica: ``python -m mxnet_tpu.serving worker`` with
    its own device context, queue, cache, and ParamStore — the unit the
    chaos tests SIGKILL.  Discovery is ledger-only: the worker publishes
    its bound port in the heartbeat beacon; this handle reads it back
    through the pool's :class:`LivenessReader` (``port_of``)."""

    kind = "proc"

    def __init__(self, rid, worker_args, hb_dir, config, port_of,
                 env=None):
        self.id = str(rid)
        self.worker_args = dict(worker_args)   # CLI flag -> value
        self.hb_dir = hb_dir
        self.cfg = config
        self.port_of = port_of                 # rid -> beacon port | None
        self.env = env
        self.proc = None

    def _argv(self):
        argv = [sys.executable, "-m", "mxnet_tpu.serving", "worker",
                "--replica-id", self.id, "--hb-dir", self.hb_dir,
                "--heartbeat-s", str(self.cfg.heartbeat_s)]
        for flag, value in sorted(self.worker_args.items()):
            if value is not None:
                argv += [flag, str(value)]
        return argv

    def start(self):
        if self.proc is not None and self.proc.poll() is None:
            return self
        self.proc = subprocess.Popen(self._argv(), env=self.env)
        get_journal().event("pool_spawn", replica=self.id,
                            pid=self.proc.pid)
        return self

    # -- wire client -----------------------------------------------------
    def _roundtrip(self, header, payload=b"", budget_s=10.0):
        port = self.port_of(self.id)
        if port is None:
            raise ReplicaUnavailable(self.id, "no port in beacon yet")
        try:
            # chaos seams (docs/chaos.md): ``wire_connect`` is the
            # fd_exhaust socket-open site, ``wire_send`` the partition
            # site — both carry the replica id so a plan targets one peer
            _atomic.trip("wire_connect", self.id)
            with socket.create_connection(
                    ("127.0.0.1", int(port)),
                    timeout=min(budget_s, 5.0)) as s:
                s.settimeout(budget_s + 5.0)
                _atomic.trip("wire_send", self.id)
                wire.send_frame(s, header, payload)
                return wire.recv_frame(s)
        except (OSError, wire.WireError) as e:
            raise ReplicaUnavailable(
                self.id, f"{type(e).__name__}: {e}") from None

    @staticmethod
    def _raise_remote(header):
        name = header.get("error", "RequestError")
        detail = header.get("detail", "")
        tenant = header.get("tenant")
        if name == "DeadlineExceeded":
            raise DeadlineExceeded(header.get("stage", "remote"),
                                   float(header.get("late_ms", 0.0)),
                                   tenant=tenant)
        if name == "ServerOverloaded":
            raise ServerOverloaded(header.get("depth", -1),
                                   header.get("limit", -1),
                                   tier=header.get("tier"),
                                   tenant=tenant)
        if name == "ServerStopped":
            raise ServerStopped(detail or "replica stopped")
        if name == "SlotsExhausted":
            raise SlotsExhausted(header.get("slots", -1),
                                 queued=header.get("queued", 0),
                                 tenant=tenant)
        if name == "TenantQuarantined":
            from .fleet import TenantQuarantined
            err = TenantQuarantined(tenant,
                                    header.get("reason", detail or
                                               "remote quarantine"))
            # preserve the wire verdict: a half-open probe-slot-busy
            # rejection is retryable on another replica; the class
            # default (False) only fits a real quarantine
            err.retryable = bool(header.get("retryable", False))
            raise err
        err = RequestError(f"{name}: {detail}")
        err.retryable = bool(header.get("retryable", True))
        err.tenant = tenant
        raise err

    def predict(self, x, deadline_ms, cancel=None, tenant=None):
        # `cancel` has no remote lever: a losing hedge's reply is simply
        # discarded by the router (in-process replicas do cancel at
        # dequeue; docs/serving.md notes the asymmetry)
        x = np.ascontiguousarray(x)
        budget_s = deadline_ms / 1000.0 if deadline_ms else 60.0
        header = {"cmd": "predict", "shape": list(x.shape),
                  "dtype": str(x.dtype), "deadline_ms": deadline_ms}
        if tenant is not None:
            header["tenant"] = str(tenant)
        # propagate the router's trace context across the process
        # boundary: the worker re-anchors its serving_request root
        # under these ids (docs/observability.md distributed tracing)
        wire.attach_trace(header)
        header, payload = self._roundtrip(
            header, x.tobytes(), budget_s=budget_s)
        if not header.get("ok"):
            self._raise_remote(header)
        out = np.frombuffer(payload, dtype=header["dtype"]).reshape(
            header["shape"])
        return out, {"replica": self.id,
                     "params_step": header.get("params_step")}

    def decode(self, tokens, max_new_tokens=None, deadline_ms=None,
               cancel=None, tenant=None):
        """One remote decode attempt: the prompt ships as int32 payload
        bytes, the generated tokens come back the same way.  ``cancel``
        has no remote lever mid-stream (same asymmetry as predict
        hedging) — the router simply discards a stale reply."""
        arr = np.ascontiguousarray(
            np.asarray(tokens, dtype=np.int32).reshape(-1))
        budget_s = deadline_ms / 1000.0 if deadline_ms else 60.0
        header = {"cmd": "decode", "count": int(arr.size),
                  "deadline_ms": deadline_ms}
        if max_new_tokens is not None:
            header["max_new"] = int(max_new_tokens)
        if tenant is not None:
            header["tenant"] = str(tenant)
        wire.attach_trace(header)
        header, payload = self._roundtrip(
            header, arr.tobytes(), budget_s=budget_s)
        if not header.get("ok"):
            self._raise_remote(header)
        out = np.frombuffer(payload, dtype=np.int32).tolist()
        return out, {"replica": self.id, "generated": len(out)}

    def drain(self, deadline_s) -> int:
        try:
            header, _ = self._roundtrip(
                {"cmd": "drain", "deadline_s": deadline_s},
                budget_s=float(deadline_s) + 5.0)
        except ReplicaUnavailable:
            return 0                   # already gone: nothing to drain
        return int(header.get("residual", 0))

    def pin(self, step):
        """Pin (or with None unpin) the worker's ParamStore to one step.
        Two levers, both needed: a ``pin`` wire frame moves the LIVE
        worker now, and ``--pin-step`` in ``worker_args`` makes the next
        (re)spawn start pinned — a canary respawned by the monitor
        mid-deploy must come back on its assigned version, not the
        newest root.  Returns True when the live worker acked."""
        if step is None:
            self.worker_args.pop("--pin-step", None)
        else:
            self.worker_args["--pin-step"] = int(step)
        try:
            header, _ = self._roundtrip(
                {"cmd": "pin",
                 "step": None if step is None else int(step)},
                budget_s=10.0)
        except ReplicaUnavailable:
            return False               # not up: the arg pins the spawn
        return bool(header.get("ok")) and bool(header.get("pinned"))

    def restart(self, deadline_s=None):
        """Stop (graceful ``stop`` frame, then terminate/kill fallback)
        and spawn a fresh worker — which reads the newest CRC-valid
        committed step at startup.  ``deadline_s`` bounds the whole
        stop ladder — pre-fix it was accepted and silently dropped
        while every wait ran on fixed constants (the exact G19 class
        this PR's audit flagged); without one the historical
        5/15/10/10 ladder applies."""
        proc = self.proc
        if proc is not None and proc.poll() is None:
            deadline = None if deadline_s is None \
                else time.monotonic() + max(float(deadline_s), 1.0)

            def budget(default):
                if deadline is None:
                    return default
                return max(min(default, deadline - time.monotonic()), 1.0)

            try:
                self._roundtrip({"cmd": "stop"}, budget_s=budget(5.0))
            except ReplicaUnavailable:
                pass
            try:
                proc.wait(timeout=budget(15.0))
            except subprocess.TimeoutExpired:
                proc.terminate()
                try:
                    proc.wait(timeout=budget(10.0))
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait(timeout=budget(10.0))
        self.proc = None
        self.start()

    def stop(self):
        proc = self.proc
        if proc is not None and proc.poll() is None:
            try:
                self._roundtrip({"cmd": "stop"}, budget_s=5.0)
            except ReplicaUnavailable:
                pass
            try:
                proc.wait(timeout=15.0)
            except subprocess.TimeoutExpired:
                proc.kill()
                try:
                    proc.wait(timeout=10.0)
                except subprocess.TimeoutExpired:
                    pass
        self.proc = None

    def kill(self):
        """SIGKILL the worker — the chaos lever ("host vanished"): no
        handlers, no drain, no beacon resignation."""
        if self.proc is not None and self.proc.poll() is None:
            self.proc.kill()

    def pid(self):
        return None if self.proc is None else self.proc.pid


class ReplicaPool:
    """Owns N replicas and the health ledger under ``root/hb``.

    Router-facing surface: :meth:`view` (ledger-derived states) and
    :attr:`replicas` (id → handle, for dispatch).  Operator surface:
    ``start/stop``, ``drain``, ``restart``, rolling ``reload``, and the
    auto-respawn ``monitor``."""

    def __init__(self, root, config=None):
        self.root = str(root)
        self.cfg = config or PoolConfig()
        self.hb_dir = os.path.join(self.root, "hb")
        os.makedirs(self.hb_dir, exist_ok=True)
        # pod run id: ONE identity every replica (and this router-side
        # process) stamps on its records so a shared-FS run directory
        # is attributable after the fact; adopt the ambient id when a
        # launcher already published one
        self.run_id = os.environ.get("MXNET_TPU_POD_RUN_ID") or \
            f"pod-{os.urandom(4).hex()}"
        # publish it in THIS process too: trace.identity() reads the
        # environment, so without this the router-side anchors/flight
        # dumps would carry no run_id while every worker's do.  A
        # journal-mode tracer configured BEFORE the pool already wrote
        # its startup anchor without the id — re-anchor so the run is
        # attributable (newest anchor wins in the aggregator; same
        # epoch, so alignment is unchanged)
        if "MXNET_TPU_POD_RUN_ID" not in os.environ:
            os.environ["MXNET_TPU_POD_RUN_ID"] = self.run_id
            from ..observability import trace as _trace
            tracer = _trace.get_tracer()
            if tracer.mode == "journal":
                tracer.journal_anchor()
        self.reader = LivenessReader(self.hb_dir, self.cfg.deadline_s,
                                     prefix="replica")
        self.replicas: dict = {}
        self._respawns: dict = {}
        self._last_respawn: dict = {}      # rid -> monotonic spawn time
        # short-TTL view cache: the ledger only changes at heartbeat
        # granularity, so per-request re-reads of N beacon files are
        # pure I/O waste on the router's hot path; a quarter-heartbeat
        # snapshot preserves the uniform-view contract
        self._view_ttl_s = self.cfg.heartbeat_s / 4.0
        self._view_cache = (None, 0.0)     # (states, monotonic stamp)
        self._monitor_stop = threading.Event()
        self._monitor = None
        self._lock = threading.Lock()      # lifecycle ops serialize
        self._deploy_owner = None          # guarded by _lock; set while a
                                           # DeployController owns the pool

    # -- construction ----------------------------------------------------
    def add_local(self, rid, factory) -> "ReplicaPool":
        """Add an in-process replica built by ``factory() -> Server``."""
        # builder-phase single writer: add_* run before start()/
        # monitor_start() spawn any thread that could observe the dict
        # graftlint: disable=G22 construction precedes thread creation
        self.replicas[str(rid)] = LocalReplica(rid, factory, self.hb_dir,
                                               self.cfg)
        return self

    def add_proc(self, rid, worker_args, env=None) -> "ReplicaPool":
        """Add a subprocess replica (``worker_args``: CLI flag → value,
        e.g. ``{"--model": "scale", "--ckpt-root": root}``).  The worker
        inherits the pod run id and its replica identity through the
        environment (every record it writes is attributable), and — when
        the pool has a ``trace_dir`` — its own journal/trace/flight
        sinks under that shared run directory."""
        rid = str(rid)
        # a caller env built as {**os.environ, ...} INHERITS an ambient
        # MXNET_TPU_TRACE — only a value that differs from the ambient
        # one is a deliberate per-worker override
        caller_trace = (env is not None and "MXNET_TPU_TRACE" in env
                        and env["MXNET_TPU_TRACE"]
                        != os.environ.get("MXNET_TPU_TRACE"))
        env = dict(os.environ if env is None else env)
        env.setdefault("MXNET_TPU_POD_RUN_ID", self.run_id)
        env["MXNET_TPU_REPLICA_ID"] = rid
        if self.cfg.aot_dir:
            # forced over ambient: the POOL's cache root is the warm-
            # restart contract — a respawned/rolled worker must land on
            # the same store its predecessor populated
            env["MXNET_TPU_AOT_CACHE_DIR"] = str(self.cfg.aot_dir)
        trace_dir = self.cfg.trace_dir
        if trace_dir:
            os.makedirs(trace_dir, exist_ok=True)
            # forced, not setdefault: one journal PER PROCESS is the
            # assembly contract — pointing every worker at one shared
            # file would interleave the per-process timelines
            env["MXNET_TPU_TRACE_DIR"] = str(trace_dir)
            env["MXNET_TPU_JOURNAL"] = os.path.join(
                str(trace_dir), f"journal-{rid}.jsonl")
            # journal mode is forced over anything AMBIENT: an
            # inherited `ring`/`off` would leave the forced per-worker
            # journal empty of spans and the assembled timeline blank
            # with no hint why.  Only an env the CALLER built and
            # passed with the knob set is a deliberate override.
            if not caller_trace:
                env["MXNET_TPU_TRACE"] = "journal"
        # builder-phase single writer (see add_local)
        # graftlint: disable=G22 construction precedes thread creation
        self.replicas[rid] = ProcReplica(
            rid, worker_args, self.hb_dir, self.cfg,
            self._port_of, env=env)
        return self

    def _port_of(self, rid):
        self.reader.observe(rid)
        doc = self.reader.payload(rid)
        return None if doc is None else doc.get("port")

    # -- the ledger view (the router's ONLY health source) ---------------
    def view(self) -> list:
        """One :class:`ReplicaState` per configured replica, derived
        entirely from the heartbeat ledger — uniform across every
        reader of the same ledger.  Snapshots are cached for a quarter
        heartbeat (the ledger's own update granularity); callers must
        not mutate the returned states."""
        cached, stamp = self._view_cache
        now = time.monotonic()
        if cached is not None and now - stamp < self._view_ttl_s:
            return cached
        out = []
        for rid in self.replicas:
            idle = self.reader.observe(rid)
            alive = idle is not None and idle <= self.cfg.deadline_s
            doc = self.reader.payload(rid) or {}
            out.append(ReplicaState(
                id=rid, alive=alive,
                ready=alive and bool(doc.get("ready")),
                draining=bool(doc.get("draining")),
                queue_depth=int(doc.get("queue_depth") or 0),
                params_step=doc.get("params_step"),
                last_batch_age_s=doc.get("last_batch_age_s"),
                port=doc.get("port"), pid=doc.get("pid"),
                idle_s=round(idle or 0.0, 3),
                tenants=doc.get("tenants")))
        self._view_cache = (out, now)
        return out

    def wait_ready(self, rids=None, deadline_s=None) -> bool:
        rids = set(map(str, rids)) if rids is not None \
            else set(self.replicas)
        deadline_s = self.cfg.spawn_s if deadline_s is None else deadline_s

        def _all_ready():
            return all(s.ready for s in self.view() if s.id in rids)

        return _wait_for(_all_ready, deadline_s, self.cfg.poll_s)

    # -- lifecycle -------------------------------------------------------
    def start(self, wait_ready=True) -> "ReplicaPool":
        get_journal().event("pool_start", root=self.root,
                            replicas=sorted(self.replicas),
                            heartbeat_s=self.cfg.heartbeat_s,
                            deadline_s=self.cfg.deadline_s,
                            run_id=self.run_id,
                            trace_dir=self.cfg.trace_dir)
        for rep in self.replicas.values():
            rep.start()
        if wait_ready and not self.wait_ready():
            laggards = [s.id for s in self.view() if not s.ready]
            raise MXNetError(
                f"replica pool did not become ready within "
                f"{self.cfg.spawn_s:g}s (not ready: {laggards}) — see "
                "the journal / worker stderr")
        return self

    def stop(self) -> None:
        self.monitor_stop()
        for rep in self.replicas.values():
            rep.stop()
        get_journal().event("pool_stop", root=self.root)

    def drain(self, rid, deadline_s=None) -> int:
        """Stop admission on one replica (the beacon flips not-ready so
        the router routes around it), then let its queue empty under a
        bounded deadline.  Returns the residual depth (0 = clean)."""
        rid = str(rid)
        deadline_s = self.cfg.drain_s if deadline_s is None else deadline_s
        with self._lock:
            residual = self.replicas[rid].drain(deadline_s)
        get_journal().event("pool_drain", replica=rid,
                            deadline_s=deadline_s, residual=residual)
        return residual

    def restart(self, rid, deadline_s=None, drain=True) -> None:
        """Draining restart: drain (bounded), replace the worker, wait
        ready.  The fresh worker loads the newest CRC-valid committed
        step from its checkpoint root — restart IS the upgrade path."""
        rid = str(rid)
        residual = self.drain(rid, deadline_s) if drain else None
        # an intentional restart resigns the beacon before the fresh
        # worker's first beat — give the monitor the same startup grace
        # as its own respawns, or it races this restart with another
        self._last_respawn[rid] = time.monotonic()
        with self._lock:
            self.replicas[rid].restart(deadline_s=deadline_s)
        ready = self.wait_ready([rid])
        get_journal().event("pool_restart", replica=rid,
                            residual=residual, ready=ready)
        if not ready:
            raise MXNetError(f"replica {rid!r} did not come back ready "
                             f"within {self.cfg.spawn_s:g}s after restart")

    # -- deploy ownership (serving/deploy.py) ----------------------------
    def deploy_acquire(self, owner) -> None:
        """Claim exclusive fleet-version ownership for a deployment.
        Raises :class:`DeployInProgress` when another deploy holds it —
        refused, not queued (two rollouts would tear old-xor-new)."""
        owner = str(owner)
        with self._lock:
            holder = self._deploy_owner
            if holder is None:
                self._deploy_owner = owner
        if holder is not None:
            raise DeployInProgress(holder, "deploy")

    def deploy_release(self, owner) -> None:
        """Release deploy ownership (idempotent; only the holder's tag
        releases)."""
        with self._lock:
            if self._deploy_owner == str(owner):
                self._deploy_owner = None

    def deploy_owner(self):
        with self._lock:
            return self._deploy_owner

    def pin_step(self, rid, step) -> bool:
        """Pin one replica to ``step`` (None unpins) through its handle
        — live store pin for in-process replicas, wire frame + respawn
        arg for subprocess workers.  Journaled so the deploy trail shows
        which replica was held on which version."""
        rid = str(rid)
        with self._lock:
            took = self.replicas[rid].pin(step)
        get_journal().event("pool_pin", replica=rid, step=step,
                            live=bool(took))
        return bool(took)

    def reload(self, surge=None, deadline_s=None) -> dict:
        """Rolling fleet upgrade: drain + restart every replica, at most
        ``surge`` out of rotation at a time, each restart landing on the
        newest CRC-valid committed step at ITS restart moment (a step
        published mid-roll splits the fleet across exactly the old and
        the new root — never a torn state).  Refused with
        :class:`DeployInProgress` while a canary deployment owns the
        pool.  Returns the post-roll ``{replica: params_step}`` map."""
        with self._lock:
            holder = self._deploy_owner
        if holder is not None:
            raise DeployInProgress(holder, "reload")
        surge = self.cfg.surge if surge is None else max(int(surge), 1)
        rids = sorted(self.replicas)
        get_journal().event("pool_reload", phase="begin", surge=surge,
                            replicas=rids)
        for i in range(0, len(rids), surge):
            wave = rids[i:i + surge]
            for rid in wave:
                self.restart(rid, deadline_s=deadline_s)
        steps = {s.id: s.params_step for s in self.view()}
        get_journal().event("pool_reload", phase="end", steps=steps)
        return steps

    # -- auto-respawn monitor -------------------------------------------
    def monitor_start(self, interval_s=None) -> None:
        """Watch the ledger; a replica whose heartbeat stalls past the
        deadline is journaled ``replica_lost`` and respawned (bounded by
        the per-replica crash-loop budget)."""
        if self._monitor is not None:
            return
        interval = self.cfg.monitor_s if interval_s is None else interval_s
        self._monitor_stop.clear()
        self._monitor = threading.Thread(
            target=self._monitor_run, args=(interval,), daemon=True,
            name="mxtpu-pool-monitor")
        self._monitor.start()

    def monitor_stop(self) -> None:
        self._monitor_stop.set()
        if self._monitor is not None:
            self._monitor.join(timeout=self.cfg.monitor_s + 5.0)
            self._monitor = None

    def _monitor_run(self, interval):
        while not self._monitor_stop.wait(interval):
            try:
                self._sweep_dead()
            except Exception as exc:       # the monitor must outlive one
                get_journal().crash(exc, where="pool_monitor")

    def _sweep_dead(self):
        now = time.monotonic()
        for state in self.view():
            if state.alive:
                continue
            # a just-respawned worker needs its startup window before
            # its first heartbeat can land — don't double-respawn it
            t = self._last_respawn.get(state.id)
            if t is not None and now - t < self.cfg.spawn_s:
                continue
            rep = self.replicas[state.id]
            proc_gone = rep.kind == "proc" and (
                rep.proc is None or rep.proc.poll() is not None)
            n = self._respawns.get(state.id, 0)
            get_journal().event("replica_lost", replica=state.id,
                                idle_s=state.idle_s, pid=state.pid,
                                proc_exited=proc_gone, respawns=n)
            if n >= self.cfg.max_respawns:
                get_journal().event("replica_respawn_exhausted",
                                    replica=state.id, respawns=n)
                self._last_respawn[state.id] = now   # re-log per window
                continue
            self._respawns[state.id] = n + 1
            self._last_respawn[state.id] = now
            with self._lock:
                rep.restart()

"""Sharding plans for serving predictors — tensor-parallel inference.

A :class:`ShardPlan` turns one serving model into a GSPMD program over a
named device mesh (SNIPPETS [2]: compile once against a ``NamedSharding``
and let XLA partition — the same executable scales from a 2-device host
mesh to a pod slice without code changes).  The plan owns three things:

1. **the mesh** — built from an axes spec (``{"model": -1}`` by default:
   every local device on the tensor-parallel axis; add ``"batch"``/
   ``"data"`` to also shard the request batch);
2. **parameter placement** — regex rules name → ``PartitionSpec``, with
   a default that column-shards 2-D+ weights on their OUTPUT dim over
   the ``model`` axis (dim 0 in MXNet's ``(out, in)`` layout — the
   ``P(None, "model")`` of SNIPPETS [2]'s ``(in, out)`` kernels) and
   replicates vectors/scalars.
   Specs are projected onto the mesh with the SAME helper the elastic
   survivor-mesh rebuild uses (``parallel.sharded.project_spec``), and a
   dim that doesn't divide by its axis extent degrades to replication —
   a plan can never produce an unplaceable array;
3. **activation placement** — the padded request batch rides the
   ``batch``/``data`` axis when the mesh has one (``P("batch", None)``),
   else it is replicated and only the weights are parallel.

Weights land on the mesh through ``elastic.reshard`` (``place_named``
at startup, ``place_global`` on hot reload) — only this process's
addressable shards ever touch a device, exactly how elastic restore
places assembled checkpoint entries onto a survivor mesh.

``plan.signature()`` joins ``parallel.mesh.mesh_signature`` with the
rule set; the AOT cache (serving/aotcache.py) folds it into the entry
key so a tensor-parallel replica warm-starts with zero XLA compiles
while single-device entries keep their pre-plan keys.
"""
from __future__ import annotations

import math
import os
import re

from ..base import MXNetError
from ..diagnostics.journal import get_journal
from ..parallel.mesh import make_mesh, mesh_signature
from ..parallel.sharded import project_spec

__all__ = ["ShardPlan", "parse_axes", "plan_from_env"]

# batch-axis aliases: repo convention is "data" (parallel/mesh.py), the
# GSPMD serving literature says "batch" — a plan accepts either name
_BATCH_AXES = ("batch", "data")


def parse_axes(spec):
    """``"model=-1"`` / ``"batch=2,model=4"`` → ordered axes dict.
    ``-1`` absorbs the remaining devices (``parallel.mesh.make_mesh``
    semantics)."""
    axes = {}
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise MXNetError(
                f"bad mesh axes spec {spec!r}: expected name=size pairs")
        name, _, size = part.partition("=")
        try:
            axes[name.strip()] = int(size)
        except ValueError:
            raise MXNetError(
                f"bad mesh axes spec {spec!r}: size {size!r} is not an "
                "integer") from None
    if not axes:
        raise MXNetError(f"bad mesh axes spec {spec!r}: no axes")
    return axes


def plan_from_env(devices=None):
    """A :class:`ShardPlan` from ``MXNET_TPU_SERVING_MESH`` (e.g.
    ``model=-1`` or ``batch=2,model=4``), or None when the knob is
    unset/empty — the single-device serving path stays exactly as
    before."""
    spec = os.environ.get("MXNET_TPU_SERVING_MESH", "").strip()
    if not spec or spec.lower() in ("off", "0", "none"):
        return None
    return ShardPlan(axes=parse_axes(spec), devices=devices)


class ShardPlan:
    """One model's tensor-parallel serving layout.

    ``axes``: mesh axes spec (dict / (name, size) pairs / the string
    form ``parse_axes`` accepts); default ``{"model": -1}``.
    ``param_rules``: ordered ``(regex, PartitionSpec)`` pairs matched
    against the structural parameter name (first match wins) before the
    default rule applies.  ``devices``: explicit device list (tests
    carve sub-meshes out of the 8-device CPU mesh with it).
    """

    def __init__(self, axes=None, param_rules=(), devices=None):
        from jax.sharding import PartitionSpec
        if isinstance(axes, str):
            axes = parse_axes(axes)
        self.axes = dict(axes) if axes else {"model": -1}
        self.mesh = make_mesh(self.axes, devices)
        self._P = PartitionSpec
        self.param_rules = tuple(
            (re.compile(pat), spec) for pat, spec in param_rules)
        self._axis_size = dict(zip(self.mesh.axis_names,
                                   self.mesh.devices.shape))
        self.model_axis = "model" if "model" in self._axis_size else None
        self.batch_axis = next((a for a in _BATCH_AXES
                                if a in self._axis_size), None)
        self.degraded = {}           # name -> requested spec that didn't
                                     # divide (served replicated instead)

    # -- spec derivation -------------------------------------------------
    def _divisible(self, name, shape, spec):
        """Degrade every dim whose extent doesn't divide by its mesh
        axes to replication — remembered in ``degraded`` so ``place``
        can journal the fallback instead of failing placement."""
        out = []
        clipped = False
        for d, entry in enumerate(spec):
            if entry is None:
                out.append(None)
                continue
            axes = entry if isinstance(entry, tuple) else (entry,)
            total = math.prod(self._axis_size.get(a, 1) for a in axes)
            if total <= 1 or (d < len(shape) and shape[d] % total == 0):
                out.append(entry)
            else:
                out.append(None)
                clipped = True
        if clipped:
            self.degraded[name] = str(spec)
        return self._P(*out)

    def param_spec(self, name, shape):
        """The (mesh-projected, divisibility-checked) PartitionSpec for
        one parameter."""
        shape = tuple(shape)
        for pat, spec in self.param_rules:
            if pat.search(name):
                return self._divisible(name, shape,
                                       project_spec(self.mesh, spec))
        if self.model_axis is None or len(shape) < 2:
            return self._P()         # vectors/scalars replicate
        # default tensor-parallel rule: shard the OUTPUT dim.  The GSPMD
        # reference (SNIPPETS [2]) writes P(None, "model") for (in, out)
        # kernels; MXNet blocks store (out, in) — Dense weight
        # (units, in_units), Conv (out_c, in_c, kh, kw) — so the output
        # dim is dim 0 here.  A column-split matmul concatenates, no
        # reduction crosses shards, so outputs stay bit-identical to the
        # single-device reference; custom (in, out) layouts opt into
        # P(None, "model") via param_rules.
        spec = self._P(*([self.model_axis] + [None] * (len(shape) - 1)))
        return self._divisible(name, shape, spec)

    def param_sharding(self, name, shape):
        from jax.sharding import NamedSharding
        return NamedSharding(self.mesh, self.param_spec(name, shape))

    def activation_spec(self, shape):
        """Batch rides the batch/data axis when the mesh has one and the
        padded batch divides; otherwise replicated (the bucket lattice
        pads batches to powers of two, so a power-of-two batch axis
        always divides)."""
        shape = tuple(shape)
        ax = self.batch_axis
        if ax is None or not shape or shape[0] % self._axis_size[ax]:
            return self._P()
        return self._P(*([ax] + [None] * (len(shape) - 1)))

    def activation_sharding(self, shape):
        from jax.sharding import NamedSharding
        return NamedSharding(self.mesh, self.activation_spec(shape))

    def replicated(self):
        from jax.sharding import NamedSharding
        return NamedSharding(self.mesh, self._P())

    # -- weight placement ------------------------------------------------
    @staticmethod
    def _params_of(block):
        """(structural name, Parameter) pairs in deterministic order —
        the same '0.weight' paths checkpoints are keyed by."""
        return list(block._structural_names().items())

    def place(self, block, site="serving"):
        """Land every parameter of ``block`` on its planned
        ``NamedSharding`` via ``elastic.reshard.place_named`` (only
        addressable shards touch a device).  Idempotent; journals one
        ``shard_place`` record.  Returns {name: spec string}."""
        from ..elastic import reshard as _reshard
        placed = {}
        for name, p in self._params_of(block):
            arr = p._data[0]
            host = arr.asnumpy()
            spec = self.param_spec(name, host.shape)
            arr._rebind(_reshard.place_named(name, self.mesh, spec, host))
            placed[name] = str(spec)
        get_journal().event(
            "shard_place", site=site, mesh=mesh_signature(self.mesh),
            params=len(placed),
            sharded=sum(1 for s in placed.values() if s != "PartitionSpec()"),
            degraded=sorted(self.degraded) or None)
        return placed

    def adopt_entries(self, block, entries):
        """Hot-reload lane: re-drop host arrays onto the LIVE params'
        exact shardings via ``elastic.reshard.place_global`` — the same
        call elastic restore uses, so a reload never silently changes a
        layout the compiled predictors were lowered against.  ``entries``
        maps structural names (arg:/aux: prefixes already normalized) to
        host arrays; params absent from it keep their current values.
        All-or-nothing: every entry is validated/placed before ANY
        rebind, so a torn checkpoint can't half-apply."""
        from ..elastic import reshard as _reshard
        staged = []
        for name, p in self._params_of(block):
            if name not in entries:
                continue
            arr = p._data[0]
            staged.append(
                (arr, _reshard.place_global(name, arr._data,
                                            entries[name])))
        for arr, placed in staged:
            arr._rebind(placed)
        return len(staged)

    # -- identity --------------------------------------------------------
    def signature(self):
        """Stable identity of the plan: the mesh signature joined with
        the rule set — folded into AOT cache keys and journaled on
        placement."""
        return {"mesh": mesh_signature(self.mesh),
                "rules": [[pat.pattern, str(spec)]
                          for pat, spec in self.param_rules]}

    def fingerprint_token(self):
        """Compact deterministic string form of :meth:`signature` for
        cache-key material."""
        sig = self.signature()
        mesh = sig["mesh"]
        axes = ",".join(f"{k}={v}" for k, v in mesh["axes"].items())
        rules = ";".join(f"{p}->{s}" for p, s in sig["rules"])
        return f"mesh[{mesh['devices']}:{axes}]rules[{rules}]"

    def __repr__(self):
        return f"ShardPlan({self.fingerprint_token()})"

"""Canary-gated deployment controller — the guarded train→serve loop.

Trainers commit CRC-valid steps (resilience/commit.py), `ParamStore`
hot-reloads them, the pool rolls restarts, the router stamps every
response with its ``params_step`` — but PROMOTING a new commit root to
the whole fleet was still an unguarded, all-or-nothing action.
:class:`DeployController` closes that gap (ROADMAP item 5):

1. **canary** — the new step is pinned onto exactly ``canary_k``
   replicas (``ParamStore.pin_step`` + the server's pin lane applies it
   in place between batches; ``restart=True`` opts into draining
   restarts instead, reusing the ``reload(surge=k)`` mechanics).  Every
   OTHER replica is pinned to the old step first, so nothing outside
   the canary set can adopt the new root mid-deploy — the blast radius
   is exactly k replicas by construction.
2. **gate** — promotion is decided by LIVE statistics, not hope: every
   ``window_s`` the controller compares canary vs control traffic from
   the router's deploy tap (fresh per-arm ``LatencySummary`` p99s,
   served/failure counts), the router counters (shed rate), the ledger
   (a canary losing its heartbeat or entering breaker-open is an
   immediate breach), and sampled output parity — a fraction of
   control-served requests is mirrored onto a canary replica and the
   answers compared tolerance-gated (``deploy_mirror_mismatch``).
3. **promote / rollback** — ``promote_after`` consecutive clean gates
   roll the remaining replicas forward (pin to the new step, in-place).
   ANY gate breach rolls back: the canary replicas are re-pinned to the
   old step, and the pins STAY installed afterwards so a rolled-back
   replica cannot silently re-adopt the bad root on its next poll
   (the operator — or the next successful deploy — unpins).

Every transition (``deploy_start``/``canary_up``/``gate_eval``/
``promote``/``rollback``/``deploy_done``) is journaled under ONE
``deploy`` trace span, so ``doctor --serving-journal`` renders the
whole trail trace-correlated (docs/serving.md, canary deployment).

Concurrent fleet mutations are refused, not queued: ``pool.reload()``
or a second ``deploy()`` during a live canary raises the structured
:class:`~.pool.DeployInProgress` — two rollouts would tear the
old-xor-new response contract.
"""
from __future__ import annotations

import os
import time
from collections import Counter
from dataclasses import dataclass, field

from ..base import MXNetError
from ..diagnostics.journal import get_journal
from ..observability import trace as _trace
from ..resilience import commit as _commit
from .pool import _wait_for

__all__ = ["DeployConfig", "DeployController"]


def _env_float(name, default):
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def _env_int(name, default):
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


@dataclass
class DeployConfig:
    """Canary-deployment knobs (docs/serving.md; ``MXNET_TPU_DEPLOY_*``
    env vars set fleet-wide defaults)."""

    canary_k: int = field(default_factory=lambda: _env_int(
        "MXNET_TPU_DEPLOY_CANARY_K", 1))
    window_s: float = field(default_factory=lambda: _env_float(
        "MXNET_TPU_DEPLOY_WINDOW_S", 2.0))       # gate-eval cadence
    promote_after: int = field(default_factory=lambda: _env_int(
        "MXNET_TPU_DEPLOY_PROMOTE_AFTER", 3))    # consecutive clean gates
    min_samples: int = field(default_factory=lambda: _env_int(
        "MXNET_TPU_DEPLOY_MIN_SAMPLES", 20))     # per arm, before verdicts
    p99_ratio: float = field(default_factory=lambda: _env_float(
        "MXNET_TPU_DEPLOY_P99_RATIO", 2.0))      # canary/control ceiling
    p99_floor_ms: float = field(default_factory=lambda: _env_float(
        "MXNET_TPU_DEPLOY_P99_FLOOR_MS", 50.0))  # ignore sub-floor deltas
    error_delta: float = field(default_factory=lambda: _env_float(
        "MXNET_TPU_DEPLOY_ERROR_DELTA", 0.05))   # failure-rate ceiling
    shed_ceiling: float = field(default_factory=lambda: _env_float(
        "MXNET_TPU_DEPLOY_SHED_CEILING", 0.2))   # window shed-rate ceiling
    mirror_fraction: float = field(default_factory=lambda: _env_float(
        "MXNET_TPU_DEPLOY_MIRROR_FRACTION", 0.25))
    mirror_rtol: float = field(default_factory=lambda: _env_float(
        "MXNET_TPU_DEPLOY_MIRROR_RTOL", 1e-5))
    mirror_atol: float = field(default_factory=lambda: _env_float(
        "MXNET_TPU_DEPLOY_MIRROR_ATOL", 1e-6))
    mismatch_budget: int = field(default_factory=lambda: _env_int(
        "MXNET_TPU_DEPLOY_MISMATCH_BUDGET", 0))  # > budget mismatches trip
    rollback_s: float = field(default_factory=lambda: _env_float(
        "MXNET_TPU_DEPLOY_ROLLBACK_S", 30.0))    # rollback deadline budget
    deadline_s: float = field(default_factory=lambda: _env_float(
        "MXNET_TPU_DEPLOY_DEADLINE_S", 600.0))   # whole-deploy bound; a
                                                 # gate stuck "insufficient"
                                                 # rolls back, never hangs
    restart: bool = False      # True: draining restart per canary (the
                               # reload(surge=k) mechanics) instead of the
                               # in-place pin lane
    poll_s: float = 0.05

    def __post_init__(self):
        if self.canary_k < 1:
            raise MXNetError("deploy canary_k must be >= 1")
        if self.window_s <= 0:
            raise MXNetError("deploy window_s must be > 0")
        if self.promote_after < 1:
            raise MXNetError("deploy promote_after must be >= 1")
        if not 0.0 <= self.mirror_fraction <= 1.0:
            raise MXNetError("deploy mirror_fraction must be in [0, 1]")
        if self.rollback_s <= 0:
            raise MXNetError("deploy rollback_s must be > 0")
        if self.deadline_s <= self.window_s:
            raise MXNetError(
                f"deploy deadline_s ({self.deadline_s:g}) must exceed "
                f"window_s ({self.window_s:g}) — the deadline must admit "
                "at least one gate evaluation")


def _newest_valid_step(root):
    """Newest committed step that passes CRC validation right now, or
    None.  Mirrors ParamStore's skip-don't-die posture: a torn newest
    step must not wedge a deploy onto it."""
    for step in sorted(_commit.committed_steps(root), reverse=True):
        try:
            _commit.validate_step(root, step)
            return step
        except ValueError:
            continue
    return None


class DeployController:
    """Drives one :class:`~.pool.ReplicaPool` + :class:`~.router.Router`
    pair through canary → gate → promote/rollback for one commit root.
    ``deploy()`` blocks until the terminal state and returns the result
    document; it is safe to call again afterwards (one deploy at a
    time — a concurrent call raises ``DeployInProgress``)."""

    def __init__(self, pool, router, root, config=None):
        self.pool = pool
        self.router = router
        self.root = str(root)
        self.cfg = config or DeployConfig()
        self._tag = f"deploy-{os.urandom(3).hex()}"

    # -- step resolution -------------------------------------------------
    def _fleet_step(self):
        """The step the fleet currently serves (the rollback target):
        the most common non-None beacon step, larger step on ties."""
        steps = [s.params_step for s in self.pool.view()
                 if s.params_step is not None]
        if not steps:
            return None
        ranked = Counter(steps).most_common()
        top = ranked[0][1]
        return max(st for st, n in ranked if n == top)

    # -- the state machine -----------------------------------------------
    def deploy(self, step=None) -> dict:
        """Run one full deployment; returns
        ``{"result": "promoted"|"rolled_back"|"noop", ...}``.  Raises
        ``DeployInProgress`` when another deploy owns the pool, and
        ``MXNetError`` when there is nothing valid to deploy or no
        served baseline to roll back to."""
        cfg = self.cfg
        new_step = _newest_valid_step(self.root) if step is None \
            else int(step)
        if new_step is None:
            raise MXNetError(
                f"nothing to deploy: no CRC-valid committed step under "
                f"{self.root!r}")
        if step is not None:
            _commit.validate_step(self.root, new_step)  # fail fast, loudly
        old_step = self._fleet_step()
        if old_step is None:
            raise MXNetError(
                "cannot canary: no replica advertises a served "
                "params_step — the fleet needs a committed baseline to "
                "roll back to before a gated deploy makes sense")
        rids = sorted(self.pool.replicas)
        if cfg.canary_k >= len(rids):
            raise MXNetError(
                f"canary_k ({cfg.canary_k}) must leave at least one "
                f"control replica (pool has {len(rids)})")
        if new_step == old_step:
            get_journal().event("deploy_done", result="noop",
                                from_step=old_step, to_step=new_step)
            return {"result": "noop", "from_step": old_step,
                    "to_step": new_step}
        self.pool.deploy_acquire(self._tag)     # DeployInProgress if held
        try:
            with _trace.span("deploy", root=self.root,
                             from_step=old_step, to_step=new_step):
                return self._run(rids, old_step, new_step)
        finally:
            self.router.clear_deploy()
            self.pool.deploy_release(self._tag)

    def _run(self, rids, old_step, new_step):
        cfg = self.cfg
        j = get_journal()
        canary = rids[:cfg.canary_k]
        control = rids[cfg.canary_k:]
        j.event("deploy_start", root=self.root, from_step=old_step,
                to_step=new_step, canary=canary, control=control,
                window_s=cfg.window_s, promote_after=cfg.promote_after,
                mirror_fraction=cfg.mirror_fraction,
                restart=cfg.restart, tag=self._tag)
        t_deploy = time.monotonic()
        # control pins FIRST: once these land, nothing outside the
        # canary set can adopt the new root — the blast-radius bound
        for rid in control:
            self.pool.pin_step(rid, old_step)
        for rid in canary:
            self.pool.pin_step(rid, new_step)
            if cfg.restart:
                self.pool.restart(rid, deadline_s=cfg.rollback_s)
        canary_set = set(canary)
        up = _wait_for(
            lambda: all(s.params_step == new_step
                        for s in self.pool.view() if s.id in canary_set),
            cfg.deadline_s / 2.0, cfg.poll_s)
        if not up:
            # the new step pinned but never became the served version
            # (failed to apply: architecture drift, torn read) — there
            # is no canary to evaluate, only a version to back out
            return self._rollback(
                canary, control, old_step, new_step,
                reason="canary_startup",
                detail="canary replicas never converged on the new step",
                gate_evals=0, t_deploy=t_deploy)
        tap = self.router.set_deploy(
            canary, mirror_fraction=cfg.mirror_fraction,
            rtol=cfg.mirror_rtol, atol=cfg.mirror_atol)
        j.event("canary_up", replicas=canary, step=new_step,
                up_ms=round((time.monotonic() - t_deploy) * 1000.0, 1))
        base = self.router.stats()              # shed-window baseline
        deadline = time.monotonic() + cfg.deadline_s
        passes = evals = 0
        breach = None
        while time.monotonic() < deadline:      # G13: bounded gate loop
            time.sleep(cfg.window_s)
            evals += 1
            verdict, metrics = self._evaluate(canary_set, base)
            j.event("gate_eval", n=evals, verdict=verdict["verdict"],
                    reasons=verdict["reasons"], **metrics)
            self._mirror_gauges(evals, verdict["verdict"])
            if verdict["verdict"] == "breach":
                breach = verdict
                break
            if verdict["verdict"] == "pass":
                passes += 1
                if passes >= cfg.promote_after:
                    break
            # "insufficient" neither passes nor resets: low traffic is
            # not evidence either way — the deploy deadline bounds it
        if breach is not None:
            return self._rollback(
                canary, control, old_step, new_step,
                reason=breach["reasons"][0],
                detail=breach, gate_evals=evals, t_deploy=t_deploy)
        if passes < cfg.promote_after:
            # deadline expired without enough clean gates: conservative
            # outcome is the old version, never a coin-flip promote
            return self._rollback(
                canary, control, old_step, new_step,
                reason="deploy_deadline",
                detail=f"only {passes} clean gates in {cfg.deadline_s:g}s",
                gate_evals=evals, t_deploy=t_deploy)
        return self._promote(rids, canary, control, old_step, new_step,
                             evals, t_deploy)

    # -- gate evaluation -------------------------------------------------
    def _evaluate(self, canary_set, base):
        """One gate evaluation: returns ``({verdict, reasons}, metrics)``
        where verdict is ``pass`` / ``insufficient`` / ``breach``.
        Hard signals (canary lost, breaker open) breach immediately even
        before the arms reach ``min_samples``."""
        cfg = self.cfg
        st = self.router.stats()
        dep = st.get("deploy") or {}
        reasons = []
        # hard signals: the ledger + breaker already decided this canary
        # is unhealthy — no statistics needed
        for s in self.pool.view():
            if s.id in canary_set and not s.alive:
                reasons.append("canary_lost")
                break
        for rid in canary_set:
            if (st["replicas"].get(rid) or {}).get("breaker") == "open":
                reasons.append("canary_breaker_open")
                break
        # output parity: mirrored control requests answered differently
        if dep.get("mirror_mismatch", 0) > cfg.mismatch_budget:
            reasons.append("parity")
        # window shed rate (router-level, both arms: a deploy that
        # starves the fleet's capacity floor is a regression even if
        # the canary itself looks healthy)
        d_req = st["requests"] - base["requests"]
        d_shed = (st["shed"] + st["no_capacity"]
                  - base["shed"] - base["no_capacity"])
        shed_rate = (d_shed / d_req) if d_req > 0 else 0.0
        if d_req > 0 and shed_rate > cfg.shed_ceiling:
            reasons.append("shed_rate")
        c_n = dep.get("canary_count", 0)
        k_n = dep.get("control_count", 0)
        c_p99 = dep.get("canary_p99_ms")
        k_p99 = dep.get("control_p99_ms")
        sufficient = c_n >= cfg.min_samples and k_n >= cfg.min_samples
        if sufficient:
            if c_p99 is not None and k_p99 is not None \
                    and c_p99 > k_p99 * cfg.p99_ratio \
                    and c_p99 > k_p99 + cfg.p99_floor_ms:
                reasons.append("p99")
            served = dep.get("served", {})
            fails = dep.get("failures", {})

            def rate(arm):
                n = served.get(arm, 0) + fails.get(arm, 0)
                return (fails.get(arm, 0) / n) if n else 0.0

            if rate("canary") - rate("control") > cfg.error_delta:
                reasons.append("error_rate")
        metrics = {
            "canary_p99_ms": c_p99, "control_p99_ms": k_p99,
            "canary_count": c_n, "control_count": k_n,
            "canary_served": dep.get("served", {}).get("canary", 0),
            "control_served": dep.get("served", {}).get("control", 0),
            "canary_failures": dep.get("failures", {}).get("canary", 0),
            "control_failures": dep.get("failures", {}).get("control", 0),
            "mirrors": dep.get("mirrors", 0),
            "mirror_mismatch": dep.get("mirror_mismatch", 0),
            "mirror_errors": dep.get("mirror_errors", 0),
            "shed_rate": round(shed_rate, 4)}
        if reasons:
            verdict = "breach"
        elif not sufficient:
            verdict = "insufficient"
        else:
            verdict = "pass"
        return {"verdict": verdict, "reasons": reasons}, metrics

    # -- terminal transitions --------------------------------------------
    def _promote(self, rids, canary, control, old_step, new_step, evals,
                 t_deploy):
        cfg = self.cfg
        j = get_journal()
        j.event("promote", step=new_step, from_step=old_step,
                replicas=control, gate_evals=evals)
        # gates are over: stop tagging/mirroring before the control arm
        # starts moving, or the tap would compare a fleet against itself
        self.router.clear_deploy()
        for rid in control:
            self.pool.pin_step(rid, new_step)
        converged = _wait_for(
            lambda: all(s.params_step == new_step
                        for s in self.pool.view() if s.alive),
            cfg.rollback_s, cfg.poll_s)
        if not converged:
            # rollback-during-promote: part of the fleet refused the new
            # step — a half-promoted fleet is the one state the version
            # contract cannot tolerate, so everyone goes back to old
            return self._rollback(
                rids, [], old_step, new_step, reason="promote_stall",
                detail="control replicas never converged on the new step",
                gate_evals=evals, t_deploy=t_deploy)
        for rid in rids:
            self.pool.pin_step(rid, None)      # resume newest-wins polling
        doc = {"result": "promoted", "from_step": old_step,
               "to_step": new_step, "canary": canary,
               "gate_evals": evals,
               "deploy_ms": round((time.monotonic() - t_deploy) * 1000.0,
                                  1)}
        j.event("deploy_done", **doc)
        self._done_gauges("promoted", evals)
        return doc

    def _rollback(self, canary, control, old_step, new_step, reason,
                  detail, gate_evals, t_deploy):
        """Re-pin every affected replica to the old step and wait (within
        the rollback deadline budget) for the live versions to converge.
        The pins STAY installed: the bad root remains committed on disk,
        and an unpinned store would re-adopt it on its next poll."""
        cfg = self.cfg
        j = get_journal()
        t0 = time.monotonic()
        j.event("rollback", reason=reason, detail=str(detail)[:300],
                from_step=new_step, to_step=old_step,
                replicas=list(canary), gate_evals=gate_evals)
        self.router.clear_deploy()             # stop mirroring first
        for rid in canary:
            self.pool.pin_step(rid, old_step)
        canary_set = set(canary)
        converged = _wait_for(
            lambda: all(s.params_step == old_step
                        for s in self.pool.view()
                        if s.id in canary_set and s.alive),
            cfg.rollback_s, cfg.poll_s)
        # a dead canary (SIGKILL) converges later: its respawn starts
        # pinned to old_step through the handle's remembered pin
        doc = {"result": "rolled_back", "reason": reason,
               "from_step": old_step, "to_step": new_step,
               "canary": list(canary), "gate_evals": gate_evals,
               "converged": bool(converged),
               "rollback_ms": round((time.monotonic() - t0) * 1000.0, 1),
               "deploy_ms": round((time.monotonic() - t_deploy) * 1000.0,
                                  1)}
        j.event("deploy_done", **doc)
        self._done_gauges("rolled_back", gate_evals)
        return doc

    # -- metrics wiring (observability/metrics.py) -----------------------
    _STATE_CODE = {"canary": 1, "promoted": 2, "rolled_back": 3}

    def _mirror_gauges(self, evals, verdict):
        from ..observability import metrics as _m
        reg = _m.default_registry()
        reg.gauge("mxnet_tpu_deploy_state",
                  "deploy state (0 idle, 1 canary, 2 promoted, "
                  "3 rolled back)").set(self._STATE_CODE["canary"])
        reg.gauge("mxnet_tpu_deploy_gate_evals",
                  "gate evaluations this deployment").set(evals)
        if verdict == "breach":
            reg.counter("mxnet_tpu_deploy_gate_breaches_total",
                        "gate breaches across deployments").inc()

    def _done_gauges(self, result, evals):
        from ..observability import metrics as _m
        reg = _m.default_registry()
        reg.gauge("mxnet_tpu_deploy_state",
                  "deploy state (0 idle, 1 canary, 2 promoted, "
                  "3 rolled back)").set(self._STATE_CODE[result])
        reg.gauge("mxnet_tpu_deploy_gate_evals",
                  "gate evaluations this deployment").set(evals)
        reg.counter("mxnet_tpu_deploy_total",
                    "terminal deployments by result",
                    ("result",)).labels(result=result).inc()

"""``mxnet_tpu.serving`` — dynamic-batching inference subsystem.

The paper's core mechanism — Gluon ``HybridBlock.hybridize()`` lowering
to ONE jitted XLA computation (``CachedOp`` ≡ ``jax.jit``, SURVEY §7) —
is an inference-serving primitive; this package is the serving story
around it (docs/serving.md):

- :mod:`.buckets` — the shape lattice that bounds XLA compiles by
  configuration instead of traffic;
- :mod:`.batcher` — bounded admission, deadline bookkeeping, micro-batch
  coalescing (stdlib threads + queues, no server framework);
- :mod:`.cache` — a bounded LRU of compiled predictors built on
  ``gluon.block.functional_apply`` (params as runtime args: hot-reload
  retraces nothing);
- :mod:`.server` — the worker loop: shed → coalesce → pad → execute →
  deadline-check, journaled per batch;
- :mod:`.reload` — newest-valid-committed-step hot-reload over
  ``resilience.commit`` (a torn checkpoint can never reach a response);
- :mod:`.report` — stdlib journal summarizer for
  ``python -m mxnet_tpu.diagnostics doctor --serving-journal``;
- ``python -m mxnet_tpu.serving bench`` — closed-loop load generator
  emitting a ``BENCH_serving`` JSON artifact.

Lazy exports (PEP 562): importing the package — or its stdlib-only
submodules ``buckets``/``batcher``/``report`` — touches neither jax nor
the runtime, so the doctor can summarize a serving journal while the
backend is wedged.
"""
from __future__ import annotations

import importlib

__all__ = ["BucketGrid", "CompiledPredictor", "DeadlineExceeded",
           "ParamStore", "PendingResponse", "PredictorCache",
           "RequestError", "Server", "ServerConfig", "ServerOverloaded",
           "serving_report"]

_LAZY = {
    "BucketGrid": ("buckets", "BucketGrid"),
    "CompiledPredictor": ("cache", "CompiledPredictor"),
    "DeadlineExceeded": ("batcher", "DeadlineExceeded"),
    "ParamStore": ("reload", "ParamStore"),
    "PendingResponse": ("batcher", "PendingResponse"),
    "PredictorCache": ("cache", "PredictorCache"),
    "RequestError": ("batcher", "RequestError"),
    "Server": ("server", "Server"),
    "ServerConfig": ("server", "ServerConfig"),
    "ServerOverloaded": ("batcher", "ServerOverloaded"),
    "serving_report": ("report", "serving_report"),
}


def __getattr__(name):
    try:
        mod_name, attr = _LAZY[name]
    except KeyError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}") from None
    mod = importlib.import_module(f".{mod_name}", __name__)
    value = getattr(mod, attr)
    globals()[name] = value          # cache: subsequent lookups are direct
    return value


def __dir__():
    return sorted(set(globals()) | set(__all__))

"""``mxnet_tpu.serving`` — dynamic-batching inference subsystem.

The paper's core mechanism — Gluon ``HybridBlock.hybridize()`` lowering
to ONE jitted XLA computation (``CachedOp`` ≡ ``jax.jit``, SURVEY §7) —
is an inference-serving primitive; this package is the serving story
around it (docs/serving.md):

- :mod:`.buckets` — the shape lattice that bounds XLA compiles by
  configuration instead of traffic;
- :mod:`.batcher` — bounded admission, deadline bookkeeping, micro-batch
  coalescing (stdlib threads + queues, no server framework);
- :mod:`.cache` — a bounded LRU of compiled predictors built on
  ``gluon.block.functional_apply`` (params as runtime args: hot-reload
  retraces nothing);
- :mod:`.aotcache` / :mod:`.aot_report` — the persistent tier behind
  that LRU: serialized AOT executables on disk, keyed by (padded
  shape, dtype, param-tree structure fingerprint) under a CRC +
  jax/jaxlib/backend envelope, so a restart, pool-worker respawn, or
  tenant page-in *loads* its bucket lattice instead of recompiling it
  (zero-cold-start; ``aot_report`` is the stdlib audit half);
- :mod:`.shardplan` — the tensor-parallel serving plan: a
  ``NamedSharding`` per parameter/activation derived from one axes
  spec, so predictors compile GSPMD-partitioned and checkpoint shards
  land on the serving mesh exactly as elastic restore would place them;
- :mod:`.decode` — the continuous-batching decode engine beside the
  one-shot batcher: a fixed slot pool, prefill/decode split, per-step
  rebatching on a dedicated single-cell lattice (decode never compiles
  outside it), per-sequence deadlines/cancellation;
- :mod:`.server` — the worker loop: shed → coalesce → pad → execute →
  deadline-check, journaled per batch;
- :mod:`.reload` — newest-valid-committed-step hot-reload over
  ``resilience.commit`` (a torn checkpoint can never reach a response);
- :mod:`.fleet` — the multi-tenant tier: a tenant registry (model +
  commit root + SLO class per tenant, hot add/remove/reload),
  SLO-classed admission (priority, deadline floor, token-bucket rate
  budget; shedding per tenant class first, never global), per-tenant
  fault domains (corrupt checkpoint / shape flood / predictor poison
  quarantine ONE tenant behind a half-open-probed breaker), and weight
  paging for cold tenants (host-RAM tier → device on demand, LRU over
  the hot set, page-in cost journaled);
- :mod:`.pool` / :mod:`.router` / :mod:`.worker` / :mod:`.wire` — the
  fault-tolerant replica tier: N Server replicas (in-process or
  subprocess workers) heartbeating readiness beacons onto an
  ``elastic.membership`` ledger, behind a health-routed front door
  with deadline-scoped retries, tail-latency hedging, per-replica
  circuit breakers, draining restarts, and capacity-floor degradation
  tiers (docs/serving.md);
- :mod:`.report` — stdlib journal summarizer for
  ``python -m mxnet_tpu.diagnostics doctor --serving-journal``;
- ``python -m mxnet_tpu.serving bench`` — closed-loop load generator
  emitting a ``BENCH_serving`` JSON artifact.

Lazy exports (PEP 562): importing the package — or its stdlib-only
submodules ``buckets``/``batcher``/``report`` — touches neither jax nor
the runtime, so the doctor can summarize a serving journal while the
backend is wedged.
"""
from __future__ import annotations

import importlib

__all__ = ["AOTCache", "BucketGrid", "CompiledPredictor",
           "DeadlineExceeded", "DecodeConfig", "DecodeEngine",
           "DecodeModel", "DecodeStream",
           "DeployConfig", "DeployController", "DeployInProgress",
           "Fleet", "FleetConfig", "LocalReplica", "ParamStore",
           "PendingResponse", "PoolConfig",
           "PredictorCache", "ProcReplica", "ReplicaPool",
           "ReplicaUnavailable", "RequestCancelled", "RequestError",
           "Router", "RouterConfig", "RouterResponse", "SLOClass",
           "Server", "ServerConfig", "ServerOverloaded", "ServerStopped",
           "ShardPlan", "SlotsExhausted",
           "TenantQuarantined", "TinyLM", "serving_report"]

_LAZY = {
    "AOTCache": ("aotcache", "AOTCache"),
    "BucketGrid": ("buckets", "BucketGrid"),
    "CompiledPredictor": ("cache", "CompiledPredictor"),
    "DeadlineExceeded": ("batcher", "DeadlineExceeded"),
    "DecodeConfig": ("decode", "DecodeConfig"),
    "DecodeEngine": ("decode", "DecodeEngine"),
    "DecodeModel": ("decode", "DecodeModel"),
    "DecodeStream": ("decode", "DecodeStream"),
    "DeployConfig": ("deploy", "DeployConfig"),
    "DeployController": ("deploy", "DeployController"),
    "DeployInProgress": ("pool", "DeployInProgress"),
    "Fleet": ("fleet", "Fleet"),
    "FleetConfig": ("fleet", "FleetConfig"),
    "SLOClass": ("fleet", "SLOClass"),
    "TenantQuarantined": ("fleet", "TenantQuarantined"),
    "LocalReplica": ("pool", "LocalReplica"),
    "ParamStore": ("reload", "ParamStore"),
    "PendingResponse": ("batcher", "PendingResponse"),
    "PoolConfig": ("pool", "PoolConfig"),
    "PredictorCache": ("cache", "PredictorCache"),
    "ProcReplica": ("pool", "ProcReplica"),
    "ReplicaPool": ("pool", "ReplicaPool"),
    "ReplicaUnavailable": ("pool", "ReplicaUnavailable"),
    "RequestCancelled": ("batcher", "RequestCancelled"),
    "RequestError": ("batcher", "RequestError"),
    "Router": ("router", "Router"),
    "RouterConfig": ("router", "RouterConfig"),
    "RouterResponse": ("router", "RouterResponse"),
    "Server": ("server", "Server"),
    "ServerConfig": ("server", "ServerConfig"),
    "ServerOverloaded": ("batcher", "ServerOverloaded"),
    "ServerStopped": ("batcher", "ServerStopped"),
    "ShardPlan": ("shardplan", "ShardPlan"),
    "SlotsExhausted": ("batcher", "SlotsExhausted"),
    "TinyLM": ("decode", "TinyLM"),
    "serving_report": ("report", "serving_report"),
}


def __getattr__(name):
    try:
        mod_name, attr = _LAZY[name]
    except KeyError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}") from None
    mod = importlib.import_module(f".{mod_name}", __name__)
    value = getattr(mod, attr)
    globals()[name] = value          # cache: subsequent lookups are direct
    return value


def __dir__():
    return sorted(set(globals()) | set(__all__))

"""The serving core: admission → dynamic batching → compiled predictors.

One worker thread owns the device (the reference's single-executor
discipline, ``native/predict.cc``): callers enqueue single samples into
a **bounded** queue (admission control — a full queue sheds with
:class:`~.batcher.ServerOverloaded` instead of growing latency),
the worker coalesces same-bucket requests under a deadline window, pads
to the bucket grid, and runs ONE jitted executable per padded shape from
the bounded :class:`~.cache.PredictorCache`.  Per-request deadlines are
honored at dequeue and post-batch; transient device errors ride
``resilience.retry``; parameters hot-reload between batches from the
newest valid committed checkpoint step (:class:`~.reload.ParamStore`)
with zero draining.  Every batch journals a structured record
(``serving_batch``) the diagnostics doctor summarizes.
"""
from __future__ import annotations

import itertools
import os
import queue
import threading
import time
from dataclasses import dataclass, field

import numpy as np

from ..base import MXNetError
from ..diagnostics.journal import get_journal
from ..metric import LatencySummary
from ..observability import instrument as _obs
from ..observability import trace as _trace
from ..resilience import atomic as _atomic
from ..resilience.retry import retry_call
from .batcher import (DeadlineExceeded, PendingResponse, Request,
                      RequestCancelled, RequestError, ServerOverloaded,
                      ServerStopped, drop_expired, take_batch)
from .buckets import BucketGrid
from .cache import CompiledPredictor, PredictorCache

__all__ = ["Server", "ServerConfig"]

_STOP = object()
_server_seq = itertools.count()


def _req_ids(req) -> dict:
    """trace_id/span_id of a request's root span for explicit journal
    correlation (the root is started manually at submit, so the
    thread-local provider can't see it); {} with tracing off."""
    sp = req.trace
    if sp is None or sp.trace_id is None:
        return {}
    return {"trace_id": sp.trace_id, "span_id": sp.span_id}


def _end_span(req, status):
    """Close a request's root span (idempotent; None-safe for Requests
    built outside submit — batcher unit tests)."""
    if req.trace is not None:
        req.trace.end(status=status)


def _env_int(name, default):
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def _env_float(name, default):
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


@dataclass
class ServerConfig:
    """Serving knobs (docs/serving.md has the tuning guide; the
    ``MXNET_TPU_SERVING_*`` env vars set fleet-wide defaults)."""

    max_batch: int = 8                       # largest coalesced batch
    batch_buckets: tuple | None = None       # default: powers of 2
    dim_buckets: dict | None = None          # {feature axis: sizes}
    max_queue: int = field(default_factory=lambda: _env_int(
        "MXNET_TPU_SERVING_MAX_QUEUE", 128))
    window_ms: float = field(default_factory=lambda: _env_float(
        "MXNET_TPU_SERVING_WINDOW_MS", 5.0))
    default_deadline_ms: float = field(default_factory=lambda: _env_float(
        "MXNET_TPU_SERVING_DEADLINE_MS", 2000.0))
    cache_entries: int = field(default_factory=lambda: _env_int(
        "MXNET_TPU_SERVING_CACHE", 16))
    reload_poll_s: float = field(default_factory=lambda: _env_float(
        "MXNET_TPU_SERVING_RELOAD_S", 10.0))
    # persistent AOT executable cache (serving/aotcache.py): a restart
    # on the same dir loads executables instead of compiling them
    aot_dir: str | None = field(default_factory=lambda: os.environ.get(
        "MXNET_TPU_AOT_CACHE_DIR") or None)
    aot_prewarm: tuple | None = None         # feature shapes warmed at start
    idle_poll_s: float = 0.05                # worker wake granularity
    dtype: str = "float32"                   # request payload dtype
    pad_value: float = 0.0
    crop_outputs: bool = True                # unpad outputs that kept dims
    device_retries: int = 2                  # transient-error retries
    transient_errors: tuple = (OSError,)     # retried via resilience.retry
    result_timeout_s: float = 60.0           # PendingResponse default wait
    # tensor-parallel serving (serving/shardplan.py): a ShardPlan, or
    # None for the historical single-device path.  Left None, the
    # Server also consults MXNET_TPU_SERVING_MESH at construction
    # (plan_from_env) so a worker can opt in by environment alone.
    shard_plan: object = None
    # continuous-batching decode (serving/decode.py): a DecodeModel to
    # serve autoregressive streams beside the one-shot batcher; its
    # knobs ride ``decode`` (a DecodeConfig; None = env defaults)
    decode_model: object = None
    decode: object = None

    def summary(self) -> dict:
        return {"max_batch": self.max_batch, "max_queue": self.max_queue,
                "window_ms": self.window_ms,
                "default_deadline_ms": self.default_deadline_ms,
                "cache_entries": self.cache_entries,
                "reload_poll_s": self.reload_poll_s, "dtype": self.dtype,
                "aot_dir": self.aot_dir,
                "decode": None if self.decode_model is None
                else type(self.decode_model).__name__,
                "shard_plan": None if self.shard_plan is None
                else self.shard_plan.fingerprint_token()}


def _apply_tuned_server(cfg) -> None:
    """Fill serving knobs from the active tuned table (autotune.table,
    ``MXNET_TPU_TUNED_TABLE``) — but only where NOTHING else chose the
    value: an explicit env var or a constructor argument that moved a
    knob off its built-in default always wins over the table (explicit
    > tuned > built-in).  Applied values journal one ``tuned_load``;
    an invalid/stale/mismatched table journals ``tuned_fallback`` in
    the loader and changes nothing here."""
    from ..autotune import table as _tt
    doc = _tt.tuned_for("server")
    if doc is None:
        return
    applied = {}
    if "MXNET_TPU_SERVING_WINDOW_MS" not in os.environ \
            and cfg.window_ms == 5.0:
        w = _tt.knob(doc, "serving", "window_ms")
        if w is not None and float(w) != cfg.window_ms:
            cfg.window_ms = float(w)
            applied["window_ms"] = cfg.window_ms
    if "MXNET_TPU_SERVING_MAX_QUEUE" not in os.environ \
            and cfg.max_queue == 128:
        q = _tt.knob(doc, "serving", "max_queue")
        if q is not None and int(q) != cfg.max_queue:
            cfg.max_queue = int(q)
            applied["max_queue"] = cfg.max_queue
    if cfg.batch_buckets is None:
        bb = _tt.knob(doc, "buckets", "batch")
        if bb:
            # the lattice must still admit a full coalesced batch: clamp
            # to max_batch and keep max_batch as the top bucket
            lat = sorted({int(b) for b in bb if int(b) <= cfg.max_batch}
                         | {int(cfg.max_batch)})
            cfg.batch_buckets = tuple(lat)
            applied["batch_buckets"] = lat
    if cfg.decode_model is not None \
            and "MXNET_TPU_DECODE_SLOTS" not in os.environ:
        s = _tt.knob(doc, "decode", "slots")
        if s is not None:
            if cfg.decode is None:
                from .decode import DecodeConfig
                cfg.decode = DecodeConfig()
            if cfg.decode.slots == 8 and int(s) != cfg.decode.slots:
                cfg.decode.slots = int(s)
                applied["decode_slots"] = cfg.decode.slots
    if applied:
        get_journal().event("tuned_load", site="server", **applied)


class Server:
    """Dynamic-batching inference server around one Gluon block.

    ``block`` must be initialized (parameters materialized) — pass any
    ``Block``/``HybridBlock``/``SymbolBlock``; ``Server.from_checkpoint``
    builds one from a ``model.save_checkpoint`` deployment pair.
    ``param_store`` (a :class:`~.reload.ParamStore`) enables hot-reload.
    """

    def __init__(self, block, config=None, param_store=None, ctx=None):
        self.block = block
        self.config = config or ServerConfig()
        _apply_tuned_server(self.config)
        cfg = self.config
        self.grid = BucketGrid(cfg.max_batch, cfg.batch_buckets,
                               cfg.dim_buckets)
        self.cache = PredictorCache(cfg.cache_entries)
        # tensor-parallel plan: explicit config wins; a bare axes spec
        # (str/dict) is promoted; unset falls back to the environment
        # knob so a subprocess worker opts in without code changes
        from .shardplan import ShardPlan, plan_from_env
        plan = cfg.shard_plan
        if plan is None:
            plan = plan_from_env()
        elif isinstance(plan, (str, dict)):
            plan = ShardPlan(axes=plan)
        self.plan = cfg.shard_plan = plan
        self._placed = False           # weights landed on the plan mesh
        # the continuous batcher (serving/decode.py): its own worker
        # thread + slot pool, started/stopped with this server, sharing
        # the plan's mesh so decode state co-exists with tensor-parallel
        # predictors
        self.decoder = None
        if cfg.decode_model is not None:
            from .decode import DecodeConfig, DecodeEngine
            self.decoder = DecodeEngine(
                cfg.decode_model, cfg.decode or DecodeConfig(),
                plan=self.plan)
        # the disk tier behind the LRU: None unless configured (env or
        # config) and not switched off — docs/serving.md AOT cache
        self.aot = None
        if cfg.aot_dir:
            from .aotcache import AOTCache
            self.aot = AOTCache.maybe(cfg.aot_dir)
        self.param_store = param_store
        self.latency = LatencySummary("request_latency_ms")
        self._ctx = ctx
        self._dtype = np.dtype(cfg.dtype)
        self._queue = queue.Queue(maxsize=cfg.max_queue)
        self._worker = None
        self._stopping = threading.Event()
        self._lock = threading.Lock()
        # admission gate: submit's closed-check + enqueue and stop's
        # close + straggler sweep serialize on this lock, so a request
        # can never slip into the queue after the final sweep (the
        # silent-drop race the ServerStopped contract closes)
        self._admit_lock = threading.Lock()
        self._closed = False
        self._params_step = None
        self._last_reload_check = None
        self._pin_dirty = False        # guarded by _lock; set by pin_params
                                       # (controller thread), consumed by the
                                       # worker thread in _maybe_reload
        self._last_batch_t = None
        self._metrics_httpd = None
        # exposition identity: the serving metric families are process-
        # wide, so two Servers in one process must not overwrite each
        # other's samples — each mirrors under its own label value
        self._metrics_id = f"srv{next(_server_seq)}"
        self.counters = {"accepted": 0, "served": 0, "shed": 0,
                         "rejected_shape": 0, "rejected_stopped": 0,
                         "cancelled": 0, "deadline_miss_dequeue": 0,
                         "deadline_miss_post_batch": 0, "errors": 0,
                         "reloads": 0, "batches": 0}

    # -- deployment-pair constructor (module/model predict-path reuse) ------
    @classmethod
    def from_checkpoint(cls, prefix, epoch, input_names=("data",),
                        config=None, param_store=None, ctx=None):
        """Serve a ``prefix-symbol.json`` + ``prefix-NNNN.params`` pair
        (``HybridBlock.export`` / ``model.save_checkpoint`` artifacts)
        via ``SymbolBlock.imports`` — the reference's deployment
        contract, behind the same batching front end."""
        from ..gluon.block import SymbolBlock
        block = SymbolBlock.imports(
            f"{prefix}-symbol.json", list(input_names),
            f"{prefix}-{epoch:04d}.params", ctx=ctx)
        return cls(block, config=config, param_store=param_store, ctx=ctx)

    # -- lifecycle -----------------------------------------------------------
    def start(self):
        if self._worker is not None and self._worker.is_alive():
            return self
        self._stopping.clear()
        with self._admit_lock:
            self._closed = False
        # serving_start opens the journal's "last run" window BEFORE the
        # initial reload so that reload is attributed to this run
        get_journal().event("serving_start", config=self.config.summary(),
                            grid=repr(self.grid))
        if self.plan is not None and not self._placed:
            # land the weights on the serving mesh BEFORE the initial
            # reload: the reload lane then re-drops host entries onto
            # these exact shardings via reshard.place_global
            self.plan.place(self.block, site="serving_start")
            self._placed = True
        self._maybe_reload(force=True)     # begin on the newest valid step
        if self.config.aot_prewarm:
            self.prewarm()                 # warm the lattice pre-traffic
        if self.decoder is not None:
            # warm the WHOLE decode program set before traffic: a
            # compile during decode is a defect, not a cold start
            self.decoder.start()
            self.decoder.warmup()
        self._worker = threading.Thread(
            target=self._run, name="mxtpu-serving-worker", daemon=True)
        self._worker.start()
        return self

    def stop(self, timeout_s=30.0, drain=True):
        """Shut down: with ``drain`` the worker finishes everything
        admitted before the sentinel; without, pending requests fail
        with a structured :class:`ServerStopped`.  Admission closes
        FIRST — before the drain deadline starts — so a submit racing
        this call either lands ahead of the sentinel (and is served or
        failed structurally) or raises :class:`ServerStopped`; it can
        never be silently dropped.  Bounded join — a wedged device
        can't hang the caller past ``timeout_s``."""
        if self._worker is None:
            return
        if self.decoder is not None:
            self.decoder.stop(timeout_s=timeout_s, drain=drain)
        with self._admit_lock:
            self._closed = True
        if not drain:
            self._stopping.set()
        try:
            self._queue.put(_STOP, timeout=timeout_s)
        except queue.Full:
            self._stopping.set()           # flooded: stop without drain
        self._worker.join(timeout=timeout_s)
        if self._metrics_httpd is not None:
            self._metrics_httpd.shutdown()
            self._metrics_httpd.server_close()   # release the socket too
            self._metrics_httpd = None
        stuck = self._worker.is_alive()
        if not stuck:
            # straggler sweep: anything still queued after the worker
            # exited (the drain=False path, or a sentinel that couldn't
            # be enqueued) fails structurally.  Only the queue DRAIN
            # needs the admission lock (atomic vs a racing submit's
            # closed-check + put); the per-request journal writes
            # happen after release (G15: no I/O under the admit lock)
            stragglers: list = []
            with self._admit_lock:
                self._drain_queue(stragglers)
            self._fail_remaining(stragglers, why="straggler")
        get_journal().event("serving_stop", drained=bool(drain),
                            stuck=stuck, **self.stats())
        if stuck:
            raise MXNetError(
                f"serving worker did not stop within {timeout_s:g}s "
                "(device wedged mid-batch? see the journal)")
        self._worker = None

    # -- tenant hooks (overridden by serving/fleet.py) -----------------------
    def _admit_tenant(self, tenant, payload):
        """Tenant-registry admission gate.  The single-tenant Server
        serves exactly one anonymous family; the fleet overrides this
        with registry lookup, quarantine gate, and the token-bucket
        rate budget.  Returns the tenant state handle (None here)."""
        if tenant is not None:
            err = RequestError(
                f"unknown tenant {tenant!r}: this replica serves a "
                "single-tenant Server, not a fleet")
            err.tenant = tenant
            raise err
        return None

    def _note_reject(self, tenant):
        """Shape-reject bookkeeping hook (the fleet feeds its per-tenant
        breaker here — an oversized-shape flood is a tenant fault)."""

    def _effective_deadline(self, deadline_ms, tstate):
        """Apply the tenant's SLO deadline floor (fleet); identity for
        the single-tenant Server."""
        return self.config.default_deadline_ms if deadline_ms is None \
            else deadline_ms

    def _class_gate(self, tstate, tenant):
        """Per-tenant-class queue-depth budget (fleet): shed LOWER
        priority classes first while the shared queue fills.  No-op for
        the single-tenant Server (only the hard bound sheds)."""

    def _note_shed(self, tenant):
        """Per-tenant shed counter hook (fleet)."""

    def _note_accept(self, tenant):
        """Per-tenant accept counter hook (fleet)."""

    # -- client surface ------------------------------------------------------
    def submit(self, x, deadline_ms=None, cancel=None,
               tenant=None, parent=None) -> PendingResponse:
        """Admit one sample (NO batch axis).  Raises
        :class:`RequestError` for a shape outside the bucket grid,
        :class:`ServerOverloaded` when the bounded queue is full, and
        :class:`ServerStopped` once ``stop()`` has closed admission.
        ``cancel`` (a ``threading.Event``) is checked at dequeue — the
        hedging router sets it on the losing attempt so a request whose
        twin already answered never spends a batch slot.  ``tenant``
        targets a fleet tenant (serving/fleet.py); on a single-tenant
        Server a non-None tenant is a structured error.  ``parent`` (a
        trace ``SpanContext``) re-anchors this request's root span under
        a caller in ANOTHER process — the worker front door passes the
        wire frame's propagated context here so the replica-side span
        tree joins the router's trace (docs/observability.md); in-process
        callers leave it None and the contextvar parent applies."""
        payload = np.asarray(x, dtype=self._dtype)
        if tenant is not None:
            # normalize ONCE at the door: every downstream lookup
            # (registry, dequeue sweep, counters, journal) is by the
            # string key the fleet registered
            tenant = str(tenant)
        tstate = self._admit_tenant(tenant, payload)
        key = self.grid.feature_key(payload.shape)
        if key is None:
            with self._lock:
                self.counters["rejected_shape"] += 1
            get_journal().event("serving_reject", shape=list(payload.shape),
                                grid=repr(self.grid), tenant=tenant)
            self._note_reject(tenant)
            err = RequestError(
                f"request shape {tuple(payload.shape)} exceeds the bucket "
                f"grid {self.grid!r} — oversized inputs are rejected, "
                "never compiled"
                + (f" [tenant: {tenant}]" if tenant else ""))
            err.retryable = False      # every replica shares the grid
            err.tenant = tenant
            raise err
        deadline_ms = self._effective_deadline(deadline_ms, tstate)
        deadline_s = None if deadline_ms is None or deadline_ms <= 0 \
            else deadline_ms / 1000.0
        self._class_gate(tstate, tenant)
        req = Request(payload, payload.shape, key, deadline_s=deadline_s,
                      cancel=cancel, tenant=tenant)
        # one linked span tree per request (docs/observability.md):
        # the root opens here and is closed by whichever thread resolves
        # the request; the worker's batch span links back via span IDs.
        # Attr construction is gated on enabled() so the off-is-free
        # contract holds on the admission hot path (req.trace stays
        # None — _req_ids/_end_span are None-safe)
        traced = _trace.enabled()
        if traced:
            req.trace = _trace.start_span("serving_request",
                                          parent=parent,
                                          shape=list(payload.shape))
        try:
            with self._admit_lock:
                stopped = self._closed
                if not stopped:
                    self._queue.put_nowait(req)
        except queue.Full:
            with self._lock:
                self.counters["shed"] += 1
            get_journal().event("serving_shed", depth=self._queue.qsize(),
                                limit=self.config.max_queue, tenant=tenant,
                                **_req_ids(req))
            self._note_shed(tenant)
            _end_span(req, "shed")
            raise ServerOverloaded(self._queue.qsize(),
                                   self.config.max_queue,
                                   tenant=tenant) from None
        if stopped:
            with self._lock:
                self.counters["rejected_stopped"] += 1
            get_journal().event("serving_stopped_reject",
                                stage="admission", **_req_ids(req))
            _end_span(req, "stopped")
            raise ServerStopped("server is stopping")
        if traced:
            _trace.event("enqueue", parent=req.trace,
                         depth=self._queue.qsize())
        with self._lock:
            self.counters["accepted"] += 1
        self._note_accept(tenant)
        return PendingResponse(req, self.config.result_timeout_s)

    def predict(self, x, deadline_ms=None, timeout_s=None, tenant=None):
        """Synchronous convenience: submit + wait."""
        return self.submit(x, deadline_ms=deadline_ms,
                           tenant=tenant).result(timeout_s)

    def decode_submit(self, tokens, max_new_tokens=None, deadline_ms=None,
                      tenant=None):
        """Admit one autoregressive stream to the continuous batcher
        (``config.decode_model``); returns a
        :class:`~.decode.DecodeStream`.  The tenant label threads into
        every decode journal record and error — the engine's slot pool
        itself is shared (admission is against slots, not per-tenant
        executables)."""
        if self.decoder is None:
            err = RequestError(
                "this server has no decode engine (config.decode_model "
                "is unset) — decode streams are not servable here")
            err.retryable = False
            err.tenant = tenant
            raise err
        return self.decoder.submit(tokens, max_new_tokens=max_new_tokens,
                                   deadline_ms=deadline_ms, tenant=tenant)

    def decode(self, tokens, max_new_tokens=None, deadline_ms=None,
               timeout_s=None, tenant=None):
        """Synchronous decode convenience: submit + wait → token list."""
        return self.decode_submit(
            tokens, max_new_tokens=max_new_tokens, deadline_ms=deadline_ms,
            tenant=tenant).result(timeout_s)

    def queue_depth(self) -> int:
        """Current admission-queue depth (approximate, lock-free) — the
        replica pool's drain-wait and readiness beacon read it."""
        return self._queue.qsize()

    # -- bucket-lattice prewarm (docs/serving.md AOT cache) ------------------
    def prewarm(self, shapes=None) -> dict:
        """Build (load-or-compile) the predictor for every batch bucket
        × feature shape ahead of traffic.  ``shapes``: per-request
        feature shapes (NO batch axis; default ``config.aot_prewarm``).
        With the AOT cache configured this is the warm-restart path —
        the second start on the same dir performs zero XLA compiles;
        without it, it simply front-loads the compiles.  Returns
        ``{warmed, loaded, compiled, skipped, ms}`` and journals an
        ``aot_prewarm`` record."""
        shapes = shapes if shapes is not None else self.config.aot_prewarm
        t0 = time.perf_counter()
        warmed = loaded = compiled = 0
        skipped = []
        for shape in shapes or ():
            key = self.grid.feature_key(tuple(shape))
            if key is None:
                skipped.append(list(shape))    # outside the grid
                continue
            for bucket in self.grid.batch_buckets:
                entry, hit = self.cache.get(
                    (bucket, key, self._dtype.str),
                    lambda b=bucket, k=key:
                        self._build_ready_predictor(self.block, b, k))
                if hit:
                    continue
                warmed += 1
                if entry.aot == "loaded":
                    loaded += 1
                else:
                    compiled += 1
        out = {"warmed": warmed, "loaded": loaded, "compiled": compiled,
               "skipped": skipped,
               "ms": round((time.perf_counter() - t0) * 1000.0, 2)}
        get_journal().event("aot_prewarm", **out)
        return out

    def stats(self) -> dict:
        with self._lock:
            counters = dict(self.counters)
        t = self._last_batch_t
        out = {"queue_depth": self.queue_depth(),
               "params_step": self._params_step,
               "last_batch_age_s": None if t is None
               else round(time.monotonic() - t, 3),
               "cache": self.cache.stats(),
               "latency_ms": self.latency.summary(),
               **counters}
        if self.aot is not None:
            out["aot"] = self.aot.stats()
        if self.plan is not None:
            out["shard_plan"] = self.plan.fingerprint_token()
        if self.decoder is not None:
            out["decode"] = self.decoder.stats()
        return out

    def beacon(self) -> dict:
        """Cheap readiness facts for a replica-pool heartbeat payload
        (serving/pool.py): no percentile math, no cache lock — safe to
        call from a beacon thread several times a second."""
        t = self._last_batch_t
        alive = self._worker is not None and self._worker.is_alive()
        return {"queue_depth": self.queue_depth(),
                "params_step": self._params_step,
                "last_batch_age_s": None if t is None
                else round(time.monotonic() - t, 3),
                "ready": alive and not self._closed}

    # -- metrics exposition (docs/observability.md) --------------------------
    def metrics_text(self) -> str:
        """Prometheus text exposition: the serving counters/gauges
        mirrored into the process default registry at call time, plus
        everything already there (compile counters, step phases).
        Mirrors are gauges — the server's own dict stays the source of
        truth, and a second Server in the same process must not trip a
        monotonicity check on shared families."""
        from ..observability import metrics as _m
        reg = _m.default_registry()
        st = self.stats()
        sid = self._metrics_id
        reg.gauge("mxnet_tpu_serving_queue_depth",
                  "admission queue depth", ("server",)).labels(
            server=sid).set(st["queue_depth"])
        if st["params_step"] is not None:
            reg.gauge("mxnet_tpu_serving_params_step",
                      "hot-reloaded checkpoint step currently served",
                      ("server",)).labels(server=sid).set(
                st["params_step"])
        ev = reg.gauge("mxnet_tpu_serving_events",
                       "serving lifecycle counters (cumulative)",
                       ("server", "event"))
        for k in ("accepted", "served", "shed", "rejected_shape",
                  "rejected_stopped", "cancelled",
                  "deadline_miss_dequeue", "deadline_miss_post_batch",
                  "errors", "reloads", "batches"):
            ev.labels(server=sid, event=k).set(st[k])
        cache = st["cache"]
        ce = reg.gauge("mxnet_tpu_serving_cache_events",
                       "compiled-predictor cache counters (cumulative; "
                       "misses == compiles)", ("server", "event"))
        for k in ("hits", "misses", "evictions", "entries"):
            ce.labels(server=sid, event=k).set(cache[k])
        lat = st["latency_ms"]
        if lat["count"]:
            lq = reg.gauge("mxnet_tpu_serving_latency_ms",
                           "end-to-end request latency percentiles",
                           ("server", "quantile"))
            for q in ("p50", "p95", "p99"):
                lq.labels(server=sid, quantile=q).set(lat[q])
        return reg.prometheus_text()

    def start_metrics_server(self, host="127.0.0.1", port=0):
        """Expose ``GET /metrics`` (Prometheus text) on a stdlib daemon
        HTTP server; returns it (``.server_address[1]`` is the bound
        port; ``port=0`` picks a free one).  Stopped by ``stop()``."""
        if self._metrics_httpd is None:
            from ..observability.export import serve_metrics
            self._metrics_httpd = serve_metrics(self.metrics_text,
                                                host=host, port=port)
        return self._metrics_httpd

    # -- worker --------------------------------------------------------------
    def _run(self):
        j = get_journal()
        pending, draining = [], False
        try:
            while True:
                if self._stopping.is_set():
                    break
                if not pending:
                    try:
                        item = self._queue.get(
                            timeout=self.config.idle_poll_s)
                    except queue.Empty:
                        self._maybe_reload()
                        continue
                    if item is _STOP:
                        draining = True
                        break
                    pending.append(item)
                # coalescing window: absorb same-cycle arrivals
                t_end = time.monotonic() + self.config.window_ms / 1000.0
                while len(pending) < self.grid.max_batch:
                    remaining = t_end - time.monotonic()
                    if remaining <= 0:
                        break
                    try:
                        item = self._queue.get(timeout=remaining)
                    except queue.Empty:
                        break
                    if item is _STOP:
                        draining = True
                        break
                    pending.append(item)
                self._flush(pending)
                self._maybe_reload()
                if draining:
                    break
        except BaseException as exc:        # worker must die loudly
            j.crash(exc, where="serving_worker")
            raise
        finally:
            if draining and not self._stopping.is_set():
                while True:                 # bounded: queue admits no more
                    try:
                        item = self._queue.get_nowait()
                    except queue.Empty:
                        break
                    if item is not _STOP:
                        pending.append(item)
                while pending:
                    self._flush(pending)
            self._drain_queue(pending)   # racing submits since the sweep
            self._fail_remaining(pending)

    def _flush(self, pending):
        """Expire, group, and run one micro-batch off ``pending``."""
        drop_expired(pending, self._on_dequeue_expired)
        self._drop_cancelled(pending)
        self._sweep_unroutable(pending)
        batch, bucket, key = take_batch(pending, self.grid,
                                        self._group_key)
        if batch:
            self._process(batch, bucket, key)

    # worker-loop grouping/sweep hooks (serving/fleet.py overrides:
    # per-(tenant, key) batches; quarantined/removed tenants' queued
    # requests resolved structurally instead of spending batch slots)
    _group_key = None

    def _sweep_unroutable(self, pending):
        pass

    def _drop_cancelled(self, pending):
        """The dequeue half of hedging: a request whose cancel event is
        set (its twin already answered) is resolved with
        :class:`RequestCancelled` instead of spending a batch slot."""
        keep = []
        for req in pending:
            if req.cancelled():
                with self._lock:
                    self.counters["cancelled"] += 1
                get_journal().event("serving_cancelled", **_req_ids(req))
                self._note_cancelled(req.tenant)
                _end_span(req, "cancelled")
                req.set_error(RequestCancelled(
                    "cancelled at dequeue (hedged twin already answered)"))
            else:
                keep.append(req)
        pending[:] = keep

    def _note_cancelled(self, tenant):
        """Per-tenant cancel hook (the fleet frees a half-open probe
        slot here)."""

    def _on_dequeue_expired(self, req):
        late = req.late_ms()
        with self._lock:
            self.counters["deadline_miss_dequeue"] += 1
        get_journal().event("serving_deadline_miss", stage="dequeue",
                            late_ms=round(late, 2), tenant=req.tenant,
                            **_req_ids(req))
        self._note_deadline_miss(req.tenant)
        _end_span(req, "deadline_miss_dequeue")
        req.set_error(DeadlineExceeded("dequeue", late, tenant=req.tenant))

    def _note_deadline_miss(self, tenant):
        """Per-tenant deadline-miss counter hook (fleet)."""

    def _drain_queue(self, pending):
        while True:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                break
            if item is not _STOP:
                pending.append(item)

    def _fail_remaining(self, pending, why="stopped"):
        for req in pending:
            with self._lock:
                self.counters["rejected_stopped"] += 1
            get_journal().event("serving_stopped_reject", stage=why,
                                **_req_ids(req))
            _end_span(req, "stopped")
            req.set_error(ServerStopped("server stopped before this "
                                        "request was served"))
        pending.clear()

    def _process(self, batch, bucket, key):
        cfg = self.config
        n = len(batch)
        # the batch execution is its own trace, linked both ways: the
        # batch span lists the member request spans, and each request's
        # "execute" child names the batch span (docs/observability.md)
        with _trace.span(
                "serving_batch", batch=n, bucket=bucket, key=list(key),
                request_spans=[i["span_id"] for r in batch
                               for i in [_req_ids(r)] if i]) as bsp:
            self._process_traced(batch, bucket, key, n, cfg, bsp)

    # -- predictor hooks (overridden by serving/fleet.py) --------------------
    def _build_predictor(self, block, bucket, key):
        """One predictor for one padded shape: disk-first when the AOT
        cache is configured (a valid entry loads with zero compiles —
        ``aot_load`` span; a miss compiles eagerly and writes through),
        else the historical lazy-jit closure (compiles at first call)."""
        if self.aot is not None:
            return self.aot.load_or_compile(
                block, (bucket,) + key, self._dtype, ctx=self._ctx,
                plan=self.plan)
        return CompiledPredictor(block, ctx=self._ctx, plan=self.plan)

    def _build_ready_predictor(self, block, bucket, key):
        """The prewarm builder: ALWAYS returns a ready (AOT-compiled or
        disk-loaded) predictor.  A lazy closure here would poison the
        accounting twice over — prewarm would report a warm lattice it
        never built, and the first real request would find a cache hit
        whose untimed first-call compile hides inside the batch's
        ``exec_ms``."""
        if self.aot is not None:
            return self.aot.load_or_compile(
                block, (bucket,) + key, self._dtype, ctx=self._ctx,
                plan=self.plan)
        pred = CompiledPredictor(block, ctx=self._ctx, plan=self.plan)
        with _obs.compile_span("serving_predictor",
                               shape=[bucket, *key],
                               dtype=self._dtype.str, aot=True):
            pred.aot_compile((bucket,) + key, self._dtype)
        return pred

    def _acquire_predictor(self, batch, bucket, key):
        """Return ``(predictor, hit)`` for this batch.  The fleet
        overrides with per-tenant executables + weight paging (a cold
        tenant pages host-RAM parameters onto the device here, OUTSIDE
        the timed execute window, journaled ``tenant_page_in``)."""
        cache_key = (bucket, key, self._dtype.str)
        return self.cache.get(
            cache_key,
            lambda: self._build_predictor(self.block, bucket, key))

    def _trip_sites(self, batch):
        """Chaos seams consulted per predictor call:
        ``faults.slow_call("serving_predict", ...)`` injects device
        latency, ``faults.io_error`` rides the transient retry path.
        The fleet adds the per-tenant ``serving_tenant`` site."""
        _atomic.trip("serving_predict", self._metrics_id)

    def _note_predict_error(self, batch, exc):
        """Non-transient predictor failure hook — the fleet feeds its
        per-tenant breaker here (a poisoned tenant quarantines itself,
        never the fleet)."""

    def _batch_step(self, batch):
        """Checkpoint step stamped on this batch's responses (the
        fleet answers per tenant)."""
        return self._params_step

    def _batch_fields(self, batch) -> dict:
        """Extra journal fields for the ``serving_batch`` record (the
        fleet adds ``tenant``)."""
        return {}

    def _observe_latency(self, req, ms):
        self.latency.observe(ms)

    def _batch_succeeded(self, batch):
        """Delivered-batch hook — the fleet's half-open tenant probe
        re-admission rides this."""

    def _process_traced(self, batch, bucket, key, n, cfg, bsp):
        padded = np.full((bucket,) + key, cfg.pad_value, dtype=self._dtype)
        for i, req in enumerate(batch):
            padded[(i,) + tuple(slice(0, d) for d in req.shape)] = req.payload
        tenant = batch[0].tenant
        try:
            predictor, hit = self._acquire_predictor(batch, bucket, key)
        except Exception as exc:
            self._fail_batch(batch, n, bucket, tenant, exc,
                             where="serving_page_in")
            return
        t0 = time.perf_counter()
        try:
            # a cache miss's first call traces + compiles the padded
            # shape: the timed compile event for this jit-miss site.
            # An AOT-built predictor (loaded OR eagerly compiled in the
            # builder, which timed itself) is already `ready` — its
            # first call includes no compile, so no span here
            def _run_predictor(p):
                self._trip_sites(batch)
                return predictor(p)

            with _obs.maybe_compile_span(
                    not hit and not predictor.ready,
                    "serving_predictor", bucket=bucket,
                    key=list(key), dtype=self._dtype.str,
                    includes_execute=True):
                outs, treedef = retry_call(
                    _run_predictor, padded, retries=cfg.device_retries,
                    retry_on=cfg.transient_errors, what="serving_predict")
            outs = [np.asarray(o) for o in outs]
        except Exception as exc:
            self._fail_batch(batch, n, bucket, tenant, exc,
                             where="serving_predict")
            return
        t1 = time.perf_counter()
        exec_ms = (t1 - t0) * 1000.0

        import jax
        now = time.monotonic()
        delivered = 0
        step = self._batch_step(batch)
        for i, req in enumerate(batch):
            if req.expired(now):
                late = req.late_ms(now)
                with self._lock:
                    self.counters["deadline_miss_post_batch"] += 1
                get_journal().event("serving_deadline_miss",
                                    stage="post_batch",
                                    late_ms=round(late, 2),
                                    tenant=req.tenant,
                                    **_req_ids(req))
                self._note_deadline_miss(req.tenant)
                _end_span(req, "deadline_miss_post_batch")
                req.set_error(DeadlineExceeded("post_batch", late,
                                               tenant=req.tenant), now)
                continue
            rows = []
            for o in outs:
                row = o[i] if o.ndim >= 1 and o.shape[0] == bucket else o
                if cfg.crop_outputs and row.shape == key \
                        and req.shape != key:
                    row = row[tuple(slice(0, d) for d in req.shape)]
                rows.append(row)
            result = rows[0] if treedef is None else \
                jax.tree_util.tree_unflatten(treedef, rows)
            if req.trace is not None and req.trace.span_id is not None:
                # the shared execution window, under this request's root
                _trace.record("execute", parent=req.trace, t0=t0, t1=t1,
                              batch_span=bsp.span_id, batch=n,
                              bucket=bucket)
                _trace.event("respond", parent=req.trace)
            _end_span(req, "ok")
            req.params_step = step                 # version stamp
            req.set_result(result, now)
            delivered += 1
            self._observe_latency(req, (now - req.enq_t) * 1000.0)
        self._last_batch_t = time.monotonic()
        with self._lock:
            self.counters["served"] += delivered
            self.counters["batches"] += 1
        if delivered:
            self._batch_succeeded(batch)
        lat = self.latency.summary()
        cache_st = self.cache.stats()      # one snapshot: consistent trio
        get_journal().event(
            "serving_batch", queue_depth=self._queue.qsize(), batch=n,
            delivered=delivered, bucket=bucket, fill=round(n / bucket, 4),
            pad_waste=BucketGrid.pad_waste(
                n, bucket, [r.shape for r in batch], key),
            cache_hit=hit, exec_ms=round(exec_ms, 2),
            params_step=step,
            hits=cache_st["hits"], misses=cache_st["misses"],
            evictions=cache_st["evictions"],
            p50_ms=lat["p50"], p95_ms=lat["p95"], p99_ms=lat["p99"],
            **self._batch_fields(batch))

    def _fail_batch(self, batch, n, bucket, tenant, exc, where):
        """Resolve every member of a failed batch with a structured,
        tenant-labeled error, journal the crash, and feed the tenant
        fault-domain hook."""
        with self._lock:
            self.counters["errors"] += n
        get_journal().crash(exc, where=where, batch=n, bucket=bucket,
                            tenant=tenant)
        self._note_predict_error(batch, exc)
        err = RequestError(f"predictor failed: "
                           f"{type(exc).__name__}: {exc}"
                           + (f" [tenant: {tenant}]" if tenant else ""))
        err.tenant = tenant
        for req in batch:
            _end_span(req, "error")
            req.set_error(err)

    # -- hot-reload ----------------------------------------------------------
    def _check_reloadable(self, loaded):
        """Shape-check every entry against the live parameters up front
        (arg:/aux: prefixes normalized like ``load_dict``).  Returns the
        normalized structural-name → array dict (the sharded reload lane
        places from it)."""
        params = self.block._structural_names()
        norm = {(k.partition(":")[2] if k.partition(":")[0] in
                 ("arg", "aux") and ":" in k else k): v
                for k, v in loaded.items()}
        for key, param in params.items():
            if key not in norm:
                raise MXNetError(f"checkpoint missing parameter {key!r}")
            got = tuple(norm[key].shape)
            if param.shape and tuple(param.shape) != got:
                raise MXNetError(
                    f"checkpoint parameter {key!r} is {got}, live "
                    f"parameter is {tuple(param.shape)} — architecture "
                    "drift; not hot-reloadable")
        return norm

    def pin_params(self, step):
        """Pin the hot-reload store to ``step`` (None unpins) — the
        deploy controller's per-replica version lever.  The pin itself
        lands immediately (``poll`` stops advancing past it); when the
        LIVE step differs from the pin, the actual load+apply happens on
        the worker thread at its next loop turn, the same between-batches
        seam every other reload uses — including a DOWNGRADE back to an
        older step, which is the rollback path.  Returns True when a
        store exists to pin."""
        store = self.param_store
        if store is None:
            return False
        store.pin_step(step)
        with self._lock:
            self._pin_dirty = step is not None
        return True

    def _apply_params(self, step, loaded, prev):
        """Apply an already-loaded parameter dict; shared by the poll
        lane and the explicit pin/rollback lane."""
        store = self.param_store
        loaded = {k: v for k, v in loaded.items() if not k.startswith("__")}
        try:
            # validate the WHOLE dict against the live parameter shapes
            # before touching any of them — a validated-but-inapplicable
            # checkpoint (architecture drift) must never half-apply
            norm = self._check_reloadable(loaded)
            if self.plan is not None and self._placed:
                # sharded lane: re-drop each host entry onto the LIVE
                # array's NamedSharding via reshard.place_global — the
                # compiled predictors were lowered against these
                # placements, so a reload must preserve them exactly
                self.plan.adopt_entries(
                    self.block, {k: v.asnumpy() if hasattr(v, "asnumpy")
                                 else np.asarray(v) for k, v in norm.items()})
            else:
                self.block.load_dict(loaded, ctx=self._ctx,
                                     ignore_extra=True)
        except MXNetError as e:
            store.mark_bad(step, revert_to=prev)
            get_journal().event("serving_reload_failed", step=step,
                                error=type(e).__name__, detail=str(e)[:300])
            return False
        self._params_step = step
        with self._lock:
            self.counters["reloads"] += 1
        get_journal().event("serving_reload", step=step,
                            n_params=len(loaded), prev_step=prev)
        return True

    def _apply_pin(self, store):
        """Converge the live step onto the pinned one — runs on the
        worker thread.  Unlike the poll lane this is an EXPLICIT load of
        one named step (downgrades allowed): there is no safe substitute
        for a rollback target, so a failure journals and stays on the
        current version rather than hunting for an alternative."""
        pinned = store.pinned_step
        if pinned is None or self._params_step == pinned:
            return False
        prev = self._params_step
        try:
            step, loaded = store.load_step(pinned)
        except (ValueError, MXNetError, OSError) as e:
            get_journal().event("serving_reload_failed", step=pinned,
                                error=type(e).__name__, detail=str(e)[:300])
            return False
        return self._apply_params(step, loaded, prev)

    def _maybe_reload(self, force=False):
        store = self.param_store
        if store is None:
            return False
        with self._lock:
            pin_dirty, self._pin_dirty = self._pin_dirty, False
        if pin_dirty:
            # the pin lane bypasses the poll throttle (and a disabled
            # poller): a deploy rollback must land within its deadline
            # budget, not at the operator's reload cadence
            return self._apply_pin(store)
        poll_s = self.config.reload_poll_s
        if poll_s < 0 and not force:
            return False
        now = time.monotonic()
        if not force and self._last_reload_check is not None and \
                now - self._last_reload_check < poll_s:
            return False
        self._last_reload_check = now
        got = store.poll()
        if got is None:
            return False
        step, loaded = got
        return self._apply_params(step, loaded, self._params_step)

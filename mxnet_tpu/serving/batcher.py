"""Request plumbing for the dynamic batcher — stdlib-only.

A :class:`Request` is one sample (no batch axis) plus its admission
timestamp and absolute deadline; completion is a ``threading.Event`` the
submitting thread waits on through :class:`PendingResponse`.  The worker
thread groups admitted requests into micro-batches with
:func:`take_batch`: FIFO within a feature-bucket key, capped at the
largest batch bucket, leaving differently-bucketed requests pending for
the next cycle (so one odd-shaped request never pads — or blocks — a
whole batch of the common shape).

Deadline semantics (docs/serving.md): a deadline is checked twice —
at dequeue (:func:`drop_expired`; a request that already missed must not
waste a batch slot) and again post-batch by the server (a result that
arrives late is an error, not a silently-slow success).  Both misses
surface as :class:`DeadlineExceeded` on the caller's ``result()``.
"""
from __future__ import annotations

import threading
import time

__all__ = ["DeadlineExceeded", "PendingResponse", "Request",
           "RequestCancelled", "RequestError", "ServerOverloaded",
           "ServerStopped", "SlotsExhausted", "drop_expired", "take_batch"]


class RequestError(RuntimeError):
    """Structured per-request failure (bad shape, predictor error).

    ``retryable`` is the replica-pool router's classification hook
    (serving/router.py): True when the same request may succeed on a
    DIFFERENT replica (predictor fault, stopped/overloaded server);
    the shape-reject path overrides it to False on the instance — every
    replica shares the bucket grid, so retrying is wasted budget.
    ``tenant`` names the fleet tenant the failure belongs to (None on a
    single-tenant Server) — the tenant-isolation contract requires every
    structured error to carry its fault domain (docs/serving.md)."""

    retryable = True
    tenant = None


class ServerOverloaded(RequestError):
    """Admission rejected: the bounded queue is full — or, with
    ``tier`` set, a pool-level degradation tier acted (the router's
    capacity-floor shed, the fleet's per-tenant-class depth budget or
    token-bucket rate budget; docs/serving.md).  Raised to the
    *submitter* immediately — the explicit load-shed that keeps queue
    latency bounded instead of letting every client get slower."""

    def __init__(self, depth, limit, tier=None, tenant=None):
        super().__init__(f"serving queue full ({depth}/{limit}); request "
                         "shed — retry with backoff or scale out"
                         + (f" [tier: {tier}]" if tier else "")
                         + (f" [tenant: {tenant}]" if tenant else ""))
        self.depth = depth
        self.limit = limit
        self.tier = tier
        self.tenant = tenant


class ServerStopped(RequestError):
    """Admission is closed: ``stop()`` has begun (or finished) on this
    server.  Raised at ``submit()`` once the server is stopping, and set
    on any straggler found in the queue after the worker exited — a
    stop can never turn a request into a silent result-timeout."""

    def __init__(self, detail="server stopped"):
        super().__init__(f"{detail} — admission closed; submit to "
                         "another replica or restart the server")


class SlotsExhausted(RequestError):
    """Decode-slot admission rejected: every KV-cache slot is occupied
    and the stream asked not to queue (serving/decode.py,
    ``queue_on_busy=False``).  Retryable — unlike a shape reject, a
    DIFFERENT replica may well have a free slot, so the pool router's
    retry loop treats this as a placement miss, not a dead request."""

    def __init__(self, slots, queued=0, tenant=None):
        super().__init__(f"all {slots} decode slots occupied "
                         f"({queued} queued); stream not admitted — "
                         "retry on another replica"
                         + (f" [tenant: {tenant}]" if tenant else ""))
        self.slots = slots
        self.queued = queued
        self.tenant = tenant


class RequestCancelled(RequestError):
    """The request was cancelled before execution (a hedged attempt
    whose twin already answered): dropped at dequeue, never spending a
    batch slot.  Not retryable — the caller already has its result."""

    retryable = False


class DeadlineExceeded(RequestError):
    """The request's deadline passed before (stage='dequeue') or while
    (stage='post_batch') it was served; stage='router_budget' means the
    pool router's retry/hedge budget ran out first (``tier`` names the
    budget that acted).  Never retryable: the time is gone."""

    retryable = False

    def __init__(self, stage, late_ms, tier=None, tenant=None):
        super().__init__(f"deadline exceeded at {stage} "
                         f"({late_ms:.1f} ms late)"
                         + (f" [tier: {tier}]" if tier else "")
                         + (f" [tenant: {tenant}]" if tenant else ""))
        self.stage = stage
        self.late_ms = late_ms
        self.tier = tier
        self.tenant = tenant


class Request:
    """One admitted sample and its completion slot."""

    __slots__ = ("payload", "shape", "key", "enq_t", "deadline_ts",
                 "done", "result", "error", "served_t", "trace",
                 "cancel", "params_step", "tenant")

    def __init__(self, payload, shape, key, deadline_s=None, now=None,
                 cancel=None, tenant=None):
        now = time.monotonic() if now is None else now
        self.payload = payload
        self.shape = tuple(shape)            # original feature shape
        self.key = key                       # bucketed feature shape
        self.enq_t = now
        self.deadline_ts = None if deadline_s is None else now + deadline_s
        self.done = threading.Event()
        self.result = None
        self.error = None
        self.served_t = None
        # the request's root span (observability.trace.start_span —
        # the shared no-op with tracing off), opened at submit and
        # closed by whichever thread resolves the request; None only
        # for Requests constructed outside Server.submit
        self.trace = None
        # cooperative cancellation (hedged attempts): a threading.Event
        # the worker checks at dequeue — set it and the request is
        # dropped with RequestCancelled instead of spending a batch slot
        self.cancel = cancel
        # the checkpoint step whose parameters served this request,
        # stamped by the worker at batch time (the rolling-reload
        # version-stamp contract; None = initializer weights)
        self.params_step = None
        # fleet tenant this request belongs to (None on a single-tenant
        # Server): the worker batches per (tenant, key) and every
        # structured failure carries it (docs/serving.md)
        self.tenant = tenant

    def cancelled(self) -> bool:
        return self.cancel is not None and self.cancel.is_set()

    def late_ms(self, now=None) -> float:
        if self.deadline_ts is None:
            return 0.0
        now = time.monotonic() if now is None else now
        return max(now - self.deadline_ts, 0.0) * 1000.0

    def expired(self, now=None) -> bool:
        return self.deadline_ts is not None and \
            (time.monotonic() if now is None else now) > self.deadline_ts

    def set_result(self, value, now=None):
        self.served_t = time.monotonic() if now is None else now
        self.result = value
        self.done.set()

    def set_error(self, exc, now=None):
        self.served_t = time.monotonic() if now is None else now
        self.error = exc
        self.done.set()


class PendingResponse:
    """Caller-side handle: ``result(timeout_s)`` blocks (bounded) until
    the worker completes the request, then returns the value or raises
    the request's structured error."""

    def __init__(self, request: Request, default_timeout_s: float = 60.0):
        self._request = request
        self._default_timeout_s = default_timeout_s

    def result(self, timeout_s=None):
        timeout_s = self._default_timeout_s if timeout_s is None \
            else timeout_s
        if not self._request.done.wait(timeout=timeout_s):
            raise RequestError(
                f"no response within {timeout_s:g}s (server stopped or "
                "wedged — check the serving journal)")
        if self._request.error is not None:
            raise self._request.error
        return self._request.result

    def done(self) -> bool:
        return self._request.done.is_set()

    @property
    def latency_ms(self):
        if self._request.served_t is None:
            return None
        return (self._request.served_t - self._request.enq_t) * 1000.0

    @property
    def params_step(self):
        """Checkpoint step whose parameters produced this response
        (stamped at batch time; None before completion or when the
        server runs on initializer weights)."""
        return self._request.params_step


def drop_expired(pending, on_expired, now=None):
    """Remove already-expired requests from ``pending`` (in place),
    reporting each through ``on_expired(request)`` — the dequeue-time
    half of the deadline contract."""
    now = time.monotonic() if now is None else now
    keep = []
    for req in pending:
        if req.expired(now):
            on_expired(req)
        else:
            keep.append(req)
    pending[:] = keep
    return pending


def take_batch(pending, grid, group_key=None):
    """Pop the next micro-batch off ``pending`` (in place): the oldest
    request's grouping key selects the batch; same-group requests join
    in FIFO order up to the largest batch bucket.  ``group_key``
    defaults to the feature-bucket key; the tenant fleet groups by
    ``(tenant, key)`` so two tenants' requests never share an
    executable.  Returns ``(batch, batch_bucket, feature_key)`` or
    ``(None, None, None)`` when pending is empty."""
    if not pending:
        return None, None, None
    gk = group_key if group_key is not None else (lambda r: r.key)
    head = gk(pending[0])
    batch, rest = [], []
    for req in pending:
        if gk(req) == head and len(batch) < grid.max_batch:
            batch.append(req)
        else:
            rest.append(req)
    pending[:] = rest
    return batch, grid.batch_bucket(len(batch)), batch[0].key

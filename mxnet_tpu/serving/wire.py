"""Replica wire protocol — length-prefixed JSON frames, stdlib-only.

The replica pool's subprocess workers (serving/worker.py) sit behind a
loopback TCP socket; the router talks to them with ONE frame shape in
each direction::

    !II  header_len payload_len   (8-byte big-endian prefix)
    header_len bytes              (UTF-8 JSON dict)
    payload_len bytes             (raw C-order array bytes, optional)

Requests: ``{"cmd": "predict", "shape": [...], "dtype": "float32",
"deadline_ms": ..., "tenant": <name|absent>, ...}`` + array bytes —
``tenant`` targets one fleet tenant on a multi-tenant worker (absent on
a single-tenant replica); control commands (``drain``, ``resume``,
``stats``, ``ping``, ``stop``) carry no payload.  Responses:
``{"ok": true, "shape": [...], "dtype": ..., "params_step": N}`` +
array bytes, or ``{"ok": false, "error": <class name>, "retryable":
bool, "tenant": <name|absent>, ...}`` — the router maps ``error`` back
onto the structured serving exceptions (batcher.py, fleet.py) so a
remote failure raises exactly like a local one, fault domain included.

Every read is bounded by the socket timeout the caller set (the G8
discipline: a dead peer is a structured error, never a hang), and both
length fields are sanity-capped so a garbage peer cannot make a reader
allocate unbounded memory.
"""
from __future__ import annotations

import json
import struct

__all__ = ["MAX_HEADER", "MAX_PAYLOAD", "WireError", "recv_frame",
           "send_frame"]

_PREFIX = struct.Struct("!II")
MAX_HEADER = 1 << 20             # 1 MiB of JSON is already a bug
MAX_PAYLOAD = 1 << 30            # caps a corrupt length field, not traffic


class WireError(ValueError):
    """Malformed frame (bad prefix, oversized length, torn stream)."""


def send_frame(sock, header: dict, payload: bytes = b"") -> None:
    """Serialize and send one frame (sendall — bounded by the socket
    timeout the caller configured).  The payload is sent as-is, never
    copied into a concatenated buffer — array replies can be large."""
    h = json.dumps(header).encode("utf-8")
    sock.sendall(_PREFIX.pack(len(h), len(payload)) + h)
    if payload:
        sock.sendall(payload)


def _recv_exact(sock, n: int) -> bytes:
    chunks = []
    got = 0
    while got < n:
        chunk = sock.recv(min(n - got, 1 << 16))
        if not chunk:
            raise WireError(f"peer closed mid-frame ({got}/{n} bytes)")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def recv_frame(sock):
    """Read one frame; returns ``(header_dict, payload_bytes)``.
    Raises :class:`WireError` on a malformed stream and propagates
    ``socket.timeout``/``OSError`` from the bounded reads."""
    raw = _recv_exact(sock, _PREFIX.size)
    hlen, plen = _PREFIX.unpack(raw)
    if hlen > MAX_HEADER or plen > MAX_PAYLOAD:
        raise WireError(f"frame lengths out of bounds ({hlen}, {plen})")
    try:
        header = json.loads(_recv_exact(sock, hlen).decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise WireError(f"unparsable frame header: {e}") from None
    if not isinstance(header, dict):
        raise WireError("frame header is not a dict")
    payload = _recv_exact(sock, plen) if plen else b""
    return header, payload

"""Replica wire protocol — length-prefixed JSON frames, stdlib-only.

The replica pool's subprocess workers (serving/worker.py) sit behind a
loopback TCP socket; the router talks to them with ONE frame shape in
each direction::

    !II  header_len payload_len   (8-byte big-endian prefix)
    header_len bytes              (UTF-8 JSON dict)
    payload_len bytes             (raw C-order array bytes, optional)

Requests: ``{"cmd": "predict", "shape": [...], "dtype": "float32",
"deadline_ms": ..., "tenant": <name|absent>, ...}`` + array bytes —
``tenant`` targets one fleet tenant on a multi-tenant worker (absent on
a single-tenant replica); control commands (``drain``, ``resume``,
``stats``, ``ping``, ``stop``) carry no payload.  Responses:
``{"ok": true, "shape": [...], "dtype": ..., "params_step": N}`` +
array bytes, or ``{"ok": false, "error": <class name>, "retryable":
bool, "tenant": <name|absent>, ...}`` — the router maps ``error`` back
onto the structured serving exceptions (batcher.py, fleet.py) so a
remote failure raises exactly like a local one, fault domain included.

Trace propagation (docs/observability.md distributed tracing): predict
and error frames carry a compact trace context — ``{"v": 1, "trace":
{"trace_id": ..., "span_id": ...}}`` — so the worker's ``serving_request``
/``serving_batch`` spans become true children of the router's request
root instead of unlinked per-process orphans.  ``v`` is the wire
protocol version: a reader that sees a NEWER major version than it
speaks must treat unknown header fields as advisory (this reader
ignores them), and ``trace`` is always optional — tracing off on either
side degrades to trace-free frames that a pre-trace peer parses
unchanged (frames always gain ``v``, which unknown-key-tolerant
readers — including the pre-trace ones — simply ignore).

Every read is bounded by the socket timeout the caller set (the G8
discipline: a dead peer is a structured error, never a hang), and both
length fields are sanity-capped so a garbage peer cannot make a reader
allocate unbounded memory.
"""
from __future__ import annotations

import json
import struct

__all__ = ["MAX_HEADER", "MAX_PAYLOAD", "PROTOCOL_VERSION", "WireError",
           "attach_trace", "extract_parent", "recv_frame", "send_frame"]

_PREFIX = struct.Struct("!II")
MAX_HEADER = 1 << 20             # 1 MiB of JSON is already a bug
MAX_PAYLOAD = 1 << 30            # caps a corrupt length field, not traffic
PROTOCOL_VERSION = 1             # bump on incompatible header changes


def attach_trace(header: dict) -> dict:
    """Stamp the protocol version + the CALLING context's trace ids
    onto an outgoing frame header (in place; returns it).  With tracing
    off — or outside any span — the header gains only ``v``, which a
    trace-unaware peer (like any unknown key) simply ignores."""
    from ..observability import trace as _trace
    header.setdefault("v", PROTOCOL_VERSION)
    ids = _trace.current_ids()
    if ids:
        header["trace"] = ids
    return header


def extract_parent(header: dict):
    """The propagated trace context of an incoming frame as a
    :class:`~..observability.trace.SpanContext` (the ``parent=`` a
    server-side root span re-anchors under), or None when the frame
    carries none / a malformed one — a garbage peer must degrade to an
    un-parented trace, never an error."""
    doc = header.get("trace")
    if not isinstance(doc, dict):
        return None
    tid, sid = doc.get("trace_id"), doc.get("span_id")
    if not isinstance(tid, str) or not isinstance(sid, str):
        return None
    from ..observability import trace as _trace
    return _trace.SpanContext(tid, sid)


class WireError(ValueError):
    """Malformed frame (bad prefix, oversized length, torn stream)."""


def send_frame(sock, header: dict, payload: bytes = b"") -> None:
    """Serialize and send one frame (sendall — bounded by the socket
    timeout the caller configured).  The payload is sent as-is, never
    copied into a concatenated buffer — array replies can be large."""
    h = json.dumps(header).encode("utf-8")
    sock.sendall(_PREFIX.pack(len(h), len(payload)) + h)
    if payload:
        sock.sendall(payload)


def _recv_exact(sock, n: int) -> bytes:
    chunks = []
    got = 0
    while got < n:
        chunk = sock.recv(min(n - got, 1 << 16))
        if not chunk:
            raise WireError(f"peer closed mid-frame ({got}/{n} bytes)")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def recv_frame(sock):
    """Read one frame; returns ``(header_dict, payload_bytes)``.
    Raises :class:`WireError` on a malformed stream and propagates
    ``socket.timeout``/``OSError`` from the bounded reads."""
    raw = _recv_exact(sock, _PREFIX.size)
    hlen, plen = _PREFIX.unpack(raw)
    if hlen > MAX_HEADER or plen > MAX_PAYLOAD:
        raise WireError(f"frame lengths out of bounds ({hlen}, {plen})")
    try:
        header = json.loads(_recv_exact(sock, hlen).decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise WireError(f"unparsable frame header: {e}") from None
    if not isinstance(header, dict):
        raise WireError("frame header is not a dict")
    payload = _recv_exact(sock, plen) if plen else b""
    return header, payload
